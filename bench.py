"""Headline benchmark: elasticnet SAC env-steps/sec on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Primary workload = the reference `elasticnet/main_sac.py` configuration
(N=M=20, batch 64, mem 1024, 5 steps/episode): every env step runs the full
inner L-BFGS elastic-net solve + influence eigen-state, and every loop
iteration also runs the SAC learn step.  Since round 4 the primary runs 20
whole episodes per device dispatch (episode-block lax.scan — same
sequential 1:1 computation, parity-tested in tests/test_epblock.py); the
rounds-1/2/3 one-dispatch-per-episode number is the per_episode_dispatch
extra.

Baseline = the reference implementation itself (torch, this host's CPU —
upstream publishes no numbers; see BASELINE.md), measured by
tools/measure_reference.py: warm-up until the replay buffer reaches
batch_size, then time 100 steps.  The per_episode_dispatch extra keeps
that protocol exactly; the primary runs the same sequential computation
with a one-block (100-step) warm-up and 200 timed steps in 2 dispatches —
steps/sec is dispatch-amortized but the per-step work is identical.

``extra`` carries BASELINE.md metric #2 — calibration-episode wall-clock at
the REFERENCE scale (N=62 stations, B=1891 baselines, Nf=8 sub-bands,
Tdelta=10, K=6 directions, 128x128 influence map; BASELINE.md workload
table): one episode = simulate + consensus-ADMM calibrate + influence map,
the dosimul.sh / docal.sh / doinfluence.sh triple of calibenv.py.  The
reference's own number does not exist (sagecal-mpi + GPUs are not
measurable here), so the entry reports absolute wall-clock, steady-state
(post-compile), with the compile time alongside.  BENCH_SKIP_CALIB=1 skips
only the expensive calib episode; BENCH_SKIP_EXTRAS=1 emits only the
primary metric.
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np

from smartcal_tpu.envs import enet
from smartcal_tpu.rl import replay as rp
from smartcal_tpu.rl import sac
from smartcal_tpu.train.enet_sac import make_episode_block_fn, make_episode_fn
from smartcal_tpu.utils import enable_compilation_cache

# Warm-cache state is recorded in the calib extra ("compile_cache_warm")
# because first_episode_incl_compile_s is only comparable across rounds
# when both runs were equally cold.
_CACHE_DIR = os.environ.get("SMARTCAL_COMPILE_CACHE_DIR",
                            "/tmp/smartcal_jax_cache")
_CACHE_WAS_WARM = bool(os.path.isdir(_CACHE_DIR) and os.listdir(_CACHE_DIR))
enable_compilation_cache(_CACHE_DIR)

def _stamp_fingerprint(payload):
    """Stamp the full host fingerprint (nproc/platform/jax versions/
    dtype policy — obs/baselines.py) into a bench payload in place.

    PR 16 recorded only ``host_cores`` and only in one artifact; the
    2026-08-07 tier-1 budget incident (24-core numbers silently
    compared on a 1-core container) is why EVERY artifact now carries
    the identity it is only comparable within.  Idempotent: an extra
    that already stamped itself is left alone."""
    if isinstance(payload, dict) and "host_fingerprint" not in payload:
        from smartcal_tpu.obs import baselines as _bl
        fp = _bl.host_fingerprint()
        payload["host_fingerprint"] = fp
        payload["host_fingerprint_digest"] = _bl.fingerprint_digest(fp)
    return payload


def _write_results_artifact(payload, out_path):
    """The shared bench artifact writer: fingerprint-stamp, then write.
    Every ``results/`` JSON produced by a bench extra must go through
    here (or stamp itself) so no future artifact can be compared
    cross-host unknowingly."""
    _stamp_fingerprint(payload)
    if out_path:
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
    return payload


STEPS_PER_EPISODE = 5
# per_episode_dispatch extra only (the rounds-1/2/3 primary): 100 timed
# env steps, matching the tools/measure_reference.py torch measurement.
# The round-4+ primary times PRIMARY_TIMED_BLOCKS x PRIMARY_BLOCK whole
# episodes per device dispatch instead (same sequential 1:1 computation).
TIMED_EPISODES = 20
PRIMARY_BLOCK = 20
PRIMARY_TIMED_BLOCKS = 2
FALLBACK_BASELINE = 4.16  # tools/reference_baseline.json, torch CPU


WINDOW_LOCK = "/tmp/tpu_window.lock"
_LOCK_MARKER = f"bench:{os.getpid()}\n"
_LOCK_OWNED = False


def _claim_window_lock():
    """Create the chip-window lock with our pid marker; True only when WE
    created it.  A pre-existing lock belongs to a capture script (or a
    crashed earlier bench): competitors still get paused, but the resume
    path must not delete a live lock this process doesn't own (bench.py
    used to unconditionally ``os.remove`` it, yanking the window out from
    under a running capture script)."""
    try:
        fd = os.open(WINDOW_LOCK, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except OSError:            # exists (FileExistsError) or unwritable
        return False
    try:
        os.write(fd, _LOCK_MARKER.encode())
    finally:
        os.close(fd)
    return True


def _refresh_window_lock():
    """Keep the lock mtime fresh (cooperating CPU jobs expire stale locks
    by age) — but only when we own it: rewriting another process's lock
    would erase its pid marker."""
    if not _LOCK_OWNED:
        return
    try:
        with open(WINDOW_LOCK, "w") as fh:
            fh.write(_LOCK_MARKER)
    except OSError:
        pass


def _pause_competitors():
    """Take the chip-window lock and SIGSTOP any running sweep so timed
    sections are uncontended on the single-core host (VERDICT r4 weak 1:
    the round-4 CPU-fallback primary read 4x under its own extras purely
    from self-contention with a background learning-curve sweep — the
    sweeps only yielded to *capture-script* windows, never to a bare
    ``python bench.py``).  Returns the stopped pids for
    ``_resume_competitors``.  A detached insurance shell CONTs the pids
    later even if this process is SIGKILLed mid-bench (driver-side
    timeouts), so a dead bench can never leave the sweeps frozen."""
    global _LOCK_OWNED
    _LOCK_OWNED = _claim_window_lock()
    try:
        # anchored like capture_round.sh's SWEEP_PAT: an unanchored
        # pattern would also freeze innocent processes whose argv merely
        # mentions the path (an editor, a tail -f)
        r = subprocess.run(
            ["pgrep", "-f", r"python[^ ]* [^ ]*tools/sweep_(calib|demix)\.py"],
            capture_output=True, text=True, timeout=10)
        pids = [int(x) for x in r.stdout.split() if x.isdigit()
                and int(x) != os.getpid()]
    except Exception:
        pids = []
    stopped = []
    for pid in pids:
        try:
            os.kill(pid, signal.SIGSTOP)
            stopped.append(pid)
        except OSError:
            pass
    insurance = None
    if stopped:
        try:
            insurance = subprocess.Popen(
                ["bash", "-c", "sleep 5400; kill -CONT "
                 + " ".join(map(str, stopped)) + " 2>/dev/null"],
                start_new_session=True,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        except Exception:
            insurance = None
    return stopped, insurance


def _resume_competitors(stopped, insurance):
    for pid in stopped:
        try:
            os.kill(pid, signal.SIGCONT)
        except OSError:
            pass
    # cancel the insurance shell on the clean path: a live one would
    # SIGCONT the same pids ~90 min later, potentially into the middle
    # of a LATER capture attempt's timed window
    if insurance is not None:
        try:
            insurance.kill()
        except Exception:
            pass
    # remove the lock ONLY if this process created it (pid-marker check):
    # a lock that predates us is a capture script's live window claim
    if _LOCK_OWNED:
        try:
            with open(WINDOW_LOCK) as fh:
                mine = fh.read() == _LOCK_MARKER
        except OSError:
            mine = False
        if mine:
            try:
                os.remove(WINDOW_LOCK)
            except OSError:
                pass


def _settle_load(threshold=1.2, max_wait_s=240.0):
    """1-min loadavg is a trailing indicator: after the sweeps are paused
    it decays toward the truly-uncontended level with a ~1 min time
    constant, so a measurement taken immediately would read stale
    contention.  Wait (bounded) for it to cross the uncontended
    threshold; return the final value — the caller records it and flags
    the run contended if it never settled."""
    t0 = time.time()
    load = os.getloadavg()[0]
    while load >= threshold and time.time() - t0 < max_wait_s:
        time.sleep(15)
        load = os.getloadavg()[0]
    return load


def load_baseline_info():
    """(value, platform) of the reference baseline every ``vs_baseline``
    multiple divides by: the torch reference implementation measured on
    THIS HOST's CPU (tools/measure_reference.py — upstream publishes no
    numbers, so there is no A100 figure to compare against; see
    BASELINE.md).  The platform string is emitted in the bench payload
    (``baseline_platform``) so a reader can never mistake the multiple
    for a GPU comparison."""
    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "tools", "reference_baseline.json")
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            ref = json.load(f)
        return ref["value"], ref.get("hardware", "torch CPU (this host)")
    return FALLBACK_BASELINE, "torch CPU (this host; fallback constant)"


def load_baseline():
    """The torch-reference steps/s measured on this host class
    (tools/measure_reference.py), shared by every 1:1-protocol metric."""
    return load_baseline_info()[0]


def probe_backend():
    """(platform, note): 'tpu' if the backend initializes within a
    bounded time, else 'cpu' with a note explaining why.

    ``BENCH_PLATFORM=cpu|tpu`` skips the probe entirely — use it for
    deliberate CPU runs, for hosts without the TPU plugin, and whenever
    another TPU process is already running (ONE client at a time: a
    concurrent probe can itself wedge the axon tunnel, see
    .claude/skills/verify/SKILL.md).  Without the override, the probe
    runs in a SUBPROCESS with a timeout because a wedged tunnel hangs
    backend init indefinitely (observed 2026-07-29/30) and bench.py must
    always print its one JSON line.  The fallback CPU measurement stays
    comparable: the recorded baseline is the torch reference on this
    same host CPU.
    """
    from smartcal_tpu import obs

    forced = os.environ.get("BENCH_PLATFORM", "").strip().lower()
    if forced in ("cpu", "tpu"):
        return forced, f"forced via BENCH_PLATFORM={forced}"
    # Bounded retries with exponential backoff + jitter (VERDICT r2 item
    # 1; blind fixed-sleep loop replaced in the runtime PR): a wedged
    # tunnel sometimes recovers within minutes, and round 2 lost its
    # on-chip numbers to a single-shot probe — but round 5 also burned 87
    # fixed-cadence probes against a dead tunnel.  The walk is 45 s
    # doubling to 300 s (+/-25% jitter) under BOTH an attempt cap
    # (BENCH_PROBE_ATTEMPTS, default 3) and a total-sleep budget
    # (BENCH_PROBE_BUDGET_S, default 900 s), so bench.py always prints
    # its JSON line.
    from smartcal_tpu.runtime import Backoff, BackoffPolicy

    def _env_num(name, default, cast):
        try:
            return cast(os.environ.get(name, str(default)))
        except ValueError:
            return default

    attempts = max(1, _env_num("BENCH_PROBE_ATTEMPTS", 3, int))
    budget_s = _env_num("BENCH_PROBE_BUDGET_S", 900.0, float)
    bo = Backoff(BackoffPolicy(base_s=45.0, factor=2.0, max_s=300.0,
                               jitter=0.25, max_attempts=attempts - 1,
                               budget_s=budget_s),
                 seed=os.getpid())
    for i in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, text=True, timeout=150)
        except subprocess.TimeoutExpired:
            # only the wedged-tunnel hang retries — a clean non-TPU answer
            # is definitive and must not cost retry sleeps on CPU-only hosts
            delay = None if i >= attempts - 1 else bo.next_delay()
            rl = obs.active()
            if rl is not None:
                # the structured chip-probe record VERDICT r5 demanded
                # (87/87 tunnel probes failed with nothing on disk)
                rl.log("probe", ok=False, attempt=i,
                       error="backend init timed out (150s)",
                       next_retry_s=None if delay is None
                       else round(delay, 1),
                       backoff_spent_s=round(bo.spent_s, 1))
            if delay is None:
                break
            time.sleep(delay)
            continue
        ok = r.returncode == 0 and r.stdout.strip() in ("axon", "tpu")
        rl = obs.active()
        if rl is not None:
            rl.log("probe", ok=ok, attempt=i,
                   platform=r.stdout.strip() or None,
                   returncode=r.returncode)
        if ok:
            return "tpu", ""
        return "cpu", ("no TPU platform available "
                       f"(probe saw {r.stdout.strip() or r.returncode})")
    return "cpu", ("TPU backend init timed out (tunnel wedged?), "
                   f"{attempts} attempts, "
                   f"{round(bo.spent_s)}s backoff spent")


def bench_configs():
    """The ONE workload both enet metrics run (reference
    elasticnet/main_sac.py:28-40) — the batched metric is only comparable
    to the 1:1 primary if they share this config."""
    env_cfg = enet.EnetConfig(M=20, N=20)
    agent_cfg = sac.SACConfig(
        obs_dim=env_cfg.obs_dim, n_actions=2, gamma=0.99, tau=0.005,
        batch_size=64, mem_size=1024, lr_a=1e-3, lr_c=1e-3,
        reward_scale=20.0, alpha=0.03)
    return env_cfg, agent_cfg


def bench_batched_throughput(n_envs: int = 16, timed_steps: int = 60):
    """Aggregate env-steps/sec with vmapped parallel environments.

    The reference scales rollout collection by fanning actors out over RPC
    nodes (distributed_per_sac.py); the TPU-native equivalent is a batch of
    vmapped envs advancing under one jit on one chip (parallel/trainer.py
    on a 1-device mesh here; the same program shards over ``dp`` on a pod
    slice).  One learn step runs per *vector* step, so the learn:env-step
    ratio is 1:n_envs — the distributed-actor regime, reported separately
    from the primary 1:1 metric.
    """
    from smartcal_tpu.parallel import make_mesh, make_parallel_sac

    env_cfg, agent_cfg = bench_configs()
    mesh = make_mesh((1,), ("dp",), devices=jax.devices()[:1])
    init_fn, train_step, reset_envs = make_parallel_sac(
        env_cfg, agent_cfg, mesh, n_envs=n_envs)
    st = init_fn(jax.random.PRNGKey(0))

    key = jax.random.PRNGKey(1)
    for i in range(max(4, agent_cfg.batch_size // n_envs + 1)):  # warm+fill
        key, k = jax.random.split(key)
        st, metrics = train_step(st, k)
        if i % STEPS_PER_EPISODE == STEPS_PER_EPISODE - 1:
            key, k = jax.random.split(key)
            st = reset_envs(st, k)
    jax.block_until_ready(metrics["mean_reward"])

    t0 = time.time()
    for i in range(timed_steps):
        key, k = jax.random.split(key)
        st, metrics = train_step(st, k)
        if i % STEPS_PER_EPISODE == STEPS_PER_EPISODE - 1:
            key, k = jax.random.split(key)
            st = reset_envs(st, k)
    jax.block_until_ready(metrics["mean_reward"])
    wall = time.time() - t0
    return {
        "metric": "enet_sac_env_steps_per_sec_batched",
        "value": round(n_envs * timed_steps / wall, 2),
        "unit": "env-steps/sec/chip",
        "vs_baseline": None,
        "n_envs": n_envs,
        "note": "vmapped parallel envs, 1 learn per vector step",
    }


def bench_batched_block_throughput(n_envs: int = 16,
                                   episodes_per_dispatch: int = 20,
                                   timed_dispatches: int = 2):
    """Batched envs AND whole-episode scan blocks: the ceiling mode.

    Combines the two dispatch-amortizations — 16 vmapped dp-sharded envs
    per vector step (bench_batched_throughput) and whole episodes scanned
    inside one program (bench_epblock_throughput) — so one dispatch runs
    episodes_per_dispatch full episodes of the entire env batch.  Same
    1-learn-per-vector-step ratio as the batched metric.
    """
    from smartcal_tpu.parallel import make_mesh, make_parallel_sac

    env_cfg, agent_cfg = bench_configs()
    mesh = make_mesh((1,), ("dp",), devices=jax.devices()[:1])
    init_fn, _, _, run_block = make_parallel_sac(
        env_cfg, agent_cfg, mesh, n_envs=n_envs,
        episode_block=(STEPS_PER_EPISODE, episodes_per_dispatch))
    st = init_fn(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(1)
    key, k = jax.random.split(key)
    st, scores = run_block(st, k)          # compile + fill
    jax.block_until_ready(scores)

    t0 = time.time()
    for _ in range(timed_dispatches):
        key, k = jax.random.split(key)
        st, scores = run_block(st, k)
    jax.block_until_ready(scores)
    wall = time.time() - t0
    steps = timed_dispatches * episodes_per_dispatch * STEPS_PER_EPISODE
    return {
        "metric": "enet_sac_env_steps_per_sec_batched_epblock",
        "value": round(n_envs * steps / wall, 2),
        "unit": "env-steps/sec/chip",
        "vs_baseline": None,
        "n_envs": n_envs,
        "episodes_per_dispatch": episodes_per_dispatch,
        "note": "vmapped env batch x whole-episode scan blocks, "
                "1 learn per vector step",
    }


def measure_epblock(block: int, timed_blocks: int, trace_dir=None):
    """ONE episode-block measurement: sequential 1:1 computation (one
    learn per env step, whole episodes), ``block`` episodes per device
    dispatch, ``timed_blocks`` timed dispatches after a compile+fill
    block.  Shared by the round-4+ primary and the epblock extra so the
    two can never drift apart."""
    from smartcal_tpu.utils import profiler_trace

    env_cfg, agent_cfg = bench_configs()
    # the single warm-up block must fill the replay buffer past
    # batch_size or the timed blocks would measure a window where learn()
    # is not yet live — a silent protocol change (ADVICE r4 item 5)
    assert block * STEPS_PER_EPISODE >= agent_cfg.batch_size, (
        f"warm-up block too small: {block} episodes x {STEPS_PER_EPISODE} "
        f"steps < batch_size {agent_cfg.batch_size}; learn() would be "
        "dead during the timed section")
    key = jax.random.PRNGKey(0)
    key, k0 = jax.random.split(key)
    agent_state = sac.sac_init(k0, agent_cfg)
    buf = rp.replay_init(agent_cfg.mem_size,
                         rp.transition_spec(env_cfg.obs_dim, 2))
    block_fn = make_episode_block_fn(env_cfg, agent_cfg, STEPS_PER_EPISODE,
                                     False, block)
    # one untimed block: compile + fill the buffer past batch_size
    # (block*steps = 100 >= 64) so the timed blocks run learn() live
    agent_state, buf, key, scores = block_fn(agent_state, buf, key)
    jax.block_until_ready(scores)

    t0 = time.time()
    with profiler_trace(trace_dir):
        for _ in range(timed_blocks):
            agent_state, buf, key, scores = block_fn(agent_state, buf, key)
        jax.block_until_ready(scores)
    wall = time.time() - t0
    return timed_blocks * block * STEPS_PER_EPISODE / wall


def bench_epblock_throughput(block: int = 20, timed_blocks: int = 3):
    """Sequential 1:1 protocol with episode-block dispatch — the SAME
    protocol as the round-4+ primary (shared measure_epblock), kept as an
    extra so the capture validation and round-over-round extras history
    stay continuous."""
    value = measure_epblock(block, timed_blocks)
    return {
        "metric": "enet_sac_env_steps_per_sec_epblock",
        "value": round(value, 2),
        "unit": "env-steps/sec/chip",
        # same 1:1 sequential protocol as the primary, so the torch
        # reference baseline is directly comparable
        "vs_baseline": round(value / load_baseline(), 2),
        "episodes_per_dispatch": block,
        "note": "sequential 1:1 protocol, whole-episode lax.scan blocks",
    }


def bench_per_episode_dispatch():
    """The rounds-1/2/3 primary protocol (one device dispatch per episode),
    kept as an extra for cross-round comparability after the round-4
    primary moved to episode-block dispatch.  On the chip this is
    dominated by the per-episode host round trip over the tunnel — that
    dispatch tax is exactly what the epblock primary removes."""
    env_cfg, agent_cfg = bench_configs()
    key = jax.random.PRNGKey(0)
    key, k0 = jax.random.split(key)
    agent_state = sac.sac_init(k0, agent_cfg)
    buf = rp.replay_init(agent_cfg.mem_size,
                         rp.transition_spec(env_cfg.obs_dim, 2))
    episode_fn = make_episode_fn(env_cfg, agent_cfg, STEPS_PER_EPISODE,
                                 use_hint=False)
    while int(buf.cntr) < agent_cfg.batch_size:
        key, k = jax.random.split(key)
        agent_state, buf, score = episode_fn(agent_state, buf, k)
    jax.block_until_ready(score)

    t0 = time.time()
    for _ in range(TIMED_EPISODES):
        key, k = jax.random.split(key)
        agent_state, buf, score = episode_fn(agent_state, buf, k)
    jax.block_until_ready(score)
    wall = time.time() - t0
    value = TIMED_EPISODES * STEPS_PER_EPISODE / wall
    return {
        "metric": "enet_sac_env_steps_per_sec_per_episode_dispatch",
        "value": round(value, 2),
        "unit": "env-steps/sec/chip",
        "vs_baseline": round(value / load_baseline(), 2),
        "note": "rounds-1/2/3 primary protocol: one dispatch per episode",
    }


def _solve_flops_estimate(backend, ep):
    """Analytic FLOP estimate of the N=62 ADMM solve (the episode's
    dominant stage).  HLO cost analysis is useless here — it counts a
    ``while_loop`` body ONCE, and the solver is loop-dominated — so this
    models the dominant op instead: applying the Jones solutions to the
    model coherencies, z_pq = J_p C_k J_q^H, two split-real 2x2 complex
    matmuls (~112 flop) per (direction, baseline-sample, sub-band).  Per
    L-BFGS iteration: the gradient eval (~2 cost-equivalents by
    reverse-mode) plus the quartic line-search coefficient build (4
    bilinear model evals since the exact-P1 fix, ~2 cost-equivalents);
    ADMM dual/consensus updates are lower-order.  This HAND model is
    reported for continuity only — the XLA-measured per-iteration count
    (cost_eval_flops) is larger and is what MFU is quoted from; their
    ratio is in the payload (flops_model_over_measured)."""
    B = backend.n_stations * (backend.n_stations - 1) // 2
    samples = backend.n_freqs * backend.n_times * B
    cost_flops = samples * ep.n_dirs * 112
    total_iters = (backend.init_iters
                   + backend.admm_iters * backend.lbfgs_iters)
    return float(total_iters * 4.0 * cost_flops)


def _calib_episode_once(backend, k, stages=None):
    """One full episode (simulate -> calibrate -> influence) with optional
    per-stage wall-clock breakdown — the dosimul.sh / docal.sh /
    doinfluence.sh triple.  block_until_ready between stages makes the
    split attributable (and is exactly the host-sync the pipelined
    multi-episode mode below removes)."""
    t = time.time()
    ep, mdl = backend.new_demixing_episode(k, K=6)
    jax.block_until_ready((ep.V, ep.Ccal))   # charge ALL construction here
    if stages is not None:
        stages["simulate_s"] = round(time.time() - t, 2)
    t = time.time()
    res = backend.calibrate(ep, mdl.rho, mask=np.ones(6, np.float32))
    jax.block_until_ready(res.residual)
    if stages is not None:
        stages["calibrate_s"] = round(time.time() - t, 2)
    t = time.time()
    img = backend.influence_image(ep, res, mdl.rho,
                                  np.zeros(6, np.float32))
    jax.block_until_ready(img)
    if stages is not None:
        stages["influence_image_s"] = round(time.time() - t, 2)
    return img, float(res.sigma_res), (ep, mdl)


def bench_calib_episode(pipeline_episodes: int = 2, small: bool = False):
    """Calibration episode wall-clock at LOFAR scale (N=62, B=1891, Nf=8).

    Measures BOTH episode paths on the same backend config so the
    pipelining win is attributable:
      * value           — the device-pipelined path (vectorized O(1)-
                          dispatch construction, mesh-aware solve routing)
      * host_loop_*     — the pre-pipeline path (per-frequency python
                          loops + np.asarray host syncs), kept in
                          envs/radio.py as the parity oracle
    plus the double-buffered multi-episode mode (run_pipelined), where
    episode t+1's simulation overlaps episode t's solve.

    ``small=True`` is the CPU-fallback scale (N=14/Nf=4: the LOFAR shape
    is hours per episode on one CPU core) — reported under a DISTINCT
    metric name so it is never read as a chip-scale number.
    """
    from smartcal_tpu.envs.radio import RadioBackend

    if small:
        kw = dict(n_stations=14, n_freqs=4, n_times=20, tdelta=10,
                  admm_iters=5, lbfgs_iters=8, init_iters=30, npix=128)
    else:
        kw = dict(n_stations=62, n_freqs=8, n_times=20, tdelta=10,
                  admm_iters=10, lbfgs_iters=8, init_iters=30, npix=128)
    backend = RadioBackend(**kw)                        # pipelined (default)
    legacy = RadioBackend(vectorized=False, shard=False, **kw)
    key = jax.random.PRNGKey(7)
    # intra-extra budget: this extra now runs up to ~3x the episode count
    # of the pre-comparison version (legacy arm + overlap arm); the
    # primary value (pipelined steady state) is always measured, the
    # comparison arms are skipped once over budget so a driver-side
    # timeout can't kill the process mid-extra with the payload unsaved
    try:
        calib_budget = float(os.environ.get("BENCH_CALIB_BUDGET_S", "900"))
    except ValueError:
        calib_budget = 900.0
    t_extra0 = time.time()

    t0 = time.time()
    ks = jax.random.split(key, 2 + max(0, pipeline_episodes))
    k1, k2, pipe_keys = ks[0], ks[1], ks[2:]
    _calib_episode_once(backend, k1)  # compile + run
    t_first = time.time() - t0
    stages = {}                       # per-stage steady-state breakdown
    t0 = time.time()
    img, sigma, (ep, mdl) = _calib_episode_once(backend, k2, stages)
    t_steady = time.time() - t0
    assert np.all(np.isfinite(np.asarray(img)))

    # pre-pipeline host-loop path, same keys (solver programs shared with
    # the run above, so the first legacy episode only adds the small
    # per-frequency construction/influence compiles)
    t_loop = None
    stages_loop = {}
    if time.time() - t_extra0 < calib_budget:
        _calib_episode_once(legacy, k1)                 # warm its kernels
        t0 = time.time()
        _calib_episode_once(legacy, k2, stages_loop)
        t_loop = time.time() - t0
    if time.time() - t_extra0 >= calib_budget:
        pipe_keys = pipe_keys[:0]                       # skip overlap arm

    out = {
        "metric": ("calib_episode_wall_clock_cpu_fallback" if small
                   else "calib_episode_wall_clock"),
        "value": round(t_steady, 2),
        "unit": "s/episode",
        "vs_baseline": None,
        "scale": ("N=14 B=91 Nf=4 Tdelta=10 K=6 npix=128 (CPU-fallback "
                  "scale)" if small
                  else "N=62 B=1891 Nf=8 Tdelta=10 K=6 npix=128"),
        "first_episode_incl_compile_s": round(t_first, 2),
        "compile_cache_warm": _CACHE_WAS_WARM,
        "stage_breakdown": stages,
    }
    if t_loop is not None:
        out["host_loop_episode_s"] = round(t_loop, 2)
        out["host_loop_stage_breakdown"] = stages_loop
        out["pipeline_speedup_vs_host_loop"] = round(
            t_loop / max(t_steady, 1e-9), 3)
    else:
        out["host_loop_skipped"] = (f"calib extra budget "
                                    f"({calib_budget:.0f}s) spent")
    if len(pipe_keys):
        # double-buffered episodes: construction of t+1 overlaps solve of t
        def body(ep_, mdl_):
            res_ = backend.calibrate(ep_, mdl_.rho,
                                     mask=np.ones(6, np.float32))
            img_ = backend.influence_image(ep_, res_, mdl_.rho,
                                           np.zeros(6, np.float32))
            jax.block_until_ready(img_)
            return float(res_.sigma_res)

        t0 = time.time()
        sigmas = list(backend.run_pipelined(
            list(pipe_keys),
            lambda kk: backend.new_demixing_episode(kk, K=6), body))
        t_pipe = (time.time() - t0) / len(pipe_keys)
        assert all(np.isfinite(s) for s in sigmas)
        out["pipelined_overlap_s_per_episode"] = round(t_pipe, 2)
        out["pipelined_overlap_episodes"] = len(pipe_keys)
        if t_loop is not None:
            # the throughput comparison for episode STREAMS (training's
            # shape): double-buffered episodes vs the serial host loop
            out["overlap_speedup_vs_host_loop"] = round(
                t_loop / max(t_pipe, 1e-9), 3)
    # hardware-utilization estimate for the dominant stage (VERDICT r3
    # item 8): FLOPs of the solve / measured calibrate seconds, and an
    # MFU %% against the v5e peak when on chip.  The solve is fp32
    # split-real einsums, so bf16 peak (197 TF) overstates the attainable
    # roofline ~4x — both references are reported.
    #
    # VERDICT r4 item 5: the per-eval FLOP numerator is MEASURED — the
    # exact batched value_and_grad + quartic line-search coefficient
    # build the L-BFGS driver runs are lowered shape-only and counted
    # by XLA cost_analysis (solver.cost_eval_flops); only the iteration
    # count stays analytic (1 value_and_grad + 1 coefficient build per
    # iteration; the Wolfe probes themselves are O(1) scalars).  The
    # hand model (112 flop/sample forward unit) is reported alongside
    # with its ratio: it counts only the core prediction matmuls, so it
    # understates the executed flops ~3x at both N=14 and N=62.
    flops_model = _solve_flops_estimate(backend, ep)
    cal_s = stages.get("calibrate_s")
    out["solve_flops_model"] = flops_model
    try:
        from smartcal_tpu.cal.solver import cost_eval_flops
        check = cost_eval_flops(
            backend._solver_cfg(ep.n_dirs), backend.n_freqs,
            backend.n_chunks, backend.tdelta,
            backend.n_stations * (backend.n_stations - 1) // 2)
        total_iters = (backend.init_iters
                       + backend.admm_iters * backend.lbfgs_iters)
        flops = total_iters * (check["xla_value_and_grad_flops"]
                               + check["xla_linesearch_setup_flops"])
        if not np.isfinite(flops) or flops <= 0:
            # cost_analysis returns NaN when the 'flops' key is absent
            # (possible across XLA versions); NaN would sail through the
            # truthiness gate below and poison the JSON payload
            raise ValueError(f"non-finite XLA flop count {flops}")
        out["solve_flops_xla_measured"] = flops
        out["flops_check"] = check
        out["flops_model_over_measured"] = round(flops_model / flops, 3)
    except Exception as e:  # noqa: BLE001 — the check must never kill a capture
        out["flops_check"] = {"error": f"{type(e).__name__}: {e}"}
        flops = flops_model
    if flops and cal_s:
        achieved = flops / cal_s
        out["solve_gflops_per_s"] = round(achieved / 1e9, 1)
        if jax.devices()[0].platform in ("tpu", "axon"):
            out["solve_mfu_pct_vs_v5e_bf16_peak"] = round(
                100 * achieved / 197e12, 3)
            out["solve_mfu_pct_vs_v5e_fp32_est"] = round(
                100 * achieved / 49e12, 3)
    return out


def bench_calib_batched(batch_sizes=(1, 4, 8), steps=2):
    """Aggregate env-steps/s of the BATCHED radio episode mode vs the
    sequential loop (ISSUE 9 tentpole metric).

    For each B: the sequential arm runs B whole CalibEnv episodes
    (reset-calibration + ``steps`` steps, each a full solve + influence
    + reward images) one at a time; the batched arm runs ONE
    BatchedCalibEnv vector episode with B lanes — the same env-step
    budget as one batched program per stage.  Both arms are timed warm
    (a full untimed episode first), so the comparison is steady-state
    throughput, not compile amortization.  CPU-safe scale (N=8, Nf=2):
    the N=62 amortized number needs a chip window — reported as skipped
    otherwise.
    """
    from smartcal_tpu.envs import BatchedCalibEnv, CalibEnv
    from smartcal_tpu.envs.radio import RadioBackend

    M = 4
    kw = dict(n_stations=8, n_freqs=2, n_times=8, tdelta=4, admm_iters=3,
              lbfgs_iters=3, init_iters=6, npix=32)
    per_b = []
    for nb in batch_sizes:
        acts_b = np.zeros((nb, 2 * M), np.float32)
        # sequential arm: B whole episodes, one at a time
        env = CalibEnv(M=M, backend=RadioBackend(**kw), seed=0)
        env.reset()                       # warm: compiles + first episode
        for _ in range(steps):
            env.step(acts_b[0])
        t0 = time.time()
        for _ in range(nb):
            env.reset()
            for _ in range(steps):
                env.step(acts_b[0])
        seq_wall = time.time() - t0

        # batched arm: one vector episode of B lanes
        benv = BatchedCalibEnv(M=M, n_envs=nb,
                               backend=RadioBackend(**kw), seed=0)
        benv.reset()                      # warm the batched programs
        for _ in range(steps):
            benv.step(acts_b)
        t0 = time.time()
        benv.reset()
        for _ in range(steps):
            benv.step(acts_b)
        bat_wall = time.time() - t0

        env_steps = nb * steps
        per_b.append({
            "n_envs": nb,
            "seq_env_steps_per_sec": round(env_steps / seq_wall, 3),
            "bat_env_steps_per_sec": round(env_steps / bat_wall, 3),
            "seq_s_per_episode": round(seq_wall / nb, 3),
            "bat_amortized_s_per_episode": round(bat_wall / nb, 3),
            "speedup_vs_sequential": round(seq_wall / max(bat_wall, 1e-9),
                                           3),
        })
    best = max(per_b, key=lambda r: r["bat_env_steps_per_sec"])
    out = {
        "metric": "calib_batched_env_steps_per_sec",
        "value": best["bat_env_steps_per_sec"],
        "unit": "env-steps/sec",
        "vs_baseline": None,
        "scale": f"N=8 B=28 Nf=2 Tdelta=4 M={M} npix=32 (CPU-safe)",
        "steps_per_episode": steps,
        "batch_sizes": list(batch_sizes),
        "results": per_b,
        "note": "sequential arm = B whole episodes one at a time; "
                "batched arm = one B-lane vector episode "
                "(RadioBackend.calibrate_batched route)",
    }
    if jax.devices()[0].platform in ("tpu", "axon"):
        out["n62_amortized"] = "run bench_calib_episode for the N=62 "\
            "anchor; batched N=62 needs a dedicated chip window"
    else:
        out["n62_amortized_skipped"] = ("no TPU: the N=62 batched "
                                        "amortized number needs a chip "
                                        "window (135 s/episode anchor "
                                        "is hours at B>=4 on one core)")
    return out


def bench_nscale(ns=(62, 128, 256), out_path=None, batch_lanes=2):
    """N-scaling sweep for the solve+influence chain (ISSUE 13): labeled
    arms over N stations x {unbatched, batched}, CPU-safe small tier.

    Each arm measures WARM wall-clock of the production routes —
    ``RadioBackend.calibrate`` (fused ADMM at this tier's work size) and
    ``RadioBackend.influence_image`` (the blocked Hessian core engages
    automatically at B >= 8128, i.e. N >= 128) — plus the per-compile
    memory-footprint accounting (obs/costs.stage_cost: XLA
    ``memory_analysis`` peak live bytes) for the blocked AND unblocked
    influence programs, so the memory story is measured, not asserted.
    The batched arm stacks ``batch_lanes`` episodes through
    ``calibrate_batched``/``influence_images_batched`` (the PR 9 lane
    axis — the multiplier that makes N^2 baselines bite).

    A separate ``full_tier_footprint`` section lowers (shape-only, no
    execution) the FULL-scale influence program — T=20 slots, npix=1024
    — at each N: at N=256 the unblocked chain's peak is tens of GB (the
    (npix, R~6.5e5) imager planes plus the (K, Td, B) Hessian
    temporaries), i.e. footprint-bounded on accelerator HBM, while the
    blocked path stays bounded by its block sizes.  Fraction-of-peak is
    None on CPU (no validated peak row — obs/costs.device_peak), and
    real at the same protocol on a chip window.

    ``BENCH_NSCALE_NS`` (comma-separated) overrides the sweep.
    """
    from smartcal_tpu.cal import influence as influence_mod
    from smartcal_tpu.envs.radio import RadioBackend
    from smartcal_tpu.obs import costs as obs_costs

    env_ns = os.environ.get("BENCH_NSCALE_NS", "").strip()
    if env_ns:
        ns = tuple(int(x) for x in env_ns.split(",") if x.strip())
    K = 3
    kw = dict(n_freqs=2, n_times=4, tdelta=2, admm_iters=2,
              lbfgs_iters=2, init_iters=2, npix=128)
    rows = []
    for n in ns:
        backend = RadioBackend(n_stations=n, **kw)
        key = jax.random.PRNGKey(13)
        ep, mdl = backend.new_demixing_episode(key, K)
        rho = np.asarray(mdl.rho, np.float32)
        alpha = np.zeros(K, np.float32)
        statics = backend._influence_statics(kw["npix"])

        # -- unbatched arm: warm once, then time the production routes
        res = backend.calibrate(ep, rho)
        jax.block_until_ready(res.J)
        img = backend.influence_image(ep, res, rho, alpha)
        jax.block_until_ready(img)
        t0 = time.time()
        res = backend.calibrate(ep, rho)
        jax.block_until_ready(res.J)
        t_solve = time.time() - t0
        t0 = time.time()
        img = backend.influence_image(ep, res, rho, alpha)
        jax.block_until_ready(img)
        t_inf = time.time() - t0

        # -- footprint accounting (shape-derived, per compile): blocked
        # vs unblocked influence program at THIS tier
        uvw = np.asarray(ep.obs.uvw).reshape(-1, 3).astype(np.float32)
        hadd_all = influence_mod.consensus_hadd_all(
            rho, alpha, np.asarray(ep.obs.freqs), ep.f0,
            n_poly=backend.n_poly, polytype=backend.polytype)
        common = dict(static_argnames=(), cell=1e-3,
                      n_stations=n, n_chunks=backend.n_chunks,
                      npix=kw["npix"])
        fp_blocked = obs_costs.stage_cost(
            influence_mod.influence_images_multi, res.residual, ep.Ccal,
            res.J, hadd_all, jnp_freqs(ep), uvw,
            block_baselines=statics["block_baselines"],
            precision=statics["precision"], **common)
        fp_unblocked = obs_costs.stage_cost(
            influence_mod.influence_images_multi, res.residual, ep.Ccal,
            res.J, hadd_all, jnp_freqs(ep), uvw,
            block_baselines=0, **common)
        from smartcal_tpu.cal import solver as solver_mod

        fp_solve = obs_costs.stage_cost(
            solver_mod.solve_admm, ep.V, ep.Ccal,
            np.asarray(ep.obs.freqs, np.float32), ep.f0, rho,
            backend._solver_cfg(K), n_chunks=backend.n_chunks)

        # -- batched arm: the PR 9 lane axis at this N
        eps = [ep]
        for lane in range(1, batch_lanes):
            e2, _ = backend.new_demixing_episode(
                jax.random.PRNGKey(13 + lane), K)
            eps.append(e2)
        bep = backend.stack_episodes(eps)
        rho_b = np.tile(rho, (batch_lanes, 1))
        alpha_b = np.tile(alpha, (batch_lanes, 1))
        bres = backend.calibrate_batched(bep, rho_b)
        jax.block_until_ready(bres.J)
        bimg = backend.influence_images_batched(bep, bres, rho_b, alpha_b)
        jax.block_until_ready(bimg)
        t0 = time.time()
        bres = backend.calibrate_batched(bep, rho_b)
        jax.block_until_ready(bres.J)
        t_solve_b = time.time() - t0
        t0 = time.time()
        bimg = backend.influence_images_batched(bep, bres, rho_b, alpha_b)
        jax.block_until_ready(bimg)
        t_inf_b = time.time() - t0

        B = n * (n - 1) // 2
        rows.append({
            "n_stations": n, "n_baselines": B,
            "block_baselines": statics["block_baselines"],
            "precision": statics["precision"],
            "unbatched": {"t_solve_s": round(t_solve, 3),
                          "t_influence_s": round(t_inf, 3)},
            "batched": {"lanes": batch_lanes,
                        "t_solve_s": round(t_solve_b, 3),
                        "t_influence_s": round(t_inf_b, 3),
                        "amortized_solve_s_per_lane":
                            round(t_solve_b / batch_lanes, 3),
                        "amortized_influence_s_per_lane":
                            round(t_inf_b / batch_lanes, 3)},
            "footprint": {
                "solve_peak_bytes": fp_solve.get("peak_bytes"),
                "influence_blocked_peak_bytes":
                    fp_blocked.get("peak_bytes"),
                "influence_unblocked_peak_bytes":
                    fp_unblocked.get("peak_bytes"),
                "influence_flops": fp_blocked.get("flops"),
            },
            "fraction_of_peak": None,     # no validated CPU peak row
        })
    peak_ref = obs_costs.device_peak()
    out = {
        "metric": "nscale",
        "value": rows[-1]["unbatched"]["t_influence_s"] if rows else None,
        "unit": f"seconds (influence, N={ns[-1]}, small tier)",
        "vs_baseline": None,
        "scale": "small tier: Nf=2, T=4 (Ts=2), K=3, npix=128, "
                 "admm 2x2 + init 2 — N is real, iteration depth is not",
        "platform": jax.devices()[0].platform,
        "device_peak": peak_ref,
        "results": rows,
        "full_tier_footprint": _nscale_full_tier_footprint(ns),
        "note": "wall-clock is warm steady-state of the production "
                "routes; footprints are XLA memory_analysis peak live "
                "bytes per compile (obs/costs.py).  fraction_of_peak is "
                "null on CPU (no validated peak row) by design — the "
                "protocol fills it on a chip window.",
    }
    return _write_results_artifact(out, out_path)


def jnp_freqs(ep):
    import jax.numpy as jnp

    return jnp.asarray(np.asarray(ep.obs.freqs), jnp.float32)


def _nscale_full_tier_footprint(ns, npix=1024, n_times=20, tdelta=10,
                                nf=2, k=3):
    """Shape-only (never executed) peak-live-bytes of the FULL-tier
    influence program at each N: the unblocked chain vs the blocked
    kernels (Hessian blocks + R-blocked factored imager).  This is the
    'report both' half of the N=256 acceptance: the unblocked chain is
    demonstrably footprint-bounded (measured ~5.6 GB peak for ONE
    two-band program at N=256/npix=1024 — ~13x the blocked path, and
    the PR 9 lane axis multiplies it past a v5e's 16 GB HBM at 3+
    lanes) while the blocked path stays in the hundreds-of-MB band."""
    import jax.numpy as jnp

    from smartcal_tpu.cal import influence as influence_mod
    from smartcal_tpu.envs import radio as radio_mod
    from smartcal_tpu.obs import costs as obs_costs

    sd = jax.ShapeDtypeStruct
    f32 = jnp.float32
    ts = n_times // tdelta
    rows = []
    for n in ns:
        B = n * (n - 1) // 2
        args = (sd((nf, n_times, B, 2, 2, 2), f32),
                sd((nf, k, n_times * B, 4, 2), f32),
                sd((nf, ts, k, 2 * n, 2, 2), f32),
                sd((nf, k), f32),
                sd((nf,), f32),
                sd((n_times * B, 3), f32))
        common = dict(cell=1e-3, n_stations=n, n_chunks=ts, npix=npix)
        row = {"n_stations": n, "n_baselines": B, "npix": npix,
               "n_times": n_times}
        try:
            fp_un = obs_costs.stage_cost(
                influence_mod.influence_images_multi, *args,
                block_baselines=0, imager_block_r=0, **common)
            row["unblocked_peak_bytes"] = fp_un.get("peak_bytes")
        except Exception as e:  # noqa: BLE001 — report, don't drop
            row["unblocked_peak_bytes"] = None
            row["unblocked_error"] = f"{type(e).__name__}: {e}"
        try:
            # the PRODUCTION block sizes (envs/radio thresholds), so the
            # reported blocked-path bound describes what production runs
            fp_blk = obs_costs.stage_cost(
                influence_mod.influence_images_multi, *args,
                block_baselines=radio_mod._BLOCK_BASELINES,
                imager_block_r=radio_mod._IMAGER_BLOCK_R, **common)
            row["blocked_peak_bytes"] = fp_blk.get("peak_bytes")
        except Exception as e:  # noqa: BLE001
            row["blocked_peak_bytes"] = None
            row["blocked_error"] = f"{type(e).__name__}: {e}"
        if row.get("unblocked_peak_bytes") and row.get(
                "blocked_peak_bytes"):
            row["blocked_over_unblocked"] = round(
                row["blocked_peak_bytes"] / row["unblocked_peak_bytes"],
                4)
        rows.append(row)
    return rows


def _mesh_compose_measure(ns=(62, 256), lanes=2, k_dirs=2):
    """The measurement body of :func:`bench_mesh_compose` (runs in the
    8-device child when the parent backend is single-device)."""
    from smartcal_tpu.envs.radio import RadioBackend
    from smartcal_tpu.obs import costs as obs_costs
    from smartcal_tpu.parallel.mesh import (AXIS_BASELINE, AXIS_LANE,
                                            largest_divisor)

    ndev = jax.device_count()
    rows = []
    for n in ns:
        B = n * (n - 1) // 2
        backend = RadioBackend(n_stations=n, n_freqs=1, n_times=2,
                               tdelta=2, admm_iters=1, lbfgs_iters=2,
                               init_iters=2, npix=32)
        eps, rhos = [], []
        for i in range(lanes):
            ep, mdl = backend.new_demixing_episode(
                jax.random.PRNGKey(7 + i), k_dirs)
            eps.append(ep)
            rhos.append(np.asarray(mdl.rho))
        bep = backend.stack_episodes(eps)
        rho = np.stack(rhos).astype(np.float32)
        alpha = np.zeros_like(rho)
        # one fused-program footprint per N: the lowered cost is the
        # single-device equivalent for EVERY arm — only the per-axis
        # division differs (obs/costs.py sharding-aware accounting)
        nb_full = largest_divisor(B, ndev)
        nb_half = largest_divisor(B, max(ndev // lanes, 1))
        arms = (("unsharded", 0, 0),
                ("lane_only", lanes, 0),
                ("baseline_only", 0, nb_full),
                ("lane_x_baseline", lanes, nb_half))
        fused_peak = None
        arm_rows = []
        for label, nl, nb in arms:
            if label != "unsharded" and max(nl, 1) * max(nb, 1) <= 1:
                # e.g. N=62: B=1891 = 31 x 61 has NO divisor <= 8 — the
                # baseline axis genuinely cannot shard on this mesh
                # (make_mesh would raise MeshFactorizationError); report
                # the fact instead of silently mislabeling the arm
                arm_rows.append({
                    "arm": label, "skipped":
                        f"B={B} has no divisor <= {ndev} "
                        "(baseline axis cannot shard; see "
                        "parallel/mesh.nearest_factorization)"})
                continue
            compose = (nl, nb)
            res = backend.calibrate_batched(bep, rho, compose=compose)
            jax.block_until_ready(res.J)
            img = backend.influence_images_batched(bep, res, rho, alpha,
                                                   compose=compose)
            jax.block_until_ready(img)
            t0 = time.time()
            res = backend.calibrate_batched(bep, rho, compose=compose)
            jax.block_until_ready(res.J)
            t_solve = time.time() - t0
            t0 = time.time()
            img = backend.influence_images_batched(bep, res, rho, alpha,
                                                   compose=compose)
            jax.block_until_ready(img)
            t_inf = time.time() - t0
            if fused_peak is None:
                ops = backend.batched_influence_operands(bep, res, rho,
                                                         alpha)
                fp = obs_costs.stage_cost(
                    backend.batched_influence_callable(bep.n_dirs,
                                                       backend.npix),
                    *ops)
                fused_peak = fp.get("peak_bytes")
            shard_axes = {}
            if nl > 1:
                shard_axes[AXIS_LANE] = nl
            if nb > 1:
                shard_axes[AXIS_BASELINE] = nb
            total = 1
            for s in shard_axes.values():
                total *= s
            row = {"arm": label, "lane_shards": nl, "baseline_shards": nb,
                   "t_solve_s": round(t_solve, 3),
                   "t_influence_s": round(t_inf, 3),
                   "peak_bytes_fused": fused_peak}
            if fused_peak:
                row["peak_bytes_per_shard"] = fused_peak / total
                row["peak_bytes_per_axis"] = {
                    a: fused_peak / s for a, s in shard_axes.items()}
            arm_rows.append(row)
        rows.append({"n_stations": n, "n_baselines": B, "devices": ndev,
                     "lanes": lanes, "arms": arm_rows})
    return rows


def bench_mesh_compose(ns=(62, 256), lanes=2, out_path=None):
    """Composed-mesh influence/solve arms (ISSUE 17 tentpole metric):
    warm wall-clock + per-axis footprint of the batched chain under
    unsharded / lane-only / baseline-only / lane x baseline placement
    at N in {62, 256} (minimal-depth tier — 1 band, 1 chunk, K=2; the
    SHAPES carry the signal, iteration depth does not).

    The footprint columns are the obs/costs.py sharding-aware
    accounting: the fused single-device peak divided per axis
    (``peak_bytes_per_axis`` — what each axis alone buys) and by the
    composed product (``peak_bytes_per_shard`` — the per-device peak on
    the composed mesh).  N=62's B=1891 = 31 x 61 has no divisor <= 8,
    so its baseline arms report the factorization refusal instead of a
    number — the honest shape of the reference scale.

    On a single-device CPU backend the measurement re-runs in a child
    process with 8 virtual host devices (the tests' conftest mesh); an
    already-multi-device parent (chip or forced-host) measures inline.
    ``BENCH_MESH_NS`` (comma-separated) overrides the sweep; the payload
    also lands in ``results/mesh_compose_r16.json`` (or ``out_path``).
    """
    env_ns = os.environ.get("BENCH_MESH_NS", "").strip()
    if env_ns:
        ns = tuple(int(x) for x in env_ns.split(",") if x.strip())
    if jax.device_count() >= 8:
        rows = _mesh_compose_measure(ns, lanes)
    else:
        import tempfile

        with tempfile.NamedTemporaryFile(suffix=".json",
                                         delete=False) as fh:
            tmp = fh.name
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            " --xla_force_host_platform_device_count=8"
                            ).strip()
        env["JAX_PLATFORMS"] = "cpu"
        code = ("import json, bench\n"
                f"rows = bench._mesh_compose_measure({tuple(ns)!r}, "
                f"{int(lanes)})\n"
                f"json.dump(rows, open({tmp!r}, 'w'))\n")
        subprocess.run([sys.executable, "-c", code], check=True, env=env,
                       cwd=os.path.dirname(os.path.abspath(__file__)))
        with open(tmp) as fh:
            rows = json.load(fh)
        os.unlink(tmp)
    sharded = [a for r in rows for a in r["arms"]
               if a.get("arm") == "lane_x_baseline"
               and "t_influence_s" in a]
    out = {
        "metric": "mesh_compose",
        "value": sharded[-1]["t_influence_s"] if sharded else None,
        "unit": f"seconds (influence, lane x baseline, N={ns[-1]})",
        "vs_baseline": None,
        "scale": "minimal-depth tier: Nf=1, T=2 (Ts=1), K=2, npix=32, "
                 "admm 1 — N and the mesh are real, depth is not",
        "platform": jax.devices()[0].platform,
        "results": rows,
        "note": "wall-clock is warm steady-state; footprints are the "
                "fused-program peak divided per axis/shard "
                "(obs/costs.py sharding-aware accounting — shard_map "
                "programs don't AOT-lower through the plain-args "
                "contract).",
    }
    if out_path is None:
        res_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "results")
        if os.path.isdir(res_dir):
            out_path = os.path.join(res_dir, "mesh_compose_r16.json")
    return _write_results_artifact(out, out_path)


def _trace_overhead_measure(duration_s=6.0, rate=40.0, service_s=0.005,
                            lanes=2, replicas=2):
    """Armed-vs-disarmed stub-fleet arms for bench_trace_overhead.

    Same router, same offered load, twice: DISARMED (no RunLog anywhere,
    flight recorder off — every obs call takes the no-op fast path, no
    trace carriers are minted) then ARMED (router + per-replica RunLog
    streams, trace propagation across the IPC frames, flight-recorder
    ring in every worker).  The armed arm also scores its own merged
    trace completeness, so the measurement doubles as a stitching check.
    """
    import contextlib
    import shutil
    import tempfile

    from smartcal_tpu import obs
    from smartcal_tpu.obs import collect
    from smartcal_tpu.serve import loadgen
    from smartcal_tpu.serve.fleet import FleetRouter, sleep_worker_spec

    arms = {}
    for arm in ("disarmed", "armed"):
        workdir = tempfile.mkdtemp(prefix=f"trace_ovh_{arm}_")
        spec = sleep_worker_spec(lanes=lanes, service_s=service_s)
        if arm == "disarmed":
            spec["flight_recorder"] = False
        cm = (obs.recording(os.path.join(workdir, "router.jsonl"),
                            run_id="router")
              if arm == "armed" else contextlib.nullcontext())
        with cm:
            router = FleetRouter(
                spec, replicas=replicas, poll_s=0.05, seed=0,
                metrics_dir=(workdir if arm == "armed" else None))
            try:
                router.start(warm_timeout_s=120.0)
                gen = loadgen.OpenLoopLoadGen(
                    router, [(1, None)] * 4, rate=rate,
                    duration_s=duration_s, seed=0)
                summary = gen.run()
            finally:
                router.stop(timeout=20.0)
        rec = {"jobs_s": summary.get("achieved_jobs_s"),
               "p99_s": summary.get("latency_p99_s"),
               "p50_s": summary.get("latency_p50_s"),
               "completed": summary.get("completed"),
               "submitted": summary.get("submitted"),
               "shed": summary.get("shed")}
        if arm == "armed":
            merged = collect.merge_directory(workdir)
            rec["events_logged"] = len(merged)
            rec["trace_completeness"] = collect.completeness(
                collect.request_paths(merged))
        arms[arm] = rec
        shutil.rmtree(workdir, ignore_errors=True)
    return arms


def bench_trace_overhead(duration_s=None, out_path=None):
    """Distributed-tracing tax on the serving fleet (ISSUE 18 satellite):
    stub-fleet jobs/s + p99 with the full tracing stack ARMED (RunLog
    streams in every process, trace carriers across IPC, flight
    recorder) vs DISARMED (obs no-op fast path).  The claim under test
    is that the tax is within run-to-run noise — the armed fleet keeps
    the disarmed fleet's throughput and tail.

    Runs in a child process pinned to JAX_PLATFORMS=cpu: the stub fleet
    never needs a chip, and the workers must not race the parent for
    one.  ``BENCH_TRACE_OVH_DURATION_S`` overrides the per-arm load
    window; the payload also lands in ``results/trace_overhead_r17.json``
    (or ``out_path``).
    """
    import tempfile

    if duration_s is None:
        try:
            duration_s = float(os.environ.get("BENCH_TRACE_OVH_DURATION_S",
                                              "6"))
        except ValueError:
            duration_s = 6.0
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as fh:
        tmp = fh.name
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    code = ("import json, bench\n"
            f"arms = bench._trace_overhead_measure({float(duration_s)!r})\n"
            f"json.dump(arms, open({tmp!r}, 'w'))\n")
    subprocess.run([sys.executable, "-c", code], check=True, env=env,
                   cwd=os.path.dirname(os.path.abspath(__file__)))
    with open(tmp) as fh:
        arms = json.load(fh)
    os.unlink(tmp)
    dis, arm = arms["disarmed"], arms["armed"]
    delta = None
    if dis.get("jobs_s") and arm.get("jobs_s"):
        delta = round((arm["jobs_s"] - dis["jobs_s"]) / dis["jobs_s"], 4)
    out = {
        "metric": "trace_overhead",
        "value": delta,
        "unit": "relative jobs/s delta, armed vs disarmed (0 = free)",
        "vs_baseline": None,
        "platform": "cpu (stub fleet, child process)",
        "duration_s_per_arm": duration_s,
        "results": arms,
        "note": "open-loop stub fleet (2 replicas x 2 lanes, 5 ms "
                "service): both arms are offered the same load, so the "
                "tracing tax shows up as lost throughput or a fatter "
                "p99, not as a different workload.",
    }
    if out_path is None:
        res_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "results")
        if os.path.isdir(res_dir):
            out_path = os.path.join(res_dir, "trace_overhead_r17.json")
    return _write_results_artifact(out, out_path)


def _sentinel_overhead_measure(duration_s=6.0, rate=5.0):
    """Child-process worker for :func:`bench_sentinel_overhead`: one
    warmed tiny CalibServer per arm under IDENTICAL open-loop load,
    numerics sentinel off vs sampling every batch.  The sequential
    parity oracle is pre-warmed in BOTH arms so the comparison measures
    steady-state sentinel cost, not a one-time compile."""
    import tempfile

    from smartcal_tpu import obs as _obs
    from smartcal_tpu.envs.radio import RadioBackend
    from smartcal_tpu.serve import CalibServer
    from smartcal_tpu.serve.loadgen import (SERVE_TIERS, OpenLoopLoadGen,
                                            build_job_pool)

    M, lanes = 3, 3
    be = RadioBackend(**SERVE_TIERS["tiny"])
    pool = build_job_pool(be, M, 6, seed=5)
    cache = tempfile.mkdtemp(prefix="sentinel_ovh_cache_")
    arms = {}
    for arm, every in (("off", 0), ("on", 1)):
        rl_path = os.path.join(tempfile.mkdtemp(prefix="sentinel_ovh_"),
                               f"{arm}.jsonl")
        rl = _obs.RunLog(rl_path, run_id=f"sentinel-{arm}",
                         flush_lines=64)
        _obs.activate(rl)
        srv = CalibServer(be, M=M, lanes=lanes, cache_dir=cache,
                          compile_cache=True, max_wait_s=0.02,
                          sentinel_every=every)
        srv.warmup(seed=7)
        k0, ep0 = pool[0]
        srv._oracle_result(ep0, np.ones(M, np.float32),
                           np.ones(M, np.float32),
                           np.zeros(M, np.float32),
                           SERVE_TIERS["tiny"]["admm_iters"])
        srv.start()
        summary = OpenLoopLoadGen(srv, pool, rate=rate,
                                  duration_s=duration_s, seed=3).run()
        srv.stop()
        stats = srv.stats()
        while _obs.active() is not None:
            _obs.deactivate()
        n_drift_events = 0
        with open(rl_path) as fh:
            for line in fh:
                if '"numerics_drift"' in line:
                    n_drift_events += 1
        arms[arm] = {"jobs_s": summary.get("achieved_jobs_s"),
                     "p99_s": summary.get("latency_p99_s"),
                     "completed": summary.get("completed"),
                     "shed": summary.get("shed"),
                     "sentinel": stats.get("sentinel"),
                     "numerics_drift_events": n_drift_events}
    return arms


def bench_sentinel_overhead(duration_s=None, out_path=None):
    """Numerics-sentinel tax on serving (ISSUE 19): sustained jobs/s +
    p99 of a warmed tiny CalibServer with the sentinel sampling EVERY
    batch vs disabled, both arms offered the same open-loop load — the
    trace_overhead protocol applied to the parity-oracle replays.  The
    claim under test is that the replay (breaker thread, off the hot
    path) leaves throughput and tail within run-to-run noise.

    Runs in a child process pinned to JAX_PLATFORMS=cpu (same isolation
    rationale as bench_trace_overhead); the payload also lands in
    ``results/sentinel_overhead_r18.json`` (or ``out_path``).
    """
    import tempfile

    if duration_s is None:
        try:
            duration_s = float(os.environ.get(
                "BENCH_SENTINEL_OVH_DURATION_S", "6"))
        except ValueError:
            duration_s = 6.0
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as fh:
        tmp = fh.name
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    code = ("import json, bench\n"
            f"arms = bench._sentinel_overhead_measure({float(duration_s)!r})\n"
            f"json.dump(arms, open({tmp!r}, 'w'))\n")
    subprocess.run([sys.executable, "-c", code], check=True, env=env,
                   cwd=os.path.dirname(os.path.abspath(__file__)))
    with open(tmp) as fh:
        arms = json.load(fh)
    os.unlink(tmp)
    off, on = arms["off"], arms["on"]
    delta = None
    if off.get("jobs_s") and on.get("jobs_s"):
        delta = round((on["jobs_s"] - off["jobs_s"]) / off["jobs_s"], 4)
    out = {
        "metric": "sentinel_overhead",
        "value": delta,
        "unit": "relative jobs/s delta, sentinel on vs off (0 = free)",
        "vs_baseline": None,
        "platform": "cpu (tiny CalibServer, child process)",
        "duration_s_per_arm": duration_s,
        "results": arms,
        "note": "sentinel_every=1 (every batch sampled) is the WORST "
                "case — production would sample sparsely.  The replay "
                "runs on the breaker thread; on a 1-core host it still "
                "competes for the CPU, which is exactly the cost being "
                "measured.",
    }
    if out_path is None:
        res_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "results")
        if os.path.isdir(res_dir):
            out_path = os.path.join(res_dir, "sentinel_overhead_r18.json")
    return _write_results_artifact(out, out_path)


def bench_actor_scaling(arms=None, episodes=16, out_path=None,
                        replay_shards=4):
    """Aggregate env-steps/s of the supervised async actor-learner fleet
    vs fleet shape (ISSUE 10 tentpole metric, extended past the thread
    ceiling by ISSUE 12).

    Each arm runs the full pipeline — N actors, each driving 2 batched
    env lanes off an episode-frozen snapshot, feeding the mesh-sharded
    device-resident learner's fused store->PER-sample->learn->priority
    step with IMPACT IS-clipping armed (is_clip=2) — and reports the
    STEADY-STATE aggregate throughput: continuous wall clock from the
    end of the warmup rounds through loop exit, counting ingest,
    telemetry and bookkeeping (run_supervised_loop's summary), so queue
    pre-fill bursts cannot inflate the number.  The default sweep
    continues results/actor_scaling_r10.json past the thread ceiling:
    the r10 4-thread point for continuity, then actor PROCESSES at 1,
    4, 8 on one host and an 8-process arm split over 2 SIMULATED hosts
    (``sim_hosts=2`` — contiguous slot blocks tagged with host ids).
    Every arm records the staleness the IS-clip absorbed
    (``transition_staleness_mean``) and how hard the clip worked
    (``is_clip_saturation``).  CPU-safe scale (tiny enet MLPs);
    ``out_path`` additionally writes the payload as a results artifact.
    """
    from smartcal_tpu.parallel import learner as plearner

    # thread arms keep the r10 configuration (FLAT buffer) so the old
    # curve's points stay comparable; process arms run the new regime
    # (mesh-sharded replay).  On one CPU the sharded sample/merge is
    # pure overhead (every "shard" shares the same core budget) — its
    # win is hardware-shaped; what this sweep shows is that actor
    # PROCESSES keep scaling where threads flatten, with the sharded
    # store/sample in the loop.
    arms = arms or (
        {"label": "thread-1", "mode": "thread", "n_actors": 1,
         "shards": 0},
        {"label": "thread-4", "mode": "thread", "n_actors": 4,
         "shards": 0},
        {"label": "process-1", "mode": "process", "n_actors": 1},
        {"label": "process-4", "mode": "process", "n_actors": 4},
        {"label": "process-8", "mode": "process", "n_actors": 8},
        {"label": "process-8x2host", "mode": "process", "n_actors": 8,
         "sim_hosts": 2},
    )
    if jax.devices()[0].platform == "cpu":
        # spawned actor workers read the ENV, not this process's
        # jax.config — pin them to the platform the parent actually
        # measured on (a dead-tunnel env var must not wedge the fleet)
        os.environ["JAX_PLATFORMS"] = "cpu"
    per_n = []
    for arm in arms:
        shards = arm.get("shards", replay_shards)
        _, _, summary = plearner.train_supervised(
            seed=0, episodes=episodes, n_actors=arm["n_actors"],
            agent_kwargs={"batch_size": 32, "mem_size": 4096},
            rollout_epochs=2, rollout_steps=10, batch_envs=2,
            is_clip=2.0, quiet=True, actor_mode=arm["mode"],
            sim_hosts=arm.get("sim_hosts", 1),
            replay_shards=shards)
        per_n.append({
            "label": arm["label"],
            "actor_mode": arm["mode"],
            "n_actors": arm["n_actors"],
            "sim_hosts": arm.get("sim_hosts", 1),
            "replay_shards": shards,
            "env_steps_per_s": summary["env_steps_per_s"],
            "transitions_steady": summary["transitions_steady"],
            "wall_steady_s": summary["wall_steady_s"],
            "rounds": summary["rounds"],
            "restarts": summary["restarts"],
            "transition_staleness_mean":
                summary.get("transition_staleness_mean"),
            "is_clip_saturation": summary.get("is_clip_saturation"),
            "critic_loss_mean": summary.get("critic_loss_mean"),
        })
    base = next((r["env_steps_per_s"] for r in per_n
                 if r["n_actors"] == 1 and r["env_steps_per_s"]), None)
    for row in per_n:
        # an arm that never reached steady state (too few non-empty
        # rounds) reports None — mark it failed rather than fabricating
        # a ratio against a sub-nanosecond denominator
        if row["env_steps_per_s"] is None:
            row["failed"] = "no steady-state window (run ended within " \
                            "the warmup rounds)"
        row["speedup_vs_1_actor"] = (
            round(row["env_steps_per_s"] / base, 3)
            if base and row["env_steps_per_s"] is not None else None)
    best = max(per_n, key=lambda r: r["env_steps_per_s"] or 0.0)
    out = {
        "metric": "actor_scaling",
        "value": best["env_steps_per_s"],
        "unit": "env-steps/sec aggregate",
        "vs_baseline": None,
        "scale": "enet default env, 2 lanes/actor, rollout 2x10, "
                 f"is_clip=2.0, replay_shards={replay_shards} (CPU-safe)",
        "platform": jax.devices()[0].platform,
        "host_cores": os.cpu_count(),
        "episodes_per_arm": episodes,
        "results": per_n,
        "note": "steady-state continuous-wall aggregate env-steps/s of "
                "the supervised fleet (thread AND process actor modes, "
                "mesh-sharded device-resident replay); warmup rounds "
                "excluded.  process-8x2host = 8 worker processes split "
                "over 2 simulated hosts on this machine — a topology "
                "rehearsal, not a second physical host",
    }
    return _write_results_artifact(out, out_path)


def main():
    # SMARTCAL_OBS=<path> records the whole bench as an obs run: backend
    # spans (simulate/solve/influence routes), solver telemetry, compile
    # events, and structured chip-probe results — aggregate with
    # tools/obs_report.py.  Unset: every obs hook is a strict no-op, so
    # timed sections are untouched (the acceptance bar for this layer).
    from smartcal_tpu import obs

    obs_path = os.environ.get("SMARTCAL_OBS", "").strip()
    # --compile-cache <dir> (or SMARTCAL_COMPILE_CACHE): persistent XLA
    # compilation cache — a repeat bench on the same host skips every
    # first-compile, and the hit/miss counters land in the obs stream
    cache_dir = os.environ.get("SMARTCAL_COMPILE_CACHE", "").strip()
    if "--compile-cache" in sys.argv:
        i = sys.argv.index("--compile-cache")
        if i + 1 >= len(sys.argv):
            raise SystemExit("--compile-cache requires a directory")
        cache_dir = sys.argv[i + 1]
    if cache_dir:
        from smartcal_tpu.serve.export import enable_compile_cache
        enable_compile_cache(cache_dir)
    runlog = None
    if obs_path:
        runlog = obs.RunLog(obs_path, meta={"entry": "bench"})
        obs.activate(runlog)
        obs.install_compile_listener()
        if cache_dir:
            obs.install_cache_listener()
    stopped, insurance = _pause_competitors()
    try:
        _measured_main()
    finally:
        _resume_competitors(stopped, insurance)
        if runlog is not None:
            obs.log_memory_gauges()
            obs.flush_counters(reset=True)
            obs.deactivate(runlog)
            runlog.close()


def _measured_main():
    platform, note = probe_backend()
    if platform != "tpu":
        # wedge-proof: measure on CPU rather than hang on a dead tunnel
        jax.config.update("jax_platforms", "cpu")
    # uncontended-window gate (VERDICT r4 item 4): the competitors are
    # paused; wait for the trailing 1-min loadavg to actually settle
    # before timing anything, and flag the payload loudly if it never
    # does (chip_checks refuses to promote a primary with load >= 1.2)
    settled_load = _settle_load()

    # Round-4 primary protocol: SAME sequential 1:1 computation as rounds
    # 1-3 (strictly sequential episodes, one learn per env step — parity
    # with the reference loop is tested in tests/test_epblock.py), but
    # PRIMARY_BLOCK whole episodes run per device dispatch via lax.scan.
    # The old one-dispatch-per-episode number is reported as the
    # per_episode_dispatch extra; on the chip that protocol measured the
    # tunnel round trip, not the framework (VERDICT r3 item 2).
    # BENCH_TRACE_DIR=<dir> captures a jax.profiler trace of the timed
    # section (view with tensorboard --logdir <dir>).
    value = measure_epblock(PRIMARY_BLOCK, PRIMARY_TIMED_BLOCKS,
                            os.environ.get("BENCH_TRACE_DIR"))
    baseline, baseline_platform = load_baseline_info()
    dispatch = f"episode_block({PRIMARY_BLOCK})"

    out = {
        "metric": "enet_sac_env_steps_per_sec",
        "value": round(value, 2),
        "unit": "env-steps/sec/chip",
        "vs_baseline": round(value / baseline, 2),
        # what vs_baseline divides by: the torch reference on THIS host's
        # CPU (tools/reference_baseline.json), NOT an A100 — upstream
        # publishes no numbers (BASELINE.md)
        "baseline_platform": baseline_platform,
        "dispatch": dispatch,
        # gate value = the WORSE of (settled pre-measurement load, load
        # right after the timed section): sweeps are SIGSTOPped and the
        # trailing 1-min average was given time to decay before timing,
        # so >= 1.2 on either side means something beyond the known
        # background jobs contended the window — flagged, and
        # chip_checks refuses to promote it.  Both components are
        # reported so a mid-run arrival is distinguishable from a
        # never-settled start.
        "host_load_avg_1m": round(max(settled_load, os.getloadavg()[0]), 2),
        "host_load_pre_timed_1m": round(settled_load, 2),
        "host_load_post_timed_1m": round(os.getloadavg()[0], 2),
    }
    if out["host_load_avg_1m"] >= 1.2:
        out["contended"] = ("loadavg exceeded 1.2 around the timed section "
                            "with sweeps paused; treat the value as a "
                            "lower bound")
    if platform != "tpu":
        out["platform"] = f"cpu ({note})"
        # the tunnel is intermittent (see results/refscale_tpu.md): when a
        # CPU fallback happens at round end, surface the round's validated
        # on-chip capture alongside so the chip number isn't lost —
        # clearly labeled as a prior capture, not this run.  Preference:
        # the clean uncontended capture, else the contended chip-session
        # one (both are data files in results/, never code literals).
        here = os.path.dirname(os.path.abspath(__file__))
        results_dir = os.path.join(here, "results")
        # newest round first; the capture scripts maintain the
        # latest_chip_capture.json pointer copy (ADVICE r3: no hardcoded
        # round names — a round-5 CPU fallback must not resurrect r3)
        candidates = ["latest_chip_capture.json"]
        import re

        def round_no(name):
            m = re.search(r"_r(\d+)\.json$", name)
            return int(m.group(1)) if m else -1

        for pat in ("bench_primary_r", "chip_primary_contended_r"):
            try:
                matches = sorted(
                    (f for f in os.listdir(results_dir)
                     if f.startswith(pat) and f.endswith(".json")),
                    key=round_no, reverse=True)  # numeric: r10 before r9
            except OSError:
                matches = []
            candidates.extend(matches)
        for cap in candidates:
            try:
                with open(os.path.join(results_dir, cap)) as f:
                    prior = json.load(f)
                # rounds-1/2/3 captures predate the episode-block primary
                # and carry no "dispatch" field — label the protocol so a
                # tunnel-bound per-episode number is never read as the
                # chip value of the (much faster) epblock primary
                prior_dispatch = prior.get("dispatch",
                                           "per_episode_dispatch")
                out["prior_tpu_capture"] = {
                    "value": prior["value"], "unit": prior["unit"],
                    "vs_baseline": prior["vs_baseline"],
                    "source": f"results/{cap}",
                    "dispatch": prior_dispatch,
                    **({"protocol_mismatch":
                        "prior capture used a different dispatch protocol "
                        "than this run's primary; values not comparable"}
                       if prior_dispatch != dispatch else {}),
                    **({"caveat": prior["caveat"]} if "caveat" in prior
                       else {})}
                break
            except (OSError, KeyError, ValueError, TypeError):
                continue
    # never let the optional extras discard the measured primary metric.
    # BENCH_SKIP_CALIB skips ONLY the expensive N=62 calib episode (it is
    # minutes of compile on a cold chip and hours on CPU); the cheap
    # throughput extras always run.  BENCH_SKIP_EXTRAS skips everything.
    # flush the measured primary to a side artifact BEFORE the extras loop:
    # the single JSON line only prints at process end, so a driver-side
    # timeout during a wedged extra (cold-chip compiles run 10-25 min)
    # would otherwise lose the already-measured number (ADVICE r3)
    partial_path = os.environ.get("BENCH_PRIMARY_ARTIFACT",
                                  "/tmp/bench_primary_partial.json")
    try:
        with open(partial_path, "w") as fh:
            json.dump(out, fh)
    except OSError:
        pass
    if not os.environ.get("BENCH_SKIP_EXTRAS"):
        out["extra"] = []
        # epblock first: chip_checks.extras_done requires it for artifact
        # promotion, and on a cold chip cache the earlier extras' compiles
        # can exhaust the extras time budget
        extras = [(bench_epblock_throughput,
                   "enet_sac_env_steps_per_sec_epblock"),
                  (bench_batched_throughput,
                   "enet_sac_env_steps_per_sec_batched"),
                  (bench_batched_block_throughput,
                   "enet_sac_env_steps_per_sec_batched_epblock"),
                  (bench_per_episode_dispatch,
                   "enet_sac_env_steps_per_sec_per_episode_dispatch"),
                  (bench_calib_batched,
                   "calib_batched_env_steps_per_sec"),
                  (bench_actor_scaling, "actor_scaling"),
                  (bench_nscale, "nscale"),
                  (bench_mesh_compose, "mesh_compose"),
                  (bench_trace_overhead, "trace_overhead"),
                  (bench_sentinel_overhead, "sentinel_overhead")]
        if os.environ.get("BENCH_SKIP_CALIB"):
            out["extra"].append({"metric": "calib_episode_wall_clock",
                                 "skipped": "BENCH_SKIP_CALIB=1"})
        elif platform == "tpu":
            extras.append((bench_calib_episode, "calib_episode_wall_clock"))
        else:
            # N=62 x Nf=8 takes hours on one CPU core — don't let the CPU
            # fallback turn the whole bench into a hang.  The pipelined-
            # vs-host-loop comparison still runs, at the reduced
            # CPU-fallback scale under its own metric name (never
            # confusable with a chip-scale capture).
            out["extra"].append({"metric": "calib_episode_wall_clock",
                                 "skipped": "no TPU (CPU fallback active; "
                                 "see calib_episode_wall_clock_cpu_"
                                 "fallback)"})
            extras.append((lambda: bench_calib_episode(small=True),
                           "calib_episode_wall_clock_cpu_fallback"))
        # time budget across extras: if a driver-side timeout killed the
        # process mid-extra, the already-measured primary (printed only at
        # the end) would be lost — skip remaining extras instead.  Chip
        # compiles can eat 10-25 min each on a cold cache, CPU block modes
        # minutes; 1500 s keeps the full set on a warm cache.
        try:
            extras_budget = float(os.environ.get("BENCH_EXTRAS_BUDGET_S",
                                                 "1500"))
        except ValueError:
            extras_budget = 1500.0
        t_extras = time.time()
        for fn, name in extras:
            # keep the window-lock mtime fresh: cooperating CPU jobs
            # expire a stale lock by age, and a cold-chip extra can
            # outlive the expiry window (no-op unless we own the lock)
            _refresh_window_lock()
            if time.time() - t_extras > extras_budget:
                out["extra"].append({"metric": name,
                                     "skipped": "extras time budget "
                                                f"({extras_budget:.0f}s) spent"})
                continue
            try:
                # every extra payload carries the host fingerprint (the
                # shared-builder backfill: see _stamp_fingerprint)
                out["extra"].append(_stamp_fingerprint(fn()))
            except Exception as e:  # noqa: BLE001 — report, don't drop
                out["extra"].append({"metric": name,
                                     "error": f"{type(e).__name__}: {e}"})
    _stamp_fingerprint(out)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
