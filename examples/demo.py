"""Runnable walkthrough of the framework (the reference Demo.ipynb role).

Mirrors the reference notebook's two demonstrations (`Demo.ipynb`):
  1. an RL agent learning the elastic-net regularization by trial and
     error (the notebook's ENetEnv + agent loop, 200 games), and
  2. influence maps of radio data (the notebook's `influence_maps.png`
     figure) — what calibration hides in the residual, visualized.

TPU-framework equivalents are used throughout: the jitted episode loop
(whole episodes under one dispatch), the split-real radio backend, and
the first-party FITS writer.  Figures land in ``results/demo/``.

Run (CPU fallback is fine for the demo scale):
    python examples/demo.py [--episodes 40] [--platform cpu]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--episodes", default=40, type=int)
    p.add_argument("--platform", default=None, choices=["cpu", "axon"])
    p.add_argument("--outdir", default="results/demo")
    args = p.parse_args()

    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp
    import numpy as np

    from smartcal_tpu.envs import enet
    from smartcal_tpu.rl import replay as rp
    from smartcal_tpu.rl import sac
    from smartcal_tpu.train.enet_sac import make_episode_fn
    from smartcal_tpu.train.plots import gray_to_unit, plot_rewards
    from smartcal_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    os.makedirs(args.outdir, exist_ok=True)

    # ---- 1. elastic-net regularization agent (Demo.ipynb's main loop:
    # N=M=20, 2 actions, the agent tunes lambda1/lambda2 per episode)
    env_cfg = enet.EnetConfig(M=20, N=20)
    agent_cfg = sac.SACConfig(obs_dim=env_cfg.obs_dim, n_actions=2,
                              gamma=0.99, tau=0.005, batch_size=64,
                              mem_size=1024, lr_a=1e-3, lr_c=1e-3,
                              reward_scale=20.0, alpha=0.03)
    key = jax.random.PRNGKey(0)
    key, k0 = jax.random.split(key)
    agent_state = sac.sac_init(k0, agent_cfg)
    buf = rp.replay_init(agent_cfg.mem_size,
                         rp.transition_spec(env_cfg.obs_dim, 2))
    episode_fn = make_episode_fn(env_cfg, agent_cfg, steps=5,
                                 use_hint=False)
    scores = []
    t0 = time.time()
    for i in range(args.episodes):
        key, k = jax.random.split(key)
        agent_state, buf, score = episode_fn(agent_state, buf, k)
        scores.append(float(score))
        if (i + 1) % 10 == 0:
            print(f"episode {i + 1}/{args.episodes} "
                  f"score {scores[-1]:.2f} "
                  f"avg10 {np.mean(scores[-10:]):.2f}", flush=True)
    print(f"enet training: {args.episodes} episodes in "
          f"{time.time() - t0:.0f}s", flush=True)
    plot_rewards(np.asarray(scores),
                 out_png=os.path.join(args.outdir, "enet_rewards.png"),
                 labels=["elastic-net SAC agent (N=M=20)"],
                 rescale=False)   # raw enet rewards, not demixing AIC units

    # ---- 2. influence maps of a simulated LOFAR observation (the
    # notebook's influence_maps.png: data image next to the influence
    # image, which exposes structure the residual hides)
    from smartcal_tpu.cal import fits_io
    from smartcal_tpu.envs.radio import RadioBackend

    backend = RadioBackend(n_stations=14, n_freqs=2, n_times=20, tdelta=10,
                           admm_iters=3, lbfgs_iters=4, init_iters=10,
                           npix=128)
    key = jax.random.PRNGKey(3)
    ep, mdl = backend.new_demixing_episode(key, K=3)
    t0 = time.time()
    res = backend.calibrate(ep, mdl.rho, mask=np.ones(3, np.float32))
    img_inf = np.asarray(backend.influence_image(
        ep, res, mdl.rho, np.zeros(3, np.float32)))
    img_data = np.asarray(backend.data_image(ep))
    print(f"calibrate+influence: {time.time() - t0:.0f}s  "
          f"sigma_data {float(res.sigma_data):.2f} -> "
          f"sigma_res {float(res.sigma_res):.2f}", flush=True)

    # FITS is the interchange surface a reference user expects
    fits_io.write_image(os.path.join(args.outdir, "influence.fits"),
                        img_inf, ra0=float(ep.obs.ra0),
                        dec0=float(ep.obs.dec0))
    from smartcal_tpu.train.plots import _plt
    plt = _plt()
    fig, axes = plt.subplots(1, 2, figsize=(9, 4.2))
    for ax, img, ttl in ((axes[0], img_data, "data (Stokes I)"),
                         (axes[1], img_inf, "influence map")):
        ax.imshow(gray_to_unit(img)[0], cmap="gray", origin="lower")
        ax.set_title(ttl)
        ax.set_xticks([])
        ax.set_yticks([])
    fig.tight_layout()
    fig.savefig(os.path.join(args.outdir, "influence_maps.png"), dpi=110)
    plt.close(fig)

    summary = {
        "enet_final_avg10": float(np.mean(scores[-10:])),
        "enet_first_avg10": float(np.mean(scores[:10])),
        "sigma_data": float(res.sigma_data),
        "sigma_res": float(res.sigma_res),
        "platform": jax.devices()[0].platform,
    }
    with open(os.path.join(args.outdir, "summary.json"), "w") as fh:
        json.dump(summary, fh, indent=1)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
