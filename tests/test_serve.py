"""Serving subsystem: AOT export cache, heterogeneous-lane micro-
batching, supervision-as-circuit-breaker, SLO telemetry.

The load-bearing claims, each pinned here:

* HETEROGENEITY RIDES ONE COMPILE — jobs with different K / rho /
  maxiter splice into one ``BatchedEpisode`` and run the programs
  exported at warmup; after warmup the compile-listener counter must
  not move, and every lane must match the sequential per-episode
  ``calibrate`` oracle (EXACTLY: serving and training jit the identical
  callable).
* WARM RESTART — a second server on the same cache dir deserializes
  every program (``source == "cache"``, zero export-cache misses)
  instead of re-tracing.
* DEGRADATION — a non-finite batched lane re-routes through the
  sequential robust solve and marks the job ``degraded`` rather than
  failing the batch.
* BREAKER — a crashing batch worker fails the in-flight futures, and a
  slot past ``max_restarts`` opens the circuit: ``submit`` sheds with
  ``ShedError("circuit_open")``.
"""

import time

import numpy as np
import pytest

from smartcal_tpu import obs
from smartcal_tpu.envs.radio import RadioBackend
from smartcal_tpu.runtime.backoff import BackoffPolicy
from smartcal_tpu.serve import (CalibServer, Job, MicroBatcher, ShedError)

M = 3
LANES = 3
SEED = 7


def tiny_backend(**kw):
    args = dict(n_stations=6, n_freqs=2, n_times=4, tdelta=2,
                admm_iters=2, lbfgs_iters=3, init_iters=5, npix=32)
    args.update(kw)
    return RadioBackend(**args)


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    """One warmed (never started) server + an active RunLog for the
    whole module: the export build runs ONCE.  ``compile_cache=False``
    keeps the process-global XLA cache config untouched for the rest of
    the suite."""
    obs.install_compile_listener()
    path = tmp_path_factory.mktemp("serve") / "run.jsonl"
    rl = obs.RunLog(str(path), run_id="serve-test", flush_lines=1)
    obs.activate(rl)
    be = tiny_backend()
    cache = str(tmp_path_factory.mktemp("serve_cache"))
    srv = CalibServer(be, M=M, lanes=LANES, cache_dir=cache,
                      compile_cache=False, max_wait_s=0.02)
    warm = srv.warmup(seed=SEED)
    yield be, srv, warm, cache, str(path)
    while obs.active() is not None:
        obs.deactivate()


def _jobs(be, specs, seed=SEED + 1):
    """(k, maxiter) specs -> jobs with distinct pinned rho per job."""
    import jax

    key = jax.random.PRNGKey(seed)
    jobs = []
    for i, (k, maxiter) in enumerate(specs):
        key, sub = jax.random.split(key)
        ep, _ = be.new_calib_episode(sub, k, M)
        rho = np.linspace(0.5 + i, 1.5 + i, k).astype(np.float32)
        jobs.append(Job(episode=ep, k=k, rho=rho, maxiter=maxiter))
    return jobs


class TestHeterogeneousBatch:
    SPECS = [(2, 2), (3, 3), (2, 4)]     # (k, maxiter) per lane — all mixed

    @pytest.fixture(scope="class")
    def batch_run(self, served):
        be, srv, _, _, _ = served
        jobs = _jobs(be, self.SPECS)
        c0 = obs.counters_snapshot().get("jax_compile_events", 0.0)
        n = srv.process_once(jobs, timeout=0.01)
        c1 = obs.counters_snapshot().get("jax_compile_events", 0.0)
        return jobs, n, c1 - c0

    def test_mixed_k_rho_maxiter_share_one_warm_program(self, batch_run):
        jobs, n, compile_delta = batch_run
        assert n == len(self.SPECS)
        assert compile_delta == 0, (
            f"{compile_delta} compile events for a heterogeneous batch "
            "after warmup — per-request K/rho/maxiter must be traced "
            "operands of the exported program")
        lanes = {j.future.result(timeout=1).lane for j in jobs}
        assert lanes == set(range(len(self.SPECS)))

    def test_each_lane_matches_sequential_oracle(self, batch_run, served):
        be = served[0]
        for j in batch_run[0]:
            got = j.future.result(timeout=1)
            rho = np.ones(M, np.float32)
            rho[:j.k] = j.rho
            mask = np.zeros(M, np.float32)
            mask[:j.k] = 1.0
            want = be.calibrate(j.episode, rho, mask=mask,
                                admm_iters=j.maxiter)
            # identical callable, two compilation paths -> exact match
            np.testing.assert_array_equal(
                got.sigma_res, np.asarray(want.sigma_res))
            assert not got.degraded

    def test_request_events_carry_slo_fields(self, batch_run, served):
        path = served[4]
        import json
        evs = [json.loads(ln) for ln in open(path).read().splitlines()]
        reqs = [e for e in evs if e.get("event") == "serve_request"
                and not e.get("warm")]
        assert len(reqs) >= len(self.SPECS)
        for e in reqs:
            assert e["queue_wait_s"] >= 0
            assert e["service_s"] > 0
            assert e["total_s"] >= e["service_s"]
        # warmup probes are tagged OUT of the SLO population
        warm = [e for e in evs if e.get("event") == "serve_request"
                and e.get("warm")]
        assert len(warm) == LANES


def test_warm_restart_deserializes_every_program(served, tmp_path):
    """Second server, same cache dir: every program comes back
    ``source == "cache"`` with zero export-cache misses — the restart
    never re-traces (and with the persistent XLA cache armed in prod,
    never re-compiles: tools/smoke_serve.sh measures that half)."""
    be, _, warm0, cache, _ = served
    assert warm0["sources"] == {"solve": "export", "influence": "export"}
    c0 = obs.counters_snapshot()
    srv2 = CalibServer(tiny_backend(), M=M, lanes=LANES, cache_dir=cache,
                       compile_cache=False)
    warm = srv2.warmup(seed=SEED)
    assert warm["sources"] == {"solve": "cache", "influence": "cache"}
    assert warm["export_cache_miss"] == 0
    c1 = obs.counters_snapshot()
    assert c1.get("export_cache_hit", 0) - c0.get("export_cache_hit", 0) == 2
    # and the restarted server actually serves
    jobs = _jobs(be, [(2, 2), (3, 2), (2, 3)])
    assert srv2.process_once(jobs, timeout=0.01) == 3
    for j in jobs:
        assert np.isfinite(j.future.result(timeout=1).sigma_res)


def test_degraded_lane_reroutes_through_sequential_solve(served):
    """A non-finite batched lane result must come back ``degraded`` via
    the sequential ``solve_admm_safe`` route, not fail the batch."""
    be, srv, _, _, _ = served
    real = srv._program("solve")

    class NaNLane0:
        source = "test"

        def __call__(self, *args):
            res = real(*args)
            sig = np.asarray(res.sigma_res).copy()
            sig[0] = np.nan
            return res._replace(sigma_res=sig)

    with srv._lock:
        srv._programs = dict(srv._programs, solve=NaNLane0())
    try:
        jobs = _jobs(be, [(2, 2), (2, 2)])
        assert srv.process_once(jobs, timeout=0.01) == 2
        r0 = jobs[0].future.result(timeout=1)
        r1 = jobs[1].future.result(timeout=1)
    finally:
        with srv._lock:
            srv._programs = dict(srv._programs, solve=real)
    assert r0.degraded and np.isfinite(r0.sigma_res)
    assert not r1.degraded
    assert srv.stats()["degraded"] >= 1


def test_submit_validates_job_shape(served):
    import jax

    be, srv, _, _, _ = served
    ep, _ = be.new_calib_episode(jax.random.PRNGKey(0), 2, M)
    with pytest.raises(ValueError, match="outside"):
        srv.submit(Job(episode=ep, k=M + 1))
    ep2, _ = be.new_calib_episode(jax.random.PRNGKey(0), 2, 2)
    with pytest.raises(ValueError, match="padded"):
        srv.submit(Job(episode=ep2, k=2))


# ---------------------------------------------------------------------------
# MicroBatcher (no jax, no backend)
# ---------------------------------------------------------------------------

def _stub_job(deadline_s=None):
    return Job(episode=None, k=1, deadline_s=deadline_s)


class TestMicroBatcher:
    def test_full_lanes_flush_immediately(self):
        b = MicroBatcher(lanes=3, max_wait_s=5.0)
        for _ in range(3):
            b.submit(_stub_job())
        t0 = time.monotonic()
        batch = b.next_batch(timeout=0.1)
        assert len(batch) == 3
        assert time.monotonic() - t0 < 1.0      # never waited max_wait_s

    def test_max_wait_flushes_partial_batch(self):
        b = MicroBatcher(lanes=4, max_wait_s=0.05)
        b.submit(_stub_job())
        t0 = time.monotonic()
        batch = b.next_batch(timeout=0.1)
        dt = time.monotonic() - t0
        assert len(batch) == 1
        assert 0.03 <= dt < 1.0                 # held ~max_wait_s, not more

    def test_deadline_pulls_flush_earlier_than_max_wait(self):
        b = MicroBatcher(lanes=4, max_wait_s=10.0, service_est_s=1.0)
        b.submit(_stub_job(deadline_s=1.0))     # slack = 1.0 - 1.0 = now
        t0 = time.monotonic()
        batch = b.next_batch(timeout=0.1)
        assert len(batch) == 1
        assert time.monotonic() - t0 < 1.0
        # EWMA feedback moves the estimate the deadline pull reads
        b.note_service_time(2.0)
        assert b.service_estimate_s() > 1.0

    def test_bounded_queue_sheds_structured(self):
        b = MicroBatcher(lanes=2, max_queue=2)
        b.submit(_stub_job())
        b.submit(_stub_job())
        with pytest.raises(ShedError) as ei:
            b.submit(_stub_job())
        assert ei.value.reason == "queue_full"
        assert b.stats() == {"accepted": 2, "shed": 1,
                             "service_est_s": 0.5}
        assert len(b.drain()) == 2 and b.depth() == 0


# ---------------------------------------------------------------------------
# Circuit breaker (stubbed batch execution — no programs, no warmup)
# ---------------------------------------------------------------------------

def test_stopped_server_sheds_submits(tmp_path):
    """A stopped server has no worker: admitting would strand the job
    in the batcher forever, so submit sheds ``ShedError("shutdown")``
    (found by the post-stop drive, not a test)."""
    srv = CalibServer(object(), M=M, lanes=2, cache_dir=str(tmp_path),
                      npix=32, compile_cache=False,
                      poll_s=0.01, idle_tick_s=0.02)
    srv.start()
    srv.stop()
    with pytest.raises(ShedError) as ei:
        srv.submit(Job(episode=None, k=1))
    assert ei.value.reason == "shutdown"


def test_worker_crash_fails_futures_then_opens_circuit(monkeypatch,
                                                       tmp_path):
    srv = CalibServer(object(), M=M, lanes=2, cache_dir=str(tmp_path),
                      npix=32, compile_cache=False, max_restarts=1,
                      backoff=BackoffPolicy(base_s=0.01, factor=1.0,
                                            max_s=0.01, jitter=0.0),
                      poll_s=0.01, idle_tick_s=0.02, heartbeat_timeout=5.0)
    monkeypatch.setattr(
        srv, "_process_batch",
        lambda batch: (_ for _ in ()).throw(RuntimeError("poison")))
    srv.start()
    try:
        job = Job(episode=None, k=1)
        fut = srv.batcher.submit(job)       # bypass n_dirs validation
        with pytest.raises(RuntimeError, match="poison"):
            fut.result(timeout=10)
        deadline = time.monotonic() + 10
        while not srv.circuit_open and time.monotonic() < deadline:
            # keep the worker crashing until the slot exhausts restarts
            try:
                srv.batcher.submit(Job(episode=None, k=1))
            except ShedError:
                pass
            time.sleep(0.05)
        assert srv.circuit_open, "slot past max_restarts must open circuit"
        with pytest.raises(ShedError) as ei:
            srv.submit(Job(episode=None, k=1))
        assert ei.value.reason == "circuit_open"
        assert srv.stats()["failed"] >= 1
    finally:
        srv.stop()


class TestNumericsSentinel:
    """Production parity sentinels (ISSUE 19): every Nth batch snapshots
    one sampled lane; the supervisor replays it through the sequential
    oracle (`_oracle_result`, the fused=False parity path) off the hot
    path and judges the fused outputs against the documented bf16 band.
    Out-of-band drift feeds the SLO burn detector, which names the
    drifting STAGE when it fires."""

    def _events(self, path, start):
        import json
        lines = open(path).read().splitlines()[start:]
        return [json.loads(ln) for ln in lines]

    def test_clean_replay_is_in_band(self, served):
        be, srv, _, _, path = served
        n0 = len(open(path).read().splitlines())
        srv.sentinel_every = 1
        try:
            jobs = _jobs(be, [(2, 2), (3, 3)], seed=SEED + 11)
            assert srv.process_once(jobs, timeout=0.01) == 2
            ev = srv.sentinel_poll()
        finally:
            srv.sentinel_every = 0
        assert ev is not None and ev["drift"] is False
        # identical callable both paths: parity is tight, not just in-band
        for stage in ("solve", "influence", "sigma"):
            assert ev[f"rel_err_{stage}"] <= obs.BF16_REL_BAND
        assert ev["worst_stage"] in ("solve", "influence", "sigma")
        drift_evs = [e for e in self._events(path, n0)
                     if e.get("event") == "numerics_drift"]
        assert len(drift_evs) == 1 and drift_evs[0]["drift"] is False
        # nothing pending afterwards; a bare poll is a hysteresis tick
        srv.sentinel_every = 1
        try:
            assert srv.sentinel_poll() is None
        finally:
            srv.sentinel_every = 0

    def test_injected_drift_trips_burn_detector_naming_stage(
            self, served, tmp_path_factory):
        """A planned perturbation of the fused solve output (the chaos
        hook rehearsal for a real numerics regression) must produce
        drift=True replays and an slo_burn(kind="numerics") transition
        naming the solve stage — on a FRESH server so the module
        fixture's detector never latches."""
        from smartcal_tpu.runtime import faults as rt_faults

        be, _, _, cache, path = served
        n0 = len(open(path).read().splitlines())
        srv = CalibServer(be, M=M, lanes=LANES, cache_dir=cache,
                          compile_cache=False, max_wait_s=0.02,
                          sentinel_every=1)
        warm = srv.warmup(seed=SEED)
        assert warm["sources"]["solve"] == "cache"
        rt_faults.install(rt_faults.FaultPlan(
            perturb_stage="sentinel_solve", perturb_at=0,
            perturb_rel=0.5, perturb_span=100))
        try:
            drifted = 0
            for i in range(4):
                jobs = _jobs(be, [(2, 2), (3, 2)], seed=SEED + 20 + i)
                srv.process_once(jobs, timeout=0.01)
                ev = srv.sentinel_poll()
                assert ev is not None
                assert ev["drift"] is True, ev
                assert ev["worst_stage"] == "solve"
                assert ev["rel_err_solve"] == pytest.approx(0.5, rel=1e-6)
                drifted += 1
                if srv.stats()["sentinel"]["firing"]:
                    break
        finally:
            rt_faults.clear()
        sent = srv.stats()["sentinel"]
        assert sent["firing"], sent
        assert sent["drift"] == drifted == sent["replayed"]
        assert sent["sampled"] >= drifted
        burns = [e for e in self._events(path, n0)
                 if e.get("event") == "slo_burn"
                 and e.get("kind") == "numerics"]
        assert burns and burns[0]["stage"] == "solve"
        assert burns[0]["state"] == "firing"
