"""Formulation-optimized influence chain vs its retained oracles.

Each rewritten kernel (scatter-free Hessian, adjoint 4-RHS Dsolutions ->
Dresiduals column means, hoisted-operand chunk path, rank-factored DFT
imager, per-band segmented image) is a REFORMULATION of a kernel that
stays in the tree as the parity oracle — same math, different lowering —
so everything here asserts equality to float round-off at toy scale
(N<=6, K<=3: the whole file is cheap enough for the tier-1 dots budget).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from smartcal_tpu.cal import creal, imager, influence, kernels, solver
from smartcal_tpu.parallel import make_mesh
from smartcal_tpu.parallel.sharded_cal import influence_sharded


@pytest.fixture(scope="module")
def problem():
    """Split-real toy problem shared by the chain tests."""
    rng = np.random.default_rng(11)
    N, K, Ts, Td = 5, 3, 2, 3
    B = N * (N - 1) // 2
    T = Ts * Td
    R = (rng.standard_normal((2 * B * T, 2))
         + 1j * rng.standard_normal((2 * B * T, 2))).astype(np.complex64)
    C = (rng.standard_normal((K, T * B, 4))
         + 1j * rng.standard_normal((K, T * B, 4))).astype(np.complex64)
    J = (rng.standard_normal((Ts, K, 2 * N, 2))
         + 1j * rng.standard_normal((Ts, K, 2 * N, 2))).astype(np.complex64)
    hadd = jnp.asarray([0.5, 1.0, 0.25])
    Rs = jnp.asarray(creal.split(R)).reshape(-1, 2, 2)
    return N, K, Ts, Td, Rs, jnp.asarray(creal.split(C)), \
        jnp.asarray(creal.split(J)), hadd


def _one_interval(problem):
    """First calibration interval's (Rs, Cs, Js) in kernel convention."""
    N, K, Ts, Td, Rs, Cs, Js, hadd = problem
    B = N * (N - 1) // 2
    R1 = Rs.reshape(Ts, 2 * B * Td, 2, 2)[0]
    C1 = Cs.reshape(K, Ts, B * Td, 4, 2)[:, 0]
    J1 = Js[0]
    return N, K, Td, R1, C1, J1, hadd


def test_hessian_opt_matches_oracle(problem):
    N, K, Td, R1, C1, J1, _ = _one_interval(problem)
    want = np.asarray(kernels.hessian_res_sr(R1, C1, J1, N))
    got = np.asarray(kernels.hessian_res_opt_sr(R1, C1, J1, N))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("addself", [False, True])
@pytest.mark.parametrize("perdir", [False, True])
def test_colmeans_adjoint_matches_oracle_chain(problem, addself, perdir):
    """The fused adjoint transpose-solve must equal the oracle chain
    dsolutions_all_sr -> dresiduals_colmeans_sr (8B-column solve)."""
    N, K, Td, R1, C1, J1, hadd = _one_interval(problem)
    H = kernels.hessian_res_sr(R1, C1, J1, N)
    N4 = H.shape[1]
    Dgs = H.at[:, jnp.arange(N4), jnp.arange(N4), 0].add(hadd[:, None])
    dJ = kernels.dsolutions_all_sr(C1, J1, N, Dgs)
    want = np.asarray(kernels.dresiduals_colmeans_sr(
        C1, J1, N, dJ, addself=addself, perdir=perdir))
    got = np.asarray(kernels.influence_colmeans_opt_sr(
        C1, J1, N, Dgs, addself=addself, perdir=perdir))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-6)


@pytest.mark.parametrize("perdir", [False, True])
@pytest.mark.parametrize("fullpol", [False, True])
def test_influence_visibilities_opt_matches_oracle(problem, perdir,
                                                   fullpol):
    N, K, Ts, Td, Rs, Cs, Js, hadd = problem
    want = influence.influence_visibilities(
        Rs, Cs, Js, hadd, N, Ts, fullpol=fullpol, perdir=perdir,
        optimized=False)
    got = influence.influence_visibilities(
        Rs, Cs, Js, hadd, N, Ts, fullpol=fullpol, perdir=perdir,
        optimized=True)
    np.testing.assert_allclose(np.asarray(got.vis), np.asarray(want.vis),
                               rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got.llr), np.asarray(want.llr),
                               rtol=1e-5, atol=1e-5)


def test_factored_imager_matches_xla():
    rng = np.random.default_rng(5)
    R = 40
    uvw = jnp.asarray(rng.standard_normal((R, 3)) * 200.0, jnp.float32)
    vis = jnp.asarray(rng.standard_normal((R, 2)), jnp.float32)
    freq = 140e6
    cell = 1e-4
    want = np.asarray(imager.dirty_image_sr_xla(uvw, vis, freq, cell,
                                                npix=32))
    got = np.asarray(imager.dirty_image_factored_sr(uvw, vis, freq, cell,
                                                    npix=32))
    # the angle-addition identity reassociates the phase evaluation, so
    # agreement is float round-off, not bitwise
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.fixture(scope="module")
def multi_band(problem):
    """(Nf-band solver-convention residual, C, J, hadd, freqs, uvw)."""
    rng = np.random.default_rng(7)
    N, K, Ts, Td, Rs, Cs, Js, hadd = problem
    B = N * (N - 1) // 2
    T = Ts * Td
    Nf = 2
    resid = jnp.asarray(rng.standard_normal((Nf, T, B, 2, 2, 2)),
                        jnp.float32)
    C = jnp.asarray(rng.standard_normal((Nf,) + tuple(Cs.shape)),
                    jnp.float32)
    J = jnp.asarray(rng.standard_normal((Nf,) + tuple(Js.shape)),
                    jnp.float32) * 0.3
    hadd_all = jnp.asarray(rng.uniform(0.1, 1.0, (Nf, K)), jnp.float32)
    freqs = np.linspace(120e6, 160e6, Nf)
    uvw = jnp.asarray(rng.standard_normal((T * B, 3)) * 300.0, jnp.float32)
    return N, Ts, resid, C, J, hadd_all, freqs, uvw


def test_images_multi_opt_matches_oracle(multi_band):
    N, Ts, resid, C, J, hadd_all, freqs, uvw = multi_band
    cell = 1e-4
    want = np.asarray(influence.influence_images_multi(
        resid, C, J, hadd_all, freqs, uvw, cell, N, Ts, npix=16,
        use_pallas=False, optimized=False))
    got = np.asarray(influence.influence_images_multi(
        resid, C, J, hadd_all, freqs, uvw, cell, N, Ts, npix=16,
        optimized=True))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=1e-5)


def test_single_band_segmented_matches_multi(multi_band):
    """The host-segmented per-band unit (influence_image_single_sr) must
    reproduce the fused all-band program band by band."""
    N, Ts, resid, C, J, hadd_all, freqs, uvw = multi_band
    cell = 1e-4
    fused = np.asarray(influence.influence_images_multi(
        resid, C, J, hadd_all, freqs, uvw, cell, N, Ts, npix=16,
        optimized=True))
    for fi in range(resid.shape[0]):
        one = np.asarray(influence.influence_image_single_sr(
            resid[fi], C[fi], J[fi], hadd_all[fi],
            jnp.float32(freqs[fi]), uvw, cell, N, Ts, npix=16))
        np.testing.assert_allclose(one, fused[fi], rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("perdir", [False, True])
def test_influence_sharded_opt_matches_single_device(problem, perdir):
    """The chunk-sharded route on the OPTIMIZED kernels vs the
    single-device ORACLE chain on the virtual mesh: the two formulation
    switches and the shard_map partitioning must all agree."""
    N, K, Ts, Td, Rs, Cs, Js, hadd = problem
    ref = influence.influence_visibilities(Rs, Cs, Js, hadd, N, Ts,
                                           perdir=perdir, optimized=False)
    mesh = make_mesh((4, 2), ("fp", "sp"))   # sp=2 divides n_chunks=Ts=2
    out = influence_sharded(mesh, Rs, Cs, Js, hadd, N, Ts, axis="sp",
                            perdir=perdir, optimized=True)
    np.testing.assert_allclose(np.asarray(out.vis), np.asarray(ref.vis),
                               rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out.llr), np.asarray(ref.llr),
                               rtol=1e-5, atol=1e-5)
