"""Observability layer: RunLog schema/rotation/sanitization, span
nesting + thread-safety + no-op contract, counters/gauges, solver
aux-stat plumbing parity (stats on ≙ stats off, bit-identical), and the
obs_report aggregation/learning-verdict tool."""

import json
import os
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smartcal_tpu import obs
from smartcal_tpu.cal import solver

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")
sys.path.insert(0, TOOLS)
import obs_report  # noqa: E402


@pytest.fixture(autouse=True)
def clean_obs_state():
    """Every test starts with no active RunLog and empty counters."""
    while obs.active() is not None:
        obs.deactivate()
    obs.reset_counters()
    yield
    while obs.active() is not None:
        obs.deactivate()
    obs.reset_counters()


def read_jsonl(path):
    return [json.loads(ln) for ln in open(path).read().splitlines()]


# ---------------------------------------------------------------------------
# RunLog
# ---------------------------------------------------------------------------

def test_runlog_header_schema_and_sanitization(tmp_path):
    path = str(tmp_path / "run.jsonl")
    with obs.RunLog(path, run_id="r-1", meta={"entry": "test"},
                    flush_lines=1) as rl:
        rl.log("episode", episode=0, score=float("nan"),
               arr=[1.0, float("inf"), -float("inf")],
               nested={"x": float("nan"), "ok": 2},
               npval=np.float32(1.5), jval=jnp.asarray(2.5))
    lines = read_jsonl(path)           # json.loads REJECTS bare NaN tokens
    hdr = lines[0]
    assert hdr["event"] == "run_header"
    assert hdr["schema"] == obs.SCHEMA_VERSION
    assert hdr["run_id"] == "r-1"
    assert hdr["host"] and hdr["pid"]
    assert hdr["meta"]["entry"] == "test"
    # jax is imported in this process, so device metadata must be present
    assert hdr["platform"] == "cpu" and hdr["n_devices"] == 8
    ep = lines[1]
    assert ep["score"] is None
    assert ep["arr"] == [1.0, None, None]
    assert ep["nested"] == {"x": None, "ok": 2}
    assert ep["npval"] == 1.5 and ep["jval"] == 2.5


def test_runlog_buffering_and_flush(tmp_path):
    path = str(tmp_path / "run.jsonl")
    rl = obs.RunLog(path, flush_lines=1000, flush_interval=1000.0)
    rl.log("e1")
    assert len(read_jsonl(path)) == 1      # header force-flushed only
    rl.flush()
    assert len(read_jsonl(path)) == 2
    rl.log("e2")
    rl.close()                             # close flushes the tail
    assert [r["event"] for r in read_jsonl(path)] == \
        ["run_header", "e1", "e2"]


def test_runlog_rotation(tmp_path):
    path = str(tmp_path / "run.jsonl")
    rl = obs.RunLog(path, run_id="rot-1", max_bytes=2000, flush_lines=1)
    for i in range(40):
        rl.log("episode", episode=i, payload="x" * 50)
    rl.close()
    assert os.path.exists(path + ".1")
    seg1, cur = read_jsonl(path + ".1"), read_jsonl(path)
    # both segments parse, share the run id, and re-announce the schema
    assert seg1[0]["event"] == "run_header" and seg1[0]["rotated"] == 0
    assert cur[0]["event"] == "run_header" and cur[0]["rotated"] >= 1
    assert cur[0]["run_id"] == "rot-1" == seg1[0]["run_id"]
    all_eps = [r["episode"] for r in seg1 + cur if r["event"] == "episode"]
    missing = set(range(40)) - set(all_eps)
    # rotation may span >2 segments; everything not in the last two must
    # live in intermediate segments
    for n in range(2, 10):
        p = path + f".{n}"
        if os.path.exists(p):
            all_eps += [r["episode"] for r in read_jsonl(p)
                        if r["event"] == "episode"]
    assert set(all_eps) == set(range(40)), missing


def test_jsonl_shim_headerless(tmp_path):
    """The back-compat JsonlLogger writes NO header and flushes per line
    (its original crash-safety contract) — but sanitizes now."""
    from smartcal_tpu.utils import JsonlLogger

    path = tmp_path / "m.jsonl"
    with JsonlLogger(str(path)) as log:
        log.log("episode", score=float("nan"))
    recs = read_jsonl(str(path))
    assert len(recs) == 1
    assert recs[0]["event"] == "episode" and recs[0]["score"] is None


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------

def test_span_noop_without_runlog():
    # the inactive path returns ONE shared null context manager: no
    # allocation, no clock reads — the strict-no-op contract
    assert obs.span("a") is obs.span("b", tag=1)
    with obs.span("a"):
        with obs.span("b"):
            pass


def test_span_nesting_paths(tmp_path):
    path = str(tmp_path / "run.jsonl")
    with obs.recording(path, flush_lines=1):
        with obs.span("episode", episode=3):
            with obs.span("solve", route="fused"):
                pass
            with obs.span("influence"):
                pass
    spans = [r for r in read_jsonl(path) if r["event"] == "span"]
    assert [s["path"] for s in spans] == \
        ["episode/solve", "episode/influence", "episode"]
    assert spans[0]["route"] == "fused"
    assert spans[2]["episode"] == 3
    assert all(s["dur_s"] >= 0 for s in spans)


def test_span_records_errors(tmp_path):
    path = str(tmp_path / "run.jsonl")
    with obs.recording(path, flush_lines=1):
        with pytest.raises(ValueError):
            with obs.span("probe"):
                raise ValueError("tunnel wedged")
    spans = [r for r in read_jsonl(path) if r["event"] == "span"]
    assert "tunnel wedged" in spans[0]["error"]


def test_span_thread_safety(tmp_path):
    """Two threads nest independently: per-thread stacks never interleave
    (the run_pipelined prefetch-worker requirement)."""
    path = str(tmp_path / "run.jsonl")
    errs = []

    def worker(name):
        try:
            for _ in range(50):
                with obs.span(name):
                    with obs.span(name + "_inner") as sp:
                        assert sp.path == f"{name}/{name}_inner", sp.path
        except Exception as e:          # surfaced below; threads swallow
            errs.append(e)

    with obs.recording(path):
        ts = [threading.Thread(target=worker, args=(f"t{i}",), name=f"t{i}")
              for i in range(2)]
        [t.start() for t in ts]
        [t.join() for t in ts]
    assert not errs
    spans = [r for r in read_jsonl(path) if r["event"] == "span"]
    assert len(spans) == 200
    for s in spans:
        # a cross-thread interleave would produce paths like t0/t1_inner
        assert s["path"] in (f"{s['thread']}",
                             f"{s['thread']}/{s['thread']}_inner")


# ---------------------------------------------------------------------------
# Counters / gauges / listeners
# ---------------------------------------------------------------------------

def test_counters_and_gauges(tmp_path):
    obs.counter_add("dead", 5)             # inactive -> strict no-op
    assert obs.counters_snapshot() == {}
    path = str(tmp_path / "run.jsonl")
    with obs.recording(path, flush_lines=1):
        obs.counter_add("solves")
        obs.counter_add("solves", 2)
        obs.gauge_set("queue_depth", 3, where="prefetch")
        obs.flush_counters()
    recs = read_jsonl(path)
    gauge = next(r for r in recs if r["event"] == "gauge")
    assert gauge["name"] == "queue_depth" and gauge["value"] == 3
    counters = next(r for r in recs if r["event"] == "counters")
    assert counters["values"]["solves"] == 3.0


def test_memory_gauges_none_safe(tmp_path):
    """CPU devices report no memory_stats — must be a clean 0, no crash,
    no malformed events."""
    path = str(tmp_path / "run.jsonl")
    with obs.recording(path, flush_lines=1):
        n = obs.log_memory_gauges()
    assert n == 0 or all("bytes_in_use" in r for r in read_jsonl(path)
                         if r["event"] == "memory")


def test_compile_listener_records_events(tmp_path, monkeypatch):
    from smartcal_tpu.obs import registry

    path = str(tmp_path / "run.jsonl")
    assert obs.install_compile_listener()
    # tiny programs compile in <10ms; drop the log floor so the stream
    # check exercises the full path (production keeps the floor so the
    # ~1k sub-ms jaxpr-trace events stay counter-only)
    monkeypatch.setattr(registry, "COMPILE_LOG_MIN_S", 0.0)

    @jax.jit
    def f(x):
        return x * 2 + 1

    with obs.recording(path, flush_lines=1):
        f(jnp.arange(7) * np.random.randint(1, 9))   # fresh shape -> compile
        snap = obs.counters_snapshot()
    recs = [r for r in read_jsonl(path) if r["event"] == "jax_event"]
    assert recs, "no compile event captured by the jax.monitoring listener"
    assert all(r["dur_s"] >= 0 for r in recs)
    assert snap.get("jax_compile_events", 0) >= 1


# ---------------------------------------------------------------------------
# Solver aux-stat plumbing
# ---------------------------------------------------------------------------

N, K, NF, T, TS = 4, 2, 2, 4, 2
CFG = solver.SolverConfig(n_stations=N, n_dirs=K, n_poly=2, admm_iters=3,
                          lbfgs_iters=2, init_iters=2)


@pytest.fixture(scope="module")
def tiny_problem():
    rng = np.random.default_rng(7)
    B = N * (N - 1) // 2
    V = jnp.asarray(rng.normal(size=(NF, T, B, 2, 2, 2)), jnp.float32)
    C = jnp.asarray(rng.normal(size=(NF, K, T * B, 4, 2)), jnp.float32)
    freqs = jnp.asarray([1.0e8, 1.1e8], jnp.float32)
    rho = jnp.asarray([0.5, 1.0], jnp.float32)
    return V, C, freqs, rho


def test_solver_stats_parity_bit_identical(tiny_problem):
    """collect_stats=True must be PURELY additive: J/Z/residual/sigmas
    bit-identical to the stats-off solve."""
    V, C, freqs, rho = tiny_problem
    # kwargs spelled exactly like RadioBackend.calibrate's call so the
    # traced-program cache is shared with the backend test (jax keys
    # jit traces on kwarg presence, not just bound values)
    off = solver.solve_admm(V, C, freqs, 1.05e8, rho, CFG, n_chunks=TS,
                            admm_iters=None, collect_stats=False)
    on = solver.solve_admm(V, C, freqs, 1.05e8, rho, CFG, n_chunks=TS,
                           admm_iters=None, collect_stats=True)
    assert off.stats is None
    for a, b in zip(off[:6], on[:6]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    st = on.stats
    assert int(st.admm_iters) == CFG.admm_iters
    assert st.primal_resid.shape == (CFG.admm_iters,)
    assert np.all(np.asarray(st.primal_resid) > 0)
    assert st.inner_iters.shape == (CFG.admm_iters,)
    # every (Nf, Ts) lane runs at least one inner iteration per outer
    assert np.all(np.asarray(st.inner_iters) >= NF * TS)
    assert int(st.init_iters) >= NF * TS
    assert int(st.n_segments) == 1


def test_solver_stats_dynamic_iters(tiny_problem):
    """Traced admm_iters < cfg.admm_iters: trailing stat entries stay 0."""
    V, C, freqs, rho = tiny_problem
    res = solver.solve_admm(V, C, freqs, 1.05e8, rho, CFG, n_chunks=TS,
                            admm_iters=jnp.asarray(2), collect_stats=True)
    st = res.stats
    assert int(st.admm_iters) == 2
    assert float(st.primal_resid[2]) == 0.0
    assert int(st.inner_iters[2]) == 0
    # over-config override (out of the <= contract, but the fuzzy env's
    # fixed maxiter does it): the scatter DROPS the excess entries — no
    # clamp onto the last slot, and admm_iters reports the true count.
    # Same compiled program as above (traced operand), so this is free.
    over = solver.solve_admm(V, C, freqs, 1.05e8, rho, CFG, n_chunks=TS,
                             admm_iters=jnp.asarray(CFG.admm_iters + 2),
                             collect_stats=True)
    assert int(over.stats.admm_iters) == CFG.admm_iters + 2
    assert over.stats.primal_resid.shape == (CFG.admm_iters,)
    assert np.all(np.asarray(over.stats.primal_resid) > 0)


# Host-segmented stats ride on tests/test_cal_backend.py::
# test_host_segmented_matches_fused, which already pays the segment-program
# traces — collect_stats reuses the same compiled segments there.


def test_backend_calibrate_logs_solver_event(tmp_path, tiny_problem):
    """RadioBackend.calibrate with a RunLog active: solve span + solver
    telemetry event with the route tag; without one: stats stay None."""
    from types import SimpleNamespace

    from smartcal_tpu.envs import radio

    V, C, freqs, rho = tiny_problem
    backend = radio.RadioBackend(n_stations=N, n_freqs=NF, n_times=T,
                                 tdelta=T // TS, n_poly=2,
                                 admm_iters=CFG.admm_iters,
                                 lbfgs_iters=CFG.lbfgs_iters,
                                 init_iters=CFG.init_iters, shard=False)
    ep = radio.Episode(obs=SimpleNamespace(freqs=freqs), V=V, Ccal=C,
                       f0=1.05e8, n_dirs=K, snr=0.05)
    res_quiet = backend.calibrate(ep, rho)
    assert res_quiet.stats is None

    path = str(tmp_path / "run.jsonl")
    with obs.recording(path, flush_lines=1):
        res = backend.calibrate(ep, rho)
    assert res.stats is not None
    np.testing.assert_array_equal(np.asarray(res.J),
                                  np.asarray(res_quiet.J))
    recs = read_jsonl(path)
    ev = next(r for r in recs if r["event"] == "solver")
    assert ev["route"] == "fused"
    assert ev["admm_iters"] == CFG.admm_iters
    assert len(ev["primal_resid"]) == CFG.admm_iters
    assert ev["lbfgs_iters_total"] > 0
    assert ev["phi_evals_est"] > ev["lbfgs_iters_total"]
    span = next(r for r in recs if r["event"] == "span")
    assert span["name"] == "solve" and span["route"] == "fused"


def test_linesearch_eval_counts():
    from smartcal_tpu.ops import lbfgs

    assert lbfgs.linesearch_phi_evals() == 50
    assert lbfgs.linesearch_phi_evals(vmapped=False) < 50
    c = lbfgs.solve_eval_counts(8)
    assert c["value_and_grad_evals"] == 9
    assert c["phi_evals"] == 8 * 50
    assert lbfgs.solve_eval_counts(8, use_line_search=False)["phi_evals"] == 0


# ---------------------------------------------------------------------------
# obs_report
# ---------------------------------------------------------------------------

def write_run(path, scores, t0=1000.0, dt=2.0, spans=(), probes=()):
    with open(path, "w") as fh:
        def w(rec):
            fh.write(json.dumps(rec) + "\n")
        w({"t": t0, "event": "run_header", "schema": 1, "run_id": "test",
           "rotated": 0, "host": "h", "pid": 1, "platform": "cpu",
           "meta": {"entry": "synthetic"}})
        for i, s in enumerate(scores):
            w({"t": t0 + dt * i, "event": "episode", "episode": i,
               "score": s})
        for name, p, dur in spans:
            w({"t": t0, "event": "span", "name": name, "path": p,
               "dur_s": dur, "thread": "MainThread"})
        for ok in probes:
            w({"t": t0, "event": "probe", "ok": ok,
               **({} if ok else {"error": "UNAVAILABLE: tunnel"})})


def test_obs_report_learning_verdict(tmp_path):
    rng = np.random.default_rng(0)
    up = str(tmp_path / "up.jsonl")
    flat = str(tmp_path / "flat.jsonl")
    n = 60
    write_run(up, list(0.05 * np.arange(n) + rng.normal(0, 0.3, n)))
    write_run(flat, list(rng.normal(0, 0.3, n)))
    rep = obs_report.build_report(
        [obs_report.load_run(up), obs_report.load_run(flat)],
        n_boot=300, seed=0)
    verdicts = {r["path"]: r["learning"]["verdict"] for r in rep["runs"]}
    assert verdicts[up] == "LEARNING"
    assert verdicts[flat] == "NO TREND"
    lo, hi = [r for r in rep["runs"] if r["path"] == up][0][
        "learning"]["slope_ci95"]
    assert lo > 0 and lo < 0.05 < hi * 1.5
    # human rendering carries the verdicts
    text = obs_report.render(rep)
    assert "LEARNING" in text and "NO TREND" in text


def test_obs_report_stage_breakdown_and_probes(tmp_path):
    path = str(tmp_path / "run.jsonl")
    spans = []
    for _ in range(4):
        spans += [("simulate", "episode/simulate", 1.0),
                  ("solve", "episode/solve", 6.0),
                  ("influence", "episode/influence", 2.9),
                  ("episode", "episode", 10.0)]
    write_run(path, [0.1, 0.2, 0.3], spans=spans,
              probes=[False] * 3 + [True])
    run = obs_report.load_run(path)
    rep = obs_report.build_report([run], n_boot=50)
    r = rep["runs"][0]
    agg = r["spans"]
    assert agg["episode"]["total_s"] == pytest.approx(40.0)
    assert agg["episode/solve"]["total_s"] == pytest.approx(24.0)
    # stage total ≈ episode wall: children cover 99% of the episode span
    assert r["coverage"]["episode"] == pytest.approx(0.99)
    assert r["probes"] == {"total": 4, "ok": 1, "failed": 3,
                           "availability": 0.25,
                           "errors": ["UNAVAILABLE: tunnel"]}
    text = obs_report.render(rep)
    assert "chip-probe availability" in text and "1/4 ok" in text


def test_obs_report_folds_rotated_segments(tmp_path):
    base = str(tmp_path / "run.jsonl")
    write_run(base + ".1", [0.1, 0.2])
    write_run(base, [0.3, 0.4])
    run = obs_report.load_run(base)
    eps, scores = obs_report.episode_series(run["events"])
    assert len(scores) == 4


def test_obs_report_serving_section(tmp_path):
    """Serving SLO aggregation: warm probes excluded from percentiles,
    shed rate over offered (live + shed) jobs, and the per-request
    compile check scoped to the live serving window — a compile logged
    while the load generator built episodes (before the first
    submission) must not count."""
    path = str(tmp_path / "serve.jsonl")
    evs = [{"event": "run_header", "run_id": "s", "schema": 1},
           {"event": "serve_warmup", "t": 100.0, "wall_s": 9.5,
            "sources": {"solve": "cache", "influence": "cache"},
            "export_cache_hit": 2.0, "export_cache_miss": 0.0},
           # pool building compiles AFTER warmup, BEFORE serving: legit
           {"event": "jax_event", "t": 101.0, "key": "compile",
            "dur_s": 0.5},
           {"event": "serve_request", "t": 110.0, "warm": True,
            "total_s": 9.0, "queue_wait_s": 0.0, "service_s": 9.0},
           {"event": "serve_shed", "t": 111.0, "job_id": 9,
            "reason": "queue_full", "depth": 4}]
    for i in range(4):
        evs.append({"event": "serve_request", "t": 112.0 + i,
                    "total_s": 0.2, "queue_wait_s": 0.05,
                    "service_s": 0.15, "degraded": i == 0,
                    "deadline_miss": False})
        evs.append({"event": "span", "name": "serve_solve",
                    "path": "serve_batch/serve_solve", "t": 112.0 + i,
                    "dur_s": 0.1})
    with open(path, "w") as fh:
        for e in evs:
            fh.write(json.dumps(e) + "\n")
    rep = obs_report.build_report([obs_report.load_run(path)], n_boot=50)
    sv = rep["runs"][0]["serving"]
    assert sv["requests"] == 4 and sv["warm_probes"] == 1
    assert sv["shed"] == 1 and sv["shed_rate"] == 0.2
    assert sv["degraded"] == 1 and sv["deadline_miss"] == 0
    # the 9 s warm probe must not smear the live percentiles
    assert sv["total_s"]["p99"] <= 0.2
    assert sv["stages"]["serve_solve"]["n"] == 4
    # pool-building compile (t=101) is outside the serving window
    assert sv["compiles_in_serving"] == 0
    assert sv["warmup"]["sources"]["solve"] == "cache"
    text = obs_report.render(rep)
    assert "serving SLO" in text
    assert "compiles in serving window: 0" in text


# ---------------------------------------------------------------------------
# Driver integration (cheap enet run)
# ---------------------------------------------------------------------------

def test_train_obs_enet_driver(tmp_path, monkeypatch):
    """train_fused records header + per-episode events + episode spans +
    run_end through the shared TrainObs helper."""
    monkeypatch.chdir(tmp_path)
    from smartcal_tpu.train.enet_sac import train_fused

    path = str(tmp_path / "run.jsonl")
    train_fused(episodes=3, steps=2, M=6, N=6, quiet=True, save_every=0,
                metrics_path=path)
    recs = read_jsonl(path)
    assert recs[0]["event"] == "run_header"
    assert recs[0]["meta"]["entry"] == "enet_sac"
    eps = [r for r in recs if r["event"] == "episode"]
    assert [e["episode"] for e in eps] == [0, 1, 2]
    spans = [r for r in recs if r["event"] == "span"]
    assert len(spans) == 3 and all(s["name"] == "episode" for s in spans)
    end = recs[-1]
    assert end["event"] == "run_end" and end["episodes"] == 3
    # the run deactivated cleanly
    assert obs.active() is None
