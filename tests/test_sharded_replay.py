"""Mesh-sharded device-resident replay (ISSUE 12): ring parity of the
store path vs the flat buffer, sampling DISTRIBUTION parity vs both
single-buffer oracles (HBM stratified + NativePER sum tree), ERE/PER
composition at eta != 1, shard-local priority updates, the
transfer-guard proof of the fused sharded
store->sample->learn->priority-update step on the virtual mesh, and
checkpoint round-trips."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from smartcal_tpu.rl import replay as rp
from smartcal_tpu.rl import replay_sharded as rps
from smartcal_tpu.rl import sac

S, SIZE = 4, 32
SPEC = {"x": ((), jnp.float32)}
AGENT_KW = {"batch_size": 8, "mem_size": 64}


def _paired_buffers(n=40, block=5):
    """The SAME store sequence (blocks of ``block``, wrapping the ring,
    block size NOT divisible by the shard count) into a flat and a
    sharded buffer."""
    flat = rp.replay_init(SIZE, SPEC)
    sh = rps.replay_init(SIZE, SPEC, S)
    for blk in range(n // block):
        vals = jnp.arange(block, dtype=jnp.float32) + block * blk
        pri = 1.0 + 0.1 * vals
        flat = rp.replay_add_batch(flat, {"x": vals}, priority=pri)
        sh = rps.replay_add_batch(sh, {"x": vals}, priority=pri)
    return flat, sh


def _interleave(arr2d):
    """(S, L) -> the flat ring order g = j*S + s."""
    return np.asarray(arr2d).T.reshape(-1)


# ---------------------------------------------------------------------------
# store / layout parity
# ---------------------------------------------------------------------------

def test_store_ring_parity_vs_flat():
    """Slot (s, j) of the sharded ring holds EXACTLY what ring slot
    j*S+s of the flat buffer holds — data, priority and counter — even
    with wrap-around and block sizes not divisible by S."""
    flat, sh = _paired_buffers()
    assert int(sh.cntr) == int(flat.cntr) == 40
    np.testing.assert_array_equal(_interleave(sh.data["x"]),
                                  np.asarray(flat.data["x"]))
    np.testing.assert_array_equal(_interleave(sh.priority),
                                  np.asarray(flat.priority))


def test_store_default_priorities_match_flat():
    """pmax-fallback and error-based store priorities follow the flat
    rules (global max, not per-shard max)."""
    flat = rp.replay_init(SIZE, SPEC)
    sh = rps.replay_init(SIZE, SPEC, S)
    trs = {"x": jnp.arange(6, dtype=jnp.float32)}
    # untouched buffer -> clip everywhere
    flat = rp.replay_add_batch(flat, trs)
    sh = rps.replay_add_batch(sh, trs)
    np.testing.assert_array_equal(_interleave(sh.priority),
                                  np.asarray(flat.priority))
    # error-based store
    errs = jnp.linspace(0.0, 3.0, 6)
    flat = rp.replay_add_batch(flat, trs, errors=errs)
    sh = rps.replay_add_batch(sh, trs, errors=errs)
    np.testing.assert_array_equal(_interleave(sh.priority),
                                  np.asarray(flat.priority))


def test_init_validation():
    with pytest.raises(ValueError, match="divisible"):
        rps.replay_init(30, SPEC, 4)
    with pytest.raises(ValueError, match="n_shards"):
        rps.replay_init(32, SPEC, 0)


# ---------------------------------------------------------------------------
# ages / ERE parity
# ---------------------------------------------------------------------------

def test_ere_weights_exact_parity_vs_flat():
    flat, sh = _paired_buffers()
    wf = np.asarray(rp.ere_weights(flat, 0.9))
    ws = _interleave(rps.ere_weights(sh, 0.9))
    np.testing.assert_allclose(ws, wf, rtol=1e-6)


def test_ere_per_composition_at_eta_below_one():
    """PER x ERE on the sharded buffer: a high-priority OLD slot is
    sampled less under recency_eta < 1 than under plain PER (the flat
    buffer's composition contract)."""
    _, sh = _paired_buffers(n=32, block=4)   # exactly full, no wrap
    # oldest ring slot (g=0 -> shard 0, local 0) gets a huge priority
    sh = sh._replace(priority=sh.priority.at[0, 0].set(50.0))
    plain = jax.jit(lambda b, k: rps.replay_sample_per(b, k, 16))
    ere = jax.jit(
        lambda b, k: rps.replay_sample_per(b, k, 16, recency_eta=0.9))
    hits_plain = hits_ere = 0
    for i in range(100):
        _, gidx, _, _ = plain(sh, jax.random.PRNGKey(i))
        hits_plain += int(np.sum(np.asarray(gidx) == 0))
        _, gidx2, _, _ = ere(sh, jax.random.PRNGKey(i))
        hits_ere += int(np.sum(np.asarray(gidx2) == 0))
    assert hits_ere < hits_plain, (hits_ere, hits_plain)


# ---------------------------------------------------------------------------
# sampling distribution parity vs both oracles
# ---------------------------------------------------------------------------

def _empirical_freq(sample_fn, buf, draws=400, batch=16):
    counts = np.zeros(SIZE)
    for i in range(draws):
        gidx = sample_fn(buf, jax.random.PRNGKey(i))
        np.add.at(counts, np.asarray(gidx), 1)
    return counts / counts.sum()


def test_sample_per_distribution_parity_vs_flat_and_theory():
    """Per-transition sampled frequency matches p_i/total (the shared
    theoretical marginal) AND the flat HBM oracle's empirical
    distribution; the returned batch rows are the rows the indices
    name; IS weights agree with the flat formula at equal priorities."""
    flat, sh = _paired_buffers()
    theo = np.asarray(flat.priority) / float(np.sum(flat.priority))

    samp_sh = jax.jit(lambda b, k: rps.replay_sample_per(b, k, 16))
    samp_fl = jax.jit(lambda b, k: rp.replay_sample_per(b, k, 16))
    emp_sh = _empirical_freq(lambda b, k: samp_sh(b, k)[1], sh)
    emp_fl = _empirical_freq(lambda b, k: samp_fl(b, k)[1], flat)
    assert np.abs(emp_sh - theo).max() < 0.012, \
        np.abs(emp_sh - theo).max()
    assert np.abs(emp_sh - emp_fl).max() < 0.012, \
        np.abs(emp_sh - emp_fl).max()

    batch, gidx, is_w, _ = samp_sh(sh, jax.random.PRNGKey(123))
    fx = np.asarray(flat.data["x"])
    np.testing.assert_allclose(np.asarray(batch["x"]),
                               fx[np.asarray(gidx)])
    assert np.asarray(is_w).max() == pytest.approx(1.0)
    assert np.all(np.asarray(is_w) > 0)


def test_sample_per_distribution_parity_vs_native_sum_tree():
    """The sharded draw and the reference-shaped NativePER sum tree
    sample from the same distribution (both stratified over the same
    priorities)."""
    from smartcal_tpu.rl.replay_native import NativePER

    flat, sh = _paired_buffers()
    native = NativePER(SIZE, {"x": ((), np.float32)})
    # replay the same store order with the same explicit priorities
    fx = np.asarray(flat.data["x"])
    fp = np.asarray(flat.priority)
    for g in range(SIZE):
        native.store({"x": fx[g]})
    native.tree.update_batch(np.arange(SIZE), fp)

    rng = np.random.default_rng(0)
    counts_nat = np.zeros(SIZE)
    for _ in range(400):
        _, idx, _ = native.sample(16, rng)
        np.add.at(counts_nat, np.asarray(idx), 1)
    emp_nat = counts_nat / counts_nat.sum()

    samp_sh = jax.jit(lambda b, k: rps.replay_sample_per(b, k, 16))
    emp_sh = _empirical_freq(lambda b, k: samp_sh(b, k)[1], sh)
    assert np.abs(emp_sh - emp_nat).max() < 0.015, \
        np.abs(emp_sh - emp_nat).max()


def test_uniform_sample_no_replacement_and_values():
    _, sh = _paired_buffers()
    flat, _ = _paired_buffers()
    samp = jax.jit(lambda b, k: rps.replay_sample_uniform(b, k, 8))
    batch, gidx = samp(sh, jax.random.PRNGKey(0))
    gi = np.asarray(gidx)
    assert len(set(gi.tolist())) == 8        # without replacement
    np.testing.assert_allclose(np.asarray(batch["x"]),
                               np.asarray(flat.data["x"])[gi])


def test_uniform_sample_respects_fill_boundary():
    sh = rps.replay_init(SIZE, SPEC, S)
    sh = rps.replay_add_batch(
        sh, {"x": jnp.arange(10, dtype=jnp.float32)}, priority=1.0)
    _, gidx = jax.jit(
        lambda b, k: rps.replay_sample_uniform(b, k, 8))(
        sh, jax.random.PRNGKey(1))
    assert np.all(np.asarray(gidx) < 10)


# ---------------------------------------------------------------------------
# priority update
# ---------------------------------------------------------------------------

def test_priority_update_shard_local_parity():
    flat, sh = _paired_buffers()
    gidx = jnp.asarray([0, 5, 13, 31, 2, 2, 17, 8])
    errs = jnp.linspace(0.0, 5.0, 8)
    flat2 = rp.replay_update_priorities(flat, gidx, errs)
    sh2 = rps.replay_update_priorities(sh, gidx, errs)
    np.testing.assert_allclose(_interleave(sh2.priority),
                               np.asarray(flat2.priority), rtol=1e-6)


# ---------------------------------------------------------------------------
# fused step on the virtual mesh: transfer guard + placement
# ---------------------------------------------------------------------------

def _versioned_sharded(cfg, key, n, version, mesh):
    spec = rp.versioned_spec(rp.transition_spec(cfg.obs_dim,
                                                cfg.n_actions))
    buf = rps.place_on_mesh(rps.replay_init(cfg.mem_size, spec, S), mesh)
    st = sac.sac_init(jax.random.PRNGKey(7), cfg)
    k_obs, k_act = jax.random.split(key)
    obs = jax.random.normal(k_obs, (n, cfg.obs_dim))
    a, lp = sac.choose_action_logp(cfg, st, obs, k_act)
    flat = {"state": obs, "new_state": obs + 0.1, "action": a,
            "reward": (jnp.arange(n) % 3).astype(jnp.float32) - 1.0,
            "done": jnp.zeros((n,), jnp.bool_),
            "hint": jnp.zeros((n, cfg.n_actions)),
            "version": jnp.full((n,), version, jnp.int32),
            "behavior_logp": lp}
    return buf, st, flat


def test_fused_sharded_store_sample_learn_update_zero_host_transfers():
    """The WHOLE sharded chain — store -> PER/ERE sample -> IS-clipped
    learn -> shard-local priority update — runs as one jitted step on a
    4-shard mesh with transfers DISALLOWED: no transition and no
    sampled batch touches the host, and the buffer stays
    shard-distributed."""
    cfg = sac.SACConfig(obs_dim=6, n_actions=2, prioritized=True,
                        is_clip=2.0, ere_eta=0.99, **AGENT_KW)
    mesh = Mesh(np.asarray(jax.devices()[:S]), ("rp",))
    repl = NamedSharding(mesh, P())
    buf, st, flat = _versioned_sharded(cfg, jax.random.PRNGKey(0), 32,
                                       1, mesh)

    def fused(st, buf, flat, key, ver):
        buf = rps.replay_add_batch(buf, flat)
        return sac.learn(cfg, st, buf, key, learner_version=ver)

    fused = jax.jit(fused)
    st, flat, k0, ver = jax.device_put(
        (st, flat, jax.random.PRNGKey(3), jnp.asarray(2, jnp.int32)),
        repl)
    out = fused(st, buf, flat, k0, ver)      # warm the compile
    jax.block_until_ready(out)
    k2 = jax.device_put(jax.random.PRNGKey(4), repl)
    with jax.transfer_guard("disallow"):
        st2, buf2, metrics = fused(st, buf, flat, k2, ver)
        jax.block_until_ready((st2, buf2))
    assert int(st2.learn_counter) == 1
    assert not np.array_equal(np.asarray(buf2.priority),
                              np.asarray(buf.priority))
    # staleness telemetry flowed out of the fused step
    assert float(metrics["staleness_mean"]) == 1.0
    # the buffer never collapsed to one device
    assert buf2.priority.sharding.spec == P("rp")


def test_place_on_mesh_shards_leading_axis():
    buf = rps.place_on_mesh(rps.replay_init(SIZE, SPEC, S))
    assert buf.priority.sharding.spec == P("rp")
    assert buf.data["x"].sharding.spec == P("rp")
    # replicated scalars
    assert buf.cntr.sharding.spec == P()
    assert len(buf.priority.sharding.mesh.devices.ravel()) == S


def test_dsac_learn_accepts_sharded_buffer():
    """The discrete-SAC fused step dispatches on buffer type too (the
    demix fleet's path)."""
    from smartcal_tpu.rl import sac_discrete as dsac

    npix, K = 2, 3
    cfg = dsac.DSACConfig(obs_dim=npix * npix + 3 * K + 2,
                          n_actions=2 ** (K - 1), img_shape=(npix, npix),
                          use_image=True, prioritized=True,
                          batch_size=8, mem_size=64)
    spec = dsac.transition_spec(cfg.obs_dim)
    buf = rps.replay_init(cfg.mem_size, spec, S)
    st = dsac.dsac_init(jax.random.PRNGKey(0), cfg)
    n = 16
    trs = {"state": jax.random.normal(jax.random.PRNGKey(1),
                                      (n, cfg.obs_dim)),
           "new_state": jax.random.normal(jax.random.PRNGKey(2),
                                          (n, cfg.obs_dim)),
           "action": jnp.zeros((n,), jnp.int32),
           "reward": jnp.ones((n,)),
           "done": jnp.zeros((n,), jnp.bool_)}
    trs = {k: jnp.asarray(v, buf.data[k].dtype) if k in buf.data else v
           for k, v in trs.items()}
    buf = rps.replay_add_batch(buf, trs)
    st2, buf2, m = jax.jit(
        lambda s, b, k: dsac.learn(cfg, s, b, k))(
        st, buf, jax.random.PRNGKey(3))
    assert int(st2.learn_counter) == 1
    assert np.isfinite(float(m["critic_loss"]))


# ---------------------------------------------------------------------------
# health / occupancy / checkpoint
# ---------------------------------------------------------------------------

def test_health_matches_flat_and_reports_occupancy():
    flat, sh = _paired_buffers()
    hf = rp.replay_health(flat)
    hs = sh.health()
    for k in ("filled", "cntr", "size", "priority_total",
              "priority_entropy", "max_mean_priority_ratio"):
        assert hs[k] == pytest.approx(hf[k], rel=1e-6), k
    assert hs["n_shards"] == S
    assert hs["shard_occupancy"] == [SIZE // S] * S
    # partially filled: round-robin keeps shards within one transition
    sh2 = rps.replay_init(SIZE, SPEC, S)
    sh2 = rps.replay_add_batch(
        sh2, {"x": jnp.arange(6, dtype=jnp.float32)}, priority=1.0)
    occ = rps.shard_occupancy(int(sh2.cntr), S, SIZE // S)
    assert occ == [2, 2, 1, 1]
    assert max(occ) - min(occ) <= 1


def test_checkpoint_roundtrip_sharded(tmp_path):
    from smartcal_tpu.runtime import pack_replay, unpack_replay

    _, sh = _paired_buffers()
    packed = pack_replay(sh)
    assert packed["kind"] == "hbm_sharded"
    back = unpack_replay(packed)
    assert isinstance(back, rps.ShardedReplayState)
    np.testing.assert_array_equal(np.asarray(back.priority),
                                  np.asarray(sh.priority))
    np.testing.assert_array_equal(np.asarray(back.data["x"]),
                                  np.asarray(sh.data["x"]))
    assert int(back.cntr) == int(sh.cntr)
