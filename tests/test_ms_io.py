"""MS data edge: npz store roundtrips, extract_dataset, featurization, CLI.

Covers VERDICT r1 item 2 (the real-data edge): the synthetic stand-in MS is
written through the same writer a real observation would use, and the
feature/evaluate path consumes it through cal.ms_io exactly as it would a
casacore MS.
"""

import numpy as np
import jax
import pytest

from smartcal_tpu.cal import creal, ms_io
from smartcal_tpu.envs.radio import RadioBackend


K = 4
STATIONS = 6
TIMES = 8
TDELTA = 4
NPIX = 8


@pytest.fixture(scope="module")
def backend():
    return RadioBackend(n_stations=STATIONS, n_times=TIMES, tdelta=TDELTA,
                        npix=NPIX, admm_iters=2, lbfgs_iters=3,
                        init_iters=4)


@pytest.fixture(scope="module")
def episode(backend):
    return backend.new_demixing_episode(jax.random.PRNGKey(7), K)[0]


@pytest.fixture()
def ms_set(tmp_path, episode):
    return ms_io.observation_to_ms_set(str(tmp_path), episode.obs,
                                       np.asarray(episode.V))


def test_write_read_roundtrip(ms_set, episode):
    """read_corr returns exactly the visibilities the simulator wrote,
    autocorrelations excluded, rows time-major baseline-minor."""
    uu, vv, ww, xx, xy, yx, yy = ms_io.read_corr(ms_set[0], "DATA")
    B = episode.obs.n_baselines
    assert uu.shape == (TIMES * B,)
    V = creal.fuse(np.asarray(episode.V[0])).reshape(TIMES * B, 4)
    np.testing.assert_allclose(xx, V[:, 0], rtol=1e-6)
    np.testing.assert_allclose(yy, V[:, 3], rtol=1e-6)
    uvw = np.asarray(episode.obs.uvw).reshape(-1, 3)
    np.testing.assert_allclose(uu, uvw[:, 0], rtol=1e-5)


def test_ms_info(ms_set, episode):
    info = ms_io.ms_info(ms_set[0])
    assert info.n_stations == STATIONS
    assert info.n_baselines == episode.obs.n_baselines
    assert info.n_times == TIMES
    assert info.ra0 == pytest.approx(episode.obs.ra0)
    assert info.freqs[0] == pytest.approx(
        float(np.asarray(episode.obs.freqs)[0]))


def test_write_corr_and_add_column(ms_set):
    uu, vv, ww, xx, xy, yx, yy = ms_io.read_corr(ms_set[0], "DATA")
    ms_io.write_corr(ms_set[0], 2 * xx, 2 * xy, 2 * yx, 2 * yy,
                     colname="CORRECTED_DATA")
    _, _, _, cxx, _, _, cyy = ms_io.read_corr(ms_set[0], "CORRECTED_DATA")
    np.testing.assert_allclose(cxx, 2 * xx, rtol=1e-6)
    np.testing.assert_allclose(cyy, 2 * yy, rtol=1e-6)


def test_change_freq_and_add_noise(ms_set):
    ms_io.change_freq(ms_set[1], 123.0e6)
    assert ms_io.ms_info(ms_set[1]).freqs[0] == pytest.approx(123.0e6)
    _, _, _, xx0, *_ = ms_io.read_corr(ms_set[1], "DATA")
    ms_io.add_noise(ms_set[1], snr=1.0, rng=np.random.default_rng(1))
    _, _, _, xx1, *_ = ms_io.read_corr(ms_set[1], "DATA")
    assert not np.allclose(xx0, xx1)
    # SNR definition: noise magnitude comparable to the data magnitude
    snr = np.linalg.norm(xx0) / np.linalg.norm(xx1 - xx0)
    assert 0.2 < snr < 5.0


def test_extract_dataset(tmp_path, episode):
    """Channel averaging + time-window cut (DP3-replacement semantics)."""
    mslist = ms_io.observation_to_ms_set(str(tmp_path), episode.obs,
                                         np.asarray(episode.V))
    # give the middle MS two identical channels to verify averaging
    main, meta = ms_io._load(mslist[1])
    main["DATA"] = np.concatenate([main["DATA"], main["DATA"]], axis=1)
    meta["CHAN_FREQ"] = np.asarray([100e6, 110e6])
    ms_io._store(mslist[1], main, meta)

    out = ms_io.extract_dataset(mslist, timesec=4.0, Nf=3,
                                rng=np.random.default_rng(0),
                                outdir=str(tmp_path))
    assert len(out) == 3
    # the hand-edited 100/110 MHz MS is a frequency ENDPOINT of the set
    # (obs freqs are either all-LBA ~40-70 MHz or all-HBA ~110-180 MHz),
    # so it must appear channel-averaged to 105 MHz at out[0] or out[-1]
    out_infos = [ms_io.ms_info(m) for m in out]
    assert all(i.n_chan == 1 for i in out_infos)
    edited = [i for i in out_infos
              if i.freqs[0] == pytest.approx(105e6)]
    assert len(edited) == 1
    assert all(4 <= i.n_times <= TIMES for i in out_infos)
    # endpoint sub-bands are always the lowest/highest FREQUENCY MS
    src_freqs = sorted(float(np.mean(ms_io.ms_info(m).freqs))
                       for m in mslist)
    out_freqs = [i.freqs[0] for i in out_infos]
    assert out_freqs[0] == pytest.approx(src_freqs[0])
    assert out_freqs[-1] == pytest.approx(src_freqs[-1])


def _check_features(x, K, npix):
    nout = npix * npix + 8
    assert x.shape == (K * nout,)
    assert np.all(np.isfinite(x))
    for ck in range(K):
        img = x[ck * nout:ck * nout + npix * npix]
        assert np.linalg.norm(img) == pytest.approx(1.0, abs=1e-4)
        sep, az, el = x[ck * nout + npix * npix:ck * nout + npix * npix + 3]
        assert -360 <= az <= 360 and -90 <= el <= 90 and sep >= 0


def test_get_info_from_dataset(tmp_path, episode):
    """End-to-end real-data featurization on the MS-shaped stand-in:
    x has the reference layout K x (Ninf^2 + 8) (generate_data.py:835-858)
    with finite values and unit-normalized image blocks (synthetic
    stand-in sky)."""
    from smartcal_tpu.cal import dataset

    mslist = ms_io.observation_to_ms_set(str(tmp_path), episode.obs,
                                         np.asarray(episode.V))
    x = dataset.get_info_from_dataset(
        mslist, timesec=float(TIMES), Ninf=NPIX, K=K, tdelta=TDELTA,
        admm_iters=2, lbfgs_iters=3, init_iters=4,
        workdir=str(tmp_path), synthetic=True)
    _check_features(x, K, NPIX)


def test_get_info_from_dataset_real_ateam(tmp_path, episode):
    """The same end-to-end path on the DEFAULT sky — the real A-team
    catalogue fixture (VERDICT r2 item 4: real-data evaluation uses the
    actual base.sky models, K=3 keeps it to CasA+CygA+target)."""
    from smartcal_tpu.cal import dataset

    mslist = ms_io.observation_to_ms_set(str(tmp_path), episode.obs,
                                         np.asarray(episode.V))
    x = dataset.get_info_from_dataset(
        mslist, timesec=float(TIMES), Ninf=NPIX, K=3, tdelta=TDELTA,
        admm_iters=2, lbfgs_iters=3, init_iters=4,
        workdir=str(tmp_path))
    _check_features(x, 3, NPIX)


@pytest.mark.slow
def test_evaluate_cli_selftest(tmp_path, monkeypatch):
    """The evaluate CLI end-to-end: simulate -> MS -> train tiny model ->
    recommend (demixing/evaluate.py:51-61 parity)."""
    monkeypatch.chdir(tmp_path)
    from smartcal_tpu.train import evaluate

    probs = evaluate._selftest(_args())
    assert probs.shape == (_args().K - 1,)
    assert np.all((probs >= 0) & (probs <= 1))


def _args():
    import argparse

    return argparse.Namespace(stations=STATIONS, times=TIMES,
                              tdelta=TDELTA, npix=NPIX, K=K)
