"""Tests for the radio RL environments (CalibEnv, DemixingEnv) against the
reference contracts (calibration/calibenv.py, demixing_rl/demixingenv.py).
Hermetic: runs on the CPU test backend with tiny shapes."""

import numpy as np
import pytest

from smartcal_tpu.envs import CalibEnv, DemixingEnv
from smartcal_tpu.envs.demixing import scalar_to_kvec
from smartcal_tpu.envs.radio import RadioBackend


def tiny_backend(**kw):
    args = dict(n_stations=6, n_freqs=2, n_times=4, tdelta=2,
                admm_iters=2, lbfgs_iters=3, init_iters=5, npix=32)
    args.update(kw)
    return RadioBackend(**args)


@pytest.fixture(scope="module")
def calib_env():
    env = CalibEnv(M=3, provide_hint=True, backend=tiny_backend(), seed=3)
    obs = env.reset()
    return env, obs


class TestCalibEnv:
    def test_reset_observation(self, calib_env):
        env, obs = calib_env
        assert obs["img"].shape == (32, 32)
        assert obs["sky"].shape == (env.M + 1, 7)
        assert np.all(np.isfinite(obs["img"]))
        # final sky row carries (ra0, dec0, K, f_low, f_high) * META_SCALE
        last = obs["sky"][-1] / 1e-3
        assert last[2] == env.K
        assert 2 <= env.K <= env.M

    def test_hint_is_analytic_rho(self, calib_env):
        env, obs = calib_env
        assert env.hint is not None
        assert env.hint.shape == (2 * env.M,)
        # spatial hint = 5% of spectral, mapped affinely: undo the map
        from smartcal_tpu.envs.calib import HIGH, LOW
        spec = env.hint[:env.K] * (HIGH - LOW) / 2 + (HIGH + LOW) / 2
        spat = (env.hint[env.M:env.M + env.K] * (HIGH - LOW) / 2
                + (HIGH + LOW) / 2)
        np.testing.assert_allclose(spat, 0.05 * spec, rtol=1e-5, atol=1e-3)

    def test_step_reward_and_penalty(self, calib_env):
        env, _ = calib_env
        a = np.zeros(2 * env.M, np.float32)        # mid-range rho
        obs, r, done, hint, info = env.step(a)
        assert np.isfinite(r)
        assert not done
        # action at -1 maps rho to LOW boundary: no clip -> no penalty;
        # the clip penalty only triggers below LOW, which the affine map
        # cannot reach, so penalty stays 0 (parity with calibenv.py:126-138)
        obs2, r2, *_ = env.step(-np.ones(2 * env.M, np.float32))
        assert np.isfinite(r2)

    def test_rho_update_reflected_in_sky_cols(self, calib_env):
        env, _ = calib_env
        a = np.full(2 * env.M, 0.5, np.float32)
        obs, *_ = env.step(a)
        sky = obs["sky"] / 1e-3
        np.testing.assert_allclose(sky[:env.K, 5], 0.5, atol=1e-5)
        np.testing.assert_allclose(sky[:env.K, 6], 0.5, atol=1e-5)


@pytest.fixture(scope="module")
def demix_env():
    env = DemixingEnv(K=3, provide_hint=False, provide_influence=True,
                      backend=tiny_backend(admm_iters=30), seed=5)
    obs = env.reset()
    return env, obs


class TestDemixingEnv:
    def test_reset_observation(self, demix_env):
        env, obs = demix_env
        assert obs["infmap"].shape == (32, 32)
        assert obs["metadata"].shape == (3 * env.K + 2,)
        md = obs["metadata"] / 1e-3
        assert md[-1] == env.backend.n_stations
        # target separation (last of the K) is zero
        assert md[env.K - 1] == 0.0
        assert np.isfinite(env.reward0)

    def test_step_selection_and_metadata_zeroing(self, demix_env):
        env, _ = demix_env
        a = np.zeros(env.K, np.float32)
        a[0] = 0.9           # select outlier 0
        a[-1] = -1.0         # maxiter -> LOW_ITER
        obs, r, done, info = env.step(a)
        assert env.maxiter == 5
        assert np.isfinite(r)
        md = obs["metadata"] / 1e-3
        assert md[0] == 0.0                       # selected -> zeroed
        assert md[env.K - 1] == 0.0               # target always zeroed

    def test_more_directions_lower_residual(self, demix_env):
        env, _ = demix_env
        none_sel = np.zeros(env.K, np.float32)
        none_sel[:-1] = -1.0
        _, _, _, _ = env.step(none_sel)
        sigma_none = env.std_residual
        all_sel = np.zeros(env.K, np.float32)
        all_sel[:-1] = 1.0
        _, _, _, _ = env.step(all_sel)
        sigma_all = env.std_residual
        assert sigma_all < sigma_none

    def test_maxiter_penalty_in_reward(self, demix_env):
        env, _ = demix_env
        base = env.calculate_reward_(1)
        env.maxiter = 30
        high_iter = env.calculate_reward_(1)
        env.maxiter = 5
        low_iter = env.calculate_reward_(1)
        assert low_iter > high_iter
        assert np.isclose(high_iter - low_iter, -25 / 100.0)


def test_scalar_to_kvec_parity():
    # demixingenv.py:297-303
    np.testing.assert_array_equal(scalar_to_kvec(0, 5), np.zeros(5))
    np.testing.assert_array_equal(scalar_to_kvec(1, 5), [0, 0, 0, 0, 1])
    np.testing.assert_array_equal(scalar_to_kvec(5, 5), [0, 0, 1, 0, 1])
    np.testing.assert_array_equal(scalar_to_kvec(31, 5), np.ones(5))


def test_demix_hint_sweep():
    env = DemixingEnv(K=3, provide_hint=True, provide_influence=False,
                      backend=tiny_backend(admm_iters=30), seed=7)
    env.reset()
    hint = env.get_hint()
    assert hint.shape == (3,)
    assert np.all(np.isfinite(hint))
    # selection components live in [-1, 1]; maxiter component encodes 10
    assert np.all(hint[:-1] >= -1.0) and np.all(hint[:-1] <= 1.0)
    expected_iter = (10 - (30 + 5) / 2) * (2 / (30 - 5))
    assert np.isclose(hint[-1], expected_iter)


def test_demix_hint_respects_low_elevation():
    env = DemixingEnv(K=3, provide_hint=True, provide_influence=False,
                      backend=tiny_backend(admm_iters=30), seed=7)
    env.reset()
    # force an outlier below the elevation floor: its configs get AIC=1e5,
    # so the hint probability of selecting it collapses
    env.elevation = env.elevation.copy()
    env.elevation[0] = 0.5
    hint = env.get_hint()
    assert hint[0] < -0.45     # ~never selected -> close to -1


def test_backend_rejects_ragged_tdelta():
    # n_times not a multiple of tdelta would silently change the solution
    # interval length (ADVICE r1): must fail loudly at construction
    with pytest.raises(ValueError, match="multiple of tdelta"):
        RadioBackend(n_stations=6, n_times=25, tdelta=10)


def test_hint_sweep_uses_stokes_i_statistic():
    """The sweep statistic must be the same Stokes-I noise_std the reward
    uses (reference get_noise_, demixingenv.py:233-252,322) — a full-pol
    RMS would rescale AIC's residual term vs the ksel*N penalty."""
    import jax

    backend = tiny_backend(admm_iters=2)
    env = DemixingEnv(K=3, backend=backend, seed=5)
    env.reset()
    mask = np.ones(3, np.float32)
    swept = np.asarray(backend.hint_sweep(
        env.ep, env.rho, mask[None, :], admm_iters=env.maxiter))[0]
    res = backend.calibrate(env.ep, env.rho, mask=mask,
                            admm_iters=env.maxiter)
    direct = float(backend.noise_std(res.residual))
    np.testing.assert_allclose(swept, direct, rtol=1e-4)
