"""Scale-out async actor-learner fleet: IMPACT IS-clip correctness
(bounds + staleness-0 bit-identity), the fused device-resident PER step
(zero host transfers), ERE sampling distribution, batched-env actors,
kill-one-actor learning continuity, and fleet checkpoint capture of
per-actor versions."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smartcal_tpu.rl import replay as rp
from smartcal_tpu.rl import sac, td3
from smartcal_tpu.runtime import (BackoffPolicy, FaultPlan, Fleet,
                                  clear_faults, install_faults)

ENV_KW = {"M": 5, "N": 5}
AGENT_KW = {"batch_size": 8, "mem_size": 64}


@pytest.fixture(autouse=True)
def in_tmp(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    yield
    clear_faults()


def _fast_backoff():
    return BackoffPolicy(base_s=0.01, factor=2.0, max_s=0.05, jitter=0.0)


def _versioned_buffer(cfg, key, n, version):
    """Fill a versioned buffer with n transitions sampled from the
    behavior policy (actions + exact behavior_logp) — one jitted batch."""
    spec = rp.versioned_spec(rp.transition_spec(cfg.obs_dim,
                                                cfg.n_actions))
    buf = rp.replay_init(cfg.mem_size, spec)
    st = sac.sac_init(jax.random.PRNGKey(7), cfg)

    @jax.jit
    def _fill(buf, key):
        k_obs, k_act = jax.random.split(key)
        obs = jax.random.normal(k_obs, (n, cfg.obs_dim))
        a, lp = sac.choose_action_logp(cfg, st, obs, k_act)
        trs = {"state": obs, "new_state": obs + 0.1, "action": a,
               "reward": (jnp.arange(n) % 3).astype(jnp.float32) - 1.0,
               "done": jnp.zeros((n,), jnp.bool_),
               "hint": jnp.zeros((n, cfg.n_actions)),
               "version": jnp.full((n,), version, jnp.int32),
               "behavior_logp": lp}
        return rp.replay_add_batch(
            buf, trs, priority=1.0 + 0.1 * jnp.arange(n, dtype=jnp.float32))

    return _fill(buf, key), st


# ---------------------------------------------------------------------------
# IS-clip weight correctness
# ---------------------------------------------------------------------------

def test_impact_weights_contract():
    """One buffer, three halves of the IMPACT-weight contract: (a) the
    stored behavior_logp round-trips through a re-evaluation of the
    stored action under the SAME params (atanh reconstruction
    tolerance); (b) weights under a DIFFERENT policy at staleness > 0
    are bounded by [1/c, c] with sane telemetry; (c) weights at
    staleness 0 are EXACTLY 1.0."""
    from smartcal_tpu.rl.networks import tanh_gaussian_log_prob

    cfg = sac.SACConfig(obs_dim=6, n_actions=2, is_clip=2.0, **AGENT_KW)
    buf, beh = _versioned_buffer(cfg, jax.random.PRNGKey(0), 16, version=3)
    batch = {k: v[:16] for k, v in buf.data.items()}

    # (a) behavior_logp round-trip under the behavior params
    actor, _ = sac._nets(cfg)
    mu, ls = actor.apply({"params": beh.actor_params}, batch["state"])
    lp = tanh_gaussian_log_prob(mu, ls, batch["action"])
    np.testing.assert_allclose(np.asarray(lp),
                               np.asarray(batch["behavior_logp"]),
                               rtol=1e-4, atol=1e-4)

    # (b) bounded + telemetry under a fresh-init (different) policy,
    # learner 3 versions ahead
    st_now = sac.sac_init(jax.random.PRNGKey(99), cfg)
    w, aux = sac.impact_weights(cfg, st_now.actor_params, batch,
                                learner_version=jnp.asarray(6))
    w = np.asarray(w)
    assert np.all(w <= 2.0 + 1e-6) and np.all(w >= 0.5 - 1e-6), w
    assert float(aux["staleness_mean"]) == 3.0
    assert 0.0 <= float(aux["is_clip_saturation"]) <= 1.0

    # (c) exactly 1.0 at staleness 0, same policy mismatch notwithstanding
    w0, aux0 = sac.impact_weights(cfg, st_now.actor_params, batch,
                                  learner_version=jnp.asarray(3))
    assert np.all(np.asarray(w0) == 1.0)
    assert float(aux0["staleness_mean"]) == 0.0


@pytest.mark.parametrize(
    "prioritized",
    [True, pytest.param(False, marks=pytest.mark.slow)])
def test_staleness0_bit_identical_to_unweighted(prioritized):
    """is_clip armed + every transition at the learner's version ==
    is_clip off, BIT-identical (the off<->on contract of collect_diag)."""
    kw = dict(obs_dim=6, n_actions=2, prioritized=prioritized, **AGENT_KW)
    cfg_on = sac.SACConfig(is_clip=2.0, **kw)
    cfg_off = sac.SACConfig(**kw)
    buf, _ = _versioned_buffer(cfg_on, jax.random.PRNGKey(1), 24,
                               version=4)
    st = sac.sac_init(jax.random.PRNGKey(2), cfg_on)
    key = jax.random.PRNGKey(5)
    st_on, buf_on, m_on = jax.jit(
        lambda s, b, k: sac.learn(cfg_on, s, b, k,
                                  learner_version=jnp.asarray(4)))(
        st, buf, key)
    st_off, buf_off, m_off = jax.jit(
        lambda s, b, k: sac.learn(cfg_off, s, b, k))(st, buf, key)
    for a, b in zip(jax.tree_util.tree_leaves(st_on),
                    jax.tree_util.tree_leaves(st_off)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(buf_on.priority),
                                  np.asarray(buf_off.priority))
    assert float(m_on["is_clip_mean"]) == 1.0
    assert float(m_on["is_clip_saturation"]) == 0.0


def test_native_backend_rejects_fleet_knobs():
    """is_clip/ERE live in the fused device-resident step; arming them
    on the native sum-tree backend must fail at CONFIG time, not
    silently no-op (ERE) or die at the first learn (is_clip)."""
    with pytest.raises(ValueError, match="native"):
        sac.SACConfig(obs_dim=6, n_actions=2, prioritized=True,
                      replay_backend="native", is_clip=2.0, **AGENT_KW)
    with pytest.raises(ValueError, match="native"):
        sac.SACConfig(obs_dim=6, n_actions=2, prioritized=True,
                      replay_backend="native", ere_eta=0.9, **AGENT_KW)


def test_slot_iterations_skip_poison_iteration_of_dead_actor():
    """A checkpoint taken while an actor is dead (not yet restarted, or
    past max_restarts) must record the iteration AFTER the killing one —
    otherwise every resume replays the poison pill."""
    import time

    def work(actor_id, iteration, weights):
        if iteration == 1:
            raise RuntimeError("poison")
        return iteration

    fleet = Fleet(1, work, max_restarts=0, backoff=_fast_backoff())
    fleet.start(None)
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            a = fleet._actors[0]
            if not a.is_alive() and a.error is not None:
                break
            time.sleep(0.01)
        assert fleet.slot_iterations() == {0: 2}
    finally:
        fleet.stop(join=True)


def test_td3_staleness_weights_bounds_and_identity():
    cfg = td3.TD3Config(obs_dim=6, n_actions=2, is_clip=4.0, is_decay=0.5,
                        **AGENT_KW)
    batch = {"version": jnp.asarray([5, 5, 4, 3, 0], jnp.int32)}
    w, aux = td3.staleness_weights(cfg, batch, learner_version=5)
    w = np.asarray(w)
    # staleness [0,0,1,2,5] -> [1, 1, .5, .25, clip(1/32 -> 1/4)]
    np.testing.assert_allclose(w, [1.0, 1.0, 0.5, 0.25, 0.25])
    assert np.all(w >= 1.0 / 4.0) and np.all(w <= 1.0)
    # of the 3 stale transitions, staleness 2 sits AT the bound
    # (0.5**2 == 1/4) and staleness 5 is past it -> 2/3 saturated
    assert float(aux["is_clip_saturation"]) == pytest.approx(2.0 / 3.0)


# ---------------------------------------------------------------------------
# fused device-resident PER step: no host round-trip
# ---------------------------------------------------------------------------

def test_fused_per_learn_step_zero_host_transfers():
    """The fused sample -> learn -> priority-update step runs start to
    finish with device transfers DISALLOWED: the sampled batch (and the
    priorities it re-writes) never round-trips the host."""
    cfg = sac.SACConfig(obs_dim=6, n_actions=2, prioritized=True,
                        is_clip=2.0, ere_eta=0.99, **AGENT_KW)
    buf, _ = _versioned_buffer(cfg, jax.random.PRNGKey(1), 32, version=1)
    st = sac.sac_init(jax.random.PRNGKey(2), cfg)
    fused = jax.jit(lambda s, b, k, v: sac.learn(cfg, s, b, k,
                                                 learner_version=v))
    # commit every input to the device and warm the compile OUTSIDE the
    # guard (tracing/compile may constant-fold through host values)
    args = jax.device_put((st, buf, jax.random.PRNGKey(3),
                           jnp.asarray(2, jnp.int32)))
    out = fused(*args)
    jax.block_until_ready(out)
    k2 = jax.device_put(jax.random.PRNGKey(4))
    with jax.transfer_guard("disallow"):
        st2, buf2, metrics = fused(args[0], args[1], k2, args[3])
        jax.block_until_ready((st2, buf2))
    # the step really did learn + re-prioritise
    assert int(st2.learn_counter) == int(st.learn_counter) + 1
    assert not np.array_equal(np.asarray(buf2.priority),
                              np.asarray(buf.priority))


# ---------------------------------------------------------------------------
# ERE sampling distribution
# ---------------------------------------------------------------------------

def _fill_uniform_buffer(n=64, size=64):
    spec = {"x": ((), jnp.float32)}
    buf = rp.replay_init(size, spec)
    for i in range(n):
        buf = rp.replay_add(buf, {"x": jnp.asarray(float(i))},
                            priority=jnp.asarray(1.0))
    return buf


def test_ere_uniform_at_eta_one():
    buf = _fill_uniform_buffer()
    w = np.asarray(rp.ere_weights(buf, 1.0))
    np.testing.assert_array_equal(w, np.ones(64, np.float32))
    sample = jax.jit(lambda b, k: rp.replay_sample_ere(b, k, 16, 1.0))
    counts = np.zeros(64)
    for i in range(200):
        _, idx = sample(buf, jax.random.PRNGKey(i))
        np.add.at(counts, np.asarray(idx), 1)
    freq = counts / counts.sum()
    # uniform within a loose tolerance at 3200 draws
    assert freq.max() < 3.5 / 64 and freq.min() > 0.2 / 64, freq


def test_ere_oversamples_recent_at_eta_below_one():
    buf = _fill_uniform_buffer()
    ages = np.asarray((int(buf.cntr) - 1 - np.arange(64)) % 64)
    sample = jax.jit(lambda b, k: rp.replay_sample_ere(b, k, 16, 0.9))
    counts = np.zeros(64)
    for i in range(200):
        _, idx = sample(buf, jax.random.PRNGKey(i))
        np.add.at(counts, np.asarray(idx), 1)
    total = counts.sum()
    frac_recent = counts[ages < 16].sum() / total   # newest quartile
    mean_age = float((counts * ages).sum() / total)
    # eta=0.9 with span 100: newest quartile should dominate
    assert frac_recent > 0.5, frac_recent
    assert mean_age < np.mean(ages), (mean_age, np.mean(ages))


def test_ere_composes_with_per_priorities():
    """PER + ERE: the effective distribution is priority * recency —
    a high-priority OLD slot is still sampled less than under plain
    PER."""
    buf = _fill_uniform_buffer()
    # give the OLDEST slot a huge priority
    buf = buf._replace(priority=buf.priority.at[0].set(50.0))
    sample_plain = jax.jit(lambda b, k: rp.replay_sample_per(b, k, 16))
    sample_ere = jax.jit(
        lambda b, k: rp.replay_sample_per(b, k, 16, recency_eta=0.9))
    hits_plain, hits_ere = 0, 0
    for i in range(100):
        _, idx, _, _ = sample_plain(buf, jax.random.PRNGKey(i))
        hits_plain += int(np.sum(np.asarray(idx) == 0))
        _, idx2, _, _ = sample_ere(buf, jax.random.PRNGKey(i))
        hits_ere += int(np.sum(np.asarray(idx2) == 0))
    assert hits_ere < hits_plain, (hits_ere, hits_plain)


# ---------------------------------------------------------------------------
# fleet end-to-end: batched lanes, kill-one-actor continuity, checkpoints
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fleet_kill_one_actor_keeps_learning(tmp_path):
    """Injected kill of actor 1 mid-run with the IS-clip armed and
    batched env lanes: the run completes, the supervisor restarts the
    slot, and the learner genuinely learned (learn counter advanced,
    versioned replay filled).  (Slow tier: the plain kill-restart path
    stays in tier-1 via tests/test_supervised.py, and the CLI chain via
    tools/smoke_fleet.sh.)"""
    from smartcal_tpu.parallel import learner

    install_faults(FaultPlan(kill_actor=1, kill_at=1))
    run = str(tmp_path / "fleet.jsonl")
    (st, buf), scores, summary = learner.train_supervised(
        seed=0, episodes=6, n_actors=2, env_kwargs=ENV_KW,
        agent_kwargs=AGENT_KW, rollout_epochs=1, rollout_steps=4,
        batch_envs=2, is_clip=2.0, quiet=True, metrics=run,
        restart_backoff=_fast_backoff())
    clear_faults()
    assert len(scores) == 6
    assert np.all(np.isfinite(scores))
    assert summary["restarts"] >= 1
    assert int(st.learn_counter) > 0          # learning continued
    assert int(buf.cntr) > 0
    assert "version" in buf.data and "behavior_logp" in buf.data
    events = [json.loads(ln) for ln in open(run) if ln.strip()]
    kinds = {e["event"] for e in events}
    assert {"fault_injected", "actor_down", "actor_restart"} <= kinds
    gauges = {e["name"] for e in events if e["event"] == "gauge"}
    assert "weight_staleness_versions" in gauges
    assert "is_clip_saturation" in gauges
    assert "per_actor_transitions_per_s" in gauges


@pytest.mark.slow
def test_fleet_checkpoint_resume_carries_actor_iterations(tmp_path):
    """A fleet checkpoint captures per-actor rollout iterations and the
    learner version; --resume restores them so the per-(actor,
    iteration) key streams continue instead of replaying."""
    from smartcal_tpu.parallel import learner
    from smartcal_tpu.runtime.checkpoint import load_latest

    kw = dict(seed=0, n_actors=2, env_kwargs=ENV_KW,
              agent_kwargs=AGENT_KW, rollout_epochs=1, rollout_steps=4,
              batch_envs=2, is_clip=2.0, quiet=True,
              ckpt_dir=str(tmp_path / "ck"), ckpt_every=2,
              restart_backoff=_fast_backoff())
    (_, _), s1, _ = learner.train_supervised(episodes=4, **kw)
    assert len(s1) == 4
    payload, step = load_latest(str(tmp_path / "ck"))
    assert payload["kind"] == "fleet"
    assert set(payload["actor_iterations"]) == {0, 1}
    assert all(v >= 1 for v in payload["actor_iterations"].values())
    assert payload["learner_version"] >= step
    saved_iters = dict(payload["actor_iterations"])

    (_, buf2), s2, summ2 = learner.train_supervised(episodes=7,
                                                    resume=True, **kw)
    # resumed run continued the episode count and kept learning
    assert len(s2) == 7
    assert s2[:step] == pytest.approx(payload["scores"][:step])
    payload2, step2 = load_latest(str(tmp_path / "ck"))
    assert step2 > step
    # the resumed fleet started at (not before) the saved iterations
    assert all(payload2["actor_iterations"][k] >= saved_iters[k]
               for k in saved_iters)
    assert payload2["learner_version"] > payload["learner_version"]


@pytest.mark.slow
def test_publish_every_forces_staleness(tmp_path):
    """publish_every > 1 (the ablation knob) produces genuinely stale
    transitions: the staleness gauge exceeds 1 and the fused step's
    transition-staleness telemetry is non-zero."""
    from smartcal_tpu.parallel import learner

    run = str(tmp_path / "stale.jsonl")
    (_, _), scores, _ = learner.train_supervised(
        seed=0, episodes=8, n_actors=2, env_kwargs=ENV_KW,
        agent_kwargs=AGENT_KW, rollout_epochs=1, rollout_steps=4,
        is_clip=2.0, publish_every=4, quiet=True, metrics=run,
        restart_backoff=_fast_backoff())
    events = [json.loads(ln) for ln in open(run) if ln.strip()]
    stale_gauges = [e["value"] for e in events
                    if e.get("event") == "gauge"
                    and e["name"] == "weight_staleness_versions"]
    assert max(stale_gauges) >= 2, stale_gauges
    tr_stale = [e["value"] for e in events
                if e.get("event") == "gauge"
                and e["name"] == "transition_staleness_mean"]
    assert tr_stale and max(tr_stale) > 0.0
