"""Tests for coherency prediction, coordinates, and the text data edge."""

import math

import numpy as np

from smartcal_tpu.cal import coherency, coords, skyio


def _loop_predict(uu, vv, ww, sky, freq, smear=False, fdelta=180e3):
    """Per-source loop oracle of the documented prediction math."""
    scale = 2 * math.pi * freq / coherency.C_LIGHT
    uu = np.asarray(uu) * scale
    vv = np.asarray(vv) * scale
    ww = np.asarray(ww) * scale
    K = sky.n_clusters
    C = np.zeros((K, len(uu), 4), np.complex64)
    for s in range(sky.lmn.shape[0]):
        l, m, n = np.asarray(sky.lmn[s])
        coef = np.asarray(sky.flux_coef[s])
        fr = math.log(freq / float(sky.f0[s]))
        si = math.exp(coef[0] + coef[1] * fr + coef[2] * fr ** 2
                      + coef[3] * fr ** 3)
        phase = uu * l + vv * m + ww * n
        amp = si * np.ones_like(phase)
        if smear:
            amp = amp * np.abs(np.sinc(phase * 0.5 * (fdelta / freq) / np.pi))
        if bool(sky.is_gauss[s]):
            # reference quirk: acos of the n-excess (calibration_tools.py:436)
            phi = -math.acos(n)
            xi = -math.atan2(-l, m)
            eX, eY, eP = np.asarray(sky.gauss[s])
            uup = uu * math.cos(xi) - vv * math.cos(phi) * math.sin(xi) \
                + ww * math.sin(phi) * math.sin(xi)
            vvp = uu * math.sin(xi) + vv * math.cos(phi) * math.cos(xi) \
                - ww * math.sin(phi) * math.cos(xi)
            uut = 2 * eX * (math.cos(eP) * uup - math.sin(eP) * vvp)
            vvt = 2 * eY * (math.sin(eP) * uup + math.cos(eP) * vvp)
            amp = amp * 0.5 * math.pi * np.exp(-(uut ** 2 + vvt ** 2))
        xx = amp * (np.cos(phase) + 1j * np.sin(phase))
        C[int(sky.cluster[s]), :, 0] += xx
    C[:, :, 3] = C[:, :, 0]
    return C


def _random_sky(rng, n_src=6, n_clusters=2, gauss=False):
    lm = rng.uniform(-0.05, 0.05, size=(n_src, 2))
    n = np.sqrt(1 - (lm ** 2).sum(-1)) - 1
    lmn = np.concatenate([lm, n[:, None]], axis=-1)
    flux = np.stack([np.log(rng.uniform(1, 10, n_src)),
                     rng.uniform(-1, 0, n_src),
                     rng.uniform(-0.1, 0.1, n_src),
                     np.zeros(n_src)], axis=-1)
    g = np.zeros((n_src, 3))
    isg = np.zeros(n_src, bool)
    if gauss:
        isg[::2] = True
        g[:, 0] = rng.uniform(1e-4, 1e-3, n_src)
        g[:, 1] = rng.uniform(1e-4, 1e-3, n_src)
        g[:, 2] = rng.uniform(0, np.pi, n_src)
    return coherency.SkyArrays(
        lmn=lmn, flux_coef=flux, f0=np.full(n_src, 150e6), gauss=g,
        is_gauss=isg, cluster=rng.integers(0, n_clusters, n_src),
        n_clusters=n_clusters)


class TestPredict:
    def test_point_sources_match_oracle(self, rng):
        sky = _random_sky(rng)
        uu, vv, ww = (rng.uniform(-500, 500, 20) for _ in range(3))
        got = np.asarray(coherency.predict_coherencies(uu, vv, ww, sky, 140e6))
        want = _loop_predict(uu, vv, ww, sky, 140e6)
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)

    def test_gaussian_and_smearing(self, rng):
        sky = _random_sky(rng, gauss=True)
        uu, vv, ww = (rng.uniform(-500, 500, 16) for _ in range(3))
        got = np.asarray(coherency.predict_coherencies(
            uu, vv, ww, sky, 140e6, smear=True))
        want = _loop_predict(uu, vv, ww, sky, 140e6, smear=True)
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)

    def test_cross_pols_zero(self, rng):
        sky = _random_sky(rng)
        uu, vv, ww = (rng.uniform(-500, 500, 8) for _ in range(3))
        C = np.asarray(coherency.predict_coherencies(uu, vv, ww, sky, 140e6))
        assert np.all(C[:, :, 1] == 0) and np.all(C[:, :, 2] == 0)


class TestCoords:
    def test_lm_roundtrip(self, rng):
        """lmtoradec keeps the reference's RA sign convention: it mirrors l
        (calibration_tools.py:36 uses atan2(-l, ...)), so a roundtrip
        through radectolm returns (-l, m)."""
        ra0, dec0 = 1.0, 0.7
        ra = ra0 + rng.uniform(-0.02, 0.02, 10)
        dec = dec0 + rng.uniform(-0.02, 0.02, 10)
        l, m, _ = coords.radectolm(ra, dec, ra0, dec0)
        ra2, dec2 = coords.lmtoradec(l, m, ra0, dec0)
        l2, m2, _ = coords.radectolm(ra2, dec2, ra0, dec0)
        np.testing.assert_allclose(np.asarray(l2), -np.asarray(l), atol=1e-5)
        # m only roundtrips to the small-field approximation error
        np.testing.assert_allclose(np.asarray(m2), np.asarray(m), atol=5e-4)
        np.testing.assert_allclose(np.asarray(dec2), dec, atol=1e-3)

    def test_sexagesimal_roundtrip(self):
        for rad in [0.3, 1.9, 5.0]:
            h, m, s = coords.rad_to_ra(rad)
            assert abs(coords.hms_to_rad(h, m, s) - rad) < 1e-9
        # includes |dec| < 1 deg (sign carried by min/sec) and ~0 edge cases
        for rad in [-0.5, 0.2, 1.2, -0.005, -0.0001, -1e-7]:
            d, m, s = coords.rad_to_dec(rad)
            assert abs(coords.dms_to_rad(d, m, s) - rad) < 1e-9
        # negative-zero degree field from text parsing ('-00 12 34')
        assert coords.dms_to_rad(-0.0, 12, 34) < 0

    def test_separation_zero_and_known(self):
        assert float(coords.angular_separation(1.0, 0.5, 1.0, 0.5)) < 1e-7
        # pole to equator = pi/2
        sep = float(coords.angular_separation(0.0, np.pi / 2, 0.0, 0.0))
        np.testing.assert_allclose(sep, np.pi / 2, rtol=1e-6)

    def test_azel_zenith(self):
        # source at dec=lat, ha=0 is at zenith
        lat = 0.9
        _, el = coords.azel_from_radec(1.0, lat, 1.0, lat)
        np.testing.assert_allclose(float(el), np.pi / 2, atol=1e-5)


class TestSkyIO:
    def test_sky_cluster_parse_and_build(self, tmp_path, rng):
        sky = tmp_path / "sky.txt"
        sky.write_text(
            "# name h m s d m s sI sQ sU sV sp1 sp2 sp3 RM eX eY eP f0\n"
            "P1 1 2 3.0 45 10 5.0 2.5 0 0 0 -0.7 0 0 0 0 0 0 150e6\n"
            "GS1 1 3 4.0 44 20 6.0 1.5 0 0 0 -0.5 0.1 0 0 1e-3 2e-3 0.3 150e6\n"
            "P2 0 59 0.0 45 0 0.0 4.0 0 0 0 0 0 0 0 0 0 0 140e6\n")
        clus = tmp_path / "cluster.txt"
        clus.write_text("# clusters\n1 1 P1 GS1\n3 1 P2\n")
        ra0 = coords.hms_to_rad(1, 0, 0)
        dec0 = coords.dms_to_rad(45, 0, 0)
        arr = skyio.build_sky_arrays(str(sky), str(clus), ra0, dec0)
        assert arr.n_clusters == 2
        assert list(np.asarray(arr.cluster)) == [0, 0, 1]
        assert list(np.asarray(arr.is_gauss)) == [False, True, False]
        np.testing.assert_allclose(
            np.asarray(arr.flux_coef[0, 0]), np.log(2.5), rtol=1e-6)
        # lmn magnitudes are small for near-center sources
        assert np.all(np.abs(np.asarray(arr.lmn)[:, :2]) < 0.05)

    def test_rho_roundtrip(self, tmp_path):
        path = tmp_path / "rho.txt"
        rs = np.asarray([1.5, 20.0, 3.25], np.float32)
        rp = np.asarray([0.075, 1.0, 0.1625], np.float32)
        skyio.write_rho(str(path), rs, rp)
        rs2, rp2 = skyio.read_rho(str(path), 3)
        np.testing.assert_allclose(rs2, rs)
        np.testing.assert_allclose(rp2, rp)

    def test_solutions_roundtrip(self, rng):
        K, N, Nto = 2, 3, 2
        J = (rng.standard_normal((K, 2 * N * Nto, 2))
             + 1j * rng.standard_normal((K, 2 * N * Nto, 2))
             ).astype(np.complex64)
        import tempfile, os
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "sols.txt")
            skyio.write_solutions(p, 150e6, J, N)
            freq, J2 = skyio.read_solutions(p)
        assert freq == 150e6
        np.testing.assert_allclose(J2, J, rtol=1e-5, atol=1e-5)

    def test_uvw_visibility_roundtrip(self, tmp_path, rng):
        T = 12
        vis = [rng.standard_normal(T) + 1j * rng.standard_normal(T)
               for _ in range(4)]
        path = tmp_path / "vis.txt"
        skyio.write_uvw_visibilities(str(path), *vis)
        # pad u,v,w columns so read (which expects 11 cols) works
        lines = path.read_text().strip().split("\n")
        path.write_text("\n".join("0 0 0 " + ln for ln in lines) + "\n")
        back = skyio.read_uvw_visibilities(str(path))
        for a, b in zip(back, vis):
            np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_global_solutions_parse(self, tmp_path, rng):
        # synthesize a zsol-format file: P=2, N=2, K=2, Nto=1
        P, N, K, Nto = 2, 2, 2, 1
        vals = rng.standard_normal((8 * P * N * Nto, K)).astype(np.float32)
        lines = ["# zsol", "# header",
                 f"150.0 {P} {N} {K} {K}"]
        for i, row in enumerate(vals):
            lines.append(f"{i % (8 * P * N)} " + " ".join(map(str, row)))
        p = tmp_path / "zsol"
        p.write_text("\n".join(lines) + "\n")
        n_stat, freq, P2, K2, Z = skyio.read_global_solutions(str(p))
        assert (n_stat, P2, K2) == (N, P, K)
        assert freq == 150e6
        assert Z.shape == (Nto, K, 2 * P * N, 2)
        # spot-check the column-major complex packing of direction 0
        b = vals[:, 0]
        c = b[0::2] + 1j * b[1::2]
        np.testing.assert_allclose(Z[0, 0, :, 0], c[:2 * P * N], rtol=1e-6)
        np.testing.assert_allclose(Z[0, 0, :, 1], c[2 * P * N:], rtol=1e-6)
