"""Regression radar (ISSUE 19): baseline store, noise-aware detector,
perf-gate judging, results index.

The load-bearing claims, each pinned here:

* KEYED BY HOST — baselines are keyed on stage + statics digest + host
  fingerprint digest; a lookup from a different host/shape finds NO
  baseline, and an explicit cross-fingerprint compare RAISES — the
  2026-08-07 cross-host comparison bug made structurally impossible.
* SEEDED REGRESSIONS FIRE — a 2x wall slowdown, inflated peak bytes,
  and out-of-band numeric drift must all produce FIRE verdicts carrying
  the measured delta and the noise band they were judged against.
* NOISE DOES NOT FIRE — resamples from the baseline's own distribution
  must produce zero FIREs across N trials (the false-positive bound the
  tier-1 gate's greenness rests on).
* SCHEMA OR REFUSE — a corrupt/mis-versioned store raises
  BaselineSchemaError instead of silently comparing garbage.

Pure host-side logic (the obs package is stdlib-only) — no JAX, runs
in milliseconds.  The end-to-end gate (real stages, fault injection,
--update-baseline round-trip) lives in tools/smoke_perfgate.sh.
"""

import json
import random

import pytest

from conftest import load_tool_module
from smartcal_tpu.obs import baselines as bl
from smartcal_tpu.obs import regress as rg

FP_A = {"nproc": 1, "platform": "linux", "machine": "x86_64",
        "python": "3.10.16", "jax": "0.4.37", "jaxlib": "0.4.36",
        "dtype_policy": {"x64": False, "bf16_rel_band": bl.BF16_REL_BAND}}
FP_B = dict(FP_A, nproc=24)            # same box, different cgroup
STATICS = {"stage": "solve", "n_stations": 6, "npix": 32}


def _samples(mean, cv, n=5, seed=42):
    rng = random.Random(seed)
    return [max(1e-9, rng.gauss(mean, cv * mean)) for _ in range(n)]


def _baseline_store(tmp_path, wall_mean=1.0, cv=0.02):
    store = bl.BaselineStore(str(tmp_path / "base.json"))
    store.record("solve", STATICS, FP_A, {
        "wall_s": bl.summarize_samples(_samples(wall_mean, cv)),
        "peak_bytes": bl.scalar_metric(1.0e6),
        "flops": bl.scalar_metric(2.0e7),
        "compile_events": bl.scalar_metric(0.0),
    })
    return store


# ---------------------------------------------------------------------------
# store
# ---------------------------------------------------------------------------

class TestBaselineStore:
    def test_round_trip_through_disk(self, tmp_path):
        store = _baseline_store(tmp_path)
        assert store.save() is True
        assert store.save() is False        # idempotent: not dirty
        re = bl.BaselineStore(store.path)
        ent = re.get("solve", STATICS, FP_A)
        assert ent is not None
        assert ent["metrics"]["wall_s"]["n"] == 5
        assert ent["fingerprint_digest"] == bl.fingerprint_digest(FP_A)

    def test_lookup_is_fingerprint_scoped(self, tmp_path):
        store = _baseline_store(tmp_path)
        assert store.get("solve", STATICS, FP_B) is None
        assert store.get("solve", dict(STATICS, npix=64), FP_A) is None
        assert store.get("influence", STATICS, FP_A) is None

    def test_corrupt_document_refuses(self, tmp_path):
        p = tmp_path / "base.json"
        p.write_text("{not json")
        with pytest.raises(bl.BaselineSchemaError):
            bl.BaselineStore(str(p)).get("solve", STATICS, FP_A)

    def test_wrong_schema_version_refuses(self, tmp_path):
        p = tmp_path / "base.json"
        p.write_text(json.dumps({"schema": 999, "entries": {}}))
        with pytest.raises(bl.BaselineSchemaError):
            bl.BaselineStore(str(p)).get("solve", STATICS, FP_A)

    def test_malformed_entry_refuses(self, tmp_path):
        p = tmp_path / "base.json"
        p.write_text(json.dumps({"schema": bl.SCHEMA_VERSION, "entries": {
            "k": {"stage": "s", "statics": {}, "fingerprint": {},
                  "metrics": {"wall_s": {"kind": "mystery"}}}}}))
        with pytest.raises(bl.BaselineSchemaError):
            bl.BaselineStore(str(p)).entries()

    def test_record_rejects_raw_metric_dicts(self, tmp_path):
        store = bl.BaselineStore(str(tmp_path / "b.json"))
        with pytest.raises(bl.BaselineSchemaError):
            store.record("s", {}, FP_A, {"wall_s": {"value": 1.0}})

    def test_fingerprint_digest_stability(self):
        fp1 = bl.host_fingerprint()
        fp2 = bl.host_fingerprint()
        assert bl.fingerprint_digest(fp1) == bl.fingerprint_digest(fp2)
        assert bl.fingerprint_digest(FP_A) != bl.fingerprint_digest(FP_B)
        assert "nproc" in fp1 and "dtype_policy" in fp1


# ---------------------------------------------------------------------------
# detector
# ---------------------------------------------------------------------------

class TestDetector:
    def test_seeded_regressions_fire_with_delta_and_band(self, tmp_path):
        """2x slowdown + inflated peak bytes + out-of-band drift: each
        axis FIREs, each finding names the stage and carries the
        measured delta and the noise band it was judged against."""
        store = _baseline_store(tmp_path)
        measured = {
            "wall_s": bl.summarize_samples(
                [2.0 * s for s in _samples(1.0, 0.02, seed=7)]),
            "peak_bytes": bl.scalar_metric(1.3e6),
            "flops": bl.scalar_metric(2.0e7),
            "compile_events": bl.scalar_metric(0.0),
            "rel_err": bl.scalar_metric(5e-2),
        }
        fs = {f.metric: f for f in rg.compare(store, "solve", STATICS,
                                              FP_A, measured)}
        assert fs["wall_s"].verdict == rg.FIRE
        assert fs["wall_s"].delta_rel == pytest.approx(1.0, abs=0.15)
        assert fs["wall_s"].ci95[0] > 1.15      # CI separated from warn
        assert fs["peak_bytes"].verdict == rg.FIRE
        assert fs["peak_bytes"].delta_rel == pytest.approx(0.3, abs=1e-6)
        assert fs["rel_err"].verdict == rg.FIRE
        assert fs["flops"].verdict == rg.OK
        for f in fs.values():
            text = f.render()
            assert f.stage == "solve" and "noise" in text

    def test_same_distribution_resamples_never_fire(self, tmp_path):
        """FP bound: N fresh resamples of the baseline's own noise must
        produce ZERO FIREs — a green gate stays green."""
        store = _baseline_store(tmp_path)
        fired = []
        for trial in range(40):
            measured = {
                "wall_s": bl.summarize_samples(
                    _samples(1.0, 0.02, seed=1000 + trial)),
                "peak_bytes": bl.scalar_metric(1.0e6),
                "compile_events": bl.scalar_metric(0.0),
            }
            for f in rg.compare(store, "solve", STATICS, FP_A, measured,
                                seed=trial):
                if f.verdict == rg.FIRE:
                    fired.append((trial, f.render()))
        assert fired == []

    def test_improvement_never_fires(self, tmp_path):
        store = _baseline_store(tmp_path)
        measured = {"wall_s": bl.summarize_samples(
            [0.5 * s for s in _samples(1.0, 0.02, seed=9)]),
            "peak_bytes": bl.scalar_metric(0.5e6)}
        assert all(f.verdict == rg.OK
                   for f in rg.compare(store, "solve", STATICS, FP_A,
                                       measured))

    def test_any_recompile_fires(self, tmp_path):
        store = _baseline_store(tmp_path)
        fs = rg.compare(store, "solve", STATICS, FP_A,
                        {"compile_events": bl.scalar_metric(1.0)})
        assert [f.verdict for f in fs] == [rg.FIRE]

    def test_cross_fingerprint_compare_raises(self, tmp_path):
        store = _baseline_store(tmp_path)
        entry = store.get("solve", STATICS, FP_A)
        with pytest.raises(rg.FingerprintMismatch):
            rg.compare_entry(entry, "solve", STATICS, FP_B,
                             {"wall_s": bl.summarize_samples([1.0])})

    def test_changed_statics_compare_raises(self, tmp_path):
        store = _baseline_store(tmp_path)
        entry = store.get("solve", STATICS, FP_A)
        with pytest.raises(rg.FingerprintMismatch):
            rg.compare_entry(entry, "solve", dict(STATICS, npix=64),
                             FP_A, {"wall_s": bl.summarize_samples([1.0])})

    def test_fresh_host_is_no_baseline_not_red(self, tmp_path):
        """Store-level compare from an unblessed host: NO BASELINE
        verdicts (informative, exit stays green) — except the absolute
        bf16 band, which applies everywhere."""
        store = _baseline_store(tmp_path)
        fs = {f.metric: f for f in rg.compare(
            store, "solve", STATICS, FP_B,
            {"wall_s": bl.summarize_samples(_samples(99.0, 0.02)),
             "rel_err": bl.scalar_metric(5e-2)})}
        assert fs["wall_s"].verdict == rg.NO_BASELINE
        assert fs["rel_err"].verdict == rg.FIRE
        assert rg.worst_verdict(list(fs.values())) == rg.FIRE

    def test_bootstrap_ci_is_deterministic(self):
        a = _samples(2.0, 0.05, seed=3)
        b = _samples(1.0, 0.05, seed=4)
        assert rg.bootstrap_ratio_ci(a, b, seed=5) == \
            rg.bootstrap_ratio_ci(a, b, seed=5)
        lo, hi = rg.bootstrap_ratio_ci(a, b, seed=5)
        assert 1.5 < lo <= hi < 2.5


# ---------------------------------------------------------------------------
# perf_gate judging (host-side half; stages run in smoke_perfgate.sh)
# ---------------------------------------------------------------------------

class TestPerfGateJudge:
    def test_numeric_drift_folds_into_band_rel_err(self, tmp_path):
        gate = load_tool_module("perf_gate")
        store = bl.BaselineStore(str(tmp_path / "b.json"))
        statics = {"stage": "solve"}
        store.record("solve", statics, FP_A, {
            "wall_s": bl.summarize_samples(_samples(1.0, 0.02)),
            "numeric": bl.scalar_metric(1.0),
        })
        metrics = {"wall_s": bl.summarize_samples(
            _samples(1.0, 0.02, seed=11)),
            "numeric": bl.scalar_metric(1.05)}
        fs = {f.metric: f for f in gate.judge(store, "solve", statics,
                                              FP_A, metrics)}
        assert "numeric" not in fs          # never compared directly
        assert fs["rel_err"].verdict == rg.FIRE
        assert fs["rel_err"].new_value == pytest.approx(0.05)
        # in-band drift stays green
        metrics["numeric"] = bl.scalar_metric(1.0 + 1e-3)
        fs = {f.metric: f for f in gate.judge(store, "solve", statics,
                                              FP_A, metrics)}
        assert fs["rel_err"].verdict == rg.OK


# ---------------------------------------------------------------------------
# results index
# ---------------------------------------------------------------------------

class TestResultsIndex:
    @pytest.fixture()
    def ridx(self):
        return load_tool_module("results_index")

    def test_round_stamp_extraction(self, ridx):
        assert ridx.artifact_round("nscale_r13.json") == 13
        assert ridx.artifact_round("serve_fleet_r15.json") == 15
        assert ridx.artifact_round("per_bench.json") is None
        assert ridx.artifact_round("enet_sweep_r2/summary.json") is None

    def test_scan_classifies_and_orders_trajectories(self, ridx,
                                                     tmp_path):
        for rnd, val in ((3, 9.0), (12, 4.0), (7, 6.0)):
            (tmp_path / f"thing_r{rnd}.json").write_text(json.dumps(
                {"metric": "thing", "value": val, "unit": "s",
                 "host_fingerprint_digest": "abc"}))
        (tmp_path / "notes.md").write_text("x")
        (tmp_path / "suite_r4.json").write_text(json.dumps(
            {"bench": "suite", "runs": []}))
        doc = ridx.scan(str(tmp_path))
        assert doc["problems"] == []
        by = {r["path"]: r for r in doc["artifacts"]}
        assert by["thing_r3.json"]["schema"] == "bench"
        assert by["thing_r3.json"]["fingerprint"] == "digest"
        assert by["suite_r4.json"]["schema"] == "bench-suite"
        traj = doc["trajectories"]["thing"]
        assert [p["round"] for p in traj] == [3, 7, 12]
        assert [p["value"] for p in traj] == [9.0, 6.0, 4.0]
        assert doc["other_files"] == ["notes.md"]

    def test_schema_problems_reported_and_strict_exit(self, ridx,
                                                      tmp_path,
                                                      capsys):
        (tmp_path / "bad_r9.json").write_text(
            json.dumps({"metric": "m", "value": "oops"}))
        (tmp_path / "broken.json").write_text("{nope")
        doc = ridx.scan(str(tmp_path))
        assert len(doc["problems"]) == 3
        assert ridx.main(["--results", str(tmp_path), "--no-write"]) == 0
        assert ridx.main(["--results", str(tmp_path), "--no-write",
                          "--strict"]) == 1
        capsys.readouterr()

    def test_index_md_written_and_repo_corpus_clean(self, ridx,
                                                    tmp_path, capsys):
        (tmp_path / "a_r1.json").write_text(json.dumps(
            {"metric": "a", "value": 1.0, "unit": "s"}))
        assert ridx.main(["--results", str(tmp_path), "--strict"]) == 0
        out = capsys.readouterr().out
        assert "1 bench payload(s)" in out
        md = (tmp_path / "INDEX.md").read_text()
        assert "| a | r1: 1.0 | s |" in md
        # the shipped results/ corpus must stay schema-clean
        repo_doc = ridx.scan("results")
        assert repo_doc["problems"] == []
