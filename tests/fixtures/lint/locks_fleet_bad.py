"""Positive fixture: unlocked writes to the FLEET-ROUTER shared state
(the ISSUE 16 replica table / fleet counters / per-replica pending
table and gauges).

The test registers this file with two specs mirroring the shipped
SHARED_FIELD_SPECS rows: class FleetRouter, fields {_replicas, _stats,
_next_rid, _retired}, lock {_lock}; class Replica, fields {_pending,
_gauges}, lock {_lock}.
"""
import threading


class FleetRouter:
    def __init__(self):
        self._lock = threading.Lock()
        self._replicas = {}            # ok: __init__ runs pre-sharing
        self._stats = {"shed": 0}
        self._next_rid = 0
        self._retired = []

    def spawn(self, r):
        self._next_rid += 1            # BAD: aug-assign without the lock
        self._replicas[0] = r          # BAD: subscript store, no lock

    def reap(self, rid, r):
        self._replicas.pop(rid)        # BAD: mutator without the lock
        self._retired.append(r)        # BAD: mutator without the lock

    def shed(self):
        self._stats["shed"] += 1       # BAD: subscript store, no lock


class Replica:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = {}
        self._gauges = {"queue_depth": 0}

    def dispatch(self, job):
        self._pending[job.job_id] = job  # BAD: pending insert, no lock

    def on_beat(self, g):
        self._gauges.update(g)         # BAD: mutator without the lock

    def take(self):
        jobs = list(self._pending.values())
        self._pending = {}             # BAD: table swap without the lock
        return jobs
