"""Negative fixture: host-static flag values — zero findings."""
import numpy as np


def literal_flags(solver, x):
    return solver(x, collect_stats=True, optimized=False)


def host_config(solver, x, args, self_like):
    a = solver(x, collect_diag=args.diag)          # argparse bool: host
    b = solver(x, fused=self_like.fused)           # instance config: host
    return a, b


def helper_call(solver, x, args, diag_from_args):
    return solver(x, collect_diag=diag_from_args(args))   # host helper


def host_numpy_is_fine(solver, x, mask):
    return solver(x, optimized=bool(np.any(mask)))  # numpy is host-side


def plain_keyword_named_like_flag(x):
    # a dict key is not a call keyword; never flagged
    return {"optimized": x}
