"""Positive fixture: unlocked writes to the CROSS-PROCESS fleet fields
(the ISSUE 12 shard directory / slot->shard map / weights outbox).

The test registers this file with two specs mirroring the shipped
SHARED_FIELD_SPECS rows: class Fleet, fields {_shard_qs, _slot_shard},
lock {_wlock}; class ProcessActor, fields {_outbox}, lock
{_outbox_lock}.
"""
import threading


class Fleet:
    def __init__(self):
        self._wlock = threading.Lock()
        self._shard_qs = []            # ok: __init__ runs pre-sharing
        self._slot_shard = {}

    def grow(self, q):
        self._shard_qs.append(q)       # BAD: mutator without the lock

    def remap(self, slot, shard):
        self._slot_shard[slot] = shard  # BAD: subscript store, no lock

    def rebuild(self, n):
        self._shard_qs = [None] * n    # BAD: rebind without the lock
        self._slot_shard = {}          # BAD: rebind without the lock


class ProcessActor:
    def __init__(self):
        self._outbox_lock = threading.Lock()
        self._outbox = None

    def publish(self, blob):
        self._outbox = blob            # BAD: learner-side write, no lock
