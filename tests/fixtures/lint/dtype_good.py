"""Negative fixture: policy-routed and reasoned-pin dtype choices in a
precision-policied kernel module."""
import jax.numpy as jnp

from smartcal_tpu.cal import precision as prec


def pixel_axis(npix, cell):
    return (jnp.arange(npix)).astype(prec.F32) * cell      # policy helper


def contract(a, b, precision="f32"):
    dt = prec.contraction_dtype("imager_matmul", precision)
    return jnp.matmul(a.astype(dt), b.astype(dt))


def kernel_accumulator(x):
    f32 = jnp.float32  # graftlint: disable=dtype-discipline -- pallas accumulator dtype pinned f32 by policy
    return x.astype(f32)


def host_side(x):
    import numpy as np

    return np.asarray(x, np.float32)     # numpy literals are host-side
