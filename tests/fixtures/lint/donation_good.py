"""Negative fixture: disciplined donation — zero findings."""
from functools import partial

import jax
import jax.numpy as jnp

_acc_add = jax.jit(lambda acc, x: acc + x, donate_argnums=(0,))


@partial(jax.jit, donate_argnames=("carry",))
def _step(carry, x):
    return carry + x


def rebind_idiom(acc, imgs):
    for img in imgs:
        acc = _acc_add(acc, img)    # ok: result rebinds the operand
    return acc


def rebind_then_read(carry, xs):
    carry = _step(carry, xs)
    return jnp.sum(carry)           # ok: this is the NEW carry


def non_donated_positions_are_free(acc, img):
    out = _acc_add(acc, img)
    return out + img                # ok: img (pos 1) was not donated


def branch_exclusive(acc, img, flag):
    if flag:
        return _acc_add(acc, img)
    return jnp.sum(acc)             # ok: donation on the other path only


def plain_jit_no_donation(x):
    f = jax.jit(lambda v: v * 2)
    y = f(x)
    return y + x                    # ok: no donate_argnums anywhere
