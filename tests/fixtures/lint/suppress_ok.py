"""Fixture: violations silenced by well-formed suppressions."""
import jax

# graftlint: disable-file=read-after-donation -- fixture demonstrates file-wide disable


def silenced_reuse(key):
    a = jax.random.normal(key, (2,))
    b = jax.random.normal(key, (2,))  # graftlint: disable=rng-key-reuse -- demo: intentional reuse
    return a + b
