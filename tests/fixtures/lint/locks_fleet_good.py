"""Negative fixture: lock-disciplined fleet-router shared state — zero
findings.  Registered with the same specs as locks_fleet_bad.py.
"""
import threading


class FleetRouter:
    def __init__(self):
        self._lock = threading.Lock()
        self._replicas = {}
        self._stats = {"shed": 0}
        self._next_rid = 0
        self._retired = []

    def spawn(self, r):
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1        # ok: under the annotated lock
            self._replicas[rid] = r

    def reap(self, rid, r):
        with self._lock:
            self._replicas.pop(rid)
            self._retired.append(r)

    def shed(self):
        with self._lock:
            self._stats["shed"] += 1

    def stats(self):
        with self._lock:
            return dict(self._stats)   # reads unchecked

    def _register_locked(self, rid, r):
        self._replicas[rid] = r        # ok: *_locked caller-holds-lock


class Replica:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = {}
        self._gauges = {"queue_depth": 0}

    def dispatch(self, job):
        with self._lock:
            self._pending[job.job_id] = job

    def on_beat(self, g):
        with self._lock:
            self._gauges.update(g)     # ok: under the annotated lock

    def take(self):
        with self._lock:
            jobs = list(self._pending.values())
            self._pending = {}
        return jobs
