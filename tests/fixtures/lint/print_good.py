"""Negative fixture: obs-routed output — zero findings."""


def quiet(x, obs):
    obs.echo(f"value: {x}")             # structured stderr route
    obs.emit_json({"value": x})         # stdout machine route
    return x


def method_print_ok(printer):
    printer.print("rendered table")     # .print( method: not bare


def print_in_string_ok():
    return "call print(x) to debug"     # tokenizer ignores strings
