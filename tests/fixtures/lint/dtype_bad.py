"""Positive fixture: bare dtype literals in a precision-policied kernel
module (linted with this file's name in dtype_policied_paths)."""
import jax
import jax.numpy as jnp


def pixel_axis(npix, cell):
    return (jnp.arange(npix)).astype(jnp.float32) * cell   # BAD: bare pin


def contract(a, b):
    return jnp.matmul(a, b, preferred_element_type=jnp.float32)  # BAD


def accum(x):
    return x.astype(jax.numpy.float64)                     # BAD: f64
