"""Negative fixture: disciplined key handling — zero findings."""
import jax
import numpy as np


def split_before_reuse(key):
    key, k1 = jax.random.split(key)
    a = jax.random.normal(k1, (4,))
    key, k2 = jax.random.split(key)
    b = jax.random.uniform(k2, (4,))
    return a + b


def self_key_stream(self_like):
    # the repo's _next_key idiom: consume-and-rebind in one statement
    self_like._key, k = jax.random.split(self_like._key)
    return jax.random.normal(k, (2,))


def loop_with_fold_in(key, n):
    total = 0.0
    for i in range(n):
        k = jax.random.fold_in(key, i)        # ok: fold_in derives a
        total += jax.random.normal(k, ())     # fresh per-i stream
    return total


def loop_with_resplit(key, n):
    total = 0.0
    for _ in range(n):
        key, k = jax.random.split(key)        # ok: rebound in the body
        total += jax.random.normal(k, ())
    return total


def branch_exclusive_use(key, flag):
    if flag:
        return jax.random.normal(key, ())
    else:
        return jax.random.uniform(key, ())    # ok: mutually exclusive


def numpy_random_is_not_tracked(loc):
    a = np.random.normal(loc, 1.0)            # numpy: no key argument
    b = np.random.normal(loc, 2.0)
    return a + b


def fresh_keys(seed):
    k1 = jax.random.PRNGKey(seed)
    x = jax.random.normal(k1, ())
    k1 = jax.random.PRNGKey(seed + 1)         # rebound: new stream
    y = jax.random.normal(k1, ())
    return x + y
