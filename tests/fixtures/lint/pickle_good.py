"""Negative fixture: guarded loads — zero findings."""
import pickle


def resumable(path):
    from smartcal_tpu.runtime.atomic import safe_pickle_load
    return safe_pickle_load(path, default=[])


def must_exist(path):
    from smartcal_tpu.runtime.atomic import strict_pickle_load
    return strict_pickle_load(path)


def dumps_is_not_load(obj):
    return pickle.dumps(obj)            # writes are the atomic_* family


def loads_on_in_memory_bytes(data):
    # bytes already in memory: no torn-file window; not this rule's scope
    return pickle.loads(data)
