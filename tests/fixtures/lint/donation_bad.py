"""Positive fixture: reads after donation (every function has one)."""
from functools import partial

import jax
import jax.numpy as jnp

_acc_add = jax.jit(lambda acc, x: acc + x, donate_argnums=(0,))


@partial(jax.jit, donate_argnames=("carry",))
def _step(carry, x):
    return carry + x


def read_after_donating_call(acc, img):
    out = _acc_add(acc, img)
    return out + acc                # BAD: acc's buffer was donated


def read_after_argnames_donation(carry, xs):
    new = _step(carry, xs)
    return new, carry.shape, carry  # BAD: carry read after donation


def loop_without_rebind(acc, imgs):
    for img in imgs:
        out = _acc_add(acc, img)    # BAD: acc re-donated every iteration
    return out


def known_helper_from_other_module(full, new, lane):
    from smartcal_tpu.envs.radio import _lane_splice
    spliced = _lane_splice(full, new, lane)
    total = jnp.sum(full)           # BAD: full was donated to the splice
    return spliced, total
