"""Negative fixture: lock-disciplined regression-radar shared state —
zero findings.  Registered with the same specs as locks_radar_bad.py.
"""
import threading


class BaselineStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._doc = {"entries": {}}
        self._dirty = False

    def record(self, key, entry):
        with self._lock:
            self._doc["entries"][key] = entry   # ok: annotated lock
            self._dirty = True

    def save(self):
        with self._lock:
            self._doc["entries"].update({})
            self._dirty = False
            return dict(self._doc)              # reads unchecked

    def _reload_locked(self):
        self._doc = {"entries": {}}    # ok: *_locked caller-holds-lock


class CalibServer:
    def __init__(self):
        self._lock = threading.Lock()
        self._sentinel_pending = None
        self._sentinel_stats = {"sampled": 0}

    def sample(self, snap):
        with self._lock:
            self._sentinel_pending = snap        # latest-wins handoff
            self._sentinel_stats["sampled"] += 1

    def poll(self):
        with self._lock:
            snap = self._sentinel_pending
            self._sentinel_pending = None
        return snap
