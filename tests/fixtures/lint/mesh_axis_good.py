"""Negative fixture: registry-spelled axes, non-axis short strings, and
a reasoned literal pin."""
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from smartcal_tpu.parallel.mesh import AXIS_DATA, AXIS_LANE, make_mesh


def shard_batch(mesh, x):
    if x.shape[0] % mesh.shape[AXIS_DATA] != 0:     # registry constant
        raise ValueError("bad batch")
    return jax.device_put(x, NamedSharding(mesh, P(AXIS_DATA)))


def reduce_lanes(v):
    return jax.lax.psum(v, AXIS_LANE)


def build(devices):
    return make_mesh((2,), (AXIS_DATA,), devices=devices)


def not_axis_contexts(df):
    mode = "sp"                       # plain string, no axis context
    df.sort_values("dp")              # not an axis call/keyword
    return {"lane": 1, "bp": 2}[mode[:2]], df.shape[0]


def layered_below(v, axis_name="bp"):  # graftlint: disable=mesh-axis-literal -- fixture: module layered below parallel, registry import would cycle
    return jax.lax.psum(v, axis_name)
