"""Positive fixture: unlocked writes to the ISSUE 19 regression-radar
shared state (baseline-store document/dirty flag, the server's
numerics-sentinel snapshot + counters).

The test registers this file with two specs mirroring the shipped
SHARED_FIELD_SPECS rows: class BaselineStore, fields {_doc, _dirty},
lock {_lock}; class CalibServer, fields {_sentinel_pending,
_sentinel_stats}, lock {_lock}.
"""
import threading


class BaselineStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._doc = {"entries": {}}    # ok: __init__ runs pre-sharing
        self._dirty = False

    def record(self, key, entry):
        self._doc[key] = entry              # BAD: store without lock
        self._dirty = True                  # BAD: flag without lock

    def save(self):
        self._doc.update({})                # BAD: mutator, no lock
        self._dirty = False                 # BAD: flag without lock


class CalibServer:
    def __init__(self):
        self._lock = threading.Lock()
        self._sentinel_pending = None
        self._sentinel_stats = {"sampled": 0}

    def sample(self, snap):
        self._sentinel_pending = snap            # BAD: handoff, no lock
        self._sentinel_stats["sampled"] += 1     # BAD: subscript store

    def poll(self):
        snap = self._sentinel_pending
        self._sentinel_pending = None            # BAD: pop without lock
        return snap
