"""Positive fixture: every function here contains rng-key-reuse."""
import jax


def straight_line_reuse(key):
    a = jax.random.normal(key, (4,))          # consumes key
    b = jax.random.uniform(key, (4,))         # BAD: same key again
    return a + b


def reuse_via_split(key):
    k1, k2 = jax.random.split(key)
    x = jax.random.normal(k1, (2,))
    y = jax.random.split(key)                 # BAD: key consumed twice
    return x, y, k2


def attribute_reuse(self_like):
    n = jax.random.normal(self_like._key, (2,))
    m = jax.random.normal(self_like._key, (2,))   # BAD: attr key reuse
    return n + m


def loop_carried_reuse(key, n):
    total = 0.0
    for _ in range(n):
        total += jax.random.normal(key, ())   # BAD: same key each iter
    return total


def reuse_after_branchless_if(key, flag):
    a = jax.random.normal(key, ())
    if flag:
        pass
    b = jax.random.normal(key, ())            # BAD: both paths consumed
    return a + b
