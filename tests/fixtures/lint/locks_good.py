"""Negative fixture: lock-disciplined shared writes — zero findings.

Registered with the same spec as locks_bad.py: class Fleet, fields
{_weights, _version, _queue}, lock {_wlock}.
"""
import threading


class Fleet:
    def __init__(self):
        self._wlock = threading.Lock()
        self._weights = None
        self._version = 0
        self._queue = []

    def set_weights(self, w):
        with self._wlock:
            self._weights = w          # ok: under the annotated lock
            self._version += 1

    def enqueue(self, item):
        with self._wlock:
            self._queue.append(item)

    def _drain_locked(self):
        self._queue.clear()            # ok: *_locked caller-holds-lock
        self._weights = None

    def get_weights(self):
        with self._wlock:
            return self._weights, self._version  # reads unchecked anyway

    def unshared_state(self, n):
        self.counter = n               # ok: not an annotated field
