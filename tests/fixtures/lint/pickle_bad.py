"""Positive fixture: bare pickle.load sites (placed as if in-package)."""
import pickle


def load_state(path):
    with open(path, "rb") as fh:
        return pickle.load(fh)          # BAD: torn file -> opaque EOFError


def load_two(path):
    fh = open(path, "rb")
    a = pickle.load(fh)                 # BAD
    b = pickle.load(fh)                 # BAD
    return a, b
