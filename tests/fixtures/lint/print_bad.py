"""Positive fixture: bare prints (linted as if under smartcal_tpu/)."""


def noisy(x):
    print("value:", x)                  # BAD: bare print in package code
    return x


def also_noisy(x):
    if x:
        print(x)                        # BAD
    return x
