"""Negative fixture: lock-disciplined observability shared state —
zero findings.  Registered with the same specs as locks_obs_bad.py.
"""
import threading


class FlightRecorder:
    def __init__(self):
        self._lock = threading.Lock()
        self._ring = []
        self._flushes = {}
        self._n_flushes = 0

    def record_line(self, line):
        with self._lock:
            self._ring.append(line)    # ok: under the annotated lock

    def flush(self, reason):
        with self._lock:
            self._flushes[reason] = 0.0
            self._n_flushes += 1
            return list(self._ring)    # reads unchecked

    def _drop_locked(self):
        self._ring = []                # ok: *_locked caller-holds-lock


class SloBurnDetector:
    def __init__(self):
        self._lock = threading.Lock()
        self._obs = []
        self._state = {"firing": False}

    def observe(self, latency_s):
        with self._lock:
            self._obs.append(latency_s)

    def evaluate(self):
        with self._lock:
            self._state["firing"] = True


class TimelineMerger:
    def __init__(self):
        self._lock = threading.Lock()
        self._streams = {}
        self._offsets = {}
        self._n_corrupt = 0

    def add_stream(self, proc, events, bad):
        with self._lock:
            self._streams[proc] = events
            self._offsets.update({})
            self._n_corrupt += bad

    def merge(self):
        with self._lock:
            return dict(self._streams)  # reads unchecked
