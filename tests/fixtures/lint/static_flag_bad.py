"""Positive fixture: traced values into python-static flags."""
import jax.numpy as jnp


def solve(x, collect_stats=False, optimized=True):
    return x


def direct_jnp_expression(solver, x, mask):
    return solver(x, collect_stats=jnp.any(mask))      # BAD: traced


def jax_indexing(solver, x, flags):
    return solver(x, optimized=jnp.asarray(flags)[0])  # BAD: traced


def via_local_name(solver, x, mask):
    use_opt = jnp.all(mask > 0)
    return solver(x, fused=use_opt)                    # BAD: jax-derived


def comparison_of_traced(solver, x, r):
    return solver(x, collect_diag=(jnp.max(r) > 1.0))  # BAD: traced bool
