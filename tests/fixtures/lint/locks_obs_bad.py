"""Positive fixture: unlocked writes to the ISSUE 18 observability
shared state (flight-recorder ring, SLO burn windows, timeline-merge
state).

The test registers this file with three specs mirroring the shipped
SHARED_FIELD_SPECS rows: class FlightRecorder, fields {_ring,
_flushes, _n_flushes}, lock {_lock}; class SloBurnDetector, fields
{_obs, _state}, lock {_lock}; class TimelineMerger, fields {_streams,
_offsets, _n_corrupt}, lock {_lock}.
"""
import threading


class FlightRecorder:
    def __init__(self):
        self._lock = threading.Lock()
        self._ring = []                # ok: __init__ runs pre-sharing
        self._flushes = {}
        self._n_flushes = 0

    def record_line(self, line):
        self._ring.append(line)        # BAD: tee without the lock

    def flush(self, reason):
        self._flushes[reason] = 0.0    # BAD: rate-limit store, no lock
        self._n_flushes += 1           # BAD: aug-assign without lock


class SloBurnDetector:
    def __init__(self):
        self._lock = threading.Lock()
        self._obs = []
        self._state = {"firing": False}

    def observe(self, latency_s):
        self._obs.append(latency_s)    # BAD: window grow, no lock

    def evaluate(self):
        self._state["firing"] = True   # BAD: subscript store, no lock


class TimelineMerger:
    def __init__(self):
        self._lock = threading.Lock()
        self._streams = {}
        self._offsets = {}
        self._n_corrupt = 0

    def add_stream(self, proc, events, bad):
        self._streams[proc] = events   # BAD: stream store, no lock
        self._offsets.update({})       # BAD: mutator without the lock
        self._n_corrupt += bad         # BAD: aug-assign without lock
