"""Negative fixture: lock-disciplined serving shared state — zero
findings.  Registered with the same specs as locks_serve_bad.py.
"""
import threading


class CalibServer:
    def __init__(self):
        self._lock = threading.Lock()
        self._programs = {}
        self._circuit_open = False
        self._stats = {"served": 0}

    def warmup(self, progs):
        with self._lock:
            self._programs = progs     # ok: under the annotated lock

    def trip(self):
        with self._lock:
            self._circuit_open = True

    def account(self, n):
        with self._lock:
            self._stats["served"] += n

    def stats(self):
        with self._lock:
            return dict(self._stats)   # reads unchecked

    def _swap_locked(self, progs):
        self._programs = progs         # ok: *_locked caller-holds-lock


class MicroBatcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._accepted = 0
        self._shed = 0
        self._service_est_s = 0.5

    def submit(self):
        with self._lock:
            self._accepted += 1        # ok: under the annotated lock

    def note_service_time(self, s):
        with self._lock:
            self._service_est_s += 0.3 * (s - self._service_est_s)
