"""Fixture: malformed suppressions (no reason / unknown rule)."""
import jax


def reasonless(key):
    a = jax.random.normal(key, (2,))
    b = jax.random.normal(key, (2,))  # graftlint: disable=rng-key-reuse
    return a + b


def unknown_rule(key):  # graftlint: disable=no-such-rule -- typo'd name
    return jax.random.normal(key, (2,))
