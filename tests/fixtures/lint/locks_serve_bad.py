"""Positive fixture: unlocked writes to the SERVING shared state
(the ISSUE 15 latest-executable table / breaker flag / admission
counters).

The test registers this file with two specs mirroring the shipped
SHARED_FIELD_SPECS rows: class CalibServer, fields {_programs,
_circuit_open, _stats}, lock {_lock}; class MicroBatcher, fields
{_accepted, _shed, _service_est_s}, lock {_lock}.
"""
import threading


class CalibServer:
    def __init__(self):
        self._lock = threading.Lock()
        self._programs = {}            # ok: __init__ runs pre-sharing
        self._circuit_open = False
        self._stats = {"served": 0}

    def warmup(self, progs):
        self._programs = progs         # BAD: swap without the lock

    def trip(self):
        self._circuit_open = True      # BAD: breaker write, no lock

    def account(self, n):
        self._stats["served"] += n     # BAD: subscript store, no lock
        self._programs.clear()         # BAD: mutator without the lock


class MicroBatcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._accepted = 0
        self._shed = 0
        self._service_est_s = 0.5

    def submit(self):
        self._accepted += 1            # BAD: aug-assign without the lock

    def note_service_time(self, s):
        self._service_est_s = s        # BAD: EWMA write, no lock
