"""Positive fixture: unlocked writes to annotated shared fields.

The test registers this file with a spec: class Fleet, fields
{_weights, _version, _queue}, lock {_wlock}.
"""
import threading


class Fleet:
    def __init__(self):
        self._wlock = threading.Lock()
        self._weights = None           # ok: __init__ runs pre-sharing
        self._version = 0
        self._queue = []

    def set_weights(self, w):
        self._weights = w              # BAD: no lock held
        self._version += 1             # BAD: no lock held

    def enqueue(self, item):
        self._queue.append(item)       # BAD: mutator without the lock

    def wrong_lock(self, w, other_lock):
        with other_lock:
            self._weights = w          # BAD: not the annotated lock

    def store_slot(self, i, w):
        self._queue[i] = w             # BAD: subscript store, no lock
