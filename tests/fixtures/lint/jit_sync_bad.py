"""Positive fixture: host syncs inside traced functions."""
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def print_inside_jit(x):
    print("step", x)                 # BAD: trace-time only
    return x * 2


@jax.jit
def item_inside_jit(x):
    return float(x.sum().item())     # BAD: .item() device->host sync


@partial(jax.jit, static_argnames=("n",))
def time_inside_jit(x, n):
    t0 = time.time()                 # BAD: frozen at trace time
    return x + t0 + n


@jax.jit
def asarray_on_traced(x):
    host = np.asarray(x)             # BAD: concretizes the tracer
    return jnp.sum(x) + host.size


@jax.jit
def float_on_traced(x):
    return jnp.full((2,), float(x))  # BAD: float() concretizes


@jax.jit
def python_if_on_traced(x):
    if x > 0:                        # BAD: ConcretizationTypeError
        return x
    return -x


def _wrapped(x):
    print("wrapped", x)              # BAD: wrapped below via jax.jit(f)
    return x + 1


apply_wrapped = jax.jit(_wrapped)


@jax.jit
def outer_with_nested(c0, xs):
    def body(c, x):
        if c:                        # BAD: nested fn param is traced too
            return c + x, x
        return c, x

    return jax.lax.scan(body, c0, xs)
