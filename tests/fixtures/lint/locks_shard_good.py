"""Negative fixture: lock-disciplined cross-process fleet fields —
zero findings.  Registered with the same specs as locks_shard_bad.py.
"""
import threading


class Fleet:
    def __init__(self):
        self._wlock = threading.Lock()
        self._shard_qs = []
        self._slot_shard = {}

    def grow(self, q):
        with self._wlock:
            self._shard_qs.append(q)   # ok: under the annotated lock

    def remap(self, slot, shard):
        with self._wlock:
            self._slot_shard[slot] = shard

    def shard_queue(self, slot):
        return self._shard_qs[self._slot_shard[slot]]  # reads unchecked

    def _rebuild_locked(self, n):
        self._shard_qs = [None] * n    # ok: *_locked caller-holds-lock
        self._slot_shard = {}


class ProcessActor:
    def __init__(self):
        self._outbox_lock = threading.Lock()
        self._outbox = None

    def publish(self, blob):
        with self._outbox_lock:
            self._outbox = blob        # ok: under the annotated lock

    def take(self):
        with self._outbox_lock:
            blob, self._outbox = self._outbox, None
        return blob
