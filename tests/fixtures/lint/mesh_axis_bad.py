"""Positive fixture: bare mesh-axis literals in axis contexts (linted
with this file's path in mesh_axis_policied_prefixes)."""
import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def shard_batch(mesh, x):
    if x.shape[0] % mesh.shape["dp"] != 0:          # BAD: shape lookup
        raise ValueError("bad batch")
    return jax.device_put(x, NamedSharding(mesh, P("dp")))   # BAD: P()


def reduce_lanes(v):
    return jax.lax.psum(v, "lane")                  # BAD: collective


def place(buf, mesh, axis="rp"):                    # BAD: param default
    return buf


def build(devices):
    from smartcal_tpu.parallel.mesh import make_mesh

    return make_mesh((2, 2), ("fp", "sp"), devices=devices)  # BAD x2


def lookup(tree, mesh):
    return tree.walk(axis_name="bp")                # BAD: axis keyword
