"""Negative fixture: jit-safe idioms — zero findings."""
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def pure_math(x):
    return jnp.sum(x) * 2.0


@partial(jax.jit, static_argnames=("mode",))
def static_branching(x, mode):
    if mode == "fast":               # ok: mode is static_argnames
        return x * 2
    return x


@jax.jit
def flag_with_literal_default(x, collect_diag=False):
    if collect_diag:                 # ok: literal default => python-static
        return x, jnp.sum(x)
    return x, None


@jax.jit
def shape_branching(x):
    if x.shape[0] > 4:               # ok: shapes are static under trace
        return x[:4]
    return x


@jax.jit
def structure_check(x, y):
    if y is None:                    # ok: `is None` is python-static
        return x
    return x + y


@jax.jit
def debug_print_is_fine(x):
    jax.debug.print("x = {}", x)     # ok: the sanctioned print
    return x


@jax.jit
def lax_cond_instead_of_if(x):
    return jax.lax.cond(x > 0, lambda v: v, lambda v: -v, x)


def host_driver(x):
    t0 = time.time()                 # ok: not traced
    arr = np.asarray(x)
    print("host side", arr.shape, time.time() - t0)
    return float(arr.sum())
