"""Picklable stub-server factories for tests/test_serve_fleet.py.

A replica worker process builds its server from a ``module:callable``
spec (the spawn context inherits ``sys.path``, so this tests-directory
module resolves inside workers exactly like ``fleet_proc_worker`` does
for the actor fleet).  :class:`StubServer` duck-types the CalibServer
surface the fleet worker drives — ``warmup`` / ``start`` / ``submit`` /
``stop`` / ``stats`` / ``batcher`` / ``lanes`` — without jax or a radio
backend, so the process-level router tests (spawn, dispatch round-trip,
kill, restart, requeue) run in seconds.  ``sigma_res`` encodes the
job's ``k`` (plus a per-replica ``tag``) so the parent can verify which
payload came back from where.
"""

import os
import queue
import threading
import time

from smartcal_tpu.serve.router import JobResult, ShedError


class _StubBatcher:
    def __init__(self, q, service_s):
        self._q = q
        self._service_s = float(service_s)

    def depth(self):
        return self._q.qsize()

    def service_estimate_s(self):
        return self._service_s


class StubServer:
    """Single-worker FIFO 'server'.  ``die_at_job=N`` calls
    ``os._exit`` mid-service of its N-th job (the future never
    resolves — the parent's pending-table reclaim is what recovers
    it); ``shed_after=N`` sheds every submit past the N-th with a
    structured ``queue_full``."""

    def __init__(self, lanes=2, service_s=0.02, max_queue=32,
                 die_at_job=None, shed_after=None, tag=0.0):
        self.lanes = int(lanes)
        self.service_s = float(service_s)
        self.die_at_job = die_at_job
        self.shed_after = shed_after
        self.tag = float(tag)
        self._q = queue.Queue(maxsize=max(1, int(max_queue)))
        self.batcher = _StubBatcher(self._q, service_s)
        self._accepted = 0
        self._served = 0
        self._stop = threading.Event()
        self._worker = None

    def warmup(self, seed=0):
        return {"wall_s": 0.001, "sources": {"solve": "stub"},
                "export_cache_hit": 0, "export_cache_miss": 0,
                "jax_compile_events": 0.0}

    def start(self):
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def submit(self, job):
        if self.shed_after is not None \
                and self._accepted >= self.shed_after:
            raise ShedError("queue_full", depth=self._q.qsize())
        try:
            self._q.put_nowait(job)
        except queue.Full:
            raise ShedError("queue_full",
                            depth=self._q.qsize()) from None
        self._accepted += 1
        return job.future

    def _loop(self):
        while not self._stop.is_set():
            try:
                job = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            n = self._served + 1
            if self.die_at_job is not None and n == self.die_at_job:
                os._exit(3)             # mid-service death: future stranded
            time.sleep(self.service_s)
            self._served = n
            total = time.monotonic() - job.t_submit
            job.future.set_result(JobResult(
                job_id=job.job_id, lane=0, batch_id=n,
                sigma_res=float(job.k) + self.tag,
                sigma_data_img=0.0, sigma_res_img=0.0, img_std=0.0,
                degraded=False, queue_wait_s=0.0,
                service_s=self.service_s, total_s=round(total, 6),
                deadline_miss=(job.deadline_s is not None
                               and total > job.deadline_s)))

    def stop(self):
        self._stop.set()
        if self._worker is not None:
            self._worker.join(timeout=2.0)

    def stats(self):
        return {"batches": self._served, "served": self._served,
                "degraded": 0, "failed": 0, "deadline_miss": 0,
                "service_est_s": self.service_s, "circuit_open": False}


def make_stub_server(**kw):
    return StubServer(**kw)
