"""Picklable worker factories for tests/test_fleet_process.py.

A spawned actor worker rebuilds its work function from a
``"module:callable"`` spec (:func:`smartcal_tpu.runtime.ipc
.resolve_factory`) because a closure defined inside a test function
cannot cross the process boundary.  Kept stdlib-only so a worker spawn
never pays a jax import for the factory itself.
"""

import os
import time


def make_echo(scale=1, fail_actor=None, fail_at=None, sleep_s=0.0):
    """Echo work function: returns a dict naming the (actor, iteration,
    weights) it saw plus the worker's simulated-host assignment;
    optionally raises at one (actor, iteration) to exercise the
    worker-death -> restart -> poison-skip path."""

    def work_fn(actor_id, iteration, weights):
        if sleep_s:
            time.sleep(sleep_s)
        if fail_at is not None and int(iteration) == int(fail_at) and (
                fail_actor is None or int(actor_id) == int(fail_actor)):
            raise RuntimeError(f"echo poison at iteration {iteration}")
        w = weights.get("w") if isinstance(weights, dict) else weights
        return {"actor": actor_id, "iteration": iteration, "w": w,
                "scaled": None if w is None else w * scale,
                "sim_host": os.environ.get("SMARTCAL_SIM_HOST", "")}

    return work_fn
