"""Unit tests for the chip-capture artifact validation (tools/chip_checks.py).

The round's headline numbers are promoted by these predicates inside the
unattended capture loop (tools/capture_round.sh + capture_r4_forever.sh),
so a validation bug silently loses or mislabels a chip window.  Pure
host-side JSON logic — no JAX, runs in milliseconds.
"""

import json
import os

import pytest

from conftest import load_tool_module

chip_checks = load_tool_module("chip_checks")


@pytest.fixture()
def results_dir(tmp_path, monkeypatch):
    d = tmp_path / "results"
    d.mkdir()
    monkeypatch.setattr(chip_checks, "RESULTS", str(d))
    return d


def _write(path, doc):
    with open(path, "w") as fh:
        json.dump(doc, fh)


# -- per_e2e ---------------------------------------------------------------

def test_per_e2e_requires_tpu_label_and_e2e_rows(results_dir):
    assert not chip_checks.per_e2e_done()          # no file
    _write(results_dir / "per_bench.json", {"measurements": [
        {"label": "cpu_123", "e2e_rows": [{"stage": "e2e_train_step"}]}]})
    assert not chip_checks.per_e2e_done()          # wrong platform
    _write(results_dir / "per_bench.json", {"measurements": [
        {"label": "round2_tpu_standalone", "e2e_rows": []}]})
    assert not chip_checks.per_e2e_done()          # standalone only
    _write(results_dir / "per_bench.json", {"measurements": [
        {"label": "round4_axon_e2e",
         "e2e_rows": [{"stage": "e2e_train_step", "us": 123}]}]})
    assert chip_checks.per_e2e_done()


# -- host_seg --------------------------------------------------------------

def test_host_seg_requires_tpu_steady_state(results_dir):
    assert not chip_checks.host_seg_done()
    _write(results_dir / "host_seg_bench.json", [
        {"platform": "cpu", "host_segmented": {"steady_s": 417.4}}])
    assert not chip_checks.host_seg_done()         # CPU measurement only
    _write(results_dir / "host_seg_bench.json", [
        {"platform": "cpu", "host_segmented": {"steady_s": 417.4}},
        {"platform": "axon", "host_segmented": {"steady_s": None}}])
    assert not chip_checks.host_seg_done()         # chip case incomplete
    _write(results_dir / "host_seg_bench.json", [
        {"platform": "axon", "host_segmented": {"steady_s": 12.3}}])
    assert chip_checks.host_seg_done()
    # a single dict (not a list) is accepted too
    _write(results_dir / "host_seg_bench.json",
           {"platform": "tpu", "host_segmented": {"steady_s": 9.9}})
    assert chip_checks.host_seg_done()


# -- primary ---------------------------------------------------------------

GOOD_PRIMARY = {"metric": "enet_sac_env_steps_per_sec", "value": 120.0,
                "unit": "env-steps/sec/chip", "vs_baseline": 28.8,
                "dispatch": "episode_block(20)", "host_load_avg_1m": 0.3}


def test_primary_rejects_cpu_fallback_and_contention(results_dir,
                                                     tmp_path):
    tmpfile = str(tmp_path / "out.json")
    _write(tmpfile, dict(GOOD_PRIMARY, platform="cpu (fallback)"))
    assert not chip_checks.primary_done(tmpfile, "r9")
    _write(tmpfile, dict(GOOD_PRIMARY, host_load_avg_1m=1.5))
    assert not chip_checks.primary_done(tmpfile, "r9")
    _write(tmpfile, dict(GOOD_PRIMARY, metric="something_else"))
    assert not chip_checks.primary_done(tmpfile, "r9")
    assert not os.path.exists(results_dir / "bench_primary_r9.json")
    assert not os.path.exists(results_dir / "latest_chip_capture.json")


def test_primary_promotes_and_maintains_latest_pointer(results_dir,
                                                       tmp_path):
    tmpfile = str(tmp_path / "out.json")
    _write(tmpfile, GOOD_PRIMARY)
    assert chip_checks.primary_done(tmpfile, "r9")
    promoted = json.load(open(results_dir / "bench_primary_r9.json"))
    assert promoted["value"] == 120.0
    latest = json.load(open(results_dir / "latest_chip_capture.json"))
    assert latest == promoted
    # idempotent re-check: final artifact exists -> done without tmpfile
    os.remove(tmpfile)
    assert chip_checks.primary_done(tmpfile, "r9")
    # the last line of a multi-line tmpfile is the JSON payload
    with open(tmpfile, "w") as fh:
        fh.write("some warning line\n")
        fh.write(json.dumps(dict(GOOD_PRIMARY, value=140.0)) + "\n")
    assert chip_checks.primary_done(tmpfile, "r10")
    assert json.load(open(results_dir /
                          "bench_primary_r10.json"))["value"] == 140.0


# -- extras ----------------------------------------------------------------

def test_extras_requires_tpu_epblock_value(results_dir, tmp_path):
    tmpfile = str(tmp_path / "extras.json")
    base = {"metric": "enet_sac_env_steps_per_sec", "value": 100.0}
    _write(tmpfile, dict(base, platform="cpu (fallback)", extra=[
        {"metric": "enet_sac_env_steps_per_sec_epblock", "value": 70.0}]))
    assert not chip_checks.extras_done(tmpfile, "r9")
    _write(tmpfile, dict(base, extra=[
        {"metric": "enet_sac_env_steps_per_sec_epblock",
         "skipped": "extras time budget spent"}]))
    assert not chip_checks.extras_done(tmpfile, "r9")   # no value
    _write(tmpfile, dict(base, extra=[
        {"metric": "enet_sac_env_steps_per_sec_epblock", "value": 150.0}]))
    assert chip_checks.extras_done(tmpfile, "r9")
    assert json.load(open(results_dir / "bench_extras_r9.json"))


# -- CLI -------------------------------------------------------------------

def test_cli_exit_codes(results_dir, tmp_path):
    assert chip_checks.main(["per_e2e"]) == 1
    assert chip_checks.main([]) == 2
    assert chip_checks.main(["nonsense"]) == 2
    tmpfile = str(tmp_path / "p.json")
    _write(tmpfile, GOOD_PRIMARY)
    assert chip_checks.main(["primary", tmpfile, "r8"]) == 0


def test_primary_probe_does_not_stomp_newer_pointer(results_dir, tmp_path):
    """A doneness re-probe of an OLDER round must not overwrite a newer
    round's latest_chip_capture.json pointer (ADVICE r4 item 3: a
    still-running old capture loop probes its artifact every pass)."""
    tmpfile = str(tmp_path / "out.json")
    _write(tmpfile, GOOD_PRIMARY)
    assert chip_checks.primary_done(tmpfile, "r9")
    _write(tmpfile, dict(GOOD_PRIMARY, value=150.0))
    assert chip_checks.primary_done(tmpfile, "r10")
    assert json.load(open(results_dir
                          / "latest_chip_capture.json"))["value"] == 150.0
    # the r9 loop keeps probing its (existing) artifact: pointer untouched
    assert chip_checks.primary_done(str(tmp_path / "gone.json"), "r9")
    assert json.load(open(results_dir
                          / "latest_chip_capture.json"))["value"] == 150.0


def test_solve_eval_requires_tpu_platform(results_dir):
    """solve_eval_done rejects (and deletes) a CPU-fallback artifact so
    the capture loop retries on chip, and accepts a TPU payload."""
    path = results_dir / "solve_eval_tpu.json"
    assert not chip_checks.solve_eval_done()
    with open(path, "w") as fh:
        json.dump({"platform": "cpu", "variants": {"onehot": {}}}, fh)
    assert not chip_checks.solve_eval_done()
    assert not path.exists()          # fallback artifact removed
    with open(path, "w") as fh:
        json.dump({"platform": "axon", "variants": {"onehot": {}}}, fh)
    assert chip_checks.solve_eval_done()
    assert path.exists()
