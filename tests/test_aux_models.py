"""Tests for the aux models (transformer, regressor, TSK) and the
supervised pipelines (reference demixing_rl/makedata.py,
train_regressor.py, train_tsk.py, demixing/train_model.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smartcal_tpu.models.regressor import RegressorNet, TrainingBuffer
from smartcal_tpu.models.transformer import TransformerEncoder, XYBuffer
from smartcal_tpu.models.tsk import (center_difference_loss, sigma_loss,
                                     train_tsk, tsk_forward, tsk_init)


class TestTransformer:
    def test_forward_shapes_and_range(self):
        K = 4
        model = TransformerEncoder(num_layers=1, input_dim=40,
                                   model_dim=8 * K, num_classes=K - 1,
                                   num_heads=K)
        x = jnp.asarray(np.random.default_rng(0).standard_normal(
            (5, 40)).astype(np.float32))
        params = model.init(jax.random.PRNGKey(0), x)["params"]
        y = model.apply({"params": params}, x)
        assert y.shape == (5, K - 1)
        assert np.all(np.asarray(y) >= 0) and np.all(np.asarray(y) <= 1)

    def test_learns_trivial_rule(self):
        """BCE training must fit y = 1[x_0 > 0] on toy data."""
        from smartcal_tpu.train.supervised import train_transformer
        rng = np.random.default_rng(1)
        K = 3
        buf = XYBuffer(64, (30,), (K - 1,))
        for _ in range(64):
            x = rng.standard_normal(30).astype(np.float32)
            y = np.asarray([x[0] > 0, x[1] > 0], np.float32)
            buf.store(x, y)
        params, info = train_transformer(buf, K=K, model_dim=8, epochs=400,
                                         batch_size=16, dropout=0.0)
        model = info["model"]
        pred = np.asarray(model.apply({"params": params},
                                      jnp.asarray(buf.x)))
        acc = np.mean((pred > 0.5) == (buf.y > 0.5))
        assert acc > 0.8

    def test_xybuffer_resize(self):
        buf = XYBuffer(4, (3,), (2,))
        for i in range(3):
            buf.store(np.full(3, i), np.full(2, i))
        buf.resize(8)
        assert buf.mem_size == 8
        np.testing.assert_array_equal(buf.x[2], np.full(3, 2))


class TestRegressor:
    def test_training_reduces_test_mse(self):
        from smartcal_tpu.train.supervised import train_regressor
        rng = np.random.default_rng(2)
        buf = TrainingBuffer(128, 6, 2)
        W = rng.standard_normal((6, 2)) * 0.3
        for _ in range(128):
            x = rng.standard_normal(6).astype(np.float32)
            buf.store(x, np.tanh(x @ W))
        params, hist = train_regressor(buf, n_iter=500, batch_size=32)
        assert hist["test_mse"] < 0.1
        assert hist["losses"][-1] < hist["losses"][0]

    def test_buffer_roundtrip(self, tmp_path):
        buf = TrainingBuffer(8, 3, 1)
        buf.store([1, 2, 3], [4])
        p = str(tmp_path / "buf.pkl")
        buf.save_checkpoint(p)
        buf2 = TrainingBuffer(8, 3, 1)
        buf2.load_checkpoint(p)
        np.testing.assert_array_equal(buf2.x[0], [1, 2, 3])
        assert buf2.mem_cntr == 1


class TestTSK:
    def test_forward_shape_and_range(self):
        params = tsk_init(jax.random.PRNGKey(0), 5, 2, n_rule=3)
        x = jnp.asarray(np.random.default_rng(0).standard_normal(
            (7, 5)).astype(np.float32))
        y = tsk_forward(params, x)
        assert y.shape == (7, 2)
        assert np.all(np.abs(np.asarray(y)) <= 1.0)

    def test_regularizers_positive(self):
        params = tsk_init(jax.random.PRNGKey(1), 4, 1, n_rule=3)
        assert float(center_difference_loss(params)) > 0
        assert float(sigma_loss(params)) == pytest.approx(1.0)

    def test_training_fits_linear_map(self):
        rng = np.random.default_rng(3)
        x = rng.standard_normal((200, 4)).astype(np.float32)
        W = rng.standard_normal((4, 2)) * 0.4
        y = np.tanh(x @ W).astype(np.float32)
        out = train_tsk(jax.random.PRNGKey(0), x[:160], y[:160], n_rule=3,
                        n_iter=800, batch_size=64, x_test=x[160:],
                        y_test=y[160:])
        assert out["test_mse"] < 0.2


@pytest.mark.slow
def test_make_hint_dataset_smoke():
    from smartcal_tpu.envs.radio import RadioBackend
    from smartcal_tpu.train.supervised import make_hint_dataset
    be = RadioBackend(n_stations=6, n_freqs=2, n_times=4, tdelta=2,
                      admm_iters=30, lbfgs_iters=3, init_iters=5, npix=32)
    buf = make_hint_dataset(n_iter=2, K=3, backend=be, seed=1)
    x, y = buf.filled()
    assert x.shape == (2, 11)
    assert y.shape == (2, 2)
    assert np.all(np.isfinite(x)) and np.all(np.isfinite(y))


def test_generate_training_data_smoke():
    from smartcal_tpu.envs.radio import RadioBackend
    from smartcal_tpu.train.supervised import generate_training_data
    be = RadioBackend(n_stations=6, n_freqs=2, n_times=4, tdelta=2,
                      admm_iters=2, lbfgs_iters=3, init_iters=5, npix=16)
    x, y = generate_training_data(jax.random.PRNGKey(5), be, K=3)
    assert x.shape == (3 * (16 * 16 + 8),)
    assert y.shape == (2,)
    assert set(np.unique(y)).issubset({0.0, 1.0})
    assert np.all(np.isfinite(x))
