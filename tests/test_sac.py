"""Tests for replay buffers and the SAC agent."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smartcal_tpu.rl import replay as rp
from smartcal_tpu.rl import sac
from smartcal_tpu.rl.networks import MLPActor, MLPCritic, gaussian_sample


def _spec():
    return rp.transition_spec(obs_dim=6, n_actions=2)


def _tr(i, obs_dim=6):
    return {"state": np.full(obs_dim, i, np.float32),
            "new_state": np.full(obs_dim, i + 0.5, np.float32),
            "action": np.array([i, -i], np.float32),
            "reward": np.float32(i),
            "done": False,
            "hint": np.zeros(2, np.float32)}


def test_uniform_ring_and_sample():
    buf = rp.replay_init(8, _spec())
    for i in range(5):
        buf = rp.replay_add(buf, _tr(i), priority=jnp.asarray(1.0))
    assert int(buf.cntr) == 5
    batch, idx = rp.replay_sample_uniform(buf, jax.random.PRNGKey(0), 4)
    # indices must come from filled region and be distinct
    idxs = np.asarray(idx)
    assert np.all(idxs < 5)
    assert len(set(idxs.tolist())) == 4
    # ring wrap: adding 6 more overwrites oldest
    for i in range(5, 11):
        buf = rp.replay_add(buf, _tr(i), priority=jnp.asarray(1.0))
    assert int(buf.cntr) == 11
    assert float(buf.data["state"][0][0]) == 8.0  # 8 % 8 == 0 slot


def test_per_priorities_and_weights():
    buf = rp.replay_init(8, _spec())
    # empty buffer, no error: priority = clip value (reference :239-240)
    buf = rp.replay_add(buf, _tr(0))
    assert float(buf.priority[0]) == 100.0
    buf = rp.replay_add(buf, _tr(1), error=jnp.asarray(0.5))
    want = (0.5 + rp.PER_EPSILON) ** rp.PER_ALPHA
    np.testing.assert_allclose(float(buf.priority[1]), want, rtol=1e-5)

    batch, idx, w, buf2 = rp.replay_sample_per(buf, jax.random.PRNGKey(1), 4)
    assert np.all(np.asarray(idx) < 2)  # only filled slots get sampled
    assert np.max(np.asarray(w)) <= 1.0 + 1e-6
    assert float(buf2.beta) > float(buf.beta)

    buf3 = rp.replay_update_priorities(buf2, jnp.asarray([0]),
                                       jnp.asarray([2.0]))
    want = (2.0 + rp.PER_EPSILON) ** rp.PER_ALPHA
    np.testing.assert_allclose(float(buf3.priority[0]), want, rtol=1e-5)


def test_per_distribution_matches_priorities():
    """Stratified cumsum sampling draws high-priority slots more often."""
    buf = rp.replay_init(8, _spec())
    pr = [1.0, 1.0, 1.0, 10.0]
    for i, p in enumerate(pr):
        buf = rp.replay_add(buf, _tr(i), priority=jnp.asarray(p))
    counts = np.zeros(8)
    for s in range(50):
        _, idx, _, _ = rp.replay_sample_per(buf, jax.random.PRNGKey(s), 4)
        for j in np.asarray(idx):
            counts[j] += 1
    assert counts[3] > counts[0] * 2
    assert counts[4:].sum() == 0


def test_per_pmax_fallback_zero_and_nonzero():
    """replay.py:103-105: priority-less store falls back to error_clip on
    an all-zero priority vector (untouched buffer) and to the running max
    afterwards — the repair that keeps the first stores sampleable."""
    buf = rp.replay_init(8, _spec())
    assert float(jnp.max(buf.priority)) == 0.0
    buf = rp.replay_add(buf, _tr(0), error_clip=7.0)
    assert float(buf.priority[0]) == 7.0          # pmax==0 -> clip
    buf = rp.replay_update_priorities(buf, jnp.asarray([0]),
                                      jnp.asarray([0.5]))
    pmax = float(jnp.max(buf.priority))
    buf = rp.replay_add(buf, _tr(1), error_clip=7.0)
    np.testing.assert_allclose(float(buf.priority[1]), pmax, rtol=1e-6)

    # batch variant, same two branches
    batch = {k: np.stack([v, v]) for k, v in _tr(2).items()}
    b2 = rp.replay_add_batch(rp.replay_init(8, _spec()), batch,
                             error_clip=5.0)
    np.testing.assert_allclose(np.asarray(b2.priority[:2]), 5.0)
    b3 = rp.replay_add_batch(b2, batch)
    np.testing.assert_allclose(np.asarray(b3.priority[2:4]),
                               float(jnp.max(b2.priority)), rtol=1e-6)


def test_per_error_clip_saturation():
    """The deliberate store/update clip asymmetry at saturation: store
    clips the POWER min((|e|+eps)^a, clip); batch_update clips the ERROR
    then exponentiates, min(|e|+eps, clip)^a (enet_sac.py:237/314)."""
    huge = jnp.asarray(1e12)
    stored = float(rp.priority_from_errors(huge, error_clip=100.0))
    assert stored == 100.0
    buf = rp.replay_init(4, _spec())
    buf = rp.replay_add(buf, _tr(0), error=huge, error_clip=100.0)
    assert float(buf.priority[0]) == 100.0
    buf = rp.replay_update_priorities(buf, jnp.asarray([0]), huge[None],
                                      error_clip=100.0)
    np.testing.assert_allclose(float(buf.priority[0]),
                               100.0 ** rp.PER_ALPHA, rtol=1e-5)
    # below the clip both rules agree (eps + exponent, no saturation)
    buf = rp.replay_add(buf, _tr(1), error=jnp.asarray(0.25))
    np.testing.assert_allclose(float(buf.priority[1]),
                               (0.25 + rp.PER_EPSILON) ** rp.PER_ALPHA,
                               rtol=1e-5)


def test_per_beta_annealing_monotone_and_capped():
    """Beta anneals by PER_BETA_INCREMENT per PER sample, never
    decreases, and saturates at exactly 1.0."""
    buf = rp.replay_init(8, _spec())
    for i in range(4):
        buf = rp.replay_add(buf, _tr(i), error=jnp.asarray(float(i)))
    betas = [float(buf.beta)]
    for s in range(5):
        _, _, _, buf = rp.replay_sample_per(buf, jax.random.PRNGKey(s), 2)
        betas.append(float(buf.beta))
    diffs = np.diff(betas)
    assert np.all(diffs > 0)
    np.testing.assert_allclose(diffs, rp.PER_BETA_INCREMENT, rtol=1e-3)
    # force the cap: one increment away from 1 -> exactly 1, then stays
    buf = buf._replace(beta=jnp.asarray(1.0 - rp.PER_BETA_INCREMENT / 2,
                                        jnp.float32))
    _, _, _, buf = rp.replay_sample_per(buf, jax.random.PRNGKey(99), 2)
    assert float(buf.beta) == 1.0
    _, _, _, buf = rp.replay_sample_per(buf, jax.random.PRNGKey(100), 2)
    assert float(buf.beta) == 1.0


def test_gaussian_sample_logprob():
    mu = jnp.zeros((1, 2))
    logsigma = jnp.zeros((1, 2))
    a, lp = gaussian_sample(mu, logsigma, jax.random.PRNGKey(0))
    assert a.shape == (1, 2)
    assert np.all(np.abs(np.asarray(a)) <= 1.0)
    # analytic check: lp = sum N(z;0,1) logpdf - log(1 - a^2 + eps)
    z = np.arctanh(np.asarray(a))
    want = (-0.5 * z ** 2 - 0.5 * np.log(2 * np.pi)
            - np.log(1 - np.asarray(a) ** 2 + 1e-6)).sum()
    np.testing.assert_allclose(float(lp[0, 0]), want, rtol=1e-3)


def test_sac_learn_updates_and_targets():
    cfg = sac.SACConfig(obs_dim=6, n_actions=2, batch_size=4, mem_size=16,
                        reward_scale=1.0)
    st = sac.sac_init(jax.random.PRNGKey(0), cfg)
    buf = rp.replay_init(cfg.mem_size, _spec())

    # below batch size: learn must be a no-op
    st2, buf2, m = sac.learn(cfg, st, buf, jax.random.PRNGKey(1))
    assert int(st2.learn_counter) == 0

    rng = np.random.default_rng(0)
    for i in range(8):
        tr = _tr(i)
        tr["state"] = rng.normal(size=6).astype(np.float32)
        tr["new_state"] = rng.normal(size=6).astype(np.float32)
        buf = rp.replay_add(buf, tr, priority=jnp.asarray(1.0))

    st3, buf3, m = sac.learn(cfg, st, buf, jax.random.PRNGKey(2))
    assert int(st3.learn_counter) == 1
    assert np.isfinite(float(m["critic_loss"]))
    # parameters changed
    a0 = jax.flatten_util.ravel_pytree(st.actor_params)[0]
    a1 = jax.flatten_util.ravel_pytree(st3.actor_params)[0]
    assert float(jnp.linalg.norm(a1 - a0)) > 0
    # target nets moved toward critics by tau
    t0 = jax.flatten_util.ravel_pytree(st.t1_params)[0]
    t1 = jax.flatten_util.ravel_pytree(st3.t1_params)[0]
    c1 = jax.flatten_util.ravel_pytree(st3.c1_params)[0]
    np.testing.assert_allclose(np.asarray(t1),
                               np.asarray(cfg.tau * c1 + (1 - cfg.tau) * t0),
                               rtol=1e-4, atol=1e-6)


def test_sac_hint_dual_update():
    cfg = sac.SACConfig(obs_dim=6, n_actions=2, batch_size=4, mem_size=16,
                        use_hint=True, hint_threshold=0.0)
    st = sac.sac_init(jax.random.PRNGKey(0), cfg)
    buf = rp.replay_init(cfg.mem_size, _spec())
    rng = np.random.default_rng(1)
    for i in range(8):
        tr = _tr(i)
        tr["state"] = rng.normal(size=6).astype(np.float32)
        tr["hint"] = np.array([0.9, -0.9], np.float32)
        buf = rp.replay_add(buf, tr, priority=jnp.asarray(1.0))
    # learn_counter 0 -> dual update fires on first call (counter % 10 == 0)
    st2, _, m = sac.learn(cfg, st, buf, jax.random.PRNGKey(3))
    assert float(st2.rho) > 0.0


def test_sac_learned_alpha_reference_rule():
    """alpha_rule='reference' (the default) is the reference's clamped SGD
    directly on alpha (enet_sac.py:613):
    alpha = max(0, alpha + alpha_lr*mean(target_entropy + logpi)),
    initialized from the alpha argument (enet_sac.py:500), fired every 10
    learn calls."""
    cfg = sac.SACConfig(obs_dim=6, n_actions=2, batch_size=4, mem_size=16,
                        learn_alpha=True, alpha=0.5, alpha_lr=0.1)
    st = sac.sac_init(jax.random.PRNGKey(0), cfg)
    assert float(st.alpha) == 0.5            # init from the alpha argument
    buf = rp.replay_init(cfg.mem_size, _spec())
    rng = np.random.default_rng(2)
    for i in range(8):
        tr = _tr(i)
        tr["state"] = rng.normal(size=6).astype(np.float32)
        buf = rp.replay_add(buf, tr, priority=jnp.asarray(1.0))
    # counter 0 -> temperature update fires on the first learn call
    st2, buf, m = sac.learn(cfg, st, buf, jax.random.PRNGKey(3))
    assert float(st2.alpha) != float(st.alpha)
    assert float(st2.alpha) >= 0.0           # clamped at zero, not positive
    # counters 1..9 -> alpha frozen between the every-10 updates
    st3, buf, _ = sac.learn(cfg, st2, buf, jax.random.PRNGKey(4))
    assert float(st3.alpha) == float(st2.alpha)
    # the clamp: a huge lr drives the update negative -> alpha == 0 exactly
    cfg_clamp = dataclasses.replace(cfg, alpha_lr=1e6)
    stc, _, _ = sac.learn(cfg_clamp, st, buf, jax.random.PRNGKey(3))
    assert float(stc.alpha) >= 0.0


def test_sac_learned_alpha_sac_v2():
    """alpha_rule='sac_v2' is the deliberate DEVIATION from the reference:
    Adam on log_alpha (alpha = exp(log_alpha), always positive), starting
    at log_alpha = 0 (alpha = 1). The reference has no log_alpha/Adam —
    this is the Haarnoja et al. v2 scheme kept for its positivity."""
    cfg = sac.SACConfig(obs_dim=6, n_actions=2, batch_size=4, mem_size=16,
                        learn_alpha=True, alpha=0.03, alpha_lr=0.1,
                        alpha_rule="sac_v2")
    st = sac.sac_init(jax.random.PRNGKey(0), cfg)
    assert float(st.alpha) == 1.0            # exp(0) init
    assert float(st.log_alpha) == 0.0
    buf = rp.replay_init(cfg.mem_size, _spec())
    rng = np.random.default_rng(2)
    for i in range(8):
        tr = _tr(i)
        tr["state"] = rng.normal(size=6).astype(np.float32)
        buf = rp.replay_add(buf, tr, priority=jnp.asarray(1.0))
    # counter 0 -> temperature update fires on the first learn call
    st2, buf, m = sac.learn(cfg, st, buf, jax.random.PRNGKey(3))
    assert float(st2.alpha) != float(st.alpha)
    assert float(st2.alpha) > 0.0
    np.testing.assert_allclose(float(st2.alpha),
                               np.exp(float(st2.log_alpha)), rtol=1e-6)
    # first Adam step moves log_alpha by ~lr in the gradient-sign direction
    assert abs(float(st2.log_alpha)) == pytest.approx(cfg.alpha_lr, rel=0.2)
    # counters 1..9 -> alpha frozen between the every-10 updates
    st3, buf, _ = sac.learn(cfg, st2, buf, jax.random.PRNGKey(4))
    assert float(st3.alpha) == float(st2.alpha)
    # ten learn calls later the update fires again; alpha stays positive
    for k in range(8):
        st3, buf, _ = sac.learn(cfg, st3, buf, jax.random.PRNGKey(5 + k))
    st4, buf, _ = sac.learn(cfg, st3, buf, jax.random.PRNGKey(20))
    assert float(st4.alpha) > 0.0
    assert float(st4.log_alpha) != float(st3.log_alpha)
    assert int(st4.learn_counter) == 11


def test_sac_prioritized_path():
    cfg = sac.SACConfig(obs_dim=6, n_actions=2, batch_size=4, mem_size=16,
                        prioritized=True)
    st = sac.sac_init(jax.random.PRNGKey(0), cfg)
    buf = rp.replay_init(cfg.mem_size, _spec())
    for i in range(8):
        buf = rp.replay_add(buf, _tr(i))
    st2, buf2, m = sac.learn(cfg, st, buf, jax.random.PRNGKey(4))
    # priorities of the sampled slots were refreshed away from the initial 100
    assert int(st2.learn_counter) == 1
    changed = np.sum(np.asarray(buf2.priority) != np.asarray(buf.priority))
    assert changed >= 1


def test_agent_wrapper_roundtrip(tmp_path):
    cfg = sac.SACConfig(obs_dim=6, n_actions=2, batch_size=4, mem_size=16)
    agent = sac.SACAgent(cfg, seed=0)
    obs = np.ones(6, np.float32)
    a = agent.choose_action(obs)
    assert a.shape == (2,)
    for i in range(6):
        agent.store_transition(obs, a, 0.5, obs, False, np.zeros(2))
    agent.learn()
    assert int(agent.state.learn_counter) == 1
    import os
    old = os.getcwd()
    os.chdir(tmp_path)
    try:
        agent.save_models()
        agent2 = sac.SACAgent(cfg, seed=1)
        agent2.load_models()
        p1 = jax.flatten_util.ravel_pytree(agent.state.actor_params)[0]
        p2 = jax.flatten_util.ravel_pytree(agent2.state.actor_params)[0]
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p2))
    finally:
        os.chdir(old)


def test_agent_native_per_backend(tmp_path):
    """replay_backend='native' routes the agent through the host C++ sum
    tree + learn_from_batch and stays checkpoint-compatible (VERDICT r2
    item 6: both PER designs selectable; default follows the e2e winner)."""
    from smartcal_tpu import native

    if native.lib() is None:
        pytest.skip("no native library (g++ unavailable)")
    cfg = sac.SACConfig(obs_dim=6, n_actions=2, batch_size=4, mem_size=16,
                        prioritized=True, replay_backend="native")
    agent = sac.SACAgent(cfg, seed=0)
    obs = np.ones(6, np.float32)
    agent.learn()                       # not ready -> no-op, no crash
    for i in range(6):
        agent.store_transition(obs * i, np.zeros(2, np.float32), 0.5,
                               obs, False, np.zeros(2, np.float32))
    agent.learn()
    assert int(agent.state.learn_counter) == 1
    assert np.isfinite(float(agent.last_metrics["critic_loss"]))
    # TD refresh reached the tree: priorities moved off the init value
    lv = agent.buffer.tree.leaves()[:6]
    assert np.any(lv != lv[0]) or np.all(lv < 100.0)
    import os
    old = os.getcwd()
    os.chdir(tmp_path)
    try:
        agent.save_models()
        agent2 = sac.SACAgent(cfg, seed=1)
        agent2.load_models()
        assert agent2.buffer.cntr == agent.buffer.cntr
        p1 = jax.flatten_util.ravel_pytree(agent.state.actor_params)[0]
        p2 = jax.flatten_util.ravel_pytree(agent2.state.actor_params)[0]
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p2))
    finally:
        os.chdir(old)


def test_old_checkpoint_migrates_learned_alpha(tmp_path):
    """A pre-log_alpha SACState pickle (log_alpha/alpha_opt = None) loads
    and resumes learn_alpha=True training instead of crashing in optax."""
    import pickle

    cfg = sac.SACConfig(obs_dim=6, n_actions=2, batch_size=4, mem_size=16,
                        learn_alpha=True, alpha=0.5)
    agent = sac.SACAgent(cfg, seed=0, name_prefix=str(tmp_path) + "/old_")
    # simulate the old checkpoint: strip the temperature fields
    old = jax.device_get(agent.state)._replace(log_alpha=None,
                                               alpha_opt=None,
                                               alpha=jnp.asarray(0.5))
    with open(str(tmp_path) + "/old_sac_state.pkl", "wb") as f:
        pickle.dump(old, f)
    rp.save_replay(agent.buffer, str(tmp_path) + "/old_replaymem_sac.pkl")

    agent.load_models()
    np.testing.assert_allclose(float(agent.state.log_alpha), np.log(0.5),
                               rtol=1e-6)
    obs = np.ones(6, np.float32)
    for i in range(6):
        agent.store_transition(obs, np.zeros(2, np.float32), 0.1, obs,
                               False, np.zeros(2))
    agent.learn()                       # counter 0 -> alpha update fires
    assert int(agent.state.learn_counter) == 1
    assert float(agent.state.alpha) > 0.0
