"""Transformer-dataset maintenance: merge + SMOTE-style balancing
(VERDICT r1 item 7; populatebuffer.py / mergebuffers.py parity)."""

import numpy as np

from smartcal_tpu.models.transformer import XYBuffer
from smartcal_tpu.train.supervised import (balance_xy_buffer,
                                           label_combination_counts,
                                           merge_xy_buffers)

DX, DY = 6, 3


def _buf(rows):
    b = XYBuffer(len(rows), (DX,), (DY,))
    for x, y in rows:
        b.store(x, y)
    return b


def test_merge_xy_buffers():
    rng = np.random.default_rng(0)
    b1 = _buf([(rng.standard_normal(DX), np.r_[1.0, 0, 0])
               for _ in range(4)])
    b2 = _buf([(rng.standard_normal(DX), np.r_[0.0, 1, 0])
               for _ in range(3)])
    m = merge_xy_buffers(b1, b2)
    assert m.mem_cntr == 7
    np.testing.assert_array_equal(m.x[:4], b1.x[:4])
    np.testing.assert_array_equal(m.y[4:7], b2.y[:3])


def test_label_combination_counts():
    b = _buf([(np.zeros(DX), np.r_[1.0, 0, 1]),
              (np.zeros(DX), np.r_[1.0, 0, 1]),
              (np.zeros(DX), np.r_[0.0, 0, 0])])
    codes, counts = label_combination_counts(b)
    # bit-encoding matches populatebuffer.py: MSB = first label
    np.testing.assert_array_equal(codes, [0b101, 0b101, 0])
    assert counts == {5: 2, 0: 1}


def test_balance_xy_buffer():
    rng = np.random.default_rng(1)
    rows = ([(rng.standard_normal(DX), np.r_[1.0, 0, 0])
             for _ in range(10)]
            + [(rng.standard_normal(DX), np.r_[0.0, 1, 0])
               for _ in range(3)]
            + [(rng.standard_normal(DX), np.r_[1.0, 1, 1])])  # singleton
    b = _buf(rows)
    out = balance_xy_buffer(b, seed=0)
    _, counts = label_combination_counts(out)
    # every combination raised to the majority count
    assert set(counts.values()) == {10}
    assert out.mem_cntr == 30
    # synthetic minority samples interpolate within their class: all
    # balanced class-0b010 x rows stay inside the convex hull coordinatewise
    codes, _ = label_combination_counts(out)
    sel = out.x[:out.mem_cntr][codes == 0b010]
    orig = b.x[10:13]
    assert sel.min() >= orig.min() - 1e-6
    assert sel.max() <= orig.max() + 1e-6
