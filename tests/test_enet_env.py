"""Tests for the elastic-net environment (reference enetenv.py semantics)."""

import jax
import jax.numpy as jnp
import numpy as np

from smartcal_tpu.envs import enet


CFG = enet.EnetConfig(M=8, N=8, lbfgs_iters=60)


def test_reset_shapes_and_normalisation():
    st, obs = enet.reset(CFG, jax.random.PRNGKey(0))
    assert st.A.shape == (8, 8)
    np.testing.assert_allclose(float(jnp.linalg.norm(st.A)), 1.0, rtol=1e-5)
    assert obs.shape == (CFG.obs_dim,)
    # initial eig block is zero
    np.testing.assert_allclose(np.asarray(obs[:8]), 0.0)
    # sparse ground truth: between 1 and M-1 nonzeros (collisions allowed),
    # at least ceil? reference allows duplicates so >=1
    nnz = int(jnp.sum(st.x0 != 0))
    assert 1 <= nnz <= 7


def test_step_reward_and_obs():
    st, _ = enet.reset(CFG, jax.random.PRNGKey(1))
    action = jnp.zeros(2)  # mid-range rho
    st2, obs, reward, done = enet.step(CFG, st, action, jax.random.PRNGKey(2))
    assert not bool(done)
    assert np.isfinite(float(reward))
    # reward = ||y||/||Ax-y|| + min(EE)/max(EE), no penalty for in-range action
    assert float(reward) > 0.0
    EE = np.asarray(obs[:8])
    assert np.all(np.isfinite(EE))
    # A block of obs unchanged by step
    np.testing.assert_allclose(np.asarray(obs[8:]),
                               np.asarray(st.A.ravel()), rtol=1e-6)


def test_out_of_range_action_penalty():
    st, _ = enet.reset(CFG, jax.random.PRNGKey(3))
    # mapping: [-1, 1] -> [LOW, HIGH]; out-of-range clamps with -0.1 each
    rho, pen = enet.action_to_rho(jnp.asarray([0.0, 1.0]))
    np.testing.assert_allclose(np.asarray(rho),
                               [(enet.HIGH + enet.LOW) / 2, enet.HIGH],
                               rtol=1e-5)
    assert float(pen) == 0.0
    rho, pen = enet.action_to_rho(jnp.asarray([2.0, -2.0]))
    np.testing.assert_allclose(np.asarray(rho), [enet.HIGH, enet.LOW],
                               rtol=1e-5)
    np.testing.assert_allclose(float(pen), -0.2, atol=1e-6)
    # and the clamped action still produces a valid (penalised) env step
    k = jax.random.PRNGKey(4)
    _, _, r_out, _ = enet.step(CFG, st, jnp.asarray([2.0, -2.0]), k)
    assert np.isfinite(float(r_out))


def test_keepnoise_determinism():
    st, _ = enet.reset(CFG, jax.random.PRNGKey(5))
    k = jax.random.PRNGKey(6)
    st1, _, r1, _ = enet.step(CFG, st, jnp.zeros(2), k)
    # keepnoise=True reuses st.y: stepping twice from same state is identical
    st2, _, r2, _ = enet.step(CFG, st1, jnp.zeros(2), k, keepnoise=True)
    st3, _, r3, _ = enet.step(CFG, st1, jnp.zeros(2), k, keepnoise=True)
    np.testing.assert_allclose(float(r2), float(r3), rtol=1e-5)


def test_eig_modes_agree():
    """Symmetrised on-device spectrum ~ host exact eig real parts."""
    cfg_sym = enet.EnetConfig(M=8, N=8, lbfgs_iters=60, eig_mode="symmetric")
    cfg_ex = enet.EnetConfig(M=8, N=8, lbfgs_iters=60, eig_mode="exact")
    st, _ = enet.reset(cfg_sym, jax.random.PRNGKey(7))
    k = jax.random.PRNGKey(8)
    _, obs_s, r_s, _ = enet.step(cfg_sym, st, jnp.zeros(2), k)
    _, obs_e, r_e, _ = enet.step(cfg_ex, st, jnp.zeros(2), k)
    Es = np.sort(np.asarray(obs_s[:8]))
    Ee = np.sort(np.asarray(obs_e[:8]))
    np.testing.assert_allclose(Es, Ee, atol=0.05)
    np.testing.assert_allclose(float(r_s), float(r_e), atol=0.05)


def test_hint_in_action_space():
    st, _ = enet.reset(CFG, jax.random.PRNGKey(9))
    st, _, _, _ = enet.step(CFG, st, jnp.zeros(2), jax.random.PRNGKey(10))
    hint = enet.get_hint(CFG, st)
    assert hint.shape == (2,)
    h = np.asarray(hint)
    assert np.all(h >= -1.0 - 1e-6) and np.all(h <= 1.0 + 1e-6)
    # hint maps back into [LOW, HIGH]
    lam = h * (enet.HIGH - enet.LOW) / 2 + (enet.HIGH + enet.LOW) / 2
    assert np.all(lam >= enet.LOW - 1e-6) and np.all(lam <= enet.HIGH + 1e-6)


def test_wrapper_gym_interface():
    env = enet.EnetEnv(M=6, N=6, provide_hint=True, seed=0, lbfgs_iters=40)
    obs = env.reset()
    assert obs.shape == (env.cfg.obs_dim,)
    obs2, reward, done, hint, info = env.step(np.zeros(2))
    assert obs2.shape == obs.shape
    assert np.isfinite(reward)
    assert hint.shape == (2,)
