"""JSONL metrics stream + profiler hook (VERDICT r1 item 9
observability)."""

import json

import numpy as np

from smartcal_tpu.utils import JsonlLogger, profiler_trace


def test_jsonl_logger(tmp_path):
    path = tmp_path / "m.jsonl"
    with JsonlLogger(str(path)) as log:
        log.log("episode", episode=0, score=np.float32(1.5))
        log.log("episode", episode=1, score=2.0, use_hint=True)
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert len(lines) == 2
    assert lines[0]["event"] == "episode"
    assert lines[0]["score"] == 1.5          # numpy scalar -> plain float
    assert lines[1]["use_hint"] is True
    assert all("t" in ln for ln in lines)


def test_jsonl_logger_disabled():
    log = JsonlLogger(None)
    log.log("episode", score=1.0)            # no-op, no error
    log.close()


def test_jsonl_logger_appends(tmp_path):
    path = tmp_path / "m.jsonl"
    for i in range(2):
        with JsonlLogger(str(path)) as log:
            log.log("run", i=i)
    assert len(path.read_text().splitlines()) == 2


def test_profiler_trace_noop():
    with profiler_trace(None):
        pass
    with profiler_trace(""):
        pass


def test_driver_metrics_stream(tmp_path, monkeypatch):
    """The enet driver emits one episode event per episode (the stream now
    also carries a run header and span/run_end events — obs.RunLog)."""
    monkeypatch.chdir(tmp_path)
    from smartcal_tpu.train.enet_sac import train_fused

    train_fused(episodes=3, steps=2, M=6, N=6, quiet=True, save_every=0,
                metrics_path=str(tmp_path / "enet.jsonl"))
    lines = [json.loads(ln)
             for ln in (tmp_path / "enet.jsonl").read_text().splitlines()]
    eps = [ln for ln in lines if ln["event"] == "episode"]
    assert len(eps) == 3
    assert [ln["episode"] for ln in eps] == [0, 1, 2]
    assert all(np.isfinite(ln["score"]) for ln in eps)
