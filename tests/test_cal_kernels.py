"""Golden tests for the calibration math layer.

Each batched kernel in smartcal_tpu.cal.kernels is checked against a
straightforward per-sample loop oracle implementing the documented math
(SURVEY.md section 2.1; the reference's numpy/torch twins are the spec).
Sizes are tiny (N=4 stations, T=2, K=2) so the oracles stay fast.
"""

import numpy as np
import pytest

from smartcal_tpu.cal import consensus, kernels


def _mk_problem(rng, N=4, T=2, K=2):
    B = N * (N - 1) // 2
    R = (rng.standard_normal((2 * B * T, 2))
         + 1j * rng.standard_normal((2 * B * T, 2))).astype(np.complex64)
    C = (rng.standard_normal((K, B * T, 4))
         + 1j * rng.standard_normal((K, B * T, 4))).astype(np.complex64)
    J = (rng.standard_normal((K, 2 * N, 2))
         + 1j * rng.standard_normal((K, 2 * N, 2))).astype(np.complex64)
    return R, C, J, B, T, K


def _pairs(N):
    return [(p, q) for p in range(N - 1) for q in range(p + 1, N)]


def _ci(C, k, ck):
    return C[k, ck, :].reshape(2, 2, order="F")


def _dvpq(r):
    v = np.zeros(4, np.complex64)
    v[r // 2] = 1j if r % 2 else 1.0
    return v


def golden_hessian(R, C, J, N):
    B = N * (N - 1) // 2
    T = R.shape[0] // (2 * B)
    K = C.shape[0]
    H = np.zeros((K, 4 * N, 4 * N), np.complex64)
    I2 = np.eye(2)
    for k in range(K):
        ck = 0
        for _t in range(T):
            for p, q in _pairs(N):
                res = R[2 * ck:2 * ck + 2, :]
                ci = _ci(C, k, ck)
                off = np.kron(-ci.conj(), res)
                H[k, 4 * p:4 * p + 4, 4 * q:4 * q + 4] += off
                H[k, 4 * q:4 * q + 4, 4 * p:4 * p + 4] += off.conj().T
                a1 = ci @ J[k, 2 * q:2 * q + 2, :].conj().T
                H[k, 4 * p:4 * p + 4, 4 * p:4 * p + 4] += np.kron(
                    (a1 @ a1.conj().T).T, I2)
                a2 = J[k, 2 * p:2 * p + 2, :] @ ci
                H[k, 4 * q:4 * q + 4, 4 * q:4 * q + 4] += np.kron(
                    (a2.conj().T @ a2).T, I2)
                ck += 1
    return H / (B * T)


def golden_dsolutions(C, J, N, Dgrad, r):
    B = N * (N - 1) // 2
    T = C.shape[1] // B
    K = C.shape[0]
    dvpq = _dvpq(r)
    dJ = np.zeros((K, 4 * N, B), np.complex64)
    for k in range(K):
        adv = np.zeros((4 * N, B), np.complex64)
        ck = 0
        for _t in range(T):
            for bi, (p, q) in enumerate(_pairs(N)):
                ci = _ci(C, k, ck)
                lhs = J[k, 2 * q:2 * q + 2, :] @ ci.conj().T
                fv = np.kron(lhs.T, np.eye(2)) @ dvpq
                adv[2 * p:2 * p + 2, bi] += fv[0:2]
                adv[2 * N + 2 * p:2 * N + 2 * p + 2, bi] += fv[2:4]
                ck += 1
        dJ[k] = np.linalg.solve(
            Dgrad[k] + kernels.EPS_SINGULAR * np.eye(4 * N), adv)
    return dJ


def golden_dresiduals(C, J, N, dJ, addself, r):
    B = N * (N - 1) // 2
    T = C.shape[1] // B
    K = C.shape[0]
    dvpq = _dvpq(r)
    dR = np.zeros((4 * B, B), np.complex64)
    for k in range(K):
        ck = 0
        for _t in range(T):
            for bi, (p, q) in enumerate(_pairs(N)):
                ci = _ci(C, k, ck)
                lhs = -(ci @ J[k, 2 * q:2 * q + 2, :].conj().T).T
                rhs = np.concatenate(
                    [dJ[k, 2 * p:2 * p + 2, :],
                     dJ[k, 2 * N + 2 * p:2 * N + 2 * p + 2, :]])
                fv = np.kron(lhs, np.eye(2)) @ rhs
                if addself:
                    fv[:, bi] += dvpq
                dR[4 * bi:4 * bi + 4, :] += fv
                ck += 1
    return dR / (B * T)


def golden_llr(R, C, J, N):
    B = N * (N - 1) // 2
    T = R.shape[0] // (2 * B)
    K = C.shape[0]
    out = np.zeros(K, np.float32)
    for k in range(K):
        ck = 0
        sigma2 = 0.0
        rv = np.zeros(B * T * 4, np.complex64)
        mv = np.zeros(B * T * 4, np.complex64)
        for _t in range(T):
            for p, q in _pairs(N):
                res = R[2 * ck:2 * ck + 2, :]
                sV = 0.5 * (res[0, 1] - res[1, 0])
                sigma2 += float(np.real(sV * np.conj(sV)))
                ci = _ci(C, k, ck)
                model = J[k, 2 * p:2 * p + 2, :] @ ci \
                    @ J[k, 2 * q:2 * q + 2, :].conj().T
                rv[4 * ck:4 * ck + 4] = res.ravel()
                mv[4 * ck:4 * ck + 4] = model.ravel()
                ck += 1
        out[k] = (-np.linalg.norm(rv) ** 2 + np.linalg.norm(rv + mv) ** 2) \
            / (sigma2 + kernels.EPS_DIV)
    return out


class TestHessianRes:
    def test_matches_loop_oracle(self, rng):
        R, C, J, B, T, K = _mk_problem(rng)
        got = np.asarray(kernels.hessian_res(R, C, J, 4))
        want = golden_hessian(R, C, J, 4)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_hermitian_diag_blocks(self, rng):
        R, C, J, *_ = _mk_problem(rng, N=3, T=1, K=1)
        H = np.asarray(kernels.hessian_res(R, C, J, 3))
        for p in range(3):
            blk = H[0, 4 * p:4 * p + 4, 4 * p:4 * p + 4]
            np.testing.assert_allclose(blk, blk.conj().T, atol=1e-5)


class TestDsolutions:
    def test_all_r_match_loop_oracle(self, rng):
        N = 4
        R, C, J, B, T, K = _mk_problem(rng, N=N)
        Dgrad = golden_hessian(R, C, J, N) \
            + 0.5 * np.eye(4 * N, dtype=np.complex64)[None]
        got = np.asarray(kernels.dsolutions_all(C, J, N, Dgrad))
        for r in range(8):
            want = golden_dsolutions(C, J, N, Dgrad, r)
            np.testing.assert_allclose(got[r], want, rtol=1e-3, atol=1e-4,
                                       err_msg=f"r={r}")

    def test_single_r_wrapper(self, rng):
        N = 3
        R, C, J, *_ = _mk_problem(rng, N=N, T=1, K=1)
        Dgrad = golden_hessian(R, C, J, N) \
            + 0.5 * np.eye(4 * N, dtype=np.complex64)[None]
        full = np.asarray(kernels.dsolutions_all(C, J, N, Dgrad))
        one = np.asarray(kernels.dsolutions(C, J, N, Dgrad, 3))
        np.testing.assert_allclose(one, full[3], atol=1e-6)


class TestDresiduals:
    @pytest.mark.parametrize("addself", [False, True])
    def test_all_r_match_loop_oracle(self, rng, addself):
        N = 4
        R, C, J, B, T, K = _mk_problem(rng, N=N)
        Dgrad = golden_hessian(R, C, J, N) \
            + 0.5 * np.eye(4 * N, dtype=np.complex64)[None]
        dJ = np.asarray(kernels.dsolutions_all(C, J, N, Dgrad))
        got = np.asarray(kernels.dresiduals_all(C, J, N, dJ, addself=addself))
        for r in range(8):
            want = golden_dresiduals(C, J, N, dJ[r], addself, r)
            np.testing.assert_allclose(got[r], want, rtol=1e-3, atol=1e-4,
                                       err_msg=f"r={r}")

    def test_perdir_sums_to_total(self, rng):
        N = 4
        R, C, J, *_ = _mk_problem(rng, N=N)
        Dgrad = golden_hessian(R, C, J, N) \
            + 0.5 * np.eye(4 * N, dtype=np.complex64)[None]
        dJ = np.asarray(kernels.dsolutions_all(C, J, N, Dgrad))
        total = np.asarray(kernels.dresiduals_all(C, J, N, dJ, addself=True))
        perdir = np.asarray(
            kernels.dresiduals_all_perdir(C, J, N, dJ, addself=True))
        np.testing.assert_allclose(perdir.sum(axis=1), total,
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("addself", [False, True])
    @pytest.mark.parametrize("perdir", [False, True])
    def test_colmeans_match_dense(self, rng, addself, perdir):
        """The fused column-means path (the N=62 memory move) must equal
        the per-pol row means of the dense dR oracle."""
        from smartcal_tpu.cal import creal

        N = 5
        R, C, J, B, T, K = _mk_problem(rng, N=N, T=2, K=3)
        Dgrad = golden_hessian(R, C, J, N) \
            + 0.5 * np.eye(4 * N, dtype=np.complex64)[None]
        Cs, Js = creal.split(C), creal.split(J)
        dJs = kernels.dsolutions_all_sr(Cs, Js, N, creal.split(Dgrad))
        got = np.asarray(kernels.dresiduals_colmeans_sr(
            Cs, Js, N, dJs, addself=addself, perdir=perdir))
        if perdir:
            dR = np.asarray(kernels.dresiduals_all_perdir_sr(
                Cs, Js, N, dJs, addself=addself))
            want = dR.reshape(8, K, B, 4, B, 2).mean(axis=2)
        else:
            dR = np.asarray(kernels.dresiduals_all_sr(
                Cs, Js, N, dJs, addself=addself))
            want = dR.reshape(8, B, 4, B, 2).mean(axis=1)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


class TestLLR:
    def test_matches_loop_oracle(self, rng):
        R, C, J, *_ = _mk_problem(rng)
        got = np.asarray(kernels.log_likelihood_ratio(R, C, J, 4))
        want = golden_llr(R, C, J, 4)
        np.testing.assert_allclose(got, want, rtol=1e-3)

    def test_perfect_model_positive(self, rng):
        """If residual contains the model, LLR should be large/positive."""
        N, T, K = 3, 2, 1
        B = N * (N - 1) // 2
        C = (rng.standard_normal((K, B * T, 4))
             + 1j * rng.standard_normal((K, B * T, 4))).astype(np.complex64)
        J = np.tile(np.eye(2, dtype=np.complex64), (K, N, 1))
        R = np.zeros((2 * B * T, 2), np.complex64)
        for ck in range(B * T):
            R[2 * ck:2 * ck + 2, :] = C[0, ck].reshape(2, 2, order="F") \
                + 0.01 * rng.standard_normal((2, 2))
        llr = np.asarray(kernels.log_likelihood_ratio(R, C, J, N))
        assert llr[0] > 0


class TestConsensusPoly:
    def golden(self, Ne, N, freqs, f0, fidx, polytype, rho, alpha):
        Nf = len(freqs)
        Bfull = np.zeros((Nf, Ne), np.float32)
        if polytype == 0:
            Bfull[:, 0] = 1.0
            ff = (freqs - f0) / f0
            for cj in range(1, Ne):
                Bfull[:, cj] = ff ** cj
        else:
            ff = (freqs - freqs.min()) / (freqs.max() - freqs.min())
            from math import comb
            for r in range(Ne):
                Bfull[:, r] = comb(Ne - 1, r) * ff ** r \
                    * (1 - ff) ** (Ne - 1 - r)
        Bi = np.zeros((Ne, Ne), np.float32)
        for cf in range(Nf):
            Bi += np.outer(Bfull[cf], Bfull[cf])
        Bi = np.linalg.pinv(rho * Bi + alpha * np.eye(Ne))
        Bf = np.kron(Bfull[fidx], np.eye(2 * N))
        P = np.kron(Bi, np.eye(2 * N)) @ Bf.T
        F = np.eye(2 * N) - rho * (Bf @ P)
        return F, P

    @pytest.mark.parametrize("polytype", [0, 1])
    def test_matches_dense_oracle(self, polytype):
        freqs = np.linspace(120e6, 160e6, 5).astype(np.float32)
        Ne, N, f0, fidx = 3, 2, 140e6, 2
        F, P = consensus.consensus_poly(Ne, N, freqs, f0, fidx,
                                        polytype=polytype, rho=0.7, alpha=0.1)
        Fg, Pg = self.golden(Ne, N, freqs, f0, fidx, polytype, 0.7, 0.1)
        np.testing.assert_allclose(np.asarray(F), Fg, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(P), Pg, rtol=1e-4, atol=1e-5)

    def test_bernstein_partition_of_unity(self):
        x = np.linspace(0, 1, 7).astype(np.float32)
        y = np.asarray(consensus.bernstein_basis(x, 4))
        np.testing.assert_allclose(y.sum(axis=1), np.ones(7), rtol=1e-5)
