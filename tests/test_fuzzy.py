"""Tests for the fuzzy controller (models/fuzzy.py) and fuzzy demixing env
against the reference (demixing_fuzzy/demix_controller.py, demixingenv.py)."""

import numpy as np
import pytest

from smartcal_tpu.models.fuzzy import (N_ACTION, DemixController,
                                       default_config, trapmf)


class TestTrapmf:
    def test_shape_points(self):
        import jax.numpy as jnp
        abcd = jnp.asarray([0.0, 10.0, 20.0, 40.0])
        assert float(trapmf(jnp.asarray(-1.0), abcd)) == 0.0
        assert float(trapmf(jnp.asarray(5.0), abcd)) == pytest.approx(0.5)
        assert float(trapmf(jnp.asarray(15.0), abcd)) == 1.0
        assert float(trapmf(jnp.asarray(30.0), abcd)) == pytest.approx(0.5)
        assert float(trapmf(jnp.asarray(41.0), abcd)) == 0.0

    def test_degenerate_edges(self):
        import jax.numpy as jnp
        # a == b (step up), as in the 'low' sets
        abcd = jnp.asarray([-90.0, -90.0, -5.0, 5.0])
        assert float(trapmf(jnp.asarray(-90.0), abcd)) == 1.0
        assert float(trapmf(jnp.asarray(0.0), abcd)) == pytest.approx(0.5)


class TestControllerActionMaps:
    def test_update_roundtrip(self):
        """update_limits then update_action must return the same action
        (the reference documents update_action_ as the exact inverse)."""
        ctrl = DemixController()
        rng = np.random.default_rng(0)
        action = rng.uniform(0.05, 0.6, N_ACTION)
        ctrl.update_limits(action)
        back = ctrl.update_action()
        np.testing.assert_allclose(back, action, rtol=1e-10)

    def test_default_action_roundtrip(self):
        ctrl = DemixController()
        a0 = ctrl.update_action()
        ctrl2 = DemixController()
        ctrl2.update_limits(a0)
        for grp in ("inputs", "outputs"):
            for k, v in ctrl2.config[grp].items():
                if k.startswith("_comment"):
                    continue
                ref = default_config()[grp][k]
                for term in ("low", "medium", "high"):
                    np.testing.assert_allclose(v[term], ref[term], atol=1e-9)

    def test_chained_breakpoints_monotone(self):
        ctrl = DemixController()
        ctrl.update_limits(np.full(N_ACTION, 0.3))
        for name, var in ctrl.config["inputs"].items():
            lo, me, hi = var["low"], var["medium"], var["high"]
            assert lo[1] <= lo[2] <= lo[3]
            assert me[0] == lo[2] and me[1] == lo[3]
            assert me[1] <= me[2] <= me[3]
            assert hi[0] == me[2] and hi[1] == me[3]


class TestPriority:
    def test_bright_close_high_elevation_scores_high(self):
        ctrl = DemixController()
        # close separation, high elevation, bright source
        p_good = ctrl.evaluate(azimuth=0.0, azimuth_target=0.0,
                               elevation=70.0, elevation_target=70.0,
                               separation=5.0, log_intensity=8.0,
                               intensity_ratio=60.0)
        # below horizon, far, weak
        p_bad = ctrl.evaluate(azimuth=120.0, azimuth_target=-100.0,
                              elevation=-30.0, elevation_target=70.0,
                              separation=120.0, log_intensity=0.5,
                              intensity_ratio=0.1)
        assert p_good > p_bad
        assert p_good >= 50.0
        assert p_bad <= 45.0

    def test_priority_in_range(self):
        ctrl = DemixController()
        rng = np.random.default_rng(1)
        for _ in range(10):
            p = ctrl.evaluate(
                azimuth=float(rng.uniform(-180, 180)),
                azimuth_target=float(rng.uniform(-180, 180)),
                elevation=float(rng.uniform(-90, 90)),
                elevation_target=float(rng.uniform(-90, 90)),
                separation=float(rng.uniform(0, 180)),
                log_intensity=float(rng.uniform(0, 10)),
                intensity_ratio=float(rng.uniform(0, 100)))
            assert 0.0 <= p <= 100.0


class TestFuzzyEnv:
    @pytest.fixture(scope="class")
    def env(self):
        from smartcal_tpu.envs import FuzzyDemixingEnv
        from smartcal_tpu.envs.radio import RadioBackend
        be = RadioBackend(n_stations=6, n_freqs=2, n_times=4, tdelta=2,
                          admm_iters=15, lbfgs_iters=3, init_iters=5,
                          npix=32)
        env = FuzzyDemixingEnv(K=3, provide_hint=True,
                               provide_influence=False, backend=be, seed=11)
        obs = env.reset()
        return env, obs

    def test_reset(self, env):
        e, obs = env
        assert obs["metadata"].shape == (5 * e.K + 2,)
        md = obs["metadata"] / 1e-3
        # selection flags: only target at reset
        flags = md[4 * e.K:5 * e.K]
        np.testing.assert_array_equal(flags, [0, 0, 1])
        assert e.hint is not None and e.hint.shape == (e.n_actions,)

    def test_hint_is_default_config(self, env):
        e, _ = env
        a01 = e.hint * 0.5 + 0.5
        base = DemixController().update_action()
        np.testing.assert_allclose(a01[:24], base[:24], atol=1e-6)
        np.testing.assert_allclose(a01[-8:], base[-8:], atol=1e-6)

    def test_step_with_hint_action(self, env):
        e, _ = env
        obs, r, done, hint, info = e.step(e.hint)
        assert np.isfinite(r)
        assert obs["metadata"].shape == (5 * e.K + 2,)
        assert len(info["priority"]) == e.K - 1
        # maxiter fixed at 15 in the fuzzy variant
        assert e.maxiter == 15
