"""Shapelet diffuse-sky models (VERDICT r1 item 8): uv-plane prediction
golden-tested against a direct numpy image-grid Fourier oracle."""

import math

import numpy as np
import pytest

from smartcal_tpu.cal import shapelets


def test_basis_orthonormal():
    """The 1D basis is orthonormal: integral phi_a phi_b = delta_ab."""
    x = np.linspace(-12, 12, 6001)
    dx = x[1] - x[0]
    B = np.asarray(shapelets.basis_1d(5, x, beta=0.7))
    G = B @ B.T * dx
    np.testing.assert_allclose(G, np.eye(5), atol=2e-5)


def test_uv_matches_numpy_dft_oracle():
    """V(u, v) from the analytic FT == direct grid integration of the
    image-domain shapelet (validates normalization, i^n routing, and the
    e^{+i} sign convention of cal/coherency)."""
    rng = np.random.default_rng(3)
    n0 = 4
    beta = 0.05
    coeff = rng.standard_normal((n0, n0)).astype(np.float32)
    # image grid wide enough to capture the envelope (n0 * beta ~ 0.2 rad)
    npix = 801
    half = 12 * beta
    grid = np.linspace(-half, half, npix)
    dl = grid[1] - grid[0]
    L, M = np.meshgrid(grid, grid, indexing="ij")
    img = np.asarray(shapelets.shapelet_image(coeff, L, M, beta))

    u = np.asarray([0.0, 1.3, -2.0, 4.0, 0.5]) / beta / (2 * np.pi)
    v = np.asarray([0.0, -0.7, 1.1, 0.2, -3.0]) / beta / (2 * np.pi)
    vis = np.asarray(shapelets.shapelet_uv_sr(coeff, u, v, beta))
    for i in range(u.size):
        kernel = np.exp(2j * np.pi * (u[i] * L + v[i] * M))
        oracle = np.sum(img * kernel) * dl * dl
        np.testing.assert_allclose(vis[i, 0], oracle.real, rtol=2e-3,
                                   atol=2e-3 * np.abs(oracle).max())
        np.testing.assert_allclose(vis[i, 1], oracle.imag, rtol=2e-3,
                                   atol=2e-3 * np.abs(oracle).max())


def test_offset_phase_ramp():
    """An off-center shapelet is the centered one times e^{2 pi i (u l0 +
    v m0)}."""
    rng = np.random.default_rng(4)
    coeff = rng.standard_normal((3, 3)).astype(np.float32)
    u = np.asarray([1.0, 2.0])
    v = np.asarray([0.5, -1.0])
    l0, m0 = 0.01, -0.02
    v_cen = np.asarray(shapelets.shapelet_uv_sr(coeff, u, v, 0.1))
    v_off = np.asarray(shapelets.shapelet_uv_sr(coeff, u, v, 0.1,
                                                l0=l0, m0=m0))
    ph = 2 * np.pi * (u * l0 + v * m0)
    expect_re = v_cen[:, 0] * np.cos(ph) - v_cen[:, 1] * np.sin(ph)
    expect_im = v_cen[:, 0] * np.sin(ph) + v_cen[:, 1] * np.cos(ph)
    np.testing.assert_allclose(v_off[:, 0], expect_re, rtol=1e-5)
    np.testing.assert_allclose(v_off[:, 1], expect_im, rtol=1e-5)


def test_modes_file_roundtrip(tmp_path):
    rng = np.random.default_rng(5)
    m = shapelets.random_shapelet(rng)
    assert 10 <= m.coeff.shape[0] < 20
    assert m.beta * m.coeff.shape[0] <= 2.001
    assert not np.allclose(m.coeff, m.coeff_cal)     # perturbed twin
    p = tmp_path / "test.modes"
    shapelets.write_modes(str(p), m.coeff, m.beta)
    coeff2, beta2 = shapelets.read_modes(str(p))
    np.testing.assert_allclose(coeff2, m.coeff, rtol=1e-5)
    assert beta2 == pytest.approx(m.beta)


def test_rescale_modes():
    c = np.ones((3, 3))
    out = shapelets.rescale_modes(c)
    # value / ((ci+1)(cj+1)), the correct_shapelet_modes factorial ratio
    assert out[0, 0] == pytest.approx(1.0 / (1 * 1))
    assert out[2, 1] == pytest.approx(1.0 / (3 * 2))


def test_diffuse_episode():
    """simulate_models(diffuse=True) + backend integration.

    At LOFAR baseline lengths a ~0.1-rad shapelet is essentially resolved
    out (its uv support is ~1/(2 pi beta) wavelengths), so the visible-
    contribution check uses a meters-scale compact layout; the standard-
    scale episode checks the full path stays finite and solvable."""
    import jax

    from smartcal_tpu.cal import observation, simulate
    from smartcal_tpu.envs.radio import RadioBackend

    backend = RadioBackend(n_stations=6, n_freqs=2, n_times=4, tdelta=2,
                           admm_iters=2, lbfgs_iters=3, init_iters=4,
                           npix=16)
    key = jax.random.PRNGKey(0)
    mdl = simulate.simulate_models(key, K=3, diffuse=True)
    assert mdl.shapelet is not None
    assert simulate.simulate_models(key, K=3).shapelet is None

    # compact array (meter baselines): the diffuse component contributes
    obs = observation.make_observation(
        key, n_stations=6, n_freqs=2, n_times=4, hba=False,
        layout_kwargs=dict(core_radius=2.0, max_radius=20.0))
    C = backend._coherencies(obs, mdl.sky_cal)
    C2 = backend._add_shapelet(obs, C, mdl.shapelet.coeff_cal,
                               mdl.shapelet.beta_cal, mdl.shapelet.flux)
    assert not np.allclose(np.asarray(C[:, 0]), np.asarray(C2[:, 0]))
    np.testing.assert_allclose(np.asarray(C[:, 1]), np.asarray(C2[:, 1]),
                               rtol=1e-6)

    # full episode at standard scale solves and stays finite
    ep1, mdl1 = backend.new_calib_episode(key, K=3, M=3, diffuse=True)
    assert mdl1.shapelet is not None
    res = backend.calibrate(ep1, mdl1.rho)
    assert np.all(np.isfinite(np.asarray(res.residual)))
