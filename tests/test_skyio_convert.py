"""DP3 skymodel conversion + parset emission (SURVEY §2.5 convertmodel /
simulate.py parset roles)."""

import numpy as np
import pytest

from smartcal_tpu.cal import coords, simulate, skyio

MAKESOURCEDB = """\
format = Name, Type, Patch, Ra, Dec, I, Q, U, V, ReferenceFrequency='134e6', SpectralIndex='[]', MajorAxis, MinorAxis, Orientation
 , , CasA, 23:23:24.0, +58.48.54.0
casa_1, POINT, CasA, 23:23:24.0, +58.48.54.0, 8000.0, 0, 0, 0, 134e6, [-0.7, 0.02], , ,
casa_2, GAUSSIAN, CasA, 23:23:27.1, +58.49.00.0, 2000.0, 0, 0, 0, 134e6, [-0.6], 120.0, 60.0, 30.0
 , , Target, 12:00:00.0, +45.00.00.0
t_1, POINT, Target, 12:00:00.0, +45.00.00.0, 2.5, 0, 0, 0, , [], , ,
t_2, POINT, Target, 12:00:10.0, -0.5123, 1.0, 0, 0, 0, , [], , ,
"""


def test_parse_makesourcedb(tmp_path):
    p = tmp_path / "model.txt"
    p.write_text(MAKESOURCEDB)
    sources, patches = skyio.parse_makesourcedb(str(p))
    assert patches == ["CasA", "Target"]
    assert len(sources) == 4
    s = sources[0]
    assert s["type"] == "POINT" and s["patch"] == "CasA"
    assert s["ra"] == pytest.approx(float(coords.hms_to_rad(23, 23, 24.0)),
                                    rel=1e-9)
    assert s["dec"] == pytest.approx(np.deg2rad(58 + 48 / 60 + 54 / 3600),
                                     rel=1e-9)
    # multi-term spectral index: brackets protect the comma; first term
    assert s["I"] == 8000.0 and s["spectral_index"] == -0.7
    # empty ReferenceFrequency uses the HEADER default, not 100 MHz
    assert sources[2]["ref_freq"] == pytest.approx(134e6)
    # decimal-degree dec is degrees, not dd.mm sexagesimal
    assert sources[3]["dec"] == pytest.approx(np.deg2rad(-0.5123),
                                              rel=1e-9)
    # Gaussian extents arrive in radians
    assert sources[1]["major"] == pytest.approx(
        120.0 * np.pi / (180 * 3600))


def test_convert_dp3_skymodel_roundtrip(tmp_path):
    model = tmp_path / "model.txt"
    model.write_text(MAKESOURCEDB)
    n = skyio.convert_dp3_skymodel(
        str(model), str(tmp_path / "sky.txt"),
        str(tmp_path / "cluster.txt"), str(tmp_path / "rho.txt"),
        start_cluster=1)
    assert n == 2
    # the emitted files parse with the standard readers
    ra0 = float(coords.hms_to_rad(12, 0, 0.0))
    dec0 = np.deg2rad(45.0)
    sky = skyio.build_sky_arrays(str(tmp_path / "sky.txt"),
                                 str(tmp_path / "cluster.txt"), ra0, dec0)
    assert sky.n_clusters == 2
    # gaussian naming: converted GAUSSIAN source leads with 'G'
    S = skyio.parse_sky_model(str(tmp_path / "sky.txt"))
    assert any(nm.startswith("GCasA") for nm in S)
    assert any(nm.startswith("PTarget") for nm in S)
    # the phase-center source (first of the Target patch) has l, m ~ 0
    tgt = np.asarray(sky.lmn)[np.asarray(sky.cluster) == 1]
    np.testing.assert_allclose(tgt[0, :2], 0.0, atol=1e-6)
    rho_spec, rho_spat = skyio.read_rho(str(tmp_path / "rho.txt"), 2)
    np.testing.assert_allclose(rho_spec, 1.0)
    np.testing.assert_allclose(rho_spat, 0.0)


def test_write_bbs_skymodel_roundtrip(tmp_path):
    rows = [("P0", 1.0, 0.5, 2.5, -0.7, 0.0, 0.0, 0.0, 150e6),
            ("G1", 1.01, 0.49, 1.5, -0.5, 1e-4, 5e-5, 0.3, 150e6),
            ("P2", 2.0, np.deg2rad(-0.5), 1.0, 0.0, 0.0, 0.0, 0.0, 150e6)]
    p = tmp_path / "bbs.txt"
    skyio.write_bbs_skymodel(str(p), rows, f0=150e6)
    sources, patches = skyio.parse_makesourcedb(str(p))
    assert len(sources) == 3
    assert sources[0]["type"] == "POINT"
    assert sources[1]["type"] == "GAUSSIAN"
    assert sources[0]["ra"] == pytest.approx(1.0, abs=1e-6)
    assert sources[0]["dec"] == pytest.approx(0.5, abs=1e-6)
    assert sources[1]["I"] == 1.5
    # orientation convention round-trips through write + parse
    assert sources[1]["orientation"] == pytest.approx(0.3, abs=1e-6)
    # declination in (-1, 0) deg keeps its sign and magnitude
    assert sources[2]["dec"] == pytest.approx(np.deg2rad(-0.5), abs=1e-9)


def test_convert_start_cluster_rho_ids(tmp_path):
    model = tmp_path / "model.txt"
    model.write_text(MAKESOURCEDB)
    skyio.convert_dp3_skymodel(
        str(model), str(tmp_path / "s.txt"), str(tmp_path / "c.txt"),
        str(tmp_path / "r.txt"), start_cluster=5)
    # rho ids match the cluster file's (the interchange contract)
    rho_ids = [ln.split()[0] for ln in
               (tmp_path / "r.txt").read_text().splitlines()
               if not ln.startswith("#")]
    clu_ids = [ln.split()[0] for ln in
               (tmp_path / "c.txt").read_text().splitlines()
               if not ln.startswith("#")]
    assert rho_ids == clu_ids == ["5", "6"]


def test_write_dp3_parsets(tmp_path):
    paths = simulate.write_dp3_parsets(str(tmp_path), sourcedb="sky.txt",
                                       tdelta=10)
    assert len(paths) == 3
    demix = (tmp_path / "test_demix.parset").read_text()
    assert "steps=[demix]" in demix
    assert "demix.demixtimestep=10" in demix
    dde = (tmp_path / "test_ddecal.parset").read_text()
    assert "ddecal.sourcedb=sky.txt" in dde
    assert "ddecal.solveralgorithm=lbfgs" in dde
    pred = (tmp_path / "test_predict.parset").read_text()
    assert "predict.operation=subtract" in pred
