"""DP3 skymodel conversion + parset emission (SURVEY §2.5 convertmodel /
simulate.py parset roles)."""

import numpy as np
import pytest

from smartcal_tpu.cal import coords, simulate, skyio

MAKESOURCEDB = """\
format = Name, Type, Patch, Ra, Dec, I, Q, U, V, ReferenceFrequency='134e6', SpectralIndex='[]', MajorAxis, MinorAxis, Orientation
 , , CasA, 23:23:24.0, +58.48.54.0
casa_1, POINT, CasA, 23:23:24.0, +58.48.54.0, 8000.0, 0, 0, 0, 134e6, [-0.7, 0.02], , ,
casa_2, GAUSSIAN, CasA, 23:23:27.1, +58.49.00.0, 2000.0, 0, 0, 0, 134e6, [-0.6], 120.0, 60.0, 30.0
 , , Target, 12:00:00.0, +45.00.00.0
t_1, POINT, Target, 12:00:00.0, +45.00.00.0, 2.5, 0, 0, 0, , [], , ,
t_2, POINT, Target, 12:00:10.0, -0.5123, 1.0, 0, 0, 0, , [], , ,
"""


def test_parse_makesourcedb(tmp_path):
    p = tmp_path / "model.txt"
    p.write_text(MAKESOURCEDB)
    sources, patches = skyio.parse_makesourcedb(str(p))
    assert patches == ["CasA", "Target"]
    assert len(sources) == 4
    s = sources[0]
    assert s["type"] == "POINT" and s["patch"] == "CasA"
    assert s["ra"] == pytest.approx(float(coords.hms_to_rad(23, 23, 24.0)),
                                    rel=1e-9)
    assert s["dec"] == pytest.approx(np.deg2rad(58 + 48 / 60 + 54 / 3600),
                                     rel=1e-9)
    # multi-term spectral index: brackets protect the comma; first term
    assert s["I"] == 8000.0 and s["spectral_index"] == -0.7
    # empty ReferenceFrequency uses the HEADER default, not 100 MHz
    assert sources[2]["ref_freq"] == pytest.approx(134e6)
    # decimal-degree dec is degrees, not dd.mm sexagesimal
    assert sources[3]["dec"] == pytest.approx(np.deg2rad(-0.5123),
                                              rel=1e-9)
    # Gaussian extents arrive in radians
    assert sources[1]["major"] == pytest.approx(
        120.0 * np.pi / (180 * 3600))


def test_convert_dp3_skymodel_roundtrip(tmp_path):
    model = tmp_path / "model.txt"
    model.write_text(MAKESOURCEDB)
    n = skyio.convert_dp3_skymodel(
        str(model), str(tmp_path / "sky.txt"),
        str(tmp_path / "cluster.txt"), str(tmp_path / "rho.txt"),
        start_cluster=1)
    assert n == 2
    # the emitted files parse with the standard readers
    ra0 = float(coords.hms_to_rad(12, 0, 0.0))
    dec0 = np.deg2rad(45.0)
    sky = skyio.build_sky_arrays(str(tmp_path / "sky.txt"),
                                 str(tmp_path / "cluster.txt"), ra0, dec0)
    assert sky.n_clusters == 2
    # gaussian naming: converted GAUSSIAN source leads with 'G'
    S = skyio.parse_sky_model(str(tmp_path / "sky.txt"))
    assert any(nm.startswith("GCasA") for nm in S)
    assert any(nm.startswith("PTarget") for nm in S)
    # the phase-center source (first of the Target patch) has l, m ~ 0
    tgt = np.asarray(sky.lmn)[np.asarray(sky.cluster) == 1]
    np.testing.assert_allclose(tgt[0, :2], 0.0, atol=1e-6)
    rho_spec, rho_spat = skyio.read_rho(str(tmp_path / "rho.txt"), 2)
    np.testing.assert_allclose(rho_spec, 1.0)
    np.testing.assert_allclose(rho_spat, 0.0)


def test_write_bbs_skymodel_roundtrip(tmp_path):
    rows = [("P0", 1.0, 0.5, 2.5, -0.7, 0.0, 0.0, 0.0, 150e6),
            ("G1", 1.01, 0.49, 1.5, -0.5, 1e-4, 5e-5, 0.3, 150e6),
            ("P2", 2.0, np.deg2rad(-0.5), 1.0, 0.0, 0.0, 0.0, 0.0, 150e6)]
    p = tmp_path / "bbs.txt"
    skyio.write_bbs_skymodel(str(p), rows, f0=150e6)
    sources, patches = skyio.parse_makesourcedb(str(p))
    assert len(sources) == 3
    assert sources[0]["type"] == "POINT"
    assert sources[1]["type"] == "GAUSSIAN"
    assert sources[0]["ra"] == pytest.approx(1.0, abs=1e-6)
    assert sources[0]["dec"] == pytest.approx(0.5, abs=1e-6)
    assert sources[1]["I"] == 1.5
    # orientation convention round-trips through write + parse
    assert sources[1]["orientation"] == pytest.approx(0.3, abs=1e-6)
    # declination in (-1, 0) deg keeps its sign and magnitude
    assert sources[2]["dec"] == pytest.approx(np.deg2rad(-0.5), abs=1e-9)


def test_convert_start_cluster_rho_ids(tmp_path):
    model = tmp_path / "model.txt"
    model.write_text(MAKESOURCEDB)
    skyio.convert_dp3_skymodel(
        str(model), str(tmp_path / "s.txt"), str(tmp_path / "c.txt"),
        str(tmp_path / "r.txt"), start_cluster=5)
    # rho ids match the cluster file's (the interchange contract)
    rho_ids = [ln.split()[0] for ln in
               (tmp_path / "r.txt").read_text().splitlines()
               if not ln.startswith("#")]
    clu_ids = [ln.split()[0] for ln in
               (tmp_path / "c.txt").read_text().splitlines()
               if not ln.startswith("#")]
    assert rho_ids == clu_ids == ["5", "6"]


def test_write_dp3_parsets(tmp_path):
    paths = simulate.write_dp3_parsets(str(tmp_path), sourcedb="sky.txt",
                                       tdelta=10)
    assert len(paths) == 3
    demix = (tmp_path / "test_demix.parset").read_text()
    assert "steps=[demix]" in demix
    assert "demix.demixtimestep=10" in demix
    dde = (tmp_path / "test_ddecal.parset").read_text()
    assert "ddecal.sourcedb=sky.txt" in dde
    assert "ddecal.solveralgorithm=lbfgs" in dde
    pred = (tmp_path / "test_predict.parset").read_text()
    assert "predict.operation=subtract" in pred


# ---------------------------------------------------------------------------
# Real A-team fixture (VERDICT r2 item 4): the reference's checked-in
# demixing/base.{sky,cluster,rho} catalogue converted through skyio by
# tools/convert_ateam.py into smartcal_tpu/data/ateam.*
# ---------------------------------------------------------------------------

ATEAM_CLUSTER_SIZES = {0: 9, 1: 5, 2: 469, 3: 26, 4: 24}  # CasA..VirA


def test_ateam_fixture_golden_parse():
    """Golden facts from the reference catalogue: 533 sources, 5 clusters
    (CasA 9, CygA 5, HerA 469, TauA 26, VirA 24), brightest CasA component
    4193 Jy with SI -0.8 at 73.7817 MHz, rho 1.0 per cluster."""
    from smartcal_tpu.cal import dataset

    sky_p, clus_p, rho_p = dataset.ateam_paths()
    S = skyio.parse_sky_model(sky_p)
    clusters = skyio.parse_cluster_file(clus_p)
    assert len(S) == 533
    assert len(clusters) == 5
    assert {cid: len(names) for cid, names in clusters} \
        == ATEAM_CLUSTER_SIZES
    casa0 = S["GCasA0"]
    assert casa0[6] == pytest.approx(4193.0)          # I (Jy)
    assert casa0[10] == pytest.approx(-0.8)           # SI0
    assert casa0[17] == pytest.approx(73781700.0)     # f0
    # all Gaussian CasA components carry extents; positions land near the
    # true CasA direction (23h23m24s +58d48m54s)
    ra = coords.hms_to_rad(casa0[0], casa0[1], casa0[2])
    dec = coords.dms_to_rad(casa0[3], casa0[4], casa0[5])
    assert float(ra) == pytest.approx(
        float(coords.hms_to_rad(23, 23, 24.0)), abs=1e-3)
    assert float(dec) == pytest.approx(np.deg2rad(58.815), abs=1e-3)
    rho_s, rho_p_ = skyio.read_rho(rho_p, 5)
    np.testing.assert_allclose(rho_s, 1.0)
    # cluster-total fluxes: CasA and CygA are the dominant A-team sources
    total = {}
    for cid, names in clusters:
        total[cid] = sum(S[nm][6] for nm in names)
    assert total[0] > 15000 and total[1] > 10000      # CasA, CygA
    assert total[2] < total[0]                        # HerA much weaker


def test_ateam_fixture_build_sky_arrays():
    """The fixture loads through the standard parser into a device-ready
    SkyArrays: 533 sources, Gaussian flags from the G/P name prefixes."""
    from smartcal_tpu.cal import dataset

    sky_p, clus_p, _ = dataset.ateam_paths()
    sky = skyio.build_sky_arrays(sky_p, clus_p, ra0=0.5, dec0=0.9)
    assert sky.lmn.shape == (533, 3)
    assert sky.n_clusters == 5
    assert np.all(np.isfinite(np.asarray(sky.lmn)))
    counts = np.bincount(np.asarray(sky.cluster), minlength=5)
    assert {i: int(c) for i, c in enumerate(counts)} == ATEAM_CLUSTER_SIZES
    # HerA is almost entirely point sources; CasA all Gaussian
    isg = np.asarray(sky.is_gauss)
    cl = np.asarray(sky.cluster)
    assert np.all(isg[cl == 0])
    assert np.mean(isg[cl == 2]) < 0.1


def test_calibration_sky_defaults_to_real_ateam():
    """calibration_sky with no sky_path now returns the REAL catalogue:
    K-1 fixture clusters + unit target at the phase center, fixture rho."""
    from smartcal_tpu.cal import dataset

    cal = dataset.calibration_sky(ra0=1.0, dec0=1.0, t0=5e9, f0=60e6, K=3)
    # clusters 0,1 = CasA, CygA; 2 = target
    assert cal.sky.n_clusters == 3
    counts = np.bincount(np.asarray(cal.sky.cluster), minlength=3)
    assert list(counts) == [9, 5, 1]
    assert cal.separations[-1] == 0.0
    np.testing.assert_allclose(cal.rho, [1.0, 1.0, 10.0])
    assert np.all(np.isfinite(cal.azimuth)) and np.all(
        np.isfinite(cal.elevation))
    # the synthetic stand-in is still reachable and differs
    syn = dataset.calibration_sky(ra0=1.0, dec0=1.0, t0=5e9, f0=60e6, K=3,
                                  synthetic=True)
    assert int(np.asarray(syn.sky.cluster).shape[0]) != 15


def test_assemble_real_sky_with_dp3_target(tmp_path):
    """VERDICT r2 missing#3: user-supplied DP3-format target model
    concatenated after the A-team fixture (generate_data.py:760-776) —
    6 clusters, target last, parseable end-to-end."""
    from smartcal_tpu.cal import dataset

    model = tmp_path / "target_model.txt"
    model.write_text(MAKESOURCEDB.replace("CasA", "TGT1")
                     .replace("Target", "TGT2"))
    sky_p, clus_p, rho_p, K = dataset.assemble_real_sky(
        str(model), str(tmp_path), num_patches=1)
    assert K == 6
    sky = skyio.build_sky_arrays(sky_p, clus_p, ra0=0.5, dec0=0.9)
    assert sky.n_clusters == 6
    counts = np.bincount(np.asarray(sky.cluster), minlength=6)
    assert list(counts[:5]) == [9, 5, 469, 26, 24]
    assert counts[5] == 2                 # the TGT1 patch
    rho_s, _ = skyio.read_rho(rho_p, 6)
    np.testing.assert_allclose(rho_s, 1.0)
