"""Fault-tolerant training runtime (smartcal_tpu/runtime/): atomic
writes, checksummed versioned checkpoints, kill-resume bit-continuity
per agent family, PER round-trip through checkpoint for both buffer
types, deterministic fault injection, watchdog rollback-and-retry e2e,
and solver graceful degradation."""

import json
import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smartcal_tpu.runtime import (Backoff, BackoffPolicy, FaultPlan,
                                  atomic_pickle, checkpoint, clear_faults,
                                  faults, install_faults, safe_pickle_load)


@pytest.fixture(autouse=True)
def in_tmp(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    yield
    clear_faults()


# ---------------------------------------------------------------------------
# atomic writes + corruption-tolerant loads
# ---------------------------------------------------------------------------

def test_atomic_pickle_roundtrip_and_no_partial(tmp_path):
    path = str(tmp_path / "obj.pkl")
    atomic_pickle({"a": 1, "b": [1, 2]}, path)
    with open(path, "rb") as f:
        assert pickle.load(f) == {"a": 1, "b": [1, 2]}
    # overwrite is atomic too, and no temp litter survives
    atomic_pickle({"a": 2}, path)
    with open(path, "rb") as f:
        assert pickle.load(f) == {"a": 2}
    assert [p for p in os.listdir(tmp_path) if p.endswith(".tmp")] == []


def test_safe_pickle_load_degrades(tmp_path):
    warns = []
    # missing file
    assert safe_pickle_load(str(tmp_path / "nope.pkl"), default=[1],
                            warn=warns.append) == [1]
    # truncated stream (the mid-write-kill signature)
    good = pickle.dumps(list(range(100)))
    trunc = tmp_path / "trunc.pkl"
    trunc.write_bytes(good[:len(good) // 2])
    assert safe_pickle_load(str(trunc), default="fresh",
                            warn=warns.append) == "fresh"
    # garbage bytes
    (tmp_path / "junk.pkl").write_bytes(b"not a pickle at all")
    assert safe_pickle_load(str(tmp_path / "junk.pkl"), default=None,
                            warn=warns.append) is None
    assert len(warns) == 3 and all("starting fresh" in w for w in warns)


def test_backoff_deterministic_bounded():
    pol = BackoffPolicy(base_s=1.0, factor=2.0, max_s=5.0, jitter=0.25,
                        max_attempts=4, budget_s=100.0)
    a, b = Backoff(pol, seed=7), Backoff(pol, seed=7)
    da = [a.next_delay() for _ in range(5)]
    db = [b.next_delay() for _ in range(5)]
    assert da == db                       # same seed, same walk
    assert da[4] is None                  # attempt cap
    for i, d in enumerate(da[:4]):
        nominal = min(1.0 * 2 ** i, 5.0)
        assert 0.75 * nominal <= d <= 1.25 * nominal
    # budget bound: tiny budget clips the walk
    c = Backoff(BackoffPolicy(base_s=10.0, jitter=0.0, budget_s=15.0))
    assert c.next_delay() == 10.0
    assert c.next_delay() == 5.0          # clipped into the budget
    assert c.next_delay() is None


# ---------------------------------------------------------------------------
# checkpoint store
# ---------------------------------------------------------------------------

def test_checkpoint_roundtrip_latest_and_retention(tmp_path):
    root = str(tmp_path / "ck")
    for step in (2, 4, 6, 8):
        checkpoint.save_checkpoint(root, step, {"step": step,
                                                "x": np.arange(step)},
                                   keep=2)
    payload, step = checkpoint.load_latest(root)
    assert step == 8 and payload["step"] == 8
    np.testing.assert_array_equal(payload["x"], np.arange(8))
    # retention pruned to the newest 2
    assert [s for s, _ in checkpoint.list_checkpoints(root)] == [6, 8]


def test_checkpoint_corruption_falls_back(tmp_path):
    root = str(tmp_path / "ck")
    checkpoint.save_checkpoint(root, 1, {"v": 1}, keep=3)
    checkpoint.save_checkpoint(root, 2, {"v": 2}, keep=3)
    # corrupt the newest payload: checksum validation must reject it and
    # fall back to step 1
    newest = os.path.join(root, "ckpt_000002", "payload.pkl")
    with open(newest, "r+b") as f:
        f.write(b"\x00\x00\x00\x00")
    payload, step = checkpoint.load_latest(root)
    assert step == 1 and payload["v"] == 1
    # corrupt LATEST too: the directory scan still finds step 1
    with open(os.path.join(root, "LATEST"), "w") as f:
        f.write("{not json")
    payload, step = checkpoint.load_latest(root)
    assert step == 1 and payload["v"] == 1
    # a stale mid-write temp dir is ignored (and pruned on the next save)
    os.makedirs(os.path.join(root, ".ckpt_000009.partial"))
    assert checkpoint.load_latest(root)[1] == 1
    checkpoint.save_checkpoint(root, 3, {"v": 3}, keep=3)
    assert not [d for d in os.listdir(root) if d.startswith(".ckpt_")]


def test_checkpoint_empty_root(tmp_path):
    assert checkpoint.load_latest(str(tmp_path / "missing")) is None


def test_per_priorities_roundtrip_hbm(tmp_path):
    from smartcal_tpu.rl import replay as rp

    buf = rp.replay_init(16, rp.transition_spec(3, 2))
    rng = np.random.default_rng(0)
    for i in range(20):                  # wraps the ring
        tr = {"state": rng.standard_normal(3).astype(np.float32),
              "new_state": rng.standard_normal(3).astype(np.float32),
              "action": rng.standard_normal(2).astype(np.float32),
              "reward": np.float32(i), "done": np.bool_(False),
              "hint": np.zeros(2, np.float32)}
        buf = rp.replay_add(buf, tr, error=jnp.asarray(float(i) / 3))
    payload = {"replay": checkpoint.pack_replay(buf)}
    checkpoint.save_checkpoint(str(tmp_path / "ck"), 1, payload)
    loaded, _ = checkpoint.load_latest(str(tmp_path / "ck"))
    buf2 = checkpoint.unpack_replay(loaded["replay"])
    np.testing.assert_array_equal(np.asarray(buf.priority),
                                  np.asarray(buf2.priority))
    assert int(buf2.cntr) == int(buf.cntr)
    np.testing.assert_array_equal(np.asarray(buf.data["state"]),
                                  np.asarray(buf2.data["state"]))
    assert float(buf2.beta) == float(buf.beta)


def test_per_priorities_roundtrip_native(tmp_path):
    native = pytest.importorskip("smartcal_tpu.native")
    if native.lib() is None:
        pytest.skip("native library unavailable")
    from smartcal_tpu.rl import replay as rp
    from smartcal_tpu.rl.replay_native import NativePER

    buf = NativePER(16, rp.transition_spec(3, 2))
    rng = np.random.default_rng(1)
    for i in range(20):
        tr = {"state": rng.standard_normal(3).astype(np.float32),
              "new_state": rng.standard_normal(3).astype(np.float32),
              "action": rng.standard_normal(2).astype(np.float32),
              "reward": np.float32(i), "done": np.bool_(False),
              "hint": np.zeros(2, np.float32)}
        buf.store(tr, error=float(i) / 3)
    checkpoint.save_checkpoint(str(tmp_path / "ck"), 1,
                               {"replay": checkpoint.pack_replay(buf)})
    loaded, _ = checkpoint.load_latest(str(tmp_path / "ck"))
    buf2 = checkpoint.unpack_replay(loaded["replay"])
    # sum-tree priorities, cursor, and ring data all survive exactly
    np.testing.assert_array_equal(buf.tree.leaves(), buf2.tree.leaves())
    assert (buf2.cntr, buf2.beta) == (buf.cntr, buf.beta)
    assert buf2.tree.cursor == buf.tree.cursor
    np.testing.assert_array_equal(buf.data["state"], buf2.data["state"])
    # and sampling from the restored tree behaves
    batch, idx, w = buf2.sample(4, np.random.default_rng(0))
    assert np.all(np.isfinite(w))


# ---------------------------------------------------------------------------
# kill-resume bit-continuity per agent family (train N, "kill", resume N
# == train 2N straight)
# ---------------------------------------------------------------------------

def _assert_tree_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _kill_resume_parity(mod, episodes=4, **kw):
    straight, _, st_all, buf_all = mod.train_fused(
        seed=0, episodes=episodes, quiet=True, prefix="a_", **kw)
    mod.train_fused(seed=0, episodes=episodes // 2, quiet=True,
                    prefix="b_", ckpt_dir="ck",
                    ckpt_every=episodes // 2, **kw)
    resumed, _, st_res, buf_res = mod.train_fused(
        seed=0, episodes=episodes, quiet=True,
        prefix="b_", ckpt_dir="ck", resume=True, **kw)
    assert resumed == straight
    _assert_tree_equal(st_all, st_res)
    np.testing.assert_array_equal(np.asarray(buf_all.priority),
                                  np.asarray(buf_res.priority))
    _assert_tree_equal(buf_all.data, buf_res.data)


def test_kill_resume_parity_sac():
    from smartcal_tpu.train import enet_sac

    _kill_resume_parity(enet_sac, steps=2, M=5, N=5)


def test_kill_resume_parity_td3_per():
    """TD3 runs prioritized replay — the PER-priorities half of the
    same-seed parity acceptance criterion rides through this one."""
    from smartcal_tpu.train import enet_td3

    _kill_resume_parity(enet_td3, steps=2, M=5, N=5, use_hint=True,
                        prioritized=True)


def test_kill_resume_parity_ddpg():
    from smartcal_tpu.train import enet_ddpg

    _kill_resume_parity(enet_ddpg, steps=2, M=5, N=5)


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------

def test_faults_mutate_diag_exact_step():
    install_faults(FaultPlan(nan_field="critic_loss", nan_step=3))
    d = {"critic_loss": 1.0, "q_mean": 0.5}
    assert faults.mutate_diag(d, 2) == d             # wrong step: identity
    out = faults.mutate_diag(d, 3)
    assert np.isnan(out["critic_loss"]) and out["q_mean"] == 0.5
    assert d["critic_loss"] == 1.0                   # input not mutated


def test_faults_kill_and_env_plan(monkeypatch):
    install_faults(FaultPlan(kill_actor=1, kill_at=2))
    assert not faults.should_kill_actor(0, 2)
    assert not faults.should_kill_actor(1, 1)
    assert faults.should_kill_actor(1, 2)
    clear_faults()
    monkeypatch.setenv("SMARTCAL_FAULTS",
                       json.dumps({"nan_field": "q_mean", "nan_step": 7,
                                   "unknown_key": 1}))
    plan = faults.plan_from_env()
    assert plan.nan_field == "q_mean" and plan.nan_step == 7
    monkeypatch.setenv("SMARTCAL_FAULTS", "{broken")
    assert faults.plan_from_env() is None


# ---------------------------------------------------------------------------
# watchdog rollback-and-retry e2e (enet driver + NaN injection)
# ---------------------------------------------------------------------------

def test_watchdog_reset_unlatches():
    from smartcal_tpu.obs.watchdog import Watchdog

    wd = Watchdog()
    assert wd.observe({"critic_loss": float("nan")}, step=0)
    assert wd.tripped and wd.trips == 1
    wd.reset()
    assert not wd.tripped and wd.trip_reason is None
    assert not wd.observe({"critic_loss": 1.0}, step=1)
    assert wd.trips == 1


@pytest.fixture(scope="module")
def enet_ref(tmp_path_factory):
    """The uninjected same-seed reference run shared by the rollback
    tests (computed once per module)."""
    from smartcal_tpu.train import enet_sac

    d = tmp_path_factory.mktemp("enet_ref")
    cwd = os.getcwd()
    os.chdir(d)
    try:
        ref, _, st_ref, _ = enet_sac.train_fused(
            seed=0, episodes=6, steps=3, M=5, N=5, quiet=True,
            save_every=0, prefix="r_", watchdog=True)
    finally:
        os.chdir(cwd)
    return ref, st_ref


def test_rollback_e2e_enet_nan_injection(tmp_path, enet_ref):
    """Injected-NaN run recovers via rollback and (with the identity
    mitigation) finishes bit-identical to the uninjected same-seed run;
    the RunLog carries the structured recovery event."""
    from smartcal_tpu.train import enet_sac

    ref, st_ref = enet_ref
    # NaN into critic_loss at global update 10 (episode 3 of 3-step
    # episodes); checkpoints every 2 episodes
    install_faults(FaultPlan(nan_field="critic_loss", nan_step=10))
    run = str(tmp_path / "inj.jsonl")
    inj, _, st_inj, _ = enet_sac.train_fused(
        seed=0, episodes=6, steps=3, M=5, N=5, quiet=True, save_every=0,
        prefix="i_", metrics_path=run, ckpt_dir="ck_inj", ckpt_every=2,
        max_recoveries=2, recovery_lr_shrink=1.0, recovery_reseed=False)
    clear_faults()
    events = [json.loads(ln) for ln in open(run) if ln.strip()]
    kinds = [e["event"] for e in events]
    assert "fault_injected" in kinds
    assert "watchdog_trip" in kinds
    rec = [e for e in events if e["event"] == "recovery"]
    assert rec and rec[0]["action"] == "rollback"
    assert rec[0]["rollback_step"] == 2
    assert rec[0]["reason"].startswith("non_finite")
    # identity mitigation -> the retried tail IS the uninjected run
    assert inj == ref
    _assert_tree_equal(st_ref, st_inj)
    end = [e for e in events if e["event"] == "run_end"][-1]
    # the stream records every LOGGED episode including the re-walked
    # tail: episodes 0-2 before the trip at episode 3, then 2-5 again
    # after rolling back to the episode-2 checkpoint
    assert end["episodes"] == 7
    ep_ids = [e["episode"] for e in events if e["event"] == "episode"]
    assert ep_ids == [0, 1, 2, 2, 3, 4, 5]


def test_rollback_budget_exhausts_to_halt(tmp_path):
    """A fault that re-fires after every rollback must exhaust the
    bounded budget and fall through to the graceful halt."""
    from smartcal_tpu.train import enet_sac

    # updates counter keeps increasing across rollbacks, so target a
    # step that recurs: use max_recoveries=1 and a second injection at a
    # later update — rollback once, trip again, halt.
    install_faults(FaultPlan(nan_field="critic_loss", nan_step=10))
    run = str(tmp_path / "halt.jsonl")
    scores, _, _, _ = enet_sac.train_fused(
        seed=0, episodes=6, steps=3, M=5, N=5, quiet=True, save_every=0,
        prefix="h_", metrics_path=run, ckpt_dir="ck_halt", ckpt_every=10,
        max_recoveries=1, recovery_lr_shrink=1.0, recovery_reseed=False)
    clear_faults()
    events = [json.loads(ln) for ln in open(run) if ln.strip()]
    rec = [e for e in events if e["event"] == "recovery"]
    # no checkpoint existed yet (ckpt_every=10 > trip episode) -> halt
    assert rec and rec[0]["action"] == "halt_no_checkpoint"
    assert len(scores) < 6                      # graceful early halt


@pytest.mark.slow
def test_recovery_mitigation_applies(tmp_path, enet_ref):
    """With LR shrink + reseed armed the retried trajectory diverges
    from the poisoned one (the mitigation actually does something).
    Slow tier: the default tier already certifies the rollback path
    bit-exactly (test_rollback_e2e_enet_nan_injection); this adds the
    mitigation-changes-the-trajectory direction."""
    from smartcal_tpu.train import enet_sac

    ref, _ = enet_ref
    install_faults(FaultPlan(nan_field="critic_loss", nan_step=10))
    inj, _, st_inj, _ = enet_sac.train_fused(
        seed=0, episodes=6, steps=3, M=5, N=5, quiet=True, save_every=0,
        prefix="m_", ckpt_dir="ck_mit", ckpt_every=2, max_recoveries=2,
        recovery_lr_shrink=0.5, recovery_reseed=True)
    clear_faults()
    assert len(inj) == 6
    # the pre-rollback prefix matches, the retried tail differs
    assert inj[:2] == ref[:2]
    assert inj[2:] != ref[2:]


# ---------------------------------------------------------------------------
# solver graceful degradation
# ---------------------------------------------------------------------------

def _fake_result(finite: bool):
    from smartcal_tpu.cal import solver

    v = 1.0 if finite else float("nan")
    return solver.SolveResult(
        J=jnp.full((2, 2), v), Z=jnp.zeros((2,)),
        residual=jnp.full((3,), v), sigma_res=jnp.asarray(0.1),
        sigma_data=jnp.asarray(1.0), final_cost=jnp.full((1,), v))


def test_solver_safe_rho_boost_then_ok():
    from smartcal_tpu.cal import solver

    calls = []

    def solve_fn(rho):
        calls.append(float(np.asarray(rho).ravel()[0]))
        return _fake_result(len(calls) >= 3)

    events = []
    res, info = solver.solve_admm_safe(
        solve_fn, jnp.ones(2), max_retries=2, rho_boost=10.0,
        on_event=lambda **kw: events.append(kw))
    assert calls == [1.0, 10.0, 100.0]
    assert info == {"degraded": True, "attempts": 2, "route": "retry_rho",
                    "rho_scale": 100.0}
    assert solver.result_finite(res)
    assert [e["route"] for e in events] == ["retry_rho", "retry_rho"]


def test_solver_safe_host_fallback_and_raise():
    from smartcal_tpu.cal import solver

    bad = lambda rho: _fake_result(False)
    host = lambda rho: _fake_result(True)
    res, info = solver.solve_admm_safe(bad, jnp.ones(2),
                                       host_fallback=host, max_retries=1)
    assert info["route"] == "host_segmented"
    with pytest.raises(solver.SolverDegradedError):
        solver.solve_admm_safe(bad, jnp.ones(2), max_retries=1)
    # an already-computed healthy result short-circuits everything
    res, info = solver.solve_admm_safe(bad, jnp.ones(2),
                                       initial_result=_fake_result(True))
    assert not info["degraded"]


@pytest.mark.slow
def test_backend_calibrate_degrades(monkeypatch):
    """RadioBackend.calibrate retries a non-finite fused solve at boosted
    rho instead of handing NaNs to the env.  Slow tier: the ladder logic
    itself is covered by the stub-based test_solver_safe_* tests; this
    exercises the real-episode wiring."""
    from smartcal_tpu.cal import solver
    from smartcal_tpu.envs.radio import RadioBackend

    backend = RadioBackend(n_stations=6, n_freqs=2, n_times=4, tdelta=2,
                           admm_iters=2, lbfgs_iters=2, init_iters=2,
                           npix=16, solver_max_retries=1)
    ep, _ = backend.new_calib_episode(jax.random.PRNGKey(0), K=2, M=3)
    real_solve = solver.solve_admm
    state = {"calls": 0}

    def flaky(*args, **kwargs):
        state["calls"] += 1
        res = real_solve(*args, **kwargs)
        if state["calls"] == 1:
            return res._replace(J=res.J * jnp.nan)
        return res

    monkeypatch.setattr(solver, "solve_admm", flaky)
    rho = np.ones(3, np.float32)
    res = backend.calibrate(ep, rho)
    assert state["calls"] == 2                   # one retry, boosted rho
    assert solver.result_finite(res)
