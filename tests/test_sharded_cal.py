"""Mesh-sharded calibration vs the single-device oracle.

solve_admm_sharded's psum over the ``fp`` axis IS the global consensus
sum, so the sharded solve must match the single-device solve bitwise-ish;
influence_sharded's chunks are embarrassingly parallel, so exactly.
(The reference's counterparts are the sagecal-mpi allreduce and the
analysis_torch.py process pool.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smartcal_tpu.cal import influence as influence_mod
from smartcal_tpu.cal import solver
from smartcal_tpu.envs.radio import RadioBackend
from smartcal_tpu.parallel import make_mesh
from smartcal_tpu.parallel.sharded_cal import (influence_sharded,
                                               solve_admm_sharded)

N_STATIONS = 6
NFREQ = 4
NCHUNKS = 4
K = 3


@pytest.fixture(scope="module")
def episode():
    backend = RadioBackend(n_stations=N_STATIONS, n_freqs=NFREQ,
                           n_times=8, tdelta=2, admm_iters=3,
                           lbfgs_iters=3, init_iters=4, npix=8)
    ep, mdl = backend.new_demixing_episode(jax.random.PRNGKey(3), K)
    return backend, ep, mdl


def test_solve_admm_sharded_matches_single_device(episode):
    backend, ep, mdl = episode
    cfg = backend._solver_cfg(K)
    rho = jnp.asarray(mdl.rho)
    ref = solver.solve_admm(ep.V, ep.Ccal, ep.obs.freqs, ep.f0, rho, cfg,
                            n_chunks=backend.n_chunks)

    mesh = make_mesh((NFREQ, 2), ("fp", "sp"))
    out = solve_admm_sharded(mesh, ep.V, ep.Ccal, ep.obs.freqs, ep.f0,
                             rho, cfg, axis="fp",
                             n_chunks=backend.n_chunks)
    # float32 reduction-order differences (psum vs local sums) amplify
    # through the ADMM iterations; observed max rel diff ~2e-3 on <1% of
    # elements — the math is identical, the summation order is not
    np.testing.assert_allclose(np.asarray(out.Z), np.asarray(ref.Z),
                               rtol=5e-3, atol=5e-4)
    np.testing.assert_allclose(np.asarray(out.J), np.asarray(ref.J),
                               rtol=5e-3, atol=5e-4)
    # residual = V - model: tiny J differences scale by ~1e3 coherency
    # amplitudes, so near-zero elements fail elementwise ratios — compare
    # in norm
    dr = np.asarray(out.residual) - np.asarray(ref.residual)
    assert (np.linalg.norm(dr)
            / max(np.linalg.norm(np.asarray(ref.residual)), 1e-12)) < 1e-3
    assert float(out.sigma_res) == pytest.approx(float(ref.sigma_res),
                                                 rel=1e-3)


@pytest.mark.parametrize("perdir", [False, True])
def test_influence_sharded_matches_single_device(episode, perdir):
    backend, ep, mdl = episode
    cfg = backend._solver_cfg(K)
    rho = jnp.asarray(mdl.rho)
    res = solver.solve_admm(ep.V, ep.Ccal, ep.obs.freqs, ep.f0, rho, cfg,
                            n_chunks=backend.n_chunks)
    freqs = np.asarray(ep.obs.freqs)
    hadd = influence_mod.consensus_hadd_scalars(
        mdl.rho, np.full(K, 0.0, np.float32), freqs, ep.f0, 0,
        n_poly=backend.n_poly, polytype=backend.polytype)
    Rk = solver.residual_to_kernel(res.residual[0])
    ref = influence_mod.influence_visibilities(
        Rk, ep.Ccal[0], res.J[0], hadd, N_STATIONS, NCHUNKS,
        perdir=perdir)

    mesh = make_mesh((2, 4), ("fp", "sp"))
    out = influence_sharded(mesh, Rk, ep.Ccal[0], res.J[0], hadd,
                            N_STATIONS, NCHUNKS, axis="sp", perdir=perdir)
    np.testing.assert_allclose(np.asarray(out.vis), np.asarray(ref.vis),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out.llr), np.asarray(ref.llr),
                               rtol=1e-5, atol=1e-5)
