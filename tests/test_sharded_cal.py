"""Mesh-sharded calibration vs the single-device oracle.

solve_admm_sharded's psum over the ``fp`` axis IS the global consensus
sum, so the sharded solve must match the single-device solve bitwise-ish;
influence_sharded's chunks are embarrassingly parallel, so exactly.
(The reference's counterparts are the sagecal-mpi allreduce and the
analysis_torch.py process pool.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smartcal_tpu.cal import influence as influence_mod
from smartcal_tpu.cal import solver
from smartcal_tpu.envs.radio import RadioBackend
from smartcal_tpu.parallel import make_mesh
from smartcal_tpu.parallel.sharded_cal import (influence_sharded,
                                               solve_admm_sharded,
                                               solve_admm_sharded2d)

N_STATIONS = 6
NFREQ = 4
NCHUNKS = 4
K = 3


@pytest.fixture(scope="module")
def episode():
    backend = RadioBackend(n_stations=N_STATIONS, n_freqs=NFREQ,
                           n_times=8, tdelta=2, admm_iters=3,
                           lbfgs_iters=3, init_iters=4, npix=8)
    ep, mdl = backend.new_demixing_episode(jax.random.PRNGKey(3), K)
    return backend, ep, mdl


def test_solve_admm_sharded_matches_single_device(episode):
    backend, ep, mdl = episode
    cfg = backend._solver_cfg(K)
    rho = jnp.asarray(mdl.rho)
    ref = solver.solve_admm(ep.V, ep.Ccal, ep.obs.freqs, ep.f0, rho, cfg,
                            n_chunks=backend.n_chunks)

    mesh = make_mesh((NFREQ, 2), ("fp", "sp"))
    out = solve_admm_sharded(mesh, ep.V, ep.Ccal, ep.obs.freqs, ep.f0,
                             rho, cfg, axis="fp",
                             n_chunks=backend.n_chunks)
    # float32 reduction-order differences (psum vs local sums) amplify
    # through the ADMM iterations; observed max rel diff ~2e-3 on <1% of
    # elements — the math is identical, the summation order is not
    np.testing.assert_allclose(np.asarray(out.Z), np.asarray(ref.Z),
                               rtol=5e-3, atol=5e-4)
    np.testing.assert_allclose(np.asarray(out.J), np.asarray(ref.J),
                               rtol=5e-3, atol=5e-4)
    # residual = V - model: tiny J differences scale by ~1e3 coherency
    # amplitudes, so near-zero elements fail elementwise ratios — compare
    # in norm
    dr = np.asarray(out.residual) - np.asarray(ref.residual)
    assert (np.linalg.norm(dr)
            / max(np.linalg.norm(np.asarray(ref.residual)), 1e-12)) < 1e-3
    assert float(out.sigma_res) == pytest.approx(float(ref.sigma_res),
                                                 rel=1e-3)


@pytest.mark.parametrize("polytype", [0, 1])
def test_solve_admm_sharded2d_matches_per_episode(episode, polytype):
    """The 2D (dp x fp) batched solve equals each episode's own solve:
    dp only batches, fp carries the consensus psum (VERDICT r3 item 7 —
    the v5e-16 mesh shape on the 8-device virtual CPU mesh).  polytype=1
    checks the per-episode Bernstein band-edge plumbing: each episode's
    basis must use its OWN band, not a shared union range."""
    backend, ep0, mdl = episode
    ep1, _ = backend.new_demixing_episode(jax.random.PRNGKey(11), K)
    cfg = backend._solver_cfg(K)._replace(polytype=polytype)
    rho = jnp.asarray(mdl.rho)

    mesh2d = make_mesh((2, 4), ("dp", "fp"))
    Vb = jnp.stack([ep0.V, ep1.V])
    Cb = jnp.stack([ep0.Ccal, ep1.Ccal])
    freqs_b = jnp.stack([jnp.asarray(ep0.obs.freqs),
                         jnp.asarray(ep1.obs.freqs)])
    f0_b = jnp.asarray([ep0.f0, ep1.f0])
    out = solve_admm_sharded2d(mesh2d, Vb, Cb, freqs_b, f0_b, rho, cfg,
                               n_chunks=backend.n_chunks)

    for i, ep in enumerate((ep0, ep1)):
        ref = solver.solve_admm(ep.V, ep.Ccal, ep.obs.freqs, ep.f0, rho,
                                cfg, n_chunks=backend.n_chunks)
        # same reduction-order tolerance story as the 1D sharded test
        np.testing.assert_allclose(np.asarray(out.Z[i]),
                                   np.asarray(ref.Z), rtol=5e-3, atol=5e-4)
        np.testing.assert_allclose(np.asarray(out.J[i]),
                                   np.asarray(ref.J), rtol=5e-3, atol=5e-4)
        assert float(out.sigma_res[i]) == pytest.approx(
            float(ref.sigma_res), rel=1e-3)


@pytest.mark.slow
def test_solve_admm_sharded_lofar_scale():
    """N=62 (B=1891) sharded solve on the 8-device mesh — the BASELINE
    v5e-16 workload shape at minimum iteration depth (slow tier)."""
    backend = RadioBackend(n_stations=62, n_freqs=8, n_times=4, tdelta=2,
                           admm_iters=2, lbfgs_iters=2, init_iters=3,
                           npix=8)
    ep, mdl = backend.new_demixing_episode(jax.random.PRNGKey(5), K)
    cfg = backend._solver_cfg(K)
    mesh = make_mesh((8,), ("fp",))
    out = solve_admm_sharded(mesh, ep.V, ep.Ccal, ep.obs.freqs, ep.f0,
                             jnp.asarray(mdl.rho), cfg, axis="fp",
                             n_chunks=backend.n_chunks)
    assert np.asarray(out.J).shape[0] == 8
    assert np.all(np.isfinite(np.asarray(out.J)))
    assert np.isfinite(float(out.sigma_res))
    # the solve must actually reduce the residual below the data level
    assert float(out.sigma_res) < float(out.sigma_data)


@pytest.mark.slow
def test_dryrun_multichip_16_devices():
    """16-device readiness: the full dryrun (SAC train step + distributed
    demixing learner + 1D fp solve + 2D dp x fp solve) in a fresh
    subprocess with 16 virtual CPU devices (VERDICT r3 item 7)."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
    env.pop("JAX_PLATFORMS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(16); "
         "print('DRYRUN16 OK')"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=1800)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "DRYRUN16 OK" in r.stdout


@pytest.mark.parametrize("perdir", [False, True])
def test_influence_sharded_matches_single_device(episode, perdir):
    backend, ep, mdl = episode
    cfg = backend._solver_cfg(K)
    rho = jnp.asarray(mdl.rho)
    res = solver.solve_admm(ep.V, ep.Ccal, ep.obs.freqs, ep.f0, rho, cfg,
                            n_chunks=backend.n_chunks)
    freqs = np.asarray(ep.obs.freqs)
    hadd = influence_mod.consensus_hadd_scalars(
        mdl.rho, np.full(K, 0.0, np.float32), freqs, ep.f0, 0,
        n_poly=backend.n_poly, polytype=backend.polytype)
    Rk = solver.residual_to_kernel(res.residual[0])
    ref = influence_mod.influence_visibilities(
        Rk, ep.Ccal[0], res.J[0], hadd, N_STATIONS, NCHUNKS,
        perdir=perdir)

    mesh = make_mesh((2, 4), ("fp", "sp"))
    out = influence_sharded(mesh, Rk, ep.Ccal[0], res.J[0], hadd,
                            N_STATIONS, NCHUNKS, axis="sp", perdir=perdir)
    np.testing.assert_allclose(np.asarray(out.vis), np.asarray(ref.vis),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out.llr), np.asarray(ref.llr),
                               rtol=1e-5, atol=1e-5)
