"""End-to-end tests of the in-framework calibration backend:
observation geometry -> sky simulation -> coherency prediction ->
corruption + noise -> consensus-ADMM solve -> imaging.

This is the hermetic "fake SAGECal" contract the radio envs run on
(SURVEY.md §4: the reference cannot run without external binaries; the
build must be able to)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smartcal_tpu.cal import (coherency, creal, imager, observation,
                              simulate, solver)


def make_key(seed):
    return jax.random.PRNGKey(seed)


@pytest.fixture(scope="module")
def small_obs():
    return observation.make_observation(
        make_key(3), n_stations=8, n_freqs=3, n_times=8, t_int=2.0,
        ra0=1.0, dec0=0.9, t0=1000.0)


def test_observation_geometry(small_obs):
    obs = small_obs
    N = obs.n_stations
    assert obs.uvw.shape == (8, N * (N - 1) // 2, 3)
    # uvw tracks rotate: first and last time samples differ
    assert not np.allclose(obs.uvw[0], obs.uvw[-1])
    # baseline antisymmetry: uvw(p,q) = -uvw(q,p) by construction of p-q
    # and w is bounded by the max baseline length
    bl = np.linalg.norm(np.asarray(obs.uvw), axis=-1)
    assert bl.max() < 2 * 40e3 * 1.01
    assert np.all(np.isfinite(np.asarray(obs.uvw)))


def test_observation_freq_band(small_obs):
    f = np.asarray(small_obs.freqs)
    assert f.shape == (3,)
    assert np.all(np.diff(f) > 0)
    assert observation.HBA_LOW * 1e6 <= f[0] <= observation.HBA_HIGH * 1e6


def test_find_valid_target_elevation():
    from smartcal_tpu.cal import coords
    for seed in range(5):
        ra0, dec0, t0 = observation.find_valid_target(make_key(seed))
        lst0 = observation.OMEGA_EARTH * t0 % (2 * np.pi)
        _, el = coords.azel_from_radec(ra0, dec0, lst0, observation.LOFAR_LAT)
        assert float(el) > np.deg2rad(3.0)


def test_simulate_models_structure():
    mdl = simulate.simulate_models(make_key(5), K=3, Kc=10, M_weak=20,
                                   M_gauss=5, M2=8)
    assert mdl.sky_sim.n_clusters == 4       # K + weak
    assert mdl.sky_cal.n_clusters == 3
    assert mdl.sky_table.shape == (3, 5)
    assert mdl.rho.shape == (3,)
    assert np.all(mdl.rho > 0)
    # calibration outlier fluxes are /100 of simulation fluxes
    sim_flux = np.exp(np.asarray(mdl.sky_sim.flux_coef[:, 0]))
    cal_flux = np.exp(np.asarray(mdl.sky_cal.flux_coef[:, 0]))
    sim_out = sim_flux[np.asarray(mdl.sky_sim.cluster) == 1]
    cal_out = cal_flux[np.asarray(mdl.sky_cal.cluster) == 1]
    np.testing.assert_allclose(cal_out, sim_out / 100.0, rtol=1e-4)


def test_demixing_sky_metadata():
    mdl = simulate.simulate_demixing_sky(make_key(7), ra0=1.0, dec0=0.9,
                                         t0=500.0, f0=150e6, K=6, Kc=8,
                                         M_weak=10, M_gauss=4)
    assert mdl.sky_cal.n_clusters == 6
    assert mdl.sky_sim.n_clusters == 7
    assert mdl.separations.shape == (6,)
    # target is the last direction, at the phase center
    assert mdl.separations[-1] == 0.0
    assert np.all(mdl.fluxes > 0)
    assert mdl.rho.shape == (6,)


def test_synth_solutions_shapes_and_structure():
    Nf, Ts, K, N = 3, 2, 4, 6
    freqs = np.linspace(120e6, 160e6, Nf)
    J = simulate.synth_solutions(make_key(11), K, N, Ts, freqs, 140e6,
                                 amp=0.01)
    assert J.shape == (Nf, Ts, K, 2 * N, 2, 2)
    # attenuated errors: J close to identity
    Jc = creal.fuse(J)
    eye = np.eye(2)
    for p in range(N):
        blk = Jc[:, :, :, 2 * p:2 * p + 2]
        assert np.abs(blk - eye).mean() < 2.0  # loose: polys modulate
    # spatial term variant runs
    lm = np.random.default_rng(0).random((K, 2))
    J2 = simulate.synth_solutions(make_key(12), K, N, Ts, freqs, 140e6,
                                  spatial_term=True, lm_dirs=lm)
    assert np.all(np.isfinite(J2))


def test_add_noise_snr():
    rng = np.random.default_rng(0)
    V = rng.standard_normal((50, 4, 2)).astype(np.float32)
    Vn, scale = simulate.add_noise(make_key(1), V, snr=0.1)
    ratio = np.linalg.norm(Vn - V) / np.linalg.norm(V)
    assert 0.05 < ratio < 0.2


class TestSolver:
    """Calibration quality: solve recovers injected gains and reduces
    residual vs the uncalibrated data."""

    @pytest.fixture(scope="class")
    def problem(self):
        key = make_key(42)
        N, K, Nf, T = 6, 2, 3, 6
        obs = observation.make_observation(
            key, n_stations=N, n_freqs=Nf, n_times=T, ra0=0.5, dec0=1.0,
            t0=100.0)
        mdl = simulate.simulate_models(key, K=K, Kc=6, M_weak=0, M_gauss=0,
                                       M2=4)
        B = obs.n_baselines
        uvw = np.asarray(obs.uvw).reshape(-1, 3)
        C = jnp.stack([
            coherency.predict_coherencies_sr(
                uvw[:, 0], uvw[:, 1], uvw[:, 2], mdl.sky_cal, f)
            for f in np.asarray(obs.freqs)])            # (Nf, K, T*B, 4, 2)
        Jtrue = simulate.synth_solutions(
            make_key(43), K, N, 1, np.asarray(obs.freqs), float(obs.freqs[1]),
            amp=0.05)                                   # (Nf, 1, K, 2N, 2, 2)
        V = jnp.stack([
            solver.simulate_vis_sr(jnp.asarray(Jtrue[f]), C[f], N, 1)
            for f in range(Nf)])                        # (Nf, T, B, 2, 2, 2)
        Vn_np, _ = simulate.add_noise(make_key(2), np.asarray(V), snr=0.05)
        return obs, mdl, C, Jtrue, V, jnp.asarray(Vn_np)

    def test_residual_reduction(self, problem):
        obs, mdl, C, Jtrue, V, Vn = problem
        cfg = solver.SolverConfig(n_stations=6, n_dirs=2, n_poly=2,
                                  admm_iters=5, lbfgs_iters=12)
        res = solver.solve_admm(Vn, C, obs.freqs, float(obs.freqs[1]),
                                jnp.asarray(mdl.rho), cfg)
        assert np.isfinite(float(res.sigma_res))
        # calibration must explain most of the signal: residual well under
        # the data scale (data is signal + 5% noise)
        assert float(res.sigma_res) < 0.5 * float(res.sigma_data)

    def test_solution_recovery(self, problem):
        """With exact data (no noise) the model V(J_est) must reproduce the
        observed visibilities (J itself has a unitary ambiguity)."""
        obs, mdl, C, Jtrue, V, Vn = problem
        # n_poly=3: the injected gains are quadratic in normalized frequency
        # (simulate.synth_solutions), so Ne=3 lets the consensus constraint
        # represent them exactly instead of fighting the data fit
        cfg = solver.SolverConfig(n_stations=6, n_dirs=2, n_poly=3,
                                  admm_iters=20, lbfgs_iters=40,
                                  init_iters=150)
        res = solver.solve_admm(V, C, obs.freqs, float(obs.freqs[1]),
                                jnp.asarray(mdl.rho), cfg)
        Vhat = jnp.stack([
            solver.simulate_vis_sr(res.J[f], C[f], 6, 1)
            for f in range(3)])
        rel = (np.linalg.norm(np.asarray(Vhat - V))
               / np.linalg.norm(np.asarray(V)))
        assert rel < 0.12

    def test_planes_chi2_matches_einsum(self, problem, rng):
        """The planes-major line-search objective equals the einsum
        formulation sum|V - predict|^2 on random operands."""
        K, N, Tc = 3, 6, 4
        B = N * (N - 1) // 2
        cfg = solver.SolverConfig(n_stations=N, n_dirs=K)
        J = jnp.asarray(rng.standard_normal((K, 2 * N, 2, 2)), jnp.float32)
        V5 = jnp.asarray(rng.standard_normal((Tc, B, 2, 2, 2)), jnp.float32)
        C5 = jnp.asarray(rng.standard_normal((K, Tc, B, 2, 2, 2)),
                         jnp.float32)
        r = V5 - solver.predict_vis_sr(J, C5, N)
        ref = float(jnp.sum(r * r))
        got = float(solver._chi2_planes(J, V5, C5, cfg))
        np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_onehot_chi2_matches_einsum(self, problem, rng):
        """The PRODUCTION objective (`_chi2_planes_onehot`, matmul-based
        station expansion — what both ADMM drivers evaluate) equals the
        einsum formulation sum|V - predict|^2 in value AND gradient, so
        a swapped onehot_p/onehot_q or conjugate-sign error cannot hide
        behind the loose end-to-end solve tolerance."""
        K, N, Tc = 3, 6, 4
        B = N * (N - 1) // 2
        cfg = solver.SolverConfig(n_stations=N, n_dirs=K)
        J = jnp.asarray(rng.standard_normal((K, 2 * N, 2, 2)), jnp.float32)
        V5 = jnp.asarray(rng.standard_normal((Tc, B, 2, 2, 2)), jnp.float32)
        C5 = jnp.asarray(rng.standard_normal((K, Tc, B, 2, 2, 2)),
                         jnp.float32)
        Vp = jnp.transpose(V5, (2, 3, 4, 0, 1))
        Cp = jnp.transpose(C5, (0, 3, 4, 5, 1, 2))
        oh_p, oh_q = solver._baseline_onehots(N)

        def ref_fn(Jx):
            r = V5 - solver.predict_vis_sr(Jx, C5, N)
            return jnp.sum(r * r)

        def got_fn(Jx):
            return solver._chi2_planes_onehot(Jx, Vp, Cp, oh_p, oh_q, cfg)

        ref_v, ref_g = jax.value_and_grad(ref_fn)(J)
        got_v, got_g = jax.value_and_grad(got_fn)(J)
        np.testing.assert_allclose(float(got_v), float(ref_v), rtol=1e-5)
        scale = float(jnp.max(jnp.abs(ref_g))) + 1e-20
        np.testing.assert_allclose(np.asarray(got_g) / scale,
                                   np.asarray(ref_g) / scale, atol=2e-5)

    def test_host_segmented_matches_fused(self, problem):
        """solve_admm_host (bounded dispatches, lbfgs_resume segments) walks
        the same trajectory as the fused solve_admm: same J/Z/residual to
        float tolerance, with seg_iters forcing several resume segments in
        both the init phase and the inner ADMM solves."""
        obs, mdl, C, Jtrue, V, Vn = problem
        cfg = solver.SolverConfig(n_stations=6, n_dirs=2, n_poly=2,
                                  admm_iters=3, lbfgs_iters=5,
                                  init_iters=11)
        fused = solver.solve_admm(Vn, C, obs.freqs, float(obs.freqs[1]),
                                  jnp.asarray(mdl.rho), cfg, n_chunks=2)
        host = solver.solve_admm_host(Vn, C, obs.freqs, float(obs.freqs[1]),
                                      jnp.asarray(mdl.rho), cfg, n_chunks=2,
                                      seg_iters=4)
        np.testing.assert_allclose(np.asarray(host.J), np.asarray(fused.J),
                                   rtol=2e-3, atol=2e-4)
        np.testing.assert_allclose(np.asarray(host.residual),
                                   np.asarray(fused.residual),
                                   rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(float(host.sigma_res),
                                   float(fused.sigma_res), rtol=1e-3)
        np.testing.assert_allclose(float(host.sigma_data),
                                   float(fused.sigma_data), rtol=1e-5)
        # obs telemetry rider: collect_stats reuses the SAME segment
        # programs (host-side counting only), so the stats pass is nearly
        # free here — and the production outputs must stay bit-identical
        # to the stats-off host solve just computed
        assert host.stats is None
        stats_on = solver.solve_admm_host(
            Vn, C, obs.freqs, float(obs.freqs[1]), jnp.asarray(mdl.rho),
            cfg, n_chunks=2, seg_iters=4, collect_stats=True)
        np.testing.assert_array_equal(np.asarray(stats_on.J),
                                      np.asarray(host.J))
        st = stats_on.stats
        # seg_iters=4: init (11 iters -> 3 dispatches) + 3 outer x
        # (5 iters -> 2 dispatches) = 9; early-exiting lanes cannot
        # change the dispatch structure
        assert int(st.n_segments) == 9
        assert int(st.admm_iters) == cfg.admm_iters
        assert st.primal_resid.shape == (cfg.admm_iters,)
        assert np.all(st.primal_resid > 0)
        assert np.all(st.inner_iters > 0)
        assert int(st.init_iters) > 0

    def test_dynamic_admm_iters(self, problem):
        obs, mdl, C, Jtrue, V, Vn = problem
        cfg = solver.SolverConfig(n_stations=6, n_dirs=2, n_poly=2,
                                  admm_iters=8, lbfgs_iters=6)
        r1 = solver.solve_admm(Vn, C, obs.freqs, float(obs.freqs[1]),
                               jnp.asarray(mdl.rho), cfg,
                               admm_iters=jnp.asarray(2))
        r2 = solver.solve_admm(Vn, C, obs.freqs, float(obs.freqs[1]),
                               jnp.asarray(mdl.rho), cfg,
                               admm_iters=jnp.asarray(8))
        # more ADMM iterations must not be (much) worse
        assert float(r2.sigma_res) < float(r1.sigma_res) * 1.5

    def test_consensus_z_polynomial(self, problem):
        """Z reconstructs J smoothly over frequency: B_f Z ~ J_f."""
        obs, mdl, C, Jtrue, V, Vn = problem
        cfg = solver.SolverConfig(n_stations=6, n_dirs=2, n_poly=3,
                                  admm_iters=8, lbfgs_iters=10)
        res = solver.solve_admm(V, C, obs.freqs, float(obs.freqs[1]),
                                jnp.asarray(mdl.rho), cfg)
        bfull = np.asarray(
            __import__("smartcal_tpu.cal.consensus",
                       fromlist=["poly_basis"]).poly_basis(
                obs.freqs, float(obs.freqs[1]), 3))
        BZ = np.einsum("fe,tkenij->ftknij", bfull, np.asarray(res.Z))
        rel = (np.linalg.norm(BZ - np.asarray(res.J))
               / np.linalg.norm(np.asarray(res.J)))
        assert rel < 0.3


def test_imager_point_source_peak():
    """A single point source at the center must image to a central peak."""
    key = make_key(9)
    obs = observation.make_observation(key, n_stations=10, n_freqs=1,
                                       n_times=10, ra0=0.3, dec0=0.8,
                                       t0=50.0)
    uvw = np.asarray(obs.uvw).reshape(-1, 3)
    sky = coherency.SkyArrays(
        lmn=np.zeros((1, 3)), flux_coef=np.asarray([[0.0, 0, 0, 0]]),
        f0=np.asarray([150e6]), gauss=np.zeros((1, 3)),
        is_gauss=np.zeros(1, bool), cluster=np.zeros(1, np.int32),
        n_clusters=1)
    f = float(obs.freqs[0])
    C = coherency.predict_coherencies_sr(uvw[:, 0], uvw[:, 1], uvw[:, 2],
                                         sky, f)       # (1, R, 4, 2)
    vis = C[0, :, 0, :]                                # XX of the one cluster
    cell = imager.default_cell(obs.uvw, f)
    img = np.asarray(imager.dirty_image_sr(jnp.asarray(uvw), vis, f, cell,
                                           npix=64))
    cy = np.unravel_index(np.argmax(img), img.shape)
    assert abs(cy[0] - 32) <= 1 and abs(cy[1] - 32) <= 1
    assert img.max() == pytest.approx(1.0, rel=0.05)   # unit flux source


def test_imager_offcenter_source_position():
    """Regression: a source at (l0, m0) must peak at the (l0, m0) pixel,
    not its point reflection (imaging kernel must conjugate the
    prediction phase)."""
    key = make_key(9)
    obs = observation.make_observation(key, n_stations=10, n_freqs=1,
                                       n_times=10, ra0=0.3, dec0=0.8,
                                       t0=50.0)
    uvw = np.asarray(obs.uvw).reshape(-1, 3)
    f = float(obs.freqs[0])
    cell = imager.default_cell(obs.uvw, f)
    l0, m0 = 8 * cell, -5 * cell
    n0 = np.sqrt(1 - l0 * l0 - m0 * m0) - 1
    sky = coherency.SkyArrays(
        lmn=np.asarray([[l0, m0, n0]]), flux_coef=np.asarray([[0.0, 0, 0, 0]]),
        f0=np.asarray([150e6]), gauss=np.zeros((1, 3)),
        is_gauss=np.zeros(1, bool), cluster=np.zeros(1, np.int32),
        n_clusters=1)
    C = coherency.predict_coherencies_sr(uvw[:, 0], uvw[:, 1], uvw[:, 2],
                                         sky, f)
    img = np.asarray(imager.dirty_image_sr(jnp.asarray(uvw), C[0, :, 0, :],
                                           f, cell, npix=64))
    iy, ix = np.unravel_index(np.argmax(img), img.shape)
    # pixel_grid: row index = l offset, col index = m offset
    assert abs((iy - 32) - 8) <= 1
    assert abs((ix - 32) - (-5)) <= 1


def test_multifreq_image_average():
    key = make_key(10)
    obs = observation.make_observation(key, n_stations=6, n_freqs=2,
                                       n_times=4, ra0=0.3, dec0=0.8, t0=50.0)
    V = jnp.asarray(np.random.default_rng(0).standard_normal(
        (2, 4, obs.n_baselines, 2, 2, 2)).astype(np.float32))
    cell = imager.default_cell(obs.uvw, float(obs.freqs[-1]))
    img = imager.multifreq_image_sr(obs.uvw, V, obs.freqs, cell, npix=32)
    assert img.shape == (32, 32)
    assert np.all(np.isfinite(np.asarray(img)))


def test_make_observation_mixed_pointing_above_horizon():
    """Supplying only one of ra0/dec0 must still yield an above-horizon
    target (ADVICE r1: the drawn coordinate's elevation guarantee does not
    transfer to the mixed combination)."""
    from smartcal_tpu.cal import coords
    from smartcal_tpu.cal.observation import LOFAR_LAT

    for seed in range(6):
        key = jax.random.PRNGKey(seed)
        obs = observation.make_observation(key, n_stations=6, n_freqs=1,
                                           n_times=2, ra0=1.0)
        _, el = coords.azel_from_radec(obs.ra0, obs.dec0, obs.lst0,
                                       LOFAR_LAT)
        assert float(el) > np.deg2rad(3.0)
    # a declination that never rises at LOFAR latitude is rejected
    with pytest.raises(ValueError, match="never rises"):
        observation.make_observation(jax.random.PRNGKey(0), n_stations=6,
                                     n_freqs=1, n_times=2, dec0=-1.2)


def test_cost_eval_flops_cross_check():
    """The XLA-counted FLOPs of the solver's inner evaluation units
    (bench.py's measured MFU numerator, VERDICT r4 item 5) are finite,
    scale-consistent, and within the analytic model's stated ~2-4x
    envelope: the 112-flop/sample model counts only the core prediction
    matmuls, so model/xla lands well below 1 but never below ~0.1."""
    cfg = solver.SolverConfig(n_stations=6, n_dirs=2, n_poly=2,
                              lbfgs_iters=2, init_iters=2, admm_iters=2)
    check = solver.cost_eval_flops(cfg, Nf=2, Ts=2, td=3, B=15)
    assert check["xla_value_and_grad_flops"] > 0
    assert check["xla_linesearch_setup_flops"] > 0
    assert 0.1 < check["vag_model_over_xla"] < 1.5
    assert 0.1 < check["setup_model_over_xla"] < 1.5
    # the count scales ~linearly with the baseline count (B follows N:
    # N=6 -> 15 baselines, N=8 -> 28, a 1.87x step)
    cfg8 = cfg._replace(n_stations=8)
    check2 = solver.cost_eval_flops(cfg8, Nf=2, Ts=2, td=3, B=28)
    ratio = (check2["xla_value_and_grad_flops"]
             / check["xla_value_and_grad_flops"])
    assert 1.5 < ratio < 2.3


def test_quartic_phi_matches_direct_jvp():
    """The exact-quartic line-search objective (`_quartic_phi_maker` —
    what both ADMM drivers now run inside strong_wolfe_cubic) agrees
    with the direct jvp-based phi of ops.lbfgs._phi_maker in value and
    directional derivative across positive/negative/large alphas: the
    polynomial is the SAME function, not an approximation."""
    from smartcal_tpu.ops.lbfgs import _phi_maker

    rng = np.random.default_rng(11)
    K, N, Tc = 2, 6, 4
    B = N * (N - 1) // 2
    cfg = solver.SolverConfig(n_stations=N, n_dirs=K)
    x = jnp.asarray(rng.normal(0, 0.4, (K * 2 * N * 2 * 2,)), jnp.float32)
    d = jnp.asarray(rng.normal(0, 0.2, x.shape), jnp.float32)
    V5 = jnp.asarray(rng.normal(0, 1, (Tc, B, 2, 2, 2)), jnp.float32)
    C5 = jnp.asarray(rng.normal(0, 1, (K, Tc, B, 2, 2, 2)), jnp.float32)
    prior = jnp.asarray(rng.normal(0, 0.3, (K, 2 * N, 2, 2)), jnp.float32)
    hr = jnp.asarray([1.5, 0.7], jnp.float32)
    Vp = jnp.transpose(V5, (2, 3, 4, 0, 1))
    Cp = jnp.transpose(C5, (0, 3, 4, 5, 1, 2))
    oh = solver._baseline_onehots(N)

    fun = lambda q: solver._cost_fn_onehot(q, Vp, Cp, oh, prior, hr, cfg)
    phi_direct = _phi_maker(fun, x, d)
    phi_poly = solver._quartic_phi_maker(Vp, Cp, oh, prior, hr, cfg)(
        fun, x, d)
    for alpha in (0.0, 0.05, 0.3, 1.0, 2.5, -0.4):
        v1, g1 = phi_direct(jnp.float32(alpha))
        v2, g2 = phi_poly(jnp.float32(alpha))
        np.testing.assert_allclose(float(v2), float(v1), rtol=2e-4)
        np.testing.assert_allclose(float(g2), float(g1), rtol=2e-3,
                                   atol=2e-2)
