"""Multi-chip sharding tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np

from smartcal_tpu.envs import enet
from smartcal_tpu.parallel import make_mesh, make_parallel_sac
from smartcal_tpu.rl import sac


def test_mesh_construction():
    mesh = make_mesh()
    assert mesh.shape["dp"] == 8
    mesh2 = make_mesh((4, 2), ("dp", "fp"))
    assert mesh2.shape == {"dp": 4, "fp": 2}


def test_parallel_sac_step_8_devices():
    mesh = make_mesh((8,), ("dp",))
    env_cfg = enet.EnetConfig(M=6, N=6, lbfgs_iters=8)
    agent_cfg = sac.SACConfig(obs_dim=env_cfg.obs_dim, n_actions=2,
                              batch_size=16, mem_size=64)
    init_fn, train_step, reset_envs = make_parallel_sac(
        env_cfg, agent_cfg, mesh, n_envs=8)
    st = init_fn(jax.random.PRNGKey(0))
    # env states are actually sharded over dp
    shard_names = {s for s in
                   st.obs.sharding.spec}
    assert "dp" in shard_names

    key = jax.random.PRNGKey(1)
    for i in range(3):
        key, k = jax.random.split(key)
        st, metrics = train_step(st, k)
    assert int(st.buf.cntr) == 24
    assert int(st.agent.learn_counter) == 2  # learn active once cntr>=16
    assert np.isfinite(float(metrics["mean_reward"]))
    assert np.isfinite(float(metrics["critic_loss"]))

    # episode boundary: reset draws fresh problems, step counter back to 0
    A_before = np.asarray(st.env_states.A)
    st = reset_envs(st, jax.random.PRNGKey(9))
    assert int(st.step_in_episode) == 0
    assert not np.allclose(np.asarray(st.env_states.A), A_before)
    st, metrics = train_step(st, jax.random.PRNGKey(10))
    assert np.isfinite(float(metrics["mean_reward"]))


def test_parallel_sac_episode_block_8_devices():
    """The dp-sharded episode-block scan runs whole episodes per dispatch
    and matches the per-step API's bookkeeping."""
    mesh = make_mesh((8,), ("dp",))
    env_cfg = enet.EnetConfig(M=6, N=6, lbfgs_iters=8)
    agent_cfg = sac.SACConfig(obs_dim=env_cfg.obs_dim, n_actions=2,
                              batch_size=16, mem_size=128)
    steps_pe, eps_pd = 2, 3
    init_fn, train_step, reset_envs, run_block = make_parallel_sac(
        env_cfg, agent_cfg, mesh, n_envs=8,
        episode_block=(steps_pe, eps_pd))
    st = init_fn(jax.random.PRNGKey(0))
    st, scores = run_block(st, jax.random.PRNGKey(1))
    assert scores.shape == (eps_pd,)
    assert np.all(np.isfinite(np.asarray(scores)))
    # every episode stored steps_pe transitions per env
    assert int(st.buf.cntr) == eps_pd * steps_pe * 8
    # state stays dp-sharded through the block program
    assert "dp" in {s for s in st.obs.sharding.spec}
    # and the per-step API still composes afterwards
    st, metrics = train_step(st, jax.random.PRNGKey(2))
    assert np.isfinite(float(metrics["mean_reward"]))


def test_graft_entry():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge

    fn, args = ge.entry()
    action, q = jax.jit(fn)(*args)
    assert action.shape == (8, 2)
    assert q.shape == (8, 1)
    ge.dryrun_multichip(8)
