"""Pallas fused dirty-imager kernel vs the XLA oracle (interpret mode on
the CPU mesh; the real-TPU path is exercised by the verify drives and
bench)."""

import jax
import numpy as np
import pytest

from smartcal_tpu.cal import imager
from smartcal_tpu.ops import pallas_imager


def _case(rng, R, freq=150e6):
    uvw = rng.uniform(-2e3, 2e3, size=(R, 3)).astype(np.float32)
    vis = rng.standard_normal((R, 2)).astype(np.float32)
    cell = imager.default_cell(uvw, freq)
    return uvw, vis, freq, cell


def test_matches_xla_oracle():
    rng = np.random.default_rng(0)
    npix = 32                                  # P=1024 = one TILE_P
    uvw, vis, freq, cell = _case(rng, R=700)   # forces R padding (2 tiles)
    ref = np.asarray(imager.dirty_image_sr(uvw, vis, freq, cell,
                                           npix=npix))
    out = np.asarray(pallas_imager.dirty_image_pallas(
        uvw, vis, freq, cell, npix=npix, interpret=True))
    assert out.shape == (npix, npix)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_multi_pixel_tiles():
    rng = np.random.default_rng(1)
    npix = 64                                  # P=4096 = 4 pixel tiles
    uvw, vis, freq, cell = _case(rng, R=512)   # exactly one R tile
    ref = np.asarray(imager.dirty_image_sr(uvw, vis, freq, cell,
                                           npix=npix))
    out = np.asarray(pallas_imager.dirty_image_pallas(
        uvw, vis, freq, cell, npix=npix, interpret=True))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_unaligned_npix_rejected_and_dispatch_falls_back():
    rng = np.random.default_rng(2)
    uvw, vis, freq, cell = _case(rng, R=64)
    with pytest.raises(ValueError):
        pallas_imager.dirty_image_pallas(uvw, vis, freq, cell, npix=8)
    # the central dispatcher routes to XLA on CPU and for unaligned sizes
    ref = np.asarray(imager.dirty_image_sr_xla(uvw, vis, freq, cell,
                                               npix=8))
    out = np.asarray(imager.dirty_image_sr(uvw, vis, freq, cell, npix=8))
    np.testing.assert_allclose(out, ref, rtol=1e-6)
    assert not pallas_imager.pallas_available()    # tests run on CPU
