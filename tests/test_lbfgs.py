"""Tests for the pure-functional L-BFGS core.

The reference has no tests; its implicit verification is "the elastic-net
solve converges" (enetenv.py:101-114).  We test convergence on quadratics
(known closed form), the elastic-net objective, the two-loop recursion
against an explicit dense BFGS inverse, and jittability.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smartcal_tpu.ops import (
    history_init,
    history_push,
    inv_hessian_mult,
    lbfgs_init,
    lbfgs_solve,
    lbfgs_step,
    two_loop_direction,
)


def quad_problem(n=10, seed=0):
    rng = np.random.default_rng(seed)
    L = rng.normal(size=(n, n)).astype(np.float32)
    A = L @ L.T + n * np.eye(n, dtype=np.float32)
    b = rng.normal(size=n).astype(np.float32)
    x_star = np.linalg.solve(A, b)

    def fun(x):
        return 0.5 * x @ (jnp.asarray(A) @ x) - jnp.asarray(b) @ x

    return fun, x_star, A, b


def test_quadratic_convergence():
    fun, x_star, _, _ = quad_problem(10)
    res = lbfgs_solve(fun, jnp.zeros(10), max_iters=100)
    np.testing.assert_allclose(np.asarray(res.x), x_star, atol=2e-3)


def test_rosenbrock():
    def fun(x):
        return jnp.sum(100.0 * (x[1:] - x[:-1] ** 2) ** 2
                       + (1.0 - x[:-1]) ** 2)

    res = lbfgs_solve(fun, jnp.zeros(4), max_iters=400)
    np.testing.assert_allclose(np.asarray(res.x), np.ones(4), atol=1e-2)


def test_elastic_net_objective():
    """The reference's actual inner solve (enetenv.py:96-114)."""
    rng = np.random.default_rng(3)
    N, M = 20, 20
    A = rng.normal(size=(N, M)).astype(np.float32)
    A /= np.linalg.norm(A)
    x0 = np.zeros(M, dtype=np.float32)
    x0[:5] = rng.normal(size=5)
    y = A @ x0 + 0.01 * rng.normal(size=N).astype(np.float32)
    lam1, lam2 = 1e-3, 1e-3
    Aj, yj = jnp.asarray(A), jnp.asarray(y)

    def fun(x):
        err = yj - Aj @ x
        return (jnp.sum(err ** 2) + lam1 * jnp.sum(x ** 2)
                + lam2 * jnp.sum(jnp.abs(x)))

    res = lbfgs_solve(fun, jnp.zeros(M), max_iters=200)
    # compare against scipy-equivalent solve via plain gradient descent proxy:
    # objective value must beat the zero vector and approach the ridge solution
    assert float(res.loss) < float(fun(jnp.zeros(M)))
    ridge = np.linalg.solve(A.T @ A + lam1 * np.eye(M), A.T @ y)
    assert float(fun(jnp.asarray(ridge))) >= float(res.loss) - 1e-5


def test_two_loop_matches_dense_bfgs():
    """Two-loop recursion == explicitly accumulated inverse-BFGS matrix."""
    n, m = 6, 4
    rng = np.random.default_rng(1)
    hist = history_init(n, m)
    pairs = []
    for _ in range(3):
        s = rng.normal(size=n).astype(np.float32)
        y = s + 0.1 * rng.normal(size=n).astype(np.float32)
        if float(np.dot(y, s)) <= 0:
            y = s
        pairs.append((s, y))
        hist = history_push(hist, jnp.asarray(s), jnp.asarray(y), True)

    # dense BFGS: H0 = gamma I, then recursive update oldest->newest
    s_l, y_l = pairs[-1]
    gamma = np.dot(y_l, s_l) / np.dot(y_l, y_l)
    H = gamma * np.eye(n)
    for s, y in pairs:
        rho = 1.0 / np.dot(y, s)
        V = np.eye(n) - rho * np.outer(s, y)
        H = V @ H @ V.T + rho * np.outer(s, s)

    g = rng.normal(size=n).astype(np.float32)
    d = two_loop_direction(hist, jnp.asarray(g))
    np.testing.assert_allclose(np.asarray(d), -H @ g, rtol=1e-4, atol=1e-5)

    # inv_hessian_mult is +H^{-1}q with the same history
    r = inv_hessian_mult(hist, jnp.asarray(g))
    np.testing.assert_allclose(np.asarray(r), H @ g, rtol=1e-4, atol=1e-5)


def test_inv_hessian_mult_empty_history_identity():
    hist = history_init(5, 7)
    q = jnp.arange(5.0)
    np.testing.assert_allclose(np.asarray(inv_hessian_mult(hist, q)),
                               np.arange(5.0), rtol=1e-6)


def test_curvature_rejection():
    """Pairs with ys <= 1e-10||s||^2 must not enter memory (lbfgsnew.py:610)."""
    hist = history_init(4, 3)
    s = jnp.ones(4)
    y = -jnp.ones(4)  # ys < 0
    h2 = history_push(hist, s, y, jnp.dot(y, s) > 1e-10 * jnp.dot(s, s))
    assert int(h2.count) == 0


def test_jit_and_grad_flow():
    fun, x_star, _, _ = quad_problem(8, seed=5)
    solve = jax.jit(lambda x0: lbfgs_solve(fun, x0, max_iters=50).x)
    out = solve(jnp.zeros(8))
    np.testing.assert_allclose(np.asarray(out), x_star, atol=2e-3)


def test_batch_mode_step_decreases_loss():
    """Stochastic mode: loss over fixed data decreases across step() calls."""
    rng = np.random.default_rng(7)
    A = jnp.asarray(rng.normal(size=(50, 10)).astype(np.float32))
    xtrue = jnp.asarray(rng.normal(size=10).astype(np.float32))
    y = A @ xtrue

    state = lbfgs_init(jnp.zeros(10))
    losses = []
    for i in range(8):
        # rotate "batches" of rows to exercise the batch-changed path
        idx = jnp.arange(25) + (i % 2) * 25

        def fun(x, A=A[idx], y=y[idx]):
            return jnp.mean((A @ x - y) ** 2)

        state, loss = lbfgs_step(fun, state, max_iter=4)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    final = float(jnp.mean((A @ state.x - y) ** 2))
    assert final < 1e-2 * float(jnp.mean(y ** 2))


def test_nan_divergence_not_reported_as_converged():
    """A solve that hits NaN stops but must not claim convergence."""
    def fun(x):
        return jnp.sum(jnp.log(x))  # NaN gradient for x <= 0

    res = lbfgs_solve(fun, -jnp.ones(3), max_iters=50)
    assert not bool(res.converged)


def test_solve_reports_convergence_on_trivial_problem():
    res = lbfgs_solve(lambda x: jnp.sum((x - 1.0) ** 2), jnp.zeros(3),
                      max_iters=100)
    assert bool(res.converged)
    assert int(res.n_iters) < 100


def test_resume_walks_identical_trajectory():
    """solve(N) == solve(k) + resume chain (exact segmented dispatch parity
    — what solve_admm_host relies on), including the stop flag short-circuit."""
    from smartcal_tpu.ops.lbfgs import lbfgs_resume

    rng = np.random.default_rng(5)
    A = jnp.asarray(rng.standard_normal((40, 12)), jnp.float32)
    y = jnp.asarray(rng.standard_normal(40), jnp.float32)

    def fun(x):
        return jnp.mean((A @ x - y) ** 2) + 0.05 * jnp.sum(x * x)

    full = lbfgs_solve(fun, jnp.zeros(12), max_iters=21)
    seg = lbfgs_solve(fun, jnp.zeros(12), max_iters=8)
    seg = lbfgs_resume(fun, seg, 8)
    seg = lbfgs_resume(fun, seg, 5)
    np.testing.assert_array_equal(np.asarray(seg.x), np.asarray(full.x))
    assert int(seg.n_iters) == int(full.n_iters)
    assert bool(seg.converged) == bool(full.converged)

    # resume past convergence is a no-op
    conv = lbfgs_solve(fun, jnp.zeros(12), max_iters=200)
    again = lbfgs_resume(fun, conv, 10)
    assert int(again.n_iters) == int(conv.n_iters)
    np.testing.assert_array_equal(np.asarray(again.x), np.asarray(conv.x))
