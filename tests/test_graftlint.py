"""graftlint framework + rule tests (ISSUE 11).

Three layers:

* per-rule positive/negative fixtures under ``tests/fixtures/lint/``
  (each rule must catch every planted bug and stay silent on the
  disciplined twin);
* framework behavior — suppression comments (reason mandatory),
  baseline grandfathering/staleness, deterministic output, CLI exit
  codes, ``--types`` audit;
* THE GATE: the shipped tree must lint clean against the checked-in
  baseline, fast enough to stay cheap relative to the tier-1 budget,
  and a seeded violation must fail it.
"""

import json
import os
import shutil
import subprocess
import sys
import time

import pytest

from smartcal_tpu import analysis
from smartcal_tpu.analysis import baseline as bl
from smartcal_tpu.analysis import typecheck

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(ROOT, "tests", "fixtures", "lint")
LINT_CLI = os.path.join(ROOT, "tools", "lint.py")


def fixture_findings(name, rule=None, options=None):
    fs = analysis.lint_file(os.path.join(FIX, name), ROOT, options=options)
    if rule is not None:
        fs = [f for f in fs if f.rule == rule]
    return fs


def lines_of(findings):
    return sorted({f.line for f in findings})


# ---------------------------------------------------------------------------
# per-rule fixtures
# ---------------------------------------------------------------------------

def test_rng_rule_positive():
    fs = fixture_findings("rng_bad.py", "rng-key-reuse")
    assert lines_of(fs) == [7, 14, 20, 27, 35], fs


def test_rng_rule_negative():
    assert fixture_findings("rng_good.py", "rng-key-reuse") == []


def test_donation_rule_positive():
    fs = fixture_findings("donation_bad.py", "read-after-donation")
    assert lines_of(fs) == [17, 22, 27, 34], fs


def test_donation_rule_negative():
    assert fixture_findings("donation_good.py", "read-after-donation") == []


def test_jit_sync_rule_positive():
    fs = fixture_findings("jit_sync_bad.py", "host-sync-in-jit")
    assert lines_of(fs) == [12, 18, 23, 29, 35, 40, 46, 56], fs


def test_jit_sync_rule_negative():
    assert fixture_findings("jit_sync_good.py", "host-sync-in-jit") == []


def test_static_flag_rule_positive():
    fs = fixture_findings("static_flag_bad.py", "traced-static-flag")
    assert lines_of(fs) == [10, 14, 19, 23], fs


def test_static_flag_rule_negative():
    assert fixture_findings("static_flag_good.py",
                            "traced-static-flag") == []


_DTYPE_OPTS_BAD = {"dtype_policied_paths": ("dtype_bad.py",)}
_DTYPE_OPTS_GOOD = {"dtype_policied_paths": ("dtype_good.py",)}


def test_dtype_rule_positive():
    fs = fixture_findings("dtype_bad.py", "dtype-discipline",
                          _DTYPE_OPTS_BAD)
    assert lines_of(fs) == [8, 12, 16], fs


def test_dtype_rule_negative():
    assert fixture_findings("dtype_good.py", "dtype-discipline",
                            _DTYPE_OPTS_GOOD) == []


def test_dtype_rule_scoped_to_policied_modules():
    """The same bad literals OUTSIDE the policied module list are not
    findings — dtype choices elsewhere are not the policy's business."""
    assert fixture_findings("dtype_bad.py", "dtype-discipline",
                            {"dtype_policied_paths":
                             ("smartcal_tpu/cal/imager.py",)}) == []


def test_dtype_rule_policy_module_exempt():
    assert fixture_findings("dtype_bad.py", "dtype-discipline",
                            {"dtype_policied_paths": ("dtype_bad.py",),
                             "dtype_exempt_paths": ("dtype_bad.py",)}) \
        == []


_MESH_OPTS_BAD = {"mesh_axis_policied_prefixes": ("tests/fixtures",)}


def test_mesh_axis_rule_positive():
    fs = fixture_findings("mesh_axis_bad.py", "mesh-axis-literal",
                          _MESH_OPTS_BAD)
    assert lines_of(fs) == [8, 10, 14, 17, 24, 28], fs
    # the make_mesh axis tuple plants TWO literals on one line
    assert len(fs) == 7, fs


def test_mesh_axis_rule_negative():
    assert fixture_findings("mesh_axis_good.py", "mesh-axis-literal",
                            _MESH_OPTS_BAD) == []


def test_mesh_axis_rule_scoped_and_registry_exempt():
    """Outside the policed prefixes (tests spell axes literally on
    purpose) and inside the registry itself, literals are not findings."""
    assert fixture_findings("mesh_axis_bad.py", "mesh-axis-literal",
                            {"mesh_axis_policied_prefixes":
                             ("smartcal_tpu/",)}) == []
    assert fixture_findings("mesh_axis_bad.py", "mesh-axis-literal",
                            dict(_MESH_OPTS_BAD,
                                 mesh_axis_exempt_paths=(
                                     "mesh_axis_bad.py",))) == []


def test_mesh_axis_rule_clean_tree():
    """THE GATE for ISSUE 17 satellite 2: the shipped package and tools
    spell every mesh axis through the registry (or carry a reasoned
    disable) — zero findings at default scope."""
    fs = [f for f in analysis.lint_paths(["smartcal_tpu", "tools"], ROOT)
          if f.rule == "mesh-axis-literal"]
    assert fs == [], fs


_LOCK_SPEC = {"class": "Fleet",
              "fields": ["_weights", "_version", "_queue"],
              "locks": ["_wlock"], "why": "fixture"}


def test_locks_rule_positive():
    opts = {"shared_specs": [dict(_LOCK_SPEC, path="locks_bad.py")]}
    fs = fixture_findings("locks_bad.py", "unlocked-shared-write", opts)
    assert lines_of(fs) == [17, 18, 21, 25, 28], fs


def test_locks_rule_negative():
    opts = {"shared_specs": [dict(_LOCK_SPEC, path="locks_good.py")]}
    assert fixture_findings("locks_good.py", "unlocked-shared-write",
                            opts) == []


# the ISSUE 12 cross-process fields: shard directory + slot->shard map
# (Fleet) and the latest-wins weights outbox (ProcessActor) — mirrors
# the shipped SHARED_FIELD_SPECS rows
def _shard_specs(path):
    return [
        {"path": path, "class": "Fleet",
         "fields": ["_shard_qs", "_slot_shard"], "locks": ["_wlock"],
         "why": "fixture"},
        {"path": path, "class": "ProcessActor",
         "fields": ["_outbox"], "locks": ["_outbox_lock"],
         "why": "fixture"},
    ]


def test_locks_shard_rule_positive():
    opts = {"shared_specs": _shard_specs("locks_shard_bad.py")}
    fs = fixture_findings("locks_shard_bad.py", "unlocked-shared-write",
                          opts)
    assert lines_of(fs) == [19, 22, 25, 26, 35], fs


def test_locks_shard_rule_negative():
    opts = {"shared_specs": _shard_specs("locks_shard_good.py")}
    assert fixture_findings("locks_shard_good.py",
                            "unlocked-shared-write", opts) == []


# the ISSUE 15 serving fields: latest-executable table + breaker flag
# + stats (CalibServer) and admission counters + service-time EWMA
# (MicroBatcher) — mirrors the shipped SHARED_FIELD_SPECS rows
def _serve_specs(path):
    return [
        {"path": path, "class": "CalibServer",
         "fields": ["_programs", "_circuit_open", "_stats"],
         "locks": ["_lock"], "why": "fixture"},
        {"path": path, "class": "MicroBatcher",
         "fields": ["_accepted", "_shed", "_service_est_s"],
         "locks": ["_lock"], "why": "fixture"},
    ]


def test_locks_serve_rule_positive():
    opts = {"shared_specs": _serve_specs("locks_serve_bad.py")}
    fs = fixture_findings("locks_serve_bad.py", "unlocked-shared-write",
                          opts)
    assert lines_of(fs) == [21, 24, 27, 28, 39, 42], fs


def test_locks_serve_rule_negative():
    opts = {"shared_specs": _serve_specs("locks_serve_good.py")}
    assert fixture_findings("locks_serve_good.py",
                            "unlocked-shared-write", opts) == []


def test_shipped_shared_specs_cover_cross_process_fields():
    """The SHIPPED spec table must keep the ISSUE 12 rows: the shard
    directory / slot->shard map and the process-actor outbox — dropping
    a row silently un-guards the concurrency surface."""
    from smartcal_tpu.analysis.rules.locks import SHARED_FIELD_SPECS

    fields = {f for s in SHARED_FIELD_SPECS
              if s["path"].endswith("supervisor.py")
              for f in s["fields"]}
    assert {"_shard_qs", "_slot_shard", "_outbox"} <= fields


def test_shipped_shared_specs_cover_serving_fields():
    """The SHIPPED spec table must keep the ISSUE 15 rows: the server's
    latest-executable table / breaker flag / stats and the batcher's
    admission counters + service-time EWMA."""
    from smartcal_tpu.analysis.rules.locks import SHARED_FIELD_SPECS

    fields = {f for s in SHARED_FIELD_SPECS
              if "smartcal_tpu/serve/" in s["path"]
              for f in s["fields"]}
    assert {"_programs", "_circuit_open", "_stats",
            "_accepted", "_shed", "_service_est_s"} <= fields


# the ISSUE 16 fleet fields: replica table + fleet counters + slot
# bookkeeping (FleetRouter) and the in-flight pending table + gauges
# (_Replica) — mirrors the shipped SHARED_FIELD_SPECS rows
def _fleet_specs(path):
    return [
        {"path": path, "class": "FleetRouter",
         "fields": ["_replicas", "_stats", "_next_rid", "_retired"],
         "locks": ["_lock"], "why": "fixture"},
        {"path": path, "class": "Replica",
         "fields": ["_pending", "_gauges"],
         "locks": ["_lock"], "why": "fixture"},
    ]


def test_locks_fleet_rule_positive():
    opts = {"shared_specs": _fleet_specs("locks_fleet_bad.py")}
    fs = fixture_findings("locks_fleet_bad.py", "unlocked-shared-write",
                          opts)
    assert lines_of(fs) == [22, 23, 26, 27, 30, 40, 43, 47], fs


def test_locks_fleet_rule_negative():
    opts = {"shared_specs": _fleet_specs("locks_fleet_good.py")}
    assert fixture_findings("locks_fleet_good.py",
                            "unlocked-shared-write", opts) == []


def test_shipped_shared_specs_cover_fleet_fields():
    """The SHIPPED spec table must keep the ISSUE 16 rows: the router's
    replica table / fleet counters / slot bookkeeping and each replica
    handle's in-flight pending table + gauges."""
    from smartcal_tpu.analysis.rules.locks import SHARED_FIELD_SPECS

    fields = {f for s in SHARED_FIELD_SPECS
              if s["path"].endswith("serve/fleet.py")
              for f in s["fields"]}
    assert {"_replicas", "_stats", "_next_rid", "_retired",
            "_pending", "_gauges"} <= fields


# the ISSUE 18 observability fields: the crash flight-recorder ring,
# the SLO burn-rate windows, and the timeline-merger state — mirrors
# the shipped SHARED_FIELD_SPECS rows
def _obs_specs(path):
    return [
        {"path": path, "class": "FlightRecorder",
         "fields": ["_ring", "_flushes", "_n_flushes"],
         "locks": ["_lock"], "why": "fixture"},
        {"path": path, "class": "SloBurnDetector",
         "fields": ["_obs", "_state"],
         "locks": ["_lock"], "why": "fixture"},
        {"path": path, "class": "TimelineMerger",
         "fields": ["_streams", "_offsets", "_n_corrupt"],
         "locks": ["_lock"], "why": "fixture"},
    ]


def test_locks_obs_rule_positive():
    opts = {"shared_specs": _obs_specs("locks_obs_bad.py")}
    fs = fixture_findings("locks_obs_bad.py", "unlocked-shared-write",
                          opts)
    assert lines_of(fs) == [22, 25, 26, 36, 39, 50, 51, 52], fs


def test_locks_obs_rule_negative():
    opts = {"shared_specs": _obs_specs("locks_obs_good.py")}
    assert fixture_findings("locks_obs_good.py",
                            "unlocked-shared-write", opts) == []


def test_shipped_shared_specs_cover_obs_fields():
    """The SHIPPED spec table must keep the ISSUE 18 rows: the
    flight-recorder ring + flush bookkeeping, the burn-rate detector's
    observation window + latch state, the timeline merger's
    stream/offset tables, and the parent-side received-frame ring on
    the replica handle."""
    from smartcal_tpu.analysis.rules.locks import SHARED_FIELD_SPECS

    obs_fields = {f for s in SHARED_FIELD_SPECS
                  if "smartcal_tpu/obs/" in s["path"]
                  for f in s["fields"]}
    assert {"_ring", "_flushes", "_n_flushes", "_shed_times",
            "_obs", "_state",
            "_streams", "_offsets", "_n_corrupt"} <= obs_fields
    fleet_fields = {f for s in SHARED_FIELD_SPECS
                    if s["path"].endswith("serve/fleet.py")
                    for f in s["fields"]}
    assert "_frames" in fleet_fields


# the ISSUE 19 regression-radar fields: the baseline-store document +
# dirty flag and the server's numerics-sentinel snapshot handoff +
# counters — mirrors the shipped SHARED_FIELD_SPECS rows
def _radar_specs(path):
    return [
        {"path": path, "class": "BaselineStore",
         "fields": ["_doc", "_dirty"],
         "locks": ["_lock"], "why": "fixture"},
        {"path": path, "class": "CalibServer",
         "fields": ["_sentinel_pending", "_sentinel_stats"],
         "locks": ["_lock"], "why": "fixture"},
    ]


def test_locks_radar_rule_positive():
    opts = {"shared_specs": _radar_specs("locks_radar_bad.py")}
    fs = fixture_findings("locks_radar_bad.py", "unlocked-shared-write",
                          opts)
    assert lines_of(fs) == [20, 21, 24, 25, 35, 36, 40], fs


def test_locks_radar_rule_negative():
    opts = {"shared_specs": _radar_specs("locks_radar_good.py")}
    assert fixture_findings("locks_radar_good.py",
                            "unlocked-shared-write", opts) == []


def test_shipped_shared_specs_cover_radar_fields():
    """The SHIPPED spec table must keep the ISSUE 19 rows: the perf
    baseline store's document + dirty flag and the serving sentinel's
    latest-wins snapshot + counters."""
    from smartcal_tpu.analysis.rules.locks import SHARED_FIELD_SPECS

    store_fields = {f for s in SHARED_FIELD_SPECS
                    if s["path"].endswith("obs/baselines.py")
                    for f in s["fields"]}
    assert {"_doc", "_dirty"} <= store_fields
    server_fields = {f for s in SHARED_FIELD_SPECS
                     if s["path"].endswith("serve/server.py")
                     for f in s["fields"]}
    assert {"_sentinel_pending", "_sentinel_stats"} <= server_fields


def _lint_as_package(tmp_path, *names):
    """Copy fixtures under a fake smartcal_tpu/ so path-scoped rules
    (pickle outside tests/, bare-print) see them as package code."""
    pkg = tmp_path / "smartcal_tpu"
    pkg.mkdir(exist_ok=True)
    for n in names:
        shutil.copy(os.path.join(FIX, n), pkg / n)
    return analysis.lint_paths(["smartcal_tpu"], str(tmp_path))


def test_pickle_rule_positive(tmp_path):
    fs = [f for f in _lint_as_package(tmp_path, "pickle_bad.py")
          if f.rule == "unguarded-pickle-load"]
    assert lines_of(fs) == [7, 12, 13], fs


def test_pickle_rule_negative(tmp_path):
    fs = [f for f in _lint_as_package(tmp_path, "pickle_good.py")
          if f.rule == "unguarded-pickle-load"]
    assert fs == []


def test_bare_print_rule_positive(tmp_path):
    fs = [f for f in _lint_as_package(tmp_path, "print_bad.py")
          if f.rule == "bare-print"]
    assert lines_of(fs) == [5, 11], fs


def test_bare_print_rule_negative(tmp_path):
    fs = [f for f in _lint_as_package(tmp_path, "print_good.py")
          if f.rule == "bare-print"]
    assert fs == []


def test_pickle_rule_exempts_test_code(tmp_path):
    tdir = tmp_path / "tests"
    tdir.mkdir()
    shutil.copy(os.path.join(FIX, "pickle_bad.py"),
                tdir / "test_pickle_stuff.py")
    fs = analysis.lint_paths(["tests"], str(tmp_path))
    assert [f for f in fs if f.rule == "unguarded-pickle-load"] == []


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

def test_suppression_with_reason_silences():
    assert fixture_findings("suppress_ok.py") == []


def test_suppression_without_reason_is_a_finding():
    fs = fixture_findings("suppress_bad.py")
    rules = sorted(f.rule for f in fs)
    # the reasonless disable does NOT disable (the rng finding stays)
    # and is itself reported; the unknown-rule disable is reported too
    assert rules == ["bad-suppression", "bad-suppression",
                     "rng-key-reuse"], fs


def test_rules_subset_does_not_misflag_other_suppressions(tmp_path):
    # a valid disable for rule B must not become "unknown rule" when
    # only rule A is selected
    rules = analysis.all_rules()
    subset = {"read-after-donation": rules["read-after-donation"]}
    fs = analysis.lint_file(os.path.join(FIX, "suppress_ok.py"), ROOT,
                            rules=subset)
    assert fs == []


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def _some_findings():
    return fixture_findings("rng_bad.py", "rng-key-reuse")


def test_baseline_grandfathers_and_detects_stale(tmp_path):
    fs = _some_findings()
    path = str(tmp_path / "base.json")
    bl.save(path, fs, default_reason="fixture corpus")
    loaded = bl.load(path)
    assert len(loaded) == len(fs)
    new, old, stale = bl.split(fs, loaded)
    assert new == [] and len(old) == len(fs) and stale == []
    # drop one finding -> exactly one stale entry surfaces
    new, old, stale = bl.split(fs[1:], loaded)
    assert new == [] and len(stale) == 1


def test_malformed_baseline_is_exit_2_not_findings(tmp_path):
    mangled = tmp_path / "mangled.json"
    mangled.write_text("{not json")
    with pytest.raises(bl.BaselineError):
        bl.load(str(mangled))
    p = _cli("--baseline", str(mangled), "smartcal_tpu")
    assert p.returncode == 2, p.stdout + p.stderr
    assert "unreadable baseline" in p.stderr
    # entry missing required keys is also a BaselineError, not KeyError
    partial = tmp_path / "partial.json"
    partial.write_text(json.dumps(
        {"version": 1, "entries": [{"rule": "bare-print",
                                    "reason": "x"}]}))
    with pytest.raises(bl.BaselineError):
        bl.load(str(partial))


def test_baseline_requires_reason(tmp_path):
    path = str(tmp_path / "base.json")
    doc = {"version": 1, "entries": [
        {"rule": "rng-key-reuse", "path": "x.py", "fingerprint": "ab#0",
         "line": 1, "source": "s", "reason": "   "}]}
    with open(path, "w") as fh:
        json.dump(doc, fh)
    with pytest.raises(bl.BaselineError):
        bl.load(path)


def test_fingerprints_distinguish_duplicate_lines(tmp_path):
    # two byte-identical violating lines must get distinct fingerprints,
    # so baselining one does not cover a copy-pasted second
    f = tmp_path / "dup.py"
    f.write_text("import jax\n\n\ndef g(key):\n"
                 "    a = jax.random.normal(key, (2,))\n"
                 "    b = jax.random.normal(key, (2,))\n"
                 "    b = jax.random.normal(key, (2,))\n"
                 "    return a + b\n")
    fs = analysis.lint_file(str(f), str(tmp_path))
    fs = [x for x in fs if x.rule == "rng-key-reuse"]
    assert len(fs) == 2
    fps = bl.fingerprints(fs)
    assert len(set(fps)) == 2 and all("#" in fp for fp in fps)


# ---------------------------------------------------------------------------
# determinism + the gate
# ---------------------------------------------------------------------------

def _gate_findings():
    findings = analysis.lint_paths(["smartcal_tpu", "tools", "tests"],
                                   ROOT)
    baseline = bl.load(os.path.join(ROOT, bl.DEFAULT_BASELINE))
    new, _old, _stale = bl.split(findings, baseline)
    return new


def test_determinism_two_runs_identical_json():
    a = [f.as_dict() for f in analysis.lint_paths(["smartcal_tpu"], ROOT)]
    b = [f.as_dict() for f in analysis.lint_paths(["smartcal_tpu"], ROOT)]
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def test_gate_repo_is_clean_and_fast():
    """THE tier-1 gate: no non-baselined finding in the shipped tree,
    in well under the 30 s budget the acceptance criteria set."""
    t0 = time.monotonic()
    new = _gate_findings()
    elapsed = time.monotonic() - t0
    assert new == [], "\n".join(f.render() for f in new)
    assert elapsed < 30.0, f"lint gate took {elapsed:.1f}s (budget 30s)"


def test_gate_catches_seeded_violation(tmp_path):
    """The gate must FAIL when a violation lands in a scanned tree —
    proven by seeding a copy with a known-bad fixture."""
    pkg = tmp_path / "smartcal_tpu"
    shutil.copytree(os.path.join(ROOT, "smartcal_tpu", "runtime"),
                    pkg / "runtime")
    shutil.copy(os.path.join(FIX, "rng_bad.py"),
                pkg / "runtime" / "seeded_violation.py")
    findings = analysis.lint_paths(["smartcal_tpu"], str(tmp_path))
    baseline = bl.load(os.path.join(ROOT, bl.DEFAULT_BASELINE))
    new, _old, _stale = bl.split(findings, baseline)
    assert any(f.rule == "rng-key-reuse"
               and f.path.endswith("seeded_violation.py") for f in new), new


def test_fixture_corpus_is_excluded_from_directory_walks():
    files = list(analysis.iter_python_files(["tests"], ROOT))
    assert not any("fixtures" + os.sep + "lint" in f or
                   "fixtures/lint" in f for f in files)


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------

def _cli(*args, cwd=ROOT):
    return subprocess.run([sys.executable, LINT_CLI, *args],
                          capture_output=True, text=True, cwd=cwd)


def test_cli_exit_codes_and_json():
    bad = os.path.join("tests", "fixtures", "lint", "rng_bad.py")
    good = os.path.join("tests", "fixtures", "lint", "rng_good.py")
    p = _cli("--json", "--no-baseline", bad)
    assert p.returncode == 1, p.stdout + p.stderr
    doc = json.loads(p.stdout)
    assert doc["new"] > 0
    assert all(f["rule"] == "rng-key-reuse" for f in doc["findings"])
    p = _cli("--json", "--no-baseline", good)
    assert p.returncode == 0, p.stdout + p.stderr
    assert json.loads(p.stdout)["new"] == 0


def test_cli_list_rules_names_all_six_plus_meta():
    p = _cli("--list-rules", "--json")
    assert p.returncode == 0
    names = {r["name"] for r in json.loads(p.stdout)["rules"]}
    for want in ("rng-key-reuse", "read-after-donation",
                 "host-sync-in-jit", "traced-static-flag",
                 "unlocked-shared-write", "unguarded-pickle-load",
                 "bare-print", "bad-suppression", "parse-error"):
        assert want in names, names


def test_cli_unknown_rule_is_usage_error():
    p = _cli("--rules", "no-such-rule", "--no-baseline")
    assert p.returncode == 2


def test_cli_changed_mode(tmp_path):
    """--changed lints only git-touched files, from a scratch repo."""
    repo = tmp_path / "repo"
    repo.mkdir()
    env = dict(os.environ,
               GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
               GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")
    subprocess.run(["git", "init", "-q"], cwd=repo, check=True, env=env)
    clean = repo / "clean.py"
    clean.write_text("x = 1\n")
    subprocess.run(["git", "add", "clean.py"], cwd=repo, check=True,
                   env=env)
    subprocess.run(["git", "commit", "-qm", "seed"], cwd=repo, check=True,
                   env=env)
    # untracked file with a violation -> --changed must catch it
    shutil.copy(os.path.join(FIX, "rng_bad.py"), repo / "touched.py")
    p = subprocess.run([sys.executable, LINT_CLI, "--changed", "--json",
                        "--root", str(repo)],
                       capture_output=True, text=True, cwd=repo)
    assert p.returncode == 1, p.stdout + p.stderr
    doc = json.loads(p.stdout)
    assert {f["path"] for f in doc["findings"]} == {"touched.py"}
    # clean worktree -> exit 0, nothing checked
    (repo / "touched.py").unlink()
    p = subprocess.run([sys.executable, LINT_CLI, "--changed", "--json",
                        "--root", str(repo)],
                       capture_output=True, text=True, cwd=repo)
    assert p.returncode == 0 and json.loads(p.stdout)["checked"] == 0


def test_changed_mode_skips_fixture_corpus(tmp_path):
    """--changed must apply the corpus exclusion: a touched
    intentional-violation fixture never fails the pre-commit path."""
    repo = tmp_path / "repo"
    (repo / "tests" / "fixtures" / "lint").mkdir(parents=True)
    env = dict(os.environ,
               GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
               GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")
    subprocess.run(["git", "init", "-q"], cwd=repo, check=True, env=env)
    subprocess.run(["git", "commit", "-qm", "s", "--allow-empty"],
                   cwd=repo, check=True, env=env)
    shutil.copy(os.path.join(FIX, "rng_bad.py"),
                repo / "tests" / "fixtures" / "lint" / "rng_bad.py")
    p = subprocess.run([sys.executable, LINT_CLI, "--changed", "--json",
                        "--root", str(repo)],
                       capture_output=True, text=True, cwd=repo)
    assert p.returncode == 0, p.stdout + p.stderr
    assert json.loads(p.stdout)["checked"] == 0


def test_suppression_inside_string_is_inert(tmp_path):
    """A docstring QUOTING the disable syntax must not suppress."""
    f = tmp_path / "doc.py"
    f.write_text('"""Docs show: # graftlint: disable-file=rng-key-reuse'
                 ' -- example only."""\nimport jax\n\n\ndef g(key):\n'
                 "    a = jax.random.normal(key, (2,))\n"
                 "    b = jax.random.normal(key, (2,))\n"
                 "    return a + b\n")
    fs = analysis.lint_file(str(f), str(tmp_path))
    assert any(x.rule == "rng-key-reuse" for x in fs), fs


def test_update_baseline_refuses_partial_scope():
    p = _cli("--update-baseline", "smartcal_tpu")
    assert p.returncode == 2 and "full default scope" in p.stderr
    p = _cli("--update-baseline", "--changed")
    assert p.returncode == 2


def test_unreadable_file_is_parse_error_not_crash(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_bytes(b"\xff\xfe broken bytes \x00\x01")
    fs = analysis.lint_file(str(bad), str(tmp_path))
    assert [f.rule for f in fs] == ["parse-error"], fs


def test_bad_suppression_cannot_be_baselined(tmp_path):
    src = ("import jax\n\n\ndef g(key):\n"
           "    a = jax.random.normal(key, (2,))"
           "  # graftlint: disable=rng-key-reuse\n"
           "    return a\n")
    f = tmp_path / "mod.py"
    f.write_text(src)
    fs = analysis.lint_file(str(f), str(tmp_path))
    assert any(x.rule == "bad-suppression" for x in fs)
    path = str(tmp_path / "base.json")
    bl.save(path, fs)                      # must drop the meta-finding
    new, old, _stale = bl.split(fs, bl.load(path))
    assert any(x.rule == "bad-suppression" for x in new)
    assert not any(x.rule == "bad-suppression" for x in old)


def test_stale_reporting_scoped_to_scanned_files():
    fs = _some_findings()
    base = {("rng-key-reuse", "other/file.py", "dead#0"): "out of scope"}
    base.update({(f.rule, f.path, fp): "r"
                 for f, fp in zip(fs, bl.fingerprints(fs))})
    # subset run that never scanned other/file.py -> not stale
    _new, _old, stale = bl.split(fs, base,
                                 scanned_paths=[fs[0].path])
    assert stale == []
    # full-scope semantics (scanned includes it) -> stale
    _new, _old, stale = bl.split(fs, base,
                                 scanned_paths=[fs[0].path,
                                                "other/file.py"])
    assert len(stale) == 1


def test_exclusion_respects_component_boundaries():
    from smartcal_tpu.analysis.core import is_excluded
    assert is_excluded(os.path.join(ROOT, "tests", "fixtures", "lint",
                                    "rng_bad.py"))
    assert not is_excluded(os.path.join(ROOT, "tests", "fixtures",
                                        "linty.py"))
    assert not is_excluded(os.path.join(ROOT, "tests", "fixtures",
                                        "lint_utils", "helper.py"))


def test_changed_mode_with_types_still_runs_types_gate(tmp_path):
    """`--changed --types` on a clean worktree must still run the typed
    core (exit 1 when the audit finds un-annotated strict-core defs)."""
    repo = tmp_path / "repo"
    (repo / "smartcal_tpu" / "obs").mkdir(parents=True)
    (repo / "smartcal_tpu" / "obs" / "x.py").write_text(
        "def public_fn(a):\n    return a\n")
    env = dict(os.environ,
               GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
               GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")
    subprocess.run(["git", "init", "-q"], cwd=repo, check=True, env=env)
    subprocess.run(["git", "add", "-A"], cwd=repo, check=True, env=env)
    subprocess.run(["git", "commit", "-qm", "s"], cwd=repo, check=True,
                   env=env)
    p = subprocess.run([sys.executable, LINT_CLI, "--changed", "--types",
                        "--json", "--root", str(repo)],
                       capture_output=True, text=True, cwd=repo)
    assert p.returncode == 1, p.stdout + p.stderr
    doc = json.loads(p.stdout)
    assert doc["types_mode"] in ("audit", "mypy")
    assert doc["new"] > 0 and doc["checked"] == 0


def test_stale_reporting_scoped_to_rules_run():
    fs = _some_findings()
    base = {("bare-print", fs[0].path, "dead#0"): "other rule's debt"}
    # rng-only run: the bare-print entry's rule never executed -> not stale
    _n, _o, stale = bl.split(fs, base, scanned_paths=[fs[0].path],
                             rules_run=["rng-key-reuse"])
    assert stale == []
    _n, _o, stale = bl.split(fs, base, scanned_paths=[fs[0].path],
                             rules_run=["rng-key-reuse", "bare-print"])
    assert len(stale) == 1


# ---------------------------------------------------------------------------
# --types gate
# ---------------------------------------------------------------------------

def test_types_audit_strict_core_is_clean():
    findings = typecheck.run_audit(ROOT)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_types_audit_catches_untyped_public_def(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text("def public_fn(a, b):\n    return a\n\n\n"
                 "def _private(a):\n    return a\n")
    fs = typecheck.audit_file(str(f), str(tmp_path))
    assert {x.rule for x in fs} == {typecheck.UNTYPED_DEF}
    # params a, b + missing return = 3 findings; _private exempt
    assert len(fs) == 3, fs
