"""Contract tests for the casacore branch of the MS data edge.

VERDICT r3 item 6: python-casacore cannot be installed in this image, so
``cal/ms_io.py``'s real-MS adapter (``_casa_*``, the LINC-facing entry)
had never executed.  These tests drive those exact code paths against
``tests/fake_casacore.py`` — a STRICT emulation of the casacore.tables
API serving the real LOFAR MS layout pinned in
``tests/fixtures/lofar_ms_layout.json`` (row axis first, (nchan, ncorr)
data cells, autocorrelation rows, baseline order shuffled within each
time block).  If the adapter drifts from that layout — wrong axis order,
an undeclared column, relying on storage row order — these fail.

On a host WITH python-casacore the same adapter runs against real tables;
one-command check there:
    python -m pytest tests/test_ms_io.py tests/test_ms_casacore_contract.py -q \
        && python -m smartcal_tpu.train.evaluate --selftest
(the contract tests keep using the fake, so they validate the adapter
even where casacore is present; reference behavior being matched:
calibration/casa_io.py:9-72, generate_data.py:623-681,727-746,877-887.)
"""

import numpy as np
import pytest

import fake_casacore as fc
from smartcal_tpu.cal import ms_io

N_ST = 7
N_T = 4
NCHAN = 8
B = N_ST * (N_ST - 1) // 2


@pytest.fixture()
def casa_ms(tmp_path, monkeypatch):
    """A fake-casacore LOFAR MS + ms_io patched to see it as real."""
    monkeypatch.setattr(ms_io, "_ctab", fc)
    path = str(tmp_path / "L123_SB000.MS")
    fc.make_lofar_ms(path, n_stations=N_ST, n_times=N_T, nchan=NCHAN)
    yield path
    fc.REGISTRY.clear()


def _expected_sorted_pattern():
    """value_pattern over the sorted (TIME, p<q) cross rows."""
    p, q = np.triu_indices(N_ST, 1)
    vals = [fc.value_pattern(t, p, q) for t in range(N_T)]
    return np.concatenate(vals)


def test_ms_info_reads_real_layout(casa_ms):
    info = ms_io.ms_info(casa_ms)
    assert info.n_stations == N_ST
    assert info.n_baselines == B
    assert info.n_times == N_T           # autocorr rows counted correctly
    assert info.n_chan == NCHAN
    assert info.freqs.shape == (NCHAN,)
    assert info.freqs[0] == pytest.approx(120e6)
    assert info.ref_freq == pytest.approx(float(info.freqs.mean()))
    assert info.ra0 == pytest.approx(1.2)     # PHASE_DIR (nfield, 1, 2)
    assert info.dec0 == pytest.approx(0.9)
    assert info.t0 == pytest.approx(fc.LAYOUT["typical"]["time_epoch_s"])
    assert info.interval == pytest.approx(fc.LAYOUT["typical"]["interval_s"])


def test_read_corr_sorts_and_takes_channel0(casa_ms):
    """The storage shuffles baselines within each time block; read_corr
    must return TIME-major, ANTENNA-sorted cross rows of channel 0."""
    uu, vv, ww, xx, xy, yx, yy = ms_io.read_corr(casa_ms, "DATA")
    assert uu.shape == (N_T * B,)
    assert xx.dtype == np.csingle
    want = _expected_sorted_pattern()
    np.testing.assert_allclose(xx.real, want, rtol=1e-6)
    # channel 0: imaginary part encodes the channel index
    np.testing.assert_allclose(xx.imag, 0.0, atol=1e-6)
    # corr axis: XY offset by +0.25 from XX (cell layout (nchan, ncorr))
    np.testing.assert_allclose((xy - xx).real, 0.25, rtol=1e-5)
    p, q = np.triu_indices(N_ST, 1)
    np.testing.assert_allclose(uu, np.tile((p - q) * 100.0, N_T), rtol=1e-6)


def test_add_column_then_write_corr_roundtrip(casa_ms):
    """add_column clones the DATA descriptor; write_corr broadcasts the
    channel-0 values over all channels through the sorted-query mapping."""
    ms_io.add_column(casa_ms, "CORRECTED_DATA")
    store = fc.REGISTRY[casa_ms]
    assert store.main["CORRECTED_DATA"].shape == \
        store.main["DATA"].shape
    assert store.main["CORRECTED_DATA"].dtype == np.complex64

    vals = _expected_sorted_pattern().astype(np.csingle)
    ms_io.write_corr(casa_ms, vals, 2 * vals, 3 * vals, 4 * vals,
                     colname="CORRECTED_DATA")
    # read back through the adapter: same sorted view
    _, _, _, xx, xy, yx, yy = ms_io.read_corr(casa_ms, "CORRECTED_DATA")
    np.testing.assert_allclose(xx, vals, rtol=1e-6)
    np.testing.assert_allclose(yy, 4 * vals, rtol=1e-6)
    # every channel carries the channel-0 value (casa_io.py:46-72), and
    # autocorrelation rows stay zero
    col = store.main["CORRECTED_DATA"]
    auto = store.main["ANTENNA1"] == store.main["ANTENNA2"]
    assert np.all(col[auto] == 0)
    cross_rows = col[~auto]
    np.testing.assert_allclose(
        cross_rows[:, 1:, :],
        np.broadcast_to(cross_rows[:, :1, :], cross_rows[:, 1:, :].shape))


def test_change_freq_rewrites_spectral_window(casa_ms):
    ms_io.change_freq(casa_ms, 150e6)
    info = ms_io.ms_info(casa_ms)
    assert info.n_chan == NCHAN               # shape preserved
    np.testing.assert_allclose(info.freqs, 150e6)
    assert info.ref_freq == pytest.approx(150e6)


def test_extract_dataset_from_casacore_sources(tmp_path, monkeypatch):
    """The DP3-averaging replacement reads casacore sources through
    _load_any and writes synthetic work stores, leaving sources
    untouched (generate_data.py:623-681)."""
    monkeypatch.setattr(ms_io, "_ctab", fc)
    paths = []
    for i, f0 in enumerate([120e6, 130e6, 140e6, 150e6]):
        p = str(tmp_path / f"L123_SB{i:03d}.MS")
        fc.make_lofar_ms(p, n_stations=N_ST, n_times=N_T, nchan=NCHAN,
                         freq0=f0, seed=i)
        paths.append(p)
    before = {p: fc.REGISTRY[p].main["DATA"].copy() for p in paths}

    outdir = tmp_path / "work"
    outdir.mkdir()
    interval = fc.LAYOUT["typical"]["interval_s"]
    out = ms_io.extract_dataset(paths, timesec=2.5 * interval, Nf=3,
                                rng=np.random.default_rng(0),
                                outdir=str(outdir))
    for p in paths:       # sources are read-only to the extractor
        np.testing.assert_array_equal(fc.REGISTRY[p].main["DATA"],
                                      before[p])
    fc.REGISTRY.clear()   # outputs must be readable WITHOUT casacore
    assert len(out) == 3
    infos = [ms_io.ms_info(m) for m in out]
    assert all(i.n_chan == 1 for i in infos)
    assert all(i.n_stations == N_ST for i in infos)
    assert all(1 <= i.n_times <= N_T for i in infos)
    # endpoint sub-bands = lowest + highest source frequency, averaged
    assert infos[0].freqs[0] == pytest.approx(
        np.mean(120e6 + 48828.125 * np.arange(NCHAN)))
    assert infos[-1].freqs[0] == pytest.approx(
        np.mean(150e6 + 48828.125 * np.arange(NCHAN)))


def test_strictness_undeclared_column_fails(casa_ms):
    """The fake enforces the fixture: an adapter that starts requesting
    columns outside the pinned LOFAR layout must fail loudly."""
    with pytest.raises(RuntimeError, match="undeclared"):
        ms_io._ctab.table(casa_ms).getcol("NOT_A_REAL_COLUMN")
    with pytest.raises(RuntimeError, match="undeclared subtable"):
        ms_io._ctab.table(casa_ms + "/POLARIZATION")


def test_fixture_declares_every_column_the_adapter_uses():
    """Static drift guard: every column/subtable name appearing in the
    casacore branch of ms_io.py must be declared in the fixture, so
    layout drift is caught even without running the adapter."""
    import inspect
    import json
    import os

    src = inspect.getsource(ms_io)
    layout = json.load(open(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "fixtures",
        "lofar_ms_layout.json")))
    declared = set(layout["main"]["columns"]) \
        | set(layout["main"]["data_columns_addable"]) \
        | {c for sub in layout["subtables"].values()
           for c in sub["columns"]} \
        | set(layout["subtables"])
    used = {"TIME", "ANTENNA1", "ANTENNA2", "UVW", "INTERVAL", "DATA",
            "SPECTRAL_WINDOW", "FIELD", "CHAN_FREQ", "REF_FREQUENCY",
            "PHASE_DIR"}
    for name in used:
        assert name in src, f"{name} no longer used — update this test"
        assert name in declared, f"{name} used by ms_io but undeclared"
