"""Episode-block dispatch parity: make_episode_block_fn must reproduce the
per-episode driver exactly (same key chain, same learning dynamics) — it
only amortizes device dispatches, it is not a batched-env mode."""

import jax
import jax.numpy as jnp
import numpy as np

from smartcal_tpu.envs import enet
from smartcal_tpu.rl import replay as rp
from smartcal_tpu.rl import sac
from smartcal_tpu.train.enet_sac import (make_episode_block_fn,
                                         make_episode_fn, train_fused)


def _setup(seed=0):
    env_cfg = enet.EnetConfig(M=6, N=6)
    agent_cfg = sac.SACConfig(obs_dim=env_cfg.obs_dim, n_actions=2,
                              batch_size=8, mem_size=64, reward_scale=6.0)
    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    st = sac.sac_init(k0, agent_cfg)
    buf = rp.replay_init(agent_cfg.mem_size,
                         rp.transition_spec(env_cfg.obs_dim, 2))
    return env_cfg, agent_cfg, st, buf, key


def test_block_matches_per_episode_chain():
    steps, block = 2, 3
    env_cfg, agent_cfg, st, buf, key = _setup()
    ep_fn = make_episode_fn(env_cfg, agent_cfg, steps, use_hint=False)
    blk_fn = make_episode_block_fn(env_cfg, agent_cfg, steps,
                                   use_hint=False, block=block)

    # per-episode path: the driver's key chain
    st_a, buf_a, key_a = st, buf, key
    scores_a = []
    for _ in range(block):
        key_a, k = jax.random.split(key_a)
        st_a, buf_a, s = ep_fn(st_a, buf_a, k)
        scores_a.append(float(s))

    # block path: one dispatch, same chain inside the scan carry
    st_b, buf_b, key_b, scores_b = blk_fn(st, buf, key)

    np.testing.assert_allclose(np.asarray(scores_b), np.asarray(scores_a),
                               rtol=1e-4, atol=1e-5)
    assert int(buf_b.cntr) == int(buf_a.cntr) == block * steps
    np.testing.assert_array_equal(np.asarray(key_b), np.asarray(key_a))
    # agent parameters advanced identically (spot-check one actor leaf)
    la = jax.tree_util.tree_leaves(st_a.actor_params)[0]
    lb = jax.tree_util.tree_leaves(st_b.actor_params)[0]
    np.testing.assert_allclose(np.asarray(lb), np.asarray(la),
                               rtol=1e-4, atol=1e-5)


def _chain_parity(ep_fn, blk_fn, st, buf, key, block):
    """Per-episode chain vs one block dispatch: identical scores/state."""
    st_a, buf_a, key_a = st, buf, key
    scores_a = []
    for _ in range(block):
        key_a, k = jax.random.split(key_a)
        st_a, buf_a, s = ep_fn(st_a, buf_a, k)
        scores_a.append(float(s))
    st_b, buf_b, key_b, scores_b = blk_fn(st, buf, key)
    np.testing.assert_allclose(np.asarray(scores_b), np.asarray(scores_a),
                               rtol=1e-4, atol=1e-5)
    assert int(buf_b.cntr) == int(buf_a.cntr)
    np.testing.assert_array_equal(np.asarray(key_b), np.asarray(key_a))


def test_block_matches_per_episode_td3():
    from smartcal_tpu.rl import td3
    from smartcal_tpu.train import enet_td3

    env_cfg = enet.EnetConfig(M=6, N=6)
    cfg = td3.TD3Config(obs_dim=env_cfg.obs_dim, n_actions=2, batch_size=8,
                        mem_size=64, warmup=4)
    key = jax.random.PRNGKey(1)
    key, k0 = jax.random.split(key)
    st = td3.td3_init(k0, cfg)
    buf = rp.replay_init(cfg.mem_size, rp.transition_spec(env_cfg.obs_dim, 2))
    _chain_parity(enet_td3.make_episode_fn(env_cfg, cfg, 2, use_hint=False),
                  enet_td3.make_episode_block_fn(env_cfg, cfg, 2,
                                                 use_hint=False, block=3),
                  st, buf, key, 3)


def test_block_matches_per_episode_ddpg():
    from smartcal_tpu.rl import ddpg
    from smartcal_tpu.train import enet_ddpg

    env_cfg = enet.EnetConfig(M=6, N=6)
    cfg = ddpg.DDPGConfig(obs_dim=env_cfg.obs_dim, n_actions=2, batch_size=8,
                          mem_size=64)
    key = jax.random.PRNGKey(2)
    key, k0 = jax.random.split(key)
    st = ddpg.ddpg_init(k0, cfg)
    buf = rp.replay_init(cfg.mem_size, rp.transition_spec(env_cfg.obs_dim, 2))
    _chain_parity(enet_ddpg.make_episode_fn(env_cfg, cfg, 2),
                  enet_ddpg.make_episode_block_fn(env_cfg, cfg, 2, block=3),
                  st, buf, key, 3)


def test_train_fused_block_mode(tmp_path, monkeypatch):
    """block>1 produces the same per-episode score stream layout, including
    a non-multiple episode count (remainder runs per-episode)."""
    monkeypatch.chdir(tmp_path)
    scores, _, _, _ = train_fused(episodes=5, steps=2, M=6, N=6, quiet=True,
                                  save_every=0, block=2)
    assert len(scores) == 5
    assert all(np.isfinite(s) for s in scores)
