"""Replicated serving fleet (ISSUE 16): RestartTracker schedule units,
FleetRouter dispatch logic against scripted in-process fake replicas
(least-loaded ranking, deadline narrowing, requeue-on-death, bounded
requeues, fleet-scoped sheds, autoscale spawn/reap on an injected
clock), load-gen accounting identities, spawn e2e with jax-free stub
servers (round-trip, mid-run SIGKILL recovery), and the (slow) real
two-replica CalibServer shared-cache warm start."""

import threading
import time

import pytest

from smartcal_tpu.runtime.backoff import BackoffPolicy
from smartcal_tpu.runtime.supervisor import RestartTracker
from smartcal_tpu.serve import fleet as serve_fleet
from smartcal_tpu.serve import loadgen
from smartcal_tpu.serve.fleet import AutoscalePolicy, FleetRouter
from smartcal_tpu.serve.router import Job, JobResult, ShedError

STUB = {"factory": "serve_fleet_worker:make_stub_server",
        "kwargs": {"service_s": 0.01, "lanes": 2},
        "lanes": 2, "beat_s": 0.05}


@pytest.fixture(autouse=True)
def in_tmp(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)


def _fast_backoff():
    return BackoffPolicy(base_s=0.01, factor=2.0, max_s=0.05, jitter=0.0)


# ---------------------------------------------------------------------------
# RestartTracker schedule
# ---------------------------------------------------------------------------

def test_restart_tracker_schedule_and_exhaustion():
    tr = RestartTracker(max_restarts=2, backoff=_fast_backoff())
    assert not tr.tracked(0)
    d = tr.note_down(0, token="spec", now=100.0)
    assert d == pytest.approx(0.01)
    assert tr.tracked(0)
    assert tr.due(now=100.005) == []          # backoff not yet elapsed
    assert tr.due(now=100.02) == [(0, "spec")]
    assert not tr.tracked(0)
    assert tr.attempts(0) == 1
    assert tr.note_down(0, now=101.0) == pytest.approx(0.02)
    assert tr.due(now=102.0) == [(0, None)]
    assert tr.attempts(0) == 2
    # third death exhausts max_restarts=2: permanently failed
    assert tr.note_down(0, now=103.0) is None
    assert 0 in tr.failed and tr.tracked(0)
    assert tr.restarts_total() == 2
    # independent slots don't interact
    assert tr.note_down(1, now=103.0) == pytest.approx(0.01)
    assert 1 not in tr.failed


# ---------------------------------------------------------------------------
# router logic against scripted fakes (no processes)
# ---------------------------------------------------------------------------

class FakeReplica:
    """In-process stand-in for ``_Replica``: scripted gauges, records
    dispatches, dies on command.  ``t_spawn`` is the replica id so the
    reap-newest-victim choice is deterministic."""

    def __init__(self, router, replica_id, spec):
        self.router = router
        self.replica_id = replica_id
        self.spec = dict(spec)
        self.lanes = int(spec.get("lanes", 2))
        self.t_spawn = float(replica_id)
        self.last_beat = router._clock()
        self.ready = threading.Event()
        self.ready.set()
        self.ready_summary = {"wall_s": 0.0, "sources": {}}
        self.stop_event = threading.Event()
        self.error = None
        self.accept = True
        self.dispatched = []
        self._alive = True
        self._g = {"queue_depth": 0, "batch_fill": 0.0,
                   "circuit_open": False, "service_est_s": 0.05}
        self._pending = {}

    def start(self):
        pass

    def healthy(self):
        return self._alive and self.error is None

    def request_stop(self):
        self.stop_event.set()

    def hard_kill(self):
        self._alive = False

    def finalize(self, timeout=2.0):
        pass

    def shutdown(self, timeout=5.0):
        self.stop_event.set()

    def gauges(self):
        g = dict(self._g)
        g["pending"] = len(self._pending)
        return g

    def pending_count(self):
        return len(self._pending)

    def dispatch(self, job):
        if not self.accept:
            return False
        self._pending[job.job_id] = job
        self.dispatched.append(job)
        return True

    def take_pending(self):
        jobs = list(self._pending.values())
        self._pending.clear()
        return jobs


def _fake_router(clk, **kw):
    kw.setdefault("backoff", _fast_backoff())
    kw.setdefault("max_restarts", 3)
    kw.setdefault("heartbeat_timeout", 1e9)  # fake-clock jumps are not hangs
    return FleetRouter({"lanes": 2}, replicas=0,
                       replica_factory=FakeReplica,
                       clock=lambda: clk[0], **kw)


def test_router_dispatch_least_loaded():
    clk = [0.0]
    router = _fake_router(clk)
    r0, r1, r2 = (router._spawn_replica() for _ in range(3))
    r0._g["queue_depth"] = 4
    r1._g["queue_depth"] = 0
    r2._g["queue_depth"] = 2
    job = Job(episode=None, k=1, t_submit=0.0)
    fut = router.submit(job)
    assert r1.dispatched == [job] and not r0.dispatched
    assert fut is job.future
    assert router.stats()["dispatched"] == 1
    # r1 now carries 1 pending; next job still lands on the emptiest
    job2 = Job(episode=None, k=2, t_submit=0.0)
    router.submit(job2)
    assert r1.dispatched == [job, job2]      # backlog 0.5 still < r2's 1.0


def test_router_batch_fill_tiebreak():
    clk = [0.0]
    router = _fake_router(clk)
    r0, r1 = (router._spawn_replica() for _ in range(2))
    r0._g["batch_fill"] = 0.9
    r1._g["batch_fill"] = 0.3
    job = Job(episode=None, k=1, t_submit=0.0)
    router.submit(job)
    assert r1.dispatched == [job]            # equal backlog: lower fill


def test_router_deadline_narrows_then_falls_back():
    clk = [0.0]
    router = _fake_router(clk)
    slow, fast = (router._spawn_replica() for _ in range(2))
    slow._g["service_est_s"] = 5.0           # eta 5s: misses the SLO
    fast._g["service_est_s"] = 0.1
    fast._g["queue_depth"] = 2               # more loaded, but fits slack
    job = Job(episode=None, k=1, deadline_s=1.0, t_submit=0.0)
    router.submit(job)
    assert fast.dispatched == [job]
    # when NO replica fits the slack, fall back to least-loaded rather
    # than shedding a servable job (late answer beats no answer)
    fast._g["service_est_s"] = 9.0
    job2 = Job(episode=None, k=1, deadline_s=1.0, t_submit=0.0)
    router.submit(job2)
    assert slow.dispatched == [job2]         # backlog 0 < fast's 1


def test_router_sheds_fleet_down_and_saturated():
    clk = [0.0]
    router = _fake_router(clk)
    with pytest.raises(ShedError) as ei:
        router.submit(Job(episode=None, k=1, t_submit=0.0))
    assert ei.value.reason == "fleet_down"
    r0 = router._spawn_replica()
    r0.accept = False                        # outbox full on every try
    with pytest.raises(ShedError) as ei:
        router.submit(Job(episode=None, k=1, t_submit=0.0))
    assert ei.value.reason == "fleet_saturated"
    st = router.stats()
    assert st["shed"] == 2
    assert st["shed_reasons"] == {"fleet_down": 1, "fleet_saturated": 1}


def test_router_requeues_lost_jobs_then_respawns():
    clk = [0.0]
    router = _fake_router(clk, max_requeues=1)
    r0, r1 = (router._spawn_replica() for _ in range(2))
    jobs = [Job(episode=None, k=i, t_submit=0.0) for i in range(4)]
    for j in jobs:
        router.submit(j)
    lost = list(r0._pending.values())
    assert lost and r1._pending               # dispatch spread both ways
    r0.hard_kill()
    events = router.poll()
    kinds = [e["event"] for e in events]
    assert "fleet_replica_down" in kinds
    # every job r0 held moved to the survivor, marked as a requeue
    for j in lost:
        assert j.job_id in r1._pending
        assert j.requeues == 1
    st = router.stats()
    assert st["requeued"] == len(lost)
    assert st["shed"] == 0                    # nothing shed unnecessarily
    # backoff elapses on the injected clock -> same-slot respawn
    clk[0] = 1.0
    events = router.poll()
    assert [e["event"] for e in events] == ["fleet_replica_restart"]
    assert router.replicas_alive() == 2
    assert router.stats()["replica_restarts"] == 1


def test_router_bounded_requeues_shed_replica_lost():
    clk = [0.0]
    router = _fake_router(clk, max_requeues=0)
    r0 = router._spawn_replica()
    job = Job(episode=None, k=1, t_submit=0.0)
    fut = router.submit(job)
    r0.hard_kill()
    router.poll()
    with pytest.raises(ShedError) as ei:
        fut.result(timeout=1.0)
    assert ei.value.reason == "replica_lost"
    assert router.stats()["shed_reasons"] == {"replica_lost": 1}


def test_router_replica_exhaustion_opens_its_circuit_only():
    clk = [0.0]
    router = _fake_router(clk, max_restarts=0)
    r0, r1 = (router._spawn_replica() for _ in range(2))
    r0.hard_kill()
    events = router.poll()
    assert [e["event"] for e in events] == ["fleet_replica_failed"]
    assert events[0]["replica"] == 0 and events[0]["reason"] == "exited"
    assert router.stats()["failed_replicas"] == [0]
    # the fleet stays up on the survivor: no fleet_down
    job = Job(episode=None, k=1, t_submit=0.0)
    router.submit(job)
    assert r1.dispatched == [job]


def test_router_hung_replica_killed_by_heartbeat():
    clk = [100.0]
    router = _fake_router(clk, heartbeat_timeout=2.0)
    r0 = router._spawn_replica()
    r0.last_beat = 100.0
    assert router.poll() == []               # fresh beat: healthy
    clk[0] = 103.0                           # beat 3s stale > 2s timeout
    events = router.poll()
    assert events[0]["event"] == "fleet_replica_down"
    assert events[0]["reason"] == "hung"
    assert not r0._alive                     # hard-killed


def test_router_autoscale_spawns_and_reaps():
    clk = [0.0]
    pol = AutoscalePolicy(min_replicas=1, max_replicas=4,
                          spawn_depth=2.0, spawn_sustain_s=1.0,
                          reap_idle_s=1.0, cooldown_s=0.0)
    router = _fake_router(clk, autoscale=pol)
    r0 = router._spawn_replica()
    r0._g["queue_depth"] = 4                 # 4 jobs over 1 replica
    assert router.poll() == []               # pressure noted, not sustained
    clk[0] = 1.5
    events = router.poll()
    assert [e["event"] for e in events] == ["fleet_scale_up"]
    assert router.replicas_alive() == 2
    assert router.stats()["scale_ups"] == 1
    # drain the fleet -> sustained idle reaps the NEWEST replica back
    # down to min_replicas
    r0._g["queue_depth"] = 0
    clk[0] = 2.0
    assert router.poll() == []               # idle noted, not sustained
    clk[0] = 3.5
    events = router.poll()
    assert [e["event"] for e in events] == ["fleet_scale_down"]
    assert events[0]["replica"] == 1         # newest (t_spawn = rid)
    assert router.replicas_alive() == 1
    assert router.stats()["scale_downs"] == 1
    # at min_replicas, idle never reaps the last replica
    clk[0] = 10.0
    assert router.poll() == []               # idle clock restarts
    clk[0] = 20.0
    assert router.poll() == []               # sustained, but at the floor
    assert router.replicas_alive() == 1


# ---------------------------------------------------------------------------
# load-gen accounting
# ---------------------------------------------------------------------------

def _result(i, miss=False):
    return JobResult(job_id=i, lane=0, batch_id=0, sigma_res=0.1,
                     sigma_data_img=0.0, sigma_res_img=0.0, img_std=0.0,
                     degraded=False, queue_wait_s=0.0, service_s=0.1,
                     total_s=0.2, deadline_miss=miss)


def test_summarize_buckets_are_disjoint_and_sum():
    gen = loadgen.OpenLoopLoadGen(None, [(1, None)], rate=2.0,
                                  duration_s=1.0)
    results = [_result(i, miss=(i % 2 == 0)) for i in range(4)]
    out = gen.summarize(9, 3, results,
                        shed_reasons={"queue_full": 2, "replica_lost": 1},
                        failed=2)
    assert out["shed"] == 3
    assert sum(out["shed_reasons"].values()) == out["shed"]
    assert out["completed"] == 4 and out["failed"] == 2
    assert out["accounted"] == out["shed"] + out["failed"] \
        + out["completed"] == 9
    # deadline misses are the served-late SUBSET of completed, never
    # double-counted against sheds
    assert out["deadline_missed"] == 2 <= out["completed"]


def test_loadgen_pick_validation():
    with pytest.raises(ValueError, match="pick"):
        loadgen.OpenLoopLoadGen(None, [], rate=1.0, duration_s=1.0,
                                pick="fifo")


class _PoolBackend:
    """Records what build_job_pool asked for (no jax episode build)."""

    def new_calib_episode(self, key, kdirs, M, diffuse=False):
        return ("ep", kdirs, diffuse), None


def test_build_job_pool_mixed_vs_uniform():
    pool = loadgen.build_job_pool(_PoolBackend(), 4, 32, seed=0)
    ks = sorted({k for k, _ in pool})
    assert set(ks) <= {2, 3, 4} and len(ks) >= 2   # heterogeneous K
    diffuse = [ep[2] for _, ep in pool]
    assert any(diffuse) and not all(diffuse)       # mixed sky types
    # the uniform flag reproduces the PR 15 deterministic cycle exactly
    pool_u = loadgen.build_job_pool(_PoolBackend(), 4, 6, seed=0,
                                    mixed=False)
    assert [k for k, _ in pool_u] == [2, 3, 4, 2, 3, 4]
    assert not any(ep[2] for _, ep in pool_u)


# ---------------------------------------------------------------------------
# spawn e2e on jax-free stub servers
# ---------------------------------------------------------------------------

def _drain(futures, timeout_s=60.0):
    deadline = time.monotonic() + timeout_s
    out = []
    for f in futures:
        out.append(f.result(timeout=max(0.1,
                                        deadline - time.monotonic())))
    return out


def test_fleet_stub_round_trip_two_replicas():
    router = FleetRouter(STUB, replicas=2, heartbeat_timeout=10.0,
                         poll_s=0.02, backoff=_fast_backoff())
    try:
        warm = router.start(warm_timeout_s=60.0, stagger=False)
        assert sorted(warm) == [0, 1]
        assert all(w["sources"] == {"solve": "stub"}
                   for w in warm.values())
        jobs = [Job(episode=None, k=i % 5) for i in range(8)]
        futs = [router.submit(j) for j in jobs]
        results = _drain(futs)
        # sigma_res round-trips the job's k: payloads reached a real
        # worker process and came back matched to the right future
        assert [r.sigma_res for r in results] == \
            [float(j.k) for j in jobs]
        assert all(r.job_id == j.job_id for r, j in zip(results, jobs))
        st = router.stats()
        assert st["completed"] == 8 and st["shed"] == 0
        assert st["replicas_alive"] == 2
    finally:
        router.stop()


def test_fleet_stub_kill_costs_only_in_flight_batch():
    """SIGKILL one of two replicas mid-run: every admitted job still
    completes (requeued to the survivor), nothing is shed, and the
    killed slot respawns."""
    router = FleetRouter(STUB, replicas=2, heartbeat_timeout=10.0,
                         poll_s=0.02, backoff=_fast_backoff(),
                         max_requeues=2)
    try:
        router.start(warm_timeout_s=60.0, stagger=False)
        jobs = [Job(episode=None, k=i % 5) for i in range(12)]
        futs = [router.submit(j) for j in jobs]
        assert router.kill_replica(0)
        results = _drain(futs)
        assert len(results) == 12
        assert [r.sigma_res for r in results] == \
            [float(j.k) for j in jobs]
        st = router.stats()
        assert st["completed"] == 12 and st["shed"] == 0
        deadline = time.monotonic() + 30.0
        while (router.stats()["replica_restarts"] < 1
               or router.replicas_alive() < 2):
            assert time.monotonic() < deadline, router.stats()
            time.sleep(0.05)
    finally:
        router.stop()


def test_fleet_stub_stop_sheds_shutdown():
    """Jobs still in flight at stop() shed with the structured
    ``shutdown`` reason on the future the client holds."""
    spec = dict(STUB, kwargs=dict(STUB["kwargs"], service_s=5.0))
    router = FleetRouter(spec, replicas=1, poll_s=0.02,
                         backoff=_fast_backoff())
    try:
        router.start(warm_timeout_s=60.0)
        futs = [router.submit(Job(episode=None, k=1)) for _ in range(3)]
    finally:
        router.stop(timeout=3.0)
    reasons = set()
    for f in futs:
        try:
            f.result(timeout=1.0)
        except ShedError as e:
            reasons.add(e.reason)
    assert reasons <= {"shutdown"}
    st = router.stats()
    assert st["shed"] == st["shed_reasons"].get("shutdown", 0) > 0


# ---------------------------------------------------------------------------
# real CalibServer fleet: shared-cache warm start (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fleet_shared_cache_second_replica_compiles_nothing(tmp_path):
    """Replica 0 builds the shared AOT+XLA cache cold (staggered
    start); replica 1 then warms up ENTIRELY from it — every program
    from cache, zero export misses — and real jobs round-trip through
    both."""
    from smartcal_tpu.envs import radio
    from smartcal_tpu.serve.fleet import calib_worker_spec
    from smartcal_tpu.serve.loadgen import SERVE_TIERS

    cache = str(tmp_path / "cache")
    spec = calib_worker_spec(SERVE_TIERS["tiny"], M=3, lanes=2,
                             cache_dir=cache, max_wait_s=0.02,
                             max_queue=16)
    spec["beat_s"] = 0.1
    router = FleetRouter(spec, replicas=2, poll_s=0.05,
                         backoff=_fast_backoff())
    try:
        warm = router.start(warm_timeout_s=600.0, stagger=True)
        w1 = warm[1]
        assert w1["export_cache_miss"] == 0
        assert all(src == "cache" for src in w1["sources"].values())
        backend = radio.RadioBackend(**SERVE_TIERS["tiny"])
        pool = loadgen.build_job_pool(backend, 3, 2, seed=1)
        jobs = [Job(episode=ep, k=k) for k, ep in pool * 2]
        results = _drain([router.submit(j) for j in jobs],
                         timeout_s=300.0)
        assert len(results) == 4
        assert all(r.sigma_res > 0 for r in results)
        st = router.stats()
        assert st["completed"] == 4 and st["shed"] == 0
    finally:
        router.stop(timeout=20.0)
