"""Tests for the influence-map engine (cal/influence.py) against the
reference's dense formulas (analysis_torch.py:141-156, analysis.py,
influence_tools.py:219-372)."""

import jax.numpy as jnp
import numpy as np
import pytest

from smartcal_tpu.cal import consensus, creal, influence


def dense_hadd_reference(rho, alpha, freqs, f0, fidx, n_stations, n_poly,
                         polytype):
    """Straight transcription of the reference's dense Hadd build
    (analysis_torch.py:141-156) using the dense (F, P) of consensus_poly."""
    F, P = consensus.consensus_poly(n_poly, n_stations, freqs, f0, fidx,
                                    polytype=polytype, rho=rho, alpha=alpha)
    F, P = np.asarray(F, np.float64), np.asarray(P, np.float64)
    FF = F.T @ F
    n2 = 2 * n_stations
    if alpha > 0.0:
        PP = P.T @ P
        H11 = 0.5 * rho * FF + 0.5 * alpha * rho * rho * PP
        H12 = 0.5 * FF + 0.5 * alpha * rho * PP
        H22 = -0.5 / rho * (np.eye(n2) - FF) + 0.5 * alpha * PP
        Ht = H11 - H12 @ np.linalg.pinv(H22) @ H12
        return np.kron(np.eye(2), Ht)
    return 0.5 * rho * np.kron(
        np.eye(2), FF @ (np.eye(n2) + np.linalg.pinv(np.eye(n2) - FF) @ FF))


@pytest.mark.parametrize("alpha", [0.0, 0.3])
@pytest.mark.parametrize("polytype", [0, 1])
def test_hadd_scalar_matches_dense_reference(alpha, polytype):
    n_stations, n_poly = 3, 2
    freqs = np.linspace(110e6, 170e6, 8)
    f0, fidx = 140e6, 3
    rho = 7.5
    h = np.asarray(influence.consensus_hadd_scalars(
        [rho], [alpha], freqs, f0, fidx, n_poly=n_poly, polytype=polytype))
    dense = dense_hadd_reference(rho, alpha, freqs, f0, fidx, n_stations,
                                 n_poly, polytype)
    np.testing.assert_allclose(h[0] * np.eye(4 * n_stations), dense,
                               rtol=2e-4, atol=2e-5)


@pytest.fixture(scope="module")
def chunk_problem():
    rng = np.random.default_rng(3)
    N, K, Ts, Td = 4, 2, 2, 3
    B = N * (N - 1) // 2
    T = Ts * Td
    R = (rng.standard_normal((2 * B * T, 2))
         + 1j * rng.standard_normal((2 * B * T, 2))).astype(np.complex64)
    C = (rng.standard_normal((K, T * B, 4))
         + 1j * rng.standard_normal((K, T * B, 4))).astype(np.complex64)
    J = (rng.standard_normal((Ts, K, 2 * N, 2))
         + 1j * rng.standard_normal((Ts, K, 2 * N, 2))).astype(np.complex64)
    hadd = jnp.asarray([0.5, 1.0])
    return N, K, Ts, Td, creal.split(R), creal.split(C), creal.split(J), hadd


def test_influence_shapes_and_finiteness(chunk_problem):
    N, K, Ts, Td, R, C, J, hadd = chunk_problem
    B = N * (N - 1) // 2
    res = influence.influence_visibilities(
        jnp.asarray(R).reshape(-1, 2, 2), jnp.asarray(C), jnp.asarray(J),
        hadd, N, Ts)
    assert res.vis.shape == (Ts * Td * B, 4, 2)
    assert res.llr.shape == (Ts, K)
    assert np.all(np.isfinite(np.asarray(res.vis)))
    # non-fullpol: XY/YX zeroed
    assert np.all(np.asarray(res.vis[:, 1, :]) == 0)
    assert np.all(np.asarray(res.vis[:, 2, :]) == 0)
    # replicated over the Td slots within a chunk
    v = np.asarray(res.vis).reshape(Ts, Td, B, 4, 2)
    np.testing.assert_allclose(v[:, 0], v[:, 1])


def test_perdir_sums_to_combined(chunk_problem):
    """dR summed over directions == the combined engine, so the perdir
    influence visibilities must sum to the all-directions ones."""
    N, K, Ts, Td, R, C, J, hadd = chunk_problem
    comb = influence.influence_visibilities(
        jnp.asarray(R).reshape(-1, 2, 2), jnp.asarray(C), jnp.asarray(J),
        hadd, N, Ts)
    perdir = influence.influence_visibilities(
        jnp.asarray(R).reshape(-1, 2, 2), jnp.asarray(C), jnp.asarray(J),
        hadd, N, Ts, perdir=True)
    assert perdir.vis.shape[0] == K
    np.testing.assert_allclose(np.asarray(perdir.vis).sum(axis=0),
                               np.asarray(comb.vis), rtol=1e-3, atol=1e-3)


def test_perdir_summary(chunk_problem):
    N, K, Ts, Td, R, C, J, hadd = chunk_problem
    perdir = influence.influence_visibilities(
        jnp.asarray(R).reshape(-1, 2, 2), jnp.asarray(C), jnp.asarray(J),
        hadd, N, Ts, perdir=True)
    summ = influence.perdir_summary(perdir.vis, perdir.llr, jnp.asarray(C),
                                    jnp.asarray(J))
    for f in summ:
        assert f.shape == (K,)
        assert np.all(np.isfinite(np.asarray(f)))
    # norms match numpy directly
    np.testing.assert_allclose(
        np.asarray(summ.c_norm),
        np.linalg.norm(np.asarray(C).reshape(K, -1), axis=1), rtol=1e-5)


def test_influence_zero_residual_zero_coherency():
    """With C = 0 the perturbation chain is all-zero -> zero influence."""
    N, K, Ts, Td = 3, 1, 1, 2
    B = N * (N - 1) // 2
    T = Ts * Td
    R = jnp.zeros((2 * B * T, 2, 2))
    C = jnp.zeros((K, T * B, 4, 2))
    J = jnp.zeros((Ts, K, 2 * N, 2, 2)).at[..., 0::2, 0, 0].set(1.0)
    res = influence.influence_visibilities(R, C, J, jnp.ones((K,)), N, Ts)
    np.testing.assert_allclose(np.asarray(res.vis), 0.0, atol=1e-6)


@pytest.mark.slow
def test_influence_reference_scale_n62():
    """LOFAR-scale regime (BASELINE.md: N=62, B=1891, K=6, Tdelta=10): the
    fused column-means path must produce finite influence visibilities
    without materializing the (8, 4B, B) tensor (VERDICT r1 next #1)."""
    N, K, Td, Ts = 62, 6, 10, 2
    B = N * (N - 1) // 2
    T = Ts * Td
    rng = np.random.default_rng(0)
    Rs = jnp.asarray(rng.standard_normal((2 * B * T, 2, 2)), jnp.float32)
    Cs = jnp.asarray(rng.standard_normal((K, T * B, 4, 2)), jnp.float32)
    Js = jnp.asarray(rng.standard_normal((Ts, K, 2 * N, 2, 2)),
                     jnp.float32) * 0.3
    hadd = jnp.ones((K,), jnp.float32) * 0.05
    out = influence.influence_visibilities(Rs, Cs, Js, hadd, N, Ts)
    assert out.vis.shape == (T * B, 4, 2)
    assert bool(jnp.all(jnp.isfinite(out.vis)))
    outk = influence.influence_visibilities(Rs, Cs, Js, hadd, N, Ts,
                                            perdir=True)
    assert outk.vis.shape == (K, T * B, 4, 2)
    assert bool(jnp.all(jnp.isfinite(outk.vis)))
    assert outk.llr.shape == (Ts, K)
