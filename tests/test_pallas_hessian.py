"""Interpret-mode parity gate for the tiled Pallas Hessian kernel
(ops/pallas_hessian, ISSUE 17 tentpole).

The kernel is the Mosaic twin of the blocked XLA Hessian core
(cal/kernels._hessian_res_core_blocked_sr), selected by the SAME static
``block_baselines`` threshold via ``influence_visibilities(...,
use_pallas=True)``; ``interpret=True`` runs the exact kernel program
through the Pallas interpreter on CPU, so these tests certify the tile
algebra, layouts, and padding without a TPU — the hardware flip is the
same code with ``interpret=False``.

Tolerances are float-round-off class: the tile reduction reassociates
the station sums exactly like the blocked scan does (the blocked-vs-
unblocked XLA parity test in test_influence.py documents the same
class).
"""

import numpy as np
import pytest

pytest.importorskip("jax")

import jax.numpy as jnp  # noqa: E402

from smartcal_tpu.cal import kernels  # noqa: E402
from smartcal_tpu.ops import pallas_hessian  # noqa: E402

RTOL, ATOL = 2e-4, 2e-5


def _operands(n_stations, K=3, Td=4, seed=0):
    rng = np.random.default_rng(seed)
    B = n_stations * (n_stations - 1) // 2
    R3 = jnp.asarray(rng.standard_normal((Td, B, 2, 2, 2)), jnp.float32)
    C5 = jnp.asarray(rng.standard_normal((K, Td, B, 2, 2, 2)),
                     jnp.float32)
    p, q = kernels.baseline_indices(n_stations)
    J4 = jnp.asarray(rng.standard_normal((K, n_stations, 2, 2, 2)),
                     jnp.float32)
    return R3, C5, J4[:, p], J4[:, q], p, q


@pytest.mark.parametrize("n_stations", [6, 20])
def test_block_sums_parity(n_stations):
    """Tile-kernel block sums == the einsum oracle, both in the
    unaligned single-tile regime (N=6 -> B=15, padded to 128) and the
    multi-tile regime with a ragged tail (N=20 -> B=190 -> 2 tiles,
    66 pad slots)."""
    R3, C5, Jp, Jq, p, q = _operands(n_stations)
    off_ref, dsum_ref = kernels._hessian_block_sums(R3, C5, Jp, Jq, p, q,
                                                    n_stations)
    off_pl, dsum_pl = pallas_hessian.hessian_block_sums_pallas(
        R3, C5, Jp, Jq, p, q, n_stations, interpret=True)
    assert off_pl.shape == off_ref.shape
    assert dsum_pl.shape == dsum_ref.shape
    np.testing.assert_allclose(np.asarray(off_pl), np.asarray(off_ref),
                               rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(np.asarray(dsum_pl), np.asarray(dsum_ref),
                               rtol=RTOL, atol=ATOL)


def test_full_core_parity_vs_blocked_and_unblocked():
    """hessian_res_core_pallas_sr == both XLA cores end to end (shared
    _hessian_assemble placement tail, so this pins the decode reshapes
    too)."""
    N = 8
    R3, C5, Jp, Jq, _, _ = _operands(N, K=2, Td=3, seed=1)
    h_blk = kernels._hessian_res_core_blocked_sr(R3, C5, Jp, Jq, N, 8)
    h_unb = kernels._hessian_res_core_sr(R3, C5, Jp, Jq, N)
    h_pl = pallas_hessian.hessian_res_core_pallas_sr(R3, C5, Jp, Jq, N,
                                                     interpret=True)
    assert h_pl.shape == (2, 4 * N, 4 * N, 2)
    np.testing.assert_allclose(np.asarray(h_pl), np.asarray(h_blk),
                               rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(np.asarray(h_pl), np.asarray(h_unb),
                               rtol=RTOL, atol=ATOL)


def test_pallas_dispatch_gated_off_cpu():
    """On CPU the blocked influence tier must keep routing to the XLA
    scan: pallas_available() is False, so use_pallas=True (the default)
    changes nothing — the flag only engages on a TPU backend."""
    assert not pallas_hessian.pallas_available()
    from smartcal_tpu.cal import influence

    N, K, Tchunks, Td = 6, 2, 2, 2
    B = N * (N - 1) // 2
    T = Tchunks * Td
    rng = np.random.default_rng(2)
    R = jnp.asarray(rng.standard_normal((2 * B * T, 2, 2)), jnp.float32)
    C = jnp.asarray(rng.standard_normal((K, T * B, 4, 2)), jnp.float32)
    J = jnp.asarray(rng.standard_normal((Tchunks, K, 2 * N, 2, 2)),
                    jnp.float32)
    hadd = jnp.zeros((K,), jnp.float32)
    base = influence.influence_visibilities(R, C, J, hadd, N, Tchunks,
                                            block_baselines=8,
                                            use_pallas=False)
    flag = influence.influence_visibilities(R, C, J, hadd, N, Tchunks,
                                            block_baselines=8,
                                            use_pallas=True)
    np.testing.assert_allclose(np.asarray(flag.vis),
                               np.asarray(base.vis), rtol=0, atol=0)
    np.testing.assert_allclose(np.asarray(flag.llr),
                               np.asarray(base.llr), rtol=0, atol=0)
