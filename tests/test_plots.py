"""Plot/inspect utilities (SURVEY §2.6 row 47: plot_databuffer,
inspect_replaybuffer, plot_tsk parity)."""

import os

import jax
import numpy as np

from smartcal_tpu.envs.demixing import META_SCALE, REWARD_MEAN, REWARD_STD
from smartcal_tpu.models.regressor import TrainingBuffer
from smartcal_tpu.models.tsk import tsk_init
from smartcal_tpu.rl import replay as rp
from smartcal_tpu.train import plots

K = 4


def test_plot_databuffer(tmp_path):
    buf = TrainingBuffer(8, 3 * K + 2, K - 1)
    rng = np.random.default_rng(0)
    md = rng.uniform(0, 90, size=(5, 3 * K + 2)).astype(np.float32)
    for row in md:
        buf.store(row * META_SCALE, np.zeros(K - 1, np.float32))
    out = tmp_path / "foo.png"
    cols = plots.plot_databuffer(buf, K, field="azimuth",
                                 out_png=str(out))
    assert out.exists() and out.stat().st_size > 0
    # un-scaled azimuth block returned
    np.testing.assert_allclose(cols, md[:, K:2 * K], rtol=1e-5)


def test_plot_rewards_rescale(tmp_path):
    out = tmp_path / "bar.png"
    normed = np.asarray([0.0, 1.0, -1.0])
    raw = plots.plot_rewards(normed, out_png=str(out))
    assert out.exists()
    # inverse of (r - mean)/std with mean = -859: r*3559 - 859
    np.testing.assert_allclose(raw[0],
                               normed * REWARD_STD + REWARD_MEAN)
    assert raw[0][0] == REWARD_MEAN


def test_inspect_replaybuffer(tmp_path):
    h = w = 6
    obs_dim = h * w + 5
    buf = rp.replay_init(16, {
        "state": ((obs_dim,), np.float32),
        "action": ((2,), np.float32),
        "reward": ((), np.float32),
        "new_state": ((obs_dim,), np.float32),
        "done": ((), np.bool_)})
    rng = np.random.default_rng(1)
    for i in range(9):
        buf = rp.replay_add(buf, {
            "state": rng.standard_normal(obs_dim).astype(np.float32),
            "action": np.zeros(2, np.float32), "reward": np.float32(0),
            "new_state": np.zeros(obs_dim, np.float32), "done": False},
            priority=1.0)
    out = tmp_path / "grid.png"
    tiles = plots.inspect_replaybuffer(buf, (h, w), out_png=str(out),
                                       stride=2)
    assert out.exists() and out.stat().st_size > 0
    assert tiles.shape == (5, h, w)                  # 9 states, stride 2
    assert np.all(np.isfinite(tiles))


def test_plot_tsk(tmp_path):
    params = tsk_init(jax.random.PRNGKey(0), n_inputs=5, n_outputs=3,
                      n_rule=3)
    out = tmp_path / "tsk.png"
    dumped = plots.plot_tsk(params, out_png=str(out))
    assert out.exists() and out.stat().st_size > 0
    assert dumped["center"].shape == (5, 3)
