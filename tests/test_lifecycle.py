"""Online lifecycle (ISSUE 20): replay tee, behavior-logp scoring,
zero-compile hot-swap publication, fleet weight frames.

The load-bearing claims, each pinned here:

* LOGP PARITY — the batch worker's host-numpy ``behavior_logp`` scorer
  is term-for-term identical to the learner's jax density (the IMPACT
  ratio's numerator and denominator must come from the same measure).
* SWAP PARITY — swapping in bit-identical params under queued load
  changes nothing but the version bookkeeping: results match the
  no-swap run exactly, and requests admitted under version V that
  execute after the swap report BOTH versions.
* TEE FIDELITY — every teed transition is derivable from its request:
  state == the job's obs_vec, action == the pinned rho in unit
  coordinates, reward == the documented sigma composite, version ==
  the acting snapshot; and offline-storing the same transitions
  reproduces the learner's ring bitwise.
* ZERO-COMPILE PUBLICATION — after the warm publish, N more publishes
  through the ExportCache move the compile counter by exactly zero.
* FLEET INDEPENDENCE — one publication frames the pytree once and
  reaches every ready replica; a non-ready replica just misses it; the
  replica-side ``_WeightsPublisher`` collapses a burst latest-wins.
"""

import json
import threading
import time

import numpy as np
import pytest

from smartcal_tpu import obs
from smartcal_tpu.envs import calib as calib_env
from smartcal_tpu.envs.radio import RadioBackend
from smartcal_tpu.serve import (CalibServer, Job, PolicyPublisher,
                                ServingLearner, TransitionStage,
                                build_obs_pool)

M = 3
LANES = 3
SEED = 7
NPIX = 32
OBS_DIM = NPIX * NPIX + (M + 1) * 7


def tiny_backend(**kw):
    args = dict(n_stations=6, n_freqs=2, n_times=4, tdelta=2,
                admm_iters=2, lbfgs_iters=3, init_iters=5, npix=NPIX)
    args.update(kw)
    return RadioBackend(**args)


@pytest.fixture(scope="module")
def lifecycle(tmp_path_factory):
    """One warmed policy-armed server with the replay tee + its learner
    and a small obs-bearing pool, shared by the whole module (the
    export build and the probe calibrations run ONCE)."""
    from smartcal_tpu.rl import sac

    obs.install_compile_listener()
    path = tmp_path_factory.mktemp("lifecycle") / "run.jsonl"
    rl = obs.RunLog(str(path), run_id="lifecycle-test", flush_lines=1)
    obs.activate(rl)
    be = tiny_backend()
    cfg = sac.SACConfig(obs_dim=OBS_DIM, n_actions=2 * M,
                        mem_size=64, batch_size=16,
                        is_clip=2.0, ere_eta=0.996)
    learner = ServingLearner(cfg, seed=SEED, n_shards=4,
                             publish_every=2, ingest_chunk=4)
    stage = TransitionStage(cap=256)
    cache = str(tmp_path_factory.mktemp("lifecycle_cache"))
    srv = CalibServer(be, M=M, lanes=LANES, cache_dir=cache,
                      compile_cache=False,
                      policy=(cfg, learner.actor_params),
                      transition_sink=stage, max_wait_s=0.02)
    srv.warmup(seed=SEED)
    learner.publisher = PolicyPublisher(srv, keep_versions=4)
    learner.warm()                       # includes the warm publish
    pool = build_obs_pool(be, M, 3, seed=SEED + 1)
    yield be, srv, learner, stage, pool, str(path)
    while obs.active() is not None:
        obs.deactivate()


def _events(path, name, start=0):
    out = []
    with open(path) as fh:
        for line in fh.readlines()[start:]:
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if ev.get("event") == name:
                out.append(ev)
    return out


def _lines(path):
    with open(path) as fh:
        return len(fh.readlines())


# ---------------------------------------------------------------------------
# behavior_logp: host scorer == jax density
# ---------------------------------------------------------------------------

def test_behavior_logp_np_matches_jax_density():
    from smartcal_tpu.rl.networks import (tanh_gaussian_log_prob,
                                          tanh_gaussian_log_prob_np)

    rng = np.random.default_rng(3)
    mu = rng.normal(size=(8, 2 * M)).astype(np.float32)
    logsigma = rng.uniform(-2.0, 0.5, (8, 2 * M)).astype(np.float32)
    act = np.tanh(rng.normal(size=(8, 2 * M))).astype(np.float32)
    want = np.asarray(tanh_gaussian_log_prob(mu, logsigma, act))
    got = np.array([tanh_gaussian_log_prob_np(mu[i], logsigma[i], act[i])
                    for i in range(len(mu))])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    # saturated actions (the pinned-rho clip boundary) stay finite
    edge = np.full((1, 2 * M), 1.0, np.float32)
    assert np.isfinite(tanh_gaussian_log_prob_np(mu[0], logsigma[0],
                                                 edge[0]))


# ---------------------------------------------------------------------------
# the tee: fidelity of served transitions
# ---------------------------------------------------------------------------

def test_teed_transitions_derivable_from_their_requests(lifecycle):
    be, srv, learner, stage, pool, path = lifecycle
    stage.drain()                        # isolate this wave
    ver = srv.policy_version
    jobs = []
    for i, (k, ep, ov) in enumerate(pool):
        rho = np.linspace(0.5 + i, 1.5 + i, k).astype(np.float32)
        jobs.append(Job(episode=ep, k=k, rho=rho, obs_vec=ov))
    srv.process_once(jobs, timeout=0.05)
    results = [j.future.result(timeout=60) for j in jobs]
    trs = stage.drain()
    assert len(trs) == len(jobs)
    spec_keys = {"state", "new_state", "action", "reward", "done",
                 "hint", "version", "behavior_logp"}
    for job, r, tr in zip(jobs, results, trs):
        assert set(tr) == spec_keys
        np.testing.assert_array_equal(tr["state"],
                                      np.asarray(job.obs_vec, np.float32))
        np.testing.assert_array_equal(tr["state"], tr["new_state"])
        # pinned-rho lanes: the served action IS the pinned rho in unit
        # coordinates (the off-policy stream the IMPACT ratio corrects)
        np.testing.assert_allclose(
            tr["action"][:job.k],
            np.clip(calib_env._to_unit(job.rho), -1.0, 1.0), rtol=1e-6)
        want_reward = (r.sigma_data_img / max(r.sigma_res_img, 1e-12)
                       + 1e-4 / (r.img_std + calib_env.EPS))
        np.testing.assert_allclose(float(tr["reward"]), want_reward,
                                   rtol=1e-5)
        assert bool(tr["done"]) is True
        assert int(tr["version"]) == ver
        assert np.isfinite(float(tr["behavior_logp"]))


def test_tee_ingest_matches_offline_filled_buffer():
    """Storing the same transitions through ``ServingLearner.ingest``
    and through a direct offline ``replay_add_batch`` yields bitwise
    identical rings (the tee adds no transformation of its own)."""
    import jax

    from smartcal_tpu.rl import replay as rp
    from smartcal_tpu.rl import replay_sharded as rps
    from smartcal_tpu.rl import sac

    cfg = sac.SACConfig(obs_dim=6, n_actions=4, mem_size=32,
                        batch_size=8)
    rng = np.random.default_rng(11)
    trs = [{"state": rng.normal(size=6).astype(np.float32),
            "new_state": rng.normal(size=6).astype(np.float32),
            "action": rng.uniform(-1, 1, 4).astype(np.float32),
            "reward": np.float32(rng.normal()),
            "done": True,
            "hint": np.zeros(4, np.float32),
            "version": np.int32(i % 3),
            "behavior_logp": np.float32(-abs(rng.normal()))}
           for i in range(8)]
    ln = ServingLearner(cfg, seed=1, n_shards=4, ingest_chunk=4)
    assert ln.ingest(list(trs)) == len(trs)
    spec = rp.versioned_spec(rp.transition_spec(cfg.obs_dim,
                                                cfg.n_actions))
    buf = rps.place_on_mesh(rps.replay_init(cfg.mem_size, spec, 4))
    for lo in range(0, len(trs), 4):     # same fixed-chunk granularity
        flat = {k: np.stack([np.asarray(t[k]) for t in trs[lo:lo + 4]])
                for k in trs[0]}
        buf = rps.replay_add_batch(buf, flat)
    for k in spec:
        np.testing.assert_array_equal(
            np.asarray(ln.buffer.data[k]), np.asarray(buf.data[k]),
            err_msg=f"ring field {k!r} diverged")
    assert int(ln.buffer.cntr) == int(buf.cntr) == len(trs)


# ---------------------------------------------------------------------------
# hot-swap: parity, stale-version contract, zero-compile publication
# ---------------------------------------------------------------------------

def test_swap_identical_params_is_bit_identical(lifecycle):
    be, srv, learner, stage, pool, path = lifecycle
    stage.drain()
    cfg, params0 = srv._policy           # the installed snapshot

    def wave():
        jobs = [Job(episode=ep, k=k, rho=None, obs_vec=ov)
                for k, ep, ov in pool]
        srv.process_once(jobs, timeout=0.05)
        return [j.future.result(timeout=60) for j in jobs]

    r0 = wave()
    v = srv.policy_version
    swap = srv.swap_policy(params0, v + 1)
    assert swap["version"] == v + 1 and swap["version_prev"] == v
    r1 = wave()
    for a, b in zip(r0, r1):
        assert a.sigma_res == b.sigma_res
        assert a.sigma_data_img == b.sigma_data_img
        assert a.sigma_res_img == b.sigma_res_img
        assert a.img_std == b.img_std
    # the teed actions are identical too — same policy, same obs
    trs = stage.drain()
    half = len(trs) // 2
    for t0, t1 in zip(trs[:half], trs[half:]):
        np.testing.assert_array_equal(t0["action"], t1["action"])
        assert int(t1["version"]) == int(t0["version"]) + 1


def test_jobs_admitted_before_swap_carry_both_versions(lifecycle):
    be, srv, learner, stage, pool, path = lifecycle
    stage.drain()
    start = _lines(path)
    v = srv.policy_version
    k, ep, ov = pool[0]
    futs = [srv.submit(Job(episode=ep, k=k, rho=None, obs_vec=ov))
            for _ in range(2)]           # admitted under v
    cfg, params0 = srv._policy
    srv.swap_policy(params0, v + 1)      # lands before execution
    srv.process_once([], timeout=0.05)
    for f in futs:
        f.result(timeout=60)
    evs = [e for e in _events(path, "serve_request", start)
           if not e.get("warm")]
    assert len(evs) >= 2
    for e in evs[:2]:
        assert e["version_admitted"] == v
        assert e["version"] == v + 1
        assert "behavior_logp" in e


def test_republish_stream_compiles_nothing(lifecycle):
    """After the warm publish, every further publication (versioned
    ExportCache entry + swap + warm forward) is compile-free — the
    ISSUE 20 zero-compile serving-window contract."""
    be, srv, learner, stage, pool, path = lifecycle
    pub = learner.publisher
    v = srv.policy_version
    c0 = obs.counters_snapshot().get("jax_compile_events", 0.0)
    recs = [pub.publish(learner.actor_params, v + 1 + i)
            for i in range(3)]
    c1 = obs.counters_snapshot().get("jax_compile_events", 0.0)
    assert c1 - c0 == 0.0
    assert [r["version"] for r in recs] == [v + 1, v + 2, v + 3]
    assert srv.policy_version == v + 3
    assert all(r["publish_s"] < 30.0 for r in recs)
    # and the server still serves on the new version
    k, ep, ov = pool[0]
    job = Job(episode=ep, k=k, rho=None, obs_vec=ov)
    srv.process_once([job], timeout=0.05)
    assert np.isfinite(job.future.result(timeout=60).sigma_res)


# ---------------------------------------------------------------------------
# fleet: weight frames, replica independence
# ---------------------------------------------------------------------------

class _SwapRecorder:
    """Stands in for a replica's CalibServer in _WeightsPublisher."""

    def __init__(self):
        self.swaps = []
        self.seen = threading.Event()

    def swap_policy(self, params, version, program=None):
        self.swaps.append(int(version))
        self.seen.set()
        return {"version": int(version), "version_prev": 0,
                "swap_s": 0.0}


def test_weights_publisher_collapses_burst_latest_wins():
    from smartcal_tpu.serve.fleet import _WeightsPublisher

    rec = _SwapRecorder()
    wp = _WeightsPublisher(rec, replica_id=0)
    for v in (1, 2, 3):                  # burst lands before the thread
        wp.offer(v, {"w": np.zeros(2)})
    wp.start()
    assert rec.seen.wait(timeout=5.0)
    wp.request_stop()
    wp.join(timeout=5.0)
    assert rec.swaps == [3]              # intermediate versions skipped
    assert wp.swaps == 1


def test_publish_policy_reaches_ready_replicas_independently():
    from smartcal_tpu.serve import fleet as serve_fleet

    class _PubReplica:
        def __init__(self, ready=True, accept=True):
            self.ready = threading.Event()
            if ready:
                self.ready.set()
            self.accept = accept
            self.frames = []

        def publish(self, blob):
            if not self.accept:
                return False
            self.frames.append(blob)
            return True

    router = serve_fleet.FleetRouter.__new__(serve_fleet.FleetRouter)
    reps = [_PubReplica(), _PubReplica(ready=False), _PubReplica()]
    router._live = lambda: reps
    reached = serve_fleet.FleetRouter.publish_policy(
        router, {"w": np.arange(3, dtype=np.float32)}, version=4)
    assert reached == 2
    assert not reps[1].frames            # not-ready replica just misses
    # one frame, byte-identical to every replica — framed once
    assert reps[0].frames == reps[2].frames
    from smartcal_tpu.runtime import ipc
    kind, payload = ipc.unframe_payload(reps[0].frames[0])
    assert (kind, payload["version"]) == ("weights", 4)
    np.testing.assert_array_equal(payload["params"]["w"],
                                  np.arange(3, dtype=np.float32))


def test_server_gauges_carry_policy_version():
    from smartcal_tpu.serve.fleet import _server_gauges

    class _Srv:
        policy_version = 5
        lanes = 2

        def stats(self):
            return {}

        class batcher:
            @staticmethod
            def depth():
                return 0

            @staticmethod
            def service_estimate_s():
                return 0.0

    g = _server_gauges(_Srv())
    assert g["policy_version"] == 5
    assert g["queue_depth"] == 0
