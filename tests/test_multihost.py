"""Multi-host bring-up: jax.distributed wiring (the MASTER_ADDR edge).

The reference's distributed runtime is wired by torch-RPC env conventions
(elasticnet/distributed_per_sac.py:154-190); here the equivalent is
parallel.multihost.initialize over jax.distributed.  A REAL 2-process CPU
job over loopback runs in subprocesses (initialize must precede backend
init, so it cannot run in the test process itself).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from smartcal_tpu.parallel import multihost


def test_initialize_noop_without_config(monkeypatch):
    for v in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
              "JAX_PROCESS_ID"):
        monkeypatch.delenv(v, raising=False)
    assert multihost.initialize() is False


def test_add_cli_args_roundtrip():
    import argparse

    p = argparse.ArgumentParser()
    multihost.add_cli_args(p)
    args = p.parse_args(["--coordinator", "h:1234", "--num_processes", "2",
                         "--process_id", "1"])
    assert (args.coordinator, args.num_processes, args.process_id) == \
        ("h:1234", 2, 1)


_WORKER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from smartcal_tpu.parallel import multihost

coord, nproc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
assert multihost.initialize(coord, nproc, pid)
info = multihost.runtime_summary()
assert info["process_count"] == nproc, info
assert info["process_index"] == pid, info

# one real DCN collective across the processes: psum of the process index
import jax.numpy as jnp
from jax.experimental import multihost_utils

total = multihost_utils.process_allgather(jnp.asarray([pid]))
assert sorted(int(x) for x in total.ravel()) == list(range(nproc)), total
print("WORKER_OK", pid)
"""


# capability probe, by attempt: some jax builds' CPU backend refuses
# cross-process collectives outright with exactly this error — on those
# the 2-process job can never pass ANY implementation, so the test
# skips (documented environment gap) instead of failing the tier
_CPU_MULTIPROC_UNSUPPORTED = "Multiprocess computations aren't implemented"


def test_two_process_cpu_job(tmp_path):
    """Both processes initialize, see process_count==2, and complete an
    allgather over the distributed client.

    Default-tier since round 3 (VERDICT r2 item 7): ~20 s wall — the
    default suite must exercise real multi-process ``jax.distributed``
    init + a cross-process collective, not only the single-process
    virtual-mesh paths.  The 120 s communicate() timeout keeps a wedged
    coordinator from hanging the suite.  Skips (capability gate) when
    the installed jax's CPU backend reports multiprocess computations
    as unimplemented — see ``_CPU_MULTIPROC_UNSUPPORTED``."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)      # no virtual-device split in the workers
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, coord, "2", str(i)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outs.append(out)
    finally:
        # a wedged coordinator must not leak live workers into the rest
        # of the suite — kill and reap both on any exit path
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    if any(p.returncode != 0 and _CPU_MULTIPROC_UNSUPPORTED in out
           for p, out in zip(procs, outs)):
        pytest.skip("this jax build's CPU backend has no multiprocess "
                    f"collectives ({_CPU_MULTIPROC_UNSUPPORTED!r}) — "
                    "the 2-process DCN path needs a chip or a CPU "
                    "backend with cross-process collective support")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"WORKER_OK {i}" in out
