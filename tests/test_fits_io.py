"""First-party FITS image I/O (cal/fits_io.py): byte-level format checks,
round trips, and calmean.sh-parity weighted averaging.  Pure numpy."""

import math

import numpy as np
import pytest

from smartcal_tpu.cal import fits_io


def test_roundtrip_and_layout(tmp_path):
    rng = np.random.default_rng(0)
    img = rng.standard_normal((16, 8)).astype(np.float32)  # ny=16, nx=8
    p = str(tmp_path / "img.fits")
    fits_io.write_image(p, img, ra0=1.2, dec0=0.9, cell_rad=2e-5,
                        freq=135e6, bmaj=0.01, bmin=0.005, bpa=30.0,
                        object_name="TEST")
    back, hdr = fits_io.read_image(p)
    np.testing.assert_array_equal(back, img)
    assert hdr["NAXIS"] == 4 and hdr["NAXIS1"] == 8 and hdr["NAXIS2"] == 16
    assert hdr["CTYPE1"] == "RA---SIN"
    assert hdr["CRVAL1"] == pytest.approx(math.degrees(1.2))
    assert hdr["CRVAL3"] == pytest.approx(135e6)
    assert hdr["BPA"] == pytest.approx(30.0)
    assert hdr["OBJECT"] == "TEST"

    # FITS structure: 2880-byte records, big-endian float32 payload
    raw = open(p, "rb").read()
    assert len(raw) % fits_io.BLOCK == 0
    assert raw[:6] == b"SIMPLE"
    data_start = len(raw) - ((img.size * 4 + fits_io.BLOCK - 1)
                             // fits_io.BLOCK) * fits_io.BLOCK
    first = np.frombuffer(raw[data_start:data_start + 4], ">f4")[0]
    assert first == img[0, 0]


def test_header_string_quoting_and_comment_slash(tmp_path):
    p = str(tmp_path / "q.fits")
    fits_io.write_image(p, np.zeros((4, 4), np.float32),
                        extra={"TELESCOP": "LO'FAR/X", "SEQ": 7,
                               "FLAG": True})
    _, hdr = fits_io.read_image(p)
    assert hdr["TELESCOP"] == "LO'FAR/X"   # quote escape + slash in string
    assert hdr["SEQ"] == 7
    assert hdr["FLAG"] is True


def test_read_bitpix16_with_scaling(tmp_path):
    """Hand-crafted 16-bit FITS with BSCALE/BZERO (a layout external
    tools may emit)."""
    cards = [
        f"{'SIMPLE':<8}= {'T':>20}", f"{'BITPIX':<8}= {16:>20}",
        f"{'NAXIS':<8}= {2:>20}", f"{'NAXIS1':<8}= {3:>20}",
        f"{'NAXIS2':<8}= {2:>20}", f"{'BSCALE':<8}= {0.5:>20}",
        f"{'BZERO':<8}= {10.0:>20}", "END",
    ]
    header = b"".join(f"{c:<80}".encode() for c in cards)
    header += b" " * ((-len(header)) % fits_io.BLOCK)
    vals = np.arange(6, dtype=">i2").reshape(2, 3)
    payload = vals.tobytes()
    payload += b"\0" * ((-len(payload)) % fits_io.BLOCK)
    p = tmp_path / "scaled.fits"
    p.write_bytes(header + payload)
    data, hdr = fits_io.read_image(str(p))
    np.testing.assert_allclose(data, np.arange(6).reshape(2, 3) * 0.5 + 10)


def test_fits_mean_weighting_and_beam(tmp_path):
    """calmean parity: inverse-variance weights, circular BPA mean,
    weighted FREQ, variance gate."""
    paths = []
    stds = [0.001, 0.002]
    bpas = [350.0, 10.0]
    freqs = [100e6, 140e6]
    rng = np.random.default_rng(1)
    for i, (s, bpa, f) in enumerate(zip(stds, bpas, freqs)):
        img = np.zeros((16, 16), np.float32)
        img[1:10, 1:10] = rng.standard_normal((9, 9)).astype(np.float32) * s
        p = str(tmp_path / f"in{i}.fits")
        fits_io.write_image(p, img, freq=f, bmaj=0.01 * (i + 1),
                            bmin=0.005, bpa=bpa)
        paths.append(p)
    # a rejected image FIRST in the list: std in the box far above vmax —
    # its header/WCS must not leak into the output (the base header comes
    # from the first ACCEPTED image)
    junk = np.full((16, 16), 0.0, np.float32)
    junk[1:10, 1:10] = rng.standard_normal((9, 9)).astype(np.float32) * 10
    pj = str(tmp_path / "junk.fits")
    fits_io.write_image(pj, junk, ra0=2.9, freq=999e6, bmaj=9.9, bmin=9.9,
                        bpa=90.0)
    paths.insert(0, pj)

    out = str(tmp_path / "bar.fits")
    fits_io.fits_mean(paths, out, vmax=0.01)
    mean, hdr = fits_io.read_image(out)
    assert hdr["NIMAGES"] == 2                      # junk rejected
    # the rejected first image's WCS did not become the output frame
    assert hdr["CRVAL1"] == pytest.approx(0.0)
    # weights: sigma_i = 1/std_i^2 computed from the written images
    imgs = [fits_io.read_image(p)[0] for p in paths[1:]]
    sig = [1.0 / float(np.std(im[1:10, 1:10])) ** 2 for im in imgs]
    want = (imgs[0] * sig[0] + imgs[1] * sig[1]) / sum(sig)
    np.testing.assert_allclose(mean, want.astype(np.float32), atol=1e-6)
    # BPA weighted circular mean of 350 and 10 degrees sits between
    # them across the wrap (never the naive arithmetic ~180)
    want_bpa = math.degrees(math.atan2(
        sig[0] * math.sin(math.radians(350)) + sig[1] * math.sin(
            math.radians(10)),
        sig[0] * math.cos(math.radians(350)) + sig[1] * math.cos(
            math.radians(10))))
    assert hdr["BPA"] == pytest.approx(want_bpa, abs=1e-6)
    w_freq = (freqs[0] * sig[0] + freqs[1] * sig[1]) / sum(sig)
    assert hdr["CRVAL3"] == pytest.approx(w_freq, rel=1e-6)
    assert hdr["RESTFREQ"] == pytest.approx(w_freq, rel=1e-6)
    # weighted beam major axis
    w_bmaj = (0.01 * sig[0] + 0.02 * sig[1]) / sum(sig)
    assert hdr["BMAJ"] == pytest.approx(w_bmaj, rel=1e-6)


def test_long_keyword_rejected(tmp_path):
    """An over-long extra keyword must fail loudly, never truncate into a
    collision with a standard card (RESTFREQX -> RESTFREQ)."""
    with pytest.raises(ValueError, match="exceeds 8"):
        fits_io.write_image(str(tmp_path / "x.fits"),
                            np.zeros((4, 4), np.float32),
                            extra={"RESTFREQX": 1.0})


def test_fits_mean_all_rejected(tmp_path):
    """Every input rejected: zero image in the first input's frame,
    consistent CRVAL3/RESTFREQ (no 0-Hz RESTFREQ next to a real CRVAL3)."""
    img = np.zeros((8, 8), np.float32)
    img[1:4, 1:4] = 100.0 * np.arange(9, dtype=np.float32).reshape(3, 3)
    p = str(tmp_path / "r.fits")
    fits_io.write_image(p, img, freq=123e6)
    out = str(tmp_path / "none.fits")
    fits_io.fits_mean([p], out, vmax=0.01)
    mean, hdr = fits_io.read_image(out)
    assert hdr["NIMAGES"] == 0
    np.testing.assert_array_equal(mean, 0.0)
    assert hdr["CRVAL3"] == pytest.approx(123e6)
    assert hdr["RESTFREQ"] == pytest.approx(123e6)


def test_fits_mean_accept_all_mode(tmp_path):
    """vmax=1.0 reproduces the shipped script's short-circuited
    accept-all behavior (wt hardcoded 0.99999): every image weighted
    equally regardless of content."""
    paths = []
    for i in range(3):
        img = np.full((8, 8), float(i), np.float32)
        img[1:4, 1:4] += np.linspace(0, 0.5, 9).reshape(3, 3)
        p = str(tmp_path / f"m{i}.fits")
        fits_io.write_image(p, img, freq=100e6)
        paths.append(p)
    out = str(tmp_path / "mean.fits")
    fits_io.fits_mean(paths, out, vmax=1.0)
    mean, hdr = fits_io.read_image(out)
    assert hdr["NIMAGES"] == 3


def test_imager_image_to_fits_roundtrip(tmp_path):
    """The device imager's output writes straight to FITS and reads back
    (the excon -> env.reset FITS contract, calibenv.py:148-158)."""
    import jax

    from smartcal_tpu.envs.radio import RadioBackend

    backend = RadioBackend(n_stations=6, n_freqs=2, n_times=4, tdelta=2,
                           admm_iters=2, lbfgs_iters=3, init_iters=4,
                           npix=16)
    from smartcal_tpu.cal import imager

    ep, mdl = backend.new_demixing_episode(jax.random.PRNGKey(2), 3)
    img = np.asarray(backend.data_image(ep))
    p = str(tmp_path / "data.fits")
    imager.image_to_fits(p, img, ep.obs)
    back, hdr = fits_io.read_image(p)
    np.testing.assert_array_equal(back, img.astype(np.float32))
    assert hdr["CRVAL1"] == pytest.approx(math.degrees(ep.obs.ra0))
    assert hdr["CRVAL3"] == pytest.approx(
        float(np.asarray(ep.obs.freqs)[-1]))
    assert hdr["CDELT2"] > 0


def test_overlong_string_value_raises(tmp_path):
    """String values that cannot fit a single card raise instead of
    silently truncating (possibly mid doubled-quote) — ADVICE r4 item 1:
    the same never-truncate-silently policy as over-length keywords."""
    img = np.zeros((4, 4), np.float32)
    with pytest.raises(ValueError, match="67 characters"):
        fits_io.write_image(str(tmp_path / "a.fits"), img,
                            extra={"LONGVAL": "x" * 70})
    # escaping can push a representable-looking value over the limit:
    # 40 quotes escape to 80 chars — must raise, never emit a split pair
    with pytest.raises(ValueError, match="67 characters"):
        fits_io.write_image(str(tmp_path / "b.fits"), img,
                            extra={"QUOTED": "'" * 40})
    # a value at exactly the limit still round-trips
    p = fits_io.write_image(str(tmp_path / "c.fits"), img,
                            extra={"EDGEVAL": "y" * 67})
    _, hdr = fits_io.read_image(p)
    assert hdr["EDGEVAL"] == "y" * 67


def test_fits_mean_carries_base_header(tmp_path):
    """fits_mean carries the accepted base image's non-computed cards
    (OBJECT, off-center CRPIX, non-square CDELT1) into the output — the
    reference calmean copies the full first header (ADVICE r4 item 2)."""
    rng = np.random.default_rng(5)
    paths = []
    for i in range(2):
        img = rng.normal(0.0, 1e-3, (16, 16)).astype(np.float32)
        p = str(tmp_path / f"in{i}.fits")
        fits_io.write_image(
            p, img, freq=120e6, object_name="3C196",
            extra={"CRPIX1": 3.0, "CRPIX2": 5.0, "CDELT1": -2e-3,
                   "TELESCOP": "LOFAR"})
        paths.append(p)
    out = str(tmp_path / "mean.fits")
    fits_io.fits_mean(paths, out, vmax=1.0)
    _, hdr = fits_io.read_image(out)
    assert hdr["OBJECT"] == "3C196"
    assert hdr["TELESCOP"] == "LOFAR"
    assert hdr["CRPIX1"] == pytest.approx(3.0)
    assert hdr["CRPIX2"] == pytest.approx(5.0)
    assert hdr["CDELT1"] == pytest.approx(-2e-3)
    # overridden cards appear ONCE (in-place override, no duplicates)
    with open(out, "rb") as fh:
        raw = fh.read(2880 * 2).decode("ascii", "replace")
    assert raw.count("CRPIX1") == 1
