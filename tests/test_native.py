"""First-party native layer: SCT columnar store + C++ sum-tree PER.

The sum tree is golden-tested against a direct python re-expression of the
reference's SumTree walk (elasticnet/enet_sac.py:120-196), and the
NativePER sampler is cross-checked distributionally against the device
prefix-sum PER in rl.replay (same stratified scheme — identical segment
draws must pick identical leaves).
"""

import os

import numpy as np
import pytest

from smartcal_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


# ---------------------------------------------------------------------------
# SCT store
# ---------------------------------------------------------------------------

def test_sct_roundtrip_all_dtypes(tmp_path, rng):
    cols = {
        "f32": rng.standard_normal((5, 3)).astype(np.float32),
        "f64": rng.standard_normal(7),
        "i32": rng.integers(-5, 5, (4, 2)).astype(np.int32),
        "i64": rng.integers(-5, 5, 6).astype(np.int64),
        "c64": (rng.standard_normal((3, 1, 4))
                + 1j * rng.standard_normal((3, 1, 4))).astype(np.complex64),
        "c128": (rng.standard_normal(2)
                 + 1j * rng.standard_normal(2)).astype(np.complex128),
        "scalar": np.float64(42.5),
        "empty": np.zeros((0, 3), np.float32),
    }
    path = str(tmp_path / "t.sct")
    native.sct_write(path, cols)
    back = native.sct_read(path)
    assert set(back) == set(cols)
    for k, v in cols.items():
        a = np.asarray(v)
        assert back[k].dtype == a.dtype and back[k].shape == a.shape
        np.testing.assert_array_equal(back[k], a)


def test_sct_bool_and_strided(tmp_path):
    flags = np.array([True, False, True, True])
    strided = np.arange(24, dtype=np.float32).reshape(4, 6)[:, ::2]
    path = str(tmp_path / "t.sct")
    native.sct_write(path, {"FLAG": flags, "S": strided})
    back = native.sct_read(path)
    np.testing.assert_array_equal(back["FLAG"], flags.astype(np.uint8))
    np.testing.assert_array_equal(back["S"], strided)


def test_sct_python_reader_matches_native(tmp_path, rng):
    """The pure-python fallback reader (no-toolchain hosts) decodes a
    native-written file identically, whole-table and single-column."""
    cols = {
        "MAIN/DATA": (rng.standard_normal((6, 1, 4))
                      + 1j * rng.standard_normal((6, 1, 4))
                      ).astype(np.complex64),
        "META/CHAN_FREQ": np.asarray([42e6]),
        "META/N_ANTENNA": np.int64(4),
    }
    path = str(tmp_path / "t.sct")
    native.sct_write(path, cols)
    via_py = native._py_read(path)
    via_native = native.sct_read(path)
    assert set(via_py) == set(via_native)
    for k in via_py:
        np.testing.assert_array_equal(via_py[k], via_native[k])
        assert via_py[k].dtype == via_native[k].dtype
    np.testing.assert_array_equal(
        native._py_read(path, only="META/CHAN_FREQ"), cols["META/CHAN_FREQ"])
    with pytest.raises(KeyError):
        native._py_read(path, only="NOPE")


def test_sct_bad_file_raises(tmp_path):
    bad = tmp_path / "bad.sct"
    bad.write_bytes(b"not a table")
    with pytest.raises(IOError):
        native.sct_read(str(bad))
    with pytest.raises(IOError):
        native.sct_read(str(tmp_path / "missing.sct"))


def test_sct_atomic_overwrite(tmp_path):
    path = str(tmp_path / "t.sct")
    native.sct_write(path, {"a": np.arange(3, dtype=np.int64)})
    native.sct_write(path, {"b": np.arange(5, dtype=np.float32)})
    back = native.sct_read(path)
    assert set(back) == {"b"}
    assert not [f for f in os.listdir(tmp_path) if ".tmp" in f]


def test_ms_io_sct_backend_roundtrip(tmp_path, monkeypatch, rng):
    """write_observation_ms -> read_corr through the SCT backend matches
    the npz backend bit-for-bit."""
    import jax

    from smartcal_tpu.cal import ms_io
    from smartcal_tpu.cal.observation import make_observation

    obs = make_observation(jax.random.PRNGKey(3), n_stations=5, n_times=3,
                           n_freqs=1)
    T, B = 3, 10
    V0 = rng.standard_normal((T, B, 2, 2, 2)).astype(np.float32)

    paths = {}
    for fmt in ("sct", "npz"):
        monkeypatch.setenv("SMARTCAL_MS_FORMAT", fmt)
        p = str(tmp_path / f"obs_{fmt}.MS")
        ms_io.write_observation_ms(p, obs, V0, float(obs.freqs[0]))
        paths[fmt] = p
    assert ms_io.is_sct_ms(paths["sct"]) and not ms_io.is_sct_ms(paths["npz"])

    ref = ms_io.read_corr(paths["npz"], "DATA")
    got = ms_io.read_corr(paths["sct"], "DATA")
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    ia, ib = ms_io.ms_info(paths["npz"]), ms_io.ms_info(paths["sct"])
    assert ia.n_stations == ib.n_stations and ia.n_times == ib.n_times
    np.testing.assert_allclose(ia.freqs, ib.freqs)


def test_ms_io_sct_mutations(tmp_path, monkeypatch):
    """add_column / write_corr / change_freq / add_noise through SCT."""
    import jax

    from smartcal_tpu.cal import ms_io
    from smartcal_tpu.cal.observation import make_observation

    monkeypatch.setenv("SMARTCAL_MS_FORMAT", "sct")
    obs = make_observation(jax.random.PRNGKey(0), n_stations=4, n_times=2,
                           n_freqs=1)
    T, B = 2, 6
    V = np.zeros((T, B, 2, 2, 2), np.float32)
    p = str(tmp_path / "m.MS")
    ms_io.write_observation_ms(p, obs, V, 50e6)

    ms_io.add_column(p, "CORRECTED_DATA")
    xx = np.arange(T * B, dtype=np.csingle)
    ms_io.write_corr(p, xx, 0 * xx, 0 * xx, xx, "CORRECTED_DATA")
    _, _, _, rxx, _, _, ryy = ms_io.read_corr(p, "CORRECTED_DATA")
    np.testing.assert_allclose(rxx, xx)
    np.testing.assert_allclose(ryy, xx)

    ms_io.change_freq(p, 42e6)
    assert ms_io.ms_info(p).ref_freq == 42e6

    ms_io.add_noise(p, snr=5.0, rng=np.random.default_rng(0),
                    colname="CORRECTED_DATA")
    _, _, _, nxx, _, _, _ = ms_io.read_corr(p, "CORRECTED_DATA")
    assert not np.allclose(nxx, xx)


# ---------------------------------------------------------------------------
# Sum tree vs python oracle (reference SumTree semantics)
# ---------------------------------------------------------------------------

def _oracle_get_leaf(leaves, v):
    """Direct walk of the implicit tree (enet_sac.py:164-196)."""
    cap = len(leaves)
    tree = np.zeros(2 * cap)
    tree[cap:] = leaves
    for i in range(cap - 1, 0, -1):
        tree[i] = tree[2 * i] + tree[2 * i + 1]
    node = 1
    while node < cap:
        left = 2 * node
        if v <= tree[left]:
            node = left
        else:
            v -= tree[left]
            node = left + 1
    return node - cap


def test_sumtree_matches_oracle(rng):
    t = native.SumTree(16)
    pri = rng.random(16) + 0.01
    for p in pri:
        t.add(float(p))
    assert t.filled == 16
    np.testing.assert_allclose(t.total(), pri.sum(), rtol=1e-12)
    np.testing.assert_allclose(t.max_priority(), pri.max())
    for v in rng.random(50) * pri.sum():
        leaf, p = t.get_leaf(float(v))
        assert leaf == _oracle_get_leaf(pri, v)
        np.testing.assert_allclose(p, pri[leaf])


def test_sumtree_ring_overwrite():
    t = native.SumTree(4)
    for p in [1.0, 2.0, 3.0, 4.0, 10.0]:   # 5th wraps onto leaf 0
        t.add(p)
    np.testing.assert_allclose(t.total(), 10 + 2 + 3 + 4)
    np.testing.assert_allclose(t.leaves(), [10.0, 2.0, 3.0, 4.0])
    assert t.cursor == 1 and t.filled == 4


def test_sumtree_update_and_state_roundtrip(rng):
    t = native.SumTree(8)
    for p in rng.random(8):
        t.add(float(p))
    t.update_batch([0, 3, 7], [5.0, 6.0, 7.0])
    leaves = t.leaves()
    np.testing.assert_allclose(leaves[[0, 3, 7]], [5.0, 6.0, 7.0])
    t2 = native.SumTree(8)
    t2.set_state(leaves, t.cursor, t.filled)
    np.testing.assert_allclose(t2.total(), t.total(), rtol=1e-12)
    assert t2.get_leaf(t.total() * 0.999)[0] == t.get_leaf(t.total() * 0.999)[0]


def test_sumtree_sampling_distribution(rng):
    """Stratified draws land proportionally to priority (chi-square-ish)."""
    pri = np.array([1.0, 1.0, 1.0, 13.0])
    t = native.SumTree(4)
    for p in pri:
        t.add(float(p))
    counts = np.zeros(4)
    for _ in range(200):
        idx, _ = t.sample_stratified(4, rng.random(4))
        np.add.at(counts, idx, 1)
    freq = counts / counts.sum()
    np.testing.assert_allclose(freq, pri / pri.sum(), atol=0.1)


# ---------------------------------------------------------------------------
# NativePER vs device PER (rl.replay)
# ---------------------------------------------------------------------------

def test_native_per_matches_device_per_sampling(rng):
    """End-to-end cross-check of the two PER implementations: identical
    priorities + identical segment uniforms -> identical index draws AND
    identical IS weights from NativePER.sample and replay_sample_per."""
    import jax
    import jax.numpy as jnp

    from smartcal_tpu.rl import replay as rp
    from smartcal_tpu.rl.replay_native import NativePER

    size, batch = 16, 8
    spec = rp.transition_spec(3, 2)
    # dyadic-rational priorities: exactly representable in float32 AND
    # float64, so both backends' cumulative sums agree bit-for-bit and the
    # segment boundaries cannot flip between implementations
    pri = rng.integers(1, 100, size).astype(np.float64) / 64.0

    nbuf = NativePER(size, spec)
    for i in range(size):
        tr = {k: np.zeros(shape, np.float64) + i
              for k, (shape, _) in spec.items()}
        nbuf.store(tr)
    nbuf.tree.update_batch(np.arange(size), pri)

    u = rng.random(batch)
    idx_native, pri_native = nbuf.tree.sample_stratified(batch, u)
    csum = np.cumsum(pri)                      # float64 oracle
    values = (np.arange(batch) + u) * (csum[-1] / batch)
    idx_oracle = np.searchsorted(csum, values, side="left")
    np.testing.assert_array_equal(idx_native,
                                  np.clip(idx_oracle, 0, size - 1))
    np.testing.assert_allclose(pri_native, pri[idx_native])

    # the ACTUAL device path: seed a device buffer with the same
    # priorities, extract the uniforms its key produces, and hand the very
    # same uniforms to NativePER.sample — fresh buffers on both sides, so
    # beta anneals identically too
    dbuf = rp.replay_init(size, spec)
    dbuf = dbuf._replace(priority=jnp.asarray(pri, jnp.float32),
                         cntr=jnp.asarray(size, jnp.int32))
    key = jax.random.PRNGKey(0)
    _, didx, dw, _ = rp.replay_sample_per(dbuf, key, batch)
    u_dev = np.asarray(jax.random.uniform(key, (batch,)), np.float64)
    batch_data, idx, is_w = nbuf.sample(batch, np.random.default_rng(7),
                                        uniforms=u_dev)
    assert batch_data["state"].shape == (batch, 3)
    np.testing.assert_array_equal(idx, np.asarray(didx))
    np.testing.assert_allclose(is_w, np.asarray(dw), rtol=1e-5)


def test_native_per_priority_rules_and_checkpoint(tmp_path, rng):
    from smartcal_tpu.rl import replay as rp
    from smartcal_tpu.rl.replay_native import NativePER

    spec = rp.transition_spec(2, 1)
    buf = NativePER(8, spec, error_clip=1.0)
    tr = {k: np.zeros(shape) for k, (shape, _) in spec.items()}

    buf.store(tr)                       # empty -> clip
    assert buf.tree.leaves()[0] == 1.0
    buf.store(tr, error=0.5)            # (0.5+eps)^alpha capped at clip
    expect = min((0.5 + rp.PER_EPSILON) ** rp.PER_ALPHA, 1.0)
    np.testing.assert_allclose(buf.tree.leaves()[1], expect)
    buf.store(tr)                       # non-empty -> max priority
    np.testing.assert_allclose(buf.tree.leaves()[2],
                               buf.tree.max_priority())

    buf.update_priorities([0, 1], [3.0, 0.2])
    lv = buf.tree.leaves()
    np.testing.assert_allclose(lv[0], 1.0 ** rp.PER_ALPHA)      # clipped
    np.testing.assert_allclose(lv[1], (0.2 + rp.PER_EPSILON) ** rp.PER_ALPHA)

    p = str(tmp_path / "per.pkl")
    buf.save(p)
    back = NativePER.load(p)
    np.testing.assert_allclose(back.tree.leaves(), buf.tree.leaves())
    assert back.cntr == buf.cntr and back.beta == buf.beta
    b1, i1, w1 = buf.sample(4, np.random.default_rng(0))
    b2, i2, w2 = back.sample(4, np.random.default_rng(0))
    np.testing.assert_array_equal(i1, i2)
    np.testing.assert_allclose(w1, w2)


def test_native_per_rejects_non_pow2():
    from smartcal_tpu.rl import replay as rp
    from smartcal_tpu.rl.replay_native import NativePER

    with pytest.raises(ValueError):
        NativePER(10, rp.transition_spec(2, 1))


def test_native_per_partial_fill_no_nan_weights(rng):
    """A stratified draw at u=1.0 on a partially-filled buffer walks into
    the unfilled (zero-priority) suffix; the IS weights must stay finite
    (the zero-priority leaf is clamped back into the filled prefix)."""
    from smartcal_tpu.rl import replay as rp
    from smartcal_tpu.rl.replay_native import NativePER

    spec = rp.transition_spec(2, 1)
    buf = NativePER(8, spec)
    tr = {k: np.zeros(shape) for k, (shape, _) in spec.items()}
    for i in range(3):                    # filled=3 of 8
        t = dict(tr)
        t["state"] = np.full(2, i, np.float32)
        buf.store(t, error=0.1 * (i + 1))
    # u=1.0 in the last segment maxes the walk value; fp rounding can land
    # on the boundary leaf — force the worst case deterministically
    b, idx, w = buf.sample(4, np.random.default_rng(0),
                           uniforms=[1.0, 1.0, 1.0, 1.0])
    assert np.all(np.isfinite(w))
    assert np.all(idx < 3)
    assert np.all(w > 0)


def test_sct_header_dims_nbytes_mismatch_raises_ioerror(tmp_path):
    """_py_read reports a dims/nbytes disagreement as IOError like the
    native reader, not as a numpy ValueError."""
    import struct

    path = tmp_path / "corrupt.sct"
    name = b"col"
    # dtype code for float64 per CODE_DTYPES, ndim=1, dims=(4,) but
    # nbytes=17 (neither a multiple of 8 nor 4*8)
    code = next(c for c, dt in native.CODE_DTYPES.items()
                if np.dtype(dt) == np.float64)
    hdr = (b"SCT1" + struct.pack("<I", 1) + struct.pack("<I", len(name))
           + name + struct.pack("<II", code, 1) + struct.pack("<Q", 4)
           + struct.pack("<Q", 17))
    path.write_bytes(hdr + b"\x00" * 256)
    with pytest.raises(IOError):
        native._py_read(str(path))
