"""Distributed demixing PER learner (discrete 2^(K-1) actions) on the
8-device mesh — VERDICT r1 item 4."""

import jax
import numpy as np
import pytest

from smartcal_tpu.envs.radio import RadioBackend
from smartcal_tpu.parallel import make_mesh
from smartcal_tpu.parallel.demix_learner import (
    make_distributed_demix_sac, make_workloads, mask_table)
from smartcal_tpu.rl import sac_discrete as dsac

K = 4
STATIONS = 6
NPIX = 8


def _backend():
    return RadioBackend(n_stations=STATIONS, n_times=8, tdelta=4,
                        npix=NPIX, admm_iters=2, lbfgs_iters=3,
                        init_iters=4)


def test_mask_table():
    tbl = mask_table(K)
    assert tbl.shape == (2 ** (K - 1), K)
    # target (last direction) always selected; index 0 = target only
    assert np.all(tbl[:, K - 1] == 1.0)
    np.testing.assert_array_equal(tbl[0], [0, 0, 0, 1])
    # index 2^(K-1)-1 = all directions
    np.testing.assert_array_equal(tbl[-1], [1, 1, 1, 1])
    # bit decode matches scalar_to_kvec ordering (LSB = last outlier)
    np.testing.assert_array_equal(tbl[1], [0, 0, 1, 1])


def test_discrete_sac_learn_smoke():
    cfg = dsac.DSACConfig(obs_dim=NPIX * NPIX + 3 * K + 2,
                          n_actions=2 ** (K - 1),
                          img_shape=(NPIX, NPIX), use_image=True,
                          batch_size=8, mem_size=32)
    st = dsac.dsac_init(jax.random.PRNGKey(0), cfg)
    from smartcal_tpu.rl import replay as rp

    buf = rp.replay_init(cfg.mem_size, dsac.transition_spec(cfg.obs_dim))
    rng = np.random.default_rng(0)
    for i in range(12):
        tr = {"state": rng.standard_normal(cfg.obs_dim).astype(np.float32),
              "action": np.int32(rng.integers(cfg.n_actions)),
              "reward": np.float32(rng.standard_normal()),
              "new_state":
                  rng.standard_normal(cfg.obs_dim).astype(np.float32),
              "done": False}
        buf = rp.replay_add(buf, tr)
    st2, buf2, m = jax.jit(
        lambda s, b, k: dsac.learn(cfg, s, b, k))(st, buf,
                                                  jax.random.PRNGKey(1))
    assert int(st2.learn_counter) == 1
    assert np.isfinite(float(m["critic_loss"]))
    # actions sample within range, argmax deterministic path works
    a = dsac.choose_action(cfg, st2, np.zeros((3, cfg.obs_dim),
                                              np.float32),
                           jax.random.PRNGKey(2))
    assert a.shape == (3,) and np.all((np.asarray(a) >= 0)
                                      & (np.asarray(a) < cfg.n_actions))
    a_det = dsac.choose_action(cfg, st2, np.zeros((3, cfg.obs_dim),
                                                  np.float32),
                               jax.random.PRNGKey(3), deterministic=True)
    assert np.all(np.asarray(a_det) == np.asarray(a_det)[0])


@pytest.mark.parametrize("provide_influence", [
    False, pytest.param(True, marks=pytest.mark.slow)])
def test_distributed_demix_8_devices(provide_influence):
    mesh = make_mesh((8,), ("dp",))
    backend = _backend()
    agent_cfg = dsac.DSACConfig(
        obs_dim=NPIX * NPIX + 3 * K + 2, n_actions=2 ** (K - 1),
        img_shape=(NPIX, NPIX), use_image=provide_influence,
        batch_size=16, mem_size=128)
    init_fn, make_wl, run_episode = make_distributed_demix_sac(
        backend, K, agent_cfg, mesh, n_actors=8, rollout_epochs=1,
        rollout_steps=2, provide_influence=provide_influence)
    st = init_fn(jax.random.PRNGKey(0))
    wl = make_wl(jax.random.PRNGKey(1))
    # workloads sharded over dp, learner replicated
    assert "dp" in {s for s in wl.V.sharding.spec}

    st, metrics = run_episode(st, wl, jax.random.PRNGKey(2))
    assert int(st.buf.cntr) == 16                  # 8 actors x 1 x 2
    assert np.isfinite(float(metrics["mean_reward"]))
    assert int(st.agent.learn_counter) == 1        # cntr hit batch_size
    # second episode keeps learning on fresh workloads
    st, metrics = run_episode(st, make_wl(jax.random.PRNGKey(3)),
                              jax.random.PRNGKey(4))
    assert int(st.agent.learn_counter) == 2
    assert np.isfinite(float(metrics["critic_loss"]))


def test_workload_shapes():
    backend = _backend()
    wl = make_workloads(backend, K, n_actors=2, n_epochs=1,
                        key=jax.random.PRNGKey(0))
    B = STATIONS * (STATIONS - 1) // 2
    assert wl.V.shape == (2, 1, backend.n_freqs, 8, B, 2, 2, 2)
    assert wl.Ccal.shape[:4] == (2, 1, backend.n_freqs, K)
    assert wl.metadata.shape == (2, 1, 3 * K + 2)
    assert np.all(np.isfinite(np.asarray(wl.cell)))
