"""Training-internals telemetry: UpdateDiag bit-identity + single-trace
contract for all four agents, replay health summaries, the divergence
watchdog (unit + end-to-end driver halt), FLOPs/roofline cost
accounting, and the obs_report training-health/roofline sections."""

import io
import json
import math
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smartcal_tpu import obs
from smartcal_tpu.obs import costs
from smartcal_tpu.rl import ddpg, sac, td3
from smartcal_tpu.rl import replay as rp
from smartcal_tpu.rl import sac_discrete as dsac
from smartcal_tpu.train import blocks

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")
sys.path.insert(0, TOOLS)
import obs_report  # noqa: E402
import obs_tail    # noqa: E402


@pytest.fixture(autouse=True)
def clean_obs_state():
    """No active RunLog, no armed cost collection, empty caches."""
    while obs.active() is not None:
        obs.deactivate()
    obs.reset_counters()
    costs.set_enabled(False)
    costs.reset_cache()
    yield
    while obs.active() is not None:
        obs.deactivate()
    obs.reset_counters()
    costs.set_enabled(False)
    costs.reset_cache()


def read_jsonl(path):
    return [json.loads(ln) for ln in open(path).read().splitlines()]


OBS_DIM, N_ACT = 5, 2


def _tr(rng, obs_dim=OBS_DIM, n_actions=N_ACT):
    return {"state": rng.standard_normal(obs_dim).astype(np.float32),
            "new_state": rng.standard_normal(obs_dim).astype(np.float32),
            "action": rng.standard_normal(n_actions).astype(np.float32),
            "reward": np.float32(rng.standard_normal()),
            "done": False,
            "hint": rng.standard_normal(n_actions).astype(np.float32)}


def _filled_buf(n=8, mem=16, prioritized=False):
    buf = rp.replay_init(mem, rp.transition_spec(OBS_DIM, N_ACT))
    rng = np.random.default_rng(0)
    for _ in range(n):
        buf = rp.replay_add(buf, _tr(rng),
                            priority=None if prioritized
                            else jnp.asarray(1.0),
                            error=jnp.asarray(abs(rng.standard_normal()))
                            if prioritized else None)
    return buf


def _assert_trees_bit_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _check_on_off(learn, cfg, st, buf, n_steps=2):
    """Run ``n_steps`` chained updates with collect_diag on and off and
    assert the primary outputs are bit-identical; returns the last diag."""
    f_off = jax.jit(lambda s, b, k: learn(cfg, s, b, k, collect_diag=False))
    f_on = jax.jit(lambda s, b, k: learn(cfg, s, b, k, collect_diag=True))
    st_off = st_on = st
    buf_off = buf_on = buf
    diag = None
    for i in range(n_steps):
        k = jax.random.PRNGKey(100 + i)
        st_off, buf_off, m_off = f_off(st_off, buf_off, k)
        st_on, buf_on, m_on = f_on(st_on, buf_on, k)
        diag = m_on.pop("diag")
        _assert_trees_bit_equal(st_off, st_on)
        _assert_trees_bit_equal(buf_off, buf_on)
        assert set(m_off) == set(m_on)
        _assert_trees_bit_equal(m_off, m_on)
    host = obs.diag_to_host(diag)
    assert set(host) == set(obs.UpdateDiag._fields)
    return host


# ---------------------------------------------------------------------------
# Per-agent bit-identity (collect_diag off ≙ on for the primary outputs)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_ddpg_diag_bit_identity():
    cfg = ddpg.DDPGConfig(obs_dim=OBS_DIM, n_actions=N_ACT, batch_size=4,
                          mem_size=16, img_shape=None)
    st = ddpg.ddpg_init(jax.random.PRNGKey(0), cfg)
    host = _check_on_off(ddpg.learn, cfg, st, _filled_buf())
    assert host["critic_grad_norm"] > 0
    assert host["q_max"] >= host["q_mean"] >= host["q_min"]
    assert host["alpha"] == 0.0          # DDPG has no temperature


@pytest.mark.slow
def test_td3_hint_admm_diag_bit_identity():
    """TD3 with the hint-ADMM actor: the fori_loop carry widening must
    not perturb the update, across both a delayed-skip and an actor
    step (update_actor_interval=2)."""
    cfg = td3.TD3Config(obs_dim=OBS_DIM, n_actions=N_ACT, batch_size=4,
                        mem_size=16, img_shape=None, use_hint=True,
                        n_admm=2, update_actor_interval=2,
                        prioritized=True)
    st = td3.td3_init(jax.random.PRNGKey(0), cfg)
    host = _check_on_off(td3.learn, cfg, st,
                         _filled_buf(prioritized=True), n_steps=2)
    assert host["critic_grad_norm"] > 0
    # step 2 is the actor step: the ADMM constraint residual is real
    assert host["actor_grad_norm"] > 0
    assert host["hint_residual"] > 0


@pytest.mark.slow
def test_sac_hint_diag_bit_identity():
    cfg = sac.SACConfig(obs_dim=OBS_DIM, n_actions=N_ACT, batch_size=4,
                        mem_size=16, img_shape=None, use_hint=True,
                        reward_scale=1.0, prioritized=True)
    st = sac.sac_init(jax.random.PRNGKey(0), cfg)
    host = _check_on_off(sac.learn, cfg, st, _filled_buf(prioritized=True))
    assert host["critic_grad_norm"] > 0
    assert host["actor_grad_norm"] > 0
    assert host["alpha"] > 0
    assert host["hint_residual"] > 0
    assert math.isfinite(host["entropy"])


@pytest.mark.slow
def test_dsac_diag_bit_identity():
    npix, K = 4, 3
    cfg = dsac.DSACConfig(obs_dim=npix * npix + 3 * K + 2,
                          n_actions=2 ** (K - 1), img_shape=(npix, npix),
                          use_image=True, batch_size=4, mem_size=16)
    st = dsac.dsac_init(jax.random.PRNGKey(0), cfg)
    buf = rp.replay_init(cfg.mem_size, dsac.transition_spec(cfg.obs_dim))
    rng = np.random.default_rng(1)
    for _ in range(8):
        buf = rp.replay_add(
            buf, {"state": rng.standard_normal(cfg.obs_dim)
                  .astype(np.float32),
                  "action": np.int32(rng.integers(cfg.n_actions)),
                  "reward": np.float32(rng.standard_normal()),
                  "new_state": rng.standard_normal(cfg.obs_dim)
                  .astype(np.float32),
                  "done": False},
            error=jnp.asarray(abs(rng.standard_normal())))
    host = _check_on_off(dsac.learn, cfg, st, buf, n_steps=1)
    assert host["critic_grad_norm"] > 0
    assert host["entropy"] > 0           # categorical entropy is exact


@pytest.mark.slow
def test_no_learn_branch_zero_diag():
    """Below batch_size the no-learn branch reports the all-zero diag and
    still bit-matches the diagnostics-off no-op."""
    cfg = ddpg.DDPGConfig(obs_dim=OBS_DIM, n_actions=N_ACT, batch_size=4,
                          mem_size=16, img_shape=None)
    st = ddpg.ddpg_init(jax.random.PRNGKey(0), cfg)
    buf = _filled_buf(n=2)               # 2 < batch_size
    host = _check_on_off(ddpg.learn, cfg, st, buf, n_steps=1)
    assert all(v == 0.0 for v in host.values())


@pytest.mark.slow
def test_agent_wrapper_single_trace_with_diag():
    """collect_diag=True costs at most ONE compiled program per agent:
    repeated ``learn()`` calls hit the same jit cache entry (the call
    site is spelled identically every step)."""
    cfg = td3.TD3Config(obs_dim=OBS_DIM, n_actions=N_ACT, batch_size=4,
                        mem_size=16, img_shape=None, warmup=0)
    agent = td3.TD3Agent(cfg, seed=0, collect_diag=True)
    rng = np.random.default_rng(2)
    for _ in range(6):
        t = _tr(rng)
        agent.store_transition(t["state"], t["action"], float(t["reward"]),
                               t["new_state"], t["done"], t["hint"])
        agent.learn()
    assert agent._learn._cache_size() == 1
    assert agent.last_diag is not None
    assert "diag" not in agent.last_metrics


# ---------------------------------------------------------------------------
# Replay health
# ---------------------------------------------------------------------------

def test_replay_health_uniform_vs_collapsed():
    buf = _filled_buf(n=8)
    h = rp.replay_health(buf)
    assert h["filled"] == 8
    np.testing.assert_allclose(h["priority_entropy"], 1.0, atol=1e-6)
    np.testing.assert_allclose(h["max_mean_priority_ratio"], 1.0,
                               atol=1e-6)
    np.testing.assert_allclose(sum(h["age_priority_hist"]), 1.0, atol=1e-4)
    assert h["is_weight_max"] >= h["is_weight_min"] > 0

    # one transition hoards the priority mass -> entropy collapses
    collapsed = buf._replace(
        priority=buf.priority.at[0].set(1e6))
    hc = rp.replay_health(collapsed)
    assert hc["priority_entropy"] < 0.1
    assert hc["max_mean_priority_ratio"] > 5.0


def test_replay_health_zero_total_degenerate():
    """The all-zero distribution (pre-first-store) reports the collapse
    explicitly instead of dividing by zero."""
    buf = rp.replay_init(16, rp.transition_spec(OBS_DIM, N_ACT))
    rng = np.random.default_rng(3)
    for _ in range(3):
        buf = rp.replay_add(buf, _tr(rng), priority=jnp.asarray(0.0))
    h = rp.replay_health(buf)
    assert h["filled"] == 3
    assert h["priority_total"] == 0.0
    assert h["priority_entropy"] == 0.0
    assert "is_weight_max" not in h      # undefined at zero mass


def test_native_per_health_matches_shared_math():
    from smartcal_tpu.rl.replay_native import NativePER

    spec = rp.transition_spec(OBS_DIM, N_ACT)
    buf = NativePER(16, spec, error_clip=100.0)
    rng = np.random.default_rng(4)
    for _ in range(6):
        buf.store(_tr(rng), error=abs(rng.standard_normal()))
    h = buf.health()
    assert h["filled"] == 6
    assert 0 < h["priority_entropy"] <= 1.0
    assert h["beta"] == buf.beta


# ---------------------------------------------------------------------------
# Watchdog
# ---------------------------------------------------------------------------

def _diag(closs=0.1, aloss=0.1, cgrad=1.0, agrad=1.0, q=0.5):
    return {"critic_loss": closs, "actor_loss": aloss,
            "critic_grad_norm": cgrad, "actor_grad_norm": agrad,
            "q_mean": q, "q_min": q - 1, "q_max": q + 1}


def test_watchdog_nan_trip_with_ring(tmp_path):
    path = str(tmp_path / "w.jsonl")
    with obs.recording(path):
        wd = obs.Watchdog(obs.WatchdogConfig(ring=4))
        for i in range(6):
            assert not wd.observe(_diag(), step=i)
        assert wd.observe(_diag(closs=float("nan")), step=6)
    assert wd.tripped and wd.trip_reason == "non_finite:critic_loss"
    trips = [e for e in read_jsonl(path) if e["event"] == "watchdog_trip"]
    assert len(trips) == 1
    t = trips[0]
    assert t["reason"] == "non_finite:critic_loss" and t["step"] == 6
    # ring holds the LAST cfg.ring diagnostics incl. the offender
    assert len(t["ring"]) == 4
    assert t["ring"][-1]["step"] == 6
    assert t["ring"][-1]["critic_loss"] is None    # sanitized NaN
    # latched: later observations keep reporting tripped, no second event
    assert wd.observe(_diag(), step=7)


def test_watchdog_sanitized_null_counts_as_non_finite():
    wd = obs.Watchdog()
    d = _diag()
    d["critic_grad_norm"] = None         # runlog sanitize()d upstream
    assert wd.observe(d, step=0)
    assert wd.trip_reason == "non_finite:critic_grad_norm"


def test_watchdog_exploding_grad_within_k_steps():
    cfg = obs.WatchdogConfig(grad_mult=10.0, warmup=5, ewma_alpha=0.1)
    wd = obs.Watchdog(cfg)
    rng = np.random.default_rng(5)
    for i in range(20):                  # healthy stream around 1.0
        assert not wd.observe(_diag(cgrad=1.0 + 0.1
                                    * rng.standard_normal()), step=i)
    assert wd.observe(_diag(cgrad=1e4), step=20)   # trips IMMEDIATELY
    assert wd.trip_reason.startswith("exploding_grad:critic_grad_norm")


def test_watchdog_skips_zero_grads_and_warmup():
    """Pre-fill/delayed-update zero grads must not poison the EWMA: the
    first real gradient after a run of zeros is NOT explosive, and no
    check arms before ``warmup`` real observations."""
    wd = obs.Watchdog(obs.WatchdogConfig(grad_mult=5.0, warmup=3))
    for i in range(50):
        assert not wd.observe(_diag(cgrad=0.0, agrad=0.0), step=i)
    assert not wd.observe(_diag(cgrad=2.0), step=50)
    for i in range(10):
        assert not wd.observe(_diag(cgrad=2.0), step=51 + i)
    assert not wd.tripped


def test_watchdog_q_blowup():
    wd = obs.Watchdog(obs.WatchdogConfig(q_limit=100.0))
    assert not wd.observe(_diag(q=50.0), step=0)
    assert wd.observe(_diag(q=500.0), step=1)
    assert wd.trip_reason.startswith("q_blowup:")


def test_watchdog_replay_non_finite():
    wd = obs.Watchdog()
    assert not wd.observe_replay({"priority_entropy": 0.9,
                                  "priority_total": 10.0})
    assert wd.observe_replay({"priority_entropy": float("nan"),
                              "priority_total": 10.0})
    assert wd.trip_reason == "replay_non_finite:priority_entropy"


# ---------------------------------------------------------------------------
# TrainObs integration (record_diag / log_replay_health / halt contract)
# ---------------------------------------------------------------------------

def test_train_obs_record_diag_stream_and_halt(tmp_path):
    path = str(tmp_path / "t.jsonl")
    tob = blocks.train_obs("unit", metrics=path, quiet=True, diag=True,
                           watchdog=True)
    try:
        # a step-stacked host diag (what an episode scan produces)
        clean = {k: [0.1] * 3 for k in obs.UpdateDiag._fields}
        assert tob.record_diag(clean, episode=0) is False
        bad = {k: [0.1, float("nan"), 0.1]
               for k in obs.UpdateDiag._fields}
        assert tob.record_diag(bad, episode=1) is True
        assert tob.tripped
        # after the trip the stream stops cleanly
        assert tob.record_diag(clean, episode=2) is True
        tob.log_replay_health(_filled_buf(), episode=2)
    finally:
        tob.close()
    recs = read_jsonl(path)
    diags = [e for e in recs if e["event"] == "diag"]
    assert [d["step"] for d in diags[:3]] == [0, 1, 2]
    assert any(e["event"] == "watchdog_trip" for e in recs)
    assert recs[-1]["event"] == "run_end"
    assert recs[-1]["watchdog_tripped"] is True


def test_train_obs_record_diag_noop_without_diag(tmp_path):
    path = str(tmp_path / "t.jsonl")
    tob = blocks.train_obs("unit", metrics=path, quiet=True)
    try:
        assert tob.record_diag(None) is False
        assert tob.record_diag({"critic_loss": float("nan")}) is False
        assert tob.log_replay_health(_filled_buf()) is False
    finally:
        tob.close()
    recs = read_jsonl(path)
    assert not [e for e in recs if e["event"] in ("diag", "replay_health",
                                                  "watchdog_trip")]


@pytest.mark.slow
def test_enet_driver_watchdog_halts_on_injected_nan(tmp_path, monkeypatch):
    """End-to-end: a NaN critic loss injected at the device->host diag
    boundary trips the watchdog, the enet driver logs watchdog_trip with
    ring context, stops early, and exits cleanly."""
    monkeypatch.chdir(tmp_path)
    from smartcal_tpu.train.enet_sac import train_fused

    real = obs.diag_to_host
    state = {"calls": 0}

    def inject(diag):
        host = real(diag)
        state["calls"] += 1
        if state["calls"] >= 2:          # poison from the second episode
            v = host["critic_loss"]
            host["critic_loss"] = ([float("nan")] * len(v)
                                   if isinstance(v, list) else float("nan"))
        return host

    monkeypatch.setattr(obs, "diag_to_host", inject)
    path = str(tmp_path / "run.jsonl")
    scores = train_fused(episodes=6, steps=2, M=6, N=6, quiet=True,
                         save_every=0, metrics_path=path,
                         watchdog=True)[0]
    assert len(scores) < 6               # halted early, returned cleanly
    recs = read_jsonl(path)
    trips = [e for e in recs if e["event"] == "watchdog_trip"]
    assert len(trips) == 1
    assert trips[0]["reason"] == "non_finite:critic_loss"
    assert len(trips[0]["ring"]) >= 1
    end = recs[-1]
    assert end["event"] == "run_end" and end["watchdog_tripped"] is True
    assert obs.active() is None


# ---------------------------------------------------------------------------
# FLOPs / roofline accounting
# ---------------------------------------------------------------------------

def test_stage_cost_counts_flops():
    f = jax.jit(lambda a, b: a @ b)
    x = jnp.ones((8, 8), jnp.float32)
    c = costs.stage_cost(f, x, x)
    assert c["flops"] > 0
    assert c["bytes_accessed"] > 0


def test_record_stage_cost_gating_and_cache(tmp_path):
    path = str(tmp_path / "c.jsonl")
    f = jax.jit(lambda a: a * 2.0)
    x = jnp.ones((4,), jnp.float32)
    # disarmed / no runlog -> strict no-op
    assert costs.record_stage_cost("s", f, x) is None
    with obs.recording(path):
        assert costs.record_stage_cost("s", f, x) is None  # not enabled
        costs.set_enabled(True)
        c1 = costs.record_stage_cost("s", f, x)
        assert c1["flops"] >= 0
        # same signature -> cached, no second event
        assert costs.record_stage_cost("s", f, x) == c1
        # new signature -> new event
        costs.record_stage_cost("s", f, jnp.ones((8,), jnp.float32))
    evs = [e for e in read_jsonl(path) if e["event"] == "cost"]
    assert len(evs) == 2
    assert all(e["stage"] == "s" for e in evs)


def test_record_stage_cost_failure_is_recorded_not_raised(tmp_path):
    path = str(tmp_path / "c.jsonl")

    def boom(a):
        raise ValueError("no lowering for you")

    with obs.recording(path):
        costs.set_enabled(True)
        out = costs.record_stage_cost("bad", boom,
                                      jnp.ones((2,), jnp.float32))
        assert "error" in out
        # negatively cached: the failure is paid once
        assert costs.record_stage_cost(
            "bad", boom, jnp.ones((2,), jnp.float32)) == out
    evs = [e for e in read_jsonl(path) if e["event"] == "cost"]
    assert len(evs) == 1 and "error" in evs[0]


def test_record_stage_cost_defer_flush(tmp_path):
    """In-span call sites defer the lower+compile; flush_pending (the
    between-episodes hook) pays it outside any timed region, once."""
    path = str(tmp_path / "c.jsonl")
    f = jax.jit(lambda a: a + 1.0)
    x = jnp.ones((4,), jnp.float32)
    with obs.recording(path):
        costs.set_enabled(True)
        assert costs.record_stage_cost("d", f, x, defer=True) is None
        # deduped while pending: the repeat does not queue again
        assert costs.record_stage_cost("d", f, x, defer=True) is None
        assert not [e for e in read_jsonl(path) if e["event"] == "cost"]
        assert costs.flush_pending() == 1
        assert costs.flush_pending() == 0
        # flushed result is cached for later immediate callers
        assert costs.record_stage_cost("d", f, x)["flops"] >= 0
    evs = [e for e in read_jsonl(path) if e["event"] == "cost"]
    assert len(evs) == 1 and evs[0]["stage"] == "d"


def test_roofline_peak_cpu_graceful(tmp_path):
    assert costs.device_peak() is None   # CPU: no known peak
    path = str(tmp_path / "c.jsonl")
    with obs.recording(path):
        assert costs.log_roofline_peak() is None
    assert not [e for e in read_jsonl(path)
                if e["event"] == "roofline_peak"]


# ---------------------------------------------------------------------------
# obs_report: training health + roofline sections
# ---------------------------------------------------------------------------

def _write_run(path, events):
    with open(path, "w") as fh:
        fh.write(json.dumps({"t": 0.0, "event": "run_header", "schema": 2,
                             "run_id": "r", "meta": {"entry": "x"}}) + "\n")
        for e in events:
            fh.write(json.dumps(e) + "\n")


def _synthetic_training_run(with_peak):
    evs = []
    # grad norms ramp 1 -> 4 over 20 learning updates + 4 skip zeros
    for i in range(24):
        g = 0.0 if i < 4 else 1.0 + 3.0 * (i - 4) / 19.0
        evs.append({"t": float(i), "event": "diag", "step": i,
                    "critic_loss": 0.1, "actor_loss": 0.1,
                    "critic_grad_norm": g, "actor_grad_norm": g / 2,
                    "q_mean": 0.5, "q_min": 0.0, "q_max": 1.0,
                    "critic_update_ratio": 1e-3, "entropy": 0.9})
    evs.append({"t": 24.0, "event": "replay_health", "priority_entropy":
                0.99, "max_mean_priority_ratio": 1.2, "beta": 0.4,
                "is_weight_max": 1.0, "filled": 24, "size": 64})
    evs.append({"t": 25.0, "event": "replay_health", "priority_entropy":
                0.8, "max_mean_priority_ratio": 3.0, "beta": 0.5,
                "is_weight_max": 2.0, "filled": 48, "size": 64})
    evs.append({"t": 26.0, "event": "watchdog_trip", "reason":
                "q_blowup:q_max (|2e+06| > 1e+06)", "step": 23,
                "observations": 24, "ring": [{"step": 23}]})
    evs.append({"t": 27.0, "event": "cost", "stage": "episode_update",
                "flops": 1e9, "bytes_accessed": 1e8})
    for i in range(4):
        evs.append({"t": 28.0 + i, "event": "span", "path": "episode",
                    "name": "episode", "dur_s": 0.5})
    if with_peak:
        evs.append({"t": 40.0, "event": "roofline_peak", "platform": "tpu",
                    "chip": "v5e", "bf16": 197e12, "fp32_est": 49e12})
    return evs


def test_obs_report_training_health_and_roofline(tmp_path):
    path = str(tmp_path / "run.jsonl")
    _write_run(path, _synthetic_training_run(with_peak=True))
    rep = obs_report.build_report([obs_report.load_run(path)], n_boot=10)
    r = rep["runs"][0]

    th = r["training_health"]
    assert th["updates"] == 24
    assert th["learning_updates"] == 20   # zeros are skip steps
    qm = th["trajectory"]["critic_grad_norm"]["quarter_means"]
    assert len(qm) == 4 and qm[-1] > qm[0]          # the ramp is visible
    assert th["replay"]["priority_entropy_last"] == 0.8
    assert th["watchdog_trips"][0]["reason"].startswith("q_blowup")

    rl = r["roofline"]
    assert rl["peak"]["chip"] == "v5e"
    st = rl["stages"]["episode_update"]
    assert st["calls"] == 4
    # 1e9 flops x 4 calls / 2.0 s = 2e9 FLOPs/s
    np.testing.assert_allclose(st["achieved_flops_per_s"], 2e9)
    np.testing.assert_allclose(st["fraction_of_peak_fp32"], 2e9 / 49e12,
                               rtol=1e-2)  # report rounds to 6 decimals

    text = obs_report.render(rep)
    assert "WATCHDOG TRIP" in text
    assert "roofline" in text
    assert "%peak" in text
    json.dumps(rep)                       # fully machine-serializable


def test_obs_report_roofline_degrades_without_peak(tmp_path):
    path = str(tmp_path / "run.jsonl")
    _write_run(path, _synthetic_training_run(with_peak=False))
    rep = obs_report.build_report([obs_report.load_run(path)], n_boot=10)
    st = rep["runs"][0]["roofline"]["stages"]["episode_update"]
    assert "achieved_flops_per_s" in st
    assert "fraction_of_peak_fp32" not in st
    text = obs_report.render(rep)
    assert "fraction-of-peak unavailable" in text


def test_obs_report_no_diag_run_has_no_health_sections(tmp_path):
    path = str(tmp_path / "run.jsonl")
    _write_run(path, [{"t": 1.0, "event": "episode", "episode": 0,
                       "score": 1.0}])
    rep = obs_report.build_report([obs_report.load_run(path)], n_boot=10)
    assert rep["runs"][0]["training_health"] is None
    assert rep["runs"][0]["roofline"] is None
    text = obs_report.render(rep)
    assert "training health" not in text


# ---------------------------------------------------------------------------
# obs_tail
# ---------------------------------------------------------------------------

def test_obs_tail_renders_all_new_event_kinds(tmp_path):
    path = str(tmp_path / "run.jsonl")
    _write_run(path, _synthetic_training_run(with_peak=True)
               + [{"t": 50.0, "event": "episode", "episode": 0,
                   "score": -0.5},
                  {"t": 51.0, "event": "run_end", "episodes": 1,
                   "updates": 24, "watchdog_tripped": True,
                   "wall_s": 9.0}])
    out = io.StringIO()
    obs_tail.tail(path, follow=False, out=out)
    text = out.getvalue()
    assert "WATCHDOG" in text and "q_blowup" in text
    assert "diag" in text and "replay" in text
    assert "cost" in text and "peak" in text
    assert "episode    #0" in text
    assert "tripped=True" in text
    # filtering
    out2 = io.StringIO()
    obs_tail.tail(path, wanted={"watchdog_trip"}, follow=False, out=out2)
    lines = [ln for ln in out2.getvalue().splitlines() if ln]
    assert len(lines) == 1 and "WATCHDOG" in lines[0]


def test_obs_tail_rotation_drains_old_segment(tmp_path, monkeypatch):
    """The writer's final flush to a segment can land between the
    tailer's last read and the rotation rename; the tailer must drain
    the old inode before following the fresh file (the burst can hold
    the watchdog_trip)."""
    base = str(tmp_path / "run.jsonl")
    with open(base, "w") as f:
        f.write(json.dumps({"t": 1.0, "event": "episode", "episode": 0,
                            "score": 1.0}) + "\n")
    state = {"rotated": False}
    real_stat = os.stat

    def stat_and_rotate(p, *a, **kw):
        # fires on the tailer's idle poll: emulate the writer flushing a
        # last burst to the old inode and rotating, exactly between the
        # tailer's read()=="" and its os.stat
        if p == base and not state["rotated"]:
            state["rotated"] = True
            with open(base, "a") as f:
                f.write(json.dumps(
                    {"t": 2.0, "event": "watchdog_trip",
                     "reason": "non_finite:critic_loss", "step": 7,
                     "observations": 8, "ring": [{}]}) + "\n")
            os.replace(base, base + ".1")
            with open(base, "w") as f:
                f.write(json.dumps({"t": 3.0, "event": "episode",
                                    "episode": 1, "score": 2.0}) + "\n")
        return real_stat(p, *a, **kw)

    monkeypatch.setattr(obs_tail.os, "stat", stat_and_rotate)
    out = io.StringIO()
    obs_tail.tail(base, follow=True, interval=0.01, out=out, max_iters=2)
    text = out.getvalue()
    assert "episode    #0" in text
    assert "WATCHDOG" in text            # drained from the rotated inode
    assert "episode    #1" in text       # and followed into the new file
