"""Fleet-wide distributed tracing (ISSUE 18): trace-context units
(carrier lineage, adoption, thread-local no-op contract), traced IPC
framing (corrupt body preserves the prelude's trace), the SLO
burn-rate detector's hysteresis state machine on an injected clock,
the crash flight recorder (ring capacity, dump format, rate limit,
shed-burst trigger), timeline collection (rotation-aware discovery,
skew-corrected merge, request-path reconstruction, completeness
scoring), router wiring (per_replica spec overrides, slo observe/
evaluate through poll, parent-side black box), and the trace-
continuity-under-failure runs: a corrupt frame's trace is reported,
not silently dropped, and a replica kill requeues under the ORIGINAL
trace id."""

import json
import os
import threading
import time
import types

import pytest

from smartcal_tpu import obs
from smartcal_tpu.obs import collect, tracectx
from smartcal_tpu.obs.flightrec import FlightRecorder
from smartcal_tpu.runtime import ipc
from smartcal_tpu.serve import fleet as serve_fleet
from smartcal_tpu.serve.fleet import FleetRouter, _Replica
from smartcal_tpu.serve.router import Job

from test_serve_fleet import (FakeReplica, _drain, _fake_router,
                              _fast_backoff)


@pytest.fixture(autouse=True)
def in_tmp(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)


def _read_jsonl(path):
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


# ---------------------------------------------------------------------------
# trace context units
# ---------------------------------------------------------------------------

def test_carrier_shapes_and_lineage():
    car = tracectx.new_root_carrier()
    assert len(car["trace"]) == 32 and len(car["span"]) == 16
    int(car["trace"], 16), int(car["span"], 16)   # valid hex
    # fields_of names the carrier's OWN span (the point of origin)
    assert tracectx.fields_of(car) == {"trace": car["trace"],
                                       "span": car["span"]}
    # child_fields mints a fresh span under the carrier's
    cf = tracectx.child_fields(car)
    assert cf["trace"] == car["trace"]
    assert cf["parent"] == car["span"]
    assert len(cf["span"]) == 16 and cf["span"] != car["span"]
    # carrier-less inputs degrade to empty fields, never raise
    assert tracectx.fields_of(None) == {}
    assert tracectx.child_fields({}) == {}
    assert tracectx.fields_of({"span": "x"}) == {}


def test_use_trace_adoption_and_noop_contract():
    assert tracectx.current_fields() == {}
    assert tracectx.carrier() is None
    assert tracectx.push_span() is None      # no adopted trace: no-op
    car = tracectx.new_root_carrier()
    with tracectx.use_trace(car):
        assert tracectx.current_fields() == {"trace": car["trace"],
                                             "span": car["span"]}
        sid, parent = tracectx.push_span()
        assert parent == car["span"] and sid != car["span"]
        assert tracectx.current_fields()["span"] == sid
        tracectx.pop_span(sid)
        assert tracectx.current_fields()["span"] == car["span"]
    assert tracectx.current_fields() == {}   # restored on exit
    with tracectx.use_trace(None):           # None adopts nothing
        assert tracectx.carrier() is None


def test_runlog_auto_attaches_adopted_trace():
    car = tracectx.new_root_carrier()
    with obs.recording("trace_rl.jsonl", run_id="t") as rl:
        with tracectx.use_trace(car):
            rl.log("traced_evt", x=1)
        rl.log("plain_evt")
    recs = {r["event"]: r for r in _read_jsonl("trace_rl.jsonl")}
    assert recs["traced_evt"]["trace"] == car["trace"]
    assert recs["traced_evt"]["span"] == car["span"]
    assert "trace" not in recs["plain_evt"]


# ---------------------------------------------------------------------------
# traced IPC framing
# ---------------------------------------------------------------------------

def test_traced_frame_roundtrip_and_plain():
    env = {"trace": "ab" * 16, "span": "cd" * 8, "t": 123.456}
    blob = ipc.frame_payload(("result", 7), trace=env)
    obj, trace = ipc.unframe_payload_traced(blob)
    assert obj == ("result", 7) and trace == env
    # plain frames carry no trace and stay readable by both paths
    plain = ipc.frame_payload(("beat", 1))
    assert ipc.unframe_payload_traced(plain) == (("beat", 1), None)
    assert ipc.unframe_payload(blob) == ("result", 7)


def test_corrupt_body_preserves_trace_prelude():
    env = {"trace": "ab" * 16, "span": "cd" * 8, "t": 1.0}
    blob = bytearray(ipc.frame_payload(("result", 7, {}), trace=env))
    blob[-1] ^= 0xFF                         # mid-send death: torn body
    with pytest.raises(ipc.CorruptPayloadError) as ei:
        ipc.unframe_payload_traced(bytes(blob))
    assert ei.value.trace == env             # the drop names its request
    # an untraced corrupt frame reports trace None (nothing to name)
    plain = bytearray(ipc.frame_payload(("result", 7)))
    plain[-1] ^= 0xFF
    with pytest.raises(ipc.CorruptPayloadError) as ei2:
        ipc.unframe_payload_traced(bytes(plain))
    assert ei2.value.trace is None
    # truncation below even the header is still a structured error
    with pytest.raises(ipc.CorruptPayloadError):
        ipc.unframe_payload_traced(b"SC")


# ---------------------------------------------------------------------------
# SLO burn-rate detector (injected clock)
# ---------------------------------------------------------------------------

def test_slo_fire_localize_clear():
    det = obs.SloBurnDetector(p99_target_s=0.1, fast_window_s=10.0,
                              slow_window_s=20.0, sustain_s=2.0,
                              clear_sustain_s=3.0, min_samples=5)
    for i in range(8):                       # replica 1 is the slow one
        det.observe(latency_s=0.5, replica=1, now=0.5 + 0.05 * i)
        det.observe(latency_s=0.05, replica=0, now=0.5 + 0.05 * i)
    assert det.evaluate(now=1.0) is None     # burning, not yet sustained
    ev = det.evaluate(now=3.5)
    assert ev is not None and ev["state"] == "firing"
    assert ev["worst_replica"] == 1
    assert ev["burn_fast"] >= 2.0
    assert det.firing and det.snapshot(now=3.5)["firing"]
    # recovery: the bad window ages out, good traffic takes over
    for i in range(6):
        det.observe(latency_s=0.01, replica=1, now=24.0 + 0.2 * i)
    assert det.evaluate(now=26.0) is None    # quiet, not yet sustained
    ev2 = det.evaluate(now=29.5)
    assert ev2 is not None and ev2["state"] == "cleared"
    snap = det.snapshot(now=29.5)
    assert not snap["firing"] and snap["transitions"] == 2


def test_slo_min_samples_and_shed_burn():
    det = obs.SloBurnDetector(p99_target_s=0.1, min_samples=20,
                              sustain_s=0.0)
    for i in range(5):                       # too few samples: no alarm
        det.observe(latency_s=9.9, now=float(i) * 0.1)
    assert det.evaluate(now=1.0) is None and not det.firing
    # shed rate alone burns (latencies all within target)
    det2 = obs.SloBurnDetector(p99_target_s=0.1, shed_target=0.02,
                               min_samples=5, sustain_s=1.0)
    for i in range(10):
        det2.observe(shed=True, now=0.1 * i)
    assert det2.evaluate(now=1.0) is None
    ev = det2.evaluate(now=2.5)
    assert ev is not None and ev["state"] == "firing"
    assert ev["shed_rate_fast"] == 1.0


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_recorder_ring_flush_and_rate_limit(tmp_path):
    fr = FlightRecorder()
    assert not fr.armed
    fr.record_line('{"dropped": true}\n')    # disarmed: no-op
    fr.arm(str(tmp_path / "bb"), capacity=4)
    for i in range(6):
        fr.record_line(json.dumps({"i": i}) + "\n")
    assert fr.stats() == {"armed": True, "depth": 4, "flushes": 0}
    path = fr.flush("crash", {"error": "boom"})
    assert path is not None and os.path.basename(path) == \
        f"blackbox_{os.getpid()}.jsonl"
    recs = _read_jsonl(path)
    hdr = recs[0]
    assert hdr["event"] == "blackbox_flush" and hdr["reason"] == "crash"
    assert hdr["n_events"] == 4 and hdr["error"] == "boom"
    assert [r["i"] for r in recs[1:]] == [2, 3, 4, 5]   # capacity kept
    # same-reason dumps are rate-limited; a new reason appends at once
    assert fr.flush("crash") is None
    assert fr.flush("watchdog_trip") == path
    assert _read_jsonl(path)[5]["reason"] == "watchdog_trip"
    fr.disarm()
    assert fr.flush("crash") is None and not fr.armed


def test_flight_recorder_shed_burst_triggers_dump(tmp_path):
    fr = FlightRecorder()
    fr.arm(str(tmp_path / "bb"), capacity=8)
    fr.record_line('{"event": "x"}\n')
    for i in range(7):                       # below the burst bar
        fr.note_shed(now=10.0 + 0.1 * i)
    assert fr.stats()["flushes"] == 0
    fr.note_shed(now=10.8)                   # 8 sheds inside 2 s: burst
    assert fr.stats()["flushes"] == 1
    hdr = _read_jsonl(os.path.join(
        str(tmp_path / "bb"), f"blackbox_{os.getpid()}.jsonl"))[0]
    assert hdr["reason"] == "shed_burst"
    assert hdr["sheds_in_window"] == 8


# ---------------------------------------------------------------------------
# timeline collection
# ---------------------------------------------------------------------------

def test_discover_streams_rotation_order_and_exclusions(tmp_path):
    d = str(tmp_path / "run")
    os.makedirs(d)
    for name in ("r.jsonl", "r.jsonl.1", "r.jsonl.2", "s.jsonl",
                 "blackbox_123.jsonl", "notes.txt"):
        with open(os.path.join(d, name), "w") as fh:
            fh.write("")
    streams = collect.discover_streams(d)
    assert sorted(streams) == ["r.jsonl", "s.jsonl"]
    assert [os.path.basename(p) for p in streams["r.jsonl"]] == \
        ["r.jsonl.1", "r.jsonl.2", "r.jsonl"]   # write order
    assert collect.discover_streams(str(tmp_path / "missing")) == {}


def test_read_stream_proc_naming_and_corrupt_tolerance(tmp_path):
    p = str(tmp_path / "replica0-g0.jsonl")
    with open(p, "w") as fh:
        fh.write(json.dumps({"event": "run_header",
                             "run_id": "replica0"}) + "\n")
        fh.write(json.dumps({"event": "x", "t": 1.0}) + "\n")
        fh.write('{"torn tail\n')            # crashed writer
        fh.write("3\n")                      # non-dict line
    proc, events, bad = collect.read_stream([p])
    assert proc == "replica0" and bad == 2 and len(events) == 2
    # no header: proc falls back to the filename stem
    q = str(tmp_path / "router.jsonl")
    with open(q, "w") as fh:
        fh.write(json.dumps({"event": "y", "t": 2.0}) + "\n")
    assert collect.read_stream([q])[0] == "router"


def _router_stream(trace):
    return [
        {"t": 100.0, "event": "clock_offset", "peer": "replica0",
         "offset_s": 4.5},
        {"t": 100.0, "event": "fleet_dispatch", "job_id": 7,
         "trace": trace, "span": "a" * 16, "requeue": False},
        {"t": 101.0, "event": "fleet_result", "job_id": 7,
         "trace": trace, "total_s": 0.8},
    ]


def _replica_stream(trace):
    return [
        {"t": 95.7, "event": "serve_admit", "trace": trace,
         "replica": 0, "requeues": 0},
        {"t": 96.0, "event": "serve_request", "trace": trace,
         "queue_wait_s": 0.05, "service_s": 0.5, "total_s": 0.8,
         "batch": 3},
        {"t": 96.1, "event": "span", "name": "serve_solve",
         "batch": 3, "dur_s": 0.4},
    ]


def test_merge_applies_clock_offset_and_paths_reconstruct():
    T = "ff" * 16
    m = collect.TimelineMerger()
    m.add_stream("router", _router_stream(T))
    m.add_stream("replica0", _replica_stream(T))
    assert m.offsets() == {"replica0": 4.5}
    merged = m.merge()
    admit = next(e for e in merged if e["event"] == "serve_admit")
    assert admit["proc"] == "replica0"
    assert admit["t_corr"] == pytest.approx(100.2)   # 95.7 + 4.5
    assert [e["event"] for e in merged[:2]] == \
        ["clock_offset", "fleet_dispatch"]           # time-ordered
    paths = collect.request_paths(merged)
    assert len(paths) == 1
    (p,) = paths
    assert p["trace"] == T and p["replica"] == 0
    assert p["proc"] == "replica0" and p["completed"] and p["complete"]
    assert not p["requeued"] and p["requeues"] == 0
    assert p["ipc_s"] == pytest.approx(0.2)
    assert p["queue_s"] == 0.05 and p["solve_s"] == 0.4
    comp = collect.completeness(paths, require_stages=True)
    assert comp == {"n_requests": 1, "n_completed": 1,
                    "n_complete_trees": 1, "fraction": 1.0}


def test_request_paths_requeue_keeps_trace_and_scores():
    T, U = "aa" * 16, "bb" * 16
    router = [
        {"t": 10.0, "event": "fleet_dispatch", "trace": T,
         "job_id": 1, "requeue": False},
        {"t": 10.5, "event": "fleet_dispatch", "trace": T,
         "job_id": 1, "requeue": True},      # same trace, second hop
        {"t": 11.0, "event": "fleet_result", "trace": T, "job_id": 1},
        # a trace whose replica-side events died with the replica
        {"t": 12.0, "event": "fleet_dispatch", "trace": U, "job_id": 2},
        {"t": 12.4, "event": "fleet_result", "trace": U, "job_id": 2},
    ]
    replica1 = [
        {"t": 10.6, "event": "serve_admit", "trace": T, "replica": 1,
         "requeues": 1},
        {"t": 10.7, "event": "serve_request", "trace": T,
         "total_s": 0.4},
    ]
    m = collect.TimelineMerger()
    m.add_stream("router", router)
    m.add_stream("replica1", replica1)
    paths = {p["trace"]: p for p in collect.request_paths(m.merge())}
    p = paths[T]
    assert p["requeued"] and p["requeues"] == 1 and p["dispatches"] == 2
    assert p["replica"] == 1 and p["complete"] and p["completed"]
    # ipc_s measures from the LAST dispatch (the hop that served)
    assert p["ipc_s"] == pytest.approx(0.1)
    assert paths[U]["completed"] and not paths[U]["complete"]
    comp = collect.completeness(list(paths.values()))
    assert comp["n_completed"] == 2 and comp["fraction"] == 0.5


# ---------------------------------------------------------------------------
# router wiring (scripted fakes, injected clock)
# ---------------------------------------------------------------------------

def test_replica_spec_merges_per_replica_overrides():
    clk = [0.0]
    router = FleetRouter(
        {"lanes": 2, "per_replica": {0: {"faults": {
            "delay_stage": "serve_batch", "delay_at": 10}}}},
        replicas=0, replica_factory=FakeReplica,
        clock=lambda: clk[0], backoff=_fast_backoff())
    s0, s1 = router._replica_spec(0), router._replica_spec(1)
    assert s0["faults"]["delay_stage"] == "serve_batch"
    assert "faults" not in s1
    assert "per_replica" not in s0 and "per_replica" not in s1
    # the override table survives in the base spec for respawns
    assert 0 in router.worker_spec["per_replica"]


def test_router_poll_emits_slo_burn_transitions():
    clk = [0.0]
    det = obs.SloBurnDetector(p99_target_s=0.1, fast_window_s=10.0,
                              slow_window_s=10.0, sustain_s=1.0,
                              clear_sustain_s=1.0, min_samples=5)
    router = _fake_router(clk, slo=det)
    router._spawn_replica()
    for _ in range(6):                       # results feed the detector
        router._note_result(0, None, {"total_s": 0.5})
    assert det.snapshot(now=0.0)["fast"]["n"] == 6
    assert router.poll() == []               # pending, not sustained
    clk[0] = 1.5
    events = router.poll()
    burns = [e for e in events if e.get("event") == "slo_burn"]
    assert len(burns) == 1 and burns[0]["state"] == "firing"
    assert burns[0]["worst_replica"] == 0
    # sheds feed the detector too
    job = Job(episode=None, k=1, t_submit=0.0)
    router._shed_record(job, "fleet_down")
    assert det.snapshot(now=clk[0])["fast"]["n"] == 7
    clk[0] = 15.0                            # bad window ages out:
    assert router.poll() == []               # quiet, clear not sustained
    clk[0] = 16.5
    clears = [e for e in router.poll() if e.get("event") == "slo_burn"]
    assert len(clears) == 1 and clears[0]["state"] == "cleared"
    assert det.snapshot(now=clk[0])["transitions"] == 2


def test_parent_blackbox_dump_format(tmp_path):
    rep = _Replica(types.SimpleNamespace(name="t"), 3, {"frame_ring": 8})
    assert rep.blackbox("exited", str(tmp_path)) is None   # empty ring
    rep._note_frame("beat", {"queue_depth": 1})
    rep._note_frame("result", {"job_id": 4, "trace": "ee" * 16})
    path = rep.blackbox("exited", str(tmp_path))
    assert os.path.basename(path) == "blackbox_replica3.jsonl"
    recs = _read_jsonl(path)
    assert recs[0]["event"] == "blackbox_flush"
    assert recs[0]["side"] == "parent" and recs[0]["replica"] == 3
    assert recs[0]["n_events"] == 2
    assert [r["kind"] for r in recs[1:]] == ["beat", "result"]
    assert recs[2]["trace"] == "ee" * 16


# ---------------------------------------------------------------------------
# trace continuity under failure
# ---------------------------------------------------------------------------

class _RouterStub:
    """Log-recording stand-in for FleetRouter on the pump-only path."""

    name = "t"

    def __init__(self):
        self.events = []
        self._lock = threading.Lock()

    def _log(self, event, **fields):
        with self._lock:
            self.events.append(dict(fields, event=event))

    def of(self, event):
        with self._lock:
            return [e for e in self.events if e["event"] == event]


def test_pump_reports_corrupt_frame_trace():
    """A replica frame whose body is torn mid-send is dropped — but the
    drop is logged as ``ipc_corrupt_payload`` WITH the trace id the
    surviving prelude names, so the merged timeline shows which request
    lost a frame instead of a silent gap."""
    import multiprocessing as mp

    stub = _RouterStub()
    rep = _Replica(stub, 0, {})
    parent, child = mp.Pipe(duplex=True)
    rep.conn = parent
    rep.proc = types.SimpleNamespace(is_alive=lambda: True)
    threading.Thread.start(rep)              # pump only; no process
    try:
        env = {"trace": "ab" * 16, "span": "cd" * 8,
               "t": round(time.time(), 6)}
        blob = bytearray(ipc.frame_payload(
            ("result", 9, {"total_s": 0.1}), trace=env))
        blob[-1] ^= 0xFF                     # emulate mid-send death
        child.send_bytes(bytes(blob))
        child.send_bytes(ipc.frame_payload(
            ("beat", {"queue_depth": 2, "served": 1,
                      "circuit_open": False}),
            trace={"t": round(time.time(), 6)}))
        deadline = time.monotonic() + 5.0
        while (not stub.of("ipc_corrupt_payload")
               or not stub.of("clock_offset")):
            assert time.monotonic() < deadline, stub.events
            time.sleep(0.01)
    finally:
        rep.stop_event.set()
        rep.join(timeout=2.0)
        parent.close()
        child.close()
    (bad,) = stub.of("ipc_corrupt_payload")
    assert bad["trace"] == "ab" * 16 and bad["span"] == "cd" * 8
    assert bad["replica"] == 0
    # the parent-side frame ring remembers the drop for the black box
    kinds = [f["kind"] for f in rep._frames]
    assert "corrupt" in kinds and "beat" in kinds
    corrupt = next(f for f in rep._frames if f["kind"] == "corrupt")
    assert corrupt["trace"] == "ab" * 16
    # the intact beat still landed (one bad frame costs one frame)
    assert rep.gauges()["queue_depth"] == 2
    # the envelope handshake produced a usable skew estimate
    (off,) = stub.of("clock_offset")
    assert off["peer"] == "replica0" and abs(off["offset_s"]) < 5.0


def test_trace_continuity_replica_kill_requeue():
    """SIGKILL one of two replicas mid-run: requeued jobs keep their
    ORIGINAL trace id across the hop (annotated, not re-rooted), the
    survivor's spans complete those trees, and the dead replica leaves
    a parent-side black box."""
    d = os.path.abspath("procs")
    os.makedirs(d)
    # the fleet's own sleep stub, not the tests' StubServer: it mirrors
    # CalibServer's serve_request + batch-span instrumentation, which is
    # exactly what the continuity assertions below reconstruct
    spec = serve_fleet.sleep_worker_spec(lanes=2, service_s=0.05,
                                         beat_s=0.05)
    router = FleetRouter(spec, replicas=2, heartbeat_timeout=10.0,
                         poll_s=0.02, backoff=_fast_backoff(),
                         max_requeues=2, metrics_dir=d)
    with obs.recording(os.path.join(d, "router.jsonl"),
                       run_id="router"):
        try:
            router.start(warm_timeout_s=60.0, stagger=False)
            jobs = [Job(episode=None, k=i % 5) for i in range(16)]
            futs = [router.submit(j) for j in jobs]
            assert router.kill_replica(0)
            results = _drain(futs, timeout_s=60.0)
            assert len(results) == 16
            st = router.stats()
            assert st["completed"] == 16 and st["shed"] == 0
            assert st["requeued"] >= 1, st
        finally:
            router.stop()
    # the SIGKILLed worker could never flush its own ring: the parent-
    # side frame ring is its black box
    assert os.path.exists(os.path.join(d, "blackbox_replica0.jsonl"))
    hdr = _read_jsonl(os.path.join(d, "blackbox_replica0.jsonl"))[0]
    assert hdr["event"] == "blackbox_flush" and hdr["side"] == "parent"
    paths = collect.request_paths(collect.merge_directory(d))
    assert len(paths) == 16                  # every admission traced
    assert len({p["trace"] for p in paths}) == 16
    requeued = [p for p in paths if p["requeued"]]
    assert requeued, "kill produced no requeued request paths"
    for p in requeued:
        # continuity: the re-dispatch rode the SAME trace id (one
        # record per trace), annotated as a later hop, and the
        # survivor's spans completed the tree
        assert p["dispatches"] >= 2 and p["requeues"] >= 1
        assert p["completed"] and p["complete"], p
    # requeued-and-served requests were flushed by a clean-exit
    # replica, so their chains must ALL have reconstructed
    comp = collect.completeness(requeued)
    assert comp["fraction"] == 1.0
