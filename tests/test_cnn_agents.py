"""Tests for the dict-obs (CNN + metadata) agent variants
(reference calibration/calib_sac.py, demixing_rl/demix_sac.py towers)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smartcal_tpu.rl import ddpg, replay as rp, sac, td3
from smartcal_tpu.rl.networks import SplitImageMetaActor, flatten_obs

H = W = 16
META = 11
OBS = H * W + META
NA = 3


def _fill(agent_buf_add, buf, rng, n):
    for _ in range(n):
        tr = {"state": rng.standard_normal(OBS).astype(np.float32),
              "action": rng.uniform(-1, 1, NA).astype(np.float32),
              "reward": np.float32(rng.standard_normal()),
              "new_state": rng.standard_normal(OBS).astype(np.float32),
              "done": np.float32(0.0),
              "hint": rng.uniform(-1, 1, NA).astype(np.float32)}
        buf = agent_buf_add(buf, tr)
    return buf


def test_flatten_obs_matches_split():
    rng = np.random.default_rng(0)
    img = rng.standard_normal((H, W)).astype(np.float32)
    meta = rng.standard_normal(META).astype(np.float32)
    flat = flatten_obs({"infmap": img, "metadata": meta})
    mod = SplitImageMetaActor(img_shape=(H, W), n_actions=NA)
    img2, meta2 = mod.split(jnp.asarray(flat))
    np.testing.assert_allclose(np.asarray(img2), img)
    np.testing.assert_allclose(np.asarray(meta2), meta)


@pytest.mark.parametrize("use_image", [True, False])
def test_sac_cnn_learn_step(use_image):
    cfg = sac.SACConfig(obs_dim=OBS, n_actions=NA, batch_size=8, mem_size=32,
                        img_shape=(H, W), use_image=use_image,
                        use_hint=True, hint_distance="kld")
    key = jax.random.PRNGKey(0)
    st = sac.sac_init(key, cfg)
    buf = rp.replay_init(cfg.mem_size, rp.transition_spec(OBS, NA))
    rng = np.random.default_rng(1)
    add = lambda b, tr: rp.replay_add(b, tr, priority=jnp.asarray(1.0))
    buf = _fill(add, buf, rng, 12)
    st2, buf2, metrics = sac.learn(cfg, st, buf, jax.random.PRNGKey(2))
    assert np.isfinite(float(metrics["critic_loss"]))
    assert np.isfinite(float(metrics["actor_loss"]))
    # params actually moved
    moved = jax.tree_util.tree_reduce(
        lambda a, b: a + b,
        jax.tree_util.tree_map(
            lambda a, b: float(jnp.sum(jnp.abs(a - b))),
            st.actor_params, st2.actor_params))
    assert moved > 0


def test_td3_cnn_learn_step():
    cfg = td3.TD3Config(obs_dim=OBS, n_actions=NA, batch_size=8, mem_size=32,
                        img_shape=(H, W), warmup=0)
    st = td3.td3_init(jax.random.PRNGKey(0), cfg)
    buf = rp.replay_init(cfg.mem_size, rp.transition_spec(OBS, NA))
    add = lambda b, tr: rp.replay_add(b, tr, priority=jnp.asarray(1.0))
    buf = _fill(add, buf, np.random.default_rng(1), 12)
    st2, buf2, metrics = td3.learn(cfg, st, buf, jax.random.PRNGKey(2))
    assert np.isfinite(float(metrics["critic_loss"]))


def test_ddpg_cnn_learn_step():
    cfg = ddpg.DDPGConfig(obs_dim=OBS, n_actions=NA, batch_size=8,
                          mem_size=32, img_shape=(H, W))
    st = ddpg.ddpg_init(jax.random.PRNGKey(0), cfg)
    buf = rp.replay_init(cfg.mem_size, rp.transition_spec(OBS, NA))
    add = lambda b, tr: rp.replay_add(b, tr, priority=jnp.asarray(1.0))
    buf = _fill(add, buf, np.random.default_rng(1), 12)
    st2, buf2, metrics = ddpg.learn(cfg, st, buf, jax.random.PRNGKey(2))
    assert np.isfinite(float(metrics["critic_loss"]))


def test_cnn_actor_action_range():
    cfg = sac.SACConfig(obs_dim=OBS, n_actions=NA, img_shape=(H, W))
    st = sac.sac_init(jax.random.PRNGKey(0), cfg)
    obs = jnp.asarray(np.random.default_rng(3).standard_normal(
        (5, OBS)).astype(np.float32))
    a = sac.choose_action(cfg, st, obs, jax.random.PRNGKey(1))
    assert a.shape == (5, NA)
    assert np.all(np.abs(np.asarray(a)) <= 1.0)
