"""Tests for autodiff/influence tools against closed-form linear-model math.

For a linear model y = A x with MSE loss L = ||Ax - y0||^2 / N the reference
quantities have closed forms, giving golden values the JAX implementations
must reproduce (the reference's own check is the elastic-net env behaviour,
enetenv.py:117-139).
"""

import jax
import jax.numpy as jnp
import numpy as np

from smartcal_tpu.ops import (
    cross_derivative,
    gradient,
    hessian_vec_prod,
    history_init,
    history_push,
    influence_matrix,
    inverse_hessian_vec_prod,
    jacobian,
    lbfgs_solve,
    loss_hvp,
)


def test_gradient_vjp():
    A = jnp.arange(12.0).reshape(3, 4)
    f = lambda x: A @ x
    x = jnp.ones(4)
    g = gradient(f, x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(A).sum(axis=0),
                               rtol=1e-6)


def test_jacobian_dense():
    A = jnp.arange(12.0).reshape(3, 4)
    jac = jacobian(lambda x: A @ x, jnp.ones(4))
    np.testing.assert_allclose(np.asarray(jac), np.asarray(A), rtol=1e-6)


def test_pearlmutter_hvp_quadratic():
    rng = np.random.default_rng(0)
    H = rng.normal(size=(5, 5))
    H = (H + H.T).astype(np.float32)
    f = lambda x: 0.5 * x @ (jnp.asarray(H) @ x)
    v = jnp.asarray(rng.normal(size=5).astype(np.float32))
    hv = hessian_vec_prod(f, jnp.zeros(5), v)
    np.testing.assert_allclose(np.asarray(hv), H @ np.asarray(v), rtol=1e-4)


def test_loss_hvp_pytree():
    params = {"w": jnp.ones((3,)), "b": jnp.zeros(())}

    def loss(p):
        return jnp.sum(p["w"] ** 2) * 2.0 + p["b"] ** 2

    # ravel_pytree sorts dict keys: flat order is (b, w0, w1, w2);
    # Hessian is diag(2, 4, 4, 4)
    v = jnp.array([1.0, 2.0, 3.0, 4.0])
    hv = loss_hvp(loss, params, v)
    np.testing.assert_allclose(np.asarray(hv), [2.0, 8.0, 12.0, 16.0],
                               rtol=1e-6)


def test_taylor_inverse_hvp_direction():
    """The reference normalises every iterate (autograd_tools.py:186-192), so
    only the *direction* of H^{-1} v is recovered — test that."""
    rng = np.random.default_rng(2)
    L = rng.normal(size=(4, 4))
    H = (L @ L.T / 8 + 0.5 * np.eye(4)).astype(np.float32)  # spectrum < 1
    f = lambda x: 0.5 * x @ (jnp.asarray(H) @ x)
    v = jnp.asarray(rng.normal(size=4).astype(np.float32))
    out = inverse_hessian_vec_prod(f, jnp.zeros(4), v, maxiter=50)
    want = np.linalg.solve(H, np.asarray(v))
    want /= np.linalg.norm(want)
    got = np.array(out)
    got /= np.linalg.norm(got)
    # sign-insensitive directional match
    cos = abs(float(got @ want))
    assert cos > 0.99


def test_cross_derivative_linear_model():
    """L(theta, x) = ||x . theta||^2 has d2L/dx dtheta closed form."""
    theta = jnp.asarray(np.array([1.0, 2.0], np.float32))
    x = jnp.asarray(np.array([3.0, 4.0], np.float32))

    def loss(p, xx):
        return jnp.sum((xx * p) ** 2)

    got = cross_derivative(loss, theta, x)  # (P, N)
    # dL/dtheta_j = 2 x_j^2 theta_j ; d/dx_i -> diag(4 x theta)
    want = np.diag(4.0 * np.asarray(x) * np.asarray(theta))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


def test_influence_matrix_linear_closed_form():
    """Linear model m(theta) = X theta, loss = mean((m - y)^2).

    H = 2 X^T X / M,   d2L/dx_i dtheta = column i of C where
    C = 2/M (X^T diag(r)~ + ...) — instead of deriving by hand we compare
    against a finite-difference reference computed in numpy float64.
    """
    rng = np.random.default_rng(4)
    M_out, N_in = 3, 3
    X = rng.normal(size=(M_out, N_in)).astype(np.float32)
    theta0 = rng.normal(size=N_in).astype(np.float32)
    y = (X @ theta0 + 0.1 * rng.normal(size=M_out)).astype(np.float32)

    # model: m_j = sum_k X_jk p_k x_k  (elementwise-scaled linear model so the
    # input actually enters the graph)
    def model_fn(p, xx):
        return jnp.asarray(X) @ (p * xx)

    params = jnp.asarray(theta0)
    x_in = jnp.ones(N_in)

    # fit params with LBFGS first so the curvature history approximates H
    def train_loss(p):
        pred = jnp.asarray(X) @ (p * x_in)
        return jnp.mean((pred - jnp.asarray(y)) ** 2)

    res = lbfgs_solve(train_loss, params, max_iters=60)

    If = influence_matrix(model_fn, res.x, x_in, jnp.asarray(y), hist=res.hist)
    assert If.shape == (M_out, N_in)
    assert np.all(np.isfinite(np.asarray(If)))

    # cross-check: with exact inverse Hessian, If = J H^{-1} C
    Xn = np.asarray(X, np.float64)
    p_opt = np.asarray(res.x, np.float64)
    # loss = mean((X (p*x) - y)^2); at x = ones, H = 2/M X^T X (w.r.t. p)
    H = 2.0 / M_out * Xn.T @ Xn
    # C[:, i] = d/dx_i (2/M X^T diag(x) ... ) evaluated via autodiff instead:
    def loss_np(p, xx):
        rr = Xn @ (p * xx) - np.asarray(y, np.float64)
        return float(np.mean(rr ** 2))

    eps = 1e-6
    P = len(p_opt)
    C = np.zeros((P, N_in))
    for i in range(N_in):
        xp = np.ones(N_in); xp[i] += eps
        xm = np.ones(N_in); xm[i] -= eps
        gp = np.zeros(P); gm = np.zeros(P)
        for j in range(P):
            pp = p_opt.copy(); pp[j] += eps
            pm = p_opt.copy(); pm[j] -= eps
            gp[j] = (loss_np(pp, xp) - loss_np(pm, xp)) / (2 * eps)
            gm[j] = (loss_np(pp, xm) - loss_np(pm, xm)) / (2 * eps)
        C[:, i] = (gp - gm) / (2 * eps)

    # dm/dp at x=1 is X, so If = X H^{-1} C
    want = (Xn @ np.linalg.solve(H, C))
    got = np.asarray(If, np.float64)
    # L-BFGS history is an approximation of H^{-1}; require qualitative match
    denom = np.linalg.norm(want) + 1e-12
    rel = np.linalg.norm(got - want) / denom
    assert rel < 0.35, f"relative deviation {rel}"


def test_influence_matrix_taylor_path_finite():
    rng = np.random.default_rng(6)
    X = jnp.asarray(rng.normal(size=(4, 4)).astype(np.float32))

    def model_fn(p, xx):
        return X @ (p * xx)

    params = jnp.asarray(rng.normal(size=4).astype(np.float32))
    If = influence_matrix(model_fn, params, jnp.ones(4),
                          jnp.zeros(4), hist=None, taylor_iters=5)
    assert If.shape == (4, 4)
    assert np.all(np.isfinite(np.asarray(If)))
