"""SKA-tier kernels (ISSUE 13) vs their retained oracles.

Every blocked / sharded / mixed-precision kernel of the N-scaling push
is pinned here against the f32/XLA chain it replaces:

* blocked Hessian core (lax.scan over baseline blocks) vs the unblocked
  scatter-free core AND the scatter oracle;
* blocked + Pallas (interpret tier) factored imagers vs the factored
  and direct-DFT oracles;
* bf16 policy rows within their DOCUMENTED tolerances, f32-pinned
  outputs bit-exact under precision="bf16" (the policy must not touch
  them);
* baseline-axis-sharded influence vs the single-device optimized chain
  on the virtual mesh, including the transfer-guard proof that no
  operand lands on the host mid-program (the PR 12 sharded-replay
  pattern);
* memory-footprint accounting: peak-bytes fields present, monotone in
  N at fixed shards, and the sharding-aware division.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smartcal_tpu.cal import imager, influence as influence_mod, kernels
from smartcal_tpu.cal import creal, solver
from smartcal_tpu.envs.radio import RadioBackend
from smartcal_tpu.obs import costs as obs_costs
from smartcal_tpu.ops import pallas_imager
from smartcal_tpu.parallel import make_mesh
from smartcal_tpu.parallel.sharded_cal import influence_baseline_sharded

N_STATIONS = 6           # B = 15 baselines: shards over the 5-device mesh
NFREQ = 2
NCHUNKS = 2
K = 3

# documented bf16 tolerance: bf16 operand rounding is ~3e-3 relative,
# the f32 accumulation keeps it from growing with the reduction length
BF16_RTOL = 2e-2


@pytest.fixture(scope="module")
def episode():
    backend = RadioBackend(n_stations=N_STATIONS, n_freqs=NFREQ,
                           n_times=4, tdelta=2, admm_iters=2,
                           lbfgs_iters=2, init_iters=3, npix=16)
    ep, mdl = backend.new_demixing_episode(jax.random.PRNGKey(7), K)
    res = solver.solve_admm(ep.V, ep.Ccal, ep.obs.freqs, ep.f0,
                            jnp.asarray(mdl.rho), backend._solver_cfg(K),
                            n_chunks=backend.n_chunks)
    freqs = np.asarray(ep.obs.freqs)
    hadd = influence_mod.consensus_hadd_scalars(
        mdl.rho, np.zeros(K, np.float32), freqs, ep.f0, 0,
        n_poly=backend.n_poly, polytype=backend.polytype)
    Rk = solver.residual_to_kernel(res.residual[0])
    return backend, ep, res, hadd, Rk


def _rel(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return np.max(np.abs(a - b)) / max(np.max(np.abs(b)), 1e-12)


# ---------------------------------------------------------------------------
# Blocked Hessian
# ---------------------------------------------------------------------------

def test_blocked_hessian_matches_oracles(episode):
    backend, ep, res, hadd, Rk = episode
    Cs, Js = ep.Ccal[0], res.J[0][0]
    H_oracle = kernels.hessian_res_sr(Rk[:2 * 15 * 2], Cs[:, :15 * 2],
                                      Js, N_STATIONS)
    H_opt = kernels.hessian_res_opt_sr(Rk[:2 * 15 * 2], Cs[:, :15 * 2],
                                       Js, N_STATIONS)
    R3, C5, B, T, _ = kernels._split_samples_sr(Rk[:2 * 15 * 2],
                                                Cs[:, :15 * 2],
                                                N_STATIONS)
    p_idx, q_idx = kernels.baseline_indices(N_STATIONS)
    J4 = kernels._jones_blocks_sr(Js, N_STATIONS)
    for blk in (4, 7, 15):      # non-dividing sizes exercise the padding
        H_blk = kernels._hessian_res_core_blocked_sr(
            R3, C5, J4[:, p_idx], J4[:, q_idx], N_STATIONS, blk)
        assert _rel(H_blk, H_opt) < 1e-5, blk
        assert _rel(H_blk, H_oracle) < 1e-5, blk


def test_blocked_influence_chain_matches_unblocked(episode):
    backend, ep, res, hadd, Rk = episode
    ref = influence_mod.influence_visibilities(
        Rk, ep.Ccal[0], res.J[0], hadd, N_STATIONS, NCHUNKS)
    blk = influence_mod.influence_visibilities(
        Rk, ep.Ccal[0], res.J[0], hadd, N_STATIONS, NCHUNKS,
        block_baselines=4)
    assert _rel(blk.vis, ref.vis) < 1e-5
    np.testing.assert_array_equal(np.asarray(blk.llr),
                                  np.asarray(ref.llr))


# ---------------------------------------------------------------------------
# Blocked / Pallas factored imagers
# ---------------------------------------------------------------------------

def _imager_case(rng, R, freq=150e6):
    uvw = rng.uniform(-2e3, 2e3, size=(R, 3)).astype(np.float32)
    vis = rng.standard_normal((R, 2)).astype(np.float32)
    return uvw, vis, freq, imager.default_cell(uvw, freq)


def test_blocked_factored_imager_matches_oracles(rng):
    uvw, vis, freq, cell = _imager_case(rng, R=700)
    ref = np.asarray(imager.dirty_image_sr_xla(uvw, vis, freq, cell,
                                               npix=64))
    fac = np.asarray(imager.dirty_image_factored_sr(uvw, vis, freq, cell,
                                                    npix=64))
    blk = np.asarray(imager.dirty_image_factored_blocked_sr(
        uvw, vis, freq, cell, npix=64, block_r=256))
    assert _rel(blk, fac) < 1e-5
    assert _rel(blk, ref) < 1e-4


def test_factored_pallas_interpret_matches_oracles(rng):
    """The tiled Pallas factored imager through the interpreter on CPU —
    the tier-1 guard that keeps the kernel from being TPU-tunnel-only
    dead code (ISSUE 13 satellite)."""
    uvw, vis, freq, cell = _imager_case(rng, R=700)  # pads to 3 R tiles
    ref = np.asarray(imager.dirty_image_factored_sr(uvw, vis, freq, cell,
                                                    npix=128))
    out = np.asarray(pallas_imager.dirty_image_factored_pallas(
        uvw, vis, freq, cell, npix=128, interpret=True))
    assert out.shape == (128, 128)
    np.testing.assert_allclose(out, ref, rtol=2e-4,
                               atol=2e-4 * np.max(np.abs(ref)))


def test_factored_pallas_rejects_unaligned_npix(rng):
    uvw, vis, freq, cell = _imager_case(rng, R=64)
    with pytest.raises(ValueError):
        pallas_imager.dirty_image_factored_pallas(uvw, vis, freq, cell,
                                                  npix=96)


# ---------------------------------------------------------------------------
# Mixed precision: bf16 within tolerance, pinned outputs bit-exact
# ---------------------------------------------------------------------------

def test_bf16_imager_within_documented_tolerance(rng):
    uvw, vis, freq, cell = _imager_case(rng, R=700)
    f32 = np.asarray(imager.dirty_image_factored_sr(uvw, vis, freq, cell,
                                                    npix=64))
    b16 = np.asarray(imager.dirty_image_factored_sr(
        uvw, vis, freq, cell, npix=64, precision="bf16"))
    scale = np.max(np.abs(f32))
    assert np.max(np.abs(b16 - f32)) < BF16_RTOL * scale
    # the env observation statistic survives the narrowing
    assert float(np.std(b16)) == pytest.approx(float(np.std(f32)),
                                               rel=BF16_RTOL)
    # and the blocked kernel applies the same policy
    b16b = np.asarray(imager.dirty_image_factored_blocked_sr(
        uvw, vis, freq, cell, npix=64, block_r=256, precision="bf16"))
    assert np.max(np.abs(b16b - f32)) < BF16_RTOL * scale


def test_bf16_influence_within_tolerance_llr_pinned(episode):
    """precision="bf16" narrows ONLY the colmeans contraction: the
    influence visibilities move within the documented band while the
    LLR detector — f32-pinned by policy — stays bit-exact."""
    backend, ep, res, hadd, Rk = episode
    f32 = influence_mod.influence_visibilities(
        Rk, ep.Ccal[0], res.J[0], hadd, N_STATIONS, NCHUNKS)
    b16 = influence_mod.influence_visibilities(
        Rk, ep.Ccal[0], res.J[0], hadd, N_STATIONS, NCHUNKS,
        precision="bf16")
    assert 0 < _rel(b16.vis, f32.vis) < BF16_RTOL
    np.testing.assert_array_equal(np.asarray(b16.llr),
                                  np.asarray(f32.llr))


def test_f32_policy_is_bit_identical_to_prepolicy(episode):
    """precision="f32" (the default everywhere) must be the EXACT
    pre-policy program — not merely close."""
    backend, ep, res, hadd, Rk = episode
    default = influence_mod.influence_visibilities(
        Rk, ep.Ccal[0], res.J[0], hadd, N_STATIONS, NCHUNKS)
    explicit = influence_mod.influence_visibilities(
        Rk, ep.Ccal[0], res.J[0], hadd, N_STATIONS, NCHUNKS,
        precision="f32")
    np.testing.assert_array_equal(np.asarray(default.vis),
                                  np.asarray(explicit.vis))


def test_precision_policy_pins_and_validates():
    from smartcal_tpu.cal import precision as prec

    assert prec.contraction_dtype("imager_matmul", "bf16") == jnp.bfloat16
    assert prec.contraction_dtype("imager_matmul", "f32") == prec.F32
    # pinned rows never narrow
    assert prec.contraction_dtype("hessian", "bf16") == prec.F32
    assert prec.contraction_dtype("solve_4n", "bf16") == prec.F32
    with pytest.raises(ValueError):
        prec.check("fp16")
    with pytest.raises(KeyError):
        prec.contraction_dtype("unknown-kernel", "bf16")
    with pytest.raises(ValueError):
        RadioBackend(precision="f16")


def test_bf16_creal_einsum_accumulates_f32():
    rng = np.random.default_rng(3)
    a = creal.split(rng.standard_normal((64, 8))
                    + 1j * rng.standard_normal((64, 8)))
    b = creal.split(rng.standard_normal((64, 8))
                    + 1j * rng.standard_normal((64, 8)))
    ref = np.asarray(creal.einsum("bi,bj->ij", jnp.asarray(a),
                                  jnp.asarray(b)))
    out = creal.einsum("bi,bj->ij", jnp.asarray(a), jnp.asarray(b),
                       compute_dtype=jnp.bfloat16)
    assert out.dtype == jnp.float32          # f32 accumulation contract
    assert _rel(out, ref) < BF16_RTOL


# ---------------------------------------------------------------------------
# Baseline-axis sharding
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("perdir", [False, True])
def test_influence_baseline_sharded_matches_single_device(episode, perdir):
    backend, ep, res, hadd, Rk = episode
    ref = influence_mod.influence_visibilities(
        Rk, ep.Ccal[0], res.J[0], hadd, N_STATIONS, NCHUNKS,
        perdir=perdir)
    mesh = make_mesh((5,), ("bp",), devices=jax.devices()[:5])
    out = influence_baseline_sharded(
        mesh, Rk, ep.Ccal[0], res.J[0], hadd, N_STATIONS, NCHUNKS,
        axis="bp", perdir=perdir)
    assert _rel(out.vis, ref.vis) < 1e-5
    np.testing.assert_allclose(np.asarray(out.llr), np.asarray(ref.llr),
                               rtol=1e-4, atol=1e-4)


def test_influence_baseline_sharded_rejects_nondividing(episode):
    backend, ep, res, hadd, Rk = episode
    mesh = make_mesh((4,), ("bp",), devices=jax.devices()[:4])
    with pytest.raises(ValueError):          # B=15 not divisible by 4
        influence_baseline_sharded(mesh, Rk, ep.Ccal[0], res.J[0], hadd,
                                   N_STATIONS, NCHUNKS, axis="bp")


def test_influence_baseline_sharded_transfer_guard(episode):
    """No operand of the baseline-sharded program lands on the host
    mid-run: collectives stay on-device (the PR 12 sharded-replay
    transfer-guard pattern).  First call compiles outside the guard;
    the guarded call is the steady-state proof."""
    backend, ep, res, hadd, Rk = episode
    mesh = make_mesh((5,), ("bp",), devices=jax.devices()[:5])
    args = (mesh, Rk, ep.Ccal[0], res.J[0], jnp.asarray(hadd),
            N_STATIONS, NCHUNKS)
    out = influence_baseline_sharded(*args, axis="bp")
    jax.block_until_ready(out.vis)
    with jax.transfer_guard("disallow"):
        out2 = influence_baseline_sharded(*args, axis="bp")
        jax.block_until_ready((out2.vis, out2.llr))
    np.testing.assert_array_equal(np.asarray(out.vis),
                                  np.asarray(out2.vis))


def test_backend_baseline_shard_route_is_reachable():
    """The RadioBackend routes influence through baseline sharding at
    SKA scale: verified on a small synthetic backend by forcing the
    thresholds down (the routing decision, not the physics, is what
    this pins)."""
    from smartcal_tpu.envs import radio as radio_mod

    backend = RadioBackend(n_stations=N_STATIONS, n_freqs=NFREQ,
                           n_times=4, tdelta=2, admm_iters=2,
                           lbfgs_iters=2, init_iters=3, npix=16,
                           shard=True)
    ep, mdl = backend.new_demixing_episode(jax.random.PRNGKey(9), K)
    res = backend.calibrate(ep, mdl.rho)
    orig_min_b = radio_mod._BLOCK_MIN_B
    radio_mod._BLOCK_MIN_B = 10          # B=15 >= 10 -> baseline route
    try:
        img = backend.influence_image(ep, res, mdl.rho,
                                      np.zeros(K, np.float32))
    finally:
        radio_mod._BLOCK_MIN_B = orig_min_b
    ref = backend.influence_image(ep, res, mdl.rho,
                                  np.zeros(K, np.float32))
    assert _rel(img, ref) < 1e-4


def test_colmeans_normalizers_survive_ska_scale():
    """At N=256 the B^2*T normalization (~1.1e10) overflows int32 if
    left as a python-int operand — the trace aborts before any compute.
    Shape-only abstract trace at real SKA N (no execution, no compile):
    the float normalizers must make this legal."""
    n = 256
    B = n * (n - 1) // 2
    T, Ts, Kd = 2, 1, 2
    sd = jax.ShapeDtypeStruct
    f32 = jnp.float32
    lowered = influence_mod.influence_visibilities.lower(
        sd((2 * B * T, 2, 2), f32),
        sd((Kd, T * B, 4, 2), f32),
        sd((Ts, Kd, 2 * n, 2, 2), f32),
        sd((Kd,), f32),
        n_stations=n, n_chunks=Ts, block_baselines=2048)
    assert lowered is not None


# ---------------------------------------------------------------------------
# Memory-footprint accounting
# ---------------------------------------------------------------------------

def _influence_footprint(n_stations, npix=16):
    """Shape-only footprint of the fused influence program at N."""
    B = n_stations * (n_stations - 1) // 2
    T = 4
    sd = jax.ShapeDtypeStruct
    f32 = jnp.float32
    args = (sd((NFREQ, T, B, 2, 2, 2), f32),            # residual
            sd((NFREQ, K, T * B, 4, 2), f32),           # C
            sd((NFREQ, NCHUNKS, K, 2 * n_stations, 2, 2), f32),  # J
            sd((NFREQ, K), f32),                        # hadd
            sd((NFREQ,), f32),                          # freqs
            sd((T * B, 3), f32))                        # uvw
    return obs_costs.stage_cost(
        influence_mod.influence_images_multi, *args,
        static_argnames=("cell", "n_stations", "n_chunks", "npix"),
        cell=1e-3, n_stations=n_stations, n_chunks=NCHUNKS, npix=npix)


def test_footprint_fields_present_and_monotone_in_n():
    small = _influence_footprint(6)
    big = _influence_footprint(10)
    for c in (small, big):
        for k in ("peak_bytes", "arg_bytes", "out_bytes", "temp_bytes"):
            assert k in c, c
        assert c["peak_bytes"] > 0
    # B grows 15 -> 45: the footprint must track it at fixed shards
    assert big["peak_bytes"] > small["peak_bytes"]


def test_footprint_shard_division_on_virtual_mesh(tmp_path):
    """record_stage_cost under a 4-shard claim divides the fused peak by
    the shard count and tags the event (the PR 12 4-shard-mesh test
    pattern applied to the accounting layer)."""
    import json

    from smartcal_tpu import obs

    path = str(tmp_path / "cost.jsonl")
    rl = obs.RunLog(path, run_id="fp-1")
    obs.activate(rl)
    obs_costs.set_enabled(True)
    try:
        a = jnp.ones((64, 64))
        got = obs_costs.record_stage_cost(
            "footprint_test", lambda x: x @ x.T, a,
            shards=4, compute_dtype="bf16")
    finally:
        obs_costs.set_enabled(False)
        obs.deactivate(rl)
        rl.close()
        obs_costs.reset_cache()
    assert got is not None and "peak_bytes" in got
    assert got["shards"] == 4
    assert got["peak_bytes_per_shard"] == pytest.approx(
        got["peak_bytes"] / 4)
    assert got["compute_dtype"] == "bf16"
    events = [json.loads(ln) for ln in open(path) if ln.strip()]
    cost = [e for e in events if e["event"] == "cost"]
    assert cost and cost[0]["peak_bytes_per_shard"] == pytest.approx(
        got["peak_bytes"] / 4)
    assert cost[0]["compute_dtype"] == "bf16"
