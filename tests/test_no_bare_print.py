"""Static check: no new bare ``print(`` in smartcal_tpu/ (obs satellite).

Diagnostics must flow through the obs layer (``obs.echo`` -> stderr +
structured event, ``obs.emit_json`` -> the stdout machine interface) so
logging stays structured and ``--quiet``-able.  ``smartcal_tpu/obs/
console.py`` is the one sanctioned ``print`` site.  Tokenizer-based so
strings, comments, and ``.print(`` method calls never false-positive.
"""

import io
import os
import tokenize

PKG = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "smartcal_tpu")

# relative paths (to smartcal_tpu/) allowed to call print()
ALLOWLIST = {
    os.path.join("obs", "console.py"),
}

_SKIP_TYPES = (tokenize.NL, tokenize.NEWLINE, tokenize.INDENT,
               tokenize.DEDENT, tokenize.COMMENT)


def bare_print_lines(path):
    """Line numbers of bare ``print(`` calls (NAME 'print' followed by
    '(', not preceded by '.' or 'def')."""
    with open(path, "rb") as fh:
        src = fh.read().decode("utf-8")
    toks = list(tokenize.generate_tokens(io.StringIO(src).readline))
    hits = []
    for i, t in enumerate(toks):
        if t.type != tokenize.NAME or t.string != "print":
            continue
        prev = next((p for p in reversed(toks[:i])
                     if p.type not in _SKIP_TYPES), None)
        if prev is not None and prev.string in (".", "def"):
            continue
        nxt = next((n for n in toks[i + 1:] if n.type not in _SKIP_TYPES),
                   None)
        if nxt is not None and nxt.string == "(":
            hits.append(t.start[0])
    return hits


def test_no_bare_print_in_package():
    offenders = []
    for root, _, files in os.walk(PKG):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, PKG)
            if rel in ALLOWLIST:
                continue
            for line in bare_print_lines(path):
                offenders.append(f"smartcal_tpu/{rel}:{line}")
    assert not offenders, (
        "bare print() found — route human output through smartcal_tpu.obs."
        "echo (stderr + structured event) or obs.emit_json (stdout machine "
        "payloads), or extend the allowlist deliberately:\n  "
        + "\n  ".join(offenders))


def test_allowlist_entries_exist():
    """A deleted/renamed sanctioned file must not linger in the list."""
    for rel in ALLOWLIST:
        assert os.path.exists(os.path.join(PKG, rel)), rel
