"""Static check: no new bare ``print(`` in smartcal_tpu/ or tools/.

Diagnostics must flow through the obs layer (``obs.echo`` -> stderr +
structured event, ``obs.emit_json`` -> the stdout machine interface) so
logging stays structured and ``--quiet``-able.  ``smartcal_tpu/obs/
console.py`` is the one sanctioned ``print`` site in the package; in
``tools/`` an explicit stdout allowlist names the CLIs whose stdout IS
their product (report/sweep/bench output that scripts parse or humans
pipe) — a new tool must either route through ``smartcal_tpu.obs.console``
or be added there deliberately.  Tokenizer-based so strings, comments,
and ``.print(`` method calls never false-positive.
"""

import io
import os
import tokenize

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(_ROOT, "smartcal_tpu")
TOOLS = os.path.join(_ROOT, "tools")

# relative paths (to smartcal_tpu/) allowed to call print()
ALLOWLIST = {
    os.path.join("obs", "console.py"),
}

# tools/ files sanctioned to print to stdout directly: their stdout is
# the tool's interface (obs_report/obs_tail render reports and must run
# standalone without the package importable; the sweeps/benches emit the
# JSON lines capture scripts parse).  Anything NOT listed here must
# route output through smartcal_tpu.obs.console.
TOOLS_STDOUT_ALLOWLIST = {
    "bench_host_seg.py",
    "bench_per.py",
    "bench_solve_eval.py",
    "capture_calib_episode.py",
    "certify_batched.py",
    "chip_checks.py",
    "convert_ateam.py",
    "eig_mode_parity.py",
    "enet_hint_stats.py",
    "measure_reference.py",
    "obs_report.py",
    "obs_tail.py",
    "summarize_demix_curves.py",
    "sweep_calib.py",
    "sweep_demix.py",
    "sweep_enet.py",
}

_SKIP_TYPES = (tokenize.NL, tokenize.NEWLINE, tokenize.INDENT,
               tokenize.DEDENT, tokenize.COMMENT)


def bare_print_lines(path):
    """Line numbers of bare ``print(`` calls (NAME 'print' followed by
    '(', not preceded by '.' or 'def')."""
    with open(path, "rb") as fh:
        src = fh.read().decode("utf-8")
    toks = list(tokenize.generate_tokens(io.StringIO(src).readline))
    hits = []
    for i, t in enumerate(toks):
        if t.type != tokenize.NAME or t.string != "print":
            continue
        prev = next((p for p in reversed(toks[:i])
                     if p.type not in _SKIP_TYPES), None)
        if prev is not None and prev.string in (".", "def"):
            continue
        nxt = next((n for n in toks[i + 1:] if n.type not in _SKIP_TYPES),
                   None)
        if nxt is not None and nxt.string == "(":
            hits.append(t.start[0])
    return hits


def test_no_bare_print_in_package():
    offenders = []
    for root, _, files in os.walk(PKG):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, PKG)
            if rel in ALLOWLIST:
                continue
            for line in bare_print_lines(path):
                offenders.append(f"smartcal_tpu/{rel}:{line}")
    assert not offenders, (
        "bare print() found — route human output through smartcal_tpu.obs."
        "echo (stderr + structured event) or obs.emit_json (stdout machine "
        "payloads), or extend the allowlist deliberately:\n  "
        + "\n  ".join(offenders))


def test_no_bare_print_in_tools():
    offenders = []
    for fn in sorted(os.listdir(TOOLS)):
        if not fn.endswith(".py") or fn in TOOLS_STDOUT_ALLOWLIST:
            continue
        for line in bare_print_lines(os.path.join(TOOLS, fn)):
            offenders.append(f"tools/{fn}:{line}")
    assert not offenders, (
        "bare print() in an unlisted tool — route output through "
        "smartcal_tpu.obs.console (echo/emit_json) or add the file to "
        "TOOLS_STDOUT_ALLOWLIST deliberately:\n  " + "\n  ".join(offenders))


def test_allowlist_entries_exist():
    """A deleted/renamed sanctioned file must not linger in the lists."""
    for rel in ALLOWLIST:
        assert os.path.exists(os.path.join(PKG, rel)), rel
    for fn in TOOLS_STDOUT_ALLOWLIST:
        assert os.path.exists(os.path.join(TOOLS, fn)), fn
