"""Static check: no new bare ``print(`` in smartcal_tpu/ or tools/.

THIN SHIM (kept one release so the tier-1 dot count doesn't regress):
the policy now lives in the graftlint ``bare-print`` rule
(:mod:`smartcal_tpu.analysis.rules.prints`, ISSUE 11) with the same
allowlist semantics — ``obs.echo`` -> stderr + structured event,
``obs.emit_json`` -> the stdout machine interface,
``smartcal_tpu/obs/console.py`` the one sanctioned package ``print``
site, and an explicit stdout allowlist for tools whose stdout IS their
product.  These tests re-assert the rule through the framework; new
code should run ``python tools/lint.py`` (which also enforces it via
tests/test_graftlint.py's gate).
"""

import os

from smartcal_tpu import analysis
from smartcal_tpu.analysis.rules.prints import (PKG_ALLOWLIST,
                                                TOOLS_STDOUT_ALLOWLIST)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bare_print_offenders(paths):
    rules = analysis.all_rules()
    sub = {"bare-print": rules["bare-print"]}
    return [f"{f.path}:{f.line}"
            for f in analysis.lint_paths(paths, _ROOT, rules=sub)
            if f.rule == "bare-print"]


def test_no_bare_print_in_package():
    offenders = _bare_print_offenders(["smartcal_tpu"])
    assert not offenders, (
        "bare print() found — route human output through smartcal_tpu.obs."
        "echo (stderr + structured event) or obs.emit_json (stdout machine "
        "payloads), or extend the allowlist in smartcal_tpu/analysis/rules/"
        "prints.py deliberately:\n  " + "\n  ".join(offenders))


def test_no_bare_print_in_tools():
    offenders = _bare_print_offenders(["tools"])
    assert not offenders, (
        "bare print() in an unlisted tool — route output through "
        "smartcal_tpu.obs.console (echo/emit_json) or add the file to "
        "TOOLS_STDOUT_ALLOWLIST in smartcal_tpu/analysis/rules/prints.py "
        "deliberately:\n  " + "\n  ".join(offenders))


def test_allowlist_entries_exist():
    """A deleted/renamed sanctioned file must not linger in the lists."""
    for rel in PKG_ALLOWLIST:
        assert os.path.exists(os.path.join(_ROOT, "smartcal_tpu", rel)), rel
    for fn in TOOLS_STDOUT_ALLOWLIST:
        assert os.path.exists(os.path.join(_ROOT, "tools", fn)), fn
