"""Cross-process actor fleet (ISSUE 12): framed-IPC integrity units,
process-mode Fleet config/topology contracts, the pump's corrupt-frame
drop-and-log (a truncated mid-send payload never reaches the learner),
and spawn e2e — echo collect/publish/stop, corrupt-mid-send recovery,
simulated 2-host attach, plus (slow) worker-death restart with poison
skip and the process-mode + sharded-replay training loop."""

import multiprocessing as mp
import threading
import time

import numpy as np
import pytest

from smartcal_tpu.runtime import BackoffPolicy, Fleet, clear_faults
from smartcal_tpu.runtime import ipc
from smartcal_tpu.runtime import supervisor as sup
from smartcal_tpu.runtime.atomic import CorruptStateError

ECHO = {"factory": "fleet_proc_worker:make_echo", "kwargs": {"scale": 3}}
ENV_KW = {"M": 5, "N": 5}
AGENT_KW = {"batch_size": 8, "mem_size": 64}


@pytest.fixture(autouse=True)
def in_tmp(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    yield
    clear_faults()


def _fast_backoff():
    return BackoffPolicy(base_s=0.01, factor=2.0, max_s=0.05, jitter=0.0)


def _collect_until(fleet, want, deadline_s=45.0, max_items=8):
    """Poll + collect until ``want`` items arrived (spawn e2e helper:
    the first result waits out the worker's interpreter start)."""
    out, deadline = [], time.monotonic() + deadline_s
    while len(out) < want and time.monotonic() < deadline:
        fleet.poll()
        out.extend(fleet.collect(max_items, timeout=0.5))
    return out


# ---------------------------------------------------------------------------
# IPC frame integrity
# ---------------------------------------------------------------------------

def test_frame_roundtrip_and_corruption_detection():
    """Every mid-send-death signature — truncated header, truncated
    body, bad magic, flipped payload byte, CRC-valid non-pickle — is a
    CorruptPayloadError (and a CorruptStateError, the drop-and-log
    currency); an intact frame round-trips."""
    msg = ("result", 3, 7, {"x": [1.0, 2.0], "y": "z"})
    blob = ipc.frame_payload(msg)
    assert ipc.unframe_payload(blob) == msg
    assert issubclass(ipc.CorruptPayloadError, CorruptStateError)

    with pytest.raises(ipc.CorruptPayloadError, match="truncated"):
        ipc.unframe_payload(blob[:6])                 # inside the header
    with pytest.raises(ipc.CorruptPayloadError, match="length mismatch"):
        ipc.unframe_payload(blob[:-3])                # body cut mid-send
    with pytest.raises(ipc.CorruptPayloadError, match="bad magic"):
        ipc.unframe_payload(b"XXXX" + blob[4:])
    flipped = bytearray(blob)
    flipped[-1] ^= 0xFF
    with pytest.raises(ipc.CorruptPayloadError, match="CRC"):
        ipc.unframe_payload(bytes(flipped))
    body = b"not a pickle at all"
    bad = ipc._HEADER.pack(ipc.MAGIC, len(body),
                           __import__("zlib").crc32(body)) + body
    with pytest.raises(ipc.CorruptPayloadError, match="unpicklable"):
        ipc.unframe_payload(bad)


def test_resolve_factory_contract():
    fn = ipc.resolve_factory("fleet_proc_worker:make_echo")
    work = fn(scale=2)
    assert work(0, 1, {"w": 4})["scaled"] == 8
    with pytest.raises(ValueError, match="module:callable"):
        ipc.resolve_factory("no_colon_here")
    with pytest.raises(ValueError, match="not found"):
        ipc.resolve_factory("fleet_proc_worker:nope")


# ---------------------------------------------------------------------------
# process-mode Fleet config / topology (no spawn)
# ---------------------------------------------------------------------------

def test_process_mode_config_contracts():
    with pytest.raises(ValueError, match="worker_spec"):
        Fleet(2, None, actor_mode="process")
    with pytest.raises(ValueError, match="actor_mode"):
        Fleet(2, None, actor_mode="banana")
    with pytest.raises(ValueError, match="process"):
        Fleet(2, lambda *a: None, actor_mode="thread", hosts=2)


def test_slot_host_blocks_and_queue_depths():
    """hosts=2 over 8 slots -> contiguous 4+4 simulated-host blocks;
    process mode exposes per-slot ingest depth, thread mode only the
    aggregate (one global queue)."""
    f = Fleet(8, None, actor_mode="process", worker_spec=ECHO, hosts=2)
    assert [f.slot_host(i) for i in range(8)] == [0] * 4 + [1] * 4
    d = f.queue_depths()
    assert d["aggregate"] == 0
    assert sorted(d["per_slot"]) == list(range(8))
    ft = Fleet(2, lambda *a: None)
    assert ft.queue_depths() == {"aggregate": 0}


def test_collect_round_robin_never_starves_a_shard():
    """One hot slot cannot monopolize a collection round: the drain
    rotates shards, so a backed-up shard 0 still yields shard 2's item
    within the first pass."""
    f = Fleet(3, None, actor_mode="process", worker_spec=ECHO,
              queue_depth=4)
    for i in range(3):
        f._shard_qs[0].put((0, i, 0, f"hot{i}"))
    f._shard_qs[2].put((2, 0, 0, "cold"))
    out = f.collect(2, timeout=0.5)
    assert len(out) == 2
    assert {o[0] for o in out} == {0, 2}       # one from each, not 2x hot
    rest = f.collect(8, timeout=0.5)
    assert len(rest) == 2                       # nothing lost
    assert f.queue_depths()["aggregate"] == 0


def test_pump_drops_corrupt_frame_and_delivers_good():
    """The parent-side pump: a corrupt frame (worker died mid-send) is
    dropped and the NEXT good frame still lands in the slot's ingest
    shard — the learner iteration is never poisoned; EOF afterwards
    surfaces as the slot error for the supervisor."""
    f = Fleet(1, None, actor_mode="process", worker_spec=ECHO,
              queue_depth=4)
    a = sup._ProcessActor(f, 0, 0)
    parent, child = mp.Pipe(duplex=True)
    a.conn = parent
    threading.Thread.start(a)                  # pump only, no spawn
    try:
        bad = bytearray(ipc.frame_payload(("result", 0, 1, {"t": 1})))
        bad[-1] ^= 0xFF
        child.send_bytes(bytes(bad))           # dropped
        child.send_bytes(ipc.frame_payload(("result", 0, 1, {"t": 2})))
        child.send_bytes(ipc.frame_payload(("beat", 1)))
        got = f.collect(2, timeout=10.0)
        assert got == [(0, 0, 1, {"t": 2})]    # ONLY the intact frame
        assert a.error is None                 # corruption != slot death
        assert a.iteration == 1                # result advanced the slot
        child.close()                          # peer gone -> slot error
        a.join(timeout=10.0)
        assert not a.is_alive()
        assert isinstance(a.error, RuntimeError)
    finally:
        a.stop_event.set()
        try:
            child.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# spawn e2e
# ---------------------------------------------------------------------------

def test_process_fleet_echo_collect_publish_stop():
    """Real spawned workers: results arrive version-stamped through the
    per-slot shards, a set_weights publish reaches running workers, the
    slot iterations advance, and stop(join=True) reaps every worker
    process."""
    f = Fleet(2, None, actor_mode="process", worker_spec=ECHO,
              queue_depth=2, backoff=_fast_backoff())
    try:
        f.start({"w": 2.0})
        v0 = f.version
        out = _collect_until(f, 2)
        assert len(out) >= 2
        for aid, it, ver, res in out:
            assert res["actor"] == aid and res["iteration"] == it
            assert ver == v0 and res["scaled"] == 6.0
        v1 = f.set_weights({"w": 5.0})
        deadline = time.monotonic() + 45.0
        seen_new = False
        while not seen_new and time.monotonic() < deadline:
            for _, _, ver, res in f.collect(8, timeout=0.5):
                if ver == v1:
                    assert res["w"] == 5.0 and res["scaled"] == 15.0
                    seen_new = True
        assert seen_new, "published weights never reached the workers"
        iters = f.slot_iterations()
        assert set(iters) == {0, 1} and all(v >= 1 for v in iters.values())
    finally:
        f.stop(join=True)
    assert f.alive_count == 0
    for a in f._actors.values():
        assert a.proc is not None and not a.proc.is_alive()


def test_corrupt_mid_send_dropped_then_slot_restarts(monkeypatch):
    """The satellite fix end to end: a worker ships a deliberately
    corrupted result frame at iteration 1 and dies (the mid-send death
    rehearsal, SMARTCAL_IPC_TEST_CORRUPT) — the frame is dropped, the
    supervisor restarts the slot, the replacement resumes PAST the
    corrupted iteration, and no iteration-1 batch ever reaches
    collect."""
    monkeypatch.setenv("SMARTCAL_IPC_TEST_CORRUPT", "1")
    f = Fleet(1, None, actor_mode="process", worker_spec=ECHO,
              queue_depth=4, backoff=_fast_backoff(), max_restarts=3)
    try:
        f.start({"w": 1.0})
        out = _collect_until(f, 1)
        assert [o[1] for o in out] == [0]      # the intact iteration 0
        # worker dies after the corrupt send; wait for restart + resume
        deadline = time.monotonic() + 60.0
        later = []
        while not later and time.monotonic() < deadline:
            f.poll()
            later = f.collect(8, timeout=0.5)
        assert later, "slot never recovered after the corrupt send"
        assert f.restarts_total() >= 1
        assert all(o[1] >= 2 for o in later), later   # 1 skipped, dropped
        assert f.slot_iterations()[0] >= 2
    finally:
        f.stop(join=True)


def test_simulated_two_host_attach():
    """hosts=2: each worker process attaches to its simulated host
    (multihost.attach_simulated) — both host ids are represented in the
    results, per the contiguous slot->host blocks."""
    f = Fleet(2, None, actor_mode="process", worker_spec=ECHO,
              queue_depth=2, hosts=2, backoff=_fast_backoff())
    try:
        f.start({"w": 1.0})
        out = _collect_until(f, 4)
        hosts = {(aid, res["sim_host"]) for aid, _, _, res in out}
        assert {a for a, _ in hosts} == {0, 1}
        assert dict(hosts) == {0: "0/2", 1: "1/2"}
    finally:
        f.stop(join=True)


@pytest.mark.slow
def test_process_worker_death_restart_poison_skip():
    """A worker that raises at iteration 1 dies with an error frame;
    the supervisor restarts the slot after backoff and the replacement
    resumes at iteration 2 — the poison-pill skip surviving a process
    boundary."""
    spec = {"factory": "fleet_proc_worker:make_echo",
            "kwargs": {"scale": 1, "fail_actor": 0, "fail_at": 1}}
    f = Fleet(1, None, actor_mode="process", worker_spec=spec,
              queue_depth=4, backoff=_fast_backoff(), max_restarts=3)
    try:
        f.start({"w": 1.0})
        out = _collect_until(f, 1)
        assert [o[1] for o in out] == [0]
        deadline = time.monotonic() + 60.0
        later, events = [], []
        while not later and time.monotonic() < deadline:
            events.extend(f.poll())
            later = f.collect(8, timeout=0.5)
        kinds = [e["event"] for e in events]
        assert "actor_down" in kinds and "actor_restart" in kinds
        down = next(e for e in events if e["event"] == "actor_down")
        assert "echo poison" in down["reason"]
        restart = next(e for e in events if e["event"] == "actor_restart")
        assert restart["iteration"] == 2       # the poison skip
        assert later and all(o[1] >= 2 for o in later)
    finally:
        f.stop(join=True)


@pytest.mark.slow
def test_train_supervised_process_mode_sharded_replay(tmp_path):
    """The whole ISSUE 12 chain in one driver call: --actor-mode
    process + --replay-shards + --sim-hosts on the enet fleet — scores
    stay finite, the learner's buffer is the mesh-sharded one and
    filled, the summary carries the staleness/saturation means, and the
    per-slot depth + shard-occupancy gauges hit the RunLog."""
    import json

    from smartcal_tpu.parallel import learner
    from smartcal_tpu.rl import replay_sharded as rps

    run = str(tmp_path / "proc_fleet.jsonl")
    (st, buf), scores, summary = learner.train_supervised(
        seed=0, episodes=6, n_actors=2, env_kwargs=ENV_KW,
        agent_kwargs=AGENT_KW, rollout_epochs=1, rollout_steps=4,
        batch_envs=2, is_clip=2.0, ere_eta=0.98, quiet=True,
        metrics=run, restart_backoff=_fast_backoff(),
        actor_mode="process", replay_shards=4, sim_hosts=2)
    assert len(scores) == 6 and np.all(np.isfinite(scores))
    assert isinstance(buf, rps.ShardedReplayState)
    assert buf.n_shards == 4 and int(buf.cntr) > 0
    assert int(st.learn_counter) > 0
    assert summary["transition_staleness_mean"] >= 0.0
    assert 0.0 <= summary["is_clip_saturation"] <= 1.0
    events = [json.loads(ln) for ln in open(run) if ln.strip()]
    gauges = {e["name"] for e in events if e.get("event") == "gauge"}
    assert {"ingest_queue_depth", "replay_shard_occupancy",
            "weight_staleness_versions"} <= gauges
    slots = {e.get("slot") for e in events if e.get("event") == "gauge"
             and e["name"] == "ingest_queue_depth" and "slot" in e}
    assert {0, 1} <= slots
    shards = {e.get("shard") for e in events if e.get("event") == "gauge"
              and e["name"] == "replay_shard_occupancy"}
    assert shards == {0, 1, 2, 3}
