"""Batched-episode radio mode: batched-vs-sequential parity + masked
resets + checkpoint round-trip.

The batched envs (envs/calib.BatchedCalibEnv, envs/demixing.
BatchedDemixingEnv) advance B lanes as ONE vmapped/lane-sharded program
(RadioBackend.calibrate_batched / influence_images_batched); lane i must
reproduce the sequential env with seed ``seed + i`` — the parity oracle
every prior rewrite kept.  Tolerances are float-round-off class: the
batched chain reassociates reductions (vmap fusion, the factored imager)
but computes the same math.
"""

import numpy as np
import pytest

from smartcal_tpu.envs import (BatchedCalibEnv, BatchedDemixingEnv,
                               CalibEnv, DemixingEnv)
from smartcal_tpu.envs.radio import RadioBackend

SEED = 11
M = 3


def tiny_backend(**kw):
    args = dict(n_stations=6, n_freqs=2, n_times=4, tdelta=2,
                admm_iters=2, lbfgs_iters=3, init_iters=5, npix=32)
    args.update(kw)
    return RadioBackend(**args)


def _actions(E, n):
    return np.linspace(-0.5, 0.5, E * n).reshape(E, n).astype(np.float32)


@pytest.fixture(scope="module", params=[1, 4])
def calib_rollout(request):
    """One reset + one step of a batched env and its sequential twins."""
    E = request.param
    benv = BatchedCalibEnv(M=M, n_envs=E, backend=tiny_backend(),
                           seed=SEED)
    bobs = benv.reset()
    acts = _actions(E, 2 * M)
    bobs2, brew, bdone, binfo = benv.step(acts)

    seq = []
    for i in range(E):
        env = CalibEnv(M=M, backend=tiny_backend(), seed=SEED + i)
        o = env.reset()
        sky_reset = env.sky.copy()
        o2, r, d, info = env.step(acts[i])
        seq.append(dict(obs=o, sky_reset=sky_reset, obs2=o2, reward=r,
                        sigma_res=info["sigma_res"], K=env.K))
    return E, benv, bobs, bobs2, brew, binfo, seq


class TestBatchedCalibParity:
    def test_reset_observation_matches_oracle(self, calib_rollout):
        E, benv, bobs, _, _, _, seq = calib_rollout
        assert bobs["img"].shape == (E, 32, 32)
        assert bobs["sky"].shape == (E, M + 1, 7)
        for i in range(E):
            assert benv.K[i] == seq[i]["K"]
            np.testing.assert_allclose(bobs["sky"][i],
                                       seq[i]["sky_reset"] * 1e-3,
                                       rtol=1e-5, atol=1e-7)
            np.testing.assert_allclose(bobs["img"][i], seq[i]["obs"]["img"],
                                       rtol=2e-3, atol=2e-5)

    def test_step_reward_and_sigma_match_oracle(self, calib_rollout):
        E, _, _, bobs2, brew, binfo, seq = calib_rollout
        for i in range(E):
            np.testing.assert_allclose(brew[i], seq[i]["reward"],
                                       rtol=2e-3, atol=1e-4)
            np.testing.assert_allclose(binfo["sigma_res"][i],
                                       seq[i]["sigma_res"], rtol=1e-3)
            np.testing.assert_allclose(bobs2["sky"][i], seq[i]["obs2"]["sky"],
                                       rtol=1e-5, atol=1e-7)
            np.testing.assert_allclose(bobs2["img"][i],
                                       seq[i]["obs2"]["img"],
                                       rtol=2e-3, atol=2e-5)

    def test_fused_matches_sequential_oracle_route(self, calib_rollout):
        """fused=False (the retained sequential parity-oracle route)
        agrees with the batched program on the same lanes."""
        E, benv, bobs, _, _, _, _ = calib_rollout
        oenv = BatchedCalibEnv(M=M, n_envs=E, backend=tiny_backend(),
                               seed=SEED, fused=False)
        oobs = oenv.reset()
        np.testing.assert_allclose(bobs["img"], oobs["img"], rtol=2e-3,
                                   atol=2e-5)
        np.testing.assert_allclose(bobs["sky"], oobs["sky"], rtol=1e-5,
                                   atol=1e-7)


def test_batched_demix_parity():
    E, K = 2, 3
    benv = BatchedDemixingEnv(K=K, n_envs=E,
                              backend=tiny_backend(admm_iters=6),
                              seed=SEED, provide_influence=True)
    bobs = benv.reset()
    acts = np.zeros((E, K), np.float32)
    acts[:, 0] = 0.9             # select outlier 0
    acts[:, -1] = -1.0           # maxiter -> LOW_ITER
    bobs2, brew, _, binfo = benv.step(acts)
    assert np.all(benv.maxiter == 5)
    for i in range(E):
        env = DemixingEnv(K=K, backend=tiny_backend(admm_iters=6),
                          seed=SEED + i, provide_influence=True)
        o = env.reset()
        np.testing.assert_allclose(bobs["metadata"][i], o["metadata"],
                                   rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(bobs["infmap"][i], o["infmap"],
                                   rtol=2e-3, atol=2e-5)
        o2, r, d, info = env.step(acts[i])
        np.testing.assert_allclose(bobs2["metadata"][i], o2["metadata"],
                                   rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(brew[i], r, rtol=2e-3, atol=1e-4)
        np.testing.assert_allclose(binfo["sigma_res"][i],
                                   info["sigma_res"], rtol=1e-3)


def test_masked_reset_boundary():
    """Per-lane episode boundary: resetting a lane subset replaces only
    those lanes (donated splice, no recompile), live lanes keep their
    observation, and the reset lane lands exactly where a sequential env
    at the same key-chain position would."""
    E, K = 3, 3
    benv = BatchedDemixingEnv(K=K, n_envs=E,
                              backend=tiny_backend(admm_iters=6),
                              seed=SEED, provide_influence=True)
    benv.reset()
    acts = np.zeros((E, K), np.float32)
    acts[:, -1] = -1.0
    bobs, _, _, _ = benv.step(acts)
    prev = {k: v.copy() for k, v in bobs.items()}
    prev_episode = benv.lane_episode.copy()

    done = np.array([False, True, False])
    bobs3 = benv.reset_lanes(done)
    # live lanes: untouched observation + counters
    for lane in (0, 2):
        np.testing.assert_array_equal(bobs3["metadata"][lane],
                                      prev["metadata"][lane])
        np.testing.assert_array_equal(bobs3["infmap"][lane],
                                      prev["infmap"][lane])
    np.testing.assert_array_equal(benv.lane_episode,
                                  prev_episode + done)
    assert benv.lane_step[1] == 0 and benv.lane_step[0] == 1
    # reset lane: matches the sequential env's SECOND episode
    env = DemixingEnv(K=K, backend=tiny_backend(admm_iters=6),
                      seed=SEED + 1, provide_influence=True)
    env.reset()
    o = env.reset()
    np.testing.assert_allclose(bobs3["metadata"][1], o["metadata"],
                               rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(bobs3["infmap"][1], o["infmap"],
                               rtol=2e-3, atol=2e-5)


def test_batched_sharded_route_matches_vmap():
    """shard=True forces the lane-sharded (shard_map) batched solve on
    the virtual mesh; results must match the plain vmapped route."""
    E = 2
    b_sh = BatchedCalibEnv(M=M, n_envs=E, backend=tiny_backend(shard=True),
                           seed=7)
    b_vm = BatchedCalibEnv(M=M, n_envs=E,
                           backend=tiny_backend(shard=False), seed=7)
    o_sh, o_vm = b_sh.reset(), b_vm.reset()
    np.testing.assert_allclose(o_sh["img"], o_vm["img"], rtol=2e-3,
                               atol=2e-5)
    np.testing.assert_allclose(b_sh._sigma_data_img, b_vm._sigma_data_img,
                               rtol=1e-3)


def test_env_state_roundtrip():
    """state_dict/load_state_dict round-trips the per-lane key array and
    counters bit-exactly (the runtime --resume payload form)."""
    E = 2
    env = BatchedCalibEnv(M=M, n_envs=E, backend=tiny_backend(), seed=5)
    env.reset()
    state = env.state_dict()
    keys_before = [np.asarray(k).copy() for k in env._keys]

    env2 = BatchedCalibEnv(M=M, n_envs=E, backend=tiny_backend(), seed=99)
    env2.load_state_dict(state)
    for a, b in zip(keys_before, env2._keys):
        np.testing.assert_array_equal(a, np.asarray(b))
    np.testing.assert_array_equal(env.lane_episode, env2.lane_episode)
    np.testing.assert_array_equal(env.lane_step, env2.lane_step)
    # a lane-count mismatch must refuse, not silently truncate
    env3 = BatchedCalibEnv(M=M, n_envs=3, backend=tiny_backend(), seed=0)
    with pytest.raises(AssertionError):
        env3.load_state_dict(state)


def test_batched_kill_resume_bit_parity(tmp_path, monkeypatch):
    """train-2N ≙ train-N / kill / resume-N at B=2: the batched driver's
    scores are bit-identical whether the run was interrupted or not
    (same-seed guarantee under --resume with the per-lane key array in
    the checkpoint payload)."""
    from smartcal_tpu.train import calib_sac

    monkeypatch.chdir(tmp_path)
    common = ["--small", "--steps", "2", "--batch-envs", "2", "--seed",
              "3", "--M", "3", "--quiet"]
    full = calib_sac.main(["--episodes", "4", "--prefix", "a"] + common)
    calib_sac.main(["--episodes", "2", "--prefix", "b", "--ckpt-every",
                    "1", "--ckpt-dir", "b_ck"] + common)
    resumed = calib_sac.main(["--episodes", "4", "--prefix", "b",
                              "--ckpt-every", "1", "--ckpt-dir", "b_ck",
                              "--resume"] + common)
    assert len(full) == 4          # 2 vector episodes x 2 lanes
    np.testing.assert_array_equal(np.asarray(full), np.asarray(resumed))
