"""Tests for TD3 (warmup, delayed actor, hint-ADMM, PER) and DDPG (OU noise)."""

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from smartcal_tpu.rl import ddpg, td3
from smartcal_tpu.rl import replay as rp


def _spec(obs_dim=6, n_actions=2):
    return rp.transition_spec(obs_dim, n_actions)


def _fill(buf, n, obs_dim=6, hint_val=0.0, seed=0):
    rng = np.random.default_rng(seed)
    for i in range(n):
        tr = {"state": rng.normal(size=obs_dim).astype(np.float32),
              "new_state": rng.normal(size=obs_dim).astype(np.float32),
              "action": rng.uniform(-1, 1, 2).astype(np.float32),
              "reward": np.float32(rng.normal()),
              "done": False,
              "hint": np.full(2, hint_val, np.float32)}
        buf = rp.replay_add(buf, tr, priority=jnp.asarray(1.0))
    return buf


def test_td3_warmup_then_actor():
    cfg = td3.TD3Config(obs_dim=6, n_actions=2, warmup=3, noise=0.1)
    st = td3.td3_init(jax.random.PRNGKey(0), cfg)
    obs = jnp.ones(6)
    # during warmup actions are pure noise; after, actor mean + noise
    a1, st = td3.choose_action(cfg, st, obs, jax.random.PRNGKey(1))
    assert int(st.time_step) == 1
    assert np.all(np.abs(np.asarray(a1)) <= 1.0)
    for i in range(5):
        a, st = td3.choose_action(cfg, st, obs, jax.random.PRNGKey(2 + i))
    assert int(st.time_step) == 6
    # post warmup, deterministic part repeats for the same obs: variance of
    # actions across keys should be the noise scale, not the warmup scale
    assert np.all(np.abs(np.asarray(a)) <= 1.0)


def test_td3_learn_and_delayed_actor():
    cfg = td3.TD3Config(obs_dim=6, n_actions=2, batch_size=4, mem_size=16,
                        update_actor_interval=2)
    st = td3.td3_init(jax.random.PRNGKey(0), cfg)
    buf = rp.replay_init(cfg.mem_size, _spec())
    buf = _fill(buf, 8)

    flat = lambda p: jax.flatten_util.ravel_pytree(p)[0]
    a0 = flat(st.actor_params)
    st1, buf, _ = td3.learn(cfg, st, buf, jax.random.PRNGKey(1))
    # counter=1: critics updated, actor NOT (interval 2)
    assert int(st1.learn_counter) == 1
    np.testing.assert_allclose(np.asarray(flat(st1.actor_params)),
                               np.asarray(a0))
    assert float(jnp.linalg.norm(flat(st1.c1_params) - flat(st.c1_params))) > 0
    st2, buf, _ = td3.learn(cfg, st1, buf, jax.random.PRNGKey(2))
    # counter=2: actor updates now
    assert float(jnp.linalg.norm(flat(st2.actor_params) - a0)) > 0


@pytest.mark.slow
def test_td3_hint_admm_pulls_towards_hint():
    """With a strong hint constraint the ADMM inner loop should move the
    actor towards the hint more than the unconstrained update does."""
    cfg_h = td3.TD3Config(obs_dim=6, n_actions=2, batch_size=8, mem_size=32,
                          update_actor_interval=1, use_hint=True,
                          admm_rho=100.0, n_admm=5, lr_a=1e-2)
    cfg_n = td3.TD3Config(obs_dim=6, n_actions=2, batch_size=8, mem_size=32,
                          update_actor_interval=1, use_hint=False, lr_a=1e-2)
    st = td3.td3_init(jax.random.PRNGKey(0), cfg_h)
    buf = rp.replay_init(32, _spec())
    buf = _fill(buf, 16, hint_val=0.8)

    actor = td3.MLPDeterministicActor(2)
    obs = jnp.asarray(np.random.default_rng(3).normal(size=(8, 6)),
                      jnp.float32)

    d_init = float(jnp.mean(
        (actor.apply({"params": st.actor_params}, obs) - 0.8) ** 2))
    st_h, st_n = st, st
    for i in range(10):
        st_h, _, _ = td3.learn(cfg_h, st_h, buf, jax.random.PRNGKey(10 + i))
        st_n, _, _ = td3.learn(cfg_n, st_n, buf, jax.random.PRNGKey(10 + i))
    ah = actor.apply({"params": st_h.actor_params}, obs)
    an = actor.apply({"params": st_n.actor_params}, obs)
    d_h = float(jnp.mean((ah - 0.8) ** 2))
    d_n = float(jnp.mean((an - 0.8) ** 2))
    assert d_h < d_init, (d_h, d_init)
    assert d_h < d_n, (d_h, d_n)
    assert d_h < 0.1


def test_td3_per_priority_refresh():
    cfg = td3.TD3Config(obs_dim=6, n_actions=2, batch_size=4, mem_size=16,
                        prioritized=True)
    st = td3.td3_init(jax.random.PRNGKey(0), cfg)
    buf = rp.replay_init(cfg.mem_size, _spec())
    buf = _fill(buf, 8)
    st1, buf1, _ = td3.learn(cfg, st, buf, jax.random.PRNGKey(5))
    assert np.sum(np.asarray(buf1.priority) != np.asarray(buf.priority)) >= 1


def test_td3_store_priority_from_reward():
    cfg = td3.TD3Config(obs_dim=6, n_actions=2, prioritized=True)
    p = td3.store_priority(cfg, jnp.asarray(2.0))
    want = (2.0 + rp.PER_EPSILON) ** rp.PER_ALPHA
    np.testing.assert_allclose(float(p), want, rtol=1e-5)
    assert td3.store_priority(
        td3.TD3Config(obs_dim=6, n_actions=2, prioritized=False),
        jnp.asarray(2.0)) is None


def test_ou_noise_autocorrelation():
    cfg = ddpg.DDPGConfig(obs_dim=6, n_actions=2)
    st = ddpg.ou_init(2)
    xs = []
    key = jax.random.PRNGKey(0)
    for i in range(200):
        key, k = jax.random.split(key)
        x, st = ddpg.ou_sample(cfg, st, k)
        xs.append(np.asarray(x))
    xs = np.stack(xs)
    # OU process: successive samples are strongly correlated (mean-reverting
    # random walk), unlike white noise
    c = np.corrcoef(xs[:-1, 0], xs[1:, 0])[0, 1]
    assert c > 0.9


def test_ddpg_learn_updates():
    cfg = ddpg.DDPGConfig(obs_dim=6, n_actions=2, batch_size=4, mem_size=16)
    st = ddpg.ddpg_init(jax.random.PRNGKey(0), cfg)
    buf = rp.replay_init(cfg.mem_size, _spec())
    buf = _fill(buf, 8)
    flat = lambda p: jax.flatten_util.ravel_pytree(p)[0]
    st1, _, m = ddpg.learn(cfg, st, buf, jax.random.PRNGKey(1))
    assert float(jnp.linalg.norm(flat(st1.actor_params)
                                 - flat(st.actor_params))) > 0
    assert float(jnp.linalg.norm(flat(st1.critic_params)
                                 - flat(st.critic_params))) > 0
    assert np.isfinite(float(m["critic_loss"]))
    # target nets interpolated by tau
    t1 = flat(st1.t_critic_params)
    want = cfg.tau * flat(st1.critic_params) + (1 - cfg.tau) * flat(
        st.t_critic_params)
    np.testing.assert_allclose(np.asarray(t1), np.asarray(want), rtol=1e-4,
                               atol=1e-6)


def test_ddpg_agent_wrapper():
    cfg = ddpg.DDPGConfig(obs_dim=6, n_actions=2, batch_size=4, mem_size=16)
    agent = ddpg.DDPGAgent(cfg, seed=0)
    obs = np.ones(6, np.float32)
    a = agent.choose_action(obs)
    assert a.shape == (2,)
    for _ in range(6):
        agent.store_transition(obs, a, 0.1, obs, False)
    agent.learn()
