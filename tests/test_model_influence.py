"""Model-influence pipelines (VERDICT r1 item 5): influence OF the trained
aux models, eval_model.py / influence_tsk.py parity."""

import os

import jax
import numpy as np

from smartcal_tpu.models.transformer import TransformerEncoder, XYBuffer
from smartcal_tpu.models.tsk import train_tsk
from smartcal_tpu.train import supervised
from smartcal_tpu.train.model_influence import (transformer_influence,
                                                tsk_influence)

K = 3
NPIX = 4
NOUT = NPIX * NPIX + 8


def _buffer(rng, n=12):
    buf = XYBuffer(n, (K * NOUT,), (K - 1,))
    for _ in range(n):
        buf.store(rng.standard_normal(K * NOUT).astype(np.float32),
                  (rng.random(K - 1) > 0.5).astype(np.float32))
    return buf


def test_transformer_influence(tmp_path):
    rng = np.random.default_rng(0)
    buf = _buffer(rng)
    params, hist = supervised.train_transformer(buf, K=K, model_dim=6,
                                                epochs=30, batch_size=4)
    model = hist["model"]
    If, maps = transformer_influence(params, model, buf, K=K, npix=NPIX,
                                     warmup_epochs=5,
                                     outdir=str(tmp_path))
    assert If.shape == (K - 1, K * NOUT)
    assert np.all(np.isfinite(If))
    assert not np.allclose(If, 0.0)
    # per-(class, direction) maps unpack the row blocks exactly
    assert maps[(0, 0)].shape == (NPIX, NPIX)
    np.testing.assert_array_equal(maps[(0, 1)].ravel(),
                                  If[0, NOUT:NOUT + NPIX * NPIX])
    np.testing.assert_array_equal(maps[("meta", 0, 0)],
                                  If[0, NPIX * NPIX:NOUT])
    assert os.path.exists(tmp_path / "transformer_influence.npz")


def test_tsk_influence():
    rng = np.random.default_rng(1)
    M = 3 * K + 2
    X = rng.standard_normal((30, M)).astype(np.float32)
    y = np.tanh(X[:, :K - 1] + 0.1 * rng.standard_normal((30, K - 1))
                ).astype(np.float32)
    params = train_tsk(jax.random.PRNGKey(0), X, y, n_iter=50)["params"]
    If = tsk_influence(params, X, y, n_avg=5, taylor_iters=5)
    assert If.shape == (K - 1, M)
    assert np.all(np.isfinite(If))
    assert not np.allclose(If, 0.0)
