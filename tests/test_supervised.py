"""Supervised actor-thread fleets (runtime.supervisor + the parallel
learners' supervised mode): heartbeat supervision, restart-with-backoff
on injected actor kills, learning from the surviving fleet, and the
clean join on a watchdog trip."""

import json
import threading
import time

import numpy as np
import pytest

from smartcal_tpu.runtime import (BackoffPolicy, FaultPlan, Fleet,
                                  clear_faults, install_faults)

ENV_KW = {"M": 5, "N": 5}
AGENT_KW = {"batch_size": 8, "mem_size": 64}


@pytest.fixture(autouse=True)
def in_tmp(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    yield
    clear_faults()


# ---------------------------------------------------------------------------
# Fleet unit behavior (no jax, cheap work functions)
# ---------------------------------------------------------------------------

def _fast_backoff():
    return BackoffPolicy(base_s=0.01, factor=2.0, max_s=0.05, jitter=0.0)


def test_fleet_collects_and_versions_weights():
    def work(actor_id, iteration, weights):
        return {"actor": actor_id, "iteration": iteration, "w": weights}

    fleet = Fleet(2, work, heartbeat_timeout=5.0, backoff=_fast_backoff())
    fleet.start("w0")
    try:
        got = fleet.collect(max_items=4, timeout=5.0)
        assert got and all(item[3]["w"] == "w0" for item in got)
        v = fleet.set_weights("w1")
        assert v > fleet.n_actors - 1
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            items = fleet.collect(max_items=8, timeout=1.0)
            if any(item[3]["w"] == "w1" for item in items):
                break
        else:
            pytest.fail("actors never picked up the new weights")
    finally:
        fleet.stop(join=True)
    assert fleet.alive_count == 0


def test_fleet_restarts_dead_actor_and_skips_poison_iteration():
    seen = []

    def work(actor_id, iteration, weights):
        seen.append((actor_id, iteration))
        if actor_id == 0 and iteration == 1:
            raise RuntimeError("boom")
        time.sleep(0.01)
        return iteration

    fleet = Fleet(1, work, heartbeat_timeout=5.0, max_restarts=2,
                  backoff=_fast_backoff())
    fleet.start(None)
    try:
        deadline = time.monotonic() + 10.0
        restarted = False
        while time.monotonic() < deadline and not restarted:
            fleet.poll()
            restarted = fleet.restarts_total() >= 1 and fleet.alive_count
            time.sleep(0.01)
        assert restarted, "supervisor never restarted the dead actor"
        # the replacement resumed AFTER the poison-pill iteration
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if any(it == 2 for (_, it) in seen):
                break
            time.sleep(0.01)
        assert (0, 2) in seen
        assert seen.count((0, 1)) == 1      # poisoned iteration not retried
    finally:
        fleet.stop(join=True)


def test_fleet_abandons_slot_after_max_restarts():
    def work(actor_id, iteration, weights):
        raise RuntimeError("always dies")

    fleet = Fleet(1, work, heartbeat_timeout=5.0, max_restarts=2,
                  backoff=_fast_backoff())
    fleet.start(None)
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not fleet.failed_slots:
            fleet.poll()
            time.sleep(0.01)
        assert fleet.failed_slots == {0}
        assert fleet.restarts_total() == 2
    finally:
        fleet.stop(join=True)


def test_fleet_detects_hung_actor():
    release = threading.Event()

    def work(actor_id, iteration, weights):
        if iteration == 0:
            release.wait(timeout=30.0)       # simulate a wedged rollout
        return iteration

    fleet = Fleet(1, work, heartbeat_timeout=0.2, max_restarts=1,
                  backoff=_fast_backoff())
    fleet.start(None)
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and fleet.restarts_total() < 1:
            fleet.poll()
            time.sleep(0.05)
        assert fleet.restarts_total() == 1   # replacement spawned
        got = fleet.collect(max_items=1, timeout=5.0)
        assert got and got[0][1] == 1        # replacement works from iter 1
    finally:
        release.set()
        fleet.stop(join=True)


# ---------------------------------------------------------------------------
# the enet supervised learner end-to-end (jitted rollouts, real SAC learn)
# ---------------------------------------------------------------------------

def test_train_supervised_actor_kill_restart(tmp_path):
    """Injected actor kill: the run completes every episode, the
    supervisor logs actor_down/actor_restart, and learning continued
    from the surviving fleet meanwhile."""
    from smartcal_tpu.parallel import learner

    install_faults(FaultPlan(kill_actor=1, kill_at=1))
    run = str(tmp_path / "sup.jsonl")
    (st, buf), scores, summary = learner.train_supervised(
        seed=0, episodes=5, n_actors=2, env_kwargs=ENV_KW,
        agent_kwargs=AGENT_KW, rollout_epochs=1, rollout_steps=4,
        quiet=True, queue_timeout=30.0, metrics=run,
        restart_backoff=_fast_backoff())
    clear_faults()
    assert len(scores) == 5
    assert np.all(np.isfinite(scores))
    assert summary["restarts"] == 1 and not summary["failed_slots"]
    assert summary["alive_at_exit"] == 0          # stop() joined the fleet
    assert int(buf.cntr) > 0
    events = [json.loads(ln) for ln in open(run) if ln.strip()]
    kinds = [e["event"] for e in events]
    for want in ("fault_injected", "actor_down", "actor_restart",
                 "actors_stopped"):
        assert want in kinds, (want, sorted(set(kinds)))
    down = [e for e in events if e["event"] == "actor_down"][0]
    assert down["actor"] == 1 and "FaultInjected" in down["reason"]
    restart = [e for e in events if e["event"] == "actor_restart"][0]
    assert restart["iteration"] == 2              # poison iteration skipped


def test_train_supervised_trip_joins_actors(tmp_path):
    """Watchdog trip in the supervised learner stops AND joins the actor
    threads (no actor left running against a dead learner)."""
    from smartcal_tpu.parallel import learner

    # critic_loss NaN at learner update 2 -> watchdog trips mid-run
    install_faults(FaultPlan(nan_field="critic_loss", nan_step=2))
    run = str(tmp_path / "trip.jsonl")
    (st, buf), scores, summary = learner.train_supervised(
        seed=0, episodes=8, n_actors=2, env_kwargs=ENV_KW,
        agent_kwargs=AGENT_KW, rollout_epochs=1, rollout_steps=4,
        quiet=True, queue_timeout=30.0, metrics=run, watchdog=True)
    clear_faults()
    assert len(scores) < 8                        # halted early
    assert summary["alive_at_exit"] == 0          # every thread joined
    events = [json.loads(ln) for ln in open(run) if ln.strip()]
    kinds = [e["event"] for e in events]
    assert "watchdog_trip" in kinds
    assert "actors_stopped" in kinds
    stop_evs = [e for e in events if e["event"] == "actors_stopped"]
    assert stop_evs[0]["joined"] == stop_evs[0]["total"]
