"""Unified 2-D/3-D mesh composition (ISSUE 17 tentpole).

Three layers:

* registry/composition units — canonical axis order, size-1 axis
  retention, the named :class:`MeshFactorizationError` with its
  nearest-valid-factorization hint (satellite 1);
* composed batched-route parity — the lane x baseline ``shard_map``
  influence/solve programs against the lane-only, baseline-only and
  unsharded-vmap oracles on the virtual 8-device mesh, including the
  masked ``splice_episode`` reset and the steady-state transfer-guard
  proof (no host round-trip once placed);
* the replay axis as a submesh ALONGSIDE the episode axes — one
  composed mesh serves the learner's replay shards and the batched
  episode program without resharding.

Tolerance classes match the neighbouring suites: shard_map psums
reassociate f32 reductions (test_sharded_cal documents ~2e-3 worst
case through the ADMM iterations), images compare at the batched-radio
round-off class.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from smartcal_tpu.envs.radio import RadioBackend
from smartcal_tpu.parallel.mesh import (AXIS_BASELINE, AXIS_CHUNK,
                                        AXIS_DATA, AXIS_FREQ, AXIS_LANE,
                                        AXIS_REPLAY, MESH_AXES,
                                        MeshFactorizationError,
                                        check_axis_divides, compose_mesh,
                                        largest_divisor, make_mesh,
                                        nearest_factorization)
from smartcal_tpu.rl import replay_sharded as rps

K = 3
E = 2


def _rel(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-12)


# ---------------------------------------------------------------------------
# registry + composition units
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_canonical_order_and_frozen_values(self):
        """The string values are checkpoint/serving ABI — frozen."""
        assert MESH_AXES == (AXIS_REPLAY, AXIS_DATA, AXIS_LANE,
                             AXIS_FREQ, AXIS_CHUNK, AXIS_BASELINE)
        assert (AXIS_REPLAY, AXIS_DATA, AXIS_LANE, AXIS_FREQ,
                AXIS_CHUNK, AXIS_BASELINE) == \
            ("rp", "dp", "lane", "fp", "sp", "bp")

    def test_compose_mesh_canonical_order_any_dict_order(self):
        m1 = compose_mesh({AXIS_BASELINE: 4, AXIS_LANE: 2})
        m2 = compose_mesh({AXIS_LANE: 2, AXIS_BASELINE: 4})
        assert m1.axis_names == (AXIS_LANE, AXIS_BASELINE)
        assert m1.axis_names == m2.axis_names
        assert m1.shape == m2.shape == {AXIS_LANE: 2, AXIS_BASELINE: 4}

    def test_compose_mesh_keeps_size1_axes(self):
        """A P(axis) spec on a size-1 axis is a no-op — keeping the axis
        lets ONE program serve every arm of the route matrix."""
        m = compose_mesh({AXIS_LANE: 1, AXIS_BASELINE: 4})
        assert m.axis_names == (AXIS_LANE, AXIS_BASELINE)
        assert m.shape[AXIS_LANE] == 1

    def test_compose_mesh_rejects_unknown_axis(self):
        with pytest.raises(MeshFactorizationError, match="registry"):
            compose_mesh({"zz": 2})

    def test_make_mesh_error_names_nearest_factorization(self):
        with pytest.raises(MeshFactorizationError,
                           match="nearest valid factorization"):
            make_mesh((4, 4), (AXIS_LANE, AXIS_BASELINE))  # 16 > 8

    def test_largest_divisor(self):
        assert largest_divisor(6, 4) == 3       # NOT gcd (gcd gives 2)
        assert largest_divisor(32640, 8) == 8
        assert largest_divisor(7, 4) == 1

    def test_nearest_factorization_divides_and_fits(self):
        out = nearest_factorization({AXIS_LANE: 6, AXIS_BASELINE: 4}, 8)
        assert out == {AXIS_LANE: 6, AXIS_BASELINE: 1}
        assert 6 % out[AXIS_LANE] == 0 and 4 % out[AXIS_BASELINE] == 0

    def test_check_axis_divides_hint(self):
        with pytest.raises(MeshFactorizationError,
                           match="nearest valid size is 3"):
            check_axis_divides(15, 4, axis=AXIS_BASELINE, what="test")
        check_axis_divides(15, 3, axis=AXIS_BASELINE, what="test")


# ---------------------------------------------------------------------------
# composed batched routes vs the single-axis / unsharded oracles
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def batched():
    backend = RadioBackend(n_stations=6, n_freqs=2, n_times=4, tdelta=2,
                           admm_iters=2, lbfgs_iters=2, init_iters=3,
                           npix=16)
    eps, rhos = [], []
    for i in range(E):
        ep, mdl = backend.new_demixing_episode(jax.random.PRNGKey(20 + i),
                                               K)
        eps.append(ep)
        rhos.append(np.asarray(mdl.rho))
    bep = backend.stack_episodes(eps)
    rho = np.stack(rhos).astype(np.float32)
    alpha = np.zeros_like(rho)
    res = backend.calibrate_batched(bep, rho, compose=(0, 0))
    img = backend.influence_images_batched(bep, res, rho, alpha,
                                           compose=(0, 0))
    return backend, eps, bep, rho, alpha, res, img


class TestComposedParity:
    def test_lane_by_baseline_solve_matches_vmap(self, batched):
        backend, _, bep, rho, _, res, _ = batched
        out = backend.calibrate_batched(bep, rho, compose=(E, 3))
        np.testing.assert_allclose(np.asarray(out.J), np.asarray(res.J),
                                   rtol=5e-3, atol=5e-4)
        assert _rel(out.residual, res.residual) < 1e-3
        np.testing.assert_allclose(np.asarray(out.sigma_res),
                                   np.asarray(res.sigma_res), rtol=5e-3)

    @pytest.mark.parametrize("compose", [(E, 3), (0, 3), (E, 0)],
                             ids=["lane_x_baseline", "baseline_only",
                                  "lane_only"])
    def test_influence_arms_match_vmap(self, batched, compose):
        """B=15 shards 3-way on the baseline axis; every composed arm
        reproduces the unsharded vmap images (collectives confined to
        the baseline axis cannot leak across lanes)."""
        backend, _, bep, rho, alpha, res, img = batched
        out = backend.influence_images_batched(bep, res, rho, alpha,
                                               compose=compose)
        assert np.asarray(out).shape == np.asarray(img).shape
        np.testing.assert_allclose(np.asarray(out), np.asarray(img),
                                   rtol=2e-3, atol=2e-5)

    def test_masked_splice_on_composed_route(self, batched):
        """splice_episode (the batched envs' masked reset) feeds the
        composed route: lane 1 replaced by a fresh episode, the
        composed solve+influence match the vmap oracles on the spliced
        batch and lane 0 is untouched."""
        backend, eps, _, rho, alpha, _, _ = batched
        # splice donates its input (in-place lane swap), so build a
        # private stack rather than consuming the shared fixture
        bep_local = backend.stack_episodes(eps)
        v0 = np.asarray(bep_local.V[0])
        ep_new, mdl_new = backend.new_demixing_episode(
            jax.random.PRNGKey(99), K)
        bep2 = backend.splice_episode(bep_local, 1, ep_new)
        rho2 = rho.copy()
        rho2[1] = np.asarray(mdl_new.rho, np.float32)
        res_v = backend.calibrate_batched(bep2, rho2, compose=(0, 0))
        res_c = backend.calibrate_batched(bep2, rho2, compose=(E, 3))
        np.testing.assert_allclose(np.asarray(res_c.J),
                                   np.asarray(res_v.J),
                                   rtol=5e-3, atol=5e-4)
        img_v = backend.influence_images_batched(bep2, res_v, rho2, alpha,
                                                 compose=(0, 0))
        img_c = backend.influence_images_batched(bep2, res_v, rho2, alpha,
                                                 compose=(E, 3))
        np.testing.assert_allclose(np.asarray(img_c), np.asarray(img_v),
                                   rtol=2e-3, atol=2e-5)
        # lane 0 of the spliced batch is bit-identical input data
        np.testing.assert_array_equal(np.asarray(bep2.V[0]), v0)

    def test_composed_route_transfer_guard_steady_state(self, batched):
        """Once compiled and placed, the composed lane x baseline
        program runs with NO implicit host transfer (PR 12/13 guard
        pattern): first call warms the cache, the guarded call is the
        steady-state proof."""
        backend, _, bep, rho, alpha, res, _ = batched
        # host-side numpy episode fields -> device arrays up front; the
        # guarded call must then stay on-device end to end
        bep_dev = bep._replace(
            freqs=jnp.asarray(bep.freqs),
            f0=jnp.asarray(bep.f0, jnp.float32),
            uvw=jnp.asarray(bep.uvw),
            cell=jnp.asarray(bep.cell, jnp.float32))
        rho_d = jnp.asarray(rho)
        alpha_d = jnp.asarray(alpha)
        out1 = backend.influence_images_batched(bep_dev, res, rho_d,
                                                alpha_d, compose=(E, 3))
        jax.block_until_ready(out1)
        with jax.transfer_guard("disallow"):
            out2 = backend.influence_images_batched(bep_dev, res, rho_d,
                                                    alpha_d,
                                                    compose=(E, 3))
            jax.block_until_ready(out2)
        np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))

    def test_nondividing_baseline_axis_fails_with_hint(self, batched):
        """compose=(E, 4): B=15 does not divide 4-way — the named error
        with the nearest-valid suggestion, not an opaque XLA failure
        (satellite 1)."""
        backend, _, bep, rho, alpha, res, _ = batched
        with pytest.raises(MeshFactorizationError, match="nearest valid"):
            backend.influence_images_batched(bep, res, rho, alpha,
                                             compose=(E, 4))


# ---------------------------------------------------------------------------
# SKA-size composed parity (small tier: minimal depth, full N=256 shapes)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_lane_by_baseline_parity_n256():
    """THE acceptance arm (ISSUE 17): N=256 stations (B=32640, the
    blocked-Hessian tier engages on its own threshold), E=2 lanes x 4
    baseline shards on the virtual mesh, vs the unsharded vmap oracle.
    Depth is minimal (1 band, 1 chunk, 1 ADMM sweep) — the SHAPES are
    the point.

    slow-tier (~90 s of compile on the 1-core CI container — the tier-1
    wall budget can't absorb it): run with ``-m slow`` or by node id.
    The composed PROGRAM is identical at every scale, and the small-N
    arms above gate it in tier-1; this arm adds the SKA shapes."""
    kd = 2
    backend = RadioBackend(n_stations=256, n_freqs=1, n_times=2,
                           tdelta=2, admm_iters=1, lbfgs_iters=2,
                           init_iters=2, npix=16)
    eps, rhos = [], []
    for i in range(E):
        ep, mdl = backend.new_demixing_episode(jax.random.PRNGKey(40 + i),
                                               kd)
        eps.append(ep)
        rhos.append(np.asarray(mdl.rho))
    bep = backend.stack_episodes(eps)
    rho = np.stack(rhos).astype(np.float32)
    alpha = np.zeros_like(rho)
    res = backend.calibrate_batched(bep, rho, compose=(0, 0))
    img = backend.influence_images_batched(bep, res, rho, alpha,
                                           compose=(0, 0))
    res_c = backend.calibrate_batched(bep, rho, compose=(E, 4))
    np.testing.assert_allclose(np.asarray(res_c.J), np.asarray(res.J),
                               rtol=5e-3, atol=5e-4)
    img_c = backend.influence_images_batched(bep, res, rho, alpha,
                                             compose=(E, 4))
    assert np.asarray(img_c).shape == (E, 16, 16)
    np.testing.assert_allclose(np.asarray(img_c), np.asarray(img),
                               rtol=2e-3, atol=2e-5)


# ---------------------------------------------------------------------------
# replay axis as a submesh alongside the episode axes
# ---------------------------------------------------------------------------

SPEC = {"x": ((), jnp.float32)}


class TestReplaySubmesh:
    def test_place_on_composed_mesh(self):
        """The learner's replay shards live on the SAME composed mesh as
        the episode program: sharded over AXIS_REPLAY, replicated over
        the lane/baseline axes — no resharding between learn and act."""
        buf = rps.replay_init(32, SPEC, 4)
        mesh = compose_mesh({AXIS_REPLAY: 2, AXIS_LANE: 2,
                             AXIS_BASELINE: 2})
        placed = rps.place_on_mesh(buf, mesh)
        assert placed.priority.sharding.spec == P(AXIS_REPLAY)
        assert placed.data["x"].sharding.spec == P(AXIS_REPLAY)
        assert placed.cntr.sharding.spec == P()
        assert placed.priority.sharding.mesh.shape == {
            AXIS_REPLAY: 2, AXIS_LANE: 2, AXIS_BASELINE: 2}

    def test_explicit_mesh_without_replay_axis_raises(self):
        buf = rps.replay_init(32, SPEC, 4)
        mesh = compose_mesh({AXIS_LANE: 2, AXIS_BASELINE: 2})
        with pytest.raises(MeshFactorizationError, match=AXIS_REPLAY):
            rps.place_on_mesh(buf, mesh)

    def test_explicit_nondividing_mesh_raises_with_hint(self):
        buf = rps.replay_init(32, SPEC, 4)
        mesh = compose_mesh({AXIS_REPLAY: 3})
        with pytest.raises(MeshFactorizationError, match="nearest valid"):
            rps.place_on_mesh(buf, mesh)

    def test_default_mesh_takes_largest_divisor(self):
        """S=12 shards on 8 devices: the default mesh is the LARGEST
        divisor (6), not gcd (4) — place_on_mesh's documented
        contract."""
        buf = rps.replay_init(24, SPEC, 12)
        placed = rps.place_on_mesh(buf)
        mesh = placed.priority.sharding.mesh
        assert mesh.shape[AXIS_REPLAY] == 6
