"""Radio TD3/DDPG + fuzzy SAC driver smoke runs (VERDICT r1 item 6):
each new train/ entry point completes episodes end-to-end on the tiny
hermetic backend and writes its checkpoints."""

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def in_tmp(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)


def test_calib_td3_driver():
    from smartcal_tpu.train import calib_td3

    scores = calib_td3.main(["--episodes", "2", "--steps", "2", "--M", "4",
                             "--small", "--seed", "0"])
    assert len(scores) == 2
    assert np.all(np.isfinite(scores))
    import os

    assert os.path.exists("calib_td3_scores.pkl")


def test_calib_ddpg_driver():
    from smartcal_tpu.train import calib_ddpg

    scores = calib_ddpg.main(["--episodes", "2", "--steps", "2", "--M", "4",
                              "--small", "--seed", "0"])
    assert len(scores) == 2
    assert np.all(np.isfinite(scores))


def test_demix_td3_driver_hint_per():
    """VERDICT r2 item 3: the demixing TD3 path — CNN/metadata TD3 with PER
    and the adaptive-rho ADMM hint loop wired to DemixingEnv
    (reference demixing_rl/main_td3.py + demix_td3.py)."""
    import os

    from smartcal_tpu.train import demix_td3

    scores = demix_td3.main(
        ["--iteration", "2", "--steps", "2", "--K", "4", "--small",
         "--use_hint", "--warmup", "2", "--batch_size", "4",
         "--memory", "64", "--seed", "0"])
    assert len(scores) == 2
    assert np.all(np.isfinite(scores))
    assert os.path.exists("demix_td3td3_state.pkl")
    assert os.path.exists("demix_td3_scores.pkl")


def test_demix_td3_learn_fires_on_env_transitions():
    """The TD3 learn step actually updates the actor on demixing-env
    transitions (batch reachable, PER priorities refreshed)."""
    import jax
    import jax.numpy as jnp

    from smartcal_tpu.envs import DemixingEnv
    from smartcal_tpu.envs.radio import RadioBackend
    from smartcal_tpu.rl import td3

    backend = RadioBackend(n_stations=6, n_freqs=2, n_times=4, tdelta=2,
                           admm_iters=2, lbfgs_iters=2, init_iters=2,
                           npix=16)
    env = DemixingEnv(K=4, provide_hint=True, backend=backend, seed=0)
    cfg = td3.TD3Config(obs_dim=3 * 4 + 2, n_actions=4, batch_size=4,
                        mem_size=16, warmup=2, use_hint=True, admm_rho=0.1,
                        prioritized=True)
    agent = td3.TD3Agent(cfg, seed=0)
    obs = env.reset()
    flat = np.asarray(obs["metadata"], np.float32)
    for _ in range(5):
        a = np.asarray(agent.choose_action(flat)).squeeze()
        obs2, r, done, hint, info = env.step(a)
        flat2 = np.asarray(obs2["metadata"], np.float32)
        agent.store_transition(flat, a, r, flat2, done, hint)
        agent.learn()
        flat = flat2
    p0 = jax.flatten_util.ravel_pytree(
        td3.td3_init(jax.random.PRNGKey(0), cfg).actor_params)[0]
    p1 = jax.flatten_util.ravel_pytree(agent.state.actor_params)[0]
    assert int(agent.state.learn_counter) >= 1
    assert not np.allclose(np.asarray(p0), np.asarray(p1))
    assert np.all(np.isfinite(np.asarray(p1)))


def test_demix_fuzzy_sac_driver():
    from smartcal_tpu.train import demix_fuzzy_sac

    scores = demix_fuzzy_sac.main(
        ["--iteration", "2", "--steps", "2", "--K", "4", "--small",
         "--warmup", "1", "--batch_size", "4", "--memory", "64",
         "--seed", "0"])
    assert len(scores) == 2
    assert np.all(np.isfinite(scores))
