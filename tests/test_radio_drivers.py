"""Radio TD3/DDPG + fuzzy SAC driver smoke runs (VERDICT r1 item 6):
each new train/ entry point completes episodes end-to-end on the tiny
hermetic backend and writes its checkpoints."""

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def in_tmp(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)


def test_calib_td3_driver():
    from smartcal_tpu.train import calib_td3

    scores = calib_td3.main(["--episodes", "2", "--steps", "2", "--M", "4",
                             "--small", "--seed", "0"])
    assert len(scores) == 2
    assert np.all(np.isfinite(scores))
    import os

    assert os.path.exists("calib_td3_scores.pkl")


def test_calib_ddpg_driver():
    from smartcal_tpu.train import calib_ddpg

    scores = calib_ddpg.main(["--episodes", "2", "--steps", "2", "--M", "4",
                              "--small", "--seed", "0"])
    assert len(scores) == 2
    assert np.all(np.isfinite(scores))


def test_demix_fuzzy_sac_driver():
    from smartcal_tpu.train import demix_fuzzy_sac

    scores = demix_fuzzy_sac.main(
        ["--iteration", "2", "--steps", "2", "--K", "4", "--small",
         "--warmup", "1", "--batch_size", "4", "--memory", "64",
         "--seed", "0"])
    assert len(scores) == 2
    assert np.all(np.isfinite(scores))
