"""Unit tests for the sweep summarizer (tools/summarize_demix_curves.py).

This tool produces the paired statistics for BOTH round-4 headline
artifacts (results/calib_curves, results/demix_curves_r4), so its delta
logic — including truncation of a boundary-cut run to the common length —
must be right.  Pure numpy, no JAX.
"""

import json
import os

import numpy as np

from conftest import load_tool_module

summ = load_tool_module("summarize_demix_curves")


def test_moving_avg_window_and_short_input():
    x = np.arange(40, dtype=float)
    ma = summ.moving_avg(x, w=20)
    assert len(ma) == 21
    assert ma[0] == np.mean(x[:20])
    assert ma[-1] == np.mean(x[-20:])
    short = summ.moving_avg(np.asarray([1.0, 3.0]), w=20)
    assert len(short) == 1 and short[0] == 2.0


def test_load_runs_parses_episode_records(tmp_path):
    for tag, scores in (("hint_seed0", [1.0, 2.0]),
                        ("nohint_seed0", [0.5]),
                        ("hint_seed12", [3.0])):
        with open(tmp_path / f"{tag}.jsonl", "w") as fh:
            for s in scores:
                fh.write(json.dumps({"event": "episode", "score": s}) + "\n")
            fh.write(json.dumps({"event": "other", "score": 99}) + "\n")
    (tmp_path / "not_a_run.jsonl").write_text("{}\n")
    runs = summ.load_runs(str(tmp_path))
    assert set(runs) == {("hint", 0), ("nohint", 0), ("hint", 12)}
    np.testing.assert_allclose(runs[("hint", 0)], [1.0, 2.0])


def _mk_runs(deltas, n=100, base=0.0):
    """Paired runs where the hint arm's scores sit ``delta`` above the
    nohint arm throughout — every paired statistic equals delta."""
    runs = {}
    for s, d in enumerate(deltas):
        ramp = base + np.linspace(0.0, 1.0, n)
        runs[("nohint", s)] = ramp
        runs[("hint", s)] = ramp + d
    return runs


def test_summarize_paired_deltas_and_tests():
    runs = _mk_runs([0.1, 0.2, 0.3, 0.4, 0.5])
    per_run, agg, paired = summ.summarize(runs)
    assert len(per_run) == 10
    assert agg["hint"]["n_runs"] == 5
    assert paired["n_pairs"] == 5
    np.testing.assert_allclose(paired["auc_mean"]["deltas"],
                               [0.1, 0.2, 0.3, 0.4, 0.5], atol=1e-4)
    assert paired["auc_mean"]["n_positive"] == 5
    # 5/5 positive: exact sign test reaches its floor p = 2 * 0.5^5
    assert paired["auc_mean"]["sign_p"] <= 0.0625 + 1e-9
    np.testing.assert_allclose(paired["tail_median"]["median_delta"], 0.3,
                               atol=1e-4)


def test_summarize_truncates_boundary_cut_pairs():
    """A seed whose hint arm was cut at the round boundary must compare
    the COMMON window, not a 100-episode tail vs a 30-episode tail."""
    runs = _mk_runs([0.0])
    # hint arm truncated mid-learning; identical to nohint over the
    # common prefix -> every paired delta must be exactly 0
    runs[("hint", 0)] = runs[("hint", 0)][:30]
    _, _, paired = summ.summarize(runs)
    assert paired["auc_mean"]["deltas"] == [0.0]
    assert paired["tail_median"]["deltas"] == [0.0]


def test_summarize_no_pairs():
    runs = {("hint", 0): np.ones(10)}
    _, agg, paired = summ.summarize(runs)
    assert paired is None
    assert "nohint" not in agg
