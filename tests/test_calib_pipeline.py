"""Device-pipelined calibration episode path vs the host-loop originals.

The pipelined path (envs/radio.py) changes HOW the episode math runs —
vectorized O(1)-dispatch construction, donated ADMM segments, mesh-aware
sharded solve/influence, double-buffered episode overlap — but not WHAT
it computes: every test here pins a pipelined mode to the pre-pipeline
host-loop oracle that remains available as ``vectorized=False`` /
``shard=False``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from smartcal_tpu.cal import solver
from smartcal_tpu.envs import CalibEnv, DemixingEnv
from smartcal_tpu.envs.radio import RadioBackend


def tiny_backend(**kw):
    args = dict(n_stations=6, n_freqs=2, n_times=4, tdelta=2,
                admm_iters=2, lbfgs_iters=3, init_iters=5, npix=32)
    args.update(kw)
    return RadioBackend(**args)


def rel(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-30)


@pytest.fixture(scope="module")
def backends():
    return (tiny_backend(shard=False),                      # vectorized
            tiny_backend(vectorized=False, shard=False))    # host loop


class TestVectorizedEpisodeParity:
    """Same key -> the one-dispatch construction reproduces the
    per-frequency loop (Ccal bitwise; V to the device/host float32
    reduction-order round-off of the noise scale)."""

    def test_calib_episode(self, backends):
        vec, loop = backends
        key = jax.random.PRNGKey(11)
        ep_v, mdl_v = vec.new_calib_episode(key, 2, 3)
        ep_l, mdl_l = loop.new_calib_episode(key, 2, 3)
        np.testing.assert_array_equal(np.asarray(ep_v.Ccal),
                                      np.asarray(ep_l.Ccal))
        assert rel(ep_v.V, ep_l.V) < 1e-5
        np.testing.assert_array_equal(mdl_v.rho, mdl_l.rho)

    def test_calib_episode_diffuse(self, backends):
        """Shapelet (diffuse) branch: the vmapped multi-band shapelet
        coherency matches the per-band loop."""
        vec, loop = backends
        key = jax.random.PRNGKey(12)
        ep_v, _ = vec.new_calib_episode(key, 2, 3, diffuse=True)
        ep_l, _ = loop.new_calib_episode(key, 2, 3, diffuse=True)
        np.testing.assert_array_equal(np.asarray(ep_v.Ccal),
                                      np.asarray(ep_l.Ccal))
        assert rel(ep_v.V, ep_l.V) < 1e-5

    def test_demixing_episode(self, backends):
        vec, loop = backends
        key = jax.random.PRNGKey(13)
        ep_v, mdl_v = vec.new_demixing_episode(key, 3)
        ep_l, mdl_l = loop.new_demixing_episode(key, 3)
        np.testing.assert_array_equal(np.asarray(ep_v.Ccal),
                                      np.asarray(ep_l.Ccal))
        assert rel(ep_v.V, ep_l.V) < 1e-5
        assert ep_v.snr == ep_l.snr


class TestShardedBackendParity:
    """The mesh-routed backend (forced shard=True on the virtual 8-device
    CPU mesh) matches the host-loop backend end to end: J, residual,
    sigma, influence image."""

    @pytest.fixture(scope="class")
    def solved(self, backends):
        _, loop = backends
        sharded = tiny_backend(shard=True)
        key = jax.random.PRNGKey(21)
        ep_s, mdl = sharded.new_demixing_episode(key, 3)
        ep_l, _ = loop.new_demixing_episode(key, 3)
        rho = mdl.rho.astype(np.float32)
        res_s = sharded.calibrate(ep_s, rho, mask=np.ones(3, np.float32))
        res_l = loop.calibrate(ep_l, rho, mask=np.ones(3, np.float32))
        return sharded, loop, ep_s, ep_l, mdl, rho, res_s, res_l

    def test_solve_parity(self, solved):
        _, _, _, _, _, _, res_s, res_l = solved
        # float32 reduction-order differences (psum vs local sums) only
        assert rel(res_s.J, res_l.J) < 5e-3
        assert rel(res_s.residual, res_l.residual) < 1e-3
        assert float(res_s.sigma_res) == pytest.approx(
            float(res_l.sigma_res), rel=1e-3)

    def test_influence_image_parity(self, solved):
        sharded, loop, ep_s, ep_l, mdl, rho, res_s, res_l = solved
        alpha = np.zeros(3, np.float32)
        img_s = sharded.influence_image(ep_s, res_s, rho, alpha)
        img_l = loop.influence_image(ep_l, res_l, rho, alpha)
        assert rel(img_s, img_l) < 5e-3

    def test_chunk_sharded_influence_fallback(self):
        """The chunk-axis fallback (sharded_cal.influence_sharded — the
        reference's process pool as a mesh axis) matches the loop
        influence.  Exercised directly: on the 8-device test mesh every
        Nf <= 8 divides, so the automatic route prefers the frequency
        axis and the fallback only triggers on real small meshes."""
        from smartcal_tpu.cal import imager, influence

        sharded = tiny_backend(shard=True)
        loop = tiny_backend(vectorized=False, shard=False)
        key = jax.random.PRNGKey(22)
        ep, mdl = sharded.new_demixing_episode(key, 3)
        rho = mdl.rho.astype(np.float32)
        res = loop.calibrate(ep, rho, mask=np.ones(3, np.float32))
        alpha = np.zeros(3, np.float32)
        freqs = np.asarray(ep.obs.freqs)
        hadd_all = influence.consensus_hadd_all(
            rho, alpha, freqs, ep.f0, n_poly=sharded.n_poly,
            polytype=sharded.polytype)
        uvw = jnp.asarray(np.asarray(ep.obs.uvw).reshape(-1, 3))
        cell = imager.default_cell(ep.obs.uvw, float(freqs[-1]))
        img_s = sharded._influence_image_chunk_sharded(
            ep, res, hadd_all, uvw, cell, sharded.npix, nsp=2)
        img_l = loop.influence_image(ep, res, rho, alpha)
        assert rel(img_s, img_l) < 1e-4


class TestEpisodePipelining:
    def test_run_pipelined_matches_sequential(self, backends):
        """The double-buffered pipeline is a pure reordering: outputs are
        a function of the keys only, identical to the serial loop."""
        vec, _ = backends
        keys = list(jax.random.split(jax.random.PRNGKey(31), 3))

        def make(k):
            return vec.new_demixing_episode(k, 3)

        def process(ep, mdl):
            res = vec.calibrate(ep, mdl.rho.astype(np.float32),
                                mask=np.ones(3, np.float32))
            return float(res.sigma_res)

        piped = list(vec.run_pipelined(keys, make, process))
        serial = [process(*make(k)) for k in keys]
        np.testing.assert_allclose(piped, serial, rtol=0, atol=0)

    def test_env_prefetch_deterministic(self):
        """CalibEnv with prefetch=True walks the same key stream and
        produces the same observations as the plain env."""
        e0 = CalibEnv(M=3, backend=tiny_backend(shard=False), seed=9)
        e1 = CalibEnv(M=3, backend=tiny_backend(shard=False), seed=9,
                      prefetch=True)
        for _ in range(2):
            o0, o1 = e0.reset(), e1.reset()
            assert e0.K == e1.K
            np.testing.assert_array_equal(o0["sky"], o1["sky"])
            np.testing.assert_allclose(o0["img"], o1["img"],
                                       rtol=1e-5, atol=1e-7)

    def test_demix_env_prefetch_deterministic(self):
        e0 = DemixingEnv(K=3, backend=tiny_backend(shard=False), seed=9)
        e1 = DemixingEnv(K=3, backend=tiny_backend(shard=False), seed=9,
                         prefetch=True)
        for _ in range(2):
            o0, o1 = e0.reset(), e1.reset()
            np.testing.assert_array_equal(o0["metadata"], o1["metadata"])


class TestSegmentDonation:
    """The bounded-segment ADMM driver donates its carries: the L-BFGS
    resume state through _seg_resume, the solution carry through
    _seg_start, the consensus dual through _host_consensus."""

    # function-scoped on purpose: these tests EXECUTE the donating jits,
    # which invalidates the donated fixture arrays for any later test
    @pytest.fixture()
    def seg_problem(self):
        rng = np.random.default_rng(0)
        Nf, Ts, K, N, td = 2, 2, 2, 6, 2
        B = N * (N - 1) // 2
        cfg = solver.SolverConfig(n_stations=N, n_dirs=K, n_poly=2,
                                  admm_iters=2, lbfgs_iters=3, init_iters=3)
        V6 = jnp.asarray(rng.normal(0, 1, (Nf, Ts, td, B, 2, 2, 2)),
                         jnp.float32)
        C7 = jnp.asarray(rng.normal(0, 1, (Nf, Ts, K, td, B, 2, 2, 2)),
                         jnp.float32)
        pr = jnp.asarray(rng.normal(0, 0.1, (Nf, Ts, K, 2 * N, 2, 2)),
                         jnp.float32)
        rho = jnp.asarray([1.0, 0.5], jnp.float32)
        x0 = jnp.asarray(rng.normal(0, 0.3, (Nf, Ts, K * 2 * N * 2 * 2)),
                         jnp.float32)
        return cfg, V6, C7, pr, rho, x0

    def test_segment_jits_declare_donation(self, seg_problem):
        """The lowered segment programs alias their carry inputs to
        outputs (tf.aliasing_output) — the actual buffer reuse on
        accelerators; CPU ignores the alias but the declaration is what
        this pins."""
        cfg, V6, C7, pr, rho, x0 = seg_problem
        txt = solver._seg_start.lower(
            x0, V6, C7, pr, rho, cfg, 2, False).as_text()
        assert "tf.aliasing_output" in txt
        res = solver._seg_start(x0, V6, C7, pr, rho, cfg, 2, False)
        txt = solver._seg_resume.lower(
            res, V6, C7, pr, rho, cfg, 2, False).as_text()
        assert "tf.aliasing_output" in txt
        J = res.x.reshape(2, 2, 2, 2 * 6, 2, 2)
        Y = jnp.zeros_like(J)
        bfull = jnp.asarray(np.random.default_rng(1).normal(0, 1, (2, 2)),
                            jnp.float32)
        Bi = jnp.broadcast_to(jnp.eye(2, dtype=jnp.float32), (2, 2, 2))
        txt = solver._host_consensus.lower(
            J, Y, bfull, Bi, rho, cfg).as_text()
        assert "tf.aliasing_output" in txt

    def test_segment_driver_no_live_buffer_growth(self, seg_problem):
        """Walking many resume segments must not accumulate live arrays:
        each segment's state replaces the previous one (donation on
        accelerators, reference drop everywhere)."""
        cfg, V6, C7, pr, rho, x0 = seg_problem
        res = solver._seg_start(x0, V6, C7, pr, rho, cfg, 2, False)
        jax.block_until_ready(res.x)
        counts = []
        for _ in range(6):
            res = solver._seg_resume(res, V6, C7, pr, rho, cfg, 2, False)
            jax.block_until_ready(res.x)
            counts.append(len(jax.live_arrays()))
        assert max(counts) - min(counts) == 0, counts

    def test_host_driver_still_matches_fused_with_donation(self,
                                                           seg_problem):
        """Donation must not change solve_admm_host numerics (guards a
        donated-buffer-read-after-free class of bug at the driver level);
        full-tolerance parity lives in test_cal_backend."""
        rng = np.random.default_rng(3)
        N, K, Nf, T, B = 6, 2, 2, 4, 15
        cfg = solver.SolverConfig(n_stations=N, n_dirs=K, n_poly=2,
                                  admm_iters=2, lbfgs_iters=3,
                                  init_iters=4)
        V = jnp.asarray(rng.normal(0, 1, (Nf, T, B, 2, 2, 2)), jnp.float32)
        C = jnp.asarray(rng.normal(0, 1, (Nf, K, T * B, 4, 2)), jnp.float32)
        freqs = jnp.asarray([120e6, 130e6], jnp.float32)
        rho = jnp.asarray([1.0, 0.7], jnp.float32)
        fused = solver.solve_admm(V, C, freqs, 125e6, rho, cfg, n_chunks=2)
        host = solver.solve_admm_host(V, C, freqs, 125e6, rho, cfg,
                                      n_chunks=2, seg_iters=2)
        np.testing.assert_allclose(np.asarray(host.J), np.asarray(fused.J),
                                   rtol=2e-3, atol=2e-4)


def test_quartic_small_step_slope_regression():
    """The exact-P1 line search (P1 = F(D,J) + F(J,D)) keeps phi'(0)
    accurate at SMALL step scales: the previous polarization-identity
    extraction F(J+D,J+D) - F(J,J) - F(D,D) cancels catastrophically in
    f32 once |D| << |J| (measured ~3e-3 relative slope error at
    |D| ~ 1e-5 |J|, vs ~2e-7 for the mixed-term form)."""
    from smartcal_tpu.cal.solver import (_baseline_onehots, _cost_fn_onehot,
                                         _quartic_phi_maker)

    rng = np.random.default_rng(5)
    K, N, Tc = 2, 6, 4
    B = N * (N - 1) // 2
    cfg = solver.SolverConfig(n_stations=N, n_dirs=K)
    x = jnp.asarray(rng.normal(0, 0.4, (K * 2 * N * 2 * 2,)), jnp.float32)
    V5 = jnp.asarray(rng.normal(0, 1, (Tc, B, 2, 2, 2)), jnp.float32)
    C5 = jnp.asarray(rng.normal(0, 1, (K, Tc, B, 2, 2, 2)), jnp.float32)
    prior = jnp.asarray(rng.normal(0, 0.3, (K, 2 * N, 2, 2)), jnp.float32)
    hr = jnp.asarray([1.5, 0.7], jnp.float32)
    Vp = jnp.transpose(V5, (2, 3, 4, 0, 1))
    Cp = jnp.transpose(C5, (0, 3, 4, 5, 1, 2))
    oh = _baseline_onehots(N)
    fun = lambda q: _cost_fn_onehot(q, Vp, Cp, oh, prior, hr, cfg)
    maker = _quartic_phi_maker(Vp, Cp, oh, prior, hr, cfg)
    for dscale in (1e-4, 1e-5):
        d = jnp.asarray(rng.normal(0, dscale, x.shape), jnp.float32)
        ref_slope = float(jnp.vdot(jax.grad(fun)(x), d))
        _, der = maker(fun, x, d)(jnp.float32(0.0))
        assert abs(float(der) - ref_slope) < 1e-5 * abs(ref_slope), (
            dscale, float(der), ref_slope)
