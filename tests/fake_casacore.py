"""Strict in-memory emulation of the ``casacore.tables`` API surface the
cal/ms_io.py casacore adapter uses, backed by the checked-in layout
contract ``tests/fixtures/lofar_ms_layout.json``.

Purpose (VERDICT r3 item 6): python-casacore is not installable in this
image, so the adapter's real-MS branches (``ms_io.py`` ``_casa_*``) had
never executed.  This fake serves a synthetic MS with the REAL LOFAR
layout — row axis first from getcol, (nchan, ncorr) data cells,
autocorrelation rows present, baseline order shuffled within each time
block — and is STRICT: requesting a column or subtable the fixture does
not declare raises, so any adapter drift away from the real layout fails
the contract tests instead of passing silently.

The emulated surface (only what the adapter touches):
    tables.table(path, readonly=) -> Table
    tables.makecoldesc(name, desc) -> dict
    Table.query(sortlist=, columns=) -> Table view (putcol writes through
        the sort mapping to the underlying rows, as casacore reference
        tables do)
    Table.getcol/putcol/colnames/nrows/close/getcoldesc/addcols
    Table[i] -> row dict
"""

from __future__ import annotations

import json
import os

import numpy as np

_FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures", "lofar_ms_layout.json")

with open(_FIXTURE) as _fh:
    LAYOUT = json.load(_fh)

_DTYPES = {"float64": np.float64, "float32": np.float32, "int32": np.int32,
           "complex64": np.complex64, "bool": np.bool_}

# path -> _Store; populated by make_lofar_ms()
REGISTRY: dict = {}


class _Store:
    """One MS: main-table columns + subtable columns, with the declared
    layout tracked so getcol can be strict."""

    def __init__(self, main, subtables):
        self.main = main                      # {col: row-major ndarray}
        self.subtables = subtables            # {name: {col: ndarray}}
        self.declared_main = set(LAYOUT["main"]["columns"])
        self.addable = set(LAYOUT["main"].get("data_columns_addable", []))


def _resolve(path):
    """(store, table_name): subtables are opened as <ms>/<SUBTABLE>."""
    path = os.path.normpath(str(path))
    if path in REGISTRY:
        return REGISTRY[path], None
    parent, name = os.path.split(path)
    parent = os.path.normpath(parent)
    if parent in REGISTRY:
        if name not in LAYOUT["subtables"]:
            raise RuntimeError(f"undeclared subtable {name!r} — not in the "
                               "LOFAR layout fixture")
        return REGISTRY[parent], name
    raise RuntimeError(f"Table {path} does not exist")


class table:  # noqa: N801 - casacore's own casing
    def __init__(self, path, readonly=True, **_):
        self._store, self._sub = _resolve(path)
        self._readonly = readonly
        self._rows = None                     # query views set this
        self._cols = None

    # -- internals ----------------------------------------------------------
    def _colmap(self):
        if self._sub is not None:
            return self._store.subtables[self._sub]
        return self._store.main

    def _declared(self, name):
        if self._sub is not None:
            return name in LAYOUT["subtables"][self._sub]["columns"]
        return (name in self._store.declared_main
                or name in self._colmap())    # addcols() extends the layout

    # -- casacore API -------------------------------------------------------
    def query(self, sortlist="", columns=""):
        if self._sub is not None:
            raise RuntimeError("query on a subtable is not part of the "
                               "contract")
        cols = self._colmap()
        order = np.arange(len(cols["TIME"]))
        if sortlist:
            keys = [k.strip() for k in sortlist.split(",")]
            for k in keys:
                if not self._declared(k):
                    raise RuntimeError(f"sort key {k!r} undeclared")
            # np.lexsort: last key is primary
            order = np.lexsort(tuple(cols[k] for k in reversed(keys)))
        view = table.__new__(table)
        view._store, view._sub = self._store, None
        view._readonly = self._readonly
        view._rows = order
        view._cols = ([c.strip() for c in columns.split(",") if c.strip()]
                      if columns else None)
        return view

    def getcol(self, name):
        if self._cols is not None and name not in self._cols:
            raise RuntimeError(f"column {name!r} not selected in query")
        if not self._declared(name):
            raise RuntimeError(f"column {name!r} undeclared in the LOFAR "
                               "layout fixture")
        arr = self._colmap()[name]
        if self._rows is not None:
            arr = arr[self._rows]
        return arr.copy()

    def putcol(self, name, value):
        if self._readonly:
            raise RuntimeError("table opened readonly")
        if self._cols is not None and name not in self._cols:
            raise RuntimeError(f"column {name!r} not selected in query")
        if not self._declared(name):
            raise RuntimeError(f"column {name!r} undeclared")
        cols = self._colmap()
        cur = cols[name]
        value = np.asarray(value, cur.dtype)
        if self._rows is not None:
            # write through the sort mapping, like a casacore reference table
            cur[self._rows] = value
        else:
            if value.shape != cur.shape:
                raise RuntimeError(f"putcol shape {value.shape} != "
                                   f"{cur.shape}")
            cols[name] = value

    def colnames(self):
        return list(self._colmap().keys())

    def nrows(self):
        cols = self._colmap()
        first = next(iter(cols.values()))
        return len(first) if self._rows is None else len(self._rows)

    def getcoldesc(self, name):
        if not self._declared(name):
            raise RuntimeError(f"column {name!r} undeclared")
        arr = self._colmap()[name]
        return {"name": name, "valueType": str(arr.dtype),
                "shape": list(arr.shape[1:])}

    def addcols(self, desc):
        name = desc["name"]
        if self._sub is not None:
            raise RuntimeError("addcols on a subtable is not part of the "
                               "contract")
        if name not in self._store.addable:
            raise RuntimeError(
                f"adding {name!r} is outside the fixture contract "
                f"(addable: {sorted(self._store.addable)})")
        vt = str(desc.get("valueType", "complex64"))
        ref_dtype = _DTYPES.get(
            vt, np.complex64 if "complex" in vt else np.float64)
        shape = tuple(desc.get("shape", []))
        n = self.nrows()
        self._colmap()[name] = np.zeros((n,) + shape, ref_dtype)

    def __getitem__(self, i):
        cols = self._colmap()
        rows = self._rows if self._rows is not None else np.arange(
            len(next(iter(cols.values()))))
        return {k: v[rows[i]] for k, v in cols.items()}

    def close(self):
        pass


def makecoldesc(name, desc):
    out = dict(desc)
    out["name"] = name
    return out


# ---------------------------------------------------------------------------
# Fixture-true MS builder
# ---------------------------------------------------------------------------

def make_lofar_ms(path, n_stations=7, n_times=4, nchan=8, freq0=120e6,
                  chan_width=48828.125, ra0=1.2, dec0=0.9, seed=0):
    """Create a registry-backed fake LOFAR MS at ``path``.

    Layout per the fixture: (B + N) rows per time including
    autocorrelations, TIME-ordered blocks with the baseline order inside
    each block SHUFFLED (the adapter must sort, not assume), DATA cells
    (nchan, 4) complex64 with a deterministic value pattern
    ``val(t, p, q, c, corr)`` the contract tests can predict.
    """
    rng = np.random.default_rng(seed)
    t0 = float(LAYOUT["typical"]["time_epoch_s"])
    interval = float(LAYOUT["typical"]["interval_s"])
    p, q = np.triu_indices(n_stations, 0)     # incl. autocorr
    npair = p.size

    times, a1, a2, uvw, data = [], [], [], [], []
    for t in range(n_times):
        perm = rng.permutation(npair)          # shuffled inside the block
        pp, qq = p[perm], q[perm]
        times.append(np.full(npair, t0 + t * interval))
        a1.append(pp)
        a2.append(qq)
        uvw.append(np.stack([(pp - qq) * 100.0,
                             (pp + qq) * 10.0 + t,
                             np.zeros(npair)], axis=1))
        cell = (value_pattern(t, pp, qq)[:, None, None]
                + 1j * np.arange(nchan)[None, :, None]
                + np.arange(4)[None, None, :] * 0.25)
        data.append(cell)
    nrows = n_times * npair
    main = {
        "TIME": np.concatenate(times).astype(np.float64),
        "ANTENNA1": np.concatenate(a1).astype(np.int32),
        "ANTENNA2": np.concatenate(a2).astype(np.int32),
        "UVW": np.concatenate(uvw).astype(np.float64),
        "INTERVAL": np.full(nrows, interval, np.float64),
        "EXPOSURE": np.full(nrows, interval, np.float64),
        "DATA": np.concatenate(data).astype(np.complex64),
        "FLAG": np.zeros((nrows, nchan, 4), np.bool_),
        "WEIGHT": np.ones((nrows, 4), np.float32),
    }
    freqs = freq0 + chan_width * np.arange(nchan)
    subtables = {
        "SPECTRAL_WINDOW": {
            "CHAN_FREQ": freqs[None, :].astype(np.float64),
            "REF_FREQUENCY": np.asarray([freqs.mean()], np.float64),
        },
        "FIELD": {
            "PHASE_DIR": np.asarray([[[ra0, dec0]]], np.float64),
        },
    }
    # validate what we built against the declared fixture before serving it
    for name, spec in LAYOUT["main"]["columns"].items():
        arr = main[name]
        assert arr.dtype == _DTYPES[spec["dtype"]], (name, arr.dtype)
        want = tuple(nchan if s == "nchan" else 4 if s == "ncorr" else s
                     for s in spec["cell_shape"])
        assert arr.shape[1:] == want, (name, arr.shape, want)
    for sub, spec in LAYOUT["subtables"].items():
        for name, cspec in spec["columns"].items():
            arr = subtables[sub][name]
            assert arr.dtype == _DTYPES[cspec["dtype"]], (sub, name)
            assert arr.ndim == len(cspec["getcol_shape"]), (sub, name)

    os.makedirs(path, exist_ok=True)
    # the table.dat marker is how ms_io recognizes a casacore MS on disk
    with open(os.path.join(path, "table.dat"), "wb") as fh:
        fh.write(b"\0")
    REGISTRY[os.path.normpath(str(path))] = _Store(main, subtables)
    return path


def value_pattern(t, p, q):
    """Deterministic channel-0 real part: row identity the tests predict."""
    return (np.asarray(t) * 1000.0 + np.asarray(p) * 10.0
            + np.asarray(q)).astype(np.float64)
