"""Test configuration: run everything on a virtual 8-device CPU mesh.

Real-TPU runs happen through bench.py / __graft_entry__.py; tests must be
hermetic and exercise the multi-chip sharding paths without hardware, so we
force the CPU platform with 8 virtual devices before JAX initialises.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
