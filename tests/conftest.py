"""Test configuration: run everything on a virtual 8-device CPU mesh.

Real-TPU runs happen through bench.py / __graft_entry__.py; tests must be
hermetic and exercise the multi-chip sharding paths without hardware, so we
force the CPU platform with 8 virtual devices before JAX initialises.
"""

import os

# JAX_PLATFORMS=axon (the TPU tunnel) is set globally in this environment and
# a sitecustomize.py imports jax at interpreter startup, so the env var is
# already latched into jax.config by the time conftest runs — override through
# jax.config, before any backend is initialised.  (The axon backend also
# lacks pure_callback support, which the 'exact' eig mode relies on.)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu", (
    "tests must run on the virtual CPU mesh, got " + str(jax.devices()))
assert jax.device_count() == 8, "expected 8 virtual CPU devices"

# Persistent compilation cache: the expensive programs (solver, meshes)
# recompile identically on every suite run — deserialize instead.  The
# single-core full-suite run measured 40 min cold; the cache removes the
# XLA-compile share on every subsequent run.  Disable with
# SMARTCAL_NO_COMPILE_CACHE=1 when debugging suspected stale-cache
# miscompiles.
from smartcal_tpu.utils import enable_compilation_cache  # noqa: E402

enable_compilation_cache()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Bound the number of live compiled executables.

    With ~180 tests compiling fresh jaxprs on the CPU client, the full
    suite deterministically segfaults near the end (observed in
    tests/test_td3_ddpg.py::test_td3_per_priority_refresh, which passes
    in isolation and in any sub-group).  Clearing jit caches at module
    teardown keeps the executable count bounded; cross-module cache
    reuse was minimal anyway (modules use distinct shapes)."""
    yield
    jax.clear_caches()


def pytest_collection_modifyitems(config, items):
    """Skip ``slow``-marked tests by default, but still run them when the
    user gives a marker expression (-m slow) or names one explicitly by
    node id — an addopts marker filter would silently deselect even an
    exact node-id selection."""
    if config.option.markexpr:
        return
    explicit = {str(a).split("::")[-1].split("[")[0]
                for a in config.invocation_params.args if "::" in str(a)}
    skip_slow = pytest.mark.skip(reason="slow test: run with -m slow")
    for item in items:
        if "slow" in item.keywords and \
                item.name.split("[")[0] not in explicit:
            item.add_marker(skip_slow)


def load_tool_module(name):
    """Import a script from tools/ by path (the tools are not a package;
    shared by the host-side tool unit tests)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod
