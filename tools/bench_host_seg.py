"""Host-segmented vs fused ADMM solve overhead (VERDICT r2 item 8).

Times ``solver.solve_admm`` (one fused XLA program) against
``solver.solve_admm_host`` (bounded per-ADMM-iteration dispatches with
exact L-BFGS resume) on the same problem, at sizes where BOTH run on the
chip (the fused program trips the device watchdog above roughly
total_iters x work ~ 2-3e7 units; see envs/radio.py:_use_host_solver).
The measured per-dispatch overhead and the largest fused-runnable size
give the routing threshold a provenance beyond the two data points it was
calibrated from.

Usage:
    PYTHONPATH=/root/repo:$PYTHONPATH python tools/bench_host_seg.py \
        [--stations 40] [--nf 8] [--repeat 3] [--cpu]

Writes results/host_seg_bench.json.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def run_case(backend_kwargs, admm_iters, repeat):
    import jax
    import jax.numpy as jnp

    from smartcal_tpu.cal import solver
    from smartcal_tpu.envs.radio import RadioBackend

    be = RadioBackend(**backend_kwargs)
    ep, _ = be.new_demixing_episode(jax.random.PRNGKey(0), 6)
    rho = jnp.ones(6, jnp.float32)
    cfg = be._solver_cfg(ep.n_dirs)

    out = {"config": {**backend_kwargs, "admm_iters": admm_iters,
                      "lbfgs_iters": cfg.lbfgs_iters,
                      "init_iters": cfg.init_iters}}
    work = (be.n_stations ** 2) * be.n_freqs * be.n_times
    total_iters = cfg.init_iters + admm_iters * cfg.lbfgs_iters
    out["work_units"] = float(total_iters * work)

    for name, fn in (
            ("fused", lambda: solver.solve_admm(
                ep.V, ep.Ccal, ep.obs.freqs, ep.f0, rho, cfg,
                n_chunks=be.n_chunks, admm_iters=jnp.asarray(admm_iters))),
            ("host_segmented", lambda: solver.solve_admm_host(
                ep.V, ep.Ccal, ep.obs.freqs, ep.f0, rho, cfg,
                n_chunks=be.n_chunks, admm_iters=admm_iters))):
        try:
            t0 = time.time()
            r = fn()
            jax.block_until_ready(r.residual)
            compile_s = time.time() - t0
            times = []
            for _ in range(repeat):
                t0 = time.time()
                r = fn()
                jax.block_until_ready(r.residual)
                times.append(time.time() - t0)
            out[name] = {"compile_s": round(compile_s, 2),
                         "steady_s": round(float(np.median(times)), 3),
                         "sigma_res": round(float(r.sigma_res), 3),
                         "sigma_data": round(float(r.sigma_data), 3)}
        except Exception as e:  # device watchdog / OOM — record, keep going
            out[name] = {"error": f"{type(e).__name__}: {str(e)[:300]}"}
    f = out.get("fused", {}).get("steady_s")
    h = out.get("host_segmented", {}).get("steady_s")
    if f and h:
        out["host_over_fused"] = round(h / f, 3)
        out["dispatch_overhead_s"] = round(h - f, 3)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stations", type=int, default=40)
    ap.add_argument("--nf", type=int, default=8)
    ap.add_argument("--times", type=int, default=20)
    ap.add_argument("--tdelta", type=int, default=10)
    ap.add_argument("--admm", type=int, default=10)
    ap.add_argument("--repeat", type=int, default=3)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    case = run_case(dict(n_stations=args.stations, n_freqs=args.nf,
                         n_times=args.times, tdelta=args.tdelta,
                         admm_iters=args.admm, lbfgs_iters=8,
                         init_iters=30),
                    admm_iters=args.admm, repeat=args.repeat)
    case["platform"] = jax.devices()[0].platform
    print(json.dumps(case, indent=1))
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "results", "host_seg_bench.json")
    existing = []
    if os.path.exists(out):
        with open(out) as fh:
            existing = json.load(fh)
            if isinstance(existing, dict):
                existing = [existing]
    existing.append(case)
    with open(out, "w") as fh:
        json.dump(existing, fh, indent=1)


if __name__ == "__main__":
    main()
