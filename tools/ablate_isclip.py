"""Staleness / IS-clip ablation for the supervised async fleet.

Runs the SAME seed + actor layout through three arms of
``parallel.learner.train_supervised``:

* ``fresh``          — publish_every=1, clip off (the baseline cadence:
                       actors are at most one learner round stale);
* ``stale_noclip``   — publish_every=K (actors act on K-round-old
                       snapshots), IS-clip OFF: stale transitions enter
                       the TD update at full weight;
* ``stale_clip``     — same forced staleness, IMPACT IS-clip ON
                       (is_clip=c): stale transitions are weighted by
                       the clipped policy ratio.

Each arm records a ``--metrics`` JSONL; the artifact aggregates the
learning signal (score trajectory, critic-loss stats, non-finite
counts) next to the staleness/clip-saturation gauges so the clip-on vs
clip-off comparison AT THE SAME forced staleness is one JSON document.

    python tools/ablate_isclip.py [--out results/isclip_ablation_r10.json]
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _run_arm(name, workdir, *, publish_every, is_clip, seed, episodes,
             n_actors):
    from smartcal_tpu.parallel import learner

    run = os.path.join(workdir, f"isclip_{name}.jsonl")
    (st, buf), scores, summary = learner.train_supervised(
        seed=seed, episodes=episodes, n_actors=n_actors,
        agent_kwargs={"batch_size": 32, "mem_size": 4096},
        rollout_epochs=2, rollout_steps=10, batch_envs=2,
        publish_every=publish_every, is_clip=is_clip,
        quiet=True, metrics=run, diag=True)
    events = [json.loads(ln) for ln in open(run) if ln.strip()]
    closs = [e["critic_loss"] for e in events
             if e.get("event") == "diag" and "critic_loss" in e]
    gauges = {}
    for e in events:
        if e.get("event") == "gauge":
            gauges.setdefault(e["name"], []).append(e["value"])
    closs_arr = np.asarray(closs, np.float64) if closs else np.zeros(1)
    finite = closs_arr[np.isfinite(closs_arr)]
    out = {
        "arm": name,
        "publish_every": publish_every,
        "is_clip": is_clip,
        "episodes": len(scores),
        "scores": [round(float(s), 4) for s in scores],
        "score_mean": round(float(np.mean(scores)), 4),
        "score_std": round(float(np.std(scores)), 4),
        "critic_loss_mean": round(float(finite.mean()), 5)
        if finite.size else None,
        "critic_loss_max": round(float(finite.max()), 5)
        if finite.size else None,
        "critic_loss_nonfinite": int((~np.isfinite(closs_arr)).sum()),
        "staleness_versions_max": max(
            gauges.get("weight_staleness_versions", [0])),
        "staleness_mean_transitions": (round(float(np.mean(
            gauges["transition_staleness_mean"])), 4)
            if "transition_staleness_mean" in gauges else None),
        "is_clip_saturation_mean": (round(float(np.mean(
            gauges["is_clip_saturation"])), 4)
            if "is_clip_saturation" in gauges else None),
        "restarts": summary["restarts"],
        "env_steps_per_s": summary["env_steps_per_s"],
    }
    return out


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default="results/isclip_ablation_r10.json")
    p.add_argument("--workdir", default="/tmp/isclip_ablation")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--episodes", type=int, default=24)
    p.add_argument("--n-actors", dest="n_actors", type=int, default=2)
    p.add_argument("--publish-every", dest="publish_every", type=int,
                   default=4, help="forced-staleness cadence of the "
                                   "stale arms")
    p.add_argument("--is-clip", dest="is_clip", type=float, default=2.0)
    args = p.parse_args(argv)
    os.makedirs(args.workdir, exist_ok=True)

    common = dict(seed=args.seed, episodes=args.episodes,
                  n_actors=args.n_actors)
    arms = [
        _run_arm("fresh", args.workdir, publish_every=1, is_clip=0.0,
                 **common),
        _run_arm("stale_noclip", args.workdir,
                 publish_every=args.publish_every, is_clip=0.0, **common),
        _run_arm("stale_clip", args.workdir,
                 publish_every=args.publish_every, is_clip=args.is_clip,
                 **common),
    ]
    payload = {
        "experiment": "isclip_staleness_ablation",
        "protocol": "same seed/actors/rollout across arms; staleness "
                    "forced by the weight-publication cadence "
                    "(publish_every); clip-on vs clip-off compared at "
                    "the SAME forced staleness",
        "seed": args.seed,
        "n_actors": args.n_actors,
        "forced_publish_every": args.publish_every,
        "clip_constant": args.is_clip,
        "arms": arms,
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(payload, fh, indent=1)
    sys.stderr.write(f"[ablate_isclip] wrote {args.out}\n")
    return payload


if __name__ == "__main__":
    main()
