#!/bin/bash
# Composed-mesh smoke (ISSUE 17): drive the lane x baseline batched
# route on the 8-virtual-device CPU mesh with the blocked-kernel tier
# forced on, record it, and assert the whole observability chain —
# per-axis footprint accounting on the influence cost event, the
# pallas-vs-blocked-XLA kernel roofline rows, the obs_report rendering
# of both, and the bench_mesh_compose extra's artifact.  ~2 min on CPU.
#
#   bash tools/smoke_mesh.sh [workdir]
#
# Exits non-zero on any broken link in the chain.
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1

REPO="$PWD"
WORK="${1:-$(mktemp -d /tmp/smoke_mesh.XXXXXX)}"
RUN="$WORK/mesh_run.jsonl"
mkdir -p "$WORK"

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"

echo "[smoke_mesh] recording composed lane x baseline episode -> $RUN" >&2
# N=17 -> B=136 = 8*17: factors cleanly as lane=2 x bp=4 on 8 devices.
# block_baselines=8 / imager_block_r=64 force the blocked tier at this
# toy scale so the kernel-family rows (hessian + imager, pallas + XLA)
# are recorded; npix=128 = pallas_imager.TILE_L so the pallas imager
# row is eligible.
PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" python - "$RUN" <<'EOF'
import sys

import jax
import numpy as np

from smartcal_tpu import obs
from smartcal_tpu.envs.radio import RadioBackend
from smartcal_tpu.obs import costs as obs_costs

assert jax.device_count() == 8, jax.devices()
backend = RadioBackend(n_stations=17, n_freqs=1, n_times=2, tdelta=2,
                       admm_iters=1, lbfgs_iters=2, init_iters=2,
                       npix=128, block_baselines=8, imager_block_r=64)
eps, rhos = [], []
for i in range(2):
    ep, mdl = backend.new_demixing_episode(jax.random.PRNGKey(i), 2)
    eps.append(ep)
    rhos.append(np.asarray(mdl.rho))
bep = backend.stack_episodes(eps)
rho = np.stack(rhos).astype(np.float32)
alpha = np.zeros_like(rho)
obs_costs.set_enabled(True)   # --diag equivalent: arm cost collection
with obs.recording(sys.argv[1]):
    res = backend.calibrate_batched(bep, rho, compose=(2, 4))
    img = backend.influence_images_batched(bep, res, rho, alpha,
                                           compose=(2, 4))
    jax.block_until_ready(img)
    n = obs_costs.flush_pending()
print("[smoke_mesh] recorded, flushed", n, "deferred cost event(s)")
EOF

python - "$RUN" <<'EOF'
import json
import sys

events = [json.loads(ln) for ln in open(sys.argv[1]) if ln.strip()]
costs = [e for e in events if e["event"] == "cost" and not e.get("error")]
inf = [e for e in costs if e.get("stage") == "influence"]
assert inf, f"no influence cost event: {sorted({e.get('stage') for e in costs})}"
row = inf[0]
assert row.get("shard_axes") == {"lane": 2, "bp": 4}, row.get("shard_axes")
pba = row.get("peak_bytes_per_axis") or {}
assert set(pba) == {"lane", "bp"} and all(v > 0 for v in pba.values()), pba
assert row.get("peak_bytes_per_shard", 0) > 0, row
kstages = sorted({e["stage"] for e in costs
                  if str(e.get("stage", "")).startswith("kernel:")})
for want in ("kernel:hessian_blocked_xla", "kernel:hessian_pallas",
             "kernel:imager_blocked_xla", "kernel:imager_pallas"):
    assert want in kstages, f"missing {want}: {kstages}"
print("[smoke_mesh] cost events OK: per-axis footprint",
      {k: int(v) for k, v in pba.items()}, "+", len(kstages),
      "kernel-family row(s)")
EOF

echo "[smoke_mesh] checking obs_report rendering (json + text)" >&2
python tools/obs_report.py "$RUN" --json --bootstrap 50 > "$WORK/report.json"
python - "$WORK/report.json" <<'EOF'
import json
import sys

report = json.load(open(sys.argv[1]))
rl = (report["runs"][0] or {}).get("roofline") or {}
stages = rl.get("stages") or {}
assert "influence" in stages, f"roofline lost influence: {list(stages)}"
row = stages["influence"]
assert row.get("shard_axes") == {"bp": 4, "lane": 2}, row.get("shard_axes")
assert (row.get("peak_bytes_per_axis") or {}).get("bp", 0) > 0, row
kern = [s for s in stages if s.startswith("kernel:")]
assert len(kern) >= 4, f"kernel rows missing from roofline: {kern}"
print("[smoke_mesh] report OK:", len(kern), "kernel row(s), axes",
      row["shard_axes"])
EOF
python tools/obs_report.py "$RUN" > "$WORK/report.txt"
grep -q "mesh axes:" "$WORK/report.txt" || {
    echo "[smoke_mesh] FAIL: no 'mesh axes:' line in text report" >&2
    exit 1
}
grep -q "kernel hessian: pallas" "$WORK/report.txt" || {
    echo "[smoke_mesh] FAIL: no pallas-vs-XLA kernel line in text report" >&2
    exit 1
}

echo "[smoke_mesh] running bench_mesh_compose extra (N=17 tier)" >&2
BENCH_MESH_NS=17 PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
    python - "$WORK/mesh_compose.json" <<'EOF'
import json
import sys

import bench

out = bench.bench_mesh_compose(out_path=sys.argv[1])
rows = out["results"]
assert rows and rows[0]["arms"], out
arms = {a["arm"]: a for a in rows[0]["arms"]}
assert set(arms) == {"unsharded", "lane_only", "baseline_only",
                     "lane_x_baseline"}, sorted(arms)
lb = arms["lane_x_baseline"]
assert lb["t_influence_s"] >= 0 and lb["peak_bytes_per_axis"], lb
assert lb["peak_bytes_per_shard"] < arms["unsharded"]["peak_bytes_fused"]
print("[smoke_mesh] bench OK: lane_x_baseline",
      lb["lane_shards"], "x", lb["baseline_shards"], "shards,",
      "per-shard peak", int(lb["peak_bytes_per_shard"]), "bytes")
EOF

echo "[smoke_mesh] OK (artifacts in $WORK)"
