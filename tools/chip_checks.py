"""Shared artifact-validation checks for the chip-capture scripts.

One place for every "is this capture already landed?" predicate, so the
per-pass capture script (tools/capture_round.sh) and the outer restart
wrapper (tools/capture_r4_forever.sh) can never disagree about doneness
(ADVICE r3: the r3 wrapper omitted the per_e2e check and could declare
victory with the PER chip measurement still missing).

Usage (exit code 0 = done / promoted, 1 = not yet):
    python tools/chip_checks.py per_e2e
    python tools/chip_checks.py host_seg
    python tools/chip_checks.py primary /tmp/bench_primary_r4.out r4
    python tools/chip_checks.py extras  /tmp/bench_extras_r4.out  r4
"""

import json
import os
import shutil
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESULTS = os.path.join(REPO, "results")


def per_e2e_done() -> bool:
    """A TPU-platform measurement with an e2e_train_step row exists
    (layout: tools/bench_per.py — measurements[].{label,rows,e2e_rows})."""
    try:
        doc = json.load(open(os.path.join(RESULTS, "per_bench.json")))
    except Exception:
        return False
    for m in doc.get("measurements", []):
        label = m.get("label", "")
        # labels get hand-renamed after landing ("round2_tpu_standalone"),
        # so match the platform anywhere in the label
        if any(p in label for p in ("tpu", "axon")) and any(
                r.get("stage") == "e2e_train_step"
                for r in m.get("e2e_rows", [])):
            return True
    return False


def host_seg_done() -> bool:
    """A TPU-platform case whose host_segmented path has a steady-state
    time (it runs after fused, so its presence proves the whole case)."""
    try:
        cases = json.load(open(os.path.join(RESULTS, "host_seg_bench.json")))
    except Exception:
        return False
    if isinstance(cases, dict):
        cases = [cases]
    return any(c.get("platform") in ("tpu", "axon")
               and c.get("host_segmented", {}).get("steady_s") is not None
               for c in cases)


def _load_last_json_line(path: str):
    with open(path) as fh:
        return json.loads(fh.readlines()[-1])


def primary_done(tmpfile: str, rnd: str) -> bool:
    """Validate + promote a clean uncontended on-chip primary.

    Validation: not a CPU fallback (the "platform" key only appears then,
    and the capture command does NOT force the platform, so it really
    checked the device) AND uncontended (load < 1.2).  On success the
    payload is promoted to results/bench_primary_<rnd>.json and copied to
    results/latest_chip_capture.json (the round-agnostic pointer bench.py
    surfaces on future CPU fallbacks).
    """
    final = os.path.join(RESULTS, f"bench_primary_{rnd}.json")
    if os.path.exists(final):
        # doneness probe only: do NOT refresh the latest_chip_capture
        # pointer here — a still-running older-round capture loop would
        # stomp a newer round's pointer with stale numbers on every probe
        # (ADVICE r4 item 3); the pointer is written once, at promotion
        return True
    try:
        out = _load_last_json_line(tmpfile)
    except Exception:
        return False
    if out.get("metric") != "enet_sac_env_steps_per_sec" \
            or "platform" in out:
        return False
    if out.get("host_load_avg_1m", 9.9) >= 1.2:
        return False  # contended — not the clean number we came for
    with open(final, "w") as fh:
        json.dump(out, fh, indent=1)
    shutil.copyfile(final, os.path.join(RESULTS, "latest_chip_capture.json"))
    return True


def extras_done(tmpfile: str, rnd: str) -> bool:
    """Validate + promote an on-chip extras run: a TPU-platform payload
    whose epblock extra carries a value."""
    final = os.path.join(RESULTS, f"bench_extras_{rnd}.json")
    if os.path.exists(final):
        return True
    try:
        out = _load_last_json_line(tmpfile)
    except Exception:
        return False
    if "platform" in out:
        return False  # CPU fallback
    if not any(e.get("metric") == "enet_sac_env_steps_per_sec_epblock"
               and "value" in e for e in out.get("extra", [])):
        return False
    with open(final, "w") as fh:
        json.dump(out, fh, indent=1)
    return True


def solve_eval_done() -> bool:
    """The solve-eval microbench landed ON CHIP: the artifact must carry
    a TPU platform string — a CPU-fallback run (axon init failing inside
    the tool degrades to CPU with only a warning) must not be promoted
    as the chip comparison."""
    try:
        doc = json.load(open(os.path.join(RESULTS, "solve_eval_tpu.json")))
    except Exception:
        return False
    ok = doc.get("platform") in ("tpu", "axon") and doc.get("variants")
    if not ok:
        # remove the fallback artifact so the capture loop retries
        try:
            os.remove(os.path.join(RESULTS, "solve_eval_tpu.json"))
        except OSError:
            pass
    return bool(ok)


def main(argv):
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    cmd, args = argv[0], argv[1:]
    if cmd == "per_e2e":
        return 0 if per_e2e_done() else 1
    if cmd == "host_seg":
        return 0 if host_seg_done() else 1
    if cmd == "primary":
        return 0 if primary_done(*args) else 1
    if cmd == "extras":
        return 0 if extras_done(*args) else 1
    if cmd == "solve_eval":
        return 0 if solve_eval_done() else 1
    print(f"unknown check {cmd!r}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
