#!/bin/bash
# Fleet-serving smoke, the scale-out chain end to end:
#
# Phase 1 (COLD): serve_fleet.py with ONE replica against a FRESH
# --cache-dir — replica 0 must BUILD every program (export sources) and
# complete jobs through the router front door.
#
# Phase 2 (WARM FLEET + KILL): a 2-replica fleet against the SAME
# cache — BOTH replicas must come up entirely from cache (cold build
# happened exactly once, fleet-wide), serve with ZERO steady-state
# compile events summed across every replica process, and survive a
# mid-run SIGKILL of replica 0: every admitted job completes on the
# survivor (requeue), nothing sheds, and the slot respawns (measured
# recover time).
#
# Every load summary must also satisfy the shed-accounting identity:
# per-reason shed counts sum to the shed total, and shed + failed +
# completed == submitted (sheds and deadline misses are DISJOINT).
#
# Phase 2 also runs with --trace-dir, so it checks the distributed-
# tracing chain: >=99% of the warm load phase's completed requests must
# reconstruct a COMPLETE cross-process span tree (router dispatch ->
# replica admit -> replica serve -> router result, clock-skew
# corrected), and the SIGKILL must leave a flushed parent-side
# blackbox_replica*.jsonl crash dump in the kill phase's trace dir.
#
# Then tools/obs_report.py over the fleet RunLog must render the
# fleet-SLO section (per-replica p50/p99, dispatch balance, replica
# lifecycle), and over the phase TRACE DIRECTORY must render the
# critical-path section (per-replica queue/ipc/solve/total percentile
# breakdown from the merged timeline).
#
# The scale-out companion of smoke_serve.sh; the cold export build
# dominates (~2-4 min on CPU), the warm fleet phase is seconds.
#
#   bash tools/smoke_serve_fleet.sh [workdir]
#
# Exits non-zero on any broken link in the chain.
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1

REPO="$PWD"
WORK="${1:-$(mktemp -d /tmp/smoke_serve_fleet.XXXXXX)}"
CACHE="$WORK/cache"
OUT="$WORK/fleet.json"
RUN_COLD="$WORK/fleet_cold.jsonl"
RUN_WARM="$WORK/fleet_warm.jsonl"
mkdir -p "$WORK"

fleet() {  # fleet <metrics.jsonl> <extra args...>
    local metrics="$1"; shift
    (cd "$WORK" && PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
        JAX_PLATFORMS=cpu \
        python "$REPO/tools/serve_fleet.py" \
        --tier tiny --M 3 --lanes 3 --rate-per-replica 4 --duration 4 \
        --pool 4 --cache-dir "$CACHE" --metrics "$metrics" \
        --out "$OUT" --quiet "$@" > /dev/null)
}

echo "[smoke_serve_fleet] phase 1: COLD single replica (fresh $CACHE)" >&2
fleet "$RUN_COLD" --replicas 1

python - "$OUT" <<'EOF'
import json
import sys

doc = json.load(open(sys.argv[1]))
cold = doc["runs"][0]["scaling"][0]
assert cold["warm_sources"] == {"0": ["export"]}, \
    f"cold replica 0 must BUILD every program: {cold['warm_sources']}"
s = cold["summary"]
assert s["completed"] > 0, f"cold fleet completed no jobs: {s}"
print("[smoke_serve_fleet] cold OK:", s["completed"], "jobs through",
      "the front door, boot", cold["boot_s"], "s")
EOF

echo "[smoke_serve_fleet] phase 2: WARM 2-replica fleet + kill" >&2
TRACES="$WORK/traces"
fleet "$RUN_WARM" --replicas 2 --kill --trace-dir "$TRACES"

python - "$OUT" <<'EOF'
import json
import sys

doc = json.load(open(sys.argv[1]))
warm = doc["runs"][-1]
pt = warm["scaling"][0]

# 1. BOTH replicas warm-start entirely off the shared cache: the cold
#    build happened exactly once, fleet-wide
assert pt["warm_sources"] == {"0": ["cache"], "1": ["cache"]}, \
    f"warm fleet must deserialize everything: {pt['warm_sources']}"

# 2. zero steady-state compiles summed across EVERY replica process
assert pt["steady_compile_events_fleet"] == 0, \
    (f"{pt['steady_compile_events_fleet']} compile events in warm "
     f"fleet steady state")

# 3. shed-accounting identity on every load summary of the run:
#    per-reason counts sum to shed; shed/failed/completed partition
#    the submitted jobs (deadline misses are a subset of completed,
#    disjoint from sheds)
summaries = [p["summary"] for p in warm["scaling"]]
summaries += [warm["kill"]["summary"]]
for s in summaries:
    assert sum(s["shed_reasons"].values()) == s["shed"], s
    assert s["shed"] + s["failed"] + s["completed"] == s["submitted"], s
    assert s["accounted"] == s["submitted"], s
    assert s["deadline_missed"] <= s["completed"], s

# 4. the kill cost nothing: every admitted job completed on the
#    survivor, the slot respawned, recovery was measured
k = warm["kill"]
ks = k["summary"]
assert ks["completed"] == ks["submitted"] and ks["shed"] == 0, ks
assert k["replica_restarts"] >= 1, k
assert k["replicas_alive_after"] == 2, k
assert k["recover_s"] is not None and k["recover_s"] < 30, k

# 5. distributed tracing stitched across processes: >=99% of the warm
#    load phase's completed requests rebuilt a full cross-process span
#    tree from the merged per-process streams
tr = pt["trace"]
assert tr is not None and tr["procs"] >= 3, tr   # router + 2 replicas
comp = tr["completeness"]
assert comp["n_completed"] > 0, comp
assert comp["fraction"] >= 0.99, \
    f"trace stitching below the 99% bar: {comp}"

# 6. the SIGKILLed replica left a crash flight record: the router's
#    parent-side frame ring dumped a blackbox (the worker itself
#    cannot flush through a SIGKILL)
assert k.get("blackbox_files"), \
    f"kill phase left no blackbox dump: {k.get('blackbox_files')}"
print("[smoke_serve_fleet] warm fleet OK:", pt["summary"]["completed"],
      "jobs, fleet steady compiles 0; kill:", ks["completed"], "/",
      ks["submitted"], "completed, recover", k["recover_s"], "s;",
      "traces", comp["n_complete_trees"], "/", comp["n_completed"],
      "complete, blackboxes", k["blackbox_files"])
EOF

# With --trace-dir the router stream is shadowed into the phase dir
# (next to the replica streams it merges with), so the fleet sections
# render from the per-phase directories, not the --metrics RunLog.
echo "[smoke_serve_fleet] fleet SLO + critical path from the warm phase dir" >&2
REPORT="$WORK/report_traces.txt"
python tools/obs_report.py "$TRACES/scale2x1" > "$REPORT"
grep -q "fleet SLO" "$REPORT" || {
    echo "[smoke_serve_fleet] FAIL: no fleet-SLO section in obs_report" >&2
    exit 1
}
grep -q "replica 0:" "$REPORT" || {
    echo "[smoke_serve_fleet] FAIL: no per-replica latency line" >&2
    exit 1
}
grep -q "critical path" "$REPORT" || {
    echo "[smoke_serve_fleet] FAIL: no critical-path section" >&2
    exit 1
}
grep -q "trace completeness" "$REPORT" || {
    echo "[smoke_serve_fleet] FAIL: no trace-completeness line" >&2
    exit 1
}

echo "[smoke_serve_fleet] replica lifecycle from the kill phase dir" >&2
KILLREPORT="$WORK/report_kill.txt"
python tools/obs_report.py "$TRACES/kill" > "$KILLREPORT"
grep -q "replica downs=" "$KILLREPORT" || {
    echo "[smoke_serve_fleet] FAIL: no replica-lifecycle line" >&2
    exit 1
}
echo "[smoke_serve_fleet] trace-overhead bench (armed vs disarmed)" >&2
PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" JAX_PLATFORMS=cpu \
    BENCH_TRACE_OVH_DURATION_S="${BENCH_TRACE_OVH_DURATION_S:-4}" \
    python - "$WORK/trace_overhead.json" <<'EOF'
import sys

import bench

out = bench.bench_trace_overhead(out_path=sys.argv[1])
arms = out["results"]
dis, arm = arms["disarmed"], arms["armed"]
# the tracing tax must be within run-to-run noise: the armed fleet
# keeps the disarmed throughput (generous 15% band for a loaded CI
# host) and does not grow the tail by more than scheduling jitter
assert out["value"] is not None and abs(out["value"]) <= 0.15, out
assert arm["p99_s"] <= dis["p99_s"] + 0.05, (arm, dis)
# and the armed arm's own streams must stitch: completeness >= 99%
comp = arm["trace_completeness"]
assert comp["n_completed"] > 0 and comp["fraction"] >= 0.99, comp
print("[smoke_serve_fleet] trace overhead OK: delta",
      f"{out['value'] * 100:+.2f}% jobs/s, p99",
      f"{dis['p99_s']}s -> {arm['p99_s']}s,",
      f"stitch {comp['fraction'] * 100:.1f}%")
EOF

echo "[smoke_serve_fleet] PASS (workdir $WORK)" >&2
