"""Paired hint-vs-no-hint statistics for the elasticnet seed sweeps.

VERDICT r2 weak#3 / next#5: the cross-seed medians (hint 2.038 vs no-hint
1.894 in ``results/enet_sweep_r2/robust_final.json``) do not say whether
the margin is real or seed noise.  This tool computes SAME-SEED paired
deltas of the spike-robust tail statistic (median score over the last
``--window`` episodes, matching the sweep's "robust final" definition) and
summarizes them with two exact nonparametric tests:

* sign test: #positive deltas ~ Binomial(n, 1/2) under H0;
* Wilcoxon signed-rank: exact null distribution over all 2^n sign
  assignments (n = 10 seeds -> 1024 terms, trivially enumerable).

Both are implemented inline (no scipy dependency) and two-sided.

Usage:
    python tools/enet_hint_stats.py results/enet_sweep_r2 [--window 100]
"""

import argparse
import collections
import itertools
import json
import os

import numpy as np


def robust_tail(scores, window):
    """Median of the last ``window`` episode scores (spike-robust)."""
    return float(np.median(np.asarray(scores[-window:])))


def sign_test_p(deltas):
    """Two-sided exact sign test (zeros dropped, standard practice)."""
    d = [x for x in deltas if x != 0.0]
    n, k = len(d), sum(1 for x in d if x > 0)
    if n == 0:
        return 1.0
    from math import comb
    tail = min(k, n - k)
    p = sum(comb(n, i) for i in range(tail + 1)) / 2 ** n * 2
    return min(1.0, p)


def wilcoxon_exact_p(deltas):
    """Two-sided Wilcoxon signed-rank p-value: exact enumeration of all
    2^n sign flips for n <= 20, normal approximation with continuity
    correction above (2^n blows up; the approximation is standard and
    accurate at those n)."""
    d = np.asarray([x for x in deltas if x != 0.0], np.float64)
    n = len(d)
    if n == 0:
        return 1.0
    # midranks for tied |d| (argsort-of-argsort would assign arbitrary
    # order-dependent ranks to ties, making the p-value input-order
    # dependent)
    absd = np.abs(d)
    order = np.argsort(absd, kind="stable")
    ranks = np.empty(n, np.float64)
    i = 0
    while i < n:
        j = i
        while j + 1 < n and absd[order[j + 1]] == absd[order[i]]:
            j += 1
        ranks[order[i:j + 1]] = (i + j) / 2.0 + 1.0
        i = j + 1
    w_pos = float(np.sum(ranks[d > 0]))
    mean_w = n * (n + 1) / 4.0
    obs_dev = abs(w_pos - mean_w)
    if n > 20:
        import math
        sd_w = math.sqrt(n * (n + 1) * (2 * n + 1) / 24.0)
        z = max(0.0, obs_dev - 0.5) / sd_w
        return float(min(1.0, math.erfc(z / math.sqrt(2.0))))
    count = 0
    total = 2 ** n
    for signs in itertools.product((0.0, 1.0), repeat=n):
        w = float(np.dot(signs, ranks))
        if abs(w - mean_w) >= obs_dev - 1e-12:
            count += 1
    return count / total


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("sweep_dir")
    p.add_argument("--window", type=int, default=100)
    p.add_argument("--out", default=None,
                   help="output json (default <sweep_dir>/paired_stats.json)")
    args = p.parse_args()

    runs = collections.defaultdict(list)   # (mode, seed) -> scores in order
    with open(os.path.join(args.sweep_dir, "scores.jsonl")) as fh:
        for ln in fh:
            r = json.loads(ln)
            runs[(r["mode"], r["seed"])].append((r["episode"], r["score"]))
    table = {}
    for (mode, seed), rows in runs.items():
        rows.sort()
        table[(mode, seed)] = robust_tail([s for _, s in rows], args.window)

    seeds = sorted({s for m, s in table if m == "hint"})
    paired = []
    for s in seeds:
        if ("nohint", s) in table:
            paired.append({"seed": s, "hint": table[("hint", s)],
                           "nohint": table[("nohint", s)],
                           "delta": table[("hint", s)]
                           - table[("nohint", s)]})
    deltas = [r["delta"] for r in paired]
    out = {
        "window": args.window,
        "n_pairs": len(paired),
        "pairs": paired,
        "median_delta": float(np.median(deltas)),
        "mean_delta": float(np.mean(deltas)),
        "n_positive": int(sum(1 for d in deltas if d > 0)),
        "sign_test_p_two_sided": sign_test_p(deltas),
        "wilcoxon_exact_p_two_sided": wilcoxon_exact_p(deltas),
        "cross_seed_median": {
            "hint": float(np.median([r["hint"] for r in paired])),
            "nohint": float(np.median([r["nohint"] for r in paired]))},
    }
    dst = args.out or os.path.join(args.sweep_dir, "paired_stats.json")
    with open(dst, "w") as fh:
        json.dump(out, fh, indent=1)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
