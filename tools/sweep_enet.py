"""Multi-seed hint/no-hint elasticnet SAC sweep (learning-curve evidence).

Reproduces the reference's reward-curve experiment (``elasticnet/do.sh:1-6``:
10 seeds x {hint, no-hint}) on the in-framework TPU driver and records the
artifacts BASELINE.md metric #3 (reward parity) is judged on:

* ``results/enet_sweep/scores.jsonl`` — one line per episode per run:
  {"mode", "seed", "episode", "score"}
* ``results/enet_sweep/summary.json`` — final 100-episode averages per run
* ``results/enet_sweep/learning_curves.png`` — mean +/- std moving average,
  hint vs no-hint (the repo's counterpart of figures/comparison.png)

The jitted episode function is built ONCE per mode and reused across seeds
(seeds only change PRNG keys and init, not the jaxpr), so the sweep pays two
compiles total instead of 2 x n_seeds.

Usage: python tools/sweep_enet.py [--seeds 10] [--episodes 1000] [--steps 5]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from smartcal_tpu.utils import enable_compilation_cache

enable_compilation_cache()

from smartcal_tpu.envs import enet
from smartcal_tpu.rl import replay as rp
from smartcal_tpu.rl import sac
from smartcal_tpu.train.enet_sac import make_episode_fn


def run_one(episode_fn, env_cfg, agent_cfg, seed, episodes, log):
    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    agent_state = sac.sac_init(k0, agent_cfg)
    buf = rp.replay_init(agent_cfg.mem_size,
                         rp.transition_spec(env_cfg.obs_dim, 2))
    scores = []
    for i in range(episodes):
        key, k = jax.random.split(key)
        agent_state, buf, score = episode_fn(agent_state, buf, k)
        scores.append(float(score))
        log(i, scores[-1])
    return scores


def moving_avg(xs, w=100):
    out = []
    for i in range(len(xs)):
        lo = max(0, i - w + 1)
        out.append(sum(xs[lo:i + 1]) / (i + 1 - lo))
    return out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seeds", default=10, type=int)
    p.add_argument("--episodes", default=1000, type=int)
    p.add_argument("--steps", default=5, type=int)
    p.add_argument("--outdir", default="results/enet_sweep")
    p.add_argument("--platform", default=None, choices=["cpu", "axon"],
                   help="force a JAX platform (the axon TPU plugin is "
                   "registered at interpreter start, so JAX_PLATFORMS=cpu "
                   "alone cannot select CPU)")
    p.add_argument("--plot-only", action="store_true",
                   help="regenerate plots/summary from existing scores.jsonl")
    args = p.parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    os.makedirs(args.outdir, exist_ok=True)
    jsonl_path = os.path.join(args.outdir, "scores.jsonl")
    if args.plot_only:
        make_plots(jsonl_path, args)
        return
    env_cfg = enet.EnetConfig(M=20, N=20)
    summary = []
    t_start = time.time()

    with open(jsonl_path, "w") as jf:
        for use_hint in (False, True):
            mode = "hint" if use_hint else "nohint"
            agent_cfg = sac.SACConfig(
                obs_dim=env_cfg.obs_dim, n_actions=2, gamma=0.99, tau=0.005,
                batch_size=64, mem_size=1024, lr_a=1e-3, lr_c=1e-3,
                reward_scale=20.0, alpha=0.03, use_hint=use_hint)
            episode_fn = make_episode_fn(env_cfg, agent_cfg, args.steps,
                                         use_hint)
            for seed in range(args.seeds):
                t0 = time.time()

                def log(i, s, mode=mode, seed=seed):
                    jf.write(json.dumps({"mode": mode, "seed": seed,
                                         "episode": i, "score": round(s, 4)})
                             + "\n")
                    if i % 200 == 0:
                        jf.flush()
                        print(f"[{time.time() - t_start:7.0f}s] {mode} "
                              f"seed {seed} episode {i} score {s:.2f}",
                              flush=True)

                scores = run_one(episode_fn, env_cfg, agent_cfg, seed,
                                 args.episodes, log)
                final = sum(scores[-100:]) / len(scores[-100:])
                summary.append({"mode": mode, "seed": seed,
                                "final_avg_100": round(final, 3),
                                "first_avg_100": round(
                                    sum(scores[:100]) / min(100, len(scores)),
                                    3),
                                "wall_s": round(time.time() - t0, 1)})
                print(f"DONE {mode} seed {seed}: final_avg {final:.2f} "
                      f"({summary[-1]['wall_s']}s)", flush=True)

    with open(os.path.join(args.outdir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=1)

    make_plots(jsonl_path, args)
    print("sweep complete:", json.dumps(summary[-1]))


def make_plots(jsonl_path, args):
    """Two-panel learning curves: mean +/- std of the per-seed moving
    average, AND the cross-seed MEDIAN curve.

    The median panel matters: the reward's eig-ratio term min(E)/max(E)
    (enetenv.py:149) occasionally explodes to ~-1e3 when max(E) ~ 0, and
    a single such episode drags a 100-episode mean by -10 — the mean
    curve is spike-dominated while the policy itself keeps producing
    normal scores (the spikes recover within a few episodes).
    """
    import numpy as np
    raw = {"hint": [], "nohint": []}
    with open(jsonl_path) as f:
        per_run = {}
        for line in f:
            r = json.loads(line)
            per_run.setdefault((r["mode"], r["seed"]), []).append(r["score"])
    for (mode, _), sc in sorted(per_run.items()):
        raw[mode].append(sc)

    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    fig, (ax, ax2) = plt.subplots(1, 2, figsize=(13, 5))
    robust = {}
    for mode, color in (("nohint", "tab:blue"), ("hint", "tab:orange")):
        if not raw[mode]:
            continue
        arr = np.asarray([moving_avg(sc) for sc in raw[mode]])
        mu, sd = arr.mean(axis=0), arr.std(axis=0)
        x = np.arange(arr.shape[1])
        ax.plot(x, mu, color=color, label=f"{mode} (n={arr.shape[0]})")
        ax.fill_between(x, mu - sd, mu + sd, color=color, alpha=0.2)
        med = np.median(np.asarray(raw[mode]), axis=0)
        med_ma = moving_avg(list(med))
        ax2.plot(x, med_ma, color=color, label=f"{mode} median")
        robust[mode] = round(float(np.mean(med_ma[-100:])), 3)
    for a, title in ((ax, "mean +/- std of per-seed moving averages"),
                     (ax2, "cross-seed median (spike-robust)")):
        a.set_xlabel("episode")
        a.set_ylabel("score (100-episode moving average)")
        a.set_title(title)
        a.legend()
    fig.suptitle(f"Elastic-net SAC: hint vs no-hint ({args.seeds} seeds)")
    fig.tight_layout()
    fig.savefig(os.path.join(args.outdir, "learning_curves.png"), dpi=120)
    with open(os.path.join(args.outdir, "robust_final.json"), "w") as f:
        json.dump(robust, f)
    print("robust final (median-curve tail):", json.dumps(robust))


if __name__ == "__main__":
    main()
