"""Multi-seed hint/no-hint CALIBRATION SAC learning-curve sweep.

VERDICT r3 item 3: CalibEnv (ADMM-rho tuning — the reference's core
workload, ``calibration/main_sac.py``) is the one capability with no
empirical learning demonstration in ``results/``.  This sweep drives the
REAL ``train.calib_sac`` episode loop (M=10 directions, 2M=20 actions,
batch 32, mem 10000, rewards > 1 scaled x10 — main_sac.py parity) at a
CPU-tractable backend tier and records per-episode JSONL in the
demix_curves format so ``tools/summarize_demix_curves.py`` aggregates it
unchanged (same paired statistics + plot).

Reference behavior to match: reward (sigma_data/sigma_res + influence
term) improves over ~50 games x 4 steps (``calibration/main_sac.py:8-21``,
``calibenv.py:170``).

Usage:
    python tools/sweep_calib.py --outdir results/calib_curves \
        [--seeds 5] [--episodes 120] [--light | --medium] [--platform cpu]

Cooperates with the chip-capture loop: between runs it waits on
``tools/wait_no_chip.sh`` so timed on-chip windows stay uncontended.
"""

import argparse
import json
import os
import subprocess
import sys
import time

TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(TOOLS))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seeds", default=5, type=int)
    p.add_argument("--episodes", default=120, type=int)
    p.add_argument("--steps", default=4, type=int)
    p.add_argument("--M", default=10, type=int)
    p.add_argument("--stations", default=14, type=int)
    p.add_argument("--npix", default=128, type=int)
    p.add_argument("--outdir", default="results/calib_curves")
    p.add_argument("--platform", default=None, choices=["cpu", "axon"])
    p.add_argument("--modes", default="nohint,hint")
    p.add_argument("--medium", action="store_true")
    p.add_argument("--light", action="store_true")
    p.add_argument("--seed0", default=0, type=int,
                   help="first seed (parallel shards of the sweep)")
    p.add_argument("--fixed_K", default=None, type=int,
                   help="pin the per-episode direction count in every "
                        "run of the sweep (variance reduction: the K "
                        "draw in [2, M] is a dominant reward-variance "
                        "source; the episode RNG stream is unchanged, "
                        "so skies stay same-seed comparable)")
    p.add_argument("--baseline_reward", action="store_true",
                   help="difference each step reward against the "
                        "episode's own reset-calibration reward "
                        "(demixing reward0 pattern) — removes the "
                        "episode-to-episode sky-draw variance component "
                        "from the hint/no-hint contrast")
    args = p.parse_args()

    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from smartcal_tpu.train import calib_sac
    from smartcal_tpu.utils import enable_compilation_cache

    enable_compilation_cache()

    os.makedirs(args.outdir, exist_ok=True)
    t_start = time.time()
    # seed-major order: a truncated sweep still has paired hint/no-hint
    # runs for every completed seed
    for seed in range(args.seed0, args.seed0 + args.seeds):
        for mode in args.modes.split(","):
            use_hint = mode == "hint"
            tag = f"{mode}_seed{seed}"
            dst = os.path.join(args.outdir, f"{tag}.jsonl")
            if os.path.exists(dst):
                print(f"skip {tag} (exists)", flush=True)
                continue
            # in-flight runs write <tag>.jsonl.partial and rename on
            # completion (VERDICT r4 item 8): a snapshot taken mid-run can
            # never be mistaken for a finished run, and a restarted sweep
            # re-runs rather than skips a truncated one
            part = dst + ".partial"
            if os.path.exists(part):
                os.remove(part)
            # yield to an active chip-capture window (single-core host);
            # package-anchored path: CWD- and __file__-independent
            import smartcal_tpu
            hook = os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.abspath(smartcal_tpu.__file__))),
                "tools", "wait_no_chip.sh")
            if os.path.isfile(hook):
                subprocess.run(["bash", hook], check=False)
            else:
                print(f"WARNING: chip-window hook missing at {hook}; "
                      "running without the yield", flush=True)
            t0 = time.time()
            argv = ["--seed", str(seed), "--episodes", str(args.episodes),
                    "--steps", str(args.steps), "--M", str(args.M),
                    "--stations", str(args.stations),
                    "--npix", str(args.npix),
                    "--prefix", os.path.join(args.outdir, f"{tag}_ck"),
                    "--metrics", part]
            if use_hint:
                argv.append("--use_hint")
            if args.medium:
                argv.append("--medium")
            if args.light:
                argv.append("--light")
            if args.fixed_K is not None:
                argv += ["--fixed_K", str(args.fixed_K)]
            if args.baseline_reward:
                argv.append("--baseline_reward")
            calib_sac.main(argv)
            os.rename(part, dst)
            print(f"[{time.time() - t_start:7.0f}s] DONE {tag} "
                  f"({time.time() - t0:.0f}s)", flush=True)


if __name__ == "__main__":
    main()
