"""Same-protocol, same-seed DDPG vs TD3 vs SAC elasticnet comparison.

VERDICT weak #5: the reference's headline figure
(``figures/comparison.png``) is a THREE-algorithm learning-curve
comparison on the elasticnet task, but ``results/`` only ever recorded
SAC sweeps.  This tool reproduces that figure's experiment on the
in-framework drivers under one protocol:

* identical ``EnetConfig(M, N)`` env, identical ``--steps``, identical
  replay capacity/batch (64/1024 — each driver's own main() protocol);
* identical SEED CHAIN: every algorithm consumes the same
  ``PRNGKey(seed)`` split sequence, so episode k of seed s draws the
  same problem instance for all three — the deltas are paired by
  construction;
* the jitted episode function is built ONCE per algorithm and reused
  across seeds (three compiles total, the sweep_enet.py pattern);
* no hint for any arm (the reference's comparison figure is the
  plain-task one; hint ablations live in sweep_enet.py).

Artifacts in ``--outdir`` (default ``results/enet_compare/``):

* ``scores.jsonl``     — {"algo", "seed", "episode", "score"} per line
* ``summary.json``     — per-run first/final 100-episode averages + wall
* ``paired_stats.json``— same-seed robust-tail deltas for each algorithm
  pair with the exact sign test + Wilcoxon signed-rank from
  tools/enet_hint_stats.py (spike-robust: median of the tail window)
* ``learning_curves.png`` — mean +/- std and cross-seed-median panels

Tool-only (not in tier-1).  Usage:
    python tools/enet_compare.py [--seeds 10] [--episodes 1000]
        [--steps 5] [--platform cpu]
"""

import argparse
import itertools
import json
import os
import sys
import time

TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(TOOLS))

import jax

from smartcal_tpu.utils import enable_compilation_cache

enable_compilation_cache()

from enet_hint_stats import robust_tail, sign_test_p, wilcoxon_exact_p
from smartcal_tpu import obs as smartcal_obs
from smartcal_tpu.envs import enet
from smartcal_tpu.rl import ddpg, replay as rp, sac, td3

ALGOS = ("ddpg", "td3", "sac")


def build_algo(name, env_cfg, steps):
    """(init_fn, episode_fn) for one algorithm under the shared protocol
    — each config mirrors its own train/enet_<algo>.py main()."""
    if name == "ddpg":
        from smartcal_tpu.train.enet_ddpg import make_episode_fn
        cfg = ddpg.DDPGConfig(obs_dim=env_cfg.obs_dim, n_actions=2,
                              batch_size=64, mem_size=1024)
        return (lambda k: ddpg.ddpg_init(k, cfg),
                make_episode_fn(env_cfg, cfg, steps))
    if name == "td3":
        from smartcal_tpu.train.enet_td3 import make_episode_fn
        cfg = td3.TD3Config(
            obs_dim=env_cfg.obs_dim, n_actions=2, gamma=0.99, tau=0.005,
            batch_size=64, mem_size=1024, lr_a=1e-3, lr_c=1e-3,
            update_actor_interval=2, warmup=100, noise=0.1,
            prioritized=True, use_hint=False, admm_rho=1.0)
        return (lambda k: td3.td3_init(k, cfg),
                make_episode_fn(env_cfg, cfg, steps, use_hint=False))
    if name == "sac":
        from smartcal_tpu.train.enet_sac import make_episode_fn
        cfg = sac.SACConfig(
            obs_dim=env_cfg.obs_dim, n_actions=2, gamma=0.99, tau=0.005,
            batch_size=64, mem_size=1024, lr_a=1e-3, lr_c=1e-3,
            reward_scale=20.0, alpha=0.03, use_hint=False)
        return (lambda k: sac.sac_init(k, cfg),
                make_episode_fn(env_cfg, cfg, steps, use_hint=False))
    raise ValueError(name)


def run_one(init_fn, episode_fn, env_cfg, mem_size, seed, episodes, log):
    """One (algo, seed) run on the SHARED key chain: the per-episode key
    depends only on (seed, episode), never the algorithm."""
    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    agent_state = init_fn(k0)
    buf = rp.replay_init(mem_size, rp.transition_spec(env_cfg.obs_dim, 2))
    scores = []
    for i in range(episodes):
        key, k = jax.random.split(key)
        agent_state, buf, score = episode_fn(agent_state, buf, k)
        scores.append(float(score))
        log(i, scores[-1])
    return scores


def paired_stats(tails, seeds, window):
    """Pairwise same-seed deltas (a - b) for every algorithm pair."""
    out = {"window": window, "pairs": {}}
    for a, b in itertools.combinations(ALGOS, 2):
        rows = [{"seed": s, a: tails[(a, s)], b: tails[(b, s)],
                 "delta": tails[(a, s)] - tails[(b, s)]}
                for s in seeds if (a, s) in tails and (b, s) in tails]
        deltas = [r["delta"] for r in rows]
        if not deltas:
            continue
        import numpy as np
        out["pairs"][f"{a}_minus_{b}"] = {
            "n_pairs": len(rows),
            "rows": rows,
            "median_delta": float(np.median(deltas)),
            "mean_delta": float(np.mean(deltas)),
            "n_positive": int(sum(1 for d in deltas if d > 0)),
            "sign_test_p_two_sided": sign_test_p(deltas),
            "wilcoxon_exact_p_two_sided": wilcoxon_exact_p(deltas),
        }
    return out


def moving_avg(xs, w=100):
    out = []
    for i in range(len(xs)):
        lo = max(0, i - w + 1)
        out.append(sum(xs[lo:i + 1]) / (i + 1 - lo))
    return out


def make_plots(jsonl_path, outdir, n_seeds):
    import numpy as np

    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    per_run = {}
    with open(jsonl_path) as f:
        for line in f:
            r = json.loads(line)
            per_run.setdefault((r["algo"], r["seed"]), []).append(r["score"])
    fig, (ax, ax2) = plt.subplots(1, 2, figsize=(13, 5))
    colors = {"ddpg": "tab:green", "td3": "tab:blue", "sac": "tab:orange"}
    for algo in ALGOS:
        runs = [sc for (a, _), sc in sorted(per_run.items()) if a == algo]
        if not runs:
            continue
        # an interrupted sweep leaves one run shorter than the rest;
        # truncate to the common prefix so --plot-only still recovers
        # curves from the usable data instead of crashing on the ragged
        # array
        n_min = min(len(sc) for sc in runs)
        if n_min < max(len(sc) for sc in runs):
            smartcal_obs.echo(f"{algo}: ragged runs, truncating curves "
                              f"to {n_min} episodes")
            runs = [sc[:n_min] for sc in runs]
        arr = np.asarray([moving_avg(sc) for sc in runs])
        mu, sd = arr.mean(axis=0), arr.std(axis=0)
        x = np.arange(arr.shape[1])
        ax.plot(x, mu, color=colors[algo],
                label=f"{algo.upper()} (n={arr.shape[0]})")
        ax.fill_between(x, mu - sd, mu + sd, color=colors[algo], alpha=0.15)
        med_ma = moving_avg(list(np.median(np.asarray(runs), axis=0)))
        ax2.plot(x, med_ma, color=colors[algo], label=f"{algo.upper()} median")
    for a, title in ((ax, "mean +/- std of per-seed moving averages"),
                     (ax2, "cross-seed median (spike-robust)")):
        a.set_xlabel("episode")
        a.set_ylabel("score (100-episode moving average)")
        a.set_title(title)
        a.legend()
    fig.suptitle(f"Elastic-net DDPG vs TD3 vs SAC ({n_seeds} seeds, "
                 "same-seed protocol)")
    fig.tight_layout()
    fig.savefig(os.path.join(outdir, "learning_curves.png"), dpi=120)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seeds", default=10, type=int)
    p.add_argument("--episodes", default=1000, type=int)
    p.add_argument("--steps", default=5, type=int)
    p.add_argument("--M", default=20, type=int)
    p.add_argument("--N", default=20, type=int)
    p.add_argument("--window", default=100, type=int,
                   help="tail window of the robust (median) statistic")
    p.add_argument("--outdir", default="results/enet_compare")
    p.add_argument("--platform", default=None, choices=["cpu", "axon"])
    p.add_argument("--plot-only", action="store_true",
                   help="regenerate plots/stats from existing scores.jsonl")
    args = p.parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    os.makedirs(args.outdir, exist_ok=True)
    jsonl_path = os.path.join(args.outdir, "scores.jsonl")
    env_cfg = enet.EnetConfig(M=args.M, N=args.N)
    t_start = time.time()

    if not args.plot_only:
        summary, tails = [], {}
        with open(jsonl_path, "w") as jf:
            for algo in ALGOS:
                init_fn, episode_fn = build_algo(algo, env_cfg, args.steps)
                for seed in range(args.seeds):
                    t0 = time.time()

                    def log(i, s, algo=algo, seed=seed):
                        jf.write(json.dumps(
                            {"algo": algo, "seed": seed, "episode": i,
                             "score": round(s, 4)}) + "\n")
                        if i % 200 == 0:
                            jf.flush()
                            smartcal_obs.echo(
                                f"[{time.time() - t_start:7.0f}s] {algo} "
                                f"seed {seed} episode {i} score {s:.2f}")

                    scores = run_one(init_fn, episode_fn, env_cfg, 1024,
                                     seed, args.episodes, log)
                    tails[(algo, seed)] = robust_tail(scores, args.window)
                    summary.append({
                        "algo": algo, "seed": seed,
                        "final_avg_100": round(
                            sum(scores[-100:]) / len(scores[-100:]), 3),
                        "first_avg_100": round(
                            sum(scores[:100]) / min(100, len(scores)), 3),
                        "robust_tail": round(tails[(algo, seed)], 3),
                        "wall_s": round(time.time() - t0, 1)})
                    smartcal_obs.echo(
                        f"DONE {algo} seed {seed}: "
                        f"final_avg {summary[-1]['final_avg_100']} "
                        f"({summary[-1]['wall_s']}s)")
        with open(os.path.join(args.outdir, "summary.json"), "w") as f:
            json.dump(summary, f, indent=1)
    else:
        tails = {}
        per_run = {}
        with open(jsonl_path) as f:
            for line in f:
                r = json.loads(line)
                per_run.setdefault((r["algo"], r["seed"]), []).append(
                    (r["episode"], r["score"]))
        for (algo, seed), rows in per_run.items():
            rows.sort()
            tails[(algo, seed)] = robust_tail([s for _, s in rows],
                                              args.window)

    stats = paired_stats(tails, sorted({s for _, s in tails}), args.window)
    with open(os.path.join(args.outdir, "paired_stats.json"), "w") as f:
        json.dump(stats, f, indent=1)
    make_plots(jsonl_path, args.outdir, args.seeds)
    smartcal_obs.emit_json({"outdir": args.outdir, "paired": {
        k: {kk: v[kk] for kk in ("median_delta", "n_pairs",
                                 "wilcoxon_exact_p_two_sided")}
        for k, v in stats["pairs"].items()}})


if __name__ == "__main__":
    main()
