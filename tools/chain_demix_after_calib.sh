#!/bin/bash
# Round-4 scheduler for the single CPU core: wait for the CalibEnv sweep
# (tools/sweep_calib.py) to finish, then run the harder-regime demixing
# hint pair (VERDICT r3 item 4) — K=6 with provide_influence image
# observations at npix=64 (npix=128 measured ~190 s/episode on this core,
# results/demix_curves_r4/README.md), one paired seed at 50 episodes.
# Both sweeps yield to chip-capture windows between runs.
set -uo pipefail
cd "$(dirname "$0")/.." || exit 1

while pgrep -f "tools/sweep_calib.py" > /dev/null; do sleep 120; done

SMARTCAL_CLEAR_EVERY=50 exec nice -n 19 python tools/sweep_demix.py \
  --light --provide_influence --npix 64 --K 6 --stations 14 \
  --seeds "${DEMIX_SEEDS:-2}" --episodes 50 --warmup 15 \
  --outdir results/demix_curves_r4 --platform cpu
