#!/bin/bash
# Round-3 chip-capture retry loop.  The axon tunnel is intermittently
# UNAVAILABLE (2026-07-31: server-side compiles run 10-25 min; the backend
# drops between/during long compiles), so each remaining capture retries in
# a FRESH process with a bounded timeout until its output artifact exists.
# Serialized — ONE TPU client at a time, and the host stays otherwise idle
# so timed sections are uncontended (bench.py's load_avg caveat).
#
#   bash tools/capture_r3.sh 2>&1 | tee -a /tmp/capture_r3.log
#
# Captures (skipping any whose artifact already validates):
#  1. results/calib_episode_r3.json   — N=62 calib episode wall-clock
#  2. results/host_seg_bench.json     — fused vs segmented at N=40
#  3. results/per_bench.json e2e TPU  — PER end-to-end train-step decision
#  4. results/bench_primary_r3.json   — clean uncontended primary re-run
#  5. results/bench_extras_r3.json    — on-chip batched + epblock extras
set -uo pipefail
cd "$(dirname "$0")/.." || exit 1
rm -f /tmp/bench_primary_r3.out /tmp/bench_extras_r3.out  # never promote stale prior-session runs

ATTEMPT_TIMEOUT=${ATTEMPT_TIMEOUT:-3000}   # 50 min: compiles alone can eat 25
MAX_ATTEMPTS=${MAX_ATTEMPTS:-12}           # dead-tunnel probes are cheap (~2.5 min)
HEAVY_MAX=${HEAVY_MAX:-4}                  # full attempts are not (up to 50 min each)
BACKOFF=${BACKOFF:-300}

# Healthy backend init is fast (<1 min observed); a sick tunnel hangs
# ~25-27 min and then fails UNAVAILABLE.  Gate every heavy attempt on a
# 150 s probe so dead-tunnel cycles cost ~2.5 min, not 27.  (Probe and
# attempt are sequential — never two TPU clients at once.)
tunnel_ok () {
  local p
  p=$(timeout --kill-after=15 150 python -c \
      "import jax; print(jax.devices()[0].platform)" 2>/dev/null | tail -1)
  [ "$p" = "axon" ] || [ "$p" = "tpu" ]
}

# Probe failures and heavy-attempt failures count SEPARATELY: probes are
# ~2.5 min (12 allowed), heavy attempts can burn ATTEMPT_TIMEOUT+BACKOFF
# each (4 allowed) — otherwise a tunnel that passes the probe but drops
# mid-capture could loop for ~11 h on one item.
try_capture () {
  local name="$1" check="$2"; shift 2
  local probes=0 heavies=0 rc
  if eval "$check"; then echo "[capture] $name: already done, skipping"; return 0; fi
  while [ "$probes" -lt "$MAX_ATTEMPTS" ] && [ "$heavies" -lt "$HEAVY_MAX" ]; do
    if ! tunnel_ok; then
      probes=$((probes + 1))
      echo "[capture] $name: probe $probes/$MAX_ATTEMPTS found tunnel dead ($(date -u +%H:%M:%S))"
      sleep "$BACKOFF"
      continue
    fi
    heavies=$((heavies + 1))
    echo "[capture] $name: attempt $heavies/$HEAVY_MAX ($(date -u +%H:%M:%S))"
    timeout --kill-after=30 "$ATTEMPT_TIMEOUT" "$@" && rc=0 || rc=$?
    if eval "$check"; then echo "[capture] $name: DONE"; return 0; fi
    echo "[capture] $name: attempt $heavies failed rc=$rc"
    sleep "$BACKOFF"
  done
  echo "[capture] $name: GAVE UP (probes=$probes heavies=$heavies)"
  return 1
}

# per_bench.json layout (tools/bench_per.py:250-254): {"measurements":
# [{"label": "<platform>_<ts>", "rows": [...], "e2e_rows": [...]}]}
tpu_e2e_done () {
  python - <<'EOF'
import json, sys
try:
    doc = json.load(open("results/per_bench.json"))
except Exception:
    sys.exit(1)
for m in doc.get("measurements", []):
    label = m.get("label", "")
    # labels get hand-renamed after landing (e.g. "round2_tpu_standalone"),
    # so match the platform anywhere in the label, not just the prefix
    if any(p in label for p in ("tpu", "axon")) and any(
            r.get("stage") == "e2e_train_step" for r in m.get("e2e_rows", [])):
        sys.exit(0)
sys.exit(1)
EOF
}

# host_seg_bench.json is a LIST of cases; success = a TPU-platform case
# whose host_segmented path produced a steady-state time (it runs after
# fused, so its presence means the session survived the whole case; fused
# may carry either steady_s or the recorded watchdog error — both are the
# evidence this capture exists to collect).
host_seg_done () {
  python - <<'EOF'
import json, sys
try:
    cases = json.load(open("results/host_seg_bench.json"))
except Exception:
    sys.exit(1)
if isinstance(cases, dict):
    cases = [cases]
for c in cases:
    if c.get("platform") in ("tpu", "axon") and \
            c.get("host_segmented", {}).get("steady_s") is not None:
        sys.exit(0)
sys.exit(1)
EOF
}

# The primary re-run writes its raw line to /tmp; validation + promotion to
# results/ happens HERE (not in the attempt command) so timeout signals
# python directly (exec) instead of an intermediate bash that would orphan
# a still-running TPU client into the next attempt.  Validation: no CPU
# fallback ("platform" key appears only then, and the probe is NOT forced
# so it really checks the device) AND uncontended (load < 1.2 — the whole
# point of the re-run; the chip-session number had load 1.5).
primary_done () {
  test -f results/bench_primary_r3.json && return 0
  python - <<'EOF'
import json, sys
try:
    with open("/tmp/bench_primary_r3.out") as fh:
        line = fh.readlines()[-1]
    out = json.loads(line)
except Exception:
    sys.exit(1)
if out.get("metric") != "enet_sac_env_steps_per_sec" or "platform" in out:
    sys.exit(1)          # "platform" key is only added on CPU fallback
if out.get("host_load_avg_1m", 9.9) >= 1.2:
    sys.exit(1)          # contended — not the clean number we came for
with open("results/bench_primary_r3.json", "w") as fh:
    json.dump(out, fh, indent=1)
sys.exit(0)
EOF
}

try_capture "calib_episode"  "test -f results/calib_episode_r3.json" \
  python tools/capture_calib_episode.py

try_capture "host_seg"       "host_seg_done" \
  python tools/bench_host_seg.py --stations 40 --nf 8 --admm 10

try_capture "per_e2e_tpu"    "tpu_e2e_done" \
  python tools/bench_per.py --e2e_iters 100

# extras validation: a TPU-platform run (no "platform" key) whose epblock
# extra carries a value
extras_done () {
  test -f results/bench_extras_r3.json && return 0
  python - <<'EOF'
import json, sys
try:
    with open("/tmp/bench_extras_r3.out") as fh:
        out = json.loads(fh.readlines()[-1])
except Exception:
    sys.exit(1)
if "platform" in out:
    sys.exit(1)          # CPU fallback
ep = [e for e in out.get("extra", [])
      if e.get("metric") == "enet_sac_env_steps_per_sec_epblock"
      and "value" in e]
if not ep:
    sys.exit(1)
with open("results/bench_extras_r3.json", "w") as fh:
    json.dump(out, fh, indent=1)
sys.exit(0)
EOF
}

# BENCH_SKIP_EXTRAS: primary ONLY — an extra that wedges after the primary
# was measured would discard it (the process gets timeout-killed before
# its single JSON line prints)
try_capture "primary_clean"  "primary_done" \
  bash -c 'exec env BENCH_SKIP_EXTRAS=1 BENCH_PROBE_ATTEMPTS=1 python bench.py > /tmp/bench_primary_r3.out 2>/tmp/bench_primary_r3.err'

try_capture "extras_tpu"     "extras_done" \
  bash -c 'exec env BENCH_SKIP_CALIB=1 BENCH_PROBE_ATTEMPTS=1 python bench.py > /tmp/bench_extras_r3.out 2>/tmp/bench_extras_r3.err'

echo "[capture] all done ($(date -u +%H:%M:%S))"
