#!/usr/bin/env python
"""Export a merged fleet run as a Chrome/Perfetto ``trace_event`` JSON.

Input: a fleet-run DIRECTORY of per-process RunLog streams (the
``--metrics-dir`` tree of tools/serve_fleet.py) or a single JSONL
stream.  The per-process streams are merged onto the router's clock via
the ``clock_offset`` handshake (smartcal_tpu/obs/collect.py), then:

* every ``span`` event becomes a complete slice (``ph: "X"``) on its
  process/thread track — span events record at EXIT, so the slice
  starts at ``t_corr - dur_s``;
* request lifecycle events (``fleet_dispatch`` / ``serve_admit`` /
  ``serve_request`` / ``fleet_result`` / ``serve_shed`` /
  ``ipc_corrupt_payload``) become instants (``ph: "i"``), and each
  traced request additionally gets a FLOW (``ph: "s"/"t"/"f"``, one id
  per trace) so the cross-process hop router -> replica -> router is
  drawn as an arrow in the UI;
* detector/recorder events (``slo_burn``, ``blackbox_flush``,
  ``watchdog_trip``, ``fault_injected``) become process-scoped
  instants — the incident markers on the timeline.

Open the output at ``ui.perfetto.dev`` or ``chrome://tracing``.

Usage:
    python tools/trace_export.py <fleet-dir | run.jsonl> [-o trace.json]

stdlib only.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _collect_mod():
    try:
        from smartcal_tpu.obs import collect
    except ImportError:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from smartcal_tpu.obs import collect
    return collect


# point events worth a timeline instant, with their display category
_INSTANTS = {
    "fleet_dispatch": "request",
    "serve_admit": "request",
    "serve_request": "request",
    "fleet_result": "request",
    "serve_shed": "incident",
    "fleet_reclaim": "incident",
    "ipc_corrupt_payload": "incident",
    "fleet_replica_down": "incident",
    "fleet_replica_failed": "incident",
    "fleet_replica_restart": "incident",
    "slo_burn": "detector",
    "blackbox_flush": "detector",
    "watchdog_trip": "detector",
    "fault_injected": "detector",
    "clock_offset": "detector",
}

# the request-flow phase each lifecycle event plays: s(tart) at the
# router's dispatch, t (step) at replica-side hops, f(inish) back at
# the router
_FLOW_PHASE = {"fleet_dispatch": "s", "serve_admit": "t",
               "serve_request": "t", "fleet_result": "f"}

_SKIP_ARG_KEYS = frozenset({"t", "t_corr", "proc", "event", "name",
                            "path", "dur_s", "thread"})


def load_events(path):
    """Merged, proc-tagged events from a directory or a single stream."""
    collect = _collect_mod()
    if os.path.isdir(path):
        return collect.merge_directory(path)
    proc, events, _bad = collect.read_stream([path])
    merger = collect.TimelineMerger()
    merger.add_stream(proc, events)
    return merger.merge()


def to_trace_events(events):
    """The ``traceEvents`` list (Chrome trace_event format)."""
    pids = {}
    tids = {}
    out = []

    def pid_of(proc):
        if proc not in pids:
            pids[proc] = len(pids) + 1
            out.append({"ph": "M", "name": "process_name",
                        "pid": pids[proc], "tid": 0,
                        "args": {"name": proc}})
        return pids[proc]

    def tid_of(proc, thread):
        key = (proc, thread)
        if key not in tids:
            tids[key] = len(tids) + 1
            out.append({"ph": "M", "name": "thread_name",
                        "pid": pid_of(proc), "tid": tids[key],
                        "args": {"name": thread}})
        return tids[key]

    t0 = min((e["t_corr"] for e in events if "t_corr" in e),
             default=0.0)

    def us(t):
        return round((float(t) - t0) * 1e6, 1)

    def args_of(e):
        return {k: v for k, v in e.items()
                if k not in _SKIP_ARG_KEYS and v is not None}

    for e in events:
        proc = str(e.get("proc", "?"))
        kind = e.get("event")
        t = e.get("t_corr", e.get("t"))
        if t is None:
            continue
        if kind == "span":
            dur = float(e.get("dur_s") or 0.0)
            out.append({"ph": "X", "name": str(e.get("name", "span")),
                        "cat": "span", "ts": us(float(t) - dur),
                        "dur": round(dur * 1e6, 1),
                        "pid": pid_of(proc),
                        "tid": tid_of(proc, str(e.get("thread", "main"))),
                        "args": args_of(e)})
        elif kind in _INSTANTS:
            rec = {"ph": "i", "name": str(kind),
                   "cat": _INSTANTS[kind], "ts": us(t), "s": "p",
                   "pid": pid_of(proc), "tid": 0, "args": args_of(e)}
            out.append(rec)
            tid_str = str(e.get("trace") or "")
            phase = _FLOW_PHASE.get(str(kind))
            if phase and tid_str:
                flow = {"ph": phase, "name": "request",
                        "cat": "request-flow",
                        "id": tid_str[:16], "ts": us(t),
                        "pid": pid_of(proc), "tid": 0}
                if phase == "f":
                    flow["bp"] = "e"
                out.append(flow)
    return out


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("path", help="fleet-run directory or one run JSONL")
    p.add_argument("-o", "--out", default="trace.json",
                   help="output trace_event JSON path")
    args = p.parse_args(argv)

    events = load_events(args.path)
    trace = to_trace_events(events)
    doc = {"traceEvents": trace, "displayTimeUnit": "ms"}
    with open(args.out, "w") as fh:
        json.dump(doc, fh)
    n_spans = sum(1 for e in trace if e.get("ph") == "X")
    n_flows = sum(1 for e in trace if e.get("cat") == "request-flow")
    print(f"wrote {args.out}: {len(trace)} trace events "
          f"({n_spans} slices, {n_flows} flow points) from "
          f"{len(events)} run events")
    return doc


if __name__ == "__main__":
    main()
