#!/bin/bash
# Outer restart loop for tools/capture_r3.sh: a single pass gives each
# capture a bounded probe/heavy budget, so an item that gave up early
# (e.g. calib at the head of the list) would never see a tunnel that
# recovers hours later.  This wrapper re-runs the pass until every
# artifact exists (done items are skipped instantly by their checks) or
# the wrapper is killed at session end.
set -uo pipefail
cd "$(dirname "$0")/.." || exit 1

all_done () {
  test -f results/calib_episode_r3.json || return 1
  test -f results/bench_primary_r3.json || return 1
  test -f results/bench_extras_r3.json  || return 1
  # host_seg + per_e2e validate inside capture_r3.sh; approximate here
  # with file presence (a pass re-runs them if their checks disagree)
  test -f results/host_seg_bench.json   || return 1
  return 0
}

pass=0
while true; do
  pass=$((pass + 1))
  echo "[forever] pass $pass ($(date -u +%H:%M:%S))"
  bash tools/capture_r3.sh
  if all_done; then echo "[forever] all artifacts captured"; break; fi
  sleep 120
done
