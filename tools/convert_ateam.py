"""Convert the reference's checked-in A-team sky models into the repo
fixture ``smartcal_tpu/data/ateam.{sky,cluster,rho}``.

Provenance: ``/root/reference/demixing/base.{sky,cluster,rho}`` — the
LOFAR A-team catalogue (CasA, CygA, HerA, TauA, VirA; 533 sources in 5
clusters) that ``generate_data.py:771-776`` concatenates with the
downloaded target model before real-data calibration.  The conversion goes
parse -> write through :mod:`smartcal_tpu.cal.skyio`, i.e. the fixture is
this framework's own serialization of the catalogue *data* (Q/U/V, SI1/SI2
and RM are zero for every row, verified below, so the 9-field writer is
lossless).

Run from the repo root (needs /root/reference present):
    python tools/convert_ateam.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from smartcal_tpu.cal import coords, skyio  # noqa: E402

REF = "/root/reference/demixing"
OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "smartcal_tpu", "data")


def main():
    sky = skyio.parse_sky_model(f"{REF}/base.sky")
    clusters = skyio.parse_cluster_file(f"{REF}/base.cluster")
    # base.rho is the 3-column 'id hybrid rho' variant (no spatial column)
    rho = np.asarray([float(ln.split()[2])
                      for ln in skyio._data_lines(f"{REF}/base.rho")])

    rows = []
    for _, names in clusters:
        for nm in names:
            f = sky[nm]
            # the 9-field writer drops Q/U/V, SI1/SI2, RM — assert they are
            # actually zero so the conversion is lossless
            assert np.all(f[[7, 8, 9, 11, 12, 13]] == 0.0), (nm, f)
            ra = coords.hms_to_rad(f[0], f[1], f[2])
            dec = coords.dms_to_rad(f[3], f[4], f[5])
            rows.append((nm, float(ra), float(dec), f[6], f[10],
                         f[14], f[15], f[16], f[17]))

    os.makedirs(OUT, exist_ok=True)
    skyio.write_sky_model(f"{OUT}/ateam.sky", rows)
    # keep cluster-file line order (CasA, CygA, HerA, TauA, VirA) with
    # sequential ids; the original ids 2..6 only existed to leave id 1 free
    # for the concatenated target cluster
    skyio.write_cluster_file(
        f"{OUT}/ateam.cluster",
        [(i + 1, names) for i, (_, names) in enumerate(clusters)])
    skyio.write_rho(f"{OUT}/ateam.rho", rho, 0.05 * rho,
                    ids=list(range(1, len(rho) + 1)))
    print(f"wrote {len(rows)} sources / {len(clusters)} clusters to {OUT}")


if __name__ == "__main__":
    main()
