"""Reward parity of the two influence-spectrum modes of ENetEnv.

VERDICT r1 weak #7: the env defaults to the on-device symmetrized
spectrum (``eigvalsh``) while the reference takes ``1+Re(eig)`` of the
nonsymmetric influence matrix; one-problem agreement was tested, but
reward equivalence OVER TRAINING was unshown.  This runs identical-seed
SAC training under both modes and compares the score trajectories.

The exact mode calls host ``numpy.linalg.eigvals`` through
``pure_callback`` — CPU/host only, which is exactly where this parity
evidence must come from anyway.

Usage: python tools/eig_mode_parity.py [--seeds 3] [--episodes 200]
Writes results/eig_parity/summary.json.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from smartcal_tpu.envs import enet
from smartcal_tpu.rl import replay as rp
from smartcal_tpu.rl import sac
from smartcal_tpu.train.enet_sac import make_episode_fn


def make_runner(mode, steps):
    """Compile once per mode; seeds only change keys/init (the same
    compile-once-per-mode pattern as tools/sweep_enet.py)."""
    env_cfg = enet.EnetConfig(M=20, N=20, eig_mode=mode)
    agent_cfg = sac.SACConfig(obs_dim=env_cfg.obs_dim, n_actions=2,
                              batch_size=64, mem_size=1024,
                              reward_scale=20.0, alpha=0.03)
    episode_fn = make_episode_fn(env_cfg, agent_cfg, steps, use_hint=False)

    def run(seed, episodes):
        key = jax.random.PRNGKey(seed)
        key, k0 = jax.random.split(key)
        st = sac.sac_init(k0, agent_cfg)
        buf = rp.replay_init(agent_cfg.mem_size,
                             rp.transition_spec(env_cfg.obs_dim, 2))
        scores = []
        for _ in range(episodes):
            key, k = jax.random.split(key)
            st, buf, score = episode_fn(st, buf, k)
            scores.append(float(score))
        return np.asarray(scores)

    return run


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seeds", default=3, type=int)
    p.add_argument("--episodes", default=200, type=int)
    p.add_argument("--steps", default=5, type=int)
    p.add_argument("--outdir", default="results/eig_parity")
    args = p.parse_args()
    os.makedirs(args.outdir, exist_ok=True)

    out = {"per_seed": []}
    t0 = time.time()
    run_sym = make_runner("symmetric", args.steps)
    run_ext = make_runner("exact", args.steps)
    for seed in range(args.seeds):
        sym = run_sym(seed, args.episodes)
        ext = run_ext(seed, args.episodes)
        w = min(100, len(sym))
        rec = {
            "seed": seed,
            "final_mean_symmetric": round(float(sym[-w:].mean()), 4),
            "final_mean_exact": round(float(ext[-w:].mean()), 4),
            "final_median_symmetric": round(float(np.median(sym[-w:])), 4),
            "final_median_exact": round(float(np.median(ext[-w:])), 4),
            # same-seed trajectories share env draws + agent init, so a
            # high rank correlation means the modes induce the same
            # learning signal episode by episode
            "spearman_rho": round(float(_spearman(sym, ext)), 4),
        }
        out["per_seed"].append(rec)
        print(json.dumps(rec), flush=True)
    out["wall_s"] = round(time.time() - t0, 1)
    meds_s = [r["final_median_symmetric"] for r in out["per_seed"]]
    meds_e = [r["final_median_exact"] for r in out["per_seed"]]
    out["median_final_symmetric"] = round(float(np.mean(meds_s)), 4)
    out["median_final_exact"] = round(float(np.mean(meds_e)), 4)
    with open(os.path.join(args.outdir, "summary.json"), "w") as f:
        json.dump(out, f, indent=1)
    print("DONE", json.dumps({k: v for k, v in out.items()
                              if k != "per_seed"}))


def _spearman(a, b):
    ra = np.argsort(np.argsort(a)).astype(np.float64)
    rb = np.argsort(np.argsort(b)).astype(np.float64)
    ra -= ra.mean()
    rb -= rb.mean()
    denom = np.sqrt((ra ** 2).sum() * (rb ** 2).sum())
    return float((ra * rb).sum() / max(denom, 1e-12))


if __name__ == "__main__":
    main()
