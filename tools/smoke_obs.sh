#!/bin/bash
# Observability smoke: record a tiny enet driver run with the full
# telemetry surface armed (--metrics --diag --watchdog), then aggregate
# it with obs_report --json and assert the machine document is non-empty
# and carries the training-health section.  Exercises the whole chain a
# CI box can run in ~1 min on CPU: RunLog schema-2 events (diag /
# replay_health / cost), the watchdog arming path, and the report's JSON
# contract — without asserting anything about learning itself.
#
#   bash tools/smoke_obs.sh [workdir]
#
# Exits non-zero on any broken link in the chain.
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1

REPO="$PWD"
WORK="${1:-$(mktemp -d /tmp/smoke_obs.XXXXXX)}"
RUN="$WORK/smoke_run.jsonl"
mkdir -p "$WORK"

echo "[smoke_obs] recording 2-episode enet_td3 run -> $RUN" >&2
# run from $WORK so the driver's checkpoint side-files land there
(cd "$WORK" && PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
    python -m smartcal_tpu.train.enet_td3 \
    --episodes 2 --steps 4 --metrics "$RUN" --diag --watchdog --quiet \
    > "$WORK/driver_stdout.json")

echo "[smoke_obs] aggregating with obs_report --json" >&2
python tools/obs_report.py "$RUN" --json --bootstrap 50 \
    > "$WORK/report.json"

echo "[smoke_obs] recording 1-episode calib_sac run (influence stage) -> " \
     "$WORK/smoke_calib.jsonl" >&2
CALIB="$WORK/smoke_calib.jsonl"
# the radio-backend driver: its episode loop is the one place the
# influence stage runs, so this is where the span + cost-analysis
# contract for the rewritten influence kernels is enforced
(cd "$WORK" && PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
    python -m smartcal_tpu.train.calib_sac \
    --small --episodes 1 --steps 1 --metrics "$CALIB" --diag --quiet)

python - "$CALIB" <<'EOF'
import json
import sys

events = [json.loads(ln) for ln in open(sys.argv[1]) if ln.strip()]
spans = [e for e in events if e["event"] == "span"]
inf_spans = [e for e in spans if e.get("name") == "influence"]
assert inf_spans, ("calib run emitted no 'influence' spans: "
                   f"{sorted({e.get('name') for e in spans})}")
assert all(e.get("route") for e in inf_spans), \
    f"influence spans missing route tag: {inf_spans[:2]}"
costs = [e for e in events if e["event"] == "cost"]
inf_costs = [e for e in costs if e.get("stage") == "influence"
             and not e.get("error")]
assert inf_costs, ("no successful influence cost-analysis event under "
                   f"--diag: {sorted({e.get('stage') for e in costs})} "
                   "— the roofline table would silently lose the "
                   "influence kernels")
# ISSUE 13: the memory-footprint accounting must ride on the cost
# events (peak live bytes per compile) and carry the precision-policy
# dtype tag, or the N-scaling report loses its memory column and the
# roofline quotes the wrong peak under bf16
fp = [e for e in inf_costs if e.get("peak_bytes")]
assert fp, f"influence cost events missing peak_bytes: {inf_costs[:2]}"
assert all(e.get("compute_dtype") in ("f32", "bf16") for e in fp), \
    f"influence cost events missing compute_dtype tag: {fp[:2]}"
print("[smoke_obs] influence OK:", len(inf_spans), "span(s), route",
      inf_spans[0].get("route") + ",", len(inf_costs), "cost event(s),",
      "peak_bytes", int(fp[0]["peak_bytes"]), "dtype",
      fp[0]["compute_dtype"])
EOF

echo "[smoke_obs] checking dtype-tagged roofline rows in the calib report" >&2
python tools/obs_report.py "$CALIB" --json --bootstrap 50 \
    > "$WORK/calib_report.json"
python - "$WORK/calib_report.json" <<'EOF'
import json
import sys

report = json.load(open(sys.argv[1]))
rl = (report["runs"][0] or {}).get("roofline") or {}
stages = rl.get("stages") or {}
assert "influence" in stages, f"roofline lost influence: {list(stages)}"
row = stages["influence"]
assert row.get("compute_dtype") in ("f32", "bf16", "mixed"), row
assert row.get("peak_bytes_max", 0) > 0, \
    f"roofline influence row missing footprint: {row}"
print("[smoke_obs] roofline OK: influence dtype", row["compute_dtype"],
      "peakMB", round(row["peak_bytes_max"] / 1e6, 1))
EOF

echo "[smoke_obs] recording 1-vector-episode batched calib_sac run -> " \
     "$WORK/smoke_batched.jsonl" >&2
BATCHED="$WORK/smoke_batched.jsonl"
# the batched-episode mode (--batch-envs): its solve/influence spans must
# carry the batched route tags + lane count, or the obs story silently
# loses the new hot path
(cd "$WORK" && PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
    python -m smartcal_tpu.train.calib_sac \
    --small --episodes 2 --steps 1 --batch-envs 2 --metrics "$BATCHED" \
    --quiet)

python - "$BATCHED" <<'EOF'
import json
import sys

events = [json.loads(ln) for ln in open(sys.argv[1]) if ln.strip()]
spans = [e for e in events if e["event"] == "span"]
solve = [e for e in spans if e.get("name") == "solve"
         and str(e.get("route", "")).startswith("batched")]
assert solve, ("batched run emitted no batched-route solve spans: "
               f"{[(e.get('name'), e.get('route')) for e in spans][:8]}")
assert all(e.get("lanes") == 2 for e in solve), solve[:2]
inf = [e for e in spans if e.get("name") == "influence"
       and str(e.get("route", "")).startswith("batched")]
assert inf, "batched run emitted no batched-route influence spans"
eps = [e for e in spans if e.get("name") == "episode"
       and e.get("lanes") == 2]
assert eps, "batched vector-episode spans missing the lane count"
print("[smoke_obs] batched OK:", len(solve), "solve +", len(inf),
      "influence batched-route span(s), route", solve[0]["route"])
EOF

python - "$RUN" "$WORK/report.json" <<'EOF'
import json
import sys

run_path, report_path = sys.argv[1], sys.argv[2]

events = [json.loads(ln) for ln in open(run_path) if ln.strip()]
kinds = {e["event"] for e in events}
for want in ("run_header", "episode", "diag", "replay_health", "cost",
             "run_end"):
    assert want in kinds, f"run JSONL missing {want!r} events: {kinds}"
header = events[0]
assert header["event"] == "run_header" and header["schema"] >= 2, header

report = json.load(open(report_path))
assert report.get("runs"), "obs_report --json produced no runs"
run = report["runs"][0]
th = run.get("training_health")
assert th and th.get("updates", 0) > 0, f"empty training_health: {th}"
assert run.get("roofline"), "missing roofline section"
assert "verdict" in (run.get("learning") or {}), "missing learning verdict"
end = [e for e in events if e["event"] == "run_end"][-1]
assert end["watchdog_tripped"] is False, "smoke run must not trip"
print("[smoke_obs] OK:", len(events), "events,",
      th["updates"], "updates,",
      len(run["roofline"]["stages"]), "costed stage(s)")
EOF
