#!/bin/bash
# Serving smoke, the restart-without-recompilation chain end to end:
#
# Phase 1 (COLD): serve_calib.py against a FRESH --cache-dir — warmup
# must BUILD every program (export-cache misses), persist them, and the
# server must actually complete jobs under open-loop load.
#
# Phase 2 (WARM): the same invocation against the SAME cache dir — a
# brand-new process must come up with every program deserialized
# (source == "cache", zero export-cache misses), serve with ZERO
# compile events in steady state, and the merged artifact must carry
# the cold-vs-warm ``restart`` section with a real warmup speedup.
#
# Then tools/obs_report.py over the warm run's RunLog must render the
# serving-SLO section (per-stage p50/p99, queue depth, and the
# "compiles in serving window: 0" line — the measured zero-recompile
# claim).
#
# The CI companion of smoke_fleet.sh / smoke_obs.sh; the cold export
# build dominates (~2-4 min on CPU), the warm phase is seconds.
#
#   bash tools/smoke_serve.sh [workdir]
#
# Exits non-zero on any broken link in the chain.
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1

REPO="$PWD"
WORK="${1:-$(mktemp -d /tmp/smoke_serve.XXXXXX)}"
CACHE="$WORK/cache"
OUT="$WORK/serve.json"
RUN_COLD="$WORK/serve_cold.jsonl"
RUN_WARM="$WORK/serve_warm.jsonl"
mkdir -p "$WORK"

serve() {  # serve <metrics.jsonl>  — one full server lifecycle
    (cd "$WORK" && PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
        JAX_PLATFORMS=cpu \
        python "$REPO/tools/serve_calib.py" \
        --tier tiny --M 3 --lanes 3 --rates 3 --duration 4 --pool 4 \
        --cache-dir "$CACHE" --metrics "$1" --out "$OUT" --quiet \
        > /dev/null)
}

echo "[smoke_serve] phase 1: COLD boot (fresh cache $CACHE)" >&2
serve "$RUN_COLD"

python - "$OUT" <<'EOF'
import json
import sys

doc = json.load(open(sys.argv[1]))
cold = doc["runs"][0]
w = cold["warmup"]
assert set(w["sources"].values()) == {"export"}, \
    f"cold warmup must BUILD every program: {w['sources']}"
assert w["export_cache_miss"] >= 2, w
served = sum(r["completed"] for r in cold["rates"])
assert served > 0, f"cold server completed no jobs: {cold['rates']}"
print("[smoke_serve] cold OK:", served, "jobs,",
      f"warmup {w['wall_s']}s, sources {w['sources']}")
EOF

echo "[smoke_serve] phase 2: WARM restart (same cache, new process)" >&2
serve "$RUN_WARM"

python - "$OUT" <<'EOF'
import json
import sys

doc = json.load(open(sys.argv[1]))
cold, warm = doc["runs"][0], doc["runs"][-1]
w = warm["warmup"]

# 1. every program deserialized — the restart never re-traced
assert set(w["sources"].values()) == {"cache"}, \
    f"warm restart must deserialize every program: {w['sources']}"
assert w["export_cache_miss"] == 0, w
assert w["export_cache_hit"] >= 2, w

# 2. zero compile events while serving (steady state)
assert warm["steady_compile_events"] == 0, \
    f"{warm['steady_compile_events']} compiles in warm steady state"
served = sum(r["completed"] for r in warm["rates"])
assert served > 0, f"warm server completed no jobs: {warm['rates']}"

# 3. the merged artifact carries the measured restart comparison
r = doc["restart"]
assert r["warm_warmup_s"] < r["cold_warmup_s"] / 5, \
    f"warm warmup not much faster than cold: {r}"
print("[smoke_serve] warm OK:", served, "jobs, warmup",
      f"{r['warm_warmup_s']}s vs cold {r['cold_warmup_s']}s",
      f"({r['speedup']}x), steady compiles 0")
EOF

echo "[smoke_serve] aggregating the warm RunLog with obs_report" >&2
REPORT="$WORK/report.txt"
python tools/obs_report.py "$RUN_WARM" > "$REPORT"
grep -q "serving SLO" "$REPORT" || {
    echo "[smoke_serve] FAIL: no serving-SLO section in obs_report" >&2
    exit 1
}
grep -q "p99" "$REPORT" || {
    echo "[smoke_serve] FAIL: no p99 line in the serving section" >&2
    exit 1
}
grep -q "compiles in serving window: 0" "$REPORT" || {
    echo "[smoke_serve] FAIL: compiles-in-serving-window not zero" >&2
    grep "compiles in serving" "$REPORT" >&2 || true
    exit 1
}
echo "[smoke_serve] PASS (workdir $WORK)" >&2
