#!/bin/bash
# Round-agnostic chip-capture pass (generalizes r3's capture_r3.sh; ADVICE
# r3 asked for no baked-in round names).  The axon tunnel is intermittently
# UNAVAILABLE (server-side compiles run 10-25 min; the backend drops
# between/during long compiles), so each remaining capture retries in a
# FRESH process with a bounded timeout until its output artifact validates
# (tools/chip_checks.py — shared with the forever wrapper).  Serialized —
# ONE TPU client at a time, and the host stays otherwise idle so timed
# sections are uncontended (bench.py's load_avg caveat).
#
#   CAPTURE_ROUND=r4 bash tools/capture_round.sh 2>&1 | tee -a /tmp/capture_r4.log
#
# Captures (skipping any whose artifact already validates):
#  1. results/calib_episode_${R}.json — N=62 calib episode wall-clock
#  2. results/host_seg_bench.json     — fused vs segmented at N=40 (chip case)
#  3. results/per_bench.json e2e TPU  — PER end-to-end train-step decision
#  4. results/bench_primary_${R}.json — clean uncontended primary
#  5. results/bench_extras_${R}.json  — on-chip batched + epblock extras
set -uo pipefail
cd "$(dirname "$0")/.." || exit 1

R=${CAPTURE_ROUND:-r4}
rm -f "/tmp/bench_primary_${R}.out" "/tmp/bench_extras_${R}.out"  # never promote stale prior-session runs

# never leave sweeps frozen or the window lock held if this pass dies
# between the STOP/CONT pair in try_capture.  INT/TERM are trapped
# explicitly: bash exits WITHOUT running an EXIT trap on an untrapped
# group SIGINT (the Ctrl-C case — verified on this host's bash 5.2)
SWEEP_PAT='python[^ ]* [^ ]*tools/sweep_(calib|demix)\.py'
TOUCHER=""
cleanup () {
  pkill -CONT -f "$SWEEP_PAT" 2>/dev/null
  # the lock-toucher subshell must die with us, or it would re-create
  # the lock every 300 s forever and freeze every cooperating sweep
  [ -n "$TOUCHER" ] && kill "$TOUCHER" 2>/dev/null
  rm -f /tmp/tpu_window.lock
}
trap 'cleanup' EXIT
trap 'cleanup; exit 130' INT TERM

ATTEMPT_TIMEOUT=${ATTEMPT_TIMEOUT:-3000}   # 50 min: compiles alone can eat 25
MAX_ATTEMPTS=${MAX_ATTEMPTS:-12}           # probe attempts per item (chip_probe.py)
HEAVY_MAX=${HEAVY_MAX:-4}                  # full attempts are not (up to 50 min each)
BACKOFF=${BACKOFF:-300}
PROBE_BUDGET=${PROBE_BUDGET:-3600}         # total probe backoff-sleep per item (s)

# Healthy backend init is fast (<1 min observed); a sick tunnel hangs
# ~25-27 min and then fails UNAVAILABLE.  Gate every heavy attempt on
# tools/chip_probe.py: bounded 150 s probes with exponential backoff +
# jitter under MAX_ATTEMPTS and a PROBE_BUDGET total-sleep bound — the
# replacement for the blind fixed-sleep loop that burned 87 dead probes
# in results/chip_attempts_r5.log.  Structured probe events (attempt /
# next_retry_s fields) land in results/chip_probe_${R}.jsonl.  (Probe
# and attempt are sequential — never two TPU clients at once.)
tunnel_wait () {
  python tools/chip_probe.py --attempts "$MAX_ATTEMPTS" \
      --budget "$PROBE_BUDGET" --base 60 \
      --metrics "results/chip_probe_${R}.jsonl"
}

# Probe exhaustion and heavy-attempt failures count SEPARATELY: the probe
# walk is bounded by MAX_ATTEMPTS/PROBE_BUDGET inside chip_probe.py,
# heavy attempts can burn ATTEMPT_TIMEOUT+BACKOFF each (4 allowed) —
# otherwise a tunnel that passes the probe but drops mid-capture could
# loop for ~11 h on one item.
try_capture () {
  local name="$1" check="$2"; shift 2
  local heavies=0 rc
  if eval "$check"; then echo "[capture] $name: already done, skipping"; return 0; fi
  while [ "$heavies" -lt "$HEAVY_MAX" ]; do
    if ! tunnel_wait; then
      echo "[capture] $name: probe budget exhausted, tunnel still dead ($(date -u +%H:%M:%S))"
      break
    fi
    heavies=$((heavies + 1))
    echo "[capture] $name: attempt $heavies/$HEAVY_MAX ($(date -u +%H:%M:%S))"
    # single-core host: hold the window lock so cooperating CPU jobs
    # (tools/wait_no_chip.sh between sweep units) pause during timed
    # sections, AND SIGSTOP any sweep mid-run — a sweep unit lasts up to
    # hours, so the between-units lock alone leaves a rare tunnel window
    # contended (the load<1.2 uncontended gate would waste it)
    touch /tmp/tpu_window.lock
    # re-touch the lock while the attempt runs: wait_no_chip.sh expires
    # stale locks by AGE, and a raised ATTEMPT_TIMEOUT would otherwise
    # outlive the fixed expiry and lose the window mid-capture (ADVICE
    # r4 item 4)
    ( while true; do sleep 300; touch /tmp/tpu_window.lock; done ) &
    TOUCHER=$!
    pkill -STOP -f "$SWEEP_PAT" 2>/dev/null || true
    timeout --kill-after=30 "$ATTEMPT_TIMEOUT" "$@" && rc=0 || rc=$?
    pkill -CONT -f "$SWEEP_PAT" 2>/dev/null || true
    kill "$TOUCHER" 2>/dev/null
    rm -f /tmp/tpu_window.lock
    if eval "$check"; then echo "[capture] $name: DONE"; return 0; fi
    echo "[capture] $name: attempt $heavies failed rc=$rc"
    sleep "$BACKOFF"
  done
  echo "[capture] $name: GAVE UP (heavies=$heavies)"
  return 1
}

try_capture "calib_episode"  "test -f results/calib_episode_${R}.json" \
  python tools/capture_calib_episode.py --out "results/calib_episode_${R}.json"

try_capture "host_seg"       "python tools/chip_checks.py host_seg" \
  python tools/bench_host_seg.py --stations 40 --nf 8 --admm 10

try_capture "per_e2e_tpu"    "python tools/chip_checks.py per_e2e" \
  python tools/bench_per.py --e2e_iters 100

# BENCH_SKIP_EXTRAS: primary ONLY — an extra that wedges after the primary
# was measured would discard the single end-of-process JSON line (the
# in-bench partial flush to /tmp is a second line of defense).  exec so
# timeout signals python directly instead of an intermediate bash that
# would orphan a still-running TPU client into the next attempt.
try_capture "primary_clean"  "python tools/chip_checks.py primary /tmp/bench_primary_${R}.out ${R}" \
  bash -c "exec env BENCH_SKIP_EXTRAS=1 BENCH_PROBE_ATTEMPTS=1 python bench.py > /tmp/bench_primary_${R}.out 2>/tmp/bench_primary_${R}.err"

try_capture "extras_tpu"     "python tools/chip_checks.py extras /tmp/bench_extras_${R}.out ${R}" \
  bash -c "exec env BENCH_SKIP_CALIB=1 BENCH_PROBE_ATTEMPTS=1 python bench.py > /tmp/bench_extras_${R}.out 2>/tmp/bench_extras_${R}.err"

# ISSUE 17: the composed-mesh arms (wall + per-axis footprint at
# N in {62, 256}) and the pallas-vs-blocked-XLA kernel rooflines at the
# full blocked tier — on TPU the pallas rows lower the real Mosaic
# kernels, which is the promotion-gate evidence (CPU interpreter rows
# are plumbing only; these two captures refuse/degrade accordingly)
try_capture "mesh_compose"   "test -s results/mesh_compose_${R}.json" \
  bash -c "exec python -c \"import bench; bench.bench_mesh_compose(out_path='results/mesh_compose_${R}.json')\""

try_capture "kernel_roofline" "test -s results/kernel_roofline_${R}.jsonl" \
  python tools/capture_kernel_roofline.py --stations 256 \
    --out "results/kernel_roofline_${R}.jsonl"

# optional (runs only after the five core captures): the solve-eval
# microbench — planes vs one-hot formulation of the inner cost+grad at
# N=62 on the chip (VERDICT r4 item 6 evidence; two variants only to
# bound server-side compiles per attempt)
try_capture "solve_eval_tpu" "python tools/chip_checks.py solve_eval" \
  python tools/bench_solve_eval.py --variants planes,onehot --repeat 30 \
    --out results/solve_eval_tpu.json

echo "[capture] pass complete ($(date -u +%H:%M:%S))"
