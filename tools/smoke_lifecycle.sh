#!/bin/bash
# Continuous-learning smoke, the serve->learn->hot-swap loop end to end:
#
# One tools/serve_learn.py window (CPU tiny tier): the server serves an
# open-loop Poisson stream, every completion tees into the sharded
# replay, the learner steps beside the server and publishes through the
# ExportCache — and the run must show
#
#   1. at least one policy hot-swap LANDED during the window,
#   2. ZERO compile events in the serving window (the learner's warmup
#      reached the sharding fixed point and pre-published, so neither
#      the learn step nor call_exported re-traces in steady state),
#   3. zero sheds attributable to a publication (swaps never push the
#      admission queue over),
#   4. unbroken trace continuity (no request lost its span tree), and
#   5. the learner actually learned from served traffic (ingested > 0,
#      learn steps > 0).
#
# Then tools/obs_report.py over the RunLog must render the lifecycle
# section (publishes/swaps + the per-version sigma_res table).
#
# The CI companion of smoke_serve.sh; the cold export build dominates
# (~2-4 min on CPU), the serving window itself is ~25 s.
#
#   bash tools/smoke_lifecycle.sh [workdir]
#
# Exits non-zero on any broken link in the chain.
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1

REPO="$PWD"
WORK="${1:-$(mktemp -d /tmp/smoke_lifecycle.XXXXXX)}"
CACHE="$WORK/cache"
OUT="$WORK/lifecycle.json"
RUN="$WORK/lifecycle.jsonl"
mkdir -p "$WORK"

echo "[smoke_lifecycle] serve+learn window (cache $CACHE)" >&2
(cd "$WORK" && PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
    JAX_PLATFORMS=cpu \
    python "$REPO/tools/serve_learn.py" \
    --tier tiny --M 3 --lanes 3 --rate 3 --duration 25 --pool 6 \
    --eval-pool 3 --eval-every-s 8 --publish-every 2 \
    --cache-dir "$CACHE" --metrics "$RUN" --out "$OUT" \
    > /dev/null)

python - "$OUT" <<'EOF'
import json
import sys

doc = json.load(open(sys.argv[1]))
srv, lc = doc["serving"], doc["lifecycle"]

assert lc["swaps"] >= 1, f"no hot-swap landed in the window: {lc['swaps']}"
assert srv["steady_compile_events"] == 0, \
    f"{srv['steady_compile_events']} compiles in the serving window"
assert lc["publication_sheds"] == 0, \
    f"{lc['publication_sheds']} sheds within 1 s of a swap"
assert srv["completed"] > 0, f"no jobs completed: {srv}"

tc = lc["trace_continuity"]
assert tc["continuous"], f"trace continuity broken: {tc}"

ln = lc["learner"]
assert ln["ingested"] > 0, f"tee fed the learner nothing: {ln}"
assert ln["learns"] > 0, f"learner never stepped: {ln}"
assert lc["p99_flat_across_swaps"], \
    f"p99 spiked across a swap: {lc['swap_p99_windows']}"

print("[smoke_lifecycle] OK:", srv["completed"], "jobs,",
      lc["swaps"], "swaps,", ln["ingested"], "transitions teed,",
      ln["learns"], "learn steps, publish p99",
      lc["publish_ms_p99"], "ms, steady compiles 0")
EOF

echo "[smoke_lifecycle] aggregating the RunLog with obs_report" >&2
REPORT="$WORK/report.txt"
python tools/obs_report.py "$RUN" > "$REPORT"
grep -q "lifecycle (online learning + hot-swap)" "$REPORT" || {
    echo "[smoke_lifecycle] FAIL: no lifecycle section in obs_report" >&2
    exit 1
}
grep -q "sigma_res by serving version" "$REPORT" || {
    echo "[smoke_lifecycle] FAIL: no per-version sigma_res table" >&2
    exit 1
}
grep -q "compiles in serving window: 0" "$REPORT" || {
    echo "[smoke_lifecycle] FAIL: compiles-in-serving-window not zero" >&2
    grep "compiles in serving" "$REPORT" >&2 || true
    exit 1
}
echo "[smoke_lifecycle] PASS (workdir $WORK)" >&2
