"""Live-tail a running RunLog JSONL as compact human lines.

``tail -f`` for the obs stream: follows the file as the run writes it
(RunLog buffers ~2 s of events, so lines arrive in small bursts),
survives size-based rotation (the writer ``os.replace``s the base path
and reopens it — the tailer re-stats the inode and follows the fresh
segment), and renders each event kind on one line:

    12:03:41 episode    #14 score=-0.0312 (mean10 -0.0298)
    12:03:41 diag       step=112 closs=0.031 cgrad=1.2e+00 q[-0.4,0.1,0.6]
    12:03:41 replay     entropy=0.98 max/mean=3.1 beta=0.43 filled=4096
    12:03:42 WATCHDOG   non_finite:critic_loss at update 113 (ring=32)

Fleet mode: point it at a DIRECTORY (a fleet run's ``--metrics-dir``
tree of per-process streams) and it tails every stream at once, merging
lines onto the router's clock via the ``clock_offset`` handshake events
and tagging each line with its process:

    [router  ] 12:03:41 fleet_dispatch {"job_id": 14, ...}
    [replica0] 12:03:41 serve_request  job=14 total=0.031s
    [replica1] 12:03:42 slo_burn       {"state": "firing", ...}

New per-replica generations appearing mid-run are picked up on the
next poll; ``blackbox_*`` crash dumps are excluded (different artifact
class — read those whole).

Usage:
    python tools/obs_tail.py <run.jsonl | fleet-dir>
        [--events diag,episode,...] [--no-follow] [--interval 0.5]

``--no-follow`` renders what is on disk and exits (scripting / tests).
stdlib only — runs anywhere, never touches jax or a device (the fleet
merge imports smartcal_tpu.obs.collect, itself stdlib-only).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _collect_mod():
    try:
        from smartcal_tpu.obs import collect
    except ImportError:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from smartcal_tpu.obs import collect
    return collect


def _ts(e):
    t = e.get("t")
    return (time.strftime("%H:%M:%S", time.localtime(t))
            if isinstance(t, (int, float)) else "--:--:--")


def _g(v, default="?"):
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v) if v is not None else default


def render_event(e):
    """One compact line for an event record, or None to skip it."""
    ev = e.get("event")
    ts = _ts(e)
    if ev == "run_header":
        meta = e.get("meta") or {}
        return (f"{ts} run        {e.get('run_id')} schema={e.get('schema')}"
                f" entry={meta.get('entry', '?')}"
                f" platform={e.get('platform', '?')}"
                + (f" (rotated {e['rotated']})" if e.get("rotated") else ""))
    if ev == "episode":
        extra = ""
        if isinstance(e.get("mean10"), (int, float)):
            extra = f" (mean10 {_g(e['mean10'])})"
        return (f"{ts} episode    #{e.get('episode', '?')} "
                f"score={_g(e.get('score'))}{extra}")
    if ev == "diag":
        q = [e.get("q_min"), e.get("q_mean"), e.get("q_max")]
        qs = ",".join("null" if v is None else f"{v:.3g}" for v in q)
        return (f"{ts} diag       step={e.get('step', '?')} "
                f"closs={_g(e.get('critic_loss'), 'null')} "
                f"cgrad={_g(e.get('critic_grad_norm'), 'null')} "
                f"agrad={_g(e.get('actor_grad_norm'), 'null')} "
                f"q[{qs}]")
    if ev == "replay_health":
        return (f"{ts} replay     "
                f"entropy={_g(e.get('priority_entropy'))} "
                f"max/mean={_g(e.get('max_mean_priority_ratio'))} "
                f"beta={_g(e.get('beta'))} filled={e.get('filled', '?')}")
    if ev == "watchdog_trip":
        return (f"{ts} WATCHDOG   {e.get('reason')} at update "
                f"{e.get('step')} (ring={len(e.get('ring') or [])})")
    if ev == "cost":
        if e.get("error"):
            return (f"{ts} cost       {e.get('stage')} FAILED: "
                    f"{e['error']}")
        return (f"{ts} cost       {e.get('stage')} "
                f"flops={_g(e.get('flops'))} "
                f"bytes={_g(e.get('bytes_accessed'))}")
    if ev == "roofline_peak":
        return (f"{ts} peak       {e.get('chip', e.get('platform'))} "
                f"fp32_est={_g(e.get('fp32_est'))}")
    if ev == "solver":
        return (f"{ts} solver     route={e.get('route', '?')} "
                f"admm={e.get('admm_iters', '?')} "
                f"lbfgs={e.get('lbfgs_iters_total', '?')}")
    if ev == "span":
        return (f"{ts} span       {e.get('path', e.get('name', '?'))} "
                f"{_g(e.get('dur_s'))}s")
    if ev == "run_end":
        return (f"{ts} run_end    episodes={e.get('episodes', '?')} "
                f"updates={e.get('updates', '?')} "
                f"tripped={e.get('watchdog_tripped', False)} "
                f"wall={_g(e.get('wall_s'))}s")
    if ev == "log":
        return f"{ts} log        {e.get('msg', '')}"
    # gauge / counters / jax_event / probe / anything future: terse
    return f"{ts} {str(ev):10s} " + json.dumps(
        {k: v for k, v in e.items() if k not in ("t", "event")})[:120]


def _emit_line(line, wanted, out):
    line = line.strip()
    if not line:
        return
    try:
        e = json.loads(line)
    except ValueError:
        return                          # mid-write partial line
    if wanted and e.get("event") not in wanted:
        return
    txt = render_event(e)
    if txt:
        out.write(txt + "\n")
        out.flush()


def tail(path, wanted=None, follow=True, interval=0.5, out=sys.stdout,
         max_iters=None):
    """Render ``path``'s events; with ``follow`` keep polling for growth
    and reopen when the writer rotates the file under us (inode change
    or truncation).  ``max_iters`` bounds the follow loop for tests."""
    fh, ino = None, None
    partial = ""
    iters = 0
    while True:
        if fh is None:
            try:
                fh = open(path)
                ino = os.fstat(fh.fileno()).st_ino
            except OSError:
                if not follow:
                    raise
                time.sleep(interval)
                continue
        chunk = fh.read()
        if chunk:
            buf = partial + chunk
            lines = buf.split("\n")
            partial = lines.pop()       # may be a half-written line
            for line in lines:
                _emit_line(line, wanted, out)
        else:
            if not follow:
                _emit_line(partial, wanted, out)
                return
            try:
                st = os.stat(path)
                if st.st_ino != ino or st.st_size < fh.tell():
                    # rotated (or truncated): drain anything the writer
                    # flushed to the old segment between our last read
                    # and the rename (the final burst can hold the
                    # watchdog_trip), then reopen the fresh file
                    last = fh.read()
                    if last:
                        for line in (partial + last).split("\n"):
                            _emit_line(line, wanted, out)
                    fh.close()
                    fh = None
                    partial = ""
                    continue
            except OSError:
                pass                    # transiently missing mid-rotate
            iters += 1
            if max_iters is not None and iters >= max_iters:
                return
            time.sleep(interval)


class _ProcTail:
    """Follow ONE per-process stream, yielding parsed event dicts.

    Same rotation handling as :func:`tail` (inode change / truncation
    reopens the base path after draining the old segment's tail), but
    events are returned to the fleet merger instead of printed, so the
    caller can order them across processes."""

    def __init__(self, proc, path):
        self.proc = proc            # display tag; upgraded to the
        self.path = path            # run_header run_id when seen
        self._fh = None
        self._ino = None
        self._partial = ""

    def _parse(self, lines):
        events = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except ValueError:
                continue            # mid-write partial line
            if isinstance(e, dict):
                if e.get("event") == "run_header" \
                        and isinstance(e.get("run_id"), str):
                    # the stream names itself (replica<rid>) — that is
                    # the name clock_offset events key their peer by
                    self.proc = e["run_id"]
                events.append(e)
        return events

    def poll(self):
        """Newly available events since the last poll (maybe empty)."""
        if self._fh is None:
            try:
                self._fh = open(self.path)
                self._ino = os.fstat(self._fh.fileno()).st_ino
            except OSError:
                return []
        chunk = self._fh.read()
        if chunk:
            buf = self._partial + chunk
            lines = buf.split("\n")
            self._partial = lines.pop()
            return self._parse(lines)
        try:
            st = os.stat(self.path)
            if st.st_ino != self._ino or st.st_size < self._fh.tell():
                last = self._fh.read()
                events = self._parse((self._partial + last).split("\n"))
                self._fh.close()
                self._fh = None
                self._partial = ""
                return events
        except OSError:
            pass                    # transiently missing mid-rotate
        return []

    def drain_tail(self):
        """Final flush of a trailing unterminated line (no-follow)."""
        return self._parse([self._partial]) if self._partial else []


def fleet_tail(directory, wanted=None, follow=True, interval=0.5,
               out=sys.stdout, max_iters=None):
    """Tail every stream under ``directory`` merged onto one clock.

    Each poll cycle rescans the directory (new replica generations
    appear as new files mid-run), reads what every stream grew, learns
    clock offsets from any ``clock_offset`` events seen so far, then
    emits the cycle's batch sorted by skew-corrected timestamp with a
    ``[proc]`` tag per line.  Ordering is exact within a cycle; across
    cycles it is as good as the poll interval — the offline merger
    (obs_report / trace_export on the same directory) is the ground
    truth."""
    collect = _collect_mod()
    tails = {}                      # base filename -> _ProcTail
    offsets = {}                    # proc -> seconds to ADD to its t
    iters = 0
    while True:
        for base, paths in collect.discover_streams(directory).items():
            if base in tails:
                continue
            t = _ProcTail(base.split(".jsonl")[0], paths[-1])
            # attach late: replay this stream's rotated history first
            for seg in paths[:-1]:
                try:
                    with open(seg) as fh:
                        t._history = t._parse(fh.read().split("\n"))
                except OSError:
                    t._history = []
            tails[base] = t
        batch = []
        for t in tails.values():
            events = getattr(t, "_history", []) + t.poll()
            t._history = []
            if not follow:
                events += t.drain_tail()
            for e in events:
                if e.get("event") == "clock_offset" \
                        and isinstance(e.get("peer"), str) \
                        and isinstance(e.get("offset_s"), (int, float)):
                    offsets[e["peer"]] = float(e["offset_s"])
                batch.append((t, e))
        width = max([8] + [len(t.proc) for t in tails.values()])
        batch.sort(key=lambda te: (
            (float(te[1]["t"]) if isinstance(te[1].get("t"), (int, float))
             else 0.0) + offsets.get(te[0].proc, 0.0)))
        for t, e in batch:
            if wanted and e.get("event") not in wanted:
                continue
            txt = render_event(e)
            if txt:
                out.write(f"[{t.proc:<{width}}] {txt}\n")
        out.flush()
        if not follow:
            return
        iters += 1
        if max_iters is not None and iters >= max_iters:
            return
        time.sleep(interval)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("path", help="RunLog JSONL path (the --metrics file "
                   "of a running driver) or a fleet --metrics-dir "
                   "directory of per-process streams")
    p.add_argument("--events", default=None,
                   help="comma-separated event kinds to show "
                        "(default: all)")
    p.add_argument("--no-follow", action="store_true",
                   help="render the current file content and exit")
    p.add_argument("--interval", type=float, default=0.5,
                   help="poll interval in seconds (default 0.5)")
    args = p.parse_args(argv)
    wanted = (set(args.events.split(",")) if args.events else None)
    fn = fleet_tail if os.path.isdir(args.path) else tail
    try:
        fn(args.path, wanted=wanted, follow=not args.no_follow,
           interval=args.interval)
    except KeyboardInterrupt:
        pass
    except BrokenPipeError:             # | head — exit quietly
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())


if __name__ == "__main__":
    main()
