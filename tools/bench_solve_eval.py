"""Micro-benchmark of the ADMM solver's inner evaluation unit.

VERDICT r4 item 6: the batched cost+grad eval is the dominant term of the
N=62 calibration stage (28 ms/eval measured on chip in the round-1
logical layout; `results/refscale_tpu.md`).  This tool times the exact
vmapped value_and_grad + line-search jvp units at reference scale under
each candidate formulation so layout work is measured, not guessed:

  * ``planes``    — the shipped `_chi2_planes` objective (operands in the
    solver's logical layout, planes transpose inside the cost fn — what
    the L-BFGS loop runs today)
  * ``pretrans``  — the same math with the coherency/data planes
    transposes HOISTED out of the eval (transposed operands prepared
    once, as a loop-invariant), isolating how much of the eval is layout
    shuffling rather than arithmetic

Usage:
    python tools/bench_solve_eval.py [--stations 62] [--nf 8] [--dirs 6] \
        [--repeat 30] [--platform cpu|axon] [--out results/solve_eval.json]

Emits one JSON dict with per-variant {value_and_grad_ms, jvp_ms} plus
shapes and platform.  Runs standalone on CPU; on the chip it is a
candidate for spare capture-loop time (cheap: a few compiles + seconds
of steady-state timing).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--stations", default=62, type=int)
    p.add_argument("--nf", default=8, type=int)
    p.add_argument("--dirs", default=6, type=int)
    p.add_argument("--ts", default=2, type=int)
    p.add_argument("--td", default=10, type=int)
    p.add_argument("--repeat", default=30, type=int)
    p.add_argument("--platform", default=None, choices=["cpu", "axon"])
    p.add_argument("--out", default=None)
    p.add_argument("--variants", default="planes,pretrans,onehot",
                   help="comma list; chip runs use planes,onehot to bound "
                   "the number of server-side compiles per attempt")
    args = p.parse_args()
    want = set(args.variants.split(","))

    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp
    import numpy as np

    from smartcal_tpu.cal import solver
    from smartcal_tpu.utils import enable_compilation_cache

    enable_compilation_cache()

    N, K, Nf, Ts, td = args.stations, args.dirs, args.nf, args.ts, args.td
    B = N * (N - 1) // 2
    cfg = solver.SolverConfig(n_stations=N, n_dirs=K, n_poly=3,
                              lbfgs_iters=8, init_iters=30, admm_iters=10)
    rng = np.random.default_rng(0)
    f32 = np.float32
    x = jnp.asarray(rng.normal(0, 0.3, (Nf, Ts, K * 2 * N * 2 * 2)), f32)
    d = jnp.asarray(rng.normal(0, 0.1, x.shape), f32)
    alpha = jnp.full((Nf, Ts), 0.3, f32)
    V5 = jnp.asarray(rng.normal(0, 1, (Nf, Ts, td, B, 2, 2, 2)), f32)
    C5 = jnp.asarray(rng.normal(0, 1, (Nf, Ts, K, td, B, 2, 2, 2)), f32)
    pr = jnp.asarray(rng.normal(0, 0.3, (Nf, Ts, K, 2 * N, 2, 2)), f32)
    hr = jnp.asarray(np.full(K, 2.5), f32)

    def time_fn(fn, *operands, rep=None):
        rep = rep or args.repeat
        out = fn(*operands)
        jax.block_until_ready(out)
        t0 = time.time()
        for _ in range(rep):
            out = fn(*operands)
        jax.block_until_ready(out)
        return (time.time() - t0) / rep * 1e3

    results = {
        "scale": f"N={N} B={B} Nf={Nf} Ts={Ts} td={td} K={K}",
        "platform": jax.devices()[0].platform,
        "repeat": args.repeat,
        "variants": {},
    }

    # --- planes: the shipped objective exactly as the L-BFGS loop sees it
    def vag_planes(xx, v, c, p, h):
        return jax.value_and_grad(
            lambda q: solver._cost_fn(q, v, c, p, h, cfg))(xx)

    def jvp_planes(xx, dd, aa, v, c, p, h):
        return jax.jvp(
            lambda a: solver._cost_fn(xx + a * dd, v, c, p, h, cfg),
            (aa,), (jnp.ones_like(aa),))

    vv = lambda f, ia: jax.jit(jax.vmap(jax.vmap(f, in_axes=ia),
                                        in_axes=ia))
    ia5 = (0, 0, 0, 0, None)
    ia7 = (0, 0, 0, 0, 0, 0, None)
    if "planes" in want:
        results["variants"]["planes"] = {
            "value_and_grad_ms": round(time_fn(
                vv(vag_planes, ia5), x, V5, C5, pr, hr), 3),
            "jvp_ms": round(time_fn(
                vv(jvp_planes, ia7), x, d, alpha, V5, C5, pr, hr), 3),
        }

    # --- pretrans: planes transposes hoisted out of the timed eval
    Cp = jnp.transpose(C5, (0, 1, 2, 5, 6, 7, 3, 4))  # (Nf,Ts,K,j,l,c,Tc,B)
    Vp = jnp.transpose(V5, (0, 1, 4, 5, 6, 2, 3))     # (Nf,Ts,i,m,c,Tc,B)
    Cp = jax.block_until_ready(Cp)
    Vp = jax.block_until_ready(Vp)

    def vag_pre(xx, vp, cp, p, h):
        return jax.value_and_grad(
            lambda q: solver._cost_fn_pretrans(q, vp, cp, p, h, cfg))(xx)

    def jvp_pre(xx, dd, aa, vp, cp, p, h):
        return jax.jvp(
            lambda a: solver._cost_fn_pretrans(xx + a * dd, vp, cp, p, h,
                                               cfg),
            (aa,), (jnp.ones_like(aa),))

    if "pretrans" in want and hasattr(solver, "_cost_fn_pretrans"):
        results["variants"]["pretrans"] = {
            "value_and_grad_ms": round(time_fn(
                vv(vag_pre, ia5), x, Vp, Cp, pr, hr), 3),
            "jvp_ms": round(time_fn(
                vv(jvp_pre, ia7), x, d, alpha, Vp, Cp, pr, hr), 3),
        }
        # parity: both formulations agree on the value
        if "planes" in want:
            v_a = vv(vag_planes, ia5)(x, V5, C5, pr, hr)[0]
            v_b = vv(vag_pre, ia5)(x, Vp, Cp, pr, hr)[0]
            results["parity_max_rel"] = float(
                jnp.max(jnp.abs(v_a - v_b) / (jnp.abs(v_a) + 1e-20)))

    # --- onehot: pretrans + matmul station expansion (scatter-free
    # backward — gathers transpose to scatter-adds, one-hot matmuls
    # transpose to matmuls)
    if "onehot" in want and hasattr(solver, "_cost_fn_onehot"):
        oh = solver._baseline_onehots(N)

        def vag_oh(xx, vp, cp, p, h):
            return jax.value_and_grad(
                lambda q: solver._cost_fn_onehot(q, vp, cp, oh, p, h,
                                                 cfg))(xx)

        def jvp_oh(xx, dd, aa, vp, cp, p, h):
            return jax.jvp(
                lambda a: solver._cost_fn_onehot(xx + a * dd, vp, cp, oh,
                                                 p, h, cfg),
                (aa,), (jnp.ones_like(aa),))

        results["variants"]["onehot"] = {
            "value_and_grad_ms": round(time_fn(
                vv(vag_oh, ia5), x, Vp, Cp, pr, hr), 3),
            "jvp_ms": round(time_fn(
                vv(jvp_oh, ia7), x, d, alpha, Vp, Cp, pr, hr), 3),
        }
        if "planes" in want:
            v_a = vv(vag_planes, ia5)(x, V5, C5, pr, hr)
            v_c = vv(vag_oh, ia5)(x, Vp, Cp, pr, hr)
            results["parity_onehot_val_max_rel"] = float(
                jnp.max(jnp.abs(v_a[0] - v_c[0])
                        / (jnp.abs(v_a[0]) + 1e-20)))
            results["parity_onehot_grad_max_rel"] = float(
                jnp.max(jnp.abs(v_a[1] - v_c[1]))
                / (float(jnp.max(jnp.abs(v_a[1]))) + 1e-20))

    # --- solve8: END-TO-END 8-iteration vmapped L-BFGS solve, jvp-probe
    # line search vs the exact-quartic phi (the production line search) —
    # measures what the formulation changes buy at the solve level, not
    # just per-eval
    if "solve8" in want and hasattr(solver, "_quartic_phi_maker"):
        from smartcal_tpu.ops import lbfgs as lb

        oh = solver._baseline_onehots(N)

        def solve_with(pm_builder):
            def one(xx, vp, cp, p):
                fun = lambda q: solver._cost_fn_onehot(q, vp, cp, oh, p,
                                                       hr, cfg)
                pm = pm_builder(vp, cp, p) if pm_builder else None
                r = lb.lbfgs_solve(fun, xx, max_iters=8,
                                   use_line_search=True, phi_maker=pm)
                return r.x, r.loss
            return jax.jit(jax.vmap(jax.vmap(one)))

        quartic = lambda vp, cp, p: solver._quartic_phi_maker(
            vp, cp, oh, p, hr, cfg)
        for name, builder in (("solve8_jvp_phi", None),
                              ("solve8_quartic_phi", quartic)):
            fn = solve_with(builder)
            ms = time_fn(fn, x, Vp, Cp, pr, rep=max(1, args.repeat // 10))
            loss = float(jnp.mean(fn(x, Vp, Cp, pr)[1]))
            results["variants"][name] = {
                "solve8_ms": round(ms, 1), "mean_loss": round(loss, 4)}

    print(json.dumps(results))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(results, fh, indent=1)


if __name__ == "__main__":
    main()
