#!/bin/bash
# One serialized TPU session (ONE client at a time — the axon tunnel wedges
# on concurrent backend init).  Run when the tunnel is up:
#   bash tools/chip_session.sh 2>&1 | tee /tmp/chip_session.log
# Captures, in order:
#  1. BENCH_r03 payload: bench.py (enet steps/s + batched + N=62 calib
#     episode wall-clock with per-stage breakdown)
#  2. PER end-to-end decision (tools/bench_per.py, elasticnet + demixing
#     obs scales)
#  3. Host-segmentation overhead at N=40 where fused + segmented both run
#     (tools/bench_host_seg.py)
set -uo pipefail
cd "$(dirname "$0")/.."

probe=$(timeout 150 python -c "import jax; print(jax.devices()[0].platform)" 2>/dev/null | tail -1)
if [ "$probe" != "axon" ] && [ "$probe" != "tpu" ]; then
  echo "TPU not reachable (probe: '$probe') — aborting chip session" >&2
  exit 1
fi

echo "=== 1. bench.py (BENCH_r03 payload) ==="
BENCH_PLATFORM=tpu python bench.py || echo "bench.py failed rc=$?"

echo "=== 2. PER end-to-end (elasticnet scale) ==="
python tools/bench_per.py --e2e_iters 100 || echo "bench_per failed rc=$?"

echo "=== 3. host-segmentation overhead (N=40, both paths on chip) ==="
python tools/bench_host_seg.py --stations 40 --nf 8 --admm 10 \
  || echo "bench_host_seg failed rc=$?"
echo "=== chip session complete ==="
