#!/bin/bash
# Outer restart loop for tools/capture_round.sh (round 4): a single pass
# gives each capture a bounded probe/heavy budget, so an item that gave up
# early (e.g. calib at the head of the list) would never see a tunnel that
# recovers hours later.  This wrapper re-runs the pass until EVERY check
# validates (done items are skipped instantly) or the wrapper is killed at
# session end.  Doneness uses the same tools/chip_checks.py predicates as
# the pass itself (ADVICE r3: the r3 wrapper approximated per_e2e/host_seg
# with file presence and could exit with the chip measurement missing).
set -uo pipefail
cd "$(dirname "$0")/.." || exit 1
export CAPTURE_ROUND=${CAPTURE_ROUND:-r4}

all_done () {
  test -f "results/calib_episode_${CAPTURE_ROUND}.json" || return 1
  test -f "results/bench_primary_${CAPTURE_ROUND}.json" || return 1
  test -f "results/bench_extras_${CAPTURE_ROUND}.json"  || return 1
  python tools/chip_checks.py host_seg || return 1
  python tools/chip_checks.py per_e2e  || return 1
  return 0
}

pass=0
while true; do
  pass=$((pass + 1))
  echo "[forever] pass $pass ($(date -u +%H:%M:%S))"
  bash tools/capture_round.sh
  if all_done; then echo "[forever] all artifacts captured"; break; fi
  sleep 120
done
