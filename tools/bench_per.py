"""Measure both PER designs (SURVEY.md §7 "PER on TPU").

Design A — HBM prefix-sum PER (rl.replay): priorities live on device, the
sum-tree walk is replaced by searchsorted(cumsum(p), v); store/sample fuse
into the jitted train step.

Design B — host-side native sum tree (rl.replay_native + native/sumtree.cc):
the reference's O(log n) pointer-chase in C++, storage in host numpy,
minibatch crosses to the device per learn step.

Run:  python tools/bench_per.py [--size 16384] [--batch 256]
      [--iters 200] [--cpu] [--e2e_obs_dim 420] [--skip_e2e]

Prints one JSON line per measurement plus summaries, and APPENDS a
platform-tagged entry to the measurement history in
results/per_bench.json (atomic replace; corrupt history is set aside as
.corrupt and restarted).
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_device(size, batch, iters, obs_dim=128, n_actions=4):
    import jax
    import jax.numpy as jnp

    from smartcal_tpu.rl import replay as rp

    spec = rp.transition_spec(obs_dim, n_actions)
    buf = rp.replay_init(size, spec)
    # fill in ONE batched dispatch (per-transition replay_add would copy
    # the whole buffer size times just for setup)
    trs = {k: jnp.zeros((size,) + shape, dtype)
           for k, (shape, dtype) in spec.items()}
    errors = jax.random.uniform(jax.random.PRNGKey(0), (size,))
    buf = jax.jit(rp.replay_add_batch)(buf, trs, errors=errors)
    jax.block_until_ready(buf.priority)

    @jax.jit
    def cycle(buf, key):
        """sample -> (pretend TD errors) -> priority update, one fused step."""
        k1, k2 = jax.random.split(key)
        batch_data, idx, is_w, buf = rp.replay_sample_per(buf, k1, batch)
        errors = jax.random.uniform(k2, (batch,))
        buf = rp.replay_update_priorities(buf, idx, errors)
        return buf, batch_data["state"].sum()

    key = jax.random.PRNGKey(1)
    buf, s = cycle(buf, key)   # compile
    jax.block_until_ready(s)
    t0 = time.time()
    for _ in range(iters):
        key, k = jax.random.split(key)
        buf, s = cycle(buf, k)
    jax.block_until_ready(s)
    dt = time.time() - t0
    return {"design": "device_prefix_sum", "size": size, "batch": batch,
            "iters": iters, "sample_update_us": round(dt / iters * 1e6, 1),
            "platform": jax.devices()[0].platform}


def bench_native(size, batch, iters, obs_dim=128, n_actions=4):
    from smartcal_tpu.rl import replay as rp
    from smartcal_tpu.rl.replay_native import NativePER

    spec = rp.transition_spec(obs_dim, n_actions)
    buf = NativePER(size, spec)
    rng = np.random.default_rng(0)
    tr = {k: np.zeros(shape, np.dtype(dtype))
          for k, (shape, dtype) in spec.items()}
    for _ in range(size):
        buf.store(tr, error=rng.random())

    t0 = time.time()
    for _ in range(iters):
        batch_data, idx, _ = buf.sample(batch, rng)
        buf.update_priorities(idx, rng.random(batch))
    dt = time.time() - t0
    return {"design": "native_sumtree", "size": size, "batch": batch,
            "iters": iters, "sample_update_us": round(dt / iters * 1e6, 1),
            "platform": "host"}


def _e2e_cfg(size, batch, obs_dim, n_actions):
    from smartcal_tpu.rl import sac

    return sac.SACConfig(obs_dim=obs_dim, n_actions=n_actions,
                         batch_size=batch, mem_size=size, prioritized=True,
                         error_clip=100.0)


def bench_e2e_device(size, batch, iters, obs_dim=420, n_actions=2):
    """Full train step, fused HBM design: one jitted
    sample + learn + priority-update (rl.sac.learn on a prioritized
    buffer) — the path every in-framework driver uses."""
    import jax
    import jax.numpy as jnp

    from smartcal_tpu.rl import replay as rp
    from smartcal_tpu.rl import sac

    cfg = _e2e_cfg(size, batch, obs_dim, n_actions)
    st = sac.sac_init(jax.random.PRNGKey(0), cfg)
    spec = rp.transition_spec(obs_dim, n_actions)
    buf = rp.replay_init(size, spec)
    trs = {k: jnp.zeros((size,) + shape, dtype)
           for k, (shape, dtype) in spec.items()}
    errors = jax.random.uniform(jax.random.PRNGKey(1), (size,))
    buf = jax.jit(rp.replay_add_batch)(buf, trs, errors=errors)

    step = jax.jit(lambda st, buf, k: sac.learn(cfg, st, buf, k))
    key = jax.random.PRNGKey(2)
    st, buf, m = step(st, buf, key)      # compile
    jax.block_until_ready(m["critic_loss"])
    t0 = time.time()
    for _ in range(iters):
        key, k = jax.random.split(key)
        st, buf, m = step(st, buf, k)
    jax.block_until_ready(m["critic_loss"])
    dt = time.time() - t0
    return {"design": "device_prefix_sum", "stage": "e2e_train_step",
            "size": size, "batch": batch, "iters": iters,
            "obs_dim": obs_dim,
            "train_step_us": round(dt / iters * 1e6, 1),
            "platform": jax.devices()[0].platform}


def bench_e2e_native(size, batch, iters, obs_dim=420, n_actions=2):
    """Full train step, host-tree design: NativePER.sample (C++ walk) ->
    jitted learn_from_batch on device -> host priority update from the
    returned TD errors — includes the host<->device hops the fused design
    avoids."""
    import jax
    import jax.numpy as jnp

    from smartcal_tpu.rl import replay as rp
    from smartcal_tpu.rl import sac
    from smartcal_tpu.rl.replay_native import NativePER

    cfg = _e2e_cfg(size, batch, obs_dim, n_actions)
    st = sac.sac_init(jax.random.PRNGKey(0), cfg)
    spec = rp.transition_spec(obs_dim, n_actions)
    buf = NativePER(size, spec)
    rng = np.random.default_rng(0)
    tr = {k: np.zeros(shape, np.dtype(dtype))
          for k, (shape, dtype) in spec.items()}
    for _ in range(size):
        buf.store(tr, error=rng.random())

    core = jax.jit(lambda st, b, w, k: sac.learn_from_batch(cfg, st, b, w, k))
    key = jax.random.PRNGKey(2)
    b, idx, w = buf.sample(batch, rng)
    st, m = core(st, {k: jnp.asarray(v) for k, v in b.items()},
                 jnp.asarray(w), key)    # compile
    jax.block_until_ready(m["critic_loss"])
    t0 = time.time()
    for _ in range(iters):
        key, k = jax.random.split(key)
        b, idx, w = buf.sample(batch, rng)
        st, m = core(st, {kk: jnp.asarray(v) for kk, v in b.items()},
                     jnp.asarray(w), k)
        buf.update_priorities(idx, np.asarray(m["td"]))
    jax.block_until_ready(m["critic_loss"])
    dt = time.time() - t0
    return {"design": "native_sumtree", "stage": "e2e_train_step",
            "size": size, "batch": batch, "iters": iters,
            "obs_dim": obs_dim,
            "train_step_us": round(dt / iters * 1e6, 1),
            "platform": "host+" + jax.devices()[0].platform}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=16384)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--e2e_obs_dim", type=int, default=420,
                    help="observation dim for the end-to-end train-step "
                         "benchmark (420 = elasticnet reference state; "
                         "use 16404 for the demixing CNN scale)")
    ap.add_argument("--e2e_iters", type=int, default=100)
    ap.add_argument("--skip_e2e", action="store_true")
    ap.add_argument("--cpu", action="store_true",
                    help="force the device design onto CPU")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    rows = [bench_native(args.size, args.batch, args.iters),
            bench_device(args.size, args.batch, args.iters)]
    for r in rows:
        print(json.dumps(r))
    ratio = rows[0]["sample_update_us"] / max(rows[1]["sample_update_us"],
                                              1e-9)
    summary = {"native_over_device_time_ratio": round(ratio, 3),
               "note": "ratio < 1 means the host tree is faster "
                       "(standalone sample+update; the device design "
                       "additionally fuses into the jitted train step)"}
    print(json.dumps(summary))

    e2e_rows, e2e_summary = [], None
    if not args.skip_e2e:
        e2e_rows = [
            bench_e2e_native(args.size, args.batch, args.e2e_iters,
                             obs_dim=args.e2e_obs_dim),
            bench_e2e_device(args.size, args.batch, args.e2e_iters,
                             obs_dim=args.e2e_obs_dim)]
        for r in e2e_rows:
            print(json.dumps(r))
        er = (e2e_rows[0]["train_step_us"]
              / max(e2e_rows[1]["train_step_us"], 1e-9))
        e2e_summary = {
            "native_over_device_time_ratio": round(er, 3),
            "winner": "device_prefix_sum" if er > 1 else "native_sumtree",
            "note": "FULL train step: sample + SAC learn + priority "
                    "update, on THIS platform.  The shipped default "
                    "(SACConfig.replay_backend='hbm') follows the "
                    "accelerator-regime winner; select 'native' "
                    "per-run on no-accelerator hosts."}
        print(json.dumps(e2e_summary))

    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "results", "per_bench.json")
    try:
        doc = {"measurements": []}
        if os.path.exists(out):
            try:
                with open(out) as f:
                    loaded = json.load(f)
                if isinstance(loaded, dict) and "measurements" in loaded:
                    doc = loaded
                elif isinstance(loaded, dict):   # pre-round-3 flat layout
                    doc = {"measurements": [{"label": "legacy", **loaded}]}
            except ValueError:
                # truncated/corrupt history: keep it aside, start fresh
                os.replace(out, out + ".corrupt")
        import jax

        doc["measurements"].append({
            "label": f"{jax.devices()[0].platform}"
                     f"_{time.strftime('%Y%m%d_%H%M')}",
            "rows": rows, "summary": summary,
            "e2e_rows": e2e_rows, "e2e_summary": e2e_summary})
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, out)     # atomic: no torn/lost history on kill
    except OSError:
        pass


if __name__ == "__main__":
    main()
