"""Measure both PER designs (SURVEY.md §7 "PER on TPU").

Design A — HBM prefix-sum PER (rl.replay): priorities live on device, the
sum-tree walk is replaced by searchsorted(cumsum(p), v); store/sample fuse
into the jitted train step.

Design B — host-side native sum tree (rl.replay_native + native/sumtree.cc):
the reference's O(log n) pointer-chase in C++, storage in host numpy,
minibatch crosses to the device per learn step.

Run:  PYTHONPATH=/root/repo:$PYTHONPATH python tools/bench_per.py
      [--size 16384] [--batch 256] [--iters 200] [--cpu]

Prints one JSON line per measurement plus a summary, and overwrites
results/per_bench.json (in-repo, cwd-independent) with the latest run.
"""

import argparse
import json
import os
import time

import numpy as np


def bench_device(size, batch, iters, obs_dim=128, n_actions=4):
    import jax
    import jax.numpy as jnp

    from smartcal_tpu.rl import replay as rp

    spec = rp.transition_spec(obs_dim, n_actions)
    buf = rp.replay_init(size, spec)
    # fill in ONE batched dispatch (per-transition replay_add would copy
    # the whole buffer size times just for setup)
    trs = {k: jnp.zeros((size,) + shape, dtype)
           for k, (shape, dtype) in spec.items()}
    errors = jax.random.uniform(jax.random.PRNGKey(0), (size,))
    buf = jax.jit(rp.replay_add_batch)(buf, trs, errors=errors)
    jax.block_until_ready(buf.priority)

    @jax.jit
    def cycle(buf, key):
        """sample -> (pretend TD errors) -> priority update, one fused step."""
        k1, k2 = jax.random.split(key)
        batch_data, idx, is_w, buf = rp.replay_sample_per(buf, k1, batch)
        errors = jax.random.uniform(k2, (batch,))
        buf = rp.replay_update_priorities(buf, idx, errors)
        return buf, batch_data["state"].sum()

    key = jax.random.PRNGKey(1)
    buf, s = cycle(buf, key)   # compile
    jax.block_until_ready(s)
    t0 = time.time()
    for _ in range(iters):
        key, k = jax.random.split(key)
        buf, s = cycle(buf, k)
    jax.block_until_ready(s)
    dt = time.time() - t0
    return {"design": "device_prefix_sum", "size": size, "batch": batch,
            "iters": iters, "sample_update_us": round(dt / iters * 1e6, 1),
            "platform": jax.devices()[0].platform}


def bench_native(size, batch, iters, obs_dim=128, n_actions=4):
    from smartcal_tpu.rl import replay as rp
    from smartcal_tpu.rl.replay_native import NativePER

    spec = rp.transition_spec(obs_dim, n_actions)
    buf = NativePER(size, spec)
    rng = np.random.default_rng(0)
    tr = {k: np.zeros(shape, np.dtype(dtype))
          for k, (shape, dtype) in spec.items()}
    for _ in range(size):
        buf.store(tr, error=rng.random())

    t0 = time.time()
    for _ in range(iters):
        batch_data, idx, _ = buf.sample(batch, rng)
        buf.update_priorities(idx, rng.random(batch))
    dt = time.time() - t0
    return {"design": "native_sumtree", "size": size, "batch": batch,
            "iters": iters, "sample_update_us": round(dt / iters * 1e6, 1),
            "platform": "host"}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=16384)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--cpu", action="store_true",
                    help="force the device design onto CPU")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    rows = [bench_native(args.size, args.batch, args.iters),
            bench_device(args.size, args.batch, args.iters)]
    for r in rows:
        print(json.dumps(r))
    ratio = rows[0]["sample_update_us"] / max(rows[1]["sample_update_us"],
                                              1e-9)
    summary = {"native_over_device_time_ratio": round(ratio, 3),
               "note": "ratio < 1 means the host tree is faster "
                       "(standalone sample+update; the device design "
                       "additionally fuses into the jitted train step)"}
    print(json.dumps(summary))
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "results", "per_bench.json")
    try:
        with open(out, "w") as f:
            json.dump({"rows": rows, "summary": summary}, f, indent=1)
    except OSError:
        pass


if __name__ == "__main__":
    main()
