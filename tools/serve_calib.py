#!/usr/bin/env python
"""Serve calibration jobs: warm up the AOT-exported CalibServer, drive
it with the synthetic open-loop load generator, and record the SLO
artifact.

One invocation is one server LIFECYCLE: warmup (export-cache load or
build — the cold/warm restart measurement), supervised serving under a
sweep of offered rates, teardown.  Results merge-append into ``--out``:
run it twice against the same ``--cache-dir`` and the artifact gains a
``restart`` section comparing the cold boot to the warm one (the
zero-recompile claim, measured).

    PYTHONPATH=. JAX_PLATFORMS=cpu python tools/serve_calib.py \
        --tier tiny --lanes 4 --rates 2,4,8 --duration 10 \
        --cache-dir /tmp/serve_cache --metrics /tmp/serve.jsonl \
        --out results/serve_r14.json

SLO telemetry rides the obs stream (``--metrics``): per-stage spans
(serve_pack/solve/influence), per-job ``serve_request`` events,
queue-depth/shed gauges and counters — aggregate with
``tools/obs_report.py`` (the "serving" section).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from smartcal_tpu import obs                               # noqa: E402
from smartcal_tpu.serve.loadgen import SERVE_TIERS as TIERS  # noqa: E402
from smartcal_tpu.train import blocks                      # noqa: E402


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--tier", choices=sorted(TIERS), default="tiny",
                   help="backend scale (tiny = the CPU test tier)")
    p.add_argument("--M", type=int, default=4,
                   help="max calibration directions (jobs carry k <= M)")
    p.add_argument("--lanes", type=int, default=4,
                   help="micro-batch width (BatchedEpisode lanes)")
    p.add_argument("--cache-dir", dest="cache_dir", required=True,
                   help="AOT export + XLA compilation cache root")
    p.add_argument("--rates", type=str, default="2,4",
                   help="comma list of offered rates (jobs/s) to sweep")
    p.add_argument("--duration", type=float, default=10.0,
                   help="seconds of offered load per rate")
    p.add_argument("--pool", type=int, default=8,
                   help="pre-built synthetic episodes cycled by the "
                        "load generator")
    p.add_argument("--pool-mode", dest="pool_mode",
                   choices=("mixed", "uniform"), default="mixed",
                   help="mixed (default): heterogeneous K/diffuse pool "
                        "drawn at random; uniform: the PR 15 "
                        "deterministic-cycle pool, for comparability "
                        "with results/serve_r14.json")
    p.add_argument("--max-wait-ms", dest="max_wait_ms", type=float,
                   default=50.0, help="micro-batch max wait")
    p.add_argument("--max-queue", dest="max_queue", type=int, default=32,
                   help="bounded admission queue depth (overload sheds)")
    p.add_argument("--deadline-ms", dest="deadline_ms", type=float,
                   default=None, help="per-job SLO deadline (deadline-"
                   "aware flush + deadline_miss accounting)")
    p.add_argument("--policy", action="store_true",
                   help="arm the exported policy head (fresh SAC actor): "
                        "jobs without pinned rho get theirs from it")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", type=str, default=None,
                   help="merge-append the run record into this JSON")
    blocks.add_obs_args(p)
    return p.parse_args(argv)


def make_policy(args, M, npix):
    from smartcal_tpu.rl import sac

    obs_dim = npix * npix + (M + 1) * 7
    agent = sac.SACAgent(sac.SACConfig(obs_dim=obs_dim, n_actions=2 * M),
                         seed=args.seed, name_prefix="serve")
    return agent.cfg, agent.state.actor_params


def main(argv=None):
    args = parse_args(argv)
    from smartcal_tpu.envs import radio
    from smartcal_tpu.serve import CalibServer, loadgen

    tobs = blocks.train_obs_from_args(args, "serve_calib",
                                      tier=args.tier, lanes=args.lanes)
    t_boot = time.time()
    # arm the persistent XLA cache BEFORE the first compile of the
    # process: jax latches the cache decision at first use, so a policy
    # head initialized ahead of CalibServer would silently un-arm it
    from smartcal_tpu.serve import enable_compile_cache
    enable_compile_cache(args.cache_dir)
    backend = radio.RadioBackend(**TIERS[args.tier])
    policy = (make_policy(args, args.M, backend.npix)
              if args.policy else None)
    srv = CalibServer(backend, M=args.M, lanes=args.lanes,
                      cache_dir=args.cache_dir, policy=policy,
                      max_wait_s=args.max_wait_ms / 1e3,
                      max_queue=args.max_queue)
    warm = srv.warmup(seed=args.seed)
    boot_s = round(time.time() - t_boot, 3)
    tobs.echo(f"server up in {boot_s}s (warmup {warm['wall_s']}s, "
              f"programs {warm['sources']})")

    pool = loadgen.build_job_pool(
        backend, args.M, args.pool, seed=args.seed + 1,
        heterogeneous=(args.pool_mode == "mixed"))
    srv.start()
    rates_out = []
    c_steady0 = obs.counters_snapshot()
    try:
        for rate in (float(r) for r in args.rates.split(",") if r):
            gen = loadgen.OpenLoopLoadGen(
                srv, pool, rate=rate, duration_s=args.duration,
                seed=args.seed,
                deadline_s=(args.deadline_ms / 1e3
                            if args.deadline_ms else None),
                maxiter_choices=(None, max(1, backend.admm_iters - 1),
                                 backend.admm_iters + 2),
                pick=("cycle" if args.pool_mode == "uniform"
                      else "random"))
            r = gen.run()
            r["stats"] = srv.stats()
            rates_out.append(r)
            tobs.echo(f"rate {rate}: " + json.dumps(r))
    finally:
        srv.stop()
    c_steady1 = obs.counters_snapshot()
    steady_compiles = (c_steady1.get("jax_compile_events", 0.0)
                      - c_steady0.get("jax_compile_events", 0.0))
    record = {
        "tier": args.tier, "M": args.M, "lanes": args.lanes,
        "policy": bool(args.policy), "pool_mode": args.pool_mode,
        "boot_s": boot_s,
        "warmup": warm,
        "rates": rates_out,
        "steady_compile_events": steady_compiles,
        "wall_s": round(time.time() - t_boot, 3),
    }
    obs.flush_counters()
    tobs.close()
    print(json.dumps(record, indent=1))
    if args.out:
        merge_out(args.out, record)
    if steady_compiles:
        print(f"WARNING: {steady_compiles:.0f} compile events in steady "
              "state (expected 0)", file=sys.stderr)
    return record


def merge_out(path, record):
    """Append ``record`` to the artifact's ``runs`` list; with >= 2 runs
    derive the cold-vs-warm ``restart`` section (run 0 is the cold boot,
    the last run the restarted server on the same cache)."""
    doc = {"bench": "serve_calib", "runs": []}
    if os.path.exists(path):
        with open(path) as fh:
            doc = json.load(fh)
    doc.setdefault("runs", []).append(record)
    runs = doc["runs"]
    if len(runs) >= 2:
        cold, warmr = runs[0], runs[-1]
        doc["restart"] = {
            "cold_boot_s": cold["boot_s"],
            "warm_boot_s": warmr["boot_s"],
            "cold_warmup_s": cold["warmup"]["wall_s"],
            "warm_warmup_s": warmr["warmup"]["wall_s"],
            "speedup": round(cold["warmup"]["wall_s"]
                             / max(1e-9, warmr["warmup"]["wall_s"]), 2),
            "warm_export_cache_hits":
                warmr["warmup"].get("export_cache_hit"),
            "warm_export_cache_misses":
                warmr["warmup"].get("export_cache_miss"),
            "warm_persistent_cache_misses":
                warmr["warmup"].get("persistent_cache_misses"),
        }
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1)
    os.replace(tmp, path)


if __name__ == "__main__":
    main()
