"""Summarize + plot the demixing hint/no-hint learning-curve sweep.

Reads ``results/demix_curves/{hint,nohint}_seed*.jsonl`` (one
``event=episode`` record per episode, written by
``smartcal_tpu.train.demix_sac --metrics``), writes
``results/demix_curves/summary.json`` and ``learning_curves.png``.

This is the demixing-workload counterpart of the elasticnet sweep
(results/enet_sweep*), reproducing the reference's reward-curve
comparison (demixing_rl/README.md:12-14 "hint agent shows increase in
reward indicating learning", figures/calibration_rewards.png).
"""

import argparse
import glob
import json
import os
import re
import sys

import numpy as np

DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "results", "demix_curves")


def load_runs(OUT):
    runs = {}
    for path in sorted(glob.glob(os.path.join(OUT, "*_seed*.jsonl"))):
        m = re.match(r"(hint|nohint)_seed(\d+)", os.path.basename(path))
        if not m:
            continue
        scores = []
        with open(path) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("event") == "episode":
                    scores.append(float(rec["score"]))
        if scores:
            runs[(m.group(1), int(m.group(2)))] = np.asarray(scores)
    return runs


def moving_avg(x, w=20):
    if len(x) < w:
        return np.asarray([np.mean(x)])
    c = np.cumsum(np.concatenate([[0.0], x]))
    return (c[w:] - c[:-w]) / w


def summarize(runs):
    """(per_run, aggregate, paired) summaries from {(mode, seed): scores}.

    Paired deltas TRUNCATE both arms of a seed to the shorter length so a
    run cut off by a round boundary compares like-for-like windows (the
    sweep READMEs rely on this; comparing a 150-episode tail against a
    90-episode tail would mix learning stages)."""
    summary = []
    for (mode, seed), sc in sorted(runs.items()):
        ma = moving_avg(sc)
        summary.append({
            "mode": mode, "seed": seed, "episodes": len(sc),
            "first20_mean": round(float(np.mean(sc[:20])), 4),
            "last20_mean": round(float(np.mean(sc[-20:])), 4),
            "max_moving_avg20": round(float(np.max(ma)), 4),
        })
    # cross-seed median of the final moving-average window, per mode
    agg = {}
    for mode in ("hint", "nohint"):
        tails = [np.mean(sc[-20:]) for (m, _), sc in runs.items()
                 if m == mode]
        starts = [np.mean(sc[:20]) for (m, _), sc in runs.items()
                  if m == mode]
        if tails:
            agg[mode] = {"median_last20": round(float(np.median(tails)), 4),
                         "median_first20": round(float(np.median(starts)), 4),
                         "n_runs": len(tails)}
    # same-seed paired deltas + exact tests (tools/enet_hint_stats.py
    # machinery) on BOTH the tail level and the learning speed
    paired = None
    seeds = sorted({s for (m, s) in runs if m == "hint"}
                   & {s for (m, s) in runs if m == "nohint"})
    if seeds:
        here = os.path.dirname(os.path.abspath(__file__))
        if here not in sys.path:   # summarize() is reusable — no dup spam
            sys.path.insert(0, here)
        from enet_hint_stats import sign_test_p, wilcoxon_exact_p

        def stats_of(fn):
            deltas = []
            for s in seeds:
                h, n = runs[("hint", s)], runs[("nohint", s)]
                ln = min(len(h), len(n))
                deltas.append(fn(h[:ln]) - fn(n[:ln]))
            return {"deltas": [round(float(d), 4) for d in deltas],
                    "median_delta": round(float(np.median(deltas)), 4),
                    "n_positive": int(sum(d > 0 for d in deltas)),
                    "sign_p": sign_test_p(deltas),
                    "wilcoxon_p": wilcoxon_exact_p(deltas)}

        paired = {
            "n_pairs": len(seeds),
            # final performance: median of the last quarter of episodes
            "tail_median": stats_of(
                lambda sc: float(np.median(sc[-max(20, len(sc) // 4):]))),
            # learning speed: mean over the whole run (area under the curve
            # — an agent that reaches the plateau earlier scores higher)
            "auc_mean": stats_of(lambda sc: float(np.mean(sc))),
        }
    return summary, agg, paired


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("sweep_dir", nargs="?", default=DEFAULT_DIR)
    OUT = ap.parse_args().sweep_dir
    runs = load_runs(OUT)
    if not runs:
        raise SystemExit(f"no runs found under {OUT}")
    summary, agg, paired = summarize(runs)
    with open(os.path.join(OUT, "summary.json"), "w") as f:
        json.dump({"per_run": summary, "aggregate": agg,
                   "paired": paired}, f, indent=1)
    print(json.dumps(agg))
    if paired:
        print("paired:", json.dumps(paired))

    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots(figsize=(8, 4.5))
        colors = {"hint": "tab:blue", "nohint": "tab:orange"}
        for (mode, seed), sc in sorted(runs.items()):
            ma = moving_avg(sc)
            ax.plot(np.arange(len(ma)), ma, color=colors[mode], alpha=0.35,
                    lw=0.8)
        for mode in colors:
            group = [moving_avg(sc) for (m, _), sc in runs.items()
                     if m == mode]
            if group:
                n = min(len(g) for g in group)
                med = np.median(np.stack([g[:n] for g in group]), axis=0)
                ax.plot(np.arange(n), med, color=colors[mode], lw=2.2,
                        label=f"{mode} (median of {len(group)})")
        ax.set_xlabel("episode")
        ax.set_ylabel("score (20-episode moving average)")
        ax.set_title("Demixing SAC: hint vs no-hint learning curves")
        ax.legend()
        fig.tight_layout()
        fig.savefig(os.path.join(OUT, "learning_curves.png"), dpi=120)
        print("wrote learning_curves.png")
    except Exception as e:  # matplotlib optional
        print(f"plot skipped: {type(e).__name__}: {e}")


if __name__ == "__main__":
    main()
