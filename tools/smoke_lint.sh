#!/bin/bash
# graftlint smoke (mirrors smoke_obs.sh/smoke_fleet.sh): prove the gate
# is both GREEN and ALIVE in one run —
#
#   1. lint the shipped tree against the checked-in baseline -> clean;
#   2. run the typed-core gate (--types: mypy when installed, else the
#      built-in annotation audit) -> clean;
#   3. inject a known-bad fixture into a scratch copy of a package
#      subtree -> the gate must CATCH it (non-zero exit, the seeded rule
#      in the --json findings);
#   4. touch a file in a scratch git repo -> --changed must lint exactly
#      the touched file (the pre-commit fast path).
#
#   bash tools/smoke_lint.sh [workdir]
#
# Exits non-zero on any broken link: a silently-green-on-bad-code linter
# is worse than no linter.
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1

REPO="$PWD"
WORK="${1:-$(mktemp -d /tmp/smoke_lint.XXXXXX)}"
mkdir -p "$WORK"

echo "[smoke_lint] 1/4 full gate over smartcal_tpu tools tests" >&2
python tools/lint.py smartcal_tpu tools tests > "$WORK/gate.txt"
echo "[smoke_lint] gate clean: $(tail -1 "$WORK/gate.txt")" >&2

echo "[smoke_lint] 2/4 typed-core gate (--types)" >&2
python tools/lint.py --types smartcal_tpu/analysis > "$WORK/types.txt"
echo "[smoke_lint] types clean: $(tail -1 "$WORK/types.txt")" >&2

echo "[smoke_lint] 3/4 seeded violation must be caught" >&2
SEED="$WORK/seeded"
rm -rf "$SEED"
mkdir -p "$SEED/smartcal_tpu"
cp smartcal_tpu/analysis/core.py "$SEED/smartcal_tpu/"   # innocent bystander
cp tests/fixtures/lint/rng_bad.py "$SEED/smartcal_tpu/injected.py"
set +e
python tools/lint.py --json --root "$SEED" smartcal_tpu \
    > "$WORK/seeded.json"
rc=$?
set -e
[ "$rc" -eq 1 ] || {
    echo "[smoke_lint] FAIL: seeded tree exited $rc (want 1)" >&2; exit 1; }
python - "$WORK/seeded.json" <<'EOF'
import json
import sys

doc = json.load(open(sys.argv[1]))
hits = [f for f in doc["findings"] if f["rule"] == "rng-key-reuse"
        and f["path"].endswith("injected.py")]
assert hits, f"seeded rng-key-reuse not caught: {doc['findings'][:3]}"
print(f"[smoke_lint] caught {len(hits)} seeded finding(s)",
      file=sys.stderr)
EOF

echo "[smoke_lint] 4/4 --changed lints exactly the touched file" >&2
CH="$WORK/changed_repo"
rm -rf "$CH"
mkdir -p "$CH"
(cd "$CH" \
 && git init -q \
 && git -c user.name=smoke -c user.email=s@s commit -q --allow-empty -m s)
cp tests/fixtures/lint/donation_bad.py "$CH/touched.py"
set +e
python tools/lint.py --changed --json --root "$CH" > "$WORK/changed.json"
rc=$?
set -e
[ "$rc" -eq 1 ] || {
    echo "[smoke_lint] FAIL: --changed exited $rc (want 1)" >&2; exit 1; }
python - "$WORK/changed.json" <<'EOF'
import json
import sys

doc = json.load(open(sys.argv[1]))
paths = {f["path"] for f in doc["findings"]}
assert paths == {"touched.py"}, paths
assert any(f["rule"] == "read-after-donation" for f in doc["findings"])
print("[smoke_lint] --changed scoped to the touched file", file=sys.stderr)
EOF

echo "[smoke_lint] OK: gate green, seeded violation caught, --changed scoped" >&2
