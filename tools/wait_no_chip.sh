#!/bin/bash
# Pause while a chip-capture heavy attempt holds the window lock
# (tools/capture_round.sh).  Long CPU jobs on this single-core host call
# this BETWEEN units (seeds, episodes-batches) so timed on-chip sections
# stay uncontended without any tighter coordination.  A stale lock (owner
# killed between touch and rm) expires after 60 min.
LOCK=/tmp/tpu_window.lock
while [ -f "$LOCK" ]; do
  # expire stale locks: heavy attempts are bounded at 50 min
  if [ -n "$(find "$LOCK" -mmin +60 2>/dev/null)" ]; then
    rm -f "$LOCK"; break
  fi
  sleep 60
done
