"""Aggregate obs run JSONLs into a human/machine report.

Input: one or more RunLog streams (``smartcal_tpu.obs.RunLog`` — train
drivers' ``--metrics``, ``SMARTCAL_OBS`` bench runs).  Rotated segments
(``run.jsonl.1`` ...) are picked up automatically when the base path is
given.  Output sections:

* **Per-stage time breakdown** — span events grouped by nesting path,
  rendered as a tree with total/mean/count and percent-of-parent, plus a
  coverage line (sum of a span's direct children vs the span itself: how
  much of the episode wall time the instrumentation attributes).
* **Episode throughput** — per run: episodes, wall span, episodes/min,
  score stats.
* **Chip-probe availability** — ``probe`` events (bench.probe_backend):
  ok/fail counts and the recorded errors, the structured record of "the
  tunnel failed N/N probes" that VERDICT r5 found missing.
* **Learning-curve verdict** — per run and pooled: least-squares slope of
  score vs episode with a bootstrap 95% CI (pairs resampling,
  deterministic seed), and a verdict: LEARNING (CI > 0), REGRESSING
  (CI < 0), or NO TREND.  This is the "the sweep cannot detect learning"
  gap: a flat curve and an improving one get different verdicts with
  quantified confidence.

Usage:
    python tools/obs_report.py run1.jsonl [run2.jsonl ...] [--json]
        [--bootstrap 1000] [--seed 0]

stdlib + numpy only — runs anywhere, never touches jax or a device.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import sys

import numpy as np


def load_run(path):
    """Read one run (base path + rotated siblings) -> dict of events."""
    paths = sorted(
        _glob.glob(path + ".[0-9]*"),
        key=lambda p: int(p.rsplit(".", 1)[1])) + [path]
    events, bad = [], 0
    for p in paths:
        try:
            fh = open(p)
        except OSError:
            continue
        with fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    bad += 1
    header = next((e for e in events if e.get("event") == "run_header"), {})
    return {"path": path, "run_id": header.get("run_id", os.path.basename(path)),
            "header": header, "events": events, "bad_lines": bad}


# ---------------------------------------------------------------------------
# Span aggregation
# ---------------------------------------------------------------------------

def span_tree(events):
    """{path: {n, total_s, mean_s}} over all span events."""
    agg = {}
    for e in events:
        if e.get("event") != "span" or "path" not in e:
            continue
        d = agg.setdefault(e["path"], {"n": 0, "total_s": 0.0})
        d["n"] += 1
        d["total_s"] += float(e.get("dur_s") or 0.0)
    for d in agg.values():
        d["mean_s"] = d["total_s"] / max(d["n"], 1)
    return agg

def children(agg, path):
    depth = path.count("/") + 1
    return {p: d for p, d in agg.items()
            if p.startswith(path + "/") and p.count("/") == depth}


def coverage(agg):
    """{parent_path: fraction of parent time attributed to child spans}."""
    out = {}
    for path, d in agg.items():
        ch = children(agg, path)
        if ch and d["total_s"] > 0:
            out[path] = sum(c["total_s"] for c in ch.values()) / d["total_s"]
    return out


def render_spans(agg, out):
    if not agg:
        out.append("  (no span events)")
        return
    cov = coverage(agg)
    roots = sorted(p for p in agg if "/" not in p)
    out.append(f"  {'stage':40s} {'count':>7s} {'total_s':>10s} "
               f"{'mean_s':>9s} {'%parent':>8s}")

    def walk(path, parent_total):
        d = agg[path]
        pct = (100.0 * d["total_s"] / parent_total
               if parent_total else 100.0)
        name = "  " * path.count("/") + path.rsplit("/", 1)[-1]
        line = (f"  {name:40s} {d['n']:>7d} {d['total_s']:>10.3f} "
                f"{d['mean_s']:>9.4f} {pct:>7.1f}%")
        if path in cov:
            line += f"   (children cover {100 * cov[path]:.1f}%)"
        out.append(line)
        for ch in sorted(children(agg, path)):
            walk(ch, d["total_s"])

    for r in roots:
        walk(r, None)


# ---------------------------------------------------------------------------
# Episodes + learning verdict
# ---------------------------------------------------------------------------

def episode_series(events):
    """(episode_idx[], score[]) from episode events, in record order."""
    eps, scores = [], []
    for e in events:
        if e.get("event") != "episode":
            continue
        s = e.get("score")
        if s is None or not np.isfinite(s):
            continue
        eps.append(int(e.get("episode", len(eps))))
        scores.append(float(s))
    return np.asarray(eps), np.asarray(scores)


def throughput(events):
    ts = [e["t"] for e in events if e.get("event") == "episode" and "t" in e]
    _, scores = episode_series(events)
    out = {"episodes": len(ts)}
    if len(ts) >= 2:
        wall = max(ts) - min(ts)
        out["wall_s"] = round(wall, 3)
        if wall > 0:
            out["episodes_per_min"] = round(60.0 * (len(ts) - 1) / wall, 3)
    if scores.size:
        out["score_mean"] = round(float(scores.mean()), 4)
        out["score_last10_mean"] = round(float(scores[-10:].mean()), 4)
    return out


def learning_verdict(eps, scores, n_boot=1000, seed=0, alpha=0.05):
    """Least-squares slope of score vs episode + bootstrap CI verdict.

    Pairs bootstrap: resample (episode, score) pairs with replacement,
    refit the slope, take the (alpha/2, 1-alpha/2) percentiles.  Verdict
    LEARNING only when the whole CI is positive — a flat noisy curve's CI
    straddles 0 and reads NO TREND, which is exactly the distinction the
    CalibEnv sweep analysis lacked."""
    n = len(scores)
    if n < 3 or np.ptp(eps) == 0:
        return {"verdict": "INSUFFICIENT DATA", "n": int(n)}
    slope, intercept = np.polyfit(eps, scores, 1)
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, n, size=(int(n_boot), n))
    slopes = np.empty(int(n_boot))
    for b, ix in enumerate(idx):
        x, y = eps[ix], scores[ix]
        if np.ptp(x) == 0:
            slopes[b] = 0.0
            continue
        slopes[b] = np.polyfit(x, y, 1)[0]
    lo, hi = np.percentile(slopes, [100 * alpha / 2, 100 * (1 - alpha / 2)])
    if lo > 0:
        verdict = "LEARNING"
    elif hi < 0:
        verdict = "REGRESSING"
    else:
        verdict = "NO TREND"
    return {"verdict": verdict, "n": int(n), "slope": float(slope),
            "intercept": float(intercept),
            "slope_ci95": [float(lo), float(hi)], "bootstrap": int(n_boot)}


# ---------------------------------------------------------------------------
# Probes / solver
# ---------------------------------------------------------------------------

def probe_summary(events):
    probes = [e for e in events if e.get("event") == "probe"]
    if not probes:
        return None
    ok = sum(1 for e in probes if e.get("ok"))
    errors = sorted({str(e.get("error")) for e in probes
                     if not e.get("ok") and e.get("error")})
    return {"total": len(probes), "ok": ok, "failed": len(probes) - ok,
            "availability": round(ok / len(probes), 4), "errors": errors}


def solver_summary(events):
    recs = [e for e in events if e.get("event") == "solver"]
    if not recs:
        return None
    by_route = {}
    for e in recs:
        d = by_route.setdefault(e.get("route", "?"),
                                {"solves": 0, "admm_iters": 0,
                                 "lbfgs_iters": 0, "segments": 0,
                                 "final_resid": []})
        d["solves"] += 1
        d["admm_iters"] += int(e.get("admm_iters") or 0)
        d["lbfgs_iters"] += int(e.get("lbfgs_iters_total") or 0)
        d["segments"] += int(e.get("n_segments") or 0)
        pr = [v for v in (e.get("primal_resid") or []) if v]
        if pr:
            d["final_resid"].append(pr[-1])
    for d in by_route.values():
        fr = d.pop("final_resid")
        if fr:
            d["final_consensus_resid_mean"] = round(float(np.mean(fr)), 6)
    return by_route


# ---------------------------------------------------------------------------
# Report
# ---------------------------------------------------------------------------

def build_report(runs, n_boot=1000, seed=0):
    report = {"runs": []}
    all_pairs = []
    for run in runs:
        ev = run["events"]
        eps, scores = episode_series(ev)
        all_pairs.append((eps, scores))
        compiles = [e for e in ev if e.get("event") == "jax_event"]
        spans = span_tree(ev)
        r = {"path": run["path"], "run_id": run["run_id"],
             "entry": (run["header"].get("meta") or {}).get("entry"),
             "platform": run["header"].get("platform"),
             "bad_lines": run["bad_lines"],
             "spans": spans,
             "coverage": coverage(spans),
             "throughput": throughput(ev),
             "learning": learning_verdict(eps, scores, n_boot, seed),
             "probes": probe_summary(ev),
             "solver": solver_summary(ev),
             "compile_events": len(compiles),
             "compile_secs": round(sum(float(e.get("dur_s") or 0)
                                       for e in compiles), 3)}
        report["runs"].append(r)
    if len(runs) > 1:
        eps = np.concatenate([p[0] for p in all_pairs])
        scores = np.concatenate([p[1] for p in all_pairs])
        report["pooled_learning"] = learning_verdict(eps, scores, n_boot,
                                                     seed)
    return report


def render(report):
    out = []
    for r in report["runs"]:
        out.append(f"== run {r['run_id']}  ({r['path']})")
        meta = [f"entry={r['entry']}" if r.get("entry") else None,
                f"platform={r['platform']}" if r.get("platform") else None,
                f"bad_lines={r['bad_lines']}" if r["bad_lines"] else None]
        meta = [m for m in meta if m]
        if meta:
            out.append("  " + "  ".join(meta))
        out.append("-- per-stage time breakdown")
        render_spans(r["spans"], out)
        out.append("-- episode throughput")
        if r["throughput"].get("episodes"):
            out.append("  " + "  ".join(f"{k}={v}" for k, v
                                        in r["throughput"].items()))
        else:
            out.append("  (no episode events)")
        if r["probes"]:
            p = r["probes"]
            out.append("-- chip-probe availability")
            out.append(f"  {p['ok']}/{p['total']} ok "
                       f"(availability {100 * p['availability']:.1f}%)")
            for err in p["errors"]:
                out.append(f"  failure: {err}")
        if r["solver"]:
            out.append("-- solver telemetry")
            for route, d in sorted(r["solver"].items()):
                out.append(f"  route={route}  " + "  ".join(
                    f"{k}={v}" for k, v in d.items()))
        if r["compile_events"]:
            out.append(f"-- jax compile: {r['compile_events']} events, "
                       f"{r['compile_secs']} s")
        lv = r["learning"]
        out.append("-- learning-curve verdict")
        if "slope" in lv:
            lo, hi = lv["slope_ci95"]
            out.append(f"  {lv['verdict']}  slope={lv['slope']:.5g} "
                       f"per episode, 95% CI [{lo:.5g}, {hi:.5g}] "
                       f"(n={lv['n']}, bootstrap={lv['bootstrap']})")
        else:
            out.append(f"  {lv['verdict']} (n={lv.get('n', 0)})")
        out.append("")
    if "pooled_learning" in report:
        lv = report["pooled_learning"]
        if "slope" in lv:
            lo, hi = lv["slope_ci95"]
            out.append(f"== pooled ({len(report['runs'])} runs): "
                       f"{lv['verdict']}  slope={lv['slope']:.5g}, "
                       f"95% CI [{lo:.5g}, {hi:.5g}] (n={lv['n']})")
        else:
            out.append(f"== pooled: {lv['verdict']}")
    return "\n".join(out)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("paths", nargs="+", help="run JSONL path(s); rotated "
                   "segments <path>.N are folded in automatically")
    p.add_argument("--json", action="store_true",
                   help="emit the full report as one JSON document")
    p.add_argument("--bootstrap", type=int, default=1000)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    runs = [load_run(path) for path in args.paths]
    report = build_report(runs, n_boot=args.bootstrap, seed=args.seed)
    if args.json:
        print(json.dumps(report))
    else:
        print(render(report))
    return report


if __name__ == "__main__":
    main()
