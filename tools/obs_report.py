"""Aggregate obs run JSONLs into a human/machine report.

Input: one or more RunLog streams (``smartcal_tpu.obs.RunLog`` — train
drivers' ``--metrics``, ``SMARTCAL_OBS`` bench runs).  Rotated segments
(``run.jsonl.1`` ...) are picked up automatically when the base path is
given.  Output sections:

* **Per-stage time breakdown** — span events grouped by nesting path,
  rendered as a tree with total/mean/count and percent-of-parent, plus a
  coverage line (sum of a span's direct children vs the span itself: how
  much of the episode wall time the instrumentation attributes).
* **Episode throughput** — per run: episodes, wall span, episodes/min,
  score stats.
* **Chip-probe availability** — ``probe`` events (bench.probe_backend):
  ok/fail counts and the recorded errors, the structured record of "the
  tunnel failed N/N probes" that VERDICT r5 found missing.
* **Learning-curve verdict** — per run and pooled: least-squares slope of
  score vs episode with a bootstrap 95% CI (pairs resampling,
  deterministic seed), and a verdict: LEARNING (CI > 0), REGRESSING
  (CI < 0), or NO TREND.  This is the "the sweep cannot detect learning"
  gap: a flat curve and an improving one get different verdicts with
  quantified confidence.
* **Fleet** (supervised actor fleets) — actors alive / restarts /
  dropped-corrupt-IPC counts, aggregate AND per-actor transitions/s,
  per-slot ingest-queue depth (the aggregate hides a single slow
  shard), per-shard replay occupancy, and the staleness / IS-clip
  gauge trajectories.
* **Critical path** (fleet-run DIRECTORIES) — pass a directory of
  per-process streams (``tools/serve_fleet.py --metrics-dir``) and the
  per-process JSONLs are merged onto the router's clock (via the
  ``clock_offset`` handshake, ``smartcal_tpu.obs.collect``); each
  request's cross-process span tree is reconstructed and the per-stage
  critical path (queue wait vs IPC vs pack/policy/solve/influence) is
  rendered per replica, with the trace-completeness fraction.
* **SLO burn** — ``slo_burn`` detector transitions (obs/slo.py):
  firing/cleared with the fast/slow burn rates and the localized worst
  replica.
* **Lifecycle** (``tools/serve_learn.py`` runs) — policy publications
  and hot-swaps with their latency percentiles, the per-serving-version
  ``sigma_res`` table (learning measured on live traffic), stale-version
  serve counts, and the learner's staleness / IS-clip gauge quarters.
* **Training health** (``--diag`` runs) — grad-norm trajectory over the
  learning updates (quarter means, so a ramp or a blowup is visible at a
  glance), non-finite counts, watchdog trips with their reasons, and the
  replay-health trend (priority entropy, max/mean ratio, IS-weight
  spread, beta).
* **Roofline** (``--diag`` runs) — per-stage XLA flops/bytes from the
  ``cost`` events joined with the span stream's call counts/wall time
  into achieved FLOPs/s, plus fraction-of-peak when the run recorded a
  ``roofline_peak`` (chip) reference; dashes, never a crash, when a
  stage has no span match or the run has no peak reference.

Usage:
    python tools/obs_report.py run1.jsonl [run2.jsonl ...] [--json]
        [--bootstrap 1000] [--seed 0]

stdlib + numpy only — runs anywhere, never touches jax or a device.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import sys

import numpy as np


def load_run(path):
    """Read one run (base path + rotated siblings) -> dict of events."""
    paths = sorted(
        _glob.glob(path + ".[0-9]*"),
        key=lambda p: int(p.rsplit(".", 1)[1])) + [path]
    events, bad = [], 0
    for p in paths:
        try:
            fh = open(p)
        except OSError:
            continue
        with fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    bad += 1
    header = next((e for e in events if e.get("event") == "run_header"), {})
    return {"path": path, "run_id": header.get("run_id", os.path.basename(path)),
            "header": header, "events": events, "bad_lines": bad}


def _collect_mod():
    """smartcal_tpu.obs.collect (stdlib-only), tolerating bare
    ``python tools/obs_report.py`` invocations without PYTHONPATH=."""
    try:
        from smartcal_tpu.obs import collect
    except ImportError:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from smartcal_tpu.obs import collect
    return collect


def load_fleet_dir(path):
    """Read a fleet-run DIRECTORY (one stream per process) as one
    merged run: events carry ``proc`` tags and skew-corrected
    ``t_corr`` timestamps (see smartcal_tpu/obs/collect.py)."""
    collect = _collect_mod()
    merger = collect.TimelineMerger()
    merger.add_directory(path)
    events = merger.merge()
    st = merger.stats()
    header = next((e for e in events if e.get("event") == "run_header"),
                  {})
    return {"path": path,
            "run_id": f"fleet:{os.path.basename(os.path.normpath(path))}",
            "header": header, "events": events,
            "bad_lines": st["corrupt_lines"], "fleet_dir": True,
            "procs": st["procs"], "clock_offsets": st["offsets"]}


# ---------------------------------------------------------------------------
# Span aggregation
# ---------------------------------------------------------------------------

def span_tree(events):
    """{path: {n, total_s, mean_s}} over all span events."""
    agg = {}
    for e in events:
        if e.get("event") != "span" or "path" not in e:
            continue
        d = agg.setdefault(e["path"], {"n": 0, "total_s": 0.0})
        d["n"] += 1
        d["total_s"] += float(e.get("dur_s") or 0.0)
    for d in agg.values():
        d["mean_s"] = d["total_s"] / max(d["n"], 1)
    return agg

def children(agg, path):
    depth = path.count("/") + 1
    return {p: d for p, d in agg.items()
            if p.startswith(path + "/") and p.count("/") == depth}


def coverage(agg):
    """{parent_path: fraction of parent time attributed to child spans}."""
    out = {}
    for path, d in agg.items():
        ch = children(agg, path)
        if ch and d["total_s"] > 0:
            out[path] = sum(c["total_s"] for c in ch.values()) / d["total_s"]
    return out


def render_spans(agg, out):
    if not agg:
        out.append("  (no span events)")
        return
    cov = coverage(agg)
    roots = sorted(p for p in agg if "/" not in p)
    out.append(f"  {'stage':40s} {'count':>7s} {'total_s':>10s} "
               f"{'mean_s':>9s} {'%parent':>8s}")

    def walk(path, parent_total):
        d = agg[path]
        pct = (100.0 * d["total_s"] / parent_total
               if parent_total else 100.0)
        name = "  " * path.count("/") + path.rsplit("/", 1)[-1]
        line = (f"  {name:40s} {d['n']:>7d} {d['total_s']:>10.3f} "
                f"{d['mean_s']:>9.4f} {pct:>7.1f}%")
        if path in cov:
            line += f"   (children cover {100 * cov[path]:.1f}%)"
        out.append(line)
        for ch in sorted(children(agg, path)):
            walk(ch, d["total_s"])

    for r in roots:
        walk(r, None)


# ---------------------------------------------------------------------------
# Episodes + learning verdict
# ---------------------------------------------------------------------------

def episode_series(events):
    """(episode_idx[], score[]) from episode events, in record order."""
    eps, scores = [], []
    for e in events:
        if e.get("event") != "episode":
            continue
        s = e.get("score")
        if s is None or not np.isfinite(s):
            continue
        eps.append(int(e.get("episode", len(eps))))
        scores.append(float(s))
    return np.asarray(eps), np.asarray(scores)


def throughput(events):
    ts = [e["t"] for e in events if e.get("event") == "episode" and "t" in e]
    _, scores = episode_series(events)
    out = {"episodes": len(ts)}
    if len(ts) >= 2:
        wall = max(ts) - min(ts)
        out["wall_s"] = round(wall, 3)
        if wall > 0:
            out["episodes_per_min"] = round(60.0 * (len(ts) - 1) / wall, 3)
    if scores.size:
        out["score_mean"] = round(float(scores.mean()), 4)
        out["score_last10_mean"] = round(float(scores[-10:].mean()), 4)
    return out


def learning_verdict(eps, scores, n_boot=1000, seed=0, alpha=0.05):
    """Least-squares slope of score vs episode + bootstrap CI verdict.

    Pairs bootstrap: resample (episode, score) pairs with replacement,
    refit the slope, take the (alpha/2, 1-alpha/2) percentiles.  Verdict
    LEARNING only when the whole CI is positive — a flat noisy curve's CI
    straddles 0 and reads NO TREND, which is exactly the distinction the
    CalibEnv sweep analysis lacked."""
    n = len(scores)
    if n < 3 or np.ptp(eps) == 0:
        return {"verdict": "INSUFFICIENT DATA", "n": int(n)}
    slope, intercept = np.polyfit(eps, scores, 1)
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, n, size=(int(n_boot), n))
    slopes = np.empty(int(n_boot))
    for b, ix in enumerate(idx):
        x, y = eps[ix], scores[ix]
        if np.ptp(x) == 0:
            slopes[b] = 0.0
            continue
        slopes[b] = np.polyfit(x, y, 1)[0]
    lo, hi = np.percentile(slopes, [100 * alpha / 2, 100 * (1 - alpha / 2)])
    if lo > 0:
        verdict = "LEARNING"
    elif hi < 0:
        verdict = "REGRESSING"
    else:
        verdict = "NO TREND"
    return {"verdict": verdict, "n": int(n), "slope": float(slope),
            "intercept": float(intercept),
            "slope_ci95": [float(lo), float(hi)], "bootstrap": int(n_boot)}


# ---------------------------------------------------------------------------
# Probes / solver
# ---------------------------------------------------------------------------

def probe_summary(events):
    probes = [e for e in events if e.get("event") == "probe"]
    if not probes:
        return None
    ok = sum(1 for e in probes if e.get("ok"))
    errors = sorted({str(e.get("error")) for e in probes
                     if not e.get("ok") and e.get("error")})
    return {"total": len(probes), "ok": ok, "failed": len(probes) - ok,
            "availability": round(ok / len(probes), 4), "errors": errors}


def solver_summary(events):
    recs = [e for e in events if e.get("event") == "solver"]
    if not recs:
        return None
    by_route = {}
    for e in recs:
        d = by_route.setdefault(e.get("route", "?"),
                                {"solves": 0, "admm_iters": 0,
                                 "lbfgs_iters": 0, "segments": 0,
                                 "final_resid": []})
        d["solves"] += 1
        d["admm_iters"] += int(e.get("admm_iters") or 0)
        d["lbfgs_iters"] += int(e.get("lbfgs_iters_total") or 0)
        d["segments"] += int(e.get("n_segments") or 0)
        pr = [v for v in (e.get("primal_resid") or []) if v]
        if pr:
            d["final_resid"].append(pr[-1])
    for d in by_route.values():
        fr = d.pop("final_resid")
        if fr:
            d["final_consensus_resid_mean"] = round(float(np.mean(fr)), 6)
    return by_route


# ---------------------------------------------------------------------------
# Fleet telemetry (supervised actor fleets: gauges + supervision events)
# ---------------------------------------------------------------------------

def _gauge_series(events, name):
    """[(tags, value)] for every gauge event called ``name``."""
    out = []
    for e in events:
        if e.get("event") == "gauge" and e.get("name") == name:
            tags = {k: v for k, v in e.items()
                    if k not in ("event", "name", "value", "t")}
            out.append((tags, e.get("value")))
    return out


def _series_stats(vals):
    v = np.asarray([x for x in vals if x is not None], np.float64)
    if not v.size:
        return None
    return {"last": round(float(v[-1]), 4), "mean": round(float(v.mean()), 4),
            "max": round(float(v.max()), 4)}


def fleet_summary(events):
    """Aggregate the supervised-fleet gauge/event streams, or None for
    a run with no fleet signals.

    The per-slot ``ingest_queue_depth`` and per-shard
    ``replay_shard_occupancy`` gauges are reported INDIVIDUALLY — the
    aggregate alone hides a single slow shard (one backed-up slot looks
    like mild global pressure), which is exactly the failure mode the
    per-slot gauges exist to expose."""
    alive = _series_stats([v for _, v in
                           _gauge_series(events, "actors_alive")])
    if alive is None:
        return None
    out = {"actors_alive": alive}
    out["restarts"] = sum(1 for e in events
                          if e.get("event") == "actor_restart")
    out["downs"] = sum(1 for e in events
                       if e.get("event") == "actor_down")
    out["failed_slots"] = sorted({e.get("actor") for e in events
                                  if e.get("event") == "actor_failed"})
    out["ipc_corrupt_payloads"] = sum(
        1 for e in events if e.get("event") == "ipc_corrupt_payload")
    # throughput
    agg = _series_stats([v for _, v in
                         _gauge_series(events, "actor_transitions_per_s")])
    if agg:
        out["transitions_per_s"] = agg
    per_actor = {}
    for tags, v in _gauge_series(events, "per_actor_transitions_per_s"):
        per_actor.setdefault(tags.get("actor"), []).append(v)
    if per_actor:
        out["per_actor_transitions_per_s"] = {
            a: _series_stats(vs) for a, vs in sorted(per_actor.items())}
    # ingest queue depth: aggregate (untagged) vs per-slot
    depth_all, depth_slot = [], {}
    for tags, v in _gauge_series(events, "ingest_queue_depth"):
        if "slot" in tags:
            depth_slot.setdefault(tags["slot"], []).append(v)
        else:
            depth_all.append(v)
    if depth_all:
        out["ingest_queue_depth"] = _series_stats(depth_all)
    if depth_slot:
        out["ingest_queue_depth_per_slot"] = {
            s: _series_stats(vs) for s, vs in sorted(depth_slot.items())}
    # replay shard occupancy (sharded buffers)
    occ = {}
    for tags, v in _gauge_series(events, "replay_shard_occupancy"):
        occ.setdefault(tags.get("shard"), []).append(v)
    if occ:
        out["replay_shard_occupancy"] = {
            s: (vs[-1] if vs else None) for s, vs in sorted(occ.items())}
    # staleness / IS-clip trajectory
    for g in ("weight_staleness_versions", "transition_staleness_mean",
              "is_clip_saturation", "is_clip_mean"):
        vals = [v for _, v in _gauge_series(events, g)]
        if vals:
            st = _series_stats(vals)
            st["quarters"] = _quarter_means(vals)
            out[g] = st
    return out


def render_fleet(fs, out):
    out.append("  " + "  ".join(
        f"{k}={v}" for k, v in (("alive_last", fs["actors_alive"]["last"]),
                                ("restarts", fs["restarts"]),
                                ("downs", fs["downs"]),
                                ("corrupt_ipc",
                                 fs["ipc_corrupt_payloads"]))))
    if fs.get("failed_slots"):
        out.append(f"  failed slots: {fs['failed_slots']}")
    if "transitions_per_s" in fs:
        t = fs["transitions_per_s"]
        out.append(f"  aggregate transitions/s: mean={t['mean']} "
                   f"max={t['max']}")
    for a, st in (fs.get("per_actor_transitions_per_s") or {}).items():
        out.append(f"    actor {a}: mean={st['mean']} max={st['max']}")
    if "ingest_queue_depth" in fs:
        d = fs["ingest_queue_depth"]
        out.append(f"  ingest queue depth (aggregate): mean={d['mean']} "
                   f"max={d['max']}")
    for s, st in (fs.get("ingest_queue_depth_per_slot") or {}).items():
        out.append(f"    slot {s}: mean={st['mean']} max={st['max']} "
                   f"last={st['last']}")
    if "replay_shard_occupancy" in fs:
        occ = fs["replay_shard_occupancy"]
        out.append("  replay shard occupancy (last): " + "  ".join(
            f"shard{s}={v}" for s, v in occ.items()))
    for g in ("weight_staleness_versions", "transition_staleness_mean",
              "is_clip_saturation", "is_clip_mean"):
        if g in fs:
            st = fs[g]
            out.append(f"  {g}: mean={st['mean']} max={st['max']} "
                       f"quarters={st['quarters']}")


# ---------------------------------------------------------------------------
# Serving SLO (CalibServer: serve_* spans, serve_request events, gauges)
# ---------------------------------------------------------------------------

_SERVE_STAGES = ("serve_batch", "serve_pack", "serve_policy", "serve_solve",
                 "serve_influence", "serve_sigma")


def _pctiles(vals):
    v = np.asarray([x for x in vals if x is not None], np.float64)
    if not v.size:
        return None
    return {"n": int(v.size),
            "p50": round(float(np.percentile(v, 50)), 4),
            "p99": round(float(np.percentile(v, 99)), 4),
            "mean": round(float(v.mean()), 4),
            "max": round(float(v.max()), 4)}


def serving_summary(events):
    """Aggregate the CalibServer telemetry streams, or None for a run
    with no serving signals.

    Warmup probes (``serve_request`` events tagged ``warm``) are counted
    but EXCLUDED from every latency percentile — the probe rides the
    cold glue-compile path by design, and folding it in would smear the
    steady-state p99 the SLO actually promises.  ``compiles_in_serving``
    counts ``jax_event`` records inside the live-request window (first
    submission -> last completion): the zero-per-request-compile claim,
    checked from the stream alone."""
    reqs = [e for e in events if e.get("event") == "serve_request"]
    spans = [e for e in events if e.get("event") == "span"
             and e.get("name") in _SERVE_STAGES]
    if not (reqs or spans):
        return None
    live = [e for e in reqs if not e.get("warm")]
    out = {"requests": len(live), "warm_probes": len(reqs) - len(live)}
    for k in ("total_s", "queue_wait_s", "service_s"):
        d = _pctiles([e.get(k) for e in live])
        if d:
            out[k] = d
    stages = {}
    for name in _SERVE_STAGES:
        d = _pctiles([e.get("dur_s") for e in spans
                      if e.get("name") == name])
        if d:
            stages[name] = d
    if stages:
        out["stages"] = stages
    shed = sum(1 for e in events if e.get("event") == "serve_shed")
    offered = len(live) + shed
    out["shed"] = shed
    out["shed_rate"] = round(shed / offered, 4) if offered else 0.0
    out["degraded"] = sum(1 for e in live if e.get("degraded"))
    out["deadline_miss"] = sum(1 for e in live if e.get("deadline_miss"))
    out["batch_failures"] = sum(1 for e in events
                                if e.get("event") == "serve_batch_failed")
    circuits = [e for e in events if e.get("event") == "serve_circuit"]
    if circuits:
        out["circuit_transitions"] = len(circuits)
        out["circuit_open_last"] = bool(circuits[-1].get("open"))
    depth = _pctiles([v for _, v in _gauge_series(events,
                                                  "serve_queue_depth")])
    if depth:
        out["queue_depth"] = depth
    fill = _pctiles([v for _, v in _gauge_series(events,
                                                 "serve_batch_fill")])
    if fill:
        out["batch_fill"] = fill
    warm_ev = next((e for e in events if e.get("event") == "serve_warmup"),
                   None)
    if warm_ev:
        out["warmup"] = {k: warm_ev.get(k) for k in
                         ("wall_s", "sources", "export_cache_hit",
                          "export_cache_miss", "persistent_cache_hits",
                          "persistent_cache_misses") if k in warm_ev}
    # zero-per-request-compile check: jax_event records inside the
    # serving window (first live submission -> last live completion).
    # Host-side work between warmup and serving (e.g. the load
    # generator simulating its episode pool) compiles its own programs
    # legitimately and must not pollute the claim.
    t_open = [e["t"] - e.get("total_s", 0.0) for e in live
              if e.get("t") is not None]
    t_close = [e["t"] for e in live if e.get("t") is not None]
    if t_open:
        t0, t1 = min(t_open), max(t_close)
        post = [e for e in events if e.get("event") == "jax_event"
                and t0 <= (e.get("t") or 0) <= t1]
        out["compiles_in_serving"] = len(post)
        out["compiles_per_request"] = round(len(post) / len(live), 4)
    counters = [e for e in events if e.get("event") == "counters"]
    if counters:
        vals = counters[-1].get("values") or {}
        for k in ("serve_jobs", "serve_admitted", "serve_shed",
                  "serve_degraded", "serve_deadline_miss",
                  "persistent_cache_hits", "persistent_cache_misses",
                  "export_cache_hit", "export_cache_miss"):
            if k in vals:
                out.setdefault("counters", {})[k] = vals[k]
    return out


def render_serving(sv, out):
    head = (f"  requests={sv['requests']} (+{sv['warm_probes']} warm "
            f"probes)  shed={sv['shed']} "
            f"(rate {100 * sv['shed_rate']:.1f}%)  "
            f"degraded={sv['degraded']}  "
            f"deadline_miss={sv['deadline_miss']}")
    out.append(head)
    for k, label in (("total_s", "total latency"),
                     ("queue_wait_s", "queue wait"),
                     ("service_s", "service")):
        if k in sv:
            d = sv[k]
            out.append(f"  {label:14s} p50={d['p50']}s p99={d['p99']}s "
                       f"max={d['max']}s (n={d['n']})")
    if sv.get("stages"):
        out.append(f"  {'stage':16s} {'count':>6s} {'p50_s':>8s} "
                   f"{'p99_s':>8s} {'mean_s':>8s}")
        for name, d in sv["stages"].items():
            out.append(f"  {name:16s} {d['n']:>6d} {d['p50']:>8.4f} "
                       f"{d['p99']:>8.4f} {d['mean']:>8.4f}")
    if "queue_depth" in sv:
        d = sv["queue_depth"]
        out.append(f"  queue depth: p50={d['p50']} p99={d['p99']} "
                   f"max={d['max']}")
    if "batch_fill" in sv:
        out.append(f"  batch fill: mean={sv['batch_fill']['mean']} "
                   f"(1.0 = all lanes carried a job)")
    if sv.get("batch_failures"):
        out.append(f"  BATCH FAILURES: {sv['batch_failures']}")
    if "circuit_transitions" in sv:
        state = "OPEN" if sv.get("circuit_open_last") else "closed"
        out.append(f"  circuit: {sv['circuit_transitions']} transition(s), "
                   f"last state {state}")
    w = sv.get("warmup")
    if w:
        out.append(f"  warmup: {w.get('wall_s')}s  sources={w.get('sources')}"
                   f"  export hit/miss={w.get('export_cache_hit')}"
                   f"/{w.get('export_cache_miss')}  persistent hit/miss="
                   f"{w.get('persistent_cache_hits')}"
                   f"/{w.get('persistent_cache_misses')}")
    if "compiles_in_serving" in sv:
        per = sv.get("compiles_per_request")
        out.append(f"  compiles in serving window: "
                   f"{sv['compiles_in_serving']}"
                   + (f" ({per} per request)" if per is not None else "")
                   + ("  <-- steady state must be 0"
                      if sv["compiles_in_serving"] else ""))


# ---------------------------------------------------------------------------
# Lifecycle (online learning beside serving: policy_publish / policy_swap
# events, per-version sigma_res trajectory, staleness + IS-clip gauges)
# ---------------------------------------------------------------------------

def lifecycle_summary(events):
    """Aggregate the continuous-learning telemetry (tools/serve_learn.py
    runs), or None for a run that never published a policy.

    The per-version ``sigma_res`` table is the section's point: each
    hot-swap opens a new version bucket, so an improving learner shows
    falling residuals ACROSS versions — improvement measured on live
    traffic, not on a held-out eval.  Requests whose acting version
    differs from their admitted version (``stale_serves``) are the
    swap-landed-mid-queue cases the dual-version event contract exists
    for."""
    pubs = [e for e in events if e.get("event") == "policy_publish"]
    swaps = [e for e in events if e.get("event") == "policy_swap"]
    if not (pubs or swaps):
        return None
    out = {"publishes": len(pubs), "swaps": len(swaps)}
    if pubs:
        for k in ("publish_s", "export_s", "swap_s"):
            d = _pctiles([e.get(k) for e in pubs])
            if d:
                out[k] = d
        out["versions_published"] = [int(e["version"]) for e in pubs
                                     if e.get("version") is not None]
        reached = [int(e.get("fleet_reached") or 0) for e in pubs]
        if any(reached):
            out["fleet_reached_total"] = sum(reached)
    live = [e for e in events if e.get("event") == "serve_request"
            and not e.get("warm")]
    scored = [e for e in live if e.get("behavior_logp") is not None]
    if live:
        out["requests"] = len(live)
        out["teed_fraction"] = round(len(scored) / len(live), 4)
        out["stale_serves"] = sum(
            1 for e in live
            if e.get("version") is not None
            and e.get("version_admitted") is not None
            and e["version"] != e["version_admitted"])
    by_ver = {}
    for e in live:
        v, s = e.get("version"), e.get("sigma_res")
        if v is None or s is None or not np.isfinite(s):
            continue
        by_ver.setdefault(int(v), []).append(float(s))
    if by_ver:
        out["sigma_res_by_version"] = {
            str(v): {"n": len(vals),
                     "mean": round(float(np.mean(vals)), 4)}
            for v, vals in sorted(by_ver.items())}
        vs = sorted(by_ver)
        if len(vs) > 1:
            first = float(np.mean(by_ver[vs[0]]))
            last = float(np.mean(by_ver[vs[-1]]))
            out["sigma_res_improvement"] = round(
                (first - last) / first, 4) if first else 0.0
    # learner-side staleness / IS-clip trajectories (gauge stream from
    # the serving learner, same names as the training fleet's)
    for g in ("transition_staleness_mean", "is_clip_mean",
              "is_clip_saturation", "policy_version"):
        vals = [v for _, v in _gauge_series(events, g)]
        if vals:
            st = _series_stats(vals)
            st["quarters"] = _quarter_means(vals)
            out[g] = st
    return out


def render_lifecycle(lc, out):
    head = f"  publishes={lc['publishes']}  swaps={lc['swaps']}"
    if "requests" in lc:
        head += (f"  requests={lc['requests']} "
                 f"(teed {100 * lc['teed_fraction']:.1f}%, "
                 f"{lc['stale_serves']} stale-version)")
    out.append(head)
    for k, label in (("publish_s", "publish"), ("export_s", "export"),
                     ("swap_s", "swap")):
        if k in lc:
            d = lc[k]
            out.append(f"  {label:8s} p50={d['p50']}s max={d['max']}s "
                       f"(n={d['n']})")
    if "fleet_reached_total" in lc:
        out.append(f"  fleet replicas reached (total): "
                   f"{lc['fleet_reached_total']}")
    if "sigma_res_by_version" in lc:
        out.append("  sigma_res by serving version:")
        for v, d in lc["sigma_res_by_version"].items():
            out.append(f"    v{v}: mean={d['mean']} (n={d['n']})")
        if "sigma_res_improvement" in lc:
            out.append(f"  improvement first->last version: "
                       f"{100 * lc['sigma_res_improvement']:.2f}%")
    for g in ("transition_staleness_mean", "is_clip_mean",
              "is_clip_saturation"):
        if g in lc:
            st = lc[g]
            out.append(f"  {g}: mean={st['mean']} max={st['max']} "
                       f"quarters={st['quarters']}")


# ---------------------------------------------------------------------------
# Fleet SLO (FleetRouter: fleet_* events, fleet-scoped sheds, gauges)
# ---------------------------------------------------------------------------

def serve_fleet_summary(events):
    """Aggregate the serving-fleet router telemetry (the PARENT-side
    stream of ``tools/serve_fleet.py``), or None for a run with no
    fleet-router signals.

    Per-replica latency is split out because the fleet-wide percentile
    hides a slow replica (one cold or overloaded replica looks like a
    mild global p99 bump — the per-replica table is how the least-
    loaded dispatch claim is audited).  ``dispatch_balance`` is
    min/max completions across replicas: 1.0 is a perfectly even
    spread, ~0 means one replica took (almost) everything."""
    disp = [e for e in events if e.get("event") == "fleet_dispatch"]
    res = [e for e in events if e.get("event") == "fleet_result"]
    if not (disp or res):
        return None
    out = {"dispatched": len(disp),
           "requeue_dispatches": sum(1 for e in disp
                                     if e.get("requeue")),
           "completed": len(res),
           "deadline_miss": sum(1 for e in res
                                if e.get("deadline_miss")),
           "requeued_jobs_completed": sum(1 for e in res
                                          if (e.get("requeues") or 0))}
    per = {}
    for e in res:
        per.setdefault(e.get("replica"), []).append(e.get("total_s"))
    out["per_replica"] = {
        str(rid): dict(_pctiles(v) or {},
                       share=round(len(v) / max(1, len(res)), 4))
        for rid, v in sorted(per.items(), key=lambda kv: str(kv[0]))}
    counts = [len(v) for v in per.values()]
    if len(counts) > 1:
        out["dispatch_balance"] = round(min(counts) / max(1, max(counts)),
                                        4)
    reasons = {}
    for e in events:
        if e.get("event") == "serve_shed" and e.get("scope") == "fleet":
            reasons[e.get("reason")] = reasons.get(e.get("reason"), 0) + 1
    out["shed"] = sum(reasons.values())
    out["shed_reasons"] = reasons
    downs = [e for e in events if e.get("event") in
             ("fleet_replica_down", "fleet_replica_failed")]
    out["replica_downs"] = len(downs)
    out["lost_jobs"] = sum(int(e.get("lost_jobs") or 0) for e in downs)
    out["replica_restarts"] = sum(1 for e in events if e.get("event")
                                  == "fleet_replica_restart")
    out["autoscale_events"] = [
        {k: e.get(k) for k in ("event", "replica", "replicas",
                               "depth_per_replica") if k in e}
        for e in events
        if e.get("event") in ("fleet_scale_up", "fleet_scale_down")]
    alive = _series_stats([v for _, v in
                           _gauge_series(events, "fleet_replicas_alive")])
    if alive:
        out["replicas_alive"] = alive
    depth = _pctiles([v for _, v in
                      _gauge_series(events, "fleet_queue_depth")])
    if depth:
        out["fleet_queue_depth"] = depth
    return out


def render_serve_fleet(fv, out):
    out.append(f"  dispatched={fv['dispatched']} "
               f"(requeues {fv['requeue_dispatches']})  "
               f"completed={fv['completed']}  shed={fv['shed']}"
               + (f" {fv['shed_reasons']}" if fv["shed_reasons"] else "")
               + f"  deadline_miss={fv['deadline_miss']}")
    for rid, d in fv["per_replica"].items():
        out.append(f"  replica {rid}: n={d.get('n', 0)} "
                   f"share={d.get('share')} p50={d.get('p50')}s "
                   f"p99={d.get('p99')}s")
    if "dispatch_balance" in fv:
        out.append(f"  dispatch balance (min/max completions): "
                   f"{fv['dispatch_balance']}")
    if fv["replica_downs"] or fv["replica_restarts"]:
        out.append(f"  replica downs={fv['replica_downs']} "
                   f"restarts={fv['replica_restarts']} "
                   f"lost_jobs={fv['lost_jobs']} "
                   f"(requeued jobs completed: "
                   f"{fv['requeued_jobs_completed']})")
    for e in fv["autoscale_events"]:
        arrow = "+" if e["event"] == "fleet_scale_up" else "-"
        out.append(f"  autoscale {arrow} replica {e.get('replica')} "
                   f"-> {e.get('replicas')} replicas"
                   + (f" (depth/replica {e['depth_per_replica']})"
                      if "depth_per_replica" in e else ""))
    if "replicas_alive" in fv:
        a = fv["replicas_alive"]
        out.append(f"  replicas alive: mean={a['mean']} last={a['last']}")
    if "fleet_queue_depth" in fv:
        d = fv["fleet_queue_depth"]
        out.append(f"  fleet queue depth: p50={d['p50']} p99={d['p99']} "
                   f"max={d['max']}")


# ---------------------------------------------------------------------------
# Cross-process critical path (merged fleet directories) + SLO burn
# ---------------------------------------------------------------------------

# per-request critical-path columns, in pipeline order (collect.py
# reconstructs them; absent columns — e.g. policy on a stub fleet —
# are simply skipped)
_CP_COLUMNS = ("queue_s", "ipc_s", "pack_s", "policy_s", "solve_s",
               "influence_s", "sigma_s", "service_s", "total_s")


def critical_path_summary(events):
    """Per-replica per-stage percentile breakdown of the reconstructed
    request chains, or None when the stream has no stitched traces
    (single-process runs, pre-schema-3 streams)."""
    collect = _collect_mod()
    paths = collect.request_paths(events)
    if not paths:
        return None
    comp = collect.completeness(paths)
    by_rep = {}
    for p in paths:
        by_rep.setdefault(p.get("replica"), []).append(p)
    per_replica = {}
    for rid, ps in sorted(by_rep.items(), key=lambda kv: str(kv[0])):
        row = {}
        for col in _CP_COLUMNS:
            d = _pctiles([p.get(col) for p in ps])
            if d:
                row[col] = d
        row["requests"] = len(ps)
        row["requeued"] = sum(1 for p in ps if p.get("requeued"))
        per_replica[str(rid)] = row
    return {"completeness": comp, "per_replica": per_replica,
            "requeued_traces": sum(1 for p in paths if p.get("requeued"))}


def render_critical_path(cp, out):
    c = cp["completeness"]
    out.append(f"  trace completeness: {c['n_complete_trees']}"
               f"/{c['n_completed']} completed requests rebuilt a full "
               f"cross-process tree ({100 * c['fraction']:.1f}%)"
               + (f"; {cp['requeued_traces']} requeued"
                  if cp.get("requeued_traces") else ""))
    for rid, row in cp["per_replica"].items():
        out.append(f"  replica {rid}  (n={row['requests']}"
                   + (f", requeued={row['requeued']}"
                      if row.get("requeued") else "") + ")")
        out.append(f"    {'stage':12s} {'p50_s':>9s} {'p99_s':>9s} "
                   f"{'mean_s':>9s}")
        for col in _CP_COLUMNS:
            if col in row:
                d = row[col]
                out.append(f"    {col:12s} {d['p50']:>9.4f} "
                           f"{d['p99']:>9.4f} {d['mean']:>9.4f}")


def slo_summary(events):
    """``slo_burn`` detector transitions, or None when none fired."""
    evs = [e for e in events if e.get("event") == "slo_burn"]
    if not evs:
        return None
    return {"transitions": [
        {k: e.get(k) for k in ("t", "t_corr", "state", "burn_fast",
                               "burn_slow", "p99_fast_s",
                               "shed_rate_fast", "p99_target_s",
                               "worst_replica") if k in e}
        for e in evs],
        "final_state": evs[-1].get("state")}


def render_slo(sl, out):
    for e in sl["transitions"]:
        state = str(e.get("state", "?")).upper()
        line = (f"  {state}: fast burn {e.get('burn_fast')}x / slow "
                f"{e.get('burn_slow')}x  p99_fast={e.get('p99_fast_s')}s"
                f" (target {e.get('p99_target_s')}s)")
        if e.get("shed_rate_fast"):
            line += f"  shed_rate={e['shed_rate_fast']}"
        if e.get("worst_replica") is not None:
            line += f"  worst replica: {e['worst_replica']}"
        out.append(line)
    out.append(f"  final state: {str(sl['final_state']).upper()}")


# ---------------------------------------------------------------------------
# Training health (diag / replay_health / watchdog_trip events)
# ---------------------------------------------------------------------------

# diag fields summarized in the health section (trajectory-worthy ones)
_DIAG_TRAJ = ("critic_grad_norm", "actor_grad_norm", "critic_loss",
              "q_mean", "q_max", "critic_update_ratio", "entropy")


def _quarter_means(vals):
    """Mean of each quarter of the series — the cheapest trajectory that
    still shows a ramp, a plateau, or a blowup."""
    v = np.asarray(vals, np.float64)
    qs = np.array_split(v, min(4, len(v)))
    return [round(float(q.mean()), 6) for q in qs if q.size]


def training_health(events):
    """Aggregate the diag/replay_health/watchdog_trip streams, or None
    for a run recorded without ``--diag``."""
    diags = [e for e in events if e.get("event") == "diag"]
    replay = [e for e in events if e.get("event") == "replay_health"]
    trips = [e for e in events if e.get("event") == "watchdog_trip"]
    if not (diags or replay or trips):
        return None
    out = {"updates": len(diags)}
    if diags:
        # learning updates: the ones where the critic actually stepped
        # (exact zeros are the pre-buffer-fill / delayed-update skips,
        # same convention as the watchdog); None = sanitized non-finite
        nonfinite = sum(1 for e in diags
                        for k in ("critic_loss", "critic_grad_norm",
                                  "q_mean")
                        if k in e and e[k] is None)
        def _learned(e):
            g = e.get("critic_grad_norm")
            if isinstance(g, (int, float)):
                return g != 0.0
            # partial streams (the parallel learners log only the
            # episode's last critic loss): any real loss value means
            # the SPMD update program learned
            return ("critic_grad_norm" not in e
                    and isinstance(e.get("critic_loss"), (int, float)))

        learn = [e for e in diags if _learned(e)]
        out["learning_updates"] = len(learn)
        out["nonfinite_values"] = nonfinite
        traj = {}
        for k in _DIAG_TRAJ:
            vals = [e[k] for e in learn
                    if isinstance(e.get(k), (int, float))
                    and np.isfinite(e[k])]
            if vals:
                traj[k] = {"quarter_means": _quarter_means(vals),
                           "last": round(float(vals[-1]), 6),
                           "max": round(float(max(vals)), 6)}
        out["trajectory"] = traj
    if replay:
        first, last = replay[0], replay[-1]
        rh = {"samples": len(replay)}
        for k in ("priority_entropy", "max_mean_priority_ratio", "beta",
                  "is_weight_max", "age_mean_weighted"):
            if isinstance(last.get(k), (int, float)):
                rh[k + "_last"] = round(float(last[k]), 6)
            if isinstance(first.get(k), (int, float)):
                rh[k + "_first"] = round(float(first[k]), 6)
        for k in ("filled", "size"):
            if last.get(k) is not None:
                rh[k] = last[k]
        out["replay"] = rh
    out["watchdog_trips"] = [
        {"reason": e.get("reason"), "step": e.get("step"),
         "observations": e.get("observations"),
         "ring_len": len(e.get("ring") or [])} for e in trips]
    return out


# ---------------------------------------------------------------------------
# Roofline (cost / roofline_peak events joined with the span stream)
# ---------------------------------------------------------------------------

# cost stage -> span leaf name, where they are not spelled identically
# (the enet drivers' whole-episode jitted update is spanned "episode");
# every other costed stage — simulate/solve/influence and the agent
# wrappers' agent_update_<algo> — spans under its own cost-stage name
_STAGE_SPAN_ALIASES = {"episode_update": "episode"}


def roofline(events, spans):
    """Per-stage flops/bytes/achieved-FLOPs/s table, or None without
    ``cost`` events.  Achieved rate = flops-per-call x span count / span
    wall; absent span match or peak reference leaves those fields unset
    (the renderer prints dashes).

    Each row carries the stage's recorded ``compute_dtype`` (the
    precision-policy tag on the cost event; untagged stages are f32) and
    fraction-of-peak is quoted against the MATCHING device peak — a bf16
    kernel against the bf16 systolic peak, an f32 kernel against the
    fp32 estimate.  Before the dtype tag existed every stage divided by
    fp32_est, which reads ~half under bf16 (or >1 if the fp32 estimate
    is beaten).  Footprint fields (peak live bytes per compile, and the
    per-shard division under sharded routes) ride along when the run
    recorded them."""
    costs = [e for e in events if e.get("event") == "cost"]
    if not costs:
        return None
    peak = next((e for e in events if e.get("event") == "roofline_peak"),
                None)
    by_stage = {}
    for e in costs:
        d = by_stage.setdefault(e.get("stage", "?"),
                                {"flops": [], "bytes": [], "errors": 0,
                                 "peak_bytes": [], "shard_bytes": [],
                                 "shards": [], "dtypes": set(),
                                 "shard_axes": {}, "axis_bytes": {}})
        if e.get("error"):
            d["errors"] += 1
        else:
            d["flops"].append(float(e.get("flops") or 0.0))
            d["bytes"].append(float(e.get("bytes_accessed") or 0.0))
            if e.get("peak_bytes") is not None:
                d["peak_bytes"].append(float(e["peak_bytes"]))
            if e.get("peak_bytes_per_shard") is not None:
                d["shard_bytes"].append(float(e["peak_bytes_per_shard"]))
                d["shards"].append(int(e.get("shards") or 1))
            # composed-mesh per-axis breakout (registry axis names):
            # keep the largest signature's per-axis footprint per axis
            for a, n in (e.get("shard_axes") or {}).items():
                d["shard_axes"][str(a)] = max(
                    d["shard_axes"].get(str(a), 0), int(n))
            for a, b in (e.get("peak_bytes_per_axis") or {}).items():
                d["axis_bytes"][str(a)] = max(
                    d["axis_bytes"].get(str(a), 0.0), float(b))
            d["dtypes"].add(str(e.get("compute_dtype") or "f32"))
    stages = {}
    for stage, d in sorted(by_stage.items()):
        row = {"signatures": len(d["flops"]) + d["errors"],
               "errors": d["errors"]}
        dtypes = d["dtypes"] or {"f32"}
        # a stage that recorded both policies reports the widest claim
        # honestly: mixed -> quoted against the bf16 peak would overstate
        # the f32 share, so flag it and quote fp32
        row["compute_dtype"] = ("mixed" if len(dtypes) > 1
                                else next(iter(dtypes)))
        if d["flops"]:
            row["flops_per_call"] = float(np.mean(d["flops"]))
            row["bytes_per_call"] = float(np.mean(d["bytes"]))
            if row["bytes_per_call"] > 0:
                row["arith_intensity"] = round(
                    row["flops_per_call"] / row["bytes_per_call"], 3)
        if d["peak_bytes"]:
            row["peak_bytes_max"] = float(np.max(d["peak_bytes"]))
        if d["shard_bytes"]:
            row["peak_bytes_per_shard_max"] = float(np.max(d["shard_bytes"]))
            row["shards"] = int(max(d["shards"]))
        if d["shard_axes"]:
            row["shard_axes"] = dict(sorted(d["shard_axes"].items()))
            row["peak_bytes_per_axis"] = {
                a: d["axis_bytes"][a] for a in row["shard_axes"]
                if a in d["axis_bytes"]}
        leaf = _STAGE_SPAN_ALIASES.get(stage, stage)
        matches = [p for p in spans if p.rsplit("/", 1)[-1] == leaf]
        if matches and "flops_per_call" in row:
            n = sum(spans[p]["n"] for p in matches)
            tot = sum(spans[p]["total_s"] for p in matches)
            row["calls"] = n
            row["span_total_s"] = round(tot, 3)
            if tot > 0 and n > 0:
                row["achieved_flops_per_s"] = \
                    row["flops_per_call"] * n / tot
                peak_key = ("bf16" if row["compute_dtype"] == "bf16"
                            else "fp32_est")
                if peak and peak.get(peak_key):
                    row["peak_dtype"] = peak_key
                    row["fraction_of_peak"] = round(
                        row["achieved_flops_per_s"]
                        / float(peak[peak_key]), 6)
                # legacy field, kept for pre-r13 report consumers
                if peak and peak.get("fp32_est") \
                        and row["compute_dtype"] != "bf16":
                    row["fraction_of_peak_fp32"] = round(
                        row["achieved_flops_per_s"]
                        / float(peak["fp32_est"]), 6)
        stages[stage] = row
    peak_out = None
    if peak is not None:
        peak_out = {k: peak[k] for k in ("platform", "chip", "bf16",
                                         "fp32_est") if k in peak}
    return {"peak": peak_out, "stages": stages}


def _fmt_si(v, unit=""):
    if v is None:
        return "-"
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(v) >= scale:
            return f"{v / scale:.2f}{suffix}{unit}"
    return f"{v:.2f}{unit}"


def render_roofline(rl, out):
    peak = rl.get("peak")
    if peak:
        out.append(f"  peak: {peak.get('chip', peak.get('platform'))} "
                   f"bf16={_fmt_si(peak.get('bf16'))}F/s "
                   f"fp32_est={_fmt_si(peak.get('fp32_est'))}F/s")
    else:
        out.append("  (no roofline_peak reference — fraction-of-peak "
                   "unavailable on this platform)")
    out.append(f"  {'stage':24s} {'dtype':>6s} {'flops/call':>11s} "
               f"{'bytes/call':>11s} {'AI':>7s} {'peakMB':>8s} "
               f"{'MB/shard':>9s} {'calls':>6s} {'span_s':>8s} "
               f"{'FLOP/s':>9s} {'%peak':>7s}")
    for stage, row in rl["stages"].items():
        ai = row.get("arith_intensity")
        span_s = row.get("span_total_s")
        frac = row.get("fraction_of_peak")
        pk = row.get("peak_bytes_max")
        pks = row.get("peak_bytes_per_shard_max")
        out.append(
            f"  {stage:24s} {row.get('compute_dtype', 'f32'):>6s} "
            f"{_fmt_si(row.get('flops_per_call')):>11s} "
            f"{_fmt_si(row.get('bytes_per_call')):>11s} "
            f"{(f'{ai:.2f}' if ai is not None else '-'):>7s} "
            f"{(f'{pk / 1e6:.1f}' if pk is not None else '-'):>8s} "
            f"{(f'{pks / 1e6:.1f}' if pks is not None else '-'):>9s} "
            f"{(str(row['calls']) if 'calls' in row else '-'):>6s} "
            f"{(f'{span_s:.2f}' if span_s is not None else '-'):>8s} "
            f"{_fmt_si(row.get('achieved_flops_per_s')):>9s} "
            f"{(f'{100 * frac:.2f}%' if frac is not None else '-'):>7s}")
        axes = row.get("shard_axes")
        if axes:
            # footprint if ONLY that axis were sharded — what each mesh
            # axis alone buys (obs/costs.py peak_bytes_per_axis)
            per_ax = row.get("peak_bytes_per_axis") or {}
            parts = " x ".join(
                f"{a}={n}"
                + (f" ({per_ax[a] / 1e6:.1f} MB alone)" if a in per_ax
                   else "")
                for a, n in axes.items())
            out.append(f"    mesh axes: {parts}")
        if row.get("errors"):
            out.append(f"    ({row['errors']} cost-analysis failure(s) "
                       f"recorded for {stage})")
    _render_kernel_rows(rl["stages"], out)


def _render_kernel_rows(stages, out):
    """Pallas-vs-blocked-XLA comparison for the ``kernel:*`` cost rows
    (envs/radio._record_kernel_costs): for each kernel family with both
    variants recorded, quote the traffic and arithmetic-intensity deltas
    the promotion gate (ISSUE 17) reads before flipping a flag."""
    fams = {}
    for stage, row in stages.items():
        if not stage.startswith("kernel:"):
            continue
        name = stage[len("kernel:"):]
        for suffix, variant in (("_blocked_xla", "xla"),
                                ("_pallas", "pallas")):
            if name.endswith(suffix):
                fams.setdefault(name[:-len(suffix)], {})[variant] = row
    for fam, pair in sorted(fams.items()):
        xla, pls = pair.get("xla"), pair.get("pallas")
        if not (xla and pls):
            continue
        bx = xla.get("bytes_per_call")
        bp = pls.get("bytes_per_call")
        ax, ap = xla.get("arith_intensity"), pls.get("arith_intensity")
        ratio = (f"{bx / bp:.2f}x less traffic" if bx and bp and bp > 0
                 else "-")
        out.append(
            f"  kernel {fam}: pallas AI "
            f"{(f'{ap:.2f}' if ap is not None else '-')} vs XLA "
            f"{(f'{ax:.2f}' if ax is not None else '-')}, bytes/call "
            f"{_fmt_si(bp)} vs {_fmt_si(bx)} ({ratio})")


def render_training_health(th, out):
    out.append(f"  updates={th.get('updates', 0)} "
               f"learning={th.get('learning_updates', 0)} "
               f"nonfinite={th.get('nonfinite_values', 0)}")
    traj = th.get("trajectory") or {}
    for k, d in traj.items():
        qm = " -> ".join(f"{v:g}" for v in d["quarter_means"])
        out.append(f"  {k:22s} quarters [{qm}]  last={d['last']:g} "
                   f"max={d['max']:g}")
    if not traj and th.get("updates"):
        out.append("  (no learning updates in the diag stream — e.g. the "
                   "buffer stayed below batch size for the whole run)")
    rh = th.get("replay")
    if rh:
        ent = (f"{rh.get('priority_entropy_first', float('nan')):.3f}"
               f" -> {rh.get('priority_entropy_last', float('nan')):.3f}"
               if "priority_entropy_last" in rh else "-")
        out.append(f"  replay: entropy {ent}  "
                   f"max/mean={rh.get('max_mean_priority_ratio_last', '-')}  "
                   f"beta={rh.get('beta_last', '-')}  "
                   f"filled={rh.get('filled', '-')}/{rh.get('size', '-')}")
    trips = th.get("watchdog_trips") or []
    if trips:
        for t in trips:
            out.append(f"  WATCHDOG TRIP at update {t.get('step')}: "
                       f"{t.get('reason')} (after {t.get('observations')} "
                       f"observations, ring={t.get('ring_len')})")
    else:
        out.append("  watchdog: no trips")


# ---------------------------------------------------------------------------
# Report
# ---------------------------------------------------------------------------

def build_report(runs, n_boot=1000, seed=0):
    report = {"runs": []}
    all_pairs = []
    for run in runs:
        ev = run["events"]
        eps, scores = episode_series(ev)
        all_pairs.append((eps, scores))
        compiles = [e for e in ev if e.get("event") == "jax_event"]
        spans = span_tree(ev)
        r = {"path": run["path"], "run_id": run["run_id"],
             "entry": (run["header"].get("meta") or {}).get("entry"),
             "platform": run["header"].get("platform"),
             "bad_lines": run["bad_lines"],
             "spans": spans,
             "coverage": coverage(spans),
             "throughput": throughput(ev),
             "learning": learning_verdict(eps, scores, n_boot, seed),
             "probes": probe_summary(ev),
             "solver": solver_summary(ev),
             "fleet": fleet_summary(ev),
             "serve_fleet": serve_fleet_summary(ev),
             "serving": serving_summary(ev),
             "lifecycle": lifecycle_summary(ev),
             "critical_path": (critical_path_summary(ev)
                               if run.get("fleet_dir") else None),
             "slo": slo_summary(ev),
             "training_health": training_health(ev),
             "roofline": roofline(ev, spans),
             "compile_events": len(compiles),
             "compile_secs": round(sum(float(e.get("dur_s") or 0)
                                       for e in compiles), 3)}
        report["runs"].append(r)
    if len(runs) > 1:
        eps = np.concatenate([p[0] for p in all_pairs])
        scores = np.concatenate([p[1] for p in all_pairs])
        report["pooled_learning"] = learning_verdict(eps, scores, n_boot,
                                                     seed)
    return report


def render(report):
    out = []
    for r in report["runs"]:
        out.append(f"== run {r['run_id']}  ({r['path']})")
        meta = [f"entry={r['entry']}" if r.get("entry") else None,
                f"platform={r['platform']}" if r.get("platform") else None,
                f"bad_lines={r['bad_lines']}" if r["bad_lines"] else None]
        meta = [m for m in meta if m]
        if meta:
            out.append("  " + "  ".join(meta))
        out.append("-- per-stage time breakdown")
        render_spans(r["spans"], out)
        out.append("-- episode throughput")
        if r["throughput"].get("episodes"):
            out.append("  " + "  ".join(f"{k}={v}" for k, v
                                        in r["throughput"].items()))
        else:
            out.append("  (no episode events)")
        if r["probes"]:
            p = r["probes"]
            out.append("-- chip-probe availability")
            out.append(f"  {p['ok']}/{p['total']} ok "
                       f"(availability {100 * p['availability']:.1f}%)")
            for err in p["errors"]:
                out.append(f"  failure: {err}")
        if r["solver"]:
            out.append("-- solver telemetry")
            for route, d in sorted(r["solver"].items()):
                out.append(f"  route={route}  " + "  ".join(
                    f"{k}={v}" for k, v in d.items()))
        if r.get("fleet"):
            out.append("-- fleet")
            render_fleet(r["fleet"], out)
        if r.get("serving"):
            out.append("-- serving SLO")
            render_serving(r["serving"], out)
        if r.get("lifecycle"):
            out.append("-- lifecycle (online learning + hot-swap)")
            render_lifecycle(r["lifecycle"], out)
        if r.get("serve_fleet"):
            out.append("-- fleet SLO (serving scale-out)")
            render_serve_fleet(r["serve_fleet"], out)
        if r.get("critical_path"):
            out.append("-- critical path (merged cross-process traces)")
            render_critical_path(r["critical_path"], out)
        if r.get("slo"):
            out.append("-- SLO burn transitions")
            render_slo(r["slo"], out)
        if r["compile_events"]:
            out.append(f"-- jax compile: {r['compile_events']} events, "
                       f"{r['compile_secs']} s")
        if r.get("training_health"):
            out.append("-- training health")
            render_training_health(r["training_health"], out)
        if r.get("roofline"):
            out.append("-- roofline")
            render_roofline(r["roofline"], out)
        lv = r["learning"]
        out.append("-- learning-curve verdict")
        if "slope" in lv:
            lo, hi = lv["slope_ci95"]
            out.append(f"  {lv['verdict']}  slope={lv['slope']:.5g} "
                       f"per episode, 95% CI [{lo:.5g}, {hi:.5g}] "
                       f"(n={lv['n']}, bootstrap={lv['bootstrap']})")
        else:
            out.append(f"  {lv['verdict']} (n={lv.get('n', 0)})")
        out.append("")
    if "pooled_learning" in report:
        lv = report["pooled_learning"]
        if "slope" in lv:
            lo, hi = lv["slope_ci95"]
            out.append(f"== pooled ({len(report['runs'])} runs): "
                       f"{lv['verdict']}  slope={lv['slope']:.5g}, "
                       f"95% CI [{lo:.5g}, {hi:.5g}] (n={lv['n']})")
        else:
            out.append(f"== pooled: {lv['verdict']}")
    return "\n".join(out)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("paths", nargs="+", help="run JSONL path(s) — rotated "
                   "segments <path>.N are folded in automatically — or a "
                   "fleet-run DIRECTORY of per-process streams, merged "
                   "onto one clock (critical-path section)")
    p.add_argument("--json", action="store_true",
                   help="emit the full report as one JSON document")
    p.add_argument("--bootstrap", type=int, default=1000)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    runs = [load_fleet_dir(path) if os.path.isdir(path)
            else load_run(path) for path in args.paths]
    report = build_report(runs, n_boot=args.bootstrap, seed=args.seed)
    if args.json:
        print(json.dumps(report))
    else:
        print(render(report))
    return report


if __name__ == "__main__":
    main()
