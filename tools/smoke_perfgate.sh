#!/bin/bash
# Regression-radar smoke: the perf gate's whole workflow end to end.
#
# 1. RECORD — perf_gate --update-baseline blesses this host's numbers
#    into a fresh baseline store (fingerprinted keys).
# 2. CLEAN  — an immediate rerun against the recorded baseline must be
#    green (exit 0, zero FIREs): same host, same tree, only noise.
# 3. SLOW   — a planned delay inside the solve stage's timed reps
#    (runtime/faults.py via SMARTCAL_FAULTS — the same chaos hook the
#    serve smoke uses) must be caught: exit 1 with a FIRE naming
#    solve.wall_s and carrying the measured delta + noise band.
# 4. DRIFT  — a planned numeric perturbation beyond the documented bf16
#    band must be caught the same way (influence.rel_err FIRE).
# 5. ROUND-TRIP — --update-baseline re-blesses, and the rerun is green
#    again: the graftlint workflow applied to performance.
#
# CI companion of smoke_lint.sh / smoke_serve.sh; ~3 min on the 1-core
# container (warm XLA cache).
#
#   bash tools/smoke_perfgate.sh [workdir]
#
# Exits non-zero on any broken link in the chain.
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1

WORK="${1:-$(mktemp -d /tmp/smoke_perfgate.XXXXXX)}"
BASE="$WORK/perf_baselines.json"
CACHE="$WORK/cache"
mkdir -p "$WORK"

gate() {  # gate <extra args...> — stdout to $WORK/last.txt, pass exit up
    JAX_PLATFORMS=cpu python tools/perf_gate.py \
        --baseline "$BASE" --cache-dir "$CACHE" "$@" \
        > "$WORK/last.txt"
}

echo "[smoke_perfgate] 1: RECORD baseline ($BASE)" >&2
gate --update-baseline
grep -q "baseline updated for 5 stage(s)" "$WORK/last.txt" || {
    echo "[smoke_perfgate] FAIL: record did not bless all stages" >&2
    cat "$WORK/last.txt" >&2; exit 1
}

echo "[smoke_perfgate] 2: CLEAN rerun must be green" >&2
gate || {
    echo "[smoke_perfgate] FAIL: clean rerun fired" >&2
    cat "$WORK/last.txt" >&2; exit 1
}
grep -q "0 FIRE" "$WORK/last.txt"

echo "[smoke_perfgate] 3: injected slowdown must FIRE (exit 1)" >&2
if SMARTCAL_FAULTS='{"delay_stage":"gate_solve","delay_at":0,"delay_s":0.05,"delay_span":10}' \
        gate --stages solve; then
    echo "[smoke_perfgate] FAIL: 6x solve slowdown not caught" >&2
    cat "$WORK/last.txt" >&2; exit 1
fi
grep -q "FIRE] solve.wall_s" "$WORK/last.txt" || {
    echo "[smoke_perfgate] FAIL: no FIRE naming solve.wall_s" >&2
    cat "$WORK/last.txt" >&2; exit 1
}

echo "[smoke_perfgate] 4: injected numeric drift must FIRE (exit 1)" >&2
if SMARTCAL_FAULTS='{"perturb_stage":"gate_numeric_influence","perturb_at":0,"perturb_rel":0.1}' \
        gate --stages influence; then
    echo "[smoke_perfgate] FAIL: out-of-band numeric drift not caught" >&2
    cat "$WORK/last.txt" >&2; exit 1
fi
grep -q "FIRE] influence.rel_err" "$WORK/last.txt" || {
    echo "[smoke_perfgate] FAIL: no FIRE naming influence.rel_err" >&2
    cat "$WORK/last.txt" >&2; exit 1
}

echo "[smoke_perfgate] 5: --update-baseline round-trip" >&2
gate --update-baseline
gate || {
    echo "[smoke_perfgate] FAIL: rerun after re-bless fired" >&2
    cat "$WORK/last.txt" >&2; exit 1
}
echo "[smoke_perfgate] PASS (workdir $WORK)" >&2
