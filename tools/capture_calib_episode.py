"""Standalone capture of the calib_episode_wall_clock metric (BENCH_r03).

The full bench.py run captures this as an extra after the primary metric;
when the axon tunnel drops mid-session (observed 2026-07-31: compiles take
10-25 min server-side and the tunnel goes UNAVAILABLE intermittently) the
extra is lost while the primary survives.  This wrapper retries JUST the
calib episode so a recovered tunnel doesn't have to re-pay the primary's
measurement, and writes the payload to results/calib_episode_r3.json.

Usage: python tools/capture_calib_episode.py [--out results/calib_episode_r3.json]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "results", "calib_episode_r3.json"))
    ap.add_argument("--allow_cpu", action="store_true",
                    help="deliberate CPU-anchor measurement (forces the "
                    "cpu platform; artifact carries platform='cpu' — "
                    "never promoted as a chip capture)")
    args = ap.parse_args()

    import jax
    if args.allow_cpu:
        jax.config.update("jax_platforms", "cpu")

    platform = jax.devices()[0].platform
    if platform not in ("tpu", "axon") and not args.allow_cpu:
        # N=62 x Nf=8 takes hours on one CPU core; a CPU artifact labeled
        # as the chip number would be worse than no artifact
        print(f"platform is {platform!r}, not a TPU — refusing to capture",
              file=sys.stderr)
        return 1

    import bench

    payload = bench.bench_calib_episode()
    payload["platform"] = platform
    print(json.dumps(payload))
    tmp = args.out + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh, indent=1)
    os.replace(tmp, args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
