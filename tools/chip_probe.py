#!/usr/bin/env python
"""Backoff-governed TPU tunnel probe for the capture scripts.

Replaces the blind fixed-sleep probe loop in ``tools/capture_round.sh``
(the loop behind the 87 dead probes of ``results/chip_attempts_r5.log``):
each probe runs ``import jax; jax.devices()`` in a bounded subprocess,
failures back off exponentially with jitter under BOTH an attempt cap
and a total-sleep budget, and every attempt emits the structured
``probe`` event (the same record bench.py writes) with ``attempt`` /
``next_retry_s`` / ``backoff_spent_s`` fields into a JSONL stream.

Exit status: 0 = tunnel alive (a capture may start), 1 = budget/attempts
exhausted with the tunnel still dead, so shell callers can gate on it::

    python tools/chip_probe.py --metrics results/chip_probe_r6.jsonl \
        --attempts 12 --budget 3600 || exit 1

``--probe-cmd`` overrides the probed command (tests use ``echo tpu``).
One TPU client at a time: probe and capture run sequentially, never
concurrently (see capture_round.sh).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from smartcal_tpu import obs                               # noqa: E402
from smartcal_tpu.runtime import Backoff, BackoffPolicy    # noqa: E402

DEFAULT_PROBE = (f"{sys.executable} -c "
                 "'import jax; print(jax.devices()[0].platform)'")


def probe_once(cmd: str, timeout: float):
    """(ok, detail) for one probe subprocess run."""
    try:
        r = subprocess.run(cmd, shell=True, capture_output=True,
                           text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return False, f"timeout ({timeout:g}s)"
    out = (r.stdout or "").strip().splitlines()
    platform = out[-1] if out else ""
    ok = r.returncode == 0 and platform in ("axon", "tpu")
    return ok, platform or f"rc={r.returncode}"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--attempts", type=int, default=12,
                   help="max probe attempts")
    p.add_argument("--budget", type=float, default=3600.0,
                   help="total backoff-sleep budget in seconds")
    p.add_argument("--timeout", type=float, default=150.0,
                   help="per-probe subprocess timeout (a sick tunnel "
                        "hangs backend init ~25 min; healthy init is "
                        "under a minute)")
    p.add_argument("--base", type=float, default=60.0,
                   help="first backoff delay")
    p.add_argument("--max-delay", type=float, default=600.0)
    p.add_argument("--metrics", type=str, default=None,
                   help="JSONL stream for the structured probe events")
    p.add_argument("--probe-cmd", type=str, default=DEFAULT_PROBE,
                   help="command whose last stdout line must be "
                        "axon/tpu (override for tests)")
    args = p.parse_args(argv)

    # side process: never let the event stream's device-metadata probe
    # touch the TPU client the probe subprocess owns
    os.environ.setdefault("SMARTCAL_OBS_NO_DEVICE_META", "1")
    rl = obs.RunLog(args.metrics, meta={"entry": "chip_probe"}) \
        if args.metrics else None
    bo = Backoff(BackoffPolicy(base_s=args.base, factor=2.0,
                               max_s=args.max_delay, jitter=0.25,
                               max_attempts=max(0, args.attempts - 1),
                               budget_s=args.budget),
                 seed=os.getpid())
    try:
        for attempt in range(max(1, args.attempts)):
            ok, detail = probe_once(args.probe_cmd, args.timeout)
            delay = None if ok else bo.next_delay()
            if rl is not None:
                rl.log("probe", ok=ok, attempt=attempt, platform=detail,
                       next_retry_s=None if delay is None
                       else round(delay, 1),
                       backoff_spent_s=round(bo.spent_s, 1))
                rl.flush()
            if ok:
                obs.echo(f"tunnel alive ({detail}) after {attempt + 1} "
                         f"probe(s)", event=None)
                return 0
            if delay is None:
                break
            obs.echo(f"probe {attempt + 1}/{args.attempts} dead "
                     f"({detail}); retrying in {delay:.0f}s "
                     f"(spent {bo.spent_s:.0f}/{args.budget:.0f}s)",
                     event=None)
            time.sleep(delay)
        obs.echo(f"tunnel still dead after {bo.attempt + 1} probe(s), "
                 f"{bo.spent_s:.0f}s backoff spent — giving up",
                 event=None)
        return 1
    finally:
        if rl is not None:
            rl.close()


if __name__ == "__main__":
    sys.exit(main())
