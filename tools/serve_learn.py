#!/usr/bin/env python
"""Close the loop: learn from served traffic with zero-downtime policy
hot-swaps, and measure it.

One invocation is one ONLINE LIFECYCLE: warm up the AOT-exported
``CalibServer`` with its policy head armed and every completed request
teed into the mesh-sharded versioned replay, drive a sustained open-loop
offered rate, and run the SAC learner BESIDE the server — draining the
tee, learning with IMPACT staleness-clipped IS weighting + ERE, and
publishing each new snapshot through the export cache as an atomic
hot-swap (``serve.lifecycle``).  A held-out scenario stream is re-scored
periodically through the policy path, so the artifact shows sigma_res
improving WHILE the server serves.

    PYTHONPATH=. JAX_PLATFORMS=cpu python tools/serve_learn.py \
        --tier tiny --M 3 --lanes 4 --rate 3 --duration 60 \
        --cache-dir /tmp/lifecycle_cache --metrics /tmp/lifecycle.jsonl \
        --out results/lifecycle_r19.json

The acceptance gates the artifact encodes: >= 3 hot-swaps inside the
serving window, ZERO compile events in it (the exported policy program
takes the weights as a traced operand — publication re-serializes and
warms, never re-traces), zero sheds attributable to publication, and
the windowed serving p99 flat across every swap.
"""

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from smartcal_tpu import obs                               # noqa: E402
from smartcal_tpu.obs import tracectx                      # noqa: E402
from smartcal_tpu.serve.loadgen import SERVE_TIERS as TIERS  # noqa: E402
from smartcal_tpu.train import blocks                      # noqa: E402


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--tier", choices=sorted(TIERS), default="tiny",
                   help="backend scale (tiny = the CPU test tier)")
    p.add_argument("--M", type=int, default=3,
                   help="max calibration directions (jobs carry k <= M)")
    p.add_argument("--lanes", type=int, default=4,
                   help="micro-batch width (BatchedEpisode lanes)")
    p.add_argument("--cache-dir", dest="cache_dir", required=True,
                   help="AOT export + XLA compilation cache root")
    p.add_argument("--rate", type=float, default=3.0,
                   help="sustained offered rate (jobs/s) for the window")
    p.add_argument("--duration", type=float, default=60.0,
                   help="seconds of the serving/learning window")
    p.add_argument("--pool", type=int, default=10,
                   help="pre-built obs-bearing episodes cycled by the "
                        "load generator (heterogeneous K/diffuse mix)")
    p.add_argument("--eval-pool", dest="eval_pool", type=int, default=6,
                   help="held-out scenarios re-scored through the policy "
                        "path each eval round")
    p.add_argument("--eval-every-s", dest="eval_every_s", type=float,
                   default=12.0, help="seconds between held-out evals")
    p.add_argument("--learn-steps", dest="learn_steps", type=int,
                   default=2, help="fused SAC steps per learner tick")
    p.add_argument("--max-wait-ms", dest="max_wait_ms", type=float,
                   default=50.0, help="micro-batch max wait")
    p.add_argument("--max-queue", dest="max_queue", type=int, default=64,
                   help="bounded admission queue depth (overload sheds)")
    p.add_argument("--swap-window-s", dest="swap_window_s", type=float,
                   default=5.0,
                   help="window either side of each swap for the "
                        "p99-flatness comparison")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", type=str, default=None,
                   help="write the lifecycle artifact JSON here")
    blocks.add_obs_args(p)
    blocks.add_lifecycle_args(p)
    return p.parse_args(argv)


class _LoadThread(threading.Thread):
    """Open-loop Poisson submitter over an obs-bearing pool, recording
    per-job completion WALL TIMES via done-callbacks — the raw series
    the swap-window p99 comparison needs (the shared ``OpenLoopLoadGen``
    only keeps the aggregate).  Half the jobs pin a log-uniform rho
    (the exploration stream the learner needs); half ride the policy."""

    def __init__(self, server, pool, rate, duration_s, seed=0):
        super().__init__(name="lifecycle-load", daemon=True)
        from smartcal_tpu.serve.router import ShedError
        self._shed_error = ShedError
        self.server = server
        self.pool = pool
        self.rate = float(rate)
        self.duration_s = float(duration_s)
        self.seed = seed
        self._lock = threading.Lock()
        self.completions = []            # (t_done_monotonic, total_s)
        self.sheds = []                  # (t_monotonic, reason)
        self.submitted = 0
        self.failed = 0

    def _on_done(self, fut):
        try:
            r = fut.result()
        except self._shed_error as e:
            with self._lock:
                self.sheds.append((time.monotonic(), e.reason))
            return
        except Exception:
            with self._lock:
                self.failed += 1
            return
        with self._lock:
            self.completions.append((time.monotonic(), float(r.total_s)))

    def run(self):
        from smartcal_tpu.serve.router import Job

        rng = np.random.default_rng(self.seed)
        t_end = time.monotonic() + self.duration_s
        next_t = time.monotonic()
        while True:
            next_t += rng.exponential(1.0 / self.rate)
            if next_t > t_end:
                return
            delay = next_t - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            k, ep, ov = self.pool[int(rng.integers(len(self.pool)))]
            rho = None
            if rng.random() < 0.5:       # pinned-rho exploration stream
                rho = np.exp(rng.uniform(np.log(0.1), np.log(10.0),
                                         k)).astype(np.float32)
            job = Job(episode=ep, k=k, rho=rho, obs_vec=ov,
                      trace=tracectx.new_root_carrier())
            self.submitted += 1
            try:
                fut = self.server.submit(job)
            except self._shed_error as e:
                with self._lock:
                    self.sheds.append((time.monotonic(), e.reason))
                continue
            fut.add_done_callback(self._on_done)

    def snapshot(self):
        with self._lock:
            return (list(self.completions), list(self.sheds),
                    self.submitted, self.failed)


def run_eval(server, eval_pool, timeout_s=60.0):
    """Re-score the held-out pool through the policy path (rho=None)
    and return mean sigma_res; eval jobs ride the live server — the
    measurement itself is served traffic."""
    from smartcal_tpu.serve.router import Job, ShedError

    futs = []
    for k, ep, ov in eval_pool:
        job = Job(episode=ep, k=k, rho=None, obs_vec=ov,
                  trace=tracectx.new_root_carrier())
        try:
            futs.append(server.submit(job))
        except ShedError:
            continue
    vals = []
    t0 = time.monotonic()
    for f in futs:
        left = timeout_s - (time.monotonic() - t0)
        try:
            vals.append(float(f.result(timeout=max(0.1, left)).sigma_res))
        except Exception:
            continue
    return (float(np.mean(vals)) if vals else float("nan")), len(vals)


def p99_windows(completions, swap_times, window_s):
    """Per-swap (pre_p99, post_p99) over ``window_s`` either side, from
    the (t_done, total_s) series.  A window with < 3 completions has no
    meaningful percentile and reports None."""
    out = []
    for t_swap in swap_times:
        pre = [s for t, s in completions if t_swap - window_s <= t < t_swap]
        post = [s for t, s in completions if t_swap <= t < t_swap + window_s]
        out.append({
            "pre_p99_s": (round(float(np.percentile(pre, 99)), 4)
                          if len(pre) >= 3 else None),
            "post_p99_s": (round(float(np.percentile(post, 99)), 4)
                           if len(post) >= 3 else None),
            "pre_n": len(pre), "post_n": len(post),
        })
    return out


def trace_continuity(metrics_path, t_wall_start):
    """Scan the run's JSONL for serve_request events inside the serving
    window: every one must carry its trace id (the request's span tree
    survives hot-swaps).  Returns (n_events, n_missing_trace) or None
    when no stream was recorded."""
    if not metrics_path or not os.path.exists(metrics_path):
        return None
    n = missing = 0
    try:
        with open(metrics_path) as fh:
            for line in fh:
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if ev.get("event") != "serve_request":
                    continue
                if float(ev.get("t", 0.0)) < t_wall_start:
                    continue
                n += 1
                if not ev.get("trace"):
                    missing += 1
    except OSError:
        return None
    return {"serve_requests": n, "missing_trace": missing,
            "continuous": missing == 0}


def main(argv=None):
    args = parse_args(argv)
    from smartcal_tpu.envs import radio
    from smartcal_tpu.rl import sac
    from smartcal_tpu.serve import (CalibServer, PolicyPublisher,
                                    ServingLearner, TransitionStage,
                                    build_obs_pool, enable_compile_cache)

    tobs = blocks.train_obs_from_args(args, "serve_learn",
                                      tier=args.tier, lanes=args.lanes)
    t_boot = time.time()
    # persistent XLA cache BEFORE the first compile (jax latches the
    # decision at first use)
    enable_compile_cache(args.cache_dir)
    backend = radio.RadioBackend(**TIERS[args.tier])
    obs_dim = backend.npix * backend.npix + (args.M + 1) * 7
    cfg = sac.SACConfig(obs_dim=obs_dim, n_actions=2 * args.M,
                        mem_size=args.mem_size,
                        batch_size=args.batch_size,
                        is_clip=args.is_clip, ere_eta=args.ere_eta)
    learner = ServingLearner(cfg, seed=args.seed,
                             n_shards=args.replay_shards,
                             publish_every=args.publish_every)
    stage = TransitionStage(cap=args.stage_cap)
    srv = CalibServer(backend, M=args.M, lanes=args.lanes,
                      cache_dir=args.cache_dir,
                      policy=(cfg, learner.actor_params),
                      transition_sink=stage,
                      max_wait_s=args.max_wait_ms / 1e3,
                      max_queue=args.max_queue)
    warm = srv.warmup(seed=args.seed)
    learner.publisher = PolicyPublisher(srv,
                                        keep_versions=args.keep_versions)
    learner.warm()                       # compile ingest+learn pre-window
    boot_s = round(time.time() - t_boot, 3)
    tobs.echo(f"server+learner up in {boot_s}s (warmup {warm['wall_s']}s,"
              f" programs {warm['sources']})")

    pool = build_obs_pool(backend, args.M, args.pool, seed=args.seed + 1)
    eval_pool = build_obs_pool(backend, args.M, args.eval_pool,
                               seed=args.seed + 101)
    srv.start()
    c0 = obs.counters_snapshot()         # the zero-compile window opens
    t_wall_start = time.time()
    t_start = time.monotonic()
    load = _LoadThread(srv, pool, rate=args.rate,
                       duration_s=args.duration, seed=args.seed)
    load.start()

    swaps = []                           # (t_monotonic, publish record)
    sigma_track = []                     # held-out trajectory
    next_eval = t_start                  # first eval scores version 0
    last_gauge = 0.0
    while load.is_alive() or srv.batcher.depth() > 0:
        tick_end = time.monotonic() + args.learn_every_s
        learner.ingest(stage.drain())
        for _ in range(args.learn_steps):
            learner.step()
        pub = learner.maybe_publish()
        if pub is not None:
            swaps.append((time.monotonic(), pub))
            tobs.echo(f"hot-swap -> v{pub['version']} "
                      f"(publish {pub['publish_s']*1e3:.1f} ms)")
        now = time.monotonic()
        if now - last_gauge >= 2.0:
            last_gauge = now
            st = learner.staleness()
            obs.gauge_set("replay_staleness_mean", st["staleness_mean"])
            obs.gauge_set("replay_stale_frac", st["stale_frac"])
            m = learner.step(pull_metrics=True)
            for key in ("staleness_mean", "is_clip_mean",
                        "is_clip_saturation"):
                if key in (m or {}):
                    obs.gauge_set(f"learn_{key}", m[key])
        if now >= next_eval:
            next_eval += args.eval_every_s
            ver = srv.policy_version
            sig, n_ok = run_eval(srv, eval_pool)
            sigma_track.append({"t_s": round(now - t_start, 2),
                                "version": ver,
                                "sigma_res_mean": round(sig, 4),
                                "n": n_ok})
            tobs.echo(f"eval @v{ver}: sigma_res {sig:.3f} ({n_ok} jobs)")
        time.sleep(max(0.0, tick_end - time.monotonic()))
        if not load.is_alive() and srv.batcher.depth() == 0:
            break
    # final held-out eval at the last published version
    ver = srv.policy_version
    sig, n_ok = run_eval(srv, eval_pool)
    sigma_track.append({"t_s": round(time.monotonic() - t_start, 2),
                        "version": ver, "sigma_res_mean": round(sig, 4),
                        "n": n_ok})
    learner.ingest(stage.drain())
    c1 = obs.counters_snapshot()
    srv.stop()

    completions, sheds, submitted, failed = load.snapshot()
    swap_times = [t for t, _ in swaps]
    pubs = [p for _, p in swaps]
    publish_ms = sorted(p["publish_s"] * 1e3 for p in pubs)
    windows = p99_windows(completions, swap_times, args.swap_window_s)
    # a swap is p99-flat when the post window is within 1.5x + 100 ms of
    # the pre window (generous vs the PR 19 serve_batch noise band; the
    # claim is "no publication spike", not "zero jitter")
    flat = all(w["pre_p99_s"] is None or w["post_p99_s"] is None
               or w["post_p99_s"] <= 1.5 * w["pre_p99_s"] + 0.1
               for w in windows)
    pub_sheds = [t for t, _ in sheds
                 if any(abs(t - ts) <= 1.0 for ts in swap_times)]
    steady_compiles = (c1.get("jax_compile_events", 0.0)
                       - c0.get("jax_compile_events", 0.0))
    lat = np.asarray([s for _, s in completions]) if completions else None
    first = next((s for s in sigma_track
                  if np.isfinite(s["sigma_res_mean"])), None)
    last = next((s for s in reversed(sigma_track)
                 if np.isfinite(s["sigma_res_mean"])), None)
    improvement = (round(1.0 - last["sigma_res_mean"]
                         / first["sigma_res_mean"], 4)
                   if first and last and first is not last
                   and first["sigma_res_mean"] > 0 else None)
    record = {
        "bench": "serve_learn",
        "tier": args.tier, "M": args.M, "lanes": args.lanes,
        "rate": args.rate, "duration_s": args.duration,
        "is_clip": args.is_clip, "ere_eta": args.ere_eta,
        "publish_every": args.publish_every,
        "boot_s": boot_s, "warmup": warm,
        "serving": {
            "submitted": submitted, "completed": len(completions),
            "shed": len(sheds), "failed": failed,
            "latency_p50_s": (round(float(np.percentile(lat, 50)), 4)
                              if lat is not None else None),
            "latency_p99_s": (round(float(np.percentile(lat, 99)), 4)
                              if lat is not None else None),
            "steady_compile_events": steady_compiles,
            "stats": srv.stats(),
        },
        "lifecycle": {
            "swaps": len(swaps),
            "publish_ms_p50": (round(float(np.percentile(publish_ms, 50)),
                                     2) if publish_ms else None),
            "publish_ms_p99": (round(float(np.percentile(publish_ms, 99)),
                                     2) if publish_ms else None),
            "publish_ms": [round(m, 2) for m in publish_ms],
            "publication_sheds": len(pub_sheds),
            "swap_p99_windows": windows,
            "p99_flat_across_swaps": flat,
            "teed": stage.stats(),
            "learner": {"learns": learner.learns,
                        "ingested": learner.ingested,
                        "version": learner.version,
                        "staleness": learner.staleness(),
                        "metrics": learner.last_metrics},
            "sigma_res_trajectory": sigma_track,
            "sigma_res_improvement": improvement,
            "trace_continuity": trace_continuity(args.metrics,
                                                 t_wall_start),
        },
        "wall_s": round(time.time() - t_boot, 3),
    }
    obs.flush_counters()
    tobs.close()
    print(json.dumps(record, indent=1))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        tmp = args.out + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(record, fh, indent=1)
        os.replace(tmp, args.out)
    if steady_compiles:
        print(f"WARNING: {steady_compiles:.0f} compile events in the "
              "serving window (expected 0)", file=sys.stderr)
    return record


if __name__ == "__main__":
    main()
