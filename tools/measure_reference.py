"""Measure the reference implementation's elasticnet SAC throughput.

The upstream repo publishes no numbers (BASELINE.md), so the baseline is
produced by running the reference code itself (read-only mount at
/root/reference) in its `main_sac.py` configuration: N=M=20, batch 64,
mem 1024, 5 steps/episode, torch CPU (no GPU in this image — the reference
falls back to CPU automatically).

Protocol (mirrored by bench.py for the TPU build):
  1. run warm-up env steps until the replay buffer holds >= batch_size
     transitions (learn() is a no-op before that, enet_sac.py:556-557);
  2. time `--steps` full loop iterations (choose_action + env.step +
     store_transition + learn).

Writes the result to stdout and to repo tools/reference_baseline.json.
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, "/root/reference/elasticnet")

import numpy as np  # noqa: E402
import torch  # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    np.random.seed(args.seed)
    torch.manual_seed(args.seed)

    # run in a temp dir: the reference Agent writes checkpoints to ./
    with tempfile.TemporaryDirectory() as tmp:
        os.chdir(tmp)
        from enetenv import ENetEnv
        from enet_sac import Agent

        N = M = 20
        env = ENetEnv(M, N, provide_hint=False)
        agent = Agent(gamma=0.99, batch_size=64, n_actions=2, tau=0.005,
                      max_mem_size=1024, input_dims=[N + N * M],
                      lr_a=1e-3, lr_c=1e-3, reward_scale=N, alpha=0.03,
                      prioritized=False, use_hint=False)

        obs = env.reset()
        # warm-up: fill the buffer so learn() is active during timing
        warm = 0
        t_warm0 = time.time()
        while agent.replaymem.mem_cntr < 64:
            action = agent.choose_action(obs)
            obs2, reward, done, info = env.step(action)
            agent.store_transition(obs, action, reward, obs2, done,
                                   np.zeros_like(action))
            agent.learn()
            obs = obs2
            warm += 1
            if warm % 5 == 0:
                obs = env.reset()
        t_warm = time.time() - t_warm0

        t0 = time.time()
        for i in range(args.steps):
            action = agent.choose_action(obs)
            obs2, reward, done, info = env.step(action)
            agent.store_transition(obs, action, reward, obs2, done,
                                   np.zeros_like(action))
            agent.learn()
            obs = obs2
            if (i + 1) % 5 == 0:
                obs = env.reset()
        wall = time.time() - t0

    result = {
        "metric": "enet_sac_env_steps_per_sec",
        "value": round(args.steps / wall, 3),
        "steps": args.steps,
        "wall_s": round(wall, 2),
        "warmup_steps": warm,
        "warmup_s": round(t_warm, 2),
        "config": "reference elasticnet main_sac.py (N=M=20, batch 64)",
        "hardware": "torch CPU (this host)",
    }
    print(json.dumps(result))
    out = os.path.join(repo_root, "tools", "reference_baseline.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")


if __name__ == "__main__":
    main()
