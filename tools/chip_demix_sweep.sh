#!/bin/bash
# Staged chip-scale demixing hint experiment (the discrimination run from
# results/demix_curves_r3/README.md: "environment too clean" vs "N=62
# scale required").  Runs ONE paired seed of the light-depth sweep at the
# LOFAR station count on the chip, probe-gated like tools/capture_r3.sh.
# Fire when the tunnel is healthy and no other TPU client is running:
#
#   bash tools/chip_demix_sweep.sh [SEED] [EPISODES] 2>&1 | tee -a /tmp/chip_demix.log
#
# Cost estimate: the hint arm is ~32 masked solves/episode; at N=62 light
# depth each fused solve is seconds on the chip, so one 100-episode paired
# seed is roughly 1-3 h of tunnel time.  Artifacts land in
# results/demix_curves_n62/ and are analyzed by summarize_demix_curves.py.
set -uo pipefail
cd "$(dirname "$0")/.." || exit 1

SEED=${1:-0}
EPISODES=${2:-100}
OUTDIR=results/demix_curves_n62

probe=$(timeout --kill-after=15 150 python -c \
  "import jax; print(jax.devices()[0].platform)" 2>/dev/null | tail -1)
if [ "$probe" != "axon" ] && [ "$probe" != "tpu" ]; then
  echo "TPU not reachable (probe: '$probe') — aborting chip demix sweep" >&2
  exit 1
fi

mkdir -p "$OUTDIR"
SMARTCAL_CLEAR_EVERY=100 python tools/sweep_demix.py --light \
  --stations 62 --seed0 "$SEED" --seeds 1 --episodes "$EPISODES" \
  --platform axon --outdir "$OUTDIR" || {
    echo "sweep failed — NOT summarizing partial artifacts" >&2
    echo "(delete the truncated <tag>.jsonl before re-running its tag)" >&2
    exit 1
  }
python tools/summarize_demix_curves.py "$OUTDIR"
