#!/bin/bash
# Async actor-learner fleet smoke, two phases:
#
# Phase 1 (threads, the PR 10 chain): record a short supervised fleet
# run with the IS-clip armed, kill actor 1 mid-run through the
# deterministic fault plan (SMARTCAL_FAULTS), and assert from the
# RunLog that
#
#   * the fault fired and the supervisor restarted the slot
#     (fault_injected -> actor_down -> actor_restart),
#   * the staleness-in-versions gauge was emitted,
#   * the learner kept making progress (non-empty episode stream with
#     finite scores after the kill).
#
# Phase 2 (PROCESSES, the ISSUE 12 chain): the same kill against
# --actor-mode process with the mesh-sharded replay armed — the fault
# fires inside a spawned WORKER PROCESS, the worker dies, the
# supervisor restarts the slot skipping the poison iteration, and the
# per-slot ingest-depth + shard-occupancy gauges are present.  (The
# fault_injected event is logged in the worker's process, which has no
# RunLog — actor_down's recorded reason carries the FaultInjected
# signature instead.)
#
# The CI companion of smoke_obs.sh / smoke_ckpt.sh; ~3 min on CPU.
#
#   bash tools/smoke_fleet.sh [workdir]
#
# Exits non-zero on any broken link in the chain.
set -euo pipefail
cd "$(dirname "$0")/.." || exit 1

REPO="$PWD"
WORK="${1:-$(mktemp -d /tmp/smoke_fleet.XXXXXX)}"
RUN="$WORK/smoke_fleet.jsonl"
mkdir -p "$WORK"

echo "[smoke_fleet] recording supervised fleet run (kill actor 1 at" \
     "iteration 1) -> $RUN" >&2
(cd "$WORK" && PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
    JAX_PLATFORMS=cpu \
    SMARTCAL_FAULTS='{"kill_actor": 1, "kill_at": 1}' \
    python -m smartcal_tpu.parallel.learner \
    --supervised --episodes 14 --n-actors 2 --batch-envs 2 \
    --is-clip 2.0 --metrics "$RUN" --diag --quiet)

python - "$RUN" <<'EOF'
import json
import math
import sys

events = [json.loads(ln) for ln in open(sys.argv[1]) if ln.strip()]
kinds = [e.get("event") for e in events]

# 1. the kill fired and the supervisor recovered the slot
assert "fault_injected" in kinds, f"no fault_injected event: {sorted(set(kinds))}"
downs = [e for e in events if e.get("event") == "actor_down"]
assert downs and downs[0]["actor"] == 1, f"no actor_down for actor 1: {downs}"
restarts = [e for e in events if e.get("event") == "actor_restart"]
assert restarts, "supervisor never restarted the killed actor"
assert restarts[0]["iteration"] == 2, \
    f"poison iteration not skipped: {restarts[0]}"

# 2. the staleness gauge stream exists
gauges = {e["name"] for e in events if e.get("event") == "gauge"}
assert "weight_staleness_versions" in gauges, \
    f"no staleness gauge: {sorted(gauges)}"
assert "is_clip_saturation" in gauges, \
    f"no clip-saturation gauge (IS-clip armed): {sorted(gauges)}"

# 3. the learner kept making progress past the kill
episodes = [e for e in events if e.get("event") == "episode"]
assert len(episodes) >= 6, f"too few learner episodes: {len(episodes)}"
assert all(math.isfinite(e["score"]) for e in episodes), "non-finite scores"
assert episodes[-1]["episode"] >= 5, "learner stalled after the kill"

print("[smoke_fleet] OK:", len(episodes), "episodes,",
      len(restarts), "restart(s), gauges:",
      sorted(g for g in gauges if "staleness" in g or "clip" in g))
EOF

RUN2="$WORK/smoke_fleet_proc.jsonl"
echo "[smoke_fleet] phase 2: PROCESS fleet (kill actor-1 worker at" \
     "iteration 1, sharded replay) -> $RUN2" >&2
(cd "$WORK" && PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
    JAX_PLATFORMS=cpu \
    SMARTCAL_FAULTS='{"kill_actor": 1, "kill_at": 1}' \
    python -m smartcal_tpu.parallel.learner \
    --supervised --actor-mode process --replay-shards 4 \
    --episodes 10 --n-actors 2 --batch-envs 2 \
    --is-clip 2.0 --metrics "$RUN2" --diag --quiet)

python - "$RUN2" <<'EOF'
import json
import math
import sys

events = [json.loads(ln) for ln in open(sys.argv[1]) if ln.strip()]
kinds = [e.get("event") for e in events]

# 1. the worker-process death was detected and the slot recovered.
# The fault fires INSIDE the worker process (no RunLog there): the
# supervisor's actor_down reason carries the FaultInjected signature.
downs = [e for e in events if e.get("event") == "actor_down"]
assert downs and downs[0]["actor"] == 1, f"no actor_down for actor 1: {downs}"
assert "FaultInjected" in downs[0]["reason"], downs[0]
restarts = [e for e in events if e.get("event") == "actor_restart"]
assert restarts, "supervisor never restarted the killed worker process"
assert restarts[0]["iteration"] == 2, \
    f"poison iteration not skipped: {restarts[0]}"

# 2. the process-fleet gauge surface: per-slot ingest depth + shard
# occupancy + the staleness pair
gauges = {e["name"] for e in events if e.get("event") == "gauge"}
for need in ("ingest_queue_depth", "replay_shard_occupancy",
             "weight_staleness_versions", "is_clip_saturation"):
    assert need in gauges, f"missing gauge {need}: {sorted(gauges)}"
slots = {e.get("slot") for e in events if e.get("event") == "gauge"
         and e["name"] == "ingest_queue_depth" and "slot" in e}
assert {0, 1} <= slots, f"per-slot depth gauges missing: {slots}"

# 3. the learner kept making progress past the worker kill
episodes = [e for e in events if e.get("event") == "episode"]
assert len(episodes) >= 5, f"too few learner episodes: {len(episodes)}"
assert all(math.isfinite(e["score"]) for e in episodes), "non-finite scores"

print("[smoke_fleet] phase 2 OK:", len(episodes), "episodes,",
      len(restarts), "process restart(s), per-slot gauges:", sorted(slots))
EOF

echo "[smoke_fleet] PASS (workdir $WORK)" >&2
