#!/usr/bin/env python
"""graftlint CLI — the repo's JAX-aware static-analysis gate (ISSUE 11).

Usage::

    python tools/lint.py [paths ...]        # default: smartcal_tpu tools tests
    python tools/lint.py --json             # machine output (stable order)
    python tools/lint.py --changed          # only git-touched files (pre-commit)
    python tools/lint.py --types            # typed-core gate (mypy or audit)
    python tools/lint.py --list-rules       # rule table
    python tools/lint.py --update-baseline  # re-grandfather current findings

Exit codes: 0 clean (no NEW findings), 1 findings, 2 internal/usage error.
Findings already recorded in ``graftlint.baseline.json`` (each with a
mandatory reason) don't fail the gate; stale baseline entries are
reported so the debt list shrinks instead of rotting.

This file's stdout IS its product (text report or ``--json`` document) —
it is on the bare-print allowlist deliberately.
"""

import argparse
import json
import os
import subprocess
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from smartcal_tpu import analysis                      # noqa: E402
from smartcal_tpu.analysis import baseline as bl       # noqa: E402
from smartcal_tpu.analysis import typecheck            # noqa: E402

DEFAULT_PATHS = ("smartcal_tpu", "tools", "tests")


def changed_files(root):
    """Python files touched per git (staged, unstaged, untracked)."""
    try:
        out = subprocess.run(
            ["git", "status", "--porcelain", "--untracked-files=all"],
            capture_output=True, text=True, cwd=root, check=True).stdout
    except (OSError, subprocess.CalledProcessError) as e:
        # usage/environment error, not findings: honor the exit-2 contract
        sys.stderr.write(f"lint: --changed needs git ({e})\n")
        raise SystemExit(2)
    from smartcal_tpu.analysis.core import is_excluded
    files = []
    for line in out.splitlines():
        if len(line) < 4 or line[:2] == "D " or line[1] == "D":
            continue
        path = line[3:].split(" -> ")[-1].strip().strip('"')
        ap = os.path.join(root, path)
        if path.endswith(".py") and os.path.exists(ap) \
                and not is_excluded(ap):
            files.append(path)
    return sorted(set(files))


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="lint.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to lint (default: "
                         f"{' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output (deterministic)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of rules to run")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: graftlint.baseline.json "
                         "at the repo root)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write current findings as the new baseline "
                         "(carries forward existing reasons)")
    ap.add_argument("--changed", action="store_true",
                    help="lint only git-modified/untracked .py files")
    ap.add_argument("--types", action="store_true",
                    help="run the typed-core gate (mypy when available, "
                         "else the built-in annotation audit)")
    ap.add_argument("--root", default=_ROOT, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    root = os.path.abspath(args.root)

    try:
        rules = analysis.all_rules()
    except Exception as e:  # registry import failure is an internal error
        sys.stderr.write(f"lint: rule registry failed to load: {e!r}\n")
        return 2

    if args.list_rules:
        rows = [(name, r.doc) for name, r in sorted(rules.items())]
        rows.append((analysis.BAD_SUPPRESSION,
                     "disable comment without a reason or naming an "
                     "unknown rule (driver meta-rule)"))
        rows.append((analysis.PARSE_ERROR,
                     "file does not parse (driver meta-rule)"))
        rows.append((typecheck.UNTYPED_DEF,
                     "strict-core def missing annotations "
                     "(--types audit mode)"))
        rows.append((typecheck.MYPY_ERROR,
                     "mypy error in the strict core (--types, mypy "
                     "available)"))
        if args.as_json:
            print(json.dumps({"rules": [{"name": n, "doc": d}
                                        for n, d in rows]}, indent=1))
        else:
            width = max(len(n) for n, _ in rows)
            for n, d in rows:
                print(f"{n:<{width}}  {d}")
        return 0

    if args.rules:
        want = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = want - set(rules)
        if unknown:
            sys.stderr.write(
                f"lint: unknown rule(s): {', '.join(sorted(unknown))} "
                "(see --list-rules)\n")
            return 2
        rules = {k: v for k, v in rules.items() if k in want}

    if args.changed:
        paths = changed_files(root)
        if not paths:
            # nothing to lint — but --types is an independent gate and
            # must still run (a pre-commit hook wired with both flags
            # must never skip the typed core silently)
            types_findings, types_mode = ([], None)
            if args.types:
                types_findings, types_mode = typecheck.run_types(root)
            if args.as_json:
                doc = {"findings": [f.as_dict() for f in types_findings],
                       "new": len(types_findings), "checked": 0,
                       "mode": "changed"}
                if types_mode:
                    doc["types_mode"] = types_mode
                print(json.dumps(doc, indent=1))
            else:
                for f in types_findings:
                    print(f.render())
                tail = "graftlint: no changed python files"
                if types_mode:
                    tail += (f"; types gate via {types_mode}: "
                             f"{len(types_findings)} finding(s)")
                print(tail)
            return 1 if types_findings else 0
    else:
        paths = list(args.paths) if args.paths else list(DEFAULT_PATHS)

    try:
        findings = analysis.lint_paths(paths, root, rules=rules)
        scanned = [analysis.core.relpath(f, root) for f in
                   analysis.iter_python_files(paths, root)]
    except Exception as e:
        sys.stderr.write(f"lint: internal error: {e!r}\n")
        return 2

    baseline_path = args.baseline or os.path.join(root,
                                                  bl.DEFAULT_BASELINE)
    if args.update_baseline:
        # a partial run must never rewrite the whole-repo debt record:
        # entries for files outside the subset would be dropped silently
        full_scope = (not args.changed and not args.rules
                      and sorted(paths) == sorted(DEFAULT_PATHS))
        if not full_scope:
            sys.stderr.write(
                "lint: --update-baseline requires the full default scope "
                f"({' '.join(DEFAULT_PATHS)}; no --changed/--rules) — a "
                "subset rewrite would delete out-of-scope baseline "
                "entries\n")
            return 2
        old = {}
        try:
            old = bl.load(baseline_path)
        except bl.BaselineError:
            pass  # rewriting anyway
        bl.save(baseline_path, findings, reasons=old)
        kept = [f for f in findings if f.rule not in bl.UNBASELINEABLE]
        print(f"graftlint: baseline updated with {len(kept)} "
              f"finding(s) -> {os.path.relpath(baseline_path, root)}")
        return 0

    baseline = {}
    if not args.no_baseline:
        try:
            baseline = bl.load(baseline_path)
        except bl.BaselineError as e:
            sys.stderr.write(f"lint: {e}\n")
            return 2
    new, grandfathered, stale = bl.split(findings, baseline,
                                         scanned_paths=scanned,
                                         rules_run=list(rules))

    types_findings, types_mode = [], None
    if args.types:
        types_findings, types_mode = typecheck.run_types(root)
        new = sorted(new + types_findings)

    n_files = len(scanned)
    if args.as_json:
        doc = {
            "findings": [f.as_dict() for f in new],
            "grandfathered": [f.as_dict() for f in grandfathered],
            "stale_baseline": stale,
            "new": len(new),
            "checked": n_files,
            "rules": sorted(rules),
        }
        if types_mode:
            doc["types_mode"] = types_mode
        print(json.dumps(doc, indent=1))
    else:
        for f in new:
            print(f.render())
        if stale:
            print(f"graftlint: {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (fixed debt — "
                  "prune with --update-baseline):")
            for s in stale:
                print(f"  {s['rule']} {s['path']} [{s['fingerprint']}]")
        tail = (f"graftlint: {len(new)} finding(s) "
                f"({len(grandfathered)} grandfathered) over {n_files} "
                f"file(s)")
        if types_mode:
            tail += f"; types gate via {types_mode}"
        print(tail)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
