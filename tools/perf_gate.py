#!/usr/bin/env python
"""perf_gate — the regression radar's tier-1 gate (graftlint for perf).

A deterministic micro-bench tier over the repo's load-bearing stages —
tiny-scale batched solve, influence chain, factored imager, the
sharded-replay fused step, and one warmed serve batch — measured in
minutes, not the 30-minute bench, then judged against the
host-fingerprinted baseline store (``smartcal_tpu/obs/baselines.py``)
by the noise-aware detector (``smartcal_tpu/obs/regress.py``).

Usage::

    python tools/perf_gate.py --update-baseline     # bless this host
    python tools/perf_gate.py                       # gate: 1 on FIRE
    python tools/perf_gate.py --json --out gate.json
    python tools/perf_gate.py --stages solve,serve_batch

Per stage the gate measures K wall-clock samples (noise model for the
bootstrap CI), XLA cost-analysis flops + peak bytes, the compile-event
count across the timed reps (must stay 0 — a recompile IS a
regression), and one deterministic numeric scalar whose relative drift
vs the blessed value is judged against the documented bf16 band.
Baselines are keyed on stage + statics signature + host fingerprint,
so a baseline recorded elsewhere is a NO BASELINE (never a bogus
compare) here.

Fault hooks (``runtime/faults.py``, armed via ``SMARTCAL_FAULTS``):
``gate_<stage>`` delays inside the timed reps and
``gate_numeric_<stage>`` perturbs the numeric scalar — how
``tools/smoke_perfgate.sh`` proves both detector axes end to end.

Exit codes: 0 clean (or baseline updated), 1 at least one FIRE,
2 internal/usage error.  This file's stdout IS its product — it is on
the bare-print allowlist deliberately.
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    # the sharded-replay stage needs the tests' virtual 8-device mesh
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

DEFAULT_BASELINE = os.path.join("results", "perf_baselines.json")
DEFAULT_CACHE = os.path.join("/tmp", "smartcal_perfgate_cache")

#: the serving tests' tiny problem shape — small enough that the whole
#: gate runs in minutes on the 1-core container
TIER = dict(n_stations=6, n_freqs=2, n_times=4, tdelta=2, admm_iters=2,
            lbfgs_iters=3, init_iters=5, npix=32)
M, LANES = 3, 3
K_SAMPLES = 5
STAGE_NAMES = ("solve", "influence", "imager", "replay_fused",
               "serve_batch", "publish")


def build_stages(names, cache_dir):
    """Construct the requested stages: each a dict with ``statics``
    (baseline key material), ``run()`` (one timed rep -> numeric
    scalar) and optional ``cost()`` (XLA cost analysis)."""
    import jax
    import numpy as np

    from smartcal_tpu import obs
    from smartcal_tpu.envs.radio import RadioBackend

    be = RadioBackend(**TIER)
    key = jax.random.PRNGKey(0)
    eps = []
    for _ in range(LANES):
        key, k = jax.random.split(key)
        ep, _ = be.new_calib_episode(k, M, M)
        eps.append(ep)
    bep = be.stack_episodes(eps)
    rho = np.ones((LANES, M), np.float32)
    mask = np.ones((LANES, M), np.float32)
    alpha = np.zeros((LANES, M), np.float32)
    iters = np.full((LANES,), TIER["admm_iters"], np.int32)
    sig = be.serve_signature(M, LANES, TIER["npix"])
    stages = {}

    solve_fn = jax.jit(be.batched_solve_callable(M))
    sops = be.batched_solve_operands(bep, rho, mask, iters)

    def run_solve():
        r = solve_fn(*sops)
        jax.block_until_ready(r.sigma_res)
        return float(np.mean(np.abs(np.asarray(r.sigma_res))))

    stages["solve"] = {
        "statics": dict(sig, stage="solve"),
        "run": run_solve,
        "cost": lambda: obs.stage_cost(solve_fn, *sops),
    }

    res = solve_fn(*sops)
    infl_fn = jax.jit(be.batched_influence_callable(M, TIER["npix"]))
    iops = be.batched_influence_operands(bep, res, rho, alpha)

    def run_influence():
        imgs = infl_fn(*iops)
        jax.block_until_ready(imgs)
        return float(np.std(np.asarray(imgs)))

    stages["influence"] = {
        "statics": dict(sig, stage="influence"),
        "run": run_influence,
        "cost": lambda: obs.stage_cost(infl_fn, *iops),
    }

    from smartcal_tpu.cal import imager as im

    ep0 = eps[0]
    cell = im.default_cell(ep0.obs.uvw,
                           float(np.asarray(ep0.obs.freqs)[-1]))
    img_fn = jax.jit(lambda uvw, V, freqs: im.multifreq_image_sr(
        uvw, V, freqs, cell, npix=TIER["npix"]))

    def run_imager():
        img = img_fn(ep0.obs.uvw, ep0.V, ep0.obs.freqs)
        jax.block_until_ready(img)
        return float(np.std(np.asarray(img)))

    stages["imager"] = {
        "statics": {"stage": "imager", "npix": TIER["npix"],
                    "n_stations": TIER["n_stations"],
                    "n_freqs": TIER["n_freqs"],
                    "n_times": TIER["n_times"]},
        "run": run_imager,
        "cost": lambda: obs.stage_cost(
            img_fn, ep0.obs.uvw, ep0.V, ep0.obs.freqs),
    }

    if "replay_fused" in names:
        stages["replay_fused"] = _build_replay_stage()
    if "serve_batch" in names:
        stages["serve_batch"] = _build_serve_stage(be, cache_dir)
    if "publish" in names:
        stages["publish"] = _build_publish_stage(be, cache_dir)
    return {n: stages[n] for n in names if n in stages}


def _build_replay_stage():
    """The ISSUE 12 fused store->PER/ERE sample->learn->priority step
    on the 4-shard virtual mesh (the tests' exact composition)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from smartcal_tpu.rl import replay as rp
    from smartcal_tpu.rl import replay_sharded as rps
    from smartcal_tpu.rl import sac

    S, n = 4, 32
    cfg = sac.SACConfig(obs_dim=6, n_actions=2, prioritized=True,
                        is_clip=2.0, ere_eta=0.99, batch_size=8,
                        mem_size=64)
    mesh = Mesh(np.asarray(jax.devices()[:S]), ("rp",))
    repl = NamedSharding(mesh, P())
    spec = rp.versioned_spec(rp.transition_spec(cfg.obs_dim,
                                                cfg.n_actions))
    buf = rps.place_on_mesh(rps.replay_init(cfg.mem_size, spec, S), mesh)
    st = sac.sac_init(jax.random.PRNGKey(7), cfg)
    k_obs, k_act = jax.random.split(jax.random.PRNGKey(11))
    obs_b = jax.random.normal(k_obs, (n, cfg.obs_dim))
    a, lp = sac.choose_action_logp(cfg, st, obs_b, k_act)
    flat = {"state": obs_b, "new_state": obs_b + 0.1, "action": a,
            "reward": (jnp.arange(n) % 3).astype(jnp.float32) - 1.0,
            "done": jnp.zeros((n,), jnp.bool_),
            "hint": jnp.zeros((n, cfg.n_actions)),
            "version": jnp.full((n,), 1, jnp.int32),
            "behavior_logp": lp}

    def fused(st, buf, flat, key, ver):
        buf = rps.replay_add_batch(buf, flat)
        return sac.learn(cfg, st, buf, key, learner_version=ver)

    fused_j = jax.jit(fused)
    st, flat, k0, ver = jax.device_put(
        (st, flat, jax.random.PRNGKey(3), jnp.asarray(2, jnp.int32)),
        repl)

    def run():
        st2, buf2, _ = fused_j(st, buf, flat, k0, ver)
        jax.block_until_ready((st2, buf2))
        return float(np.mean(np.asarray(buf2.priority)))

    from smartcal_tpu import obs as _obs

    return {
        "statics": {"stage": "replay_fused", "shards": S,
                    "obs_dim": cfg.obs_dim, "batch_size": cfg.batch_size,
                    "mem_size": cfg.mem_size, "n_store": n},
        "run": run,
        "cost": lambda: _obs.stage_cost(fused_j, st, buf, flat, k0, ver),
    }


def _build_serve_stage(be, cache_dir):
    """One warmed CalibServer batch: pack -> exported solve ->
    influence -> sigmas on the caller's thread (process_once)."""
    import jax
    import numpy as np

    from smartcal_tpu.serve import CalibServer, Job

    srv = CalibServer(be, M=M, lanes=LANES, cache_dir=cache_dir,
                      compile_cache=False, max_wait_s=0.02)
    srv.warmup(seed=7)
    key = jax.random.PRNGKey(9)
    jeps, ks = [], [2, 3, 2]
    for k in ks:
        key, sub = jax.random.split(key)
        ep, _ = be.new_calib_episode(sub, k, M)
        jeps.append(ep)

    def run():
        jobs = [Job(episode=ep, k=k,
                    rho=np.linspace(0.5 + i, 1.5 + i, k).astype(
                        np.float32),
                    maxiter=TIER["admm_iters"])
                for i, (ep, k) in enumerate(zip(jeps, ks))]
        srv.process_once(jobs, timeout=0.01)
        return float(jobs[0].future.result(timeout=5).sigma_res)

    return {
        "statics": dict(be.serve_signature(M, LANES, TIER["npix"]),
                        stage="serve_batch", jobs=len(ks)),
        "run": run,
        "cost": None,
    }


def _build_publish_stage(be, cache_dir):
    """Warm hot-swap publication latency (the ISSUE 20 serving-side
    half): one versioned ``ExportCache.publish`` + atomic
    ``swap_policy`` against a warmed, policy-armed server per rep.  The
    compile-event metric is the whole point here — the exported policy
    takes the weights as a traced operand, so a publication that
    compiles ANYTHING is a regression of the zero-compile hot-swap
    contract."""
    import jax
    import numpy as np

    from smartcal_tpu.rl import sac
    from smartcal_tpu.serve import CalibServer, PolicyPublisher

    obs_dim = TIER["npix"] * TIER["npix"] + (M + 1) * 7
    cfg = sac.SACConfig(obs_dim=obs_dim, n_actions=2 * M)
    st = sac.sac_init(jax.random.PRNGKey(7), cfg)
    srv = CalibServer(be, M=M, lanes=LANES, cache_dir=cache_dir,
                      compile_cache=False,
                      policy=(cfg, st.actor_params), max_wait_s=0.02)
    srv.warmup(seed=7)
    pub = PolicyPublisher(srv, keep_versions=4)
    heads = jax.jit(lambda p, o: sac.policy_heads(cfg, p, o))
    probe = np.linspace(-0.5, 0.5, obs_dim).astype(np.float32)[None, :]
    ver = [0]

    def run():
        ver[0] += 1
        pub.publish(st.actor_params, ver[0])
        act, _, _ = heads(st.actor_params, probe)
        return float(np.mean(np.abs(np.asarray(act))))

    return {
        "statics": dict(be.serve_signature(M, LANES, TIER["npix"]),
                        stage="publish", obs_dim=obs_dim),
        "run": run,
        "cost": None,
    }


def measure_stage(name, stage, k_samples):
    """K timed reps (after one warm rep) + cost analysis + the numeric
    scalar, as baseline-store metric dicts.  The fault hooks sit INSIDE
    the timed loop / on the numeric so injected regressions are
    measured exactly like real ones."""
    import time as _time

    from smartcal_tpu import obs
    from smartcal_tpu.obs import baselines as bl
    from smartcal_tpu.runtime import faults as rt_faults

    stage["run"]()                       # warm: compile outside timing
    c0 = obs.counters_snapshot().get("jax_compile_events", 0.0)
    walls, numeric = [], 0.0
    for i in range(k_samples):
        t0 = _time.perf_counter()
        rt_faults.maybe_delay(f"gate_{name}", i)
        numeric = stage["run"]()
        walls.append(_time.perf_counter() - t0)
    c1 = obs.counters_snapshot().get("jax_compile_events", 0.0)
    numeric = rt_faults.maybe_perturb(f"gate_numeric_{name}", 0,
                                      float(numeric))
    metrics = {"wall_s": bl.summarize_samples(walls),
               "compile_events": bl.scalar_metric(c1 - c0),
               "numeric": bl.scalar_metric(numeric)}
    if stage.get("cost") is not None:
        try:
            cost = stage["cost"]()
        except Exception:  # cost analysis is best-effort extra
            cost = {}
        for k in ("flops", "peak_bytes"):
            if cost.get(k):
                metrics[k] = bl.scalar_metric(cost[k])
    return metrics


def judge(store, name, statics, fp, metrics):
    """Findings for one stage: wall/bytes/flops/compiles through the
    regular policies, and the numeric scalar folded into a ``rel_err``
    vs the blessed value, judged against the documented bf16 band."""
    from smartcal_tpu.obs import regress as rg

    measured = {k: v for k, v in metrics.items() if k != "numeric"}
    entry = store.get(name, statics, fp)
    if entry is not None and "numeric" in entry.get("metrics", {}):
        base_num = float(entry["metrics"]["numeric"]["value"])
        new_num = float(metrics["numeric"]["value"])
        rel = abs(new_num - base_num) / max(abs(base_num), 1e-12)
        measured["rel_err"] = {"kind": "scalar", "value": rel}
    return rg.compare(store, name, statics, fp, measured)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="perf_gate.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--baseline", default=None,
                    help=f"baseline store (default: {DEFAULT_BASELINE} "
                         "at the repo root)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="record this run as the blessed baseline for "
                         "this host fingerprint")
    ap.add_argument("--stages", default=None,
                    help="comma-separated subset of "
                         f"{','.join(STAGE_NAMES)}")
    ap.add_argument("--samples", type=int, default=K_SAMPLES,
                    help="timed reps per stage (noise model size)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--out", default=None,
                    help="also write the full result document here")
    ap.add_argument("--cache-dir", default=DEFAULT_CACHE,
                    help="serve-stage AOT export cache (stable path => "
                         "warm reruns)")
    args = ap.parse_args(argv)

    names = list(STAGE_NAMES)
    if args.stages:
        names = [s.strip() for s in args.stages.split(",") if s.strip()]
        unknown = set(names) - set(STAGE_NAMES)
        if unknown:
            sys.stderr.write(
                f"perf_gate: unknown stage(s): {', '.join(sorted(unknown))}"
                f" (known: {', '.join(STAGE_NAMES)})\n")
            return 2

    from smartcal_tpu import obs
    from smartcal_tpu.obs import baselines as bl
    from smartcal_tpu.obs import regress as rg
    from smartcal_tpu.runtime import faults as rt_faults
    from smartcal_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    obs.install_compile_listener()
    rt_faults.install_from_env()

    t0 = time.time()
    baseline_path = args.baseline or os.path.join(_ROOT,
                                                  DEFAULT_BASELINE)
    store = bl.BaselineStore(baseline_path)
    fp = bl.host_fingerprint()

    try:
        stages = build_stages(names, args.cache_dir)
    except Exception as e:
        sys.stderr.write(f"perf_gate: stage build failed: {e!r}\n")
        return 2

    doc = {"fingerprint": fp,
           "fingerprint_digest": bl.fingerprint_digest(fp),
           "baseline": os.path.relpath(baseline_path, _ROOT),
           "samples": args.samples, "stages": {}, "findings": []}
    n_fire = n_warn = 0
    for name, stage in stages.items():
        metrics = measure_stage(name, stage, args.samples)
        doc["stages"][name] = {"statics": stage["statics"],
                               "metrics": metrics}
        if args.update_baseline:
            store.record(name, stage["statics"], fp, metrics)
            continue
        try:
            findings = judge(store, name, stage["statics"], fp, metrics)
        except rg.FingerprintMismatch as e:
            sys.stderr.write(f"perf_gate: {e}\n")
            return 2
        for f in findings:
            doc["findings"].append(dataclass_dict(f))
            n_fire += f.verdict == rg.FIRE
            n_warn += f.verdict == rg.WARN
            if not args.as_json:
                print(f.render())

    doc["wall_s"] = round(time.time() - t0, 3)
    if args.update_baseline:
        store.save()
        doc["updated"] = True
        msg = (f"perf_gate: baseline updated for {len(stages)} stage(s) "
               f"on fingerprint {doc['fingerprint_digest']} -> "
               f"{doc['baseline']}")
    else:
        doc["fires"], doc["warns"] = n_fire, n_warn
        msg = (f"perf_gate: {n_fire} FIRE / {n_warn} WARN over "
               f"{len(stages)} stage(s) in {doc['wall_s']}s "
               f"[fingerprint {doc['fingerprint_digest']}]")
    if args.as_json:
        print(json.dumps(doc, indent=1, sort_keys=True))
    else:
        print(msg)
    if args.out:
        from smartcal_tpu.runtime.atomic import atomic_write_text
        atomic_write_text(args.out,
                          json.dumps(doc, indent=1, sort_keys=True) + "\n")
    return 1 if n_fire else 0


def dataclass_dict(f):
    import dataclasses
    d = dataclasses.asdict(f)
    if d.get("ci95"):
        d["ci95"] = list(d["ci95"])
    return d


if __name__ == "__main__":
    sys.exit(main())
