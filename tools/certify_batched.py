"""Curve-parity certification of the batched throughput modes.

VERDICT r4 item 9: the vmapped-env modes (the headline 155-2186
env-steps/s numbers) run ONE learn step per *vector* step — a 1:n_envs
learn:env-step ratio, versus the reference's sequential 1:1 loop
(`elasticnet/main_sac.py:47-76`).  Fast is only useful if it still
trains, so this tool produces the certification artifact: same-seed
sequential vs batched learning curves on equal env-step budgets, with
final-window score statistics.

``--mode enet`` (default) — per seed: sequential = the jitted 1:1
episode loop (`train.enet_sac.make_episode_fn`, the bench primary's
computation); batched = `parallel.make_parallel_sac` with n_envs
vmapped envs in episode-block mode.  Both see the same total env-steps,
and both score units are MEAN STEP REWARD per episode already
(`enet_sac`'s episode body returns ``jnp.mean(rewards)``; the trainer's
block scores are the env-batch mean of the same quantity) — directly
comparable.  The default budget is the reference's full 1000 episodes
(VERDICT r5 #6: the r4 artifact stopped at 300).

``--mode calib`` — the RADIO batched mode (ISSUE 9): sequential = the
real ``train.calib_sac`` episode loop; batched = the same driver with
``--batch-envs n_envs`` (BatchedCalibEnv lanes through
``RadioBackend.calibrate_batched``, one fat learn per vector step).
Scores in both arms are per-episode mean step reward (the batched loop
emits one entry per LANE episode), so the curves compare 1:1.  Radio
episodes cost seconds even at the ``--small`` tier — pass a smaller
``--episodes`` than the enet default.

Usage:
    python tools/certify_batched.py [--mode enet|calib] [--seeds 3] \
        [--episodes 1000] [--n_envs 16] \
        [--outdir results/batched_parity] [--platform cpu]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STEPS = 5   # reference episode length (elasticnet/enetenv.py loop bound)
CALIB_STEPS = 4   # calibration/main_sac.py episode length


def run_calib(args):
    """Radio (CalibEnv) certification: the real calib_sac driver loop,
    sequential vs ``--batch-envs`` batched, equal env-step budgets."""
    import math

    import numpy as np

    from smartcal_tpu.train import calib_sac

    episodes = int(math.ceil(args.episodes / args.n_envs) * args.n_envs)
    bat_window = max(1, args.final_window)
    runs = {"config": {"mode": "calib", "episodes": episodes,
                       "episodes_requested": args.episodes,
                       "n_envs": args.n_envs,
                       "steps_per_episode": CALIB_STEPS,
                       "final_window": args.final_window,
                       "backend": "small tier (N=6, Nf=2, npix=32)"},
            "seeds": {}}
    os.makedirs(args.outdir, exist_ok=True)
    import tempfile

    # model/score side-files go to a scratch dir — the artifact is the
    # parity JSON, not per-seed agent pickles
    scratch = tempfile.mkdtemp(prefix="certify_calib_")
    for seed in range(args.seeds):
        t0 = time.time()
        common = ["--small", "--episodes", str(episodes), "--steps",
                  str(CALIB_STEPS), "--M", "5", "--seed", str(seed),
                  "--quiet"]
        seq = [float(s) for s in calib_sac.main(
            ["--prefix", os.path.join(scratch, f"seq_s{seed}")]
            + common)]
        bat = [float(s) for s in calib_sac.main(
            ["--prefix", os.path.join(scratch, f"bat_s{seed}"),
             "--batch-envs", str(args.n_envs)] + common)]
        w = args.final_window
        runs["seeds"][seed] = {
            "sequential_mean_step_reward": seq,
            "batched_mean_step_reward": bat,
            "seq_final_mean": float(np.mean(seq[-w:])),
            "seq_first_mean": float(np.mean(seq[:w])),
            "bat_final_mean": float(np.mean(bat[-bat_window:])),
            "bat_first_mean": float(np.mean(bat[:bat_window])),
            "wall_s": round(time.time() - t0, 1),
        }
        print(f"seed {seed}: seq final "
              f"{runs['seeds'][seed]['seq_final_mean']:.3f} batched final "
              f"{runs['seeds'][seed]['bat_final_mean']:.3f} "
              f"({runs['seeds'][seed]['wall_s']}s)", flush=True)

    seqf = [r["seq_final_mean"] for r in runs["seeds"].values()]
    batf = [r["bat_final_mean"] for r in runs["seeds"].values()]
    runs["aggregate"] = {
        "seq_final_mean": float(np.mean(seqf)),
        "seq_final_std": float(np.std(seqf)),
        "bat_final_mean": float(np.mean(batf)),
        "bat_final_std": float(np.std(batf)),
        "bat_minus_seq": float(np.mean(batf) - np.mean(seqf)),
    }
    out_json = os.path.join(args.outdir, "parity_calib.json")
    with open(out_json, "w") as fh:
        json.dump(runs, fh, indent=1)
    print(json.dumps(runs["aggregate"]))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--mode", default="enet", choices=["enet", "calib"])
    p.add_argument("--seeds", default=3, type=int)
    p.add_argument("--episodes", default=1000, type=int,
                   help="sequential episodes per seed; the batched arm "
                   "gets the same TOTAL env-steps (default: the "
                   "reference's full 1000-episode budget)")
    p.add_argument("--n_envs", default=16, type=int)
    p.add_argument("--outdir", default="results/batched_parity")
    p.add_argument("--platform", default=None, choices=["cpu", "axon"])
    p.add_argument("--final_window", default=30, type=int)
    args = p.parse_args()

    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    if args.mode == "calib":
        from smartcal_tpu.utils import enable_compilation_cache

        enable_compilation_cache()
        return run_calib(args)
    import numpy as np

    from smartcal_tpu.envs import enet
    from smartcal_tpu.parallel import AXIS_DATA, make_mesh, make_parallel_sac
    from smartcal_tpu.rl import replay as rp
    from smartcal_tpu.rl import sac
    from smartcal_tpu.train.enet_sac import make_episode_fn
    from smartcal_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    os.makedirs(args.outdir, exist_ok=True)

    env_cfg = enet.EnetConfig(M=20, N=20)
    agent_cfg = sac.SACConfig(obs_dim=env_cfg.obs_dim, n_actions=2,
                              gamma=0.99, tau=0.005, batch_size=64,
                              mem_size=1024, lr_a=1e-3, lr_c=1e-3,
                              reward_scale=20.0, alpha=0.03)

    # round --episodes UP to a multiple of n_envs: the batched arm runs
    # episodes // n_envs vector episodes, so a non-multiple silently gave
    # the two arms different env-step budgets (e.g. 150 sequential vs
    # 144 batched at n_envs=16) — the curves compared unequal work
    import math

    episodes = int(math.ceil(args.episodes / args.n_envs) * args.n_envs)
    n_vec_episodes = episodes // args.n_envs
    # the batched final window covers the same fraction of env-steps as
    # the sequential one: ceil, not floor (floor could round a 30-episode
    # window to 1 vector episode where 2 cover it)
    bat_window = max(1, math.ceil(args.final_window / args.n_envs))

    runs = {"config": {"episodes": episodes,
                       "episodes_requested": args.episodes,
                       "n_envs": args.n_envs,
                       "steps_per_episode": STEPS,
                       "final_window": args.final_window,
                       "batched_final_window_vec_episodes": bat_window,
                       # actual env-step budgets of each arm (equal by
                       # construction after rounding; recorded so the
                       # artifact is self-describing)
                       "seq_env_steps": episodes * STEPS,
                       "bat_env_steps": n_vec_episodes * args.n_envs * STEPS},
            "seeds": {}}
    for seed in range(args.seeds):
        t0 = time.time()
        # ---- sequential 1:1 (mean step reward per episode)
        key = jax.random.PRNGKey(seed)
        key, k0 = jax.random.split(key)
        agent_state = sac.sac_init(k0, agent_cfg)
        buf = rp.replay_init(agent_cfg.mem_size,
                             rp.transition_spec(env_cfg.obs_dim, 2))
        episode_fn = make_episode_fn(env_cfg, agent_cfg, STEPS,
                                     use_hint=False)
        seq = []
        for _ in range(episodes):
            key, k = jax.random.split(key)
            agent_state, buf, score = episode_fn(agent_state, buf, k)
            seq.append(float(score))   # already mean step reward

        # ---- batched (episode-block; scores are already mean step
        # reward per episode across the env batch)
        mesh = make_mesh((1,), (AXIS_DATA,), devices=jax.devices()[:1])
        init_fn, _, _, run_block = make_parallel_sac(
            env_cfg, agent_cfg, mesh, n_envs=args.n_envs,
            episode_block=(STEPS, n_vec_episodes))
        st = init_fn(jax.random.PRNGKey(seed))
        key_b = jax.random.PRNGKey(1000 + seed)
        key_b, kb = jax.random.split(key_b)
        st, scores_b = run_block(st, kb)
        bat = [float(s) for s in np.asarray(scores_b)]

        w = args.final_window
        runs["seeds"][seed] = {
            "sequential_mean_step_reward": seq,
            "batched_mean_step_reward": bat,
            "seq_final_mean": float(np.mean(seq[-w:])),
            "seq_first_mean": float(np.mean(seq[:w])),
            # the batched arm has episodes/n_envs vector episodes; its
            # final window covers the same env-step fraction (ceil)
            "bat_final_mean": float(np.mean(bat[-bat_window:])),
            "bat_first_mean": float(np.mean(bat[:bat_window])),
            "wall_s": round(time.time() - t0, 1),
        }
        print(f"seed {seed}: seq final {runs['seeds'][seed]['seq_final_mean']:.3f} "
              f"batched final {runs['seeds'][seed]['bat_final_mean']:.3f} "
              f"({runs['seeds'][seed]['wall_s']}s)", flush=True)

    import numpy as np  # noqa: F811 — local scope for aggregates
    seqf = [r["seq_final_mean"] for r in runs["seeds"].values()]
    batf = [r["bat_final_mean"] for r in runs["seeds"].values()]
    runs["aggregate"] = {
        "seq_final_mean": float(np.mean(seqf)),
        "seq_final_std": float(np.std(seqf)),
        "bat_final_mean": float(np.mean(batf)),
        "bat_final_std": float(np.std(batf)),
        "bat_minus_seq": float(np.mean(batf) - np.mean(seqf)),
    }
    out_json = os.path.join(args.outdir, "parity.json")
    with open(out_json, "w") as fh:
        json.dump(runs, fh, indent=1)

    # curve figure: env-step-aligned mean step reward
    from smartcal_tpu.train.plots import _plt
    plt = _plt()
    fig = plt.figure(figsize=(7, 4))
    for seed, r in runs["seeds"].items():
        xs = np.arange(len(r["sequential_mean_step_reward"])) * STEPS
        plt.plot(xs, r["sequential_mean_step_reward"], alpha=0.5,
                 color="C0",
                 label="sequential 1:1" if seed == 0 else None)
        xb = (np.arange(len(r["batched_mean_step_reward"])) + 1) \
            * STEPS * args.n_envs
        plt.plot(xb, r["batched_mean_step_reward"], alpha=0.8, color="C1",
                 marker="o", ms=3,
                 label=f"batched n={args.n_envs}" if seed == 0 else None)
    plt.xlabel("env steps")
    plt.ylabel("mean step reward")
    plt.legend()
    fig.tight_layout()
    fig.savefig(os.path.join(args.outdir, "parity.png"), dpi=110)
    plt.close(fig)
    print(json.dumps(runs["aggregate"]))


if __name__ == "__main__":
    main()
