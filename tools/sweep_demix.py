"""Multi-seed hint/no-hint DEMIXING SAC sweep at reference-like scale.

VERDICT r2 item 2: demonstrate (or honestly refute) the reference's
headline demixing claim — the hint-constrained agent learns faster
(``demixing_rl/README.md:12-14``) — at K=6, N>=14, >=5 seeds x >=500
episodes.  The round-2 artifact (2 seeds x 100 episodes on the N=6 toy
config, ``results/demix_curves/``) was too easy a task to separate the
modes.

This sweep drives the REAL ``train.demix_sac`` episode loop (same env,
same agent config: batch 256, mem 16000, KLD hint distance) on the
default backend scale N=14/Nf=3/T=20 (B=91 baselines, 2 solution
intervals, 2^(K-1)=32-lane exhaustive AIC hint sweep per episode).

Writes per-episode JSONL + summary in the demix_curves format so
``tools/summarize_demix_curves.py`` can aggregate.

Usage:
    python tools/sweep_demix.py --outdir results/demix_curves_r3 \
        [--seeds 5] [--episodes 500] [--stations 14] [--platform cpu]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seeds", default=5, type=int)
    p.add_argument("--episodes", default=500, type=int)
    p.add_argument("--warmup", default=30, type=int)
    p.add_argument("--steps", default=7, type=int)
    p.add_argument("--K", default=6, type=int)
    p.add_argument("--stations", default=14, type=int)
    p.add_argument("--outdir", default="results/demix_curves_r3")
    p.add_argument("--platform", default=None, choices=["cpu", "axon"])
    p.add_argument("--modes", default="nohint,hint")
    p.add_argument("--medium", action="store_true",
                   help="pass --medium to demix_sac (N=14 with thinner "
                   "time/freq axes; CPU-tractable)")
    p.add_argument("--light", action="store_true",
                   help="pass --light to demix_sac (one solution "
                   "interval, minimum solver iterations)")
    p.add_argument("--provide_influence", action="store_true",
                   help="pass --provide_influence to demix_sac (full "
                   "image observations — the harder-regime sweep where "
                   "the hint plausibly binds, VERDICT r3 item 4)")
    p.add_argument("--npix", default=128, type=int)
    p.add_argument("--seed0", default=0, type=int,
                   help="first seed (parallel shards of the sweep)")
    args = p.parse_args()

    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from smartcal_tpu.train import demix_sac
    from smartcal_tpu.utils import enable_compilation_cache

    enable_compilation_cache()

    os.makedirs(args.outdir, exist_ok=True)
    t_start = time.time()
    # seed-major order: a truncated sweep still has paired hint/no-hint
    # runs for every completed seed
    for seed in range(args.seed0, args.seed0 + args.seeds):
        for mode in args.modes.split(","):
            use_hint = mode == "hint"
            tag = f"{mode}_seed{seed}"
            dst = os.path.join(args.outdir, f"{tag}.jsonl")
            if os.path.exists(dst):
                print(f"skip {tag} (exists)", flush=True)
                continue
            # in-flight runs write <tag>.jsonl.partial and rename on
            # completion (VERDICT r4 item 8): a snapshot taken mid-run can
            # never be mistaken for a finished run, and a restarted sweep
            # re-runs rather than skips a truncated one
            part = dst + ".partial"
            if os.path.exists(part):
                os.remove(part)
            # yield to an active chip-capture window (single-core host);
            # resolve the hook from the package location — CWD- and
            # __file__-independent (exec() harnesses have neither the
            # script path nor a guaranteed repo-root CWD)
            import subprocess

            import smartcal_tpu
            hook = os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.abspath(smartcal_tpu.__file__))),
                "tools", "wait_no_chip.sh")
            if os.path.isfile(hook):
                subprocess.run(["bash", hook], check=False)
            else:
                print(f"WARNING: chip-window hook missing at {hook}; "
                      "running without the yield", flush=True)
            t0 = time.time()
            argv = ["--seed", str(seed), "--iteration", str(args.episodes),
                    "--warmup", str(args.warmup), "--steps", str(args.steps),
                    "--K", str(args.K), "--stations", str(args.stations),
                    "--npix", str(args.npix),
                    "--prefix", os.path.join(args.outdir, f"{tag}_ck"),
                    "--metrics", part]
            if use_hint:
                argv.append("--use_hint")
            if args.provide_influence:
                argv.append("--provide_influence")
            if args.medium:
                argv.append("--medium")
            if args.light:
                argv.append("--light")
            demix_sac.main(argv)
            os.rename(part, dst)
            print(f"[{time.time() - t_start:7.0f}s] DONE {tag} "
                  f"({time.time() - t0:.0f}s)", flush=True)


if __name__ == "__main__":
    main()
