"""Standalone capture of the pallas-vs-blocked-XLA kernel rooflines
(ISSUE 17 promotion gate evidence).

Drives ONE batched influence dispatch at the blocked tier (default
N=256, npix=1024 — both kernel families engage: Hessian at B >= 8128,
imager at npix >= 512) with cost collection armed, so
``RadioBackend._record_kernel_costs`` records the ``kernel:<fam>_pallas``
vs ``kernel:<fam>_blocked_xla`` cost rows and the per-axis footprint
rides on the influence cost event.  On TPU the pallas rows lower the
real Mosaic kernels — those are the rooflines that gate promotion; on
CPU (``--allow_cpu``) the interpreter lowering only certifies plumbing
and the artifact says so.

The JSONL artifact is a plain RunLog — render it with::

    python tools/obs_report.py results/kernel_roofline_<round>.jsonl

Usage: python tools/capture_kernel_roofline.py \
           [--out results/kernel_roofline_r16.jsonl] [--stations 256]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "results", "kernel_roofline_r16.jsonl"))
    ap.add_argument("--stations", type=int, default=256)
    ap.add_argument("--npix", type=int, default=1024)
    ap.add_argument("--lanes", type=int, default=2)
    ap.add_argument("--allow_cpu", action="store_true",
                    help="deliberate CPU run (interpreter pallas rows — "
                    "plumbing evidence, NOT rooflines; never promoted "
                    "as a chip capture)")
    args = ap.parse_args()

    import jax

    platform = jax.devices()[0].platform
    if platform not in ("tpu", "axon") and not args.allow_cpu:
        print(f"platform is {platform!r}, not a TPU — refusing to capture "
              "(interpreter pallas rows are not rooflines; --allow_cpu "
              "for plumbing checks)", file=sys.stderr)
        return 1

    import numpy as np

    from smartcal_tpu import obs
    from smartcal_tpu.envs.radio import RadioBackend
    from smartcal_tpu.obs import costs as obs_costs

    backend = RadioBackend(n_stations=args.stations, n_freqs=1,
                           n_times=2, tdelta=2, admm_iters=1,
                           lbfgs_iters=2, init_iters=2, npix=args.npix)
    eps, rhos = [], []
    for i in range(args.lanes):
        ep, mdl = backend.new_demixing_episode(jax.random.PRNGKey(i), 2)
        eps.append(ep)
        rhos.append(np.asarray(mdl.rho))
    bep = backend.stack_episodes(eps)
    rho = np.stack(rhos).astype(np.float32)
    alpha = np.zeros_like(rho)

    obs_costs.set_enabled(True)
    tmp = args.out + ".tmp"
    with obs.recording(tmp):
        res = backend.calibrate_batched(bep, rho)
        img = backend.influence_images_batched(bep, res, rho, alpha)
        jax.block_until_ready(img)
        n = obs_costs.flush_pending()
    os.replace(tmp, args.out)
    print(f"captured {n} cost event(s) on {platform!r} -> {args.out}")
    print(f"render: python tools/obs_report.py {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
